"""Benchmark: batched TPU scheduling step vs serial reference-semantics floors.

North-star config (BASELINE.md): 10k pending pods x 5k nodes, full chain, pods
scheduled/sec + p50/p99 schedule latency over >=20 steps. Two floors, both the
same plugin semantics executed the same serial per-pod/per-node way the
reference executes them (the reference's own Go chain is not runnable here —
no Go toolchain / no cluster):
  * compiled floor — C++ -O2 transcription (native/serial_floor.cpp), run on
    the FULL packed trace; an order-of-magnitude-honest proxy for the Go
    chain, and a full-batch binding parity check in the same run;
  * python floor — the numpy scalar oracle (scheduler/parity.py), timed on a
    prefix sample (kept for continuity with earlier rounds).
On TPU the Pallas kernel's full-batch bindings are additionally diffed
against the XLA step on-chip (parity_ok).

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": "pods/s", "vs_baseline": N,
   "vs_compiled_floor": N, "vs_python_floor": N, "parity_ok": bool,
   "p50_ms": N, "p99_ms": N}
vs_baseline == vs_compiled_floor (the honest ratio). Detail lines on stderr.

Usage: python bench.py [--smoke] [--pods P] [--nodes N] [--serial-sample S]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _guard_against_dead_accelerator(timeout_seconds: int,
                                    attempts: int = 3) -> None:
    """Device init blocks in native code when the accelerator tunnel is
    wedged, which would hang the whole bench (and its caller) forever.
    Probe `jax.devices()` in a SUBPROCESS first; a transient tunnel outage
    often recovers within minutes, so retry the probe (with backoff) before
    giving up — a CPU-fallback bench artifact misrepresents a whole round.
    Only after every attempt fails, flip this process to the CPU backend and
    report honestly on stderr + in the JSON (the `platform` field) rather
    than never finishing."""
    import os
    import subprocess

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # explicitly CPU: nothing to probe. An UNSET variable still
        # auto-detects accelerators, so it must be probed like tpu/axon.
        return
    for attempt in range(1, attempts + 1):
        # Popen + wait(timeout), output to DEVNULL: subprocess.run would
        # drain captured pipes after the kill, which blocks forever if the
        # child is wedged uninterruptibly in a device ioctl — the exact
        # failure mode this guard exists for. With no pipes there is nothing
        # to drain; a D-state child is abandoned.
        child = subprocess.Popen(
            [sys.executable, "-c", "import jax; jax.devices()"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True,
        )
        try:
            if child.wait(timeout=timeout_seconds) == 0:
                if attempt > 1:
                    log(f"device probe recovered on attempt {attempt}")
                return
            log(f"device probe attempt {attempt}/{attempts} failed "
                f"(rc={child.returncode})")
        except subprocess.TimeoutExpired:
            child.kill()
            log(f"device probe attempt {attempt}/{attempts} hung "
                f">{timeout_seconds}s (accelerator tunnel unresponsive)")
        if attempt < attempts:
            backoff = 30 * attempt
            log(f"retrying device probe in {backoff}s")
            time.sleep(backoff)
    log("all device probe attempts failed; falling back to CPU")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny shapes, quick check")
    ap.add_argument("--pods", type=int, default=None)
    ap.add_argument("--nodes", type=int, default=None)
    ap.add_argument("--serial-sample", type=int, default=200)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument(
        "--chain",
        choices=["full", "loadaware", "numa", "quota-gang", "rebalance",
                 "churn"],
        default="full",
        help="full = Fit+LoadAware+NUMA+quota+gang (BASELINE config 4); "
        "loadaware = config 1 kernel; numa = config 2 standalone "
        "(NodeNUMAResource Filter+Score, 1k pods x 500 2-socket nodes); "
        "quota-gang = config 3 standalone (ElasticQuota+Coscheduling, "
        "5k pods, 200 PodGroups, 3-level tree); rebalance = config 5, the "
        "koord-descheduler LowNodeLoad 50k-running-pod global rebalance",
    )
    ap.add_argument(
        "--kernel",
        choices=["auto", "serial", "pallas", "wave"],
        default="auto",
        help="full-chain kernel selection (auto = backend/VMEM-based)",
    )
    ap.add_argument(
        "--waves", default=None,
        help="comma list of fused-wave depths for the steady-state sweep "
        "(default 1,2,4,8; smoke runs 1,2; empty string disables). Each K "
        "runs the steady loop with KOORD_TPU_WAVES=K semantics and the "
        "JSON gains pods_per_sec_at_k + fixed_overhead_ms_amortized",
    )
    ap.add_argument(
        "--mesh", action="store_true",
        help="mesh-backed dispatch sweep: the steady-state loop through "
        "the production Scheduler with KOORD_TPU_MESH pinned to each "
        "device count in --mesh-devices, each emitted as a back-to-back "
        "A/B stash pair against the single-device path (BENCH_NOTES "
        "convention: only pair ratios are real on this box). On the CPU "
        "backend the process is forced onto 8 virtual host devices",
    )
    ap.add_argument(
        "--mesh-devices", default=None,
        help="comma list of mesh device counts for --mesh "
        "(default 1,2,4,8; capped at the visible device count)",
    )
    ap.add_argument(
        "--mesh-scale", type=int, default=None, choices=(0, 1),
        help="include the 100k pods x 50k nodes cluster config in the "
        "--mesh sweep (the 'millions of users' shape: ~100k pods via the "
        "incremental pack memo, 2048-pod pending queue, 8-device mesh). "
        "SLOW — several minutes on CPU. Default: on unless --smoke",
    )
    ap.add_argument(
        "--rebalance", action="store_true",
        help="koordbalance A/B: the device rebalance pass vs the host "
        "LowNodeLoad oracle back-to-back at 10k pods x 5k nodes "
        "(rebalance_pass_ms pair + victim parity), then the drain-storm "
        "and hotspot churn pairs (time-to-dissipate p50/p99 in the "
        "rebalance block, BENCH_NOTES convention)",
    )
    ap.add_argument(
        "--colo", action="store_true",
        help="koordcolo A/B: the overcommit-shift churn scenario run "
        "with the DEVICE colo pass (KOORD_TPU_COLO=on) vs the host "
        "oracle (=host) back-to-back — binding logs must be IDENTICAL "
        "(the control-plane engine may not change a single decision), "
        "with the batch-bind/staleness SLO report from both runs "
        "(BENCH_NOTES convention)",
    )
    ap.add_argument(
        "--churn", default=None, metavar="SCENARIO",
        help="run a named koordsim churn scenario (python -m "
        "koordinator_tpu.sim --list) TWICE back-to-back in this process "
        "and emit the SLO report as an A/B stash pair (BENCH_NOTES "
        "convention: same-process pairs are the only comparable numbers "
        "on a noisy box). The JSON carries bound-pods/sec for both runs, "
        "time-to-bind p50/p99, invariant breaches and the binding-log "
        "hashes (pair determinism)",
    )
    ap.add_argument(
        "--churn-cycles", type=int, default=None,
        help="override the --churn scenario's cycle count "
        "(--smoke caps it at 30)",
    )
    ap.add_argument(
        "--coldstart", action="store_true",
        help="persistent compile cache + warm-up ladder A/B: the "
        "crash-restart scenario as a cold/warm PROCESS pair (cold = no "
        "cache dir, warm = KOORD_TPU_COMPILE_CACHE_DIR armed with "
        "KOORD_TPU_WARMUP=sync) — emits cold/warm total and "
        "restart-to-first-bind walls, the compile/pack split, per-rung "
        "warm-up counts, and the binding-log determinism verdict "
        "(COLDSTART_rNN convention)",
    )
    ap.add_argument(
        "--device-probe-timeout", type=int, default=150,
        help="seconds per device-init probe attempt (subprocess); after "
        "--device-probe-attempts failures the bench falls back to CPU "
        "instead of hanging forever",
    )
    ap.add_argument(
        "--device-probe-attempts", type=int, default=3,
        help="device probe attempts (with 30s*attempt backoff between) "
        "before the CPU fallback",
    )
    args_cli = ap.parse_args()

    churn_scenario = None
    if args_cli.churn is not None:
        # resolve the scenario BEFORE jax imports: a mesh scenario needs
        # the virtual device split forced first (see below)
        from koordinator_tpu.sim.scenarios import SCENARIOS

        churn_scenario = SCENARIOS.get(args_cli.churn)
        if churn_scenario is None:
            ap.error(f"unknown churn scenario {args_cli.churn!r}; "
                     f"catalog: {', '.join(sorted(SCENARIOS))}")

    if args_cli.mesh or (churn_scenario is not None
                         and churn_scenario.mesh is not None):
        # the CPU backend exposes ONE device unless the 8-way virtual
        # split is forced before the first jax import (same shape
        # tests/conftest.py pins); real accelerators keep their topology
        import os

        if (os.environ.get("JAX_PLATFORMS", "") == "cpu"
                and "jax" not in sys.modules):
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()

    _guard_against_dead_accelerator(args_cli.device_probe_timeout,
                                    args_cli.device_probe_attempts)

    if args_cli.coldstart:
        run_coldstart(args_cli)
        return

    if churn_scenario is not None:
        run_sim_churn(args_cli, churn_scenario)
        return

    if args_cli.mesh:
        run_mesh_sweep(args_cli)
        return

    if args_cli.rebalance:
        run_rebalance_ab(
            args_cli,
            args_cli.pods or (500 if args_cli.smoke else 10_000),
            args_cli.nodes or (50 if args_cli.smoke else 5_000),
        )
        return

    if args_cli.colo:
        run_colo_ab(args_cli)
        return

    num_pods = args_cli.pods or (100 if args_cli.smoke else 10_000)
    num_nodes = args_cli.nodes or (50 if args_cli.smoke else 5_000)

    if args_cli.chain == "churn":
        run_churn(
            args_cli,
            args_cli.pods or (100 if args_cli.smoke else 10_000),
            args_cli.nodes or (50 if args_cli.smoke else 5_000),
        )
        return
    if args_cli.chain == "rebalance":
        run_rebalance(
            args_cli,
            args_cli.pods or (500 if args_cli.smoke else 50_000),
            num_nodes,
        )
        return
    if args_cli.chain == "numa":
        run_full_chain(
            args_cli,
            args_cli.pods or (100 if args_cli.smoke else 1_000),
            args_cli.nodes or (20 if args_cli.smoke else 500),
            variant="numa",
        )
        return
    if args_cli.chain == "quota-gang":
        run_full_chain(
            args_cli,
            args_cli.pods or (250 if args_cli.smoke else 5_000),
            args_cli.nodes or (50 if args_cli.smoke else 1_000),
            variant="quota-gang",
        )
        return
    if args_cli.chain == "full":
        run_full_chain(args_cli, num_pods, num_nodes)
        return

    import jax

    from koordinator_tpu.models.scheduler_model import (
        build_best_schedule_step,
        make_inputs,
    )
    from koordinator_tpu.ops.loadaware import LoadAwareArgs, build_loadaware_node_state
    from koordinator_tpu.ops.packing import pack_nodes, pack_pods
    from koordinator_tpu.scheduler.parity import serial_schedule
    from koordinator_tpu.testing import synth_cluster

    log(f"devices: {jax.devices()}")
    log(f"config: {num_pods} pending pods x {num_nodes} nodes (LoadAware chain)")

    t0 = time.perf_counter()
    cluster = synth_cluster(num_nodes=num_nodes, num_pods=num_pods, seed=42)
    la = LoadAwareArgs()
    pods = pack_pods(cluster.pods, la.resource_weights, la.estimated_scaling_factors)
    nodes = pack_nodes(cluster.nodes)
    nodes.extras = build_loadaware_node_state(
        cluster.nodes,
        cluster.node_metrics,
        cluster.pods_by_key,
        cluster.assigned,
        la,
        cluster.now,
        pad_to=nodes.padded_size,
    )
    inputs = make_inputs(pods, nodes, la)
    t_pack = time.perf_counter() - t0
    log(f"packing: {t_pack:.3f}s (padded {pods.padded_size} x {nodes.padded_size})")

    step = build_best_schedule_step(la)  # pallas on TPU, XLA elsewhere
    t0 = time.perf_counter()
    chosen, _ = step(inputs)
    chosen = np.asarray(jax.block_until_ready(chosen))
    t_compile = time.perf_counter() - t0
    log(f"first call (compile+run): {t_compile:.3f}s")

    iters = max(args_cli.iters, 2 if args_cli.smoke else 20)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        chosen_j, _ = step(inputs)
        jax.block_until_ready(chosen_j)
        times.append(time.perf_counter() - t0)
    t_batch = float(np.median(times))
    p50_ms = float(np.percentile(np.asarray(times) * 1000.0, 50))
    p99_ms = float(np.percentile(np.asarray(times) * 1000.0, 99))
    scheduled = int((chosen[: pods.num_valid] >= 0).sum())
    tpu_pps = pods.num_valid / t_batch
    log(
        f"batched step: median {t_batch:.4f}s over {iters} iters for "
        f"{pods.num_valid} pods ({scheduled} scheduled) -> "
        f"{tpu_pps:,.0f} pods/s; latency p50 {p50_ms:.1f}ms p99 {p99_ms:.1f}ms"
    )

    # serial floor on a sample of the same queue (per-pod cost is constant)
    sample = min(args_cli.serial_sample, pods.num_valid)
    sub = ScheduleInputsSlice(inputs, sample)
    t0 = time.perf_counter()
    chosen_serial = serial_schedule(sub, la)
    t_serial = time.perf_counter() - t0
    serial_pps = sample / t_serial
    log(
        f"serial floor: {t_serial:.3f}s for {sample} pods -> {serial_pps:,.1f} pods/s"
    )

    # parity spot check on the sample prefix
    mism = int((chosen[:sample] != chosen_serial[:sample]).sum())
    log(f"parity on first {sample} pods: {'OK' if mism == 0 else f'{mism} MISMATCHES'}")

    ratio = tpu_pps / serial_pps if serial_pps > 0 else 0.0
    print(
        json.dumps(
            {
                "metric": f"pods_scheduled_per_sec_{num_pods}x{num_nodes}_loadaware",
                "value": round(tpu_pps, 1),
                "unit": "pods/s",
                "vs_baseline": round(ratio, 2),
                "platform": jax.default_backend(),
            }
        )
    )


def run_colo_ab(args_cli) -> None:
    """koordcolo A/B: the overcommit-shift scenario with the DEVICE colo
    pass vs the host oracle, back to back in this process.

    Unlike the same-config --churn pairs (noise floor), this pair flips
    the CONTROL-PLANE ENGINE: run A computes batch/mid overcommit +
    runtime quotas on device (KOORD_TPU_COLO=on, the third consumer of
    the shared DeviceSnapshot), run B pins the retained host reconcilers
    (=host). The binding logs must be byte-IDENTICAL — the engine may
    not change a single scheduling decision (the run_colo_parity
    property, re-proven under 160 cycles of churn) — and both runs must
    hold 0 invariant breaches with the batch-bind discipline + the
    metric-write-to-observing-dispatch staleness SLO met."""
    import dataclasses

    import jax

    from koordinator_tpu.sim.harness import run_scenario
    from koordinator_tpu.sim.scenarios import SCENARIOS

    sc = SCENARIOS["overcommit-shift"]
    if args_cli.churn_cycles is not None:
        sc = dataclasses.replace(sc, cycles=args_cli.churn_cycles)
    elif args_cli.smoke:
        # keep at least one full surge+recede inside the smoke window
        # (surge at overcommit_surge_every, recede +surge_cycles): a
        # 30-cycle cap would never exercise an overcommit shift
        floor = sc.overcommit_surge_every + sc.overcommit_surge_cycles + 8
        sc = dataclasses.replace(sc, cycles=min(sc.cycles, max(30, floor)))
    log(f"devices: {jax.devices()}")
    log(f"config: colo A/B on scenario {sc.name!r} — {sc.cycles} "
        f"cycles, {sc.nodes} nodes, seed {sc.seed}; run A = device colo "
        "pass, run B = host oracle (decisions must be identical)")
    reports = {}
    for label, engine in (("A", "on"), ("B", "host")):
        rep = run_scenario(dataclasses.replace(sc, colo=engine))
        reports[label] = rep
        colo = rep.to_dict()["colo"]
        log(f"run {label} ({engine}): bound {rep.pods_bound} "
            f"({colo['batch_pods_bound']} batch) in "
            f"{rep.wall_seconds:.1f}s, manager rounds "
            f"{colo['manager_rounds']} "
            f"(device/host passes {colo['device_passes']}/"
            f"{colo['host_passes']}), shifts "
            f"{colo['overcommit_shifts']}, staleness p99 "
            f"{colo['staleness_cycles']['p99']:.0f} cycles, "
            f"{len(rep.invariant_breaches)} breaches")
    a, b = reports["A"], reports["B"]
    identical = a.binding_log == b.binding_log
    log(f"binding logs {'IDENTICAL' if identical else 'DIVERGED'} "
        f"across the engine pair (sha256 {a.binding_log_sha256[:16]})")
    a_colo, b_colo = a.to_dict()["colo"], b.to_dict()["colo"]
    pair = [round(r.pods_bound / max(r.wall_seconds, 1e-9), 1)
            for r in (a, b)]
    print(json.dumps({
        "metric": "colo_bound_pods_per_sec_overcommit_shift",
        "value": pair[0],
        "unit": "pods/s",
        "pair": pair,
        "pair_ratio": round(pair[1] / pair[0], 3) if pair[0] else 0.0,
        "scenario": sc.name,
        "seed": sc.seed,
        "cycles": sc.cycles,
        "engine_pair": ["device", "host"],
        "binding_logs_identical": identical,
        "binding_log_sha256": a.binding_log_sha256,
        "colo_device": a_colo,
        "colo_host": b_colo,
        "invariant_breaches": (len(a.invariant_breaches)
                               + len(b.invariant_breaches)),
        "staleness_slo_met": (a_colo["staleness_slo_met"]
                              and b_colo["staleness_slo_met"]),
        "ttb_p99_seconds": round(a.percentile(99), 3),
        "ttb_slo_met": a.percentile(99) <= sc.ttb_slo_seconds,
        "platform": jax.default_backend(),
    }))


def run_coldstart(args_cli) -> None:
    """Coldstart A/B (PR 15): the crash-restart scenario as a cold/warm
    process pair, plus a dir-reuse third run (the production restart:
    a whole NEW process against a populated cache).

    cold       — no compile-cache dir: every compile is a fresh XLA
                 build, at startup AND at the mid-run crash-restart;
    warm       — KOORD_TPU_COMPILE_CACHE_DIR on a fresh dir with
                 KOORD_TPU_WARMUP=sync: startup compiles write the
                 cache, the restart replays the rung index (disk-served
                 XLA) and binds its first pod with zero steady-state
                 recompiles;
    warm-reuse — the same dir again in a NEW process: the whole
                 startup ladder disk-serves too — the
                 restart-to-first-bind *wall-clock* story the ROADMAP
                 host-tail item targets.

    Binding logs must be byte-identical across all three (the cache may
    never move a decision). BENCH_NOTES convention: wall numbers are a
    same-box pair; only ratios travel.

    The cold/warm subprocess protocol is hack/check_coldstart.py's —
    ONE implementation shared with the lint gate, so the env knobs and
    report keys can never drift between the two."""
    import os
    import tempfile

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "hack"))
    from check_coldstart import (
        report_restart_wall,
        run_crash_restart,
        warm_env,
    )

    def run(env_extra, label):
        rep, wall = run_crash_restart(env_extra, label)
        if rep is None:
            raise RuntimeError(f"{label} crash-restart run failed")
        rep["_process_wall_seconds"] = round(wall, 2)
        return rep

    cache_dir = tempfile.mkdtemp(prefix="koord_coldstart_")
    runs = {}
    for label, env in (("cold", {}), ("warm", warm_env(cache_dir)),
                       ("warm-reuse", warm_env(cache_dir))):
        rep = runs[label] = run(env, label)
        log(f"{label}: process wall {rep['_process_wall_seconds']}s, "
            f"restart-to-first-bind wall "
            f"{rep['restart']['to_first_bind_wall_seconds']}s "
            f"(compile {rep['restart']['restart_wall_compile_seconds']} / "
            f"pack {rep['restart']['restart_wall_pack_seconds']}), "
            f"warm-up {rep.get('warmup', {}) or 'off'}, "
            f"{rep['invariant_breaches']} breaches")
    shas = {label: rep["binding_log_sha256"]
            for label, rep in runs.items()}
    deterministic = len(set(shas.values())) == 1
    log(f"binding logs {'IDENTICAL' if deterministic else 'DIVERGED'} "
        f"across the trio")

    cold_wall = report_restart_wall(runs["cold"])
    warm_wall = report_restart_wall(runs["warm"])
    print(json.dumps({
        "metric": "coldstart_restart_to_first_bind_wall_seconds",
        "value": warm_wall,
        "unit": "s",
        "pair": [cold_wall, warm_wall],
        "pair_ratio": round(warm_wall / cold_wall, 3) if cold_wall else 0.0,
        "scenario": "crash-restart",
        "waves": 4,
        "restart_wall_compile_seconds": {
            label: rep["restart"]["restart_wall_compile_seconds"]
            for label, rep in runs.items()},
        "restart_wall_pack_seconds": {
            label: rep["restart"]["restart_wall_pack_seconds"]
            for label, rep in runs.items()},
        "steady_state_compiles": {
            label: rep["restart"]["steady_state_compiles"]
            for label, rep in runs.items()},
        "process_wall_seconds": {
            label: rep["_process_wall_seconds"]
            for label, rep in runs.items()},
        "warmup": {label: rep.get("warmup", {})
                   for label, rep in runs.items()},
        "pair_deterministic": deterministic,
        "binding_log_sha256": shas["cold"],
        "invariant_breaches": sum(r["invariant_breaches"]
                                  for r in runs.values()),
        "platform": "cpu",
    }))


def run_sim_churn(args_cli, scenario) -> None:
    """koordsim scenario as a back-to-back A/B stash pair.

    PR 15: the pair is now the PACK-OVERLAP A/B — run A pins
    KOORD_TPU_PACK_OVERLAP on (the default architecture), run B pins it
    off (the gap-pack twin). Binding logs MUST still be identical (the
    overlap is a latency lever, never a decision change — the parity
    gates pin that too) and the report carries both runs' device idle
    fractions: the overlap's whole claim is run A's idle fraction
    strictly below run B's. Everything else is unchanged: bound-pods/s
    for both runs, time-to-bind p50/p99, invariant breaches and the
    binding-log hashes (pair determinism), per the BENCH_NOTES
    noise protocol (same-process pairs only)."""
    import dataclasses

    import jax

    from koordinator_tpu.sim.harness import run_scenario

    sc = scenario
    if args_cli.churn_cycles is not None:
        sc = dataclasses.replace(sc, cycles=args_cli.churn_cycles)
    elif args_cli.smoke:
        sc = dataclasses.replace(sc, cycles=min(sc.cycles, 30))
    log(f"devices: {jax.devices()}")
    log(f"config: churn scenario {sc.name!r} — {sc.cycles} cycles, "
        f"{sc.nodes} nodes, seed {sc.seed}, {len(sc.faults)} scheduled "
        "faults; back-to-back pack-overlap A/B pair (A=on, B=off)")
    reports = []
    for label, overlap in (("A", True), ("B", False)):
        rep = run_scenario(dataclasses.replace(sc, pack_overlap=overlap))
        reports.append(rep)
        log(f"run {label} (pack_overlap={'on' if overlap else 'off'}): "
            f"bound {rep.pods_bound}/{rep.pods_created} in "
            f"{rep.wall_seconds:.1f}s "
            f"({rep.pods_bound / max(rep.wall_seconds, 1e-9):.1f} "
            f"bound/s), ttb p50/p99 {rep.percentile(50):.1f}/"
            f"{rep.percentile(99):.1f}s, device idle fraction "
            f"{rep.device_idle_fraction:.3f}, "
            f"{len(rep.invariant_breaches)} breaches, final ladder "
            f"level {rep.final_level}")
    a, b = reports
    pair = [round(r.pods_bound / max(r.wall_seconds, 1e-9), 1)
            for r in reports]
    deterministic = a.binding_log == b.binding_log
    log(f"binding logs {'IDENTICAL' if deterministic else 'DIVERGED'} "
        f"across the pair (sha256 {a.binding_log_sha256[:16]})")
    # occupancy + per-K throughput under REALISTIC arrivals (not the
    # synthetic 2%-delta loop): both runs of the pair, so the occupancy
    # number itself is citable as a back-to-back pair
    a_dict = a.to_dict()  # built once: each call rebuilds the SLO math
    occ_pair = [a_dict["pipeline"]["occupancy"],
                b.to_dict()["pipeline"]["occupancy"]]
    log(f"pipeline occupancy (pair): {occ_pair[0]:.3f} / {occ_pair[1]:.3f}; "
        f"pods/s by consumed waves: "
        f"{a_dict['pipeline']['pods_per_sec_at_k']}")
    print(json.dumps({
        "metric": f"churn_bound_pods_per_sec_{sc.name}",
        "value": pair[0],
        "unit": "pods/s",
        "pair": pair,
        "pair_ratio": round(pair[1] / pair[0], 3) if pair[0] else 0.0,
        "scenario": sc.name,
        "seed": sc.seed,
        "cycles": sc.cycles,
        "pipeline_occupancy": occ_pair[0],
        "pipeline_occupancy_pair": occ_pair,
        # pack overlap (PR 15): the pair IS the overlap A/B — A on, B
        # off. The idle fraction (gap-over-wall between device windows,
        # koord_device_idle_fraction) is the overlap's deliverable: A
        # strictly below B, logs identical.
        "pack_overlap_pair": [True, False],
        "device_idle_fraction_pair": [
            round(a.device_idle_fraction, 3),
            round(b.device_idle_fraction, 3)],
        "pods_per_sec_at_k": a_dict["pipeline"]["pods_per_sec_at_k"],
        "ttb_p50_seconds": round(a.percentile(50), 3),
        "ttb_p99_seconds": round(a.percentile(99), 3),
        "ttb_slo_seconds": sc.ttb_slo_seconds,
        "slo_met": a.percentile(99) <= sc.ttb_slo_seconds,
        "invariant_breaches": len(a.invariant_breaches)
        + len(b.invariant_breaches),
        "cycle_exceptions": len(a.cycle_exceptions),
        "degradation_transitions": len(a.ladder_transitions),
        # koordguard: deadline-overrun counts, ladder residency per
        # level (incl. partial-mesh) and the restart-to-first-bind SLO
        "deadline_overruns": a.deadline_overruns,
        "cycles_at_level": a.cycles_at_level,
        "restart": a_dict["restart"],
        "pair_deterministic": deterministic,
        "binding_log_sha256": a.binding_log_sha256,
        # koordbalance: migration-job/eviction activity + the hotspot
        # time-to-dissipate SLO (cycles), straight from the SimReport
        "rebalance": a_dict["rebalance"],
        # koordwatch: the per-scenario demotion profile (fraction of
        # cycles demoted, by structured reason — the real-traffic data
        # the ROADMAP demotion burn-down starts from), the queue
        # depth/wait stats, and the SLO registry dump with burn rates
        "demotions": a_dict["demotions"],
        "queue": a_dict["queue"],
        "slos": a_dict["slos"],
        "platform": jax.default_backend(),
    }))


def run_churn(args_cli, num_pods: int, num_nodes: int) -> None:
    """Steady-state churn: the honest END-TO-END scheduler cycle.

    Cycle 0 schedules `num_pods` pending pods cold (full snapshot build +
    compile + full device upload). Every later cycle receives
    `num_pods // 10` fresh arrivals and runs the REAL `Scheduler.run_cycle`
    path: incremental snapshot deltas (scheduler/snapshot_cache.py),
    device-buffer reuse + donated scatter uploads, the fused kernel, and
    the per-binding Reserve/PreBind host loop. A twin scheduler with the
    cache disabled runs the identical arrival stream on an identical
    store; bindings are diffed EVERY cycle (delta-built state must
    schedule exactly like rebuilt state) and its cycle time is the
    full-rebuild comparison point."""
    import jax

    from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import (
        KIND_ELASTIC_QUOTA,
        KIND_NODE,
        KIND_NODE_METRIC,
        KIND_NODE_TOPOLOGY,
        KIND_POD,
        KIND_POD_GROUP,
        ObjectStore,
    )
    from koordinator_tpu.scheduler.cycle import Scheduler
    from koordinator_tpu.testing import synth_full_cluster
    from koordinator_tpu.utils.features import SCHEDULER_GATES

    GIB = 1024 ** 3
    log(f"devices: {jax.devices()}")
    arrivals = max(10, num_pods // 10)
    cycles = 3 if args_cli.smoke else max(5, args_cli.iters // 4)
    log(f"config: churn — {num_pods} initial pending x {num_nodes} nodes, "
        f"then {arrivals} arrivals/cycle for {cycles} cycles "
        f"(full Scheduler.run_cycle incl. bind loop)")

    t0 = time.perf_counter()

    def make_store():
        # an INDEPENDENT synth per store: the twins must not share object
        # instances — a binding in one world would mutate the other's pods
        _cluster, state = synth_full_cluster(
            num_nodes, num_pods, seed=42,
            num_quotas=max(8, num_pods // 100),
            num_gangs=max(4, num_pods // 50))
        store = ObjectStore()
        for n in state.nodes:
            store.add(KIND_NODE, n)
        for nm in state.node_metrics.values():
            store.add(KIND_NODE_METRIC, nm)
        for p in state.pods_by_key.values():
            store.add(KIND_POD, p)
        for p in state.pending_pods:
            store.add(KIND_POD, p)
        for pg in state.pod_groups:
            store.add(KIND_POD_GROUP, pg)
        for q in state.quotas:
            store.add(KIND_ELASTIC_QUOTA, q)
        for t in state.topologies.values():
            store.add(KIND_NODE_TOPOLOGY, t)
        return store, state

    # waves=1 keeps the churn numbers comparable across rounds: this
    # bench isolates the snapshot-cache delta path, not wave fusion
    store_inc, state = make_store()
    sched_inc = Scheduler(store_inc, waves=1)
    assert sched_inc.snapshot_cache is not None
    store_cold, _state2 = make_store()
    SCHEDULER_GATES.set_from_map({"IncrementalSnapshot": False})
    try:
        sched_cold = Scheduler(store_cold, waves=1)
    finally:
        SCHEDULER_GATES.reset()
    log(f"fixture + stores: {time.perf_counter() - t0:.2f}s "
        "(not framework cost)")

    def bound_set(res):
        return sorted((b.pod_key, b.node_name) for b in res.bound)

    now = state.now
    t0 = time.perf_counter()
    res0 = sched_inc.run_cycle(now=now)
    t_cold_cycle0 = time.perf_counter() - t0
    res0_cold = sched_cold.run_cycle(now=now)
    if bound_set(res0) != bound_set(res0_cold):
        log("cycle 0 bindings MISMATCH vs cold-rebuild twin!")
    log(f"cycle 0 (cold build + compile): {t_cold_cycle0:.3f}s, "
        f"{len(res0.bound)} bound")

    inc_times, cold_times, kernel_times = [], [], []
    bindings_match = True
    warmup = 2  # first delta cycles pay one-time device-put/scatter compiles
    for c in range(1, cycles + warmup + 1):
        batch = []
        for i in range(arrivals):
            batch.append(dict(
                name=f"churn-{c}-{i}", uid=f"churn-{c}-{i}",
                prio=5000 + (i % 4) * 1000,
                cpu=250 * (1 + i % 8), mem=(1 + i % 4) * GIB))
        for store in (store_inc, store_cold):
            for b in batch:
                store.add(KIND_POD, Pod(
                    meta=ObjectMeta(name=b["name"], namespace="churn",
                                    uid=b["uid"],
                                    creation_timestamp=now + c),
                    spec=PodSpec(priority=b["prio"],
                                 requests=ResourceList.of(
                                     cpu=b["cpu"], memory=b["mem"],
                                     pods=1)),
                ))
        t0 = time.perf_counter()
        res_inc = sched_inc.run_cycle(now=now + 2 * c)
        t_i = time.perf_counter() - t0
        t0 = time.perf_counter()
        res_cold = sched_cold.run_cycle(now=now + 2 * c)
        t_c = time.perf_counter() - t0
        if c > warmup:
            inc_times.append(t_i)
            cold_times.append(t_c)
            kernel_times.append(res_inc.kernel_seconds)
        if bound_set(res_inc) != bound_set(res_cold):
            bindings_match = False
            log(f"cycle {c}: bindings MISMATCH vs cold-rebuild twin")

    t_inc = float(np.median(inc_times))
    t_cold = float(np.median(cold_times))
    t_kernel = float(np.median(kernel_times))
    inc_pps = arrivals / t_inc
    cold_pps = arrivals / t_cold
    cs = sched_inc.snapshot_cache.stats
    ds = sched_inc.device_snapshot.stats
    log(f"steady-state cycle: median {t_inc*1000:.1f}ms incremental "
        f"(kernel {t_kernel*1000:.1f}ms, host {1000*(t_inc-t_kernel):.1f}ms)"
        f" vs {t_cold*1000:.1f}ms full-rebuild -> {t_cold/t_inc:.2f}x; "
        f"{arrivals} arrivals/cycle -> {inc_pps:,.0f} pods/s end-to-end "
        f"(rebuild {cold_pps:,.0f})")
    log(f"snapshot cache: {cs}")
    log(f"device snapshot: {ds} (bytes put per cycle amortized "
        f"{ds['bytes_put'] / max(1, cycles + warmup + 1):,.0f})")
    log(f"bindings vs cold-rebuild twin: "
        f"{'identical every cycle' if bindings_match else 'MISMATCH'}")
    print(json.dumps({
        "metric": f"churn_end_to_end_pods_per_sec_{arrivals}x{num_nodes}",
        "value": round(inc_pps, 1),
        "unit": "pods/s",
        "vs_baseline": round(inc_pps / cold_pps, 2) if cold_pps else 0.0,
        "vs_full_rebuild": round(inc_pps / cold_pps, 2) if cold_pps else 0.0,
        "bindings_match": bindings_match,
        "cycle_ms": round(t_inc * 1000, 1),
        "kernel_ms": round(t_kernel * 1000, 1),
        "host_ms": round((t_inc - t_kernel) * 1000, 1),
        "full_rebuild_cycle_ms": round(t_cold * 1000, 1),
        "cycles": cycles,
        "platform": jax.default_backend(),
    }))


def _build_rebalance_fixture(num_pods: int, num_nodes: int, now: float):
    """The BASELINE config 5 store: num_pods RUNNING pods on num_nodes
    nodes, 30% overloaded (85% cpu), 40% underloaded (20%), 30% in-band
    (60%). ONE home for the shape — `run_rebalance` (host pass vs C++
    floor) and `run_rebalance_ab` (device vs host pair) must measure the
    identical fixture or their reports stop being comparable."""
    import random

    from koordinator_tpu.api.objects import (
        Node,
        NodeMetric,
        NodeMetricInfo,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.api.resources import ResourceList
    from koordinator_tpu.client.store import (
        KIND_NODE,
        KIND_NODE_METRIC,
        KIND_POD,
        ObjectStore,
    )

    GIB = 1024 ** 3
    rng = random.Random(7)
    store = ObjectStore()
    for i in range(num_nodes):
        cores = 32
        band = 85.0 if i % 10 < 3 else (20.0 if i % 10 < 7 else 60.0)
        store.add(KIND_NODE, Node(
            meta=ObjectMeta(name=f"node-{i}", namespace=""),
            allocatable=ResourceList.of(cpu=cores * 1000, memory=128 * GIB,
                                        pods=256),
        ))
        store.add(KIND_NODE_METRIC, NodeMetric(
            meta=ObjectMeta(name=f"node-{i}", namespace=""),
            update_time=now - 30,
            node_metric=NodeMetricInfo(
                node_usage=ResourceList.of(
                    cpu=int(cores * 1000 * band / 100),
                    memory=int(128 * GIB * band / 100),
                )
            ),
        ))
    for p in range(num_pods):
        node_idx = p % num_nodes
        prio = rng.choice([5500, 6500, 9000])
        store.add(KIND_POD, Pod(
            meta=ObjectMeta(name=f"pod-{p}", uid=f"uid-{p}",
                            owner_kind="ReplicaSet", owner_name=f"rs-{p % 97}",
                            creation_timestamp=now - 3600),
            spec=PodSpec(node_name=f"node-{node_idx}", priority=prio,
                         requests=ResourceList.of(
                             cpu=rng.choice([500, 1000, 2000]),
                             memory=rng.choice([1, 2, 4]) * GIB)),
            phase="Running",
        ))
    return store


def run_rebalance(args_cli, num_pods: int, num_nodes: int) -> None:
    """BASELINE config 5: koord-descheduler LowNodeLoad over num_pods RUNNING
    pods on num_nodes nodes (30% overloaded, 40% underloaded). Measures one
    full global rebalance pass: classification, victim selection, and
    PodMigrationJob creation — the reference walks this with per-node Go
    loops; here classification is one [N, R] compare."""
    import jax

    from koordinator_tpu.descheduler.lownodeload import LowNodeLoad

    now = 1_000_000.0
    log(f"config: {num_pods} running pods x {num_nodes} nodes "
        f"(LowNodeLoad global rebalance, BASELINE config 5)")
    t0 = time.perf_counter()
    store = _build_rebalance_fixture(num_pods, num_nodes, now)
    log(f"fixture: {time.perf_counter() - t0:.2f}s (not framework cost)")

    plugin = LowNodeLoad(store)
    iters = 2 if args_cli.smoke else max(5, args_cli.iters // 4)
    times = []
    picked = np.zeros(0, np.int64)
    # warm the event-maintained pack cache (the store fixture above was
    # ingested via subscription replay; the first view() refreshes nodes)
    plugin.select_victims(now=now)
    for it in range(iters):
        # the TIMED pass is the pure classify/sort/select math on packed
        # arrays (select_victims); victim materialization, job
        # construction and store writes are API-server work outside it —
        # the same cut as the C++ floor, whose output is victim flags
        t0 = time.perf_counter()
        picked, _src, _v = plugin.select_victims(now=now)
        times.append(time.perf_counter() - t0)
    t_pass = float(np.median(times))
    t0 = time.perf_counter()
    jobs = plugin.balance(now=now)
    t_jobs = time.perf_counter() - t0
    jobs_created = len(jobs)
    assert len(picked) == len(jobs), "balance() must select identically"
    log(f"job construction + store writes (untimed pass): {t_jobs:.3f}s "
        f"for {jobs_created} PodMigrationJobs")
    pps = num_pods / t_pass
    if jobs_created == 0:
        # a degenerate fixture (e.g. --nodes too small for both bands) does
        # no rebalance work; a pods/s figure would be meaningless
        log("rebalance produced 0 migration jobs — fixture degenerate, "
            "metric not meaningful")
        pps = 0.0
    log(f"rebalance pass: median {t_pass:.3f}s over {iters} iters "
        f"({jobs_created} migration jobs) -> {pps:,.0f} pods considered/s")

    # ---- compiled serial floor: per-node/per-pod C++ transcription of the
    # same classify/sort/select pass, with victim-set parity
    from koordinator_tpu.native import floor as native_floor

    compiled_pps = 0.0
    # None (JSON null) until the victim-set diff actually runs: a missing
    # floor must not report parity it never checked
    parity_ok = None
    if not native_floor.available():
        native_floor.build()
    if native_floor.available():
        from koordinator_tpu.descheduler.lownodeload import pack_floor_inputs

        pods_l, floor_arrays = pack_floor_inputs(store, plugin, now)
        floor_times = []
        victim = None
        for _ in range(1 if args_cli.smoke else 5):
            t0 = time.perf_counter()
            victim = native_floor.lownodeload_floor_native(**floor_arrays)
            floor_times.append(time.perf_counter() - t0)
        t_floor = float(np.min(floor_times))
        compiled_pps = num_pods / t_floor if t_floor > 0 else 0.0
        floor_victims = {
            f"{pods_l[i].meta.namespace}/{pods_l[i].meta.name}"
            for i in np.nonzero(victim)[0]
        }
        plugin_victims = {f"{j.pod_namespace}/{j.pod_name}" for j in jobs}
        parity_ok = floor_victims == plugin_victims
        log(f"compiled serial floor (C++ -O2): min {t_floor:.4f}s over "
            f"{len(floor_times)} runs -> "
            f"{compiled_pps:,.0f} pods/s; victim-set parity "
            f"{'OK' if parity_ok else 'MISMATCH'} "
            f"({len(floor_victims)} vs {len(plugin_victims)} victims)")
    else:
        log("compiled serial floor: libkoordfloor.so unavailable")
    ratio = pps / compiled_pps if compiled_pps > 0 else 0.0
    print(
        json.dumps(
            {
                "metric": f"rebalance_pods_per_sec_{num_pods}x{num_nodes}",
                "value": round(pps, 1),
                "unit": "pods/s",
                "vs_baseline": round(ratio, 2),
                "vs_compiled_floor": round(ratio, 2),
                "parity_ok": parity_ok,
                "migration_jobs": jobs_created,
                "p50_ms": round(t_pass * 1000, 2),
                "platform": jax.default_backend(),
            }
        )
    )


def run_rebalance_ab(args_cli, num_pods: int, num_nodes: int) -> None:
    """koordbalance A/B: the device rebalance pass vs the host
    LowNodeLoad oracle, back-to-back in one process (BENCH_NOTES
    convention — only the pair ratio is real on a noisy box), plus the
    drain-storm and hotspot churn pairs the subsystem opens.

    The selection pair runs BOTH engines over the SAME packed view of
    the 10k x 5k rebalance fixture (`_build_rebalance_fixture` — the
    identical BASELINE config 5 store `run_rebalance` measures): N
    timed host passes, then N timed device passes (upload + dispatch +
    readback — the warm steady state reuses unchanged device buffers
    through the shared DeviceSnapshot machinery), with victim-set
    parity asserted every iteration. The churn legs ride run_sim_churn
    and report time-to-dissipate p50/p99 from the hotspot scenario."""
    import jax

    from koordinator_tpu.balance.rebalancer import DeviceRebalancer
    from koordinator_tpu.descheduler.lownodeload import LowNodeLoad
    from koordinator_tpu.sim.scenarios import SCENARIOS

    now = 1_000_000.0
    log(f"config: {num_pods} running pods x {num_nodes} nodes "
        f"(device rebalance pass vs host LowNodeLoad, A/B pair)")
    t0 = time.perf_counter()
    store = _build_rebalance_fixture(num_pods, num_nodes, now)
    log(f"fixture: {time.perf_counter() - t0:.2f}s (not framework cost)")

    plugin = LowNodeLoad(store)
    plugin.select_victims(now=now)  # warm the pack (subscription replay)
    view, _src = plugin._view(now)
    iters = 2 if args_cli.smoke else max(5, args_cli.iters // 4)

    host_times = []
    host_picked = None
    for _ in range(iters):
        t0 = time.perf_counter()
        host_picked = plugin.select_victims_host(view)
        host_times.append(time.perf_counter() - t0)
    host_ms = float(np.median(host_times)) * 1000.0

    reb = DeviceRebalancer()
    plugin.attach_device(reb)
    dev_times = []
    parity_ok = True
    dev_picked = None
    plugin.select_victims(now=now)  # compile + first upload outside loop
    for _ in range(iters):
        t0 = time.perf_counter()
        dev_picked, _s, view = plugin.select_victims(now=now)
        dev_times.append(time.perf_counter() - t0)
        parity_ok = parity_ok and (
            plugin.last_pass_stats.get("engine") == "device"
            and list(dev_picked) == list(host_picked))
    dev_ms = float(np.median(dev_times)) * 1000.0
    log(f"host oracle: median {host_ms:.2f}ms; device pass: median "
        f"{dev_ms:.2f}ms over {iters} iters each "
        f"({len(host_picked)} victims) -> pair ratio "
        f"{host_ms / dev_ms if dev_ms else 0.0:.2f}x, victim parity "
        f"{'OK' if parity_ok else 'MISMATCH'}")
    print(json.dumps({
        "metric": f"rebalance_pass_ms_{num_pods}x{num_nodes}",
        "value": round(dev_ms, 3),
        "unit": "ms",
        "rebalance_pass_ms_device": round(dev_ms, 3),
        "rebalance_pass_ms_host": round(host_ms, 3),
        "pair_ratio_host_over_device": round(
            host_ms / dev_ms, 3) if dev_ms else 0.0,
        "victims": int(len(host_picked)),
        "parity_ok": bool(parity_ok),
        "platform": jax.default_backend(),
    }))

    # ---- the scenario pairs the subsystem opens: drain-storm (mass
    # cordon + migration) and hotspot (time-to-dissipate p50/p99 rides
    # the churn JSON's "rebalance" block)
    for name in ("drain-storm", "hotspot"):
        run_sim_churn(args_cli, SCENARIOS[name])


def run_steady_state(args_cli, num_pods: int, num_nodes: int) -> dict:
    """Warm steady-state loop: the honest answer to "what does a CYCLE cost
    once the cluster is warm?".

    Builds a store world, runs one cold cycle (full pack + compile + full
    upload), then applies a synthetic ~2% store delta per round (fresh
    pending arrivals + node-metric touches) and runs pipelined cycles
    (scheduler/cycle.CyclePipeline: incremental pack, delta upload,
    non-blocking dispatch, deferred diagnose). A serial twin scheduler
    replays the identical delta stream on an identical store; bindings are
    diffed every round and PodScheduled conditions at the end — the
    pipeline must be byte-for-byte the serial path.

    Returns the JSON fields: steady_state_pods_per_sec, pack_seconds_warm
    / pack_seconds_cold (the pack_incremental span), pipeline_occupancy
    (fraction of wall where the device has work) and pipeline_parity_ok."""
    from koordinator_tpu.scheduler.cycle import CyclePipeline, Scheduler
    from koordinator_tpu.scheduler.pipeline_parity import (
        _conditions,
        apply_round_delta,
        build_store_from_state,
    )
    from koordinator_tpu.testing import synth_full_cluster

    arrivals = max(4, num_pods // 100)      # ~1% new pods...
    metric_touches = max(2, num_nodes // 100)  # ...+ ~1% metric updates
    warmup = 1 if args_cli.smoke else 2     # delta cycles paying one-time
    rounds = 2 if args_cli.smoke else 3     # scatter/step compiles
    log(f"steady-state loop: {arrivals} arrivals + {metric_touches} metric "
        f"touches per round (~2% delta), {warmup} warmup + {rounds} "
        f"measured rounds, serial twin for parity")

    def make_store():
        _cluster, state = synth_full_cluster(
            num_nodes, num_pods, seed=42,
            num_quotas=max(8, num_pods // 100),
            num_gangs=max(4, num_pods // 50))
        return build_store_from_state(state), state

    t0 = time.perf_counter()
    store_p, state = make_store()
    store_s, _state2 = make_store()
    # waves pinned to 1: this loop is the PR-3-comparable pipeline-vs-
    # serial measurement (auto-K would fuse the deep cold queue and
    # change what steady_state_pods_per_sec/pack_seconds_cold mean);
    # the sweep below covers K > 1 explicitly
    sched_p = Scheduler(store_p, waves=1)
    pipeline = CyclePipeline(sched_p)  # KOORD_TPU_PIPELINE gates
    sched_s = Scheduler(store_s, waves=1)
    assert sched_s.pipeline_mode is False
    log(f"steady-state fixture + twin stores: {time.perf_counter() - t0:.2f}s "
        "(not framework cost)")

    def pack_span_seconds(sched) -> float:
        root = sched.tracer.roots(limit=1)[0]
        sp = root.find("pack_incremental")
        return sp.duration_seconds if sp is not None else 0.0

    def bound_list(res):
        return [(b.pod_key, b.node_name) for b in res.bound]

    def apply_delta(store, r: int, now: float) -> None:
        # the SAME delta generator the lint parity gate uses, scaled to
        # this fixture's arrival/metric-touch budget
        apply_round_delta(store, r, now, arrivals,
                          metric_touches=metric_touches,
                          prefix="ss", namespace="steady")

    now = state.now
    parity_ok = True
    t0 = time.perf_counter()
    res0 = pipeline.run_cycle(now=now)
    t_cycle0 = time.perf_counter() - t0
    pack_cold = pack_span_seconds(sched_p)
    res0_s = sched_s.run_cycle(now=now)
    if bound_list(res0) != bound_list(res0_s):
        parity_ok = False
        log("steady-state cycle 0: bindings MISMATCH vs serial twin!")
    log(f"steady-state cycle 0 (cold): {t_cycle0:.3f}s, pack "
        f"{pack_cold:.3f}s, {len(res0.bound)} bound")

    walls, packs, busys, bound_counts = [], [], [], []
    for r in range(1, warmup + rounds + 1):
        apply_delta(store_p, r, now)
        apply_delta(store_s, r, now)
        t = now + 2 * r
        t0 = time.perf_counter()
        res_p = pipeline.run_cycle(now=t)
        wall = time.perf_counter() - t0
        res_s = sched_s.run_cycle(now=t)
        if (bound_list(res_p) != bound_list(res_s)
                or sorted(res_p.failed) != sorted(res_s.failed)):
            parity_ok = False
            log(f"steady-state round {r}: MISMATCH vs serial twin")
        if r > warmup:
            walls.append(wall)
            packs.append(pack_span_seconds(sched_p))
            busys.append(res_p.device_busy_seconds)
            bound_counts.append(len(res_p.bound))
    pipeline.flush()
    if _conditions(store_p) != _conditions(store_s):
        parity_ok = False
        log("steady-state: PodScheduled conditions MISMATCH vs serial twin")

    pack_warm = float(np.median(packs))
    wall_sum = float(np.sum(walls))
    occupancy = float(np.sum(busys)) / wall_sum if wall_sum > 0 else 0.0
    steady_pps = float(np.sum(bound_counts)) / wall_sum if wall_sum else 0.0
    speedup = pack_cold / pack_warm if pack_warm > 0 else 0.0
    log(f"steady state: {steady_pps:,.0f} pods/s end-to-end over {rounds} "
        f"rounds (median cycle {float(np.median(walls))*1000:.1f}ms); pack "
        f"warm {pack_warm*1000:.1f}ms vs cold {pack_cold*1000:.1f}ms -> "
        f"{speedup:.1f}x; device occupancy {occupancy:.0%}; serial parity "
        f"{'OK' if parity_ok else 'MISMATCH'}")
    cs = sched_p.snapshot_cache.stats if sched_p.snapshot_cache else {}
    if cs:
        log(f"steady-state snapshot cache: {cs}")
    out = {
        "steady_state_pods_per_sec": round(steady_pps, 1),
        "pack_seconds_warm": round(pack_warm, 4),
        "pack_seconds_cold": round(pack_cold, 4),
        "pack_warm_speedup": round(speedup, 2),
        "pipeline_occupancy": round(occupancy, 3),
        "pipeline_parity_ok": parity_ok,
        "pipeline_enabled": pipeline.enabled,
        "steady_rows_reused": int(cs.get("pod_row_hits", 0)),
        "steady_rows_repacked": int(cs.get("pod_row_misses", 0)),
    }

    # ---- koordexplain overhead: the same steady loop at
    # KOORD_TPU_EXPLAIN=counts vs off, as a back-to-back A/B pair inside
    # ONE process (BENCH_NOTES convention: this box's noise makes numbers
    # from different runs incomparable — only the pair ratio is real)
    def steady_pps_at(explain_level: str) -> float:
        store_e, _state_e = make_store()
        sched_e = Scheduler(store_e, waves=1, explain=explain_level)
        pl_e = CyclePipeline(sched_e)
        pl_e.run_cycle(now=now)  # cold build + compile
        walls_e, bound_e = [], []
        for r in range(1, warmup + rounds + 1):
            apply_delta(store_e, r, now)
            t = now + 2 * r
            t0 = time.perf_counter()
            res_e = pl_e.run_cycle(now=t)
            wall = time.perf_counter() - t0
            if r > warmup:
                walls_e.append(wall)
                bound_e.append(len(res_e.bound))
        pl_e.flush()
        wsum = float(np.sum(walls_e))
        return float(np.sum(bound_e)) / wsum if wsum else 0.0

    pps_counts = steady_pps_at("counts")
    pps_off = steady_pps_at("off")
    overhead = (100.0 * (1.0 - pps_counts / pps_off)) if pps_off > 0 else 0.0
    log(f"explain overhead (A/B pair): counts {pps_counts:,.1f} vs off "
        f"{pps_off:,.1f} pods/s -> {overhead:+.1f}%")
    out.update({
        "explain_overhead_pct": round(overhead, 1),
        "steady_pods_per_sec_explain_counts": round(pps_counts, 1),
        "steady_pods_per_sec_explain_off": round(pps_off, 1),
    })

    # ---- koordwatch overhead: the same steady loop with the device
    # timeline + demotion accounting + queue metrics on vs off, as a
    # back-to-back A/B pair inside ONE process (BENCH_NOTES convention).
    # Target <= 2%, the koordexplain budget discipline.
    def steady_pps_watch(watch_on: bool) -> float:
        store_w, _state_w = make_store()
        sched_w = Scheduler(store_w, waves=1, watch=watch_on)
        pl_w = CyclePipeline(sched_w)
        pl_w.run_cycle(now=now)  # cold build + compile
        walls_w, bound_w = [], []
        for r in range(1, warmup + rounds + 1):
            apply_delta(store_w, r, now)
            t = now + 2 * r
            t0 = time.perf_counter()
            res_w = pl_w.run_cycle(now=t)
            wall = time.perf_counter() - t0
            if r > warmup:
                walls_w.append(wall)
                bound_w.append(len(res_w.bound))
        pl_w.flush()
        wsum = float(np.sum(walls_w))
        return float(np.sum(bound_w)) / wsum if wsum else 0.0

    pps_watch_on = steady_pps_watch(True)
    pps_watch_off = steady_pps_watch(False)
    watch_overhead = (100.0 * (1.0 - pps_watch_on / pps_watch_off)
                      if pps_watch_off > 0 else 0.0)
    log(f"koordwatch overhead (A/B pair): on {pps_watch_on:,.1f} vs off "
        f"{pps_watch_off:,.1f} pods/s -> {watch_overhead:+.1f}%")
    out.update({
        "koordwatch_overhead_pct": round(watch_overhead, 1),
        "steady_pods_per_sec_watch_on": round(pps_watch_on, 1),
        "steady_pods_per_sec_watch_off": round(pps_watch_off, 1),
    })

    # ---- fused-wave sweep: the same steady loop pinned to each K
    # (models/fused_waves.py), plus the per-dispatch fixed-overhead probe.
    # The probe times an already-compiled no-op jit with the fused step's
    # readback footprint: every dispatch pays it regardless of program
    # (the ~66ms axon-tunnel RTT on chip, sub-ms on local CPU), and a
    # fused dispatch amortizes it over K dependent rounds — that quotient
    # is fixed_overhead_ms_amortized[K].
    raw_sweep = args_cli.waves
    if raw_sweep is None:
        raw_sweep = "1,2" if args_cli.smoke else "1,2,4,8"
    sweep = [int(x) for x in raw_sweep.split(",") if x.strip()]
    if not sweep:
        return out
    import jax

    probe_buf = np.zeros(max(256, num_pods), np.int32)
    probe = jax.jit(lambda x: x + 1)
    np.asarray(probe(probe_buf))  # compile + warm
    probe_walls = []
    for _ in range(15):
        t0 = time.perf_counter()
        np.asarray(probe(probe_buf))
        probe_walls.append(time.perf_counter() - t0)
    fixed_ms = float(np.median(probe_walls)) * 1000.0
    # Every K-world consumes the SAME logical-cycle budget per round
    # (max(sweep), the run_fused_wave_parity driving pattern): a fused
    # K-dispatch IS K serial cycles, so comparing one K=8 dispatch
    # against ONE K=1 cycle — the old sweep — mismeasured by counting
    # the deep dispatch's 7 extra logical cycles as free work. All
    # worlds bind identical pods per round (parity); the wall is what
    # differs — pack/dispatch amortization across the budget.
    budget = max(sweep)
    pps_at_k = {}
    occ_at_k = {}
    waves_seen = {}
    for k in sweep:
        store_k, _state_k = make_store()
        sched_k = Scheduler(store_k, waves=k)
        pl_k = CyclePipeline(sched_k)
        pl_k.run_cycle(now=now)  # cold build + compile
        walls_k, bound_k, busy_k, waves_k = [], [], [], []
        for r in range(1, warmup + rounds + 1):
            apply_delta(store_k, r, now)
            t = now + 2 * r
            consumed, wall, busy, bound, deepest = 0, 0.0, 0.0, 0, 0
            while consumed < budget:
                # largest power of two <= the remaining budget: an odd
                # depth would compile a fresh fused program mid-loop in
                # the serial-replay world (its step cache is keyed per
                # K; only powers of two are ever warmed)
                w = min(k, budget - consumed)
                w = 1 << (w.bit_length() - 1)
                t0 = time.perf_counter()
                res_k = pl_k.run_cycle(now=t, waves=w)
                wall += time.perf_counter() - t0
                busy += res_k.device_busy_seconds
                bound += len(res_k.bound)
                consumed += max(1, res_k.waves)
                deepest = max(deepest, res_k.waves)
            if r > warmup:
                walls_k.append(wall)
                busy_k.append(busy)
                bound_k.append(bound)
                waves_k.append(deepest)
        pl_k.flush()
        wsum = float(np.sum(walls_k))
        pps_at_k[str(k)] = round(
            float(np.sum(bound_k)) / wsum if wsum else 0.0, 1)
        occ_at_k[str(k)] = round(
            float(np.sum(busy_k)) / wsum if wsum else 0.0, 3)
        waves_seen[str(k)] = int(max(waves_k)) if waves_k else 0
        log(f"wave sweep K={k}: {pps_at_k[str(k)]:,.1f} pods/s steady "
            f"over {budget} logical cycles/round (occupancy "
            f"{occ_at_k[str(k)]:.0%}, max logical cycles/dispatch "
            f"{waves_seen[str(k)]}, amortized fixed overhead "
            f"{fixed_ms / k:.2f}ms/round)")
    out.update({
        "dispatch_fixed_overhead_ms": round(fixed_ms, 3),
        "fixed_overhead_ms_amortized": {
            str(k): round(fixed_ms / k, 3) for k in sweep},
        "logical_cycles_per_round": budget,
        "pods_per_sec_at_k": pps_at_k,
        "pipeline_occupancy_at_k": occ_at_k,
        "waves_consumed_at_k": waves_seen,
    })
    return out


def run_mesh_sweep(args_cli) -> None:
    """Mesh-backed dispatch sweep (KOORD_TPU_MESH, scheduler/cycle.py +
    parallel/mesh.py): the warm steady-state loop through the PRODUCTION
    Scheduler — sharded DeviceSnapshot upload, sharded kernel, per-shard
    readback merge — at each mesh size, emitted as back-to-back A/B stash
    pairs against the single-device path in the SAME process (BENCH_NOTES
    convention: this box's noise makes numbers from different runs
    incomparable; only the pair ratio is real). Bindings are diffed
    against the single-device twin every round (mesh parity inside the
    bench, not just the lint gate).

    Unless --smoke (or --mesh-scale 0), a final SLOW row runs the
    100k pods x 50k nodes cluster — ~100k total pods flowing through the
    incremental pack memo with a 2048-pod pending queue — end to end at
    the maximum mesh size; this is the "millions of users" config no
    single chip can hold whose host side only stays feasible because of
    the PR 3 pack memo.

    JSON: pods_per_sec_at_devices{d}, pods_per_sec_single_pair{d},
    mesh_parity_ok, and mesh_scale{...} for the large config."""
    import jax

    from koordinator_tpu.scheduler.cycle import CyclePipeline, Scheduler
    from koordinator_tpu.scheduler.pipeline_parity import (
        apply_round_delta,
        build_store_from_state,
    )
    from koordinator_tpu.testing import synth_full_cluster

    num_pods = args_cli.pods or (96 if args_cli.smoke else 2048)
    num_nodes = args_cli.nodes or (48 if args_cli.smoke else 1024)
    visible = len(jax.devices())
    raw_devs = args_cli.mesh_devices or "1,2,4,8"
    devices = [int(x) for x in raw_devs.split(",") if x.strip()]
    skipped = [d for d in devices if d > visible]
    if skipped:
        log(f"mesh sweep: skipping device counts {skipped} "
            f"(only {visible} visible)")
    devices = [d for d in devices if 1 <= d <= visible]
    warmup = 1 if args_cli.smoke else 2
    rounds = 2 if args_cli.smoke else 3
    log(f"mesh sweep: {num_pods} pods x {num_nodes} nodes, device counts "
        f"{devices}, {warmup} warmup + {rounds} measured rounds, "
        f"single-device twin per count (A/B pair)")

    def make_store(nn, np_, seed=42):
        _cluster, state = synth_full_cluster(
            nn, np_, seed=seed,
            num_quotas=max(8, np_ // 100), num_gangs=max(4, np_ // 50))
        return build_store_from_state(state), state

    def bound_list(res):
        return [(b.pod_key, b.node_name) for b in res.bound]

    def steady(sched, store, now, nn, np_):
        # waves pinned to 1 by the caller: the sweep isolates the MESH
        # dimension (pipeline on, the production default); composition
        # with K-fusion is gated byte-identical by run_mesh_parity
        pipeline = CyclePipeline(sched)
        rounds_out = []
        t0 = time.perf_counter()
        res0 = pipeline.run_cycle(now=now)
        cold = time.perf_counter() - t0
        rounds_out.append(bound_list(res0))
        walls, bound = [], []
        for r in range(1, warmup + rounds + 1):
            apply_round_delta(store, r, now, max(4, np_ // 100),
                              metric_touches=max(2, nn // 100),
                              prefix="mesh", namespace="meshbench")
            t = now + 2 * r
            t0 = time.perf_counter()
            res = pipeline.run_cycle(now=t)
            wall = time.perf_counter() - t0
            rounds_out.append(bound_list(res))
            if r > warmup:
                walls.append(wall)
                bound.append(len(res.bound))
        pipeline.flush()
        wsum = float(np.sum(walls))
        pps = float(np.sum(bound)) / wsum if wsum else 0.0
        return pps, cold, rounds_out

    pps_at_dev = {}
    pair_single = {}
    parity_ok = True
    for d in devices:
        store_m, state_m = make_store(num_nodes, num_pods)
        sched_m = Scheduler(store_m, mesh=d, waves=1)
        assert (sched_m.mesh is not None
                and sched_m.mesh.devices.size == d), (
            f"mesh={d} did not resolve to a {d}-device mesh — the A/B "
            "pair would fabricate a mesh datapoint")
        pps_m, cold_m, rounds_m = steady(
            sched_m, store_m, state_m.now, num_nodes, num_pods)
        # the back-to-back single-device half of the stash pair
        store_s, state_s = make_store(num_nodes, num_pods)
        sched_s = Scheduler(store_s, mesh="off", waves=1)
        pps_s, cold_s, rounds_s = steady(
            sched_s, store_s, state_s.now, num_nodes, num_pods)
        if rounds_m != rounds_s:
            parity_ok = False
            log(f"mesh sweep d={d}: bindings MISMATCH vs single-device twin")
        pps_at_dev[str(d)] = round(pps_m, 1)
        pair_single[str(d)] = round(pps_s, 1)
        ratio = pps_m / pps_s if pps_s > 0 else 0.0
        log(f"mesh sweep d={d}: {pps_m:,.1f} pods/s (mesh) vs "
            f"{pps_s:,.1f} (single, same process) -> pair ratio "
            f"{ratio:.2f}; cold {cold_m:.2f}s/{cold_s:.2f}s")

    out = {
        "metric": f"mesh_pods_per_sec_{num_pods}x{num_nodes}",
        "value": pps_at_dev.get(str(max(devices))) if devices else 0.0,
        "unit": "pods/s",
        "pods_per_sec_at_devices": pps_at_dev,
        "pods_per_sec_single_pair": pair_single,
        "mesh_parity_ok": parity_ok,
        "rounds": rounds,
        "platform": jax.default_backend(),
        "devices_visible": visible,
    }

    scale_on = (args_cli.mesh_scale if args_cli.mesh_scale is not None
                else (0 if args_cli.smoke else 1))
    if scale_on and devices:
        from koordinator_tpu.api.objects import ObjectMeta, Pod, PodSpec
        from koordinator_tpu.api.resources import ResourceList
        from koordinator_tpu.client.store import KIND_POD

        d = max(devices)
        nn, np_, target = 50_000, 2_048, 100_000

        def top_up_assigned(store):
            # deterministic assigned filler up to the target pod count:
            # running pods only shape the per-node requested sums, which
            # the incremental pack memo aggregates — exactly the host-side
            # scale story this config exists to prove
            have = len(store.list(KIND_POD))
            for i in range(max(0, target - have)):
                store.add(KIND_POD, Pod(
                    meta=ObjectMeta(name=f"filler-{i}",
                                    namespace="meshscale",
                                    uid=f"filler-{i}"),
                    spec=PodSpec(
                        node_name=f"node-{i % nn}",
                        requests=ResourceList.of(
                            cpu=50, memory=64 * 1024 * 1024, pods=1)),
                    phase="Running"))

        log(f"mesh scale config (SLOW): {np_} pending x {nn} nodes, "
            f"topped up to {target} pods total, mesh d={d}")
        t0 = time.perf_counter()
        store_l, state_l = make_store(nn, np_, seed=7)
        top_up_assigned(store_l)
        t_fixture = time.perf_counter() - t0
        sched_l = Scheduler(store_l, mesh=d, waves=1)
        pps_l, cold_l, _ = steady(sched_l, store_l, state_l.now, nn, np_)
        # back-to-back single-device pair (one fewer round would save
        # minutes but break the pair convention — keep it symmetric)
        store_1, state_1 = make_store(nn, np_, seed=7)
        top_up_assigned(store_1)
        sched_1 = Scheduler(store_1, mesh="off", waves=1)
        pps_1, cold_1, _ = steady(sched_1, store_1, state_1.now, nn, np_)
        total_pods = len(store_l.list(KIND_POD))
        cs = sched_l.snapshot_cache.stats if sched_l.snapshot_cache else {}
        log(f"mesh scale: {pps_l:,.1f} pods/s (mesh d={d}) vs "
            f"{pps_1:,.1f} (single pair); cold cycle {cold_l:.1f}s, "
            f"fixture {t_fixture:.1f}s, {total_pods} pods in store")
        out["mesh_scale"] = {
            "config": f"{total_pods}x{nn}",
            "pending_per_cycle": np_,
            "pods_per_sec_at_devices": {str(d): round(pps_l, 1)},
            "pods_per_sec_single_pair": round(pps_1, 1),
            "cold_cycle_seconds": round(cold_l, 2),
            "pack_rows_reused": int(cs.get("pod_row_hits", 0)),
        }

    print(json.dumps(out))


def run_full_chain(args_cli, num_pods: int, num_nodes: int,
                   variant: str = "full") -> None:
    import jax

    from koordinator_tpu.models.full_chain import build_best_full_chain_step
    from koordinator_tpu.ops.loadaware import LoadAwareArgs
    from koordinator_tpu.scheduler.parity import serial_schedule_full
    from koordinator_tpu.scheduler.snapshot import build_full_chain_inputs
    from koordinator_tpu.testing import synth_full_cluster

    la = LoadAwareArgs()
    log(f"devices: {jax.devices()}")
    # BASELINE measurement-plan fixtures: config 2 isolates the
    # NodeNUMAResource Filter+Score (every node reports a 2-socket
    # topology, no quotas/gangs, more LSR cpuset pods); config 3 isolates
    # ElasticQuota+Coscheduling (200 PodGroups, 3-level tree)
    if variant == "numa":
        synth_kwargs = dict(num_quotas=0, num_gangs=0,
                            topology_fraction=1.0, lsr_fraction=0.35)
        desc = "NodeNUMAResource standalone (BASELINE config 2)"
    elif variant == "quota-gang":
        synth_kwargs = dict(
            num_quotas=max(8, min(30, num_pods // 100)),
            num_gangs=min(200, max(4, num_pods // 25)),
            topology_fraction=0.0, lsr_fraction=0.0,
        )
        desc = "ElasticQuota+Coscheduling standalone (BASELINE config 3)"
    else:
        synth_kwargs = dict(num_quotas=max(8, num_pods // 100),
                            num_gangs=max(4, num_pods // 50))
        desc = "full chain: Fit+LoadAware+NUMA+quota+gang"
    log(f"config: {num_pods} pending pods x {num_nodes} nodes ({desc})")
    t0 = time.perf_counter()
    cluster, state = synth_full_cluster(
        num_nodes,
        num_pods,
        seed=42,
        **synth_kwargs,
    )
    t_synth = time.perf_counter() - t0
    log(f"synth fixture: {t_synth:.3f}s (not framework cost)")
    t0 = time.perf_counter()
    fc, pods, nodes, tree, gang_index, ng, ngroups = build_full_chain_inputs(
        state, la
    )
    from koordinator_tpu.scheduler.snapshot import reduce_to_active_axes

    fc, active_axes = reduce_to_active_axes(fc)
    t_pack = time.perf_counter() - t0
    log(
        f"packing: {t_pack:.3f}s (padded {pods.padded_size} x {nodes.padded_size}, "
        f"{len(tree.names)} quota groups, {ng} gangs, "
        f"{len(active_axes)} active resource axes)"
    )

    step = build_best_full_chain_step(la, ng, ngroups, active_axes=active_axes,
                                      kernel=args_cli.kernel)
    t0 = time.perf_counter()
    chosen, _, _ = step(fc)
    chosen = np.asarray(jax.block_until_ready(chosen))
    t_compile = time.perf_counter() - t0
    log(f"first call (compile+run): {t_compile:.3f}s")

    # Device-resident inputs for the steady-state timing: the scheduler
    # keeps the packed cluster state on device across cycles and applies
    # store deltas instead of re-uploading, so the kernel-time metric must
    # not re-pay a full host->device snapshot upload per round. (Through
    # the axon tunnel that upload also makes numpy-input timings unstable
    # by 30-100%+ run to run.) The honest pack+upload cost is reported
    # separately as end_to_end_pods_per_sec.
    t0 = time.perf_counter()
    fc_dev = jax.block_until_ready(jax.device_put(fc))
    t_upload = time.perf_counter() - t0
    log(f"snapshot upload (host->device, full): {t_upload:.3f}s")

    iters = max(args_cli.iters, 2 if args_cli.smoke else 30)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = step(fc_dev)
        jax.block_until_ready(out[0])
        times.append(time.perf_counter() - t0)
    chosen_dev = np.asarray(out[0])
    dev_parity = not (chosen_dev != chosen).any()
    if not dev_parity:
        log("device-resident bindings DIFFER from host-input call!")
    times_ms = np.sort(np.asarray(times)) * 1000.0
    p50_ms = float(np.percentile(times_ms, 50))
    p99_ms = float(np.percentile(times_ms, 99))
    t_batch = float(np.median(times))
    scheduled = int((chosen[: pods.num_valid] >= 0).sum())
    tpu_pps = pods.num_valid / t_batch
    log(
        f"batched step: median {t_batch:.4f}s over {iters} iters for "
        f"{pods.num_valid} pods ({scheduled} scheduled) -> "
        f"{tpu_pps:,.0f} pods/s; latency p50 {p50_ms:.1f}ms "
        f"p99 {p99_ms:.1f}ms (batch == one scheduling round)"
    )

    # ---- on-chip kernel parity: whenever the selected step is NOT the XLA
    # fori_loop itself (pallas or wave), run the serial XLA step once at FULL
    # scale and diff the bindings
    parity_ok = dev_parity
    backend = getattr(step, "last_backend", None)
    if jax.default_backend() == "tpu" and backend in ("pallas", "wave"):
        from koordinator_tpu.models.full_chain import build_full_chain_step

        xla_step = build_full_chain_step(la, ng, ngroups,
                                         active_axes=active_axes)
        chosen_xla = np.asarray(jax.block_until_ready(xla_step(fc)[0]))
        mism = int((chosen != chosen_xla).sum())
        parity_ok = mism == 0
        log(f"on-chip {backend}-vs-XLA full-batch parity: "
            f"{'OK' if parity_ok else f'{mism} MISMATCHES'}")
    else:
        log(f"on-chip parity: skipped (backend={backend or 'xla'})")

    # ---- compiled serial floor: C++ transcription of the same chain, run on
    # the FULL trace (honest floor + full-batch binding parity in one run)
    from koordinator_tpu.native import floor as native_floor

    compiled_pps = 0.0
    if not native_floor.available():
        native_floor.build()
    floor_s_median = floor_s_min = 0.0
    floor_runs = 0
    if native_floor.available():
        # >=5 runs on the same padded trace; the MIN (the floor's best
        # showing — host-load noise only ever slows it) is the ratio
        # denominator, so vs_compiled_floor is the most conservative
        # number the data supports. Median also reported for context.
        floor_times = []
        for _ in range(1 if args_cli.smoke else 5):
            t0 = time.perf_counter()
            chosen_native = native_floor.serial_schedule_full_native(
                fc, la, num_groups=ngroups, active_axes=active_axes)
            floor_times.append(time.perf_counter() - t0)
        floor_runs = len(floor_times)
        floor_s_median = float(np.median(floor_times))
        floor_s_min = float(np.min(floor_times))
        compiled_pps = pods.num_valid / floor_s_min
        mism = int(
            (chosen[: pods.num_valid] != chosen_native[: pods.num_valid]).sum()
        )
        parity_ok = parity_ok and mism == 0
        log(
            f"compiled serial floor (C++ -O2, full trace): min "
            f"{floor_s_min:.3f}s / median {floor_s_median:.3f}s over "
            f"{floor_runs} runs for "
            f"{pods.num_valid} pods -> {compiled_pps:,.1f} pods/s (min); "
            f"binding parity vs batched step: "
            f"{'OK' if mism == 0 else f'{mism} MISMATCHES'}"
        )
    else:
        log("compiled serial floor: libkoordfloor.so unavailable (no g++?)")

    # ---- python serial floor (numpy oracle) on a prefix sample
    if pods.padded_size <= 1024:
        t0 = time.perf_counter()
        chosen_serial = serial_schedule_full(fc, la,
                                            active_axes=active_axes)
        t_serial = time.perf_counter() - t0
        python_pps = pods.num_valid / t_serial
        mism = int(
            (chosen[: pods.num_valid] != chosen_serial[: pods.num_valid]).sum()
        )
        parity_ok = parity_ok and mism == 0
        log(
            f"python serial floor: {t_serial:.3f}s for {pods.num_valid} pods "
            f"-> {python_pps:,.1f} pods/s; parity on full batch: "
            f"{'OK' if mism == 0 else f'{mism} MISMATCHES'}"
        )
    else:
        from koordinator_tpu.scheduler.parity import serial_schedule_full_core

        sample = min(args_cli.serial_sample, pods.num_valid)
        fc_slice = slice_full_chain(fc, sample)
        t0 = time.perf_counter()
        serial_schedule_full_core(fc_slice, la, active_axes=active_axes)
        t_serial = time.perf_counter() - t0
        python_pps = sample / t_serial
        log(
            f"python serial floor: {t_serial:.3f}s for {sample} pods "
            f"-> {python_pps:,.1f} pods/s (prefix sample)"
        )

    # ---- marginal (tunnel-free) kernel time. Through the axon tunnel,
    # EVERY synchronized call pays a fixed ~66 ms result-readback RTT
    # (measured: a zero-compute scalar add + np.asarray costs the same
    # 66 ms; a ~44 ms matmul chain costs 66+44). The per-call numbers
    # above keep that cost — it is what this environment delivers — but
    # the kernel's own time is recovered differentially: ONE jit runs the
    # step S times with a forced serial data dependency, so
    # wall(S2) - wall(S1) = (S2-S1) x kernel with the fixed RTT cancelled.
    # On local (untunneled) TPU hardware the per-call number converges to
    # this marginal one.
    # None until the probe actually RUNS: a skipped probe (CPU backend,
    # smoke, unsupported kernel) must OMIT these keys from the JSON —
    # emitting 0.0/{} here read as a regression-to-zero in trajectory
    # tooling diffing BENCH_*.json across rounds
    kernel_ms_marginal = None
    fixed_overhead_ms = None
    marginal_pps = 0.0
    marginal_walls_ms = None  # str(S) -> measured wall ms (auditable)
    if (jax.default_backend() == "tpu" and not args_cli.smoke
            and backend in ("pallas", "xla", None)):
        try:
            import jax.numpy as jnp

            from koordinator_tpu.models.full_chain import (
                build_full_chain_step,
            )
            from koordinator_tpu.ops.pallas_full_chain import (
                build_pallas_full_chain_step,
            )

            if backend == "pallas":
                # match the dispatched variant: a volume-less batch ran
                # the enable_volumes=False kernel above, so the marginal
                # measurement must time the same program
                has_vol = bool((np.asarray(fc.vol_needed) > 0).any())
                raw = build_pallas_full_chain_step(
                    la, ng, ngroups, active_axes=active_axes, jit=False,
                    enable_volumes=has_vol)
            else:
                raw = build_full_chain_step(
                    la, ng, ngroups, active_axes=active_axes, jit=False)
            P_pad = int(fc.base.fit_requests.shape[0])

            def many(fc_in, S):
                def body(_i, carry):
                    dep = carry[0] > jnp.int32(-(2**30))  # always True:
                    # forces batch k to wait for batch k-1 on device
                    fc_i = fc_in._replace(base=fc_in.base._replace(
                        node_ok=fc_in.base.node_ok & dep))
                    chosen_i, _r, _q = raw(fc_i)
                    return chosen_i
                return jax.lax.fori_loop(
                    0, S, body, jnp.full(P_pad, -1, jnp.int32))

            # 3+ S values so the slope is a least-squares fit, not a
            # noise-amplifying 2-point difference (a single outlier median
            # at S=1 used to swing the headline silently)
            reps = (1, 5, 9)
            walls = {}
            for S in reps:
                # per-S compile is the measurement itself (each S is a
                # distinct unrolled program)
                # koordlint: disable=jax-jit-in-loop
                fn = jax.jit(lambda f, S=S: many(f, S))
                np.asarray(fn(fc_dev))  # compile + warm
                ws = []
                for _ in range(5):
                    t0 = time.perf_counter()
                    np.asarray(fn(fc_dev))
                    ws.append(time.perf_counter() - t0)
                walls[S] = float(np.median(ws)) * 1000.0
            slope, intercept = np.polyfit(
                list(reps), [walls[S] for S in reps], 1)
            kernel_ms_marginal = max(float(slope), 0.0)
            fixed_overhead_ms = max(float(intercept), 0.0)
            if kernel_ms_marginal > 0:
                marginal_pps = pods.num_valid / (kernel_ms_marginal / 1000.0)
            log(
                f"marginal kernel (least-squares over S={reps} chained "
                f"in-jit, fixed readback cancelled): "
                f"{kernel_ms_marginal:.2f}ms/batch "
                f"-> {marginal_pps:,.0f} pods/s; fixed per-call overhead "
                f"{fixed_overhead_ms:.1f}ms (axon tunnel readback); raw "
                f"walls "
                + ", ".join(f"S={S}: {walls[S]:.2f}ms" for S in reps)
            )
            marginal_walls_ms = {str(S): round(walls[S], 3) for S in reps}
        except Exception as e:  # measurement is advisory, never fatal
            log(f"marginal kernel measurement skipped: {e}")

    vs_compiled = tpu_pps / compiled_pps if compiled_pps > 0 else 0.0
    vs_python = tpu_pps / python_pps if python_pps > 0 else 0.0
    # end-to-end scheduler time: host pack + full snapshot upload + step.
    # This is the COLD-path bound; the warm steady-state loop below runs
    # real pipelined cycles against store deltas and reports what a cycle
    # costs once the cluster is warm.
    e2e_pps = pods.num_valid / (t_pack + t_upload + t_batch)
    log(f"end-to-end (pack {t_pack:.3f}s + upload {t_upload:.3f}s + step "
        f"{t_batch:.3f}s): {e2e_pps:,.0f} pods/s")
    steady = {}
    if variant == "full":
        try:
            steady = run_steady_state(args_cli, num_pods, num_nodes)
        except Exception as e:  # the cold numbers must still ship
            log(f"steady-state loop failed: {e!r}")
            steady = {"steady_state_error": repr(e)[:200]}
    suffix = {"numa": "numa", "quota-gang": "quota_gang"}.get(
        variant, "full_chain")
    marginal_fields = {}
    if marginal_walls_ms is not None:
        # the probe ran: these are measurements (0.0 would be a real
        # measured zero, not a skip artifact)
        marginal_fields = {
            "kernel_ms_marginal": round(kernel_ms_marginal, 2),
            "marginal_walls_ms": marginal_walls_ms,
            "fixed_overhead_ms": round(fixed_overhead_ms, 1),
            "pods_per_sec_marginal": round(marginal_pps, 1),
            "vs_compiled_floor_marginal": round(
                marginal_pps / compiled_pps if compiled_pps else 0.0, 2),
        }
    print(
        json.dumps(
            {
                "metric": f"pods_scheduled_per_sec_{num_pods}x{num_nodes}_{suffix}",
                "value": round(tpu_pps, 1),
                "unit": "pods/s",
                "vs_baseline": round(vs_compiled, 2),
                "vs_compiled_floor": round(vs_compiled, 2),
                "vs_python_floor": round(vs_python, 2),
                "parity_ok": parity_ok,
                "p50_ms": round(p50_ms, 2),
                "p99_ms": round(p99_ms, 2),
                "end_to_end_pods_per_sec": round(e2e_pps, 1),
                "pack_seconds": round(t_pack, 3),
                "upload_seconds": round(t_upload, 3),
                "floor_s_median": round(floor_s_median, 3),
                "floor_s_min": round(floor_s_min, 3),
                "floor_runs": floor_runs,
                **marginal_fields,
                "platform": jax.default_backend(),
                **steady,
            }
        )
    )


def slice_full_chain(fc, num_pods: int):
    """First-k-pods view of FullChainInputs."""
    pod_fields = (
        "requests",
        "gang_id",
        "quota_id",
        "needs_numa",
        "needs_bind",
        "cores_needed",
        "full_pcpus",
    )
    kwargs = {
        k: (v[:num_pods] if k in pod_fields else v)
        for k, v in fc._asdict().items()
        if k != "base"
    }
    return type(fc)(base=ScheduleInputsSlice(fc.base, num_pods), **kwargs)


def ScheduleInputsSlice(inputs, num_pods: int):
    """First-k-pods view of ScheduleInputs (pod axis sliced, nodes kept)."""
    return type(inputs)(
        fit_requests=inputs.fit_requests[:num_pods],
        estimated=inputs.estimated[:num_pods],
        is_prod=inputs.is_prod[:num_pods],
        is_daemonset=inputs.is_daemonset[:num_pods],
        pod_valid=inputs.pod_valid[:num_pods],
        allocatable=inputs.allocatable,
        requested=inputs.requested,
        node_ok=inputs.node_ok,
        la_filter_usage=inputs.la_filter_usage,
        la_has_filter_usage=inputs.la_has_filter_usage,
        la_filter_thresholds=inputs.la_filter_thresholds,
        la_prod_thresholds=inputs.la_prod_thresholds,
        la_prod_pod_usage=inputs.la_prod_pod_usage,
        la_term_nonprod=inputs.la_term_nonprod,
        la_term_prod=inputs.la_term_prod,
        la_score_valid=inputs.la_score_valid,
        la_filter_skip=inputs.la_filter_skip,
        weights=inputs.weights,
    )


if __name__ == "__main__":
    main()
