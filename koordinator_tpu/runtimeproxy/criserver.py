"""CRI proxy server: a real gRPC server on a unix socket between kubelet and
the backend runtime.

Analog of reference `pkg/runtimeproxy/server/cri/criserver.go`: kubelet dials
the proxy endpoint; intercepted RuntimeService methods run the hook chain
(PreRunPodSandbox / PreCreateContainer / ...) through the koordlet hook
server, merge the hook response into the CRI request, and forward the merged
request to the backend runtime's socket; every other method is transparently
passed through as raw bytes (criserver.go:92-95 TransparentHandler). On
start, ``failover()`` replays ListPodSandbox/ListContainers from the backend
to rebuild the pod/container store after a proxy restart (criserver.go:236+).

FailurePolicy (reference pkg/runtimeproxy/config) governs hook-server
outages: Ignore forwards the original request, Fail aborts the RPC so
kubelet retries.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from koordinator_tpu.runtimeproxy import api_pb2, cri_pb2
from koordinator_tpu.runtimeproxy.server import FailurePolicy

_SERVICE = "runtime.v1.RuntimeService"

# method -> (request type, response type); the typed (interceptable) surface
_METHODS = {
    "RunPodSandbox": (cri_pb2.RunPodSandboxRequest, cri_pb2.RunPodSandboxResponse),
    "StopPodSandbox": (cri_pb2.StopPodSandboxRequest, cri_pb2.StopPodSandboxResponse),
    "CreateContainer": (cri_pb2.CreateContainerRequest, cri_pb2.CreateContainerResponse),
    "StartContainer": (cri_pb2.StartContainerRequest, cri_pb2.StartContainerResponse),
    "StopContainer": (cri_pb2.StopContainerRequest, cri_pb2.StopContainerResponse),
    "UpdateContainerResources": (
        cri_pb2.UpdateContainerResourcesRequest,
        cri_pb2.UpdateContainerResourcesResponse,
    ),
    "ListPodSandbox": (cri_pb2.ListPodSandboxRequest, cri_pb2.ListPodSandboxResponse),
    "ListContainers": (cri_pb2.ListContainersRequest, cri_pb2.ListContainersResponse),
}


def _hook_resources_from_cri(
    res: cri_pb2.LinuxContainerResources,
) -> api_pb2.LinuxContainerResources:
    return api_pb2.LinuxContainerResources(
        cpu_period=res.cpu_period,
        cpu_quota=res.cpu_quota,
        cpu_shares=res.cpu_shares,
        memory_limit_bytes=res.memory_limit_in_bytes,
        cpuset_cpus=res.cpuset_cpus,
        cpuset_mems=res.cpuset_mems,
    )


def _merge_hook_into_cri(
    res: cri_pb2.LinuxContainerResources,
    patch: Optional[api_pb2.LinuxContainerResources],
) -> None:
    """Overlay non-zero hook fields onto the CRI request in place
    (resexecutor/cri/container.go UpdateResource semantics)."""
    if patch is None:
        return
    for src, dst in (
        ("cpu_period", "cpu_period"),
        ("cpu_quota", "cpu_quota"),
        ("cpu_shares", "cpu_shares"),
        ("memory_limit_bytes", "memory_limit_in_bytes"),
    ):
        v = getattr(patch, src)
        if v:
            setattr(res, dst, v)
    if patch.cpuset_cpus:
        res.cpuset_cpus = patch.cpuset_cpus
    if patch.cpuset_mems:
        res.cpuset_mems = patch.cpuset_mems
    if patch.cpu_bvt_warp_ns:
        # no first-class CRI field: lower to the unified cgroup map
        res.unified["cpu.bvt_warp_ns"] = str(patch.cpu_bvt_warp_ns)


class CRIClient:
    """Typed client for the trimmed RuntimeService (used by the proxy toward
    the backend, and by tests as the 'kubelet')."""

    def __init__(self, socket_path: str, timeout_seconds: float = 5.0):
        import grpc

        self._channel = grpc.insecure_channel(f"unix://{socket_path}")
        self._timeout = timeout_seconds
        self._stubs = {
            method: self._channel.unary_unary(
                f"/{_SERVICE}/{method}",
                request_serializer=req_t.SerializeToString,
                response_deserializer=res_t.FromString,
            )
            for method, (req_t, res_t) in _METHODS.items()
        }
        # raw-bytes lane for methods outside the trimmed surface
        self._raw = {}

    def call(self, method: str, request):
        return self._stubs[method](request, timeout=self._timeout)

    def call_raw(self, method: str, payload: bytes) -> bytes:
        if method not in self._raw:
            self._raw[method] = self._channel.unary_unary(
                f"/{_SERVICE}/{method}",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
        return self._raw[method](payload, timeout=self._timeout)

    def close(self) -> None:
        self._channel.close()


class CRIProxyServer:
    """The koord-runtime-proxy binary's core: UDS in, UDS out."""

    def __init__(self, proxy_endpoint: str, backend_endpoint: str,
                 hook_client=None,
                 failure_policy: FailurePolicy = FailurePolicy.IGNORE):
        self.proxy_endpoint = proxy_endpoint
        self.backend = CRIClient(backend_endpoint)
        self.hook_client = hook_client
        self.failure_policy = failure_policy
        # store/ analog: sandbox id -> hook pod meta; container id -> (sandbox
        # id, hook container meta)
        self.pod_store: Dict[str, api_pb2.PodSandboxMeta] = {}
        self.container_store: Dict[str, Tuple[str, api_pb2.ContainerMeta]] = {}
        self._lock = threading.Lock()
        self._server = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        import grpc
        from concurrent import futures

        self.failover()

        outer = self

        class _Generic(grpc.GenericRpcHandler):
            def service(self, call_details):
                service, _, method = call_details.method.lstrip("/").partition("/")
                if service != _SERVICE:
                    return None
                if method in _METHODS:
                    req_t, _res_t = _METHODS[method]
                    return grpc.unary_unary_rpc_method_handler(
                        lambda request, context, m=method: outer._intercept(
                            m, request, context
                        ),
                        request_deserializer=req_t.FromString,
                        response_serializer=lambda msg: msg.SerializeToString(),
                    )
                # transparent passthrough: raw bytes to the backend
                return grpc.unary_unary_rpc_method_handler(
                    lambda payload, context, m=method: outer.backend.call_raw(
                        m, payload
                    ),
                    request_deserializer=lambda b: b,
                    response_serializer=lambda b: b,
                )

        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((_Generic(),))
        self._server.add_insecure_port(f"unix://{self.proxy_endpoint}")
        self._server.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=None)
            self._server = None
        self.backend.close()

    def failover(self) -> None:
        """Rebuild the pod/container store from the backend after a restart
        (criserver.go failOver)."""
        try:
            sandboxes = self.backend.call(
                "ListPodSandbox", cri_pb2.ListPodSandboxRequest()
            )
            containers = self.backend.call(
                "ListContainers", cri_pb2.ListContainersRequest()
            )
        except Exception:
            return  # backend not up yet; stores fill as calls arrive
        with self._lock:
            for sandbox in sandboxes.items:
                self.pod_store[sandbox.id] = api_pb2.PodSandboxMeta(
                    name=sandbox.metadata.name,
                    namespace=sandbox.metadata.namespace,
                    uid=sandbox.metadata.uid,
                    labels=dict(sandbox.labels),
                    annotations=dict(sandbox.annotations),
                )
            for container in containers.containers:
                self.container_store[container.id] = (
                    container.pod_sandbox_id,
                    api_pb2.ContainerMeta(
                        name=container.metadata.name,
                        id=container.id,
                        labels=dict(container.labels),
                        annotations=dict(container.annotations),
                    ),
                )

    # -- hook dispatch -------------------------------------------------------
    def _call_hook(self, method: str, request, context):
        if self.hook_client is None:
            return None
        try:
            return self.hook_client.call(method, request)
        except Exception as exc:
            if self.failure_policy is FailurePolicy.FAIL:
                import grpc

                context.abort(
                    grpc.StatusCode.INTERNAL,
                    f"runtime hook {method} failed: {exc}",
                )
            return None

    def _intercept(self, method: str, request, context):
        handler = getattr(self, f"_do_{_snake(method)}", None)
        if handler is not None:
            return handler(request, context)
        return self.backend.call(method, request)

    # -- intercepted methods (criserver.go:106-131) --------------------------
    def _do_run_pod_sandbox(self, request, context):
        config = request.config
        pod_meta = api_pb2.PodSandboxMeta(
            name=config.metadata.name,
            namespace=config.metadata.namespace,
            uid=config.metadata.uid,
            labels=dict(config.labels),
            annotations=dict(config.annotations),
            cgroup_parent=config.linux.cgroup_parent,
        )
        res = self._call_hook(
            "PreRunPodSandboxHook",
            api_pb2.PodSandboxHookRequest(pod_meta=pod_meta),
            context,
        )
        if res is not None:
            for k, v in res.annotations.items():
                config.annotations[k] = v
                pod_meta.annotations[k] = v
            if res.cgroup_parent:
                config.linux.cgroup_parent = res.cgroup_parent
                pod_meta.cgroup_parent = res.cgroup_parent
        response = self.backend.call("RunPodSandbox", request)
        with self._lock:
            self.pod_store[response.pod_sandbox_id] = pod_meta
        return response

    def _do_stop_pod_sandbox(self, request, context):
        response = self.backend.call("StopPodSandbox", request)
        with self._lock:
            pod_meta = self.pod_store.pop(
                request.pod_sandbox_id, api_pb2.PodSandboxMeta()
            )
        self._call_hook(
            "PostStopPodSandboxHook",
            api_pb2.PodSandboxHookRequest(pod_meta=pod_meta),
            context,
        )
        return response

    def _do_create_container(self, request, context):
        with self._lock:
            pod_meta = self.pod_store.get(request.pod_sandbox_id)
        if pod_meta is None:
            pod_meta = api_pb2.PodSandboxMeta(
                name=request.sandbox_config.metadata.name,
                namespace=request.sandbox_config.metadata.namespace,
                uid=request.sandbox_config.metadata.uid,
                labels=dict(request.sandbox_config.labels),
                annotations=dict(request.sandbox_config.annotations),
            )
        container_meta = api_pb2.ContainerMeta(
            name=request.config.metadata.name,
            labels=dict(request.config.labels),
            annotations=dict(request.config.annotations),
        )
        hook_req = api_pb2.ContainerResourceHookRequest(
            pod_meta=pod_meta,
            container_meta=container_meta,
            resources=_hook_resources_from_cri(request.config.linux.resources),
        )
        for kv in request.config.envs:
            hook_req.env[kv.key] = kv.value
        res = self._call_hook("PreCreateContainerHook", hook_req, context)
        if res is not None:
            _merge_hook_into_cri(request.config.linux.resources, res.resources)
            # hook env wins on key collision (same semantics as the
            # in-process RuntimeProxy merge in server.py)
            by_key = {kv.key: kv for kv in request.config.envs}
            for k, v in res.env.items():
                if k in by_key:
                    by_key[k].value = v
                else:
                    request.config.envs.add(key=k, value=v)
        response = self.backend.call("CreateContainer", request)
        container_meta.id = response.container_id
        with self._lock:
            self.container_store[response.container_id] = (
                request.pod_sandbox_id, container_meta
            )
        return response

    def _container_hook_request(self, container_id: str):
        with self._lock:
            sandbox_id, container_meta = self.container_store.get(
                container_id, ("", api_pb2.ContainerMeta(id=container_id))
            )
            pod_meta = self.pod_store.get(sandbox_id, api_pb2.PodSandboxMeta())
        return api_pb2.ContainerResourceHookRequest(
            pod_meta=pod_meta, container_meta=container_meta
        )

    def _do_start_container(self, request, context):
        self._call_hook(
            "PreStartContainerHook",
            self._container_hook_request(request.container_id),
            context,
        )
        return self.backend.call("StartContainer", request)

    def _do_stop_container(self, request, context):
        response = self.backend.call("StopContainer", request)
        hook_req = self._container_hook_request(request.container_id)
        with self._lock:
            self.container_store.pop(request.container_id, None)
        self._call_hook("PostStopContainerHook", hook_req, context)
        return response

    def _do_update_container_resources(self, request, context):
        hook_req = self._container_hook_request(request.container_id)
        hook_req.resources.CopyFrom(_hook_resources_from_cri(request.linux))
        res = self._call_hook("PreUpdateContainerResourcesHook", hook_req, context)
        if res is not None:
            _merge_hook_into_cri(request.linux, res.resources)
        return self.backend.call("UpdateContainerResources", request)


def _snake(name: str) -> str:
    out = []
    for ch in name:
        if ch.isupper() and out:
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


class FakeContainerdServer:
    """A backend runtime implemented as a real gRPC server on a second unix
    socket (the e2e stand-in for containerd). Records every request it
    receives; unknown methods (the passthrough lane) land in ``raw_calls``."""

    def __init__(self, socket_path: str):
        import itertools

        self.socket_path = socket_path
        self.requests = []  # (method, request message)
        self.raw_calls = []  # (method, payload bytes)
        self._counter = itertools.count(1)
        self._sandboxes: Dict[str, cri_pb2.PodSandbox] = {}
        self._containers: Dict[str, cri_pb2.Container] = {}
        # gRPC handler threads run concurrently; every request-log and
        # sandbox/container map access goes through this lock
        self._lock = threading.Lock()
        self._server = None

    def _next_id(self, prefix: str) -> str:
        return f"{prefix}-{next(self._counter)}"

    def handle(self, method: str, request):
        with self._lock:
            self.requests.append((method, request))
            if method == "RunPodSandbox":
                sandbox_id = self._next_id("sandbox")
                self._sandboxes[sandbox_id] = cri_pb2.PodSandbox(
                    id=sandbox_id, metadata=request.config.metadata,
                    labels=request.config.labels,
                    annotations=request.config.annotations,
                )
                return cri_pb2.RunPodSandboxResponse(pod_sandbox_id=sandbox_id)
            if method == "StopPodSandbox":
                self._sandboxes.pop(request.pod_sandbox_id, None)
                return cri_pb2.StopPodSandboxResponse()
            if method == "CreateContainer":
                container_id = self._next_id("container")
                self._containers[container_id] = cri_pb2.Container(
                    id=container_id, pod_sandbox_id=request.pod_sandbox_id,
                    metadata=request.config.metadata,
                    labels=request.config.labels,
                    annotations=request.config.annotations,
                )
                return cri_pb2.CreateContainerResponse(
                    container_id=container_id)
            if method == "StartContainer":
                return cri_pb2.StartContainerResponse()
            if method == "StopContainer":
                self._containers.pop(request.container_id, None)
                return cri_pb2.StopContainerResponse()
            if method == "UpdateContainerResources":
                return cri_pb2.UpdateContainerResourcesResponse()
            if method == "ListPodSandbox":
                return cri_pb2.ListPodSandboxResponse(
                    items=list(self._sandboxes.values())
                )
            if method == "ListContainers":
                return cri_pb2.ListContainersResponse(
                    containers=list(self._containers.values())
                )
        raise KeyError(method)

    def start(self) -> None:
        import grpc
        from concurrent import futures

        outer = self

        class _Generic(grpc.GenericRpcHandler):
            def service(self, call_details):
                service, _, method = call_details.method.lstrip("/").partition("/")
                if service != _SERVICE:
                    return None
                if method in _METHODS:
                    req_t, _ = _METHODS[method]
                    return grpc.unary_unary_rpc_method_handler(
                        lambda request, context, m=method: outer.handle(m, request),
                        request_deserializer=req_t.FromString,
                        response_serializer=lambda msg: msg.SerializeToString(),
                    )

                def raw(payload, context, m=method):
                    with outer._lock:
                        outer.raw_calls.append((m, payload))
                    if m == "Version":
                        return cri_pb2.VersionResponse(
                            version="0.1.0", runtime_name="fake-containerd",
                            runtime_version="1.7", runtime_api_version="v1",
                        ).SerializeToString()
                    return b""

                return grpc.unary_unary_rpc_method_handler(
                    raw,
                    request_deserializer=lambda b: b,
                    response_serializer=lambda b: b,
                )

        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        self._server.add_generic_rpc_handlers((_Generic(),))
        self._server.add_insecure_port(f"unix://{self.socket_path}")
        self._server.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=None)
            self._server = None
