"""Hook service transport: gRPC/UDS client + server glue.

The reference talks gRPC over unix sockets between runtime-proxy and koordlet
(dispatcher -> RuntimeHookService). grpc_tools isn't available for stub
codegen, so the service is wired with grpc's generic handler API over the
protoc-generated message classes — same wire protocol, no generated stubs.
An in-process client short-circuits the transport for tests and for NRI-style
embedding (hooks in the same process)."""

from __future__ import annotations

from typing import Callable, Dict, Optional

from koordinator_tpu.runtimeproxy import api_pb2

SERVICE_NAME = "koordinator.runtimeproxy.v1.RuntimeHookService"

POD_METHODS = ("PreRunPodSandboxHook", "PostStopPodSandboxHook")
CONTAINER_METHODS = (
    "PreCreateContainerHook",
    "PreStartContainerHook",
    "PostStartContainerHook",
    "PreUpdateContainerResourcesHook",
    "PostStopContainerHook",
)


def _req_res_types(method: str):
    if method in POD_METHODS:
        return api_pb2.PodSandboxHookRequest, api_pb2.PodSandboxHookResponse
    return (
        api_pb2.ContainerResourceHookRequest,
        api_pb2.ContainerResourceHookResponse,
    )


class HookClient:
    """gRPC client over a unix socket."""

    def __init__(self, socket_path: str, timeout_seconds: float = 5.0):
        import grpc

        self._channel = grpc.insecure_channel(f"unix://{socket_path}")
        self._timeout = timeout_seconds
        self._stubs: Dict[str, Callable] = {}
        for method in POD_METHODS + CONTAINER_METHODS:
            req_t, res_t = _req_res_types(method)
            self._stubs[method] = self._channel.unary_unary(
                f"/{SERVICE_NAME}/{method}",
                request_serializer=req_t.SerializeToString,
                response_deserializer=res_t.FromString,
            )

    def call(self, method: str, request):
        return self._stubs[method](request, timeout=self._timeout)

    def close(self) -> None:
        self._channel.close()


class InProcessHookClient:
    """Short-circuit transport: calls the handler object directly."""

    def __init__(self, handler):
        self._handler = handler

    def call(self, method: str, request):
        return getattr(self._handler, method)(request)


def serve_hook_service(handler, socket_path: str):
    """Start a gRPC server for RuntimeHookService on a unix socket; returns the
    started server (caller stops it). `handler` has one method per RPC taking
    the request message and returning the response message."""
    import grpc
    from concurrent import futures

    def make_behavior(method: str):
        def behavior(request, context):
            return getattr(handler, method)(request)

        return behavior

    handlers = {}
    for method in POD_METHODS + CONTAINER_METHODS:
        req_t, res_t = _req_res_types(method)
        handlers[method] = grpc.unary_unary_rpc_method_handler(
            make_behavior(method),
            request_deserializer=req_t.FromString,
            response_serializer=res_t.SerializeToString,
        )
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )
    server.add_insecure_port(f"unix://{socket_path}")
    server.start()
    return server
