"""Runtime proxy: fake CRI server logic between kubelet and the real runtime.

Analog of reference `pkg/runtimeproxy/server/cri/` + `dispatcher/` + `store/`:
intercepts the container-lifecycle calls kubelet makes, invokes the registered
hook service before/after selected calls, merges the hook response into the
request, and forwards to the backend runtime (containerd/docker; a
FakeRuntimeBackend here records the merged calls for tests). FailurePolicy
(Fail|Ignore, reference config/) governs hook-server outages. A store of
pod/container info keeps context for calls that lack it (StopContainer)."""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from koordinator_tpu.runtimeproxy import api_pb2


class FailurePolicy(enum.Enum):
    FAIL = "Fail"
    IGNORE = "Ignore"


@dataclass
class RuntimeCall:
    method: str
    pod_name: str
    container_name: str = ""
    resources: Optional[api_pb2.LinuxContainerResources] = None
    env: Dict[str, str] = field(default_factory=dict)
    cgroup_parent: str = ""


class FakeRuntimeBackend:
    """Stands in for containerd/docker: records forwarded calls."""

    def __init__(self) -> None:
        self.calls: List[RuntimeCall] = []

    def forward(self, call: RuntimeCall) -> None:
        self.calls.append(call)


class RuntimeProxy:
    def __init__(self, hook_client, backend: Optional[FakeRuntimeBackend] = None,
                 failure_policy: FailurePolicy = FailurePolicy.IGNORE):
        self.hook_client = hook_client
        self.backend = backend or FakeRuntimeBackend()
        self.failure_policy = failure_policy
        # store/ analog: pod uid -> sandbox meta; container id -> (pod, meta)
        self.pod_store: Dict[str, api_pb2.PodSandboxMeta] = {}
        self.container_store: Dict[str, api_pb2.ContainerMeta] = {}
        self.container_pod: Dict[str, str] = {}

    # ------------------------------------------------------------------
    def _call_hook(self, method: str, request):
        try:
            return self.hook_client.call(method, request)
        except Exception:
            if self.failure_policy is FailurePolicy.FAIL:
                raise
            return None

    @staticmethod
    def _merge_resources(base: api_pb2.LinuxContainerResources,
                         patch: Optional[api_pb2.LinuxContainerResources]):
        if patch is None:
            return base
        out = api_pb2.LinuxContainerResources()
        out.CopyFrom(base)
        for fld in ("cpu_period", "cpu_quota", "cpu_shares",
                    "memory_limit_bytes", "cpu_bvt_warp_ns"):
            v = getattr(patch, fld)
            if v:
                setattr(out, fld, v)
        if patch.cpuset_cpus:
            out.cpuset_cpus = patch.cpuset_cpus
        if patch.cpuset_mems:
            out.cpuset_mems = patch.cpuset_mems
        return out

    # -- CRI surface ----------------------------------------------------
    def run_pod_sandbox(self, pod_meta: api_pb2.PodSandboxMeta,
                        resources: Optional[api_pb2.LinuxContainerResources] = None):
        req = api_pb2.PodSandboxHookRequest(
            pod_meta=pod_meta,
            resources=resources or api_pb2.LinuxContainerResources(),
        )
        res = self._call_hook("PreRunPodSandboxHook", req)
        merged = self._merge_resources(req.resources, res.resources if res else None)
        cgroup_parent = (
            res.cgroup_parent if res and res.cgroup_parent else pod_meta.cgroup_parent
        )
        if res:
            for k, v in res.annotations.items():
                pod_meta.annotations[k] = v
        self.pod_store[pod_meta.uid] = pod_meta
        self.backend.forward(
            RuntimeCall("RunPodSandbox", pod_meta.name, resources=merged,
                        cgroup_parent=cgroup_parent)
        )
        return merged

    def create_container(self, pod_uid: str, container: api_pb2.ContainerMeta,
                         resources: Optional[api_pb2.LinuxContainerResources] = None,
                         env: Optional[Dict[str, str]] = None):
        pod_meta = self.pod_store.get(pod_uid, api_pb2.PodSandboxMeta(uid=pod_uid))
        req = api_pb2.ContainerResourceHookRequest(
            pod_meta=pod_meta,
            container_meta=container,
            resources=resources or api_pb2.LinuxContainerResources(),
        )
        for k, v in (env or {}).items():
            req.env[k] = v
        res = self._call_hook("PreCreateContainerHook", req)
        merged = self._merge_resources(req.resources, res.resources if res else None)
        out_env = dict(env or {})
        if res:
            out_env.update(dict(res.env))
        self.container_store[container.id] = container
        self.container_pod[container.id] = pod_uid
        self.backend.forward(
            RuntimeCall("CreateContainer", pod_meta.name, container.name,
                        resources=merged, env=out_env)
        )
        return merged, out_env

    def update_container_resources(self, container_id: str,
                                   resources: api_pb2.LinuxContainerResources):
        pod_uid = self.container_pod.get(container_id, "")
        pod_meta = self.pod_store.get(pod_uid, api_pb2.PodSandboxMeta(uid=pod_uid))
        container = self.container_store.get(
            container_id, api_pb2.ContainerMeta(id=container_id)
        )
        req = api_pb2.ContainerResourceHookRequest(
            pod_meta=pod_meta, container_meta=container, resources=resources
        )
        res = self._call_hook("PreUpdateContainerResourcesHook", req)
        merged = self._merge_resources(resources, res.resources if res else None)
        self.backend.forward(
            RuntimeCall("UpdateContainerResources", pod_meta.name, container.name,
                        resources=merged)
        )
        return merged

    def stop_container(self, container_id: str):
        pod_uid = self.container_pod.get(container_id, "")
        pod_meta = self.pod_store.get(pod_uid, api_pb2.PodSandboxMeta(uid=pod_uid))
        container = self.container_store.pop(
            container_id, api_pb2.ContainerMeta(id=container_id)
        )
        self.container_pod.pop(container_id, None)
        req = api_pb2.ContainerResourceHookRequest(
            pod_meta=pod_meta, container_meta=container
        )
        self._call_hook("PostStopContainerHook", req)
        self.backend.forward(
            RuntimeCall("StopContainer", pod_meta.name, container.name)
        )
