"""Docker (dockershim-style) backend for koord-runtime-proxy.

Analog of reference `pkg/runtimeproxy/server/docker/`: where the CRI path
(criserver.py) is a gRPC interceptor, the docker path is an HTTP reverse
proxy on a unix socket speaking the Docker Engine API. kubelet's dockershim
dials the proxy socket; the proxy intercepts the container-lifecycle calls

    POST /<ver>/containers/create          (hook: PreCreateContainer)
    POST /<ver>/containers/<id>/start      (hook: PreStartContainer)
    POST /<ver>/containers/<id>/stop       (hook: PostStopContainer, fired
                                            AFTER the daemon confirms the
                                            stop — same order as the CRI
                                            path — then the meta entry is
                                            dropped)
    POST /<ver>/containers/<id>/update     (hook: PreUpdateContainerResources)

runs the koordlet hook chain, overlays the hook's resource response onto the
request's HostConfig JSON (CpuPeriod/CpuQuota/CpuShares/Memory/CpusetCpus/
CpusetMems — the docker-API spellings of resexecutor's update semantics),
and forwards the mutated request to the real docker daemon's socket. Every
other path/method passes through untouched (the docker analog of the CRI
TransparentHandler), including Connection-Upgrade hijacks (exec/attach):
after relaying the request raw, the proxy pumps bytes both ways until
either side closes, so `kubectl exec` / `attach` / `logs -f` work through
the docker path exactly as through the reference's server
(pkg/runtimeproxy/server/docker/server.go proxies all endpoints).
FailurePolicy matches the CRI path: Ignore forwards the original request
when the hook server is down, Fail returns 502 so kubelet retries.

The pod/sandbox linkage rides docker labels the way dockershim writes them
(`io.kubernetes.pod.*`, `io.kubernetes.container.name`): create requests
carry them, so hook requests can be populated without a separate sandbox
store.
"""

from __future__ import annotations

import json
import os
import re
import socket
import socketserver
import stat
import threading
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler
from typing import Dict, Optional, Tuple

from koordinator_tpu.runtimeproxy import api_pb2
from koordinator_tpu.runtimeproxy.server import FailurePolicy

_CREATE_RE = re.compile(r"^/v[\d.]+/containers/create$")
_LIFECYCLE_RE = re.compile(
    r"^/v[\d.]+/containers/(?P<id>[^/]+)/(?P<op>start|stop|update)$")

# dockershim's well-known labels
_LABEL_POD_NAME = "io.kubernetes.pod.name"
_LABEL_POD_NS = "io.kubernetes.pod.namespace"
_LABEL_POD_UID = "io.kubernetes.pod.uid"
_LABEL_CONTAINER = "io.kubernetes.container.name"


def _host_config_to_hook(hc: dict) -> api_pb2.LinuxContainerResources:
    return api_pb2.LinuxContainerResources(
        cpu_period=int(hc.get("CpuPeriod") or 0),
        cpu_quota=int(hc.get("CpuQuota") or 0),
        cpu_shares=int(hc.get("CpuShares") or 0),
        memory_limit_bytes=int(hc.get("Memory") or 0),
        cpuset_cpus=hc.get("CpusetCpus") or "",
        cpuset_mems=hc.get("CpusetMems") or "",
    )


def _merge_hook_into_host_config(
    hc: dict, patch: Optional[api_pb2.LinuxContainerResources]
) -> None:
    """Overlay non-zero hook fields (same merge stance as the CRI path's
    _merge_hook_into_cri)."""
    if patch is None:
        return
    for src, dst in (
        ("cpu_period", "CpuPeriod"),
        ("cpu_quota", "CpuQuota"),
        ("cpu_shares", "CpuShares"),
        ("memory_limit_bytes", "Memory"),
    ):
        v = getattr(patch, src)
        if v:
            hc[dst] = int(v)
    if patch.cpuset_cpus:
        hc["CpusetCpus"] = patch.cpuset_cpus
    if patch.cpuset_mems:
        hc["CpusetMems"] = patch.cpuset_mems


class _UnixHTTPConnection(HTTPConnection):
    def __init__(self, socket_path: str, timeout: float = 10.0):
        super().__init__("localhost", timeout=timeout)
        self._socket_path = socket_path

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout)
        sock.connect(self._socket_path)
        self.sock = sock


class _UnixHTTPServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True

    def handle_error(self, request, client_address):
        # keep-alive peers closing mid-read are routine, not reportable
        pass


def _unlink_stale_socket(path: str) -> None:
    """allow_reuse_address is a no-op for AF_UNIX: a socket file left by an
    unclean shutdown raises 'Address already in use' on rebind, so remove
    it first — but only if it IS a socket (never a regular file) and
    nobody answers on it (a live server's endpoint must not be destroyed
    by a double start; its bind error surfaces instead)."""
    try:
        if not stat.S_ISSOCK(os.stat(path).st_mode):
            return
    except OSError:
        return  # nothing there
    probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        probe.settimeout(0.2)
        probe.connect(path)
        return  # something is serving: leave it alone
    except ConnectionRefusedError:
        pass  # definitively stale: bound-then-abandoned file
    except OSError:
        # timeout / EAGAIN (full backlog) / anything ambiguous: the server
        # may be alive but busy — never destroy its endpoint; our own bind
        # error will surface instead
        return
    finally:
        probe.close()
    try:
        os.unlink(path)
    except OSError:
        pass


class DockerProxyServer:
    """HTTP/UDS reverse proxy between kubelet(dockershim) and dockerd."""

    def __init__(self, proxy_socket: str, backend_socket: str,
                 hook_client=None,
                 failure_policy: FailurePolicy = FailurePolicy.IGNORE):
        self.proxy_socket = proxy_socket
        self.backend_socket = backend_socket
        self.hook_client = hook_client
        self.failure_policy = failure_policy
        # container id -> (pod meta, container meta) from create labels
        self.container_store: Dict[
            str, Tuple[api_pb2.PodSandboxMeta, api_pb2.ContainerMeta]] = {}
        # create-name -> meta awaiting the daemon-assigned id (keyed by the
        # ?name= query param so concurrent creates cannot cross-bind)
        self._pending_meta: Dict[str, Tuple] = {}
        self._lock = threading.Lock()
        self._server: Optional[_UnixHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- hook dispatch -------------------------------------------------------
    def _call_hook(self, method: str, request):
        """(response | None, abort) under the failure policy."""
        if self.hook_client is None:
            return None, False
        try:
            return self.hook_client.call(method, request), False
        except Exception:
            if self.failure_policy == FailurePolicy.FAIL:
                return None, True
            return None, False

    # -- request interception ------------------------------------------------
    @staticmethod
    def _query_name(path: str) -> str:
        from urllib.parse import parse_qs, urlsplit

        qs = parse_qs(urlsplit(path).query)
        return (qs.get("name") or [""])[0]

    def _intercept(self, method: str, path: str, body: bytes,
                   ) -> Tuple[bytes, Optional[int], Optional[str]]:
        """Returns (possibly mutated body, error status or None, pending-
        meta key for creates). Stop is NOT handled here: its hook is
        post-forward (see _after_response)."""
        if method != "POST":
            return body, None, None
        if _CREATE_RE.match(path.split("?")[0]):
            try:
                payload = json.loads(body or b"{}")
            except ValueError:
                return body, None, None
            labels = payload.get("Labels") or {}
            pod_meta = api_pb2.PodSandboxMeta(
                name=labels.get(_LABEL_POD_NAME, ""),
                namespace=labels.get(_LABEL_POD_NS, ""),
                uid=labels.get(_LABEL_POD_UID, ""),
                labels=labels,
            )
            container_meta = api_pb2.ContainerMeta(
                name=labels.get(_LABEL_CONTAINER, ""), labels=labels)
            hc = payload.setdefault("HostConfig", {})
            req = api_pb2.ContainerResourceHookRequest(
                pod_meta=pod_meta,
                container_meta=container_meta,
                resources=_host_config_to_hook(hc),
            )
            resp, abort = self._call_hook("PreCreateContainerHook", req)
            if abort:
                return body, 502, None
            if resp is not None and resp.HasField("resources"):
                _merge_hook_into_host_config(hc, resp.resources)
            # dockershim always names containers (?name=...); unnamed
            # creates get a unique token so concurrent ones cannot share
            # the "" key and cross-bind pod metadata
            import uuid

            pending_key = self._query_name(path) or f"unnamed-{uuid.uuid4()}"
            with self._lock:
                self._pending_meta[pending_key] = (pod_meta, container_meta)
            return json.dumps(payload).encode(), None, pending_key
        m = _LIFECYCLE_RE.match(path.split("?")[0])
        if m:
            cid, op = m.group("id"), m.group("op")
            if op == "stop":  # post-forward hook: nothing to do pre-flight
                return body, None, None
            with self._lock:
                pod_meta, container_meta = self.container_store.get(
                    cid, (api_pb2.PodSandboxMeta(), api_pb2.ContainerMeta()))
            hook_method = {
                "start": "PreStartContainerHook",
                "update": "PreUpdateContainerResourcesHook",
            }[op]
            meta = api_pb2.ContainerMeta()
            meta.CopyFrom(container_meta)
            meta.id = cid
            req = api_pb2.ContainerResourceHookRequest(
                pod_meta=pod_meta, container_meta=meta)
            if op == "update":
                try:
                    payload = json.loads(body or b"{}")
                except ValueError:
                    payload = None
                if payload is not None:
                    req.resources.CopyFrom(_host_config_to_hook(payload))
                resp, abort = self._call_hook(hook_method, req)
                if abort:
                    return body, 502, None
                if (payload is not None and resp is not None
                        and resp.HasField("resources")):
                    _merge_hook_into_host_config(payload, resp.resources)
                    return json.dumps(payload).encode(), None, None
                return body, None, None
            _resp, abort = self._call_hook(hook_method, req)
            if abort:
                return body, 502, None
        return body, None, None

    def _after_response(self, method: str, path: str, status: int,
                        resp_body: bytes,
                        pending_key: Optional[str] = None) -> None:
        """Post-forward bookkeeping: bind create ids, fire the post-stop
        hook only once the daemon CONFIRMED the stop (CRI-path order), and
        drop meta on stop/delete so the store cannot leak."""
        clean = path.split("?")[0]
        if method == "POST" and _CREATE_RE.match(clean):
            # pop the pending entry on EVERY create outcome — a rejected
            # create (409/500) must not leak it
            with self._lock:
                meta = (self._pending_meta.pop(pending_key, None)
                        if pending_key else None)
            if status != 201 or meta is None:
                return
            try:
                cid = json.loads(resp_body).get("Id", "")
            except ValueError:
                return
            if cid:
                with self._lock:
                    self.container_store[cid] = meta
            return
        m = _LIFECYCLE_RE.match(clean)
        if method == "POST" and m and m.group("op") == "stop":
            # 404 == the daemon no longer knows the container (AutoRemove,
            # out-of-band rm, daemon restart): treat it as a confirmed
            # teardown — fire the post-stop hook so koordlet releases its
            # per-container state, then drop the meta (no DELETE may ever
            # come). Other non-2xx are transient: keep the entry for the
            # kubelet retry.
            if status >= 300 and status != 404:
                return
            cid = m.group("id")
            with self._lock:
                entry = self.container_store.pop(cid, None)
            if entry is None:
                # never tracked (non-k8s container) or already handled (a
                # stop retry after an earlier 404): no blank-meta hook and
                # no duplicate teardown event for koordlet
                return
            pod_meta, container_meta = entry
            meta = api_pb2.ContainerMeta()
            meta.CopyFrom(container_meta)
            meta.id = cid
            self._call_hook(
                "PostStopContainerHook",
                api_pb2.ContainerResourceHookRequest(
                    pod_meta=pod_meta, container_meta=meta))
            return
        dm = re.match(r"^/v[\d.]+/containers/(?P<id>[^/]+)$", clean)
        if method == "DELETE" and dm and status < 300:
            with self._lock:
                self.container_store.pop(dm.group("id"), None)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet
                pass

            def _relay(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                # hijacked/upgraded connections (exec/attach/logs over the
                # hijack protocol) cannot ride an http.client relay: tunnel
                # the raw bytes instead — request verbatim to the daemon,
                # then a bidirectional pump until either side closes (the
                # reference's docker server proxies these transparently).
                # Decided BEFORE _intercept: upgrade endpoints are not
                # lifecycle hooks, and the tunnel forwards the ORIGINAL
                # headers, so a hook-mutated body (new length) or a pending
                # create entry must never reach this path
                if "upgrade" in (self.headers.get("Connection") or "").lower():
                    self._tunnel(body)
                    return
                body, err, pending_key = proxy._intercept(
                    self.command, self.path, body)
                if err is not None:
                    self.send_response(err)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                conn = _UnixHTTPConnection(proxy.backend_socket)
                streamed = False
                try:
                    headers = {
                        k: v for k, v in self.headers.items()
                        if k.lower() not in ("host", "content-length")
                    }
                    headers["Content-Length"] = str(len(body))
                    conn.request(self.command, self.path, body=body,
                                 headers=headers)
                    resp = conn.getresponse()
                    # 204/304 are BODYLESS — Go's net/http (real dockerd)
                    # omits Content-Length on them, and stop/delete return
                    # 204; they must take the buffered path or
                    # _after_response (post-stop hook, store cleanup)
                    # would never run
                    if (resp.getheader("Content-Length") is None
                            and resp.status not in (204, 304)):
                        # unbounded/streaming response (logs?follow, events,
                        # stats?stream): forward chunks as they arrive —
                        # buffering with read() would block forever
                        streamed = True
                        if conn.sock is not None:
                            conn.sock.settimeout(None)  # sporadic stream
                        self.send_response(resp.status)
                        ctype = resp.getheader("Content-Type")
                        if ctype:
                            self.send_header("Content-Type", ctype)
                        self.send_header("Connection", "close")
                        self.end_headers()
                        self.close_connection = True
                        while True:
                            chunk = resp.read(16384)
                            if not chunk:
                                break
                            self.wfile.write(chunk)
                            self.wfile.flush()
                        return
                    resp_body = resp.read()
                except OSError:
                    if pending_key:  # failed create must not leak its meta
                        with proxy._lock:
                            proxy._pending_meta.pop(pending_key, None)
                    if streamed:
                        return  # headers already sent; peer/daemon gone
                    self.send_response(502)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                finally:
                    conn.close()
                proxy._after_response(self.command, self.path, resp.status,
                                      resp_body, pending_key)
                self.send_response(resp.status)
                self.send_header("Content-Length", str(len(resp_body)))
                ctype = resp.getheader("Content-Type")
                if ctype:
                    self.send_header("Content-Type", ctype)
                self.end_headers()
                self.wfile.write(resp_body)

            def _tunnel(self, body: bytes) -> None:
                """Byte-for-byte Connection-Upgrade relay. The daemon's
                response (101 UPGRADED + raw stream) flows back verbatim;
                after it, the connection is a plain duplex pipe."""
                back = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                try:
                    back.settimeout(10.0)
                    back.connect(proxy.backend_socket)
                except OSError:
                    back.close()
                    self.send_response(502)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.close_connection = True
                lines = [f"{self.command} {self.path} HTTP/1.1"]
                lines.extend(
                    f"{k}: {v}" for k, v in self.headers.items()
                    if k.lower() != "host")
                lines.append("Host: docker")
                raw = ("\r\n".join(lines) + "\r\n\r\n").encode(
                    "latin-1") + body
                try:
                    back.sendall(raw)
                    back.settimeout(None)  # interactive stream: no deadline
                    client = self.connection
                    client.settimeout(None)

                    def client_to_back():
                        try:
                            while True:
                                # read1 drains rfile's buffer before hitting
                                # the socket — bytes the client pipelined
                                # behind the request must not be lost
                                data = self.rfile.read1(65536)
                                if not data:
                                    break
                                back.sendall(data)
                            back.shutdown(socket.SHUT_WR)  # half-close
                        except OSError:
                            pass

                    t = threading.Thread(target=client_to_back, daemon=True)
                    t.start()
                    while True:
                        data = back.recv(65536)
                        if not data:
                            break
                        client.sendall(data)
                except OSError:
                    pass
                finally:
                    try:
                        back.close()
                    except OSError:
                        pass
                    try:
                        # unblocks the pump thread's rfile read
                        self.connection.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

            do_GET = do_POST = do_DELETE = do_PUT = do_HEAD = _relay

        _unlink_stale_socket(self.proxy_socket)
        self._server = _UnixHTTPServer(self.proxy_socket, Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


class FakeDockerDaemon:
    """Engine-API stub for tests (the docker analog of criserver.py's
    FakeContainerdServer): /containers/create assigns ids and records
    HostConfig, lifecycle posts record state transitions, /containers/
    <id>/json exposes what the daemon believes, /_ping answers OK (the
    passthrough probe)."""

    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self.containers: Dict[str, dict] = {}
        self._seq = 0
        self._lock = threading.Lock()
        self._server: Optional[_UnixHTTPServer] = None

    def start(self) -> None:
        daemon = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _reply(self, status: int, payload=None):
                body = (json.dumps(payload).encode()
                        if payload is not None else b"")
                self.send_response(status)
                if status == 204:
                    # Go's net/http omits Content-Length on 204 — mirror
                    # it so the proxy's streaming detection is tested
                    # against real-daemon behavior
                    self.end_headers()
                    return
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?")[0]
                if path.endswith("/_ping"):
                    return self._reply(200, "OK")
                m = re.match(r"^/v[\d.]+/containers/([^/]+)/json$", path)
                if m:
                    with daemon._lock:
                        ctr = daemon.containers.get(m.group(1))
                    if ctr is None:
                        return self._reply(404, {"message": "no such container"})
                    return self._reply(200, ctr)
                return self._reply(404, {"message": "unknown path"})

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                path = self.path.split("?")[0]
                # attach/exec hijack: answer 101 and become an echo pipe
                # (each chunk comes back prefixed "echo:"), like dockerd's
                # raw-stream hijack — exercises the proxy's upgrade tunnel
                am = re.match(r"^/v[\d.]+/containers/([^/]+)/attach$", path)
                if am and "upgrade" in (
                        self.headers.get("Connection") or "").lower():
                    self.close_connection = True
                    self.wfile.write(
                        b"HTTP/1.1 101 UPGRADED\r\n"
                        b"Content-Type: application/vnd.docker.raw-stream\r\n"
                        b"Connection: Upgrade\r\nUpgrade: tcp\r\n\r\n")
                    self.wfile.flush()
                    while True:
                        try:
                            data = self.rfile.read1(65536)
                        except OSError:
                            break
                        if not data:
                            break
                        self.wfile.write(b"echo:" + data)
                        self.wfile.flush()
                    return
                payload = json.loads(body) if body else {}
                if _CREATE_RE.match(path):
                    with daemon._lock:
                        daemon._seq += 1
                        cid = f"ctr-{daemon._seq}"
                        daemon.containers[cid] = {
                            "Id": cid, "State": {"Status": "created"},
                            "Config": {"Labels": payload.get("Labels") or {}},
                            "HostConfig": payload.get("HostConfig") or {},
                        }
                    return self._reply(201, {"Id": cid})
                m = _LIFECYCLE_RE.match(path)
                if m:
                    cid, op = m.group("id"), m.group("op")
                    with daemon._lock:
                        ctr = daemon.containers.get(cid)
                        if ctr is None:
                            return self._reply(
                                404, {"message": "no such container"})
                        if op == "start":
                            ctr["State"]["Status"] = "running"
                        elif op == "stop":
                            ctr["State"]["Status"] = "exited"
                        elif op == "update":
                            ctr["HostConfig"].update(payload)
                    return self._reply(
                        200 if op == "update" else 204,
                        {"Warnings": []} if op == "update" else None)
                return self._reply(404, {"message": "unknown path"})

        _unlink_stale_socket(self.socket_path)
        self._server = _UnixHTTPServer(self.socket_path, Handler)
        threading.Thread(
            target=self._server.serve_forever, daemon=True).start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
