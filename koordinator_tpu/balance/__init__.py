"""koordbalance: device-resident rebalancing.

The descheduler's LowNodeLoad pass re-expressed as a batched node x pod
tensor pass sharing the scheduler's device mirror — one upload, two
consumers (PAPER.md layer map: koord-descheduler peers with the
scheduler only through Reservation/migration CRDs; ROADMAP "Batch the
descheduler onto the device snapshot").

Three pieces:

  * :mod:`koordinator_tpu.balance.pack` — ``RebalancePack``, the
    event-maintained packed arrays (node usage/metric columns + assigned
    pod rows). One pack per store; when a scheduler shares the process
    its :class:`~koordinator_tpu.scheduler.snapshot_cache.SnapshotCache`
    FORWARDS its store events into the pack, so the cluster is encoded
    once for both consumers (the old ``RebalancePackCache``'s duplicate
    subscription chain is gone).
  * :mod:`koordinator_tpu.balance.step` — ``build_rebalance_step``, the
    jitted tensor pass: node classification against the high/low
    thresholds, per-node overload margins, and the victim-candidate
    selection (sorted-by-usage victim order, movability masks, the
    per-segment freed-prefix greedy) in ONE batched device program with
    compacted (node_idx, pod_idx, score) readback.
  * :mod:`koordinator_tpu.balance.rebalancer` — ``DeviceRebalancer``,
    the driver: pad-bucketed upload through the (shared)
    ``DeviceSnapshot``, the ``rebalance`` span tree, rebalance metrics,
    and the PR 7 degradation ladder (device pass -> host ``LowNodeLoad``
    fallback) so a rebalance fault never kills either component.

``KOORD_TPU_REBALANCE=on|off|host`` selects the engine (see
``rebalance_from_env``); decision parity against the host oracle is
gated by ``pipeline_parity.run_rebalance_parity`` at mesh 1/2/4/8.
"""

from koordinator_tpu.balance.pack import RebalancePack, has_pdb_like_guard
from koordinator_tpu.balance.rebalancer import (
    DeviceRebalancer,
    rebalance_from_env,
)
from koordinator_tpu.balance.step import RebalanceOut, build_rebalance_step

__all__ = [
    "RebalancePack",
    "DeviceRebalancer",
    "RebalanceOut",
    "build_rebalance_step",
    "has_pdb_like_guard",
    "rebalance_from_env",
]
