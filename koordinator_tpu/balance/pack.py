"""RebalancePack: event-maintained packed arrays for the rebalance pass.

Moved here from ``descheduler/lownodeload.py`` (where it was
``RebalancePackCache``) so the scheduler and the descheduler share ONE
encode of the cluster: when a :class:`SnapshotCache` lives in the same
process it *forwards* its existing store subscriptions into the pack
(``SnapshotCache.rebalance_pack``) instead of the pack opening a second
subscription chain and walking the store again — the "one upload, two
consumers" invariant koordlint rule 16 (`host-loop-in-rebalance-path`)
pins for new code in this package.

The reference keeps incremental caches and walks them per run
(utilization_util.go reads informer caches, not the API server); the
batch analog keeps the pod/node state PACKED so the victim pass is pure
array math — the store walk and object packing move out of the per-pass
cost entirely. Slots are append-only (compacted when >50% dead) so
masked views preserve store insertion order, which the stable sort
relies on for exact victim-set parity with the serial C++ floor AND the
device tensor pass (balance/step.py).
"""

from __future__ import annotations

import weakref
from typing import Dict, List, Optional

import numpy as np

from koordinator_tpu.api.objects import NodeMetric, Pod
from koordinator_tpu.api.resources import (
    NUM_RESOURCES,
    RESOURCE_INDEX,
    ResourceName,
)
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    ObjectStore,
)

CPU = RESOURCE_INDEX[ResourceName.CPU]


def has_pdb_like_guard(pod: Pod) -> bool:
    """The descheduler opt-out annotation: such pods are never victims."""
    return pod.meta.annotations.get(
        "descheduler.alpha.kubernetes.io/evict") == "false"


# store -> {expiration -> RebalancePack}; weak so stores die normally
_PACKS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


class RebalancePack:
    """Packed node usage/metric columns + assigned-pod rows (see module
    doc). Construct via :meth:`for_store` (standalone descheduler:
    subscribes itself) or with ``subscribe=False`` when a SnapshotCache
    forwards its events (shared-process deployments)."""

    _GROW = 1024

    @classmethod
    def for_store(cls, store: ObjectStore,
                  expiration_seconds: float) -> "RebalancePack":
        """One pack per (store, expiration): ObjectStore has no
        unsubscribe, so every construction would leak a live handler —
        repeat LowNodeLoad constructions on the same store (per-pass
        plugin re-inits) must share the subscription."""
        by_exp = _PACKS.setdefault(store, {})
        pack = by_exp.get(expiration_seconds)
        if pack is None:
            pack = cls(store, expiration_seconds)
            by_exp[expiration_seconds] = pack
        return pack

    def __init__(self, store: ObjectStore, expiration_seconds: float,
                 subscribe: bool = True) -> None:
        self.store = store
        self.expiration = expiration_seconds
        # node side
        self._node_names: List[str] = []
        self._node_idx: Dict[str, int] = {}
        self.alloc = np.zeros((0, NUM_RESOURCES), np.float32)
        self.usage_pct = np.zeros((0, NUM_RESOURCES), np.float32)
        self.nm_time = np.zeros(0, np.float64)
        self.has_raw = np.zeros(0, bool)
        self._nodes_stale = True
        # pod side (append-only slots)
        self._slot: Dict[str, int] = {}
        self._cap = 0
        self._len = 0
        self._dead = 0
        self.pod_alive = np.zeros(0, bool)
        self.pod_node_name: List[Optional[str]] = []
        self.pod_node = np.zeros(0, np.int64)
        self._pod_node_stale = True
        self.pod_prio = np.zeros(0, np.int64)
        self.pod_cpu = np.zeros(0, np.float32)
        self.pod_req = np.zeros((0, NUM_RESOURCES), np.float32)
        self.pod_movable = np.zeros(0, bool)
        self.pod_ref: List[Optional[Pod]] = []
        if subscribe:
            store.subscribe(KIND_NODE, self.on_node)
            store.subscribe(KIND_NODE_METRIC, self.on_metric)
            store.subscribe(KIND_POD, self.on_pod)

    # -- events (called by the store OR forwarded by SnapshotCache) ----
    def on_node(self, ev, node, old) -> None:
        self._nodes_stale = True

    def on_metric(self, ev, nm, old) -> None:
        # metric rows refresh lazily with the node table; a metric-only
        # update just recomputes that row
        self._nodes_stale = True

    def on_pod(self, ev, pod: Pod, old) -> None:
        from koordinator_tpu.client.store import EventType

        key = pod.meta.key
        slot = self._slot.get(key)
        live = (ev is not EventType.DELETED and pod.is_assigned
                and not pod.is_terminated)
        if not live:
            if slot is not None and self.pod_alive[slot]:
                self.pod_alive[slot] = False
                self.pod_ref[slot] = None
                self._dead += 1
            if ev is EventType.DELETED:
                # a deleted-then-recreated pod must land in a FRESH slot:
                # the store dict re-inserts it at the end, and slot order
                # must track store insertion order for sort-parity with
                # the cold pass / C++ floor (terminated-in-place pods keep
                # their slot — the store preserves their dict position)
                self._slot.pop(key, None)
            return
        if slot is None:
            if self._len == self._cap:
                grow = max(self._GROW, self._cap)
                self.pod_alive = np.concatenate(
                    [self.pod_alive, np.zeros(grow, bool)])
                self.pod_node = np.concatenate(
                    [self.pod_node, np.full(grow, -1, np.int64)])
                self.pod_prio = np.concatenate(
                    [self.pod_prio, np.zeros(grow, np.int64)])
                self.pod_cpu = np.concatenate(
                    [self.pod_cpu, np.zeros(grow, np.float32)])
                self.pod_req = np.concatenate(
                    [self.pod_req,
                     np.zeros((grow, NUM_RESOURCES), np.float32)])
                self.pod_movable = np.concatenate(
                    [self.pod_movable, np.zeros(grow, bool)])
                self.pod_node_name.extend([None] * grow)
                self.pod_ref.extend([None] * grow)
                self._cap += grow
            slot = self._len
            self._slot[key] = slot
            self._len += 1
        elif not self.pod_alive[slot]:
            self._dead -= 1
        self.pod_alive[slot] = True
        self.pod_node_name[slot] = pod.spec.node_name
        self.pod_prio[slot] = pod.spec.priority or 0
        self.pod_cpu[slot] = pod.spec.requests[ResourceName.CPU]
        self.pod_req[slot] = pod.spec.requests.to_vector()
        self.pod_movable[slot] = (
            pod.meta.owner_kind != "DaemonSet"
            and not has_pdb_like_guard(pod))
        self.pod_ref[slot] = pod
        self._pod_node_stale = True

    # -- refresh -------------------------------------------------------
    def _refresh_nodes(self) -> None:
        nodes = self.store.list(KIND_NODE)
        names = [n.meta.name for n in nodes]
        remap = names != self._node_names
        if remap:
            self._node_names = names
            self._node_idx = {n: i for i, n in enumerate(names)}
            self._pod_node_stale = True
        N = len(nodes)
        self.alloc = np.zeros((N, NUM_RESOURCES), np.float32)
        self.usage_pct = np.zeros((N, NUM_RESOURCES), np.float32)
        self.nm_time = np.zeros(N, np.float64)
        self.has_raw = np.zeros(N, bool)
        # event-driven refresh, not per-pass work: the rows rebuilt here
        # are exactly the nodes whose store objects changed since the
        # last view (the pass itself is pure array math on the result)
        # koordlint: disable=host-loop-in-rebalance-path
        for i, node in enumerate(nodes):
            self.alloc[i] = node.allocatable.to_vector()
            nm: Optional[NodeMetric] = self.store.get(
                KIND_NODE_METRIC, f"/{node.meta.name}")
            if nm is None or nm.update_time <= 0:
                continue
            usage = nm.node_metric.node_usage.to_vector()
            a = self.alloc[i]
            with np.errstate(divide="ignore", invalid="ignore"):
                self.usage_pct[i] = np.where(
                    a > 0, usage * 100.0 / np.maximum(a, 1e-9), 0.0)
            self.nm_time[i] = nm.update_time
            self.has_raw[i] = True
        self._nodes_stale = False

    def _compact(self) -> None:
        keep = np.nonzero(self.pod_alive[: self._len])[0]
        self.pod_alive = np.concatenate(
            [np.ones(keep.size, bool), np.zeros(self._cap - keep.size, bool)])
        # four fixed column arrays, not a per-pod walk
        # koordlint: disable=host-loop-in-rebalance-path
        for arr_name in ("pod_node", "pod_prio", "pod_cpu", "pod_movable"):
            arr = getattr(self, arr_name)
            packed = arr[keep]
            arr[: keep.size] = packed
            arr[keep.size:] = 0
        self.pod_req[: keep.size] = self.pod_req[keep]
        self.pod_req[keep.size:] = 0
        names = [self.pod_node_name[k] for k in keep]
        refs = [self.pod_ref[k] for k in keep]
        pad = self._cap - keep.size
        self.pod_node_name = names + [None] * pad
        self.pod_ref = refs + [None] * pad
        self._slot = {
            refs[j].meta.key: j for j in range(keep.size)
        }
        self._len = keep.size
        self._dead = 0

    def view(self, now: float):
        """(packed arrays dict) for the victim pass — refreshes lazily."""
        if self._nodes_stale:
            self._refresh_nodes()
        if self._dead * 2 > max(1, self._len):
            self._compact()
        if self._pod_node_stale:
            idx = self._node_idx
            # string node-name -> layout-index remap: runs only when the
            # node layout or a pod's placement changed (event-flagged)
            # koordlint: disable=host-loop-in-rebalance-path
            for j in range(self._len):
                name = self.pod_node_name[j]
                self.pod_node[j] = idx.get(name, -1) if name else -1
            self._pod_node_stale = False
        has_metric = self.has_raw & (
            now - self.nm_time < self.expiration)
        return {
            "alloc": self.alloc,
            "usage_pct": self.usage_pct,
            "has_metric": has_metric,
            "pod_alive": self.pod_alive[: self._len],
            "pod_node": self.pod_node[: self._len],
            "pod_prio": self.pod_prio[: self._len],
            "pod_cpu": self.pod_cpu[: self._len],
            "pod_req": self.pod_req[: self._len],
            "pod_movable": self.pod_movable[: self._len],
        }
