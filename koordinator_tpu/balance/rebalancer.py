"""DeviceRebalancer: drive the rebalance tensor pass against the shared
device mirror, with the PR 7 degradation ladder underneath.

The rebalancer is the descheduler-side consumer of the scheduler's
``DeviceSnapshot``: its arrays upload through the SAME reuse/scatter/put
machinery (``upload_fields``) under ``rb_*`` names, so a steady-state
cluster ships only row deltas and the two consumers share one device
mirror — the "one upload, two consumers" closing of the ROADMAP item.
Under ``KOORD_TPU_MESH`` the node-axis fields shard over the mesh via
the existing ``put_on_mesh``/NamedSharding helpers
(parallel/rebalance_mesh.py) and the compacted readback replicates.

Resilience reuses the scheduler's ladder machine
(scheduler/degrade.DegradationLadder) with only the rungs that change
behavior here: ``full`` (sharded device pass) -> ``no-mesh`` (single-
device pass, skipped when no mesh is configured) -> ``host-fallback``
(the host ``LowNodeLoad`` oracle). A rebalance fault therefore never
kills the descheduler — it sheds the device, keeps the decisions (the
host oracle is decision-identical by the parity gate), and re-promotes
after clean passes exactly like the dispatch ladder.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from koordinator_tpu.obs import Tracer
from koordinator_tpu.obs.flight import FLIGHT_SCHEMA_VERSION, FlightRecorder
from koordinator_tpu.scheduler.deadline import (
    DeadlineWatchdog,
    DispatchDeadlineExceeded,
    deadline_seconds_from,
)
from koordinator_tpu.scheduler.degrade import (
    LEVEL_HOST_FALLBACK,
    LEVEL_NO_MESH,
    DegradationLadder,
)

logger = logging.getLogger(__name__)

# names of the node-axis upload fields — shared with
# snapshot_cache._mesh_node_fields so the mesh-backed DeviceSnapshot
# shards them exactly like the scheduler's own node arrays
RB_NODE_FIELDS = ("rb_usage_pct", "rb_has_metric", "rb_rhs_hi",
                  "rb_rhs_lo")


def rebalance_from_env():
    """KOORD_TPU_REBALANCE=on|off|host selects the LowNodeLoad engine:
    "on" (default) runs the device tensor pass (with the host fallback
    ladder underneath), "host" pins the host numpy oracle, "off"
    disables the rebalance pass entirely (the incident kill switch —
    the descheduler's other plugins keep running)."""
    import os

    raw = os.environ.get("KOORD_TPU_REBALANCE", "on").strip().lower()
    if raw in ("", "on", "1", "true", "device"):
        return "on"
    if raw in ("off", "0", "false", "no"):
        return "off"
    if raw == "host":
        return "host"
    logger.warning("KOORD_TPU_REBALANCE=%r unknown; using 'on'", raw)
    return "on"


def _bucket(n: int, lo: int) -> int:
    """Power-of-two pad bucket (>= lo): each distinct padded shape is a
    distinct compiled program, so shapes quantize."""
    p = lo
    while p < n:
        p *= 2
    return p


class DeviceRebalancer:
    """Owns the compiled rebalance steps, the (possibly shared) device
    mirror, the rebalance ladder, span tree, metrics and flight ring.

    ``snapshot_getter`` returns the scheduler's live ``DeviceSnapshot``
    (it is rebuilt on scheduler ladder transitions, so the reference
    must be read per pass); without one the rebalancer owns a private
    mirror. ``mesh`` is the configured mesh (parallel/mesh.py) — the
    ladder's no-mesh rung drops to a private single-device mirror."""

    def __init__(self, mesh=None,
                 snapshot_getter: Optional[Callable[[], object]] = None,
                 ladder: Optional[DegradationLadder] = None,
                 promote_after: int = 16,
                 tracer: Optional[Tracer] = None,
                 flight: Optional[FlightRecorder] = None,
                 dispatch_deadline_ms=None,
                 timeline=None) -> None:
        self.mesh = mesh
        self.snapshot_getter = snapshot_getter
        self.ladder = ladder if ladder is not None else DegradationLadder(
            promote_after=promote_after)
        self.tracer = tracer if tracer is not None else Tracer()
        self.flight = flight if flight is not None else FlightRecorder()
        # koordwatch: the device timeline this pass records its windows
        # into — the SCHEDULER's ring when co-located (the three
        # consumers share one device, so they share one timeline and one
        # decision-id sequence), a private ring standalone. Every pass
        # mints a decision id; migration jobs carry it (-> Reservation
        # annotations), joining descheduler decisions to the window.
        if timeline is None:
            # standalone: record into the DESCHEDULER's registry — the
            # one this binary's /metrics actually serves — and honor
            # the KOORD_TPU_WATCH kill switch like every other ring
            from koordinator_tpu.descheduler import metrics as dm
            from koordinator_tpu.obs.timeline import (
                DeviceTimeline,
                watch_from_env,
            )

            timeline = DeviceTimeline(
                window_histogram=dm.DEVICE_WINDOW_SECONDS,
                idle_gauge=dm.DEVICE_IDLE_FRACTION,
                enabled=watch_from_env())
        self.timeline = timeline
        self.last_decision_id: Optional[str] = None
        self._step_cache: Dict[Tuple, object] = {}
        self._last_step_compiled = False
        self._own_snapshots: Dict[bool, object] = {}  # mesh_on -> mirror
        self._seq = 0
        self._warned_host_only = False
        # sim/test failure-injection hook: a callable() invoked at the
        # top of every device-pass window; raising from it exercises the
        # rebalance ladder exactly like a real XLA/mesh fault
        self.fault_injector = None
        # koordguard dispatch deadline: the rebalance pass shares the
        # scheduler's KOORD_TPU_DISPATCH_DEADLINE_MS knob and watchdog
        # discipline — an overrun abandons the pass (the shared mirror's
        # dispatch window stays open so donation never re-arms under the
        # slow program) and walks THIS ladder toward the host oracle.
        self.dispatch_deadline_seconds = deadline_seconds_from(
            dispatch_deadline_ms)
        self.dispatch_watchdog = DeadlineWatchdog(
            self.dispatch_deadline_seconds,
            on_overrun=self._on_deadline_overrun)
        # sim/test latency hook: invoked inside the monitored readback
        self.sync_delay_injector = None
        self.stats = {"device_passes": 0, "host_passes": 0,
                      "candidates": 0, "victims": 0}

    def _on_deadline_overrun(self, path: str) -> None:
        from koordinator_tpu.scheduler import metrics as scheduler_metrics

        scheduler_metrics.DISPATCH_DEADLINE_OVERRUNS.inc(path=path)
        self.flight.dump("dispatch_deadline")

    # ------------------------------------------------------------------
    def _features(self) -> Dict[str, bool]:
        return {"mesh": self.mesh is not None,
                "waves": False, "explain": False}

    def _active_mesh(self):
        return self.mesh if self.ladder.level < LEVEL_NO_MESH else None

    def _snapshot(self, mesh):
        """The device mirror for this pass. The scheduler's shared
        mirror is used only while its mesh placement matches ours —
        otherwise (scheduler demoted independently, or we did) the
        rebalancer falls back to a private mirror so the upload
        placement always matches the compiled step."""
        if self.snapshot_getter is not None:
            shared = self.snapshot_getter()
            if shared is not None and getattr(shared, "mesh", None) is mesh:
                return shared
        key = mesh is not None
        snap = self._own_snapshots.get(key)
        if snap is None:
            from koordinator_tpu.scheduler.snapshot_cache import (
                DeviceSnapshot,
            )

            snap = DeviceSnapshot(mesh=mesh)
            self._own_snapshots[key] = snap
        return snap

    def _get_step(self, p_pad: int, n_pad: int, cap: int, mesh):
        # device IDS, not just the count: the scheduler's partial-mesh
        # rung can hand this pass two same-size submeshes over different
        # survivors, and a step compiled against the old Mesh must never
        # serve the new one
        mesh_tag = (tuple(d.id for d in mesh.devices.flat)
                    if mesh is not None else ())
        key = (p_pad, n_pad, cap, mesh_tag)
        step = self._step_cache.get(key)
        self._last_step_compiled = step is None
        if step is None:
            with self.tracer.span("compile", signature=str(key)):
                if mesh is not None:
                    from koordinator_tpu.parallel import (
                        build_sharded_rebalance_step,
                    )

                    step = build_sharded_rebalance_step(cap, mesh)
                else:
                    from koordinator_tpu.balance.step import (
                        build_rebalance_step,
                    )

                    step = build_rebalance_step(cap)
            self._step_cache[key] = step
        return step

    # ------------------------------------------------------------------
    # a per-SEGMENT freed total above this bound could make the f32
    # product X = freed * 100 inexact and flip the limb compare near the
    # threshold (balance/step.py module doc): f32 is integer-exact to
    # 2^24, so freed*100 is unconditionally exact below 2^24/100. The
    # per-node sum of ALL movable pod requests upper-bounds any
    # segment's freed prefix.
    _X_EXACT_BOUND = (2 ** 24) // 100

    @staticmethod
    def _device_eligible(view) -> Optional[str]:
        """The device pass's exactness preconditions (module doc of
        balance/step.py). A view outside them is not a fault — it is a
        per-pass demotion to the host oracle, like the fused-wave
        feature demotions."""
        req = view["pod_req"]
        if not req.size:
            return None
        if not np.all(np.floor(req) == req):
            return "non-integer packed request rows"
        n = view["alloc"].shape[0]
        live = view["pod_alive"] & view["pod_movable"] & (
            view["pod_node"] >= 0)
        per_node = np.zeros((n, req.shape[1]), np.float64)
        np.add.at(per_node, view["pod_node"][live],
                  np.abs(req[live], dtype=np.float64))
        if np.any(per_node > DeviceRebalancer._X_EXACT_BOUND):
            return ("per-node request totals exceed the f32 "
                    "freed*100 exactness bound")
        return None

    def _prep(self, view, low_thr: np.ndarray, high_thr: np.ndarray):
        """Pad-bucketed host arrays + the float64 rhs limb split."""
        from koordinator_tpu.balance.step import split_rhs_limbs

        n = view["alloc"].shape[0]
        p = view["pod_node"].shape[0]
        n_pad = _bucket(n, 8)
        p_pad = _bucket(p, 64)
        usage = np.zeros((n_pad, view["usage_pct"].shape[1]), np.float32)
        usage[:n] = view["usage_pct"]
        has_metric = np.zeros(n_pad, bool)
        has_metric[:n] = view["has_metric"]
        rhs_hi, rhs_lo = split_rhs_limbs(
            view["usage_pct"], view["alloc"], high_thr)
        hi = np.zeros_like(usage)
        hi[:n] = rhs_hi
        lo = np.zeros_like(usage)
        lo[:n] = rhs_lo
        pod_node = np.full(p_pad, -1, np.int32)
        pod_node[:p] = view["pod_node"].astype(np.int32)
        pod_prio = np.zeros(p_pad, np.int32)
        pod_prio[:p] = view["pod_prio"].astype(np.int32)
        pod_cpu = np.zeros(p_pad, np.float32)
        pod_cpu[:p] = view["pod_cpu"]
        pod_req = np.zeros((p_pad, view["pod_req"].shape[1]), np.int32)
        pod_req[:p] = view["pod_req"].astype(np.int32)
        pod_ok = np.zeros(p_pad, bool)
        pod_ok[:p] = view["pod_alive"] & view["pod_movable"]
        return {
            "rb_usage_pct": usage, "rb_has_metric": has_metric,
            "rb_rhs_hi": hi, "rb_rhs_lo": lo,
            "rb_low_thr": low_thr, "rb_high_thr": high_thr,
            "rb_pod_node": pod_node, "rb_pod_prio": pod_prio,
            "rb_pod_cpu": pod_cpu, "rb_pod_req": pod_req,
            "rb_pod_ok": pod_ok,
        }, p_pad, n_pad

    # ------------------------------------------------------------------
    def select_victims(self, plugin, view, now: float):
        """One rebalance pass over the packed view. Returns
        (picked slot indices, stats dict) — decision-identical to the
        host oracle ``plugin.select_victims_host`` (the parity gate
        pins it); the ladder demotes to that oracle on faults."""
        t0 = time.perf_counter()
        self._seq += 1
        self.ladder.begin_pass()
        # koordwatch: one decision id per pass (device OR host — jobs
        # need the join either way); the timeline window records only
        # completed device passes
        win = self.timeline.open(
            "rebalance",
            "mesh" if self._active_mesh() is not None else "serial")
        self.last_decision_id = win.decision_id
        reason = self._device_eligible(view)
        if reason is not None:
            if not self._warned_host_only:
                logger.warning("rebalance device pass ineligible (%s); "
                               "using the host oracle", reason)
                self._warned_host_only = True
            return self._host_pass(plugin, view, now, t0,
                                   engine="host-ineligible")
        attempts = 0
        had_deadline = False
        level0 = self.ladder.level
        while True:
            if self.ladder.level >= LEVEL_HOST_FALLBACK:
                return self._host_pass(plugin, view, now, t0)
            mesh = self._active_mesh()
            try:
                picked, stats = self._device_pass(plugin, view, mesh, win)
                outcome = ("deadline" if had_deadline
                           else "demoted" if self.ladder.level > level0
                           else "retried" if attempts else "clean")
                self.timeline.close(win, outcome)
                self._record(now, t0, stats)
                self.ladder.note_cycle()
                return picked, stats
            except Exception as exc:
                attempts += 1
                if isinstance(exc, DispatchDeadlineExceeded):
                    had_deadline = True
                action = self.ladder.on_failure(
                    self._features(),
                    error=f"{type(exc).__name__}: {exc}")
                if action == "exhausted":
                    # cannot happen above the host rung (it always
                    # changes behavior); defensive parity with the
                    # scheduler's wrapper
                    raise
                logger.warning(
                    "rebalance device pass failed (%s: %s); %s at "
                    "ladder level %s", type(exc).__name__, exc, action,
                    self.ladder.level_name)

    def _host_pass(self, plugin, view, now: float, t0: float,
                   engine: str = "host"):
        with self.tracer.span("score", host="1"):
            picked = plugin.select_victims_host(view)
        stats = {"engine": engine,
                 "candidates": int(plugin.last_pass_stats.get(
                     "candidates", 0)),
                 "victims": int(picked.size),
                 "decision_id": self.last_decision_id,
                 "ladder_level": self.ladder.level_name}
        self.stats["host_passes"] += 1
        self.stats["candidates"] += stats["candidates"]
        self.stats["victims"] += stats["victims"]
        self._record(now, t0, stats)
        self.ladder.note_cycle()
        return picked, stats

    def _device_pass(self, plugin, view, mesh, win):
        if self.fault_injector is not None:
            self.fault_injector()
        with self.tracer.span("classify") as csp:
            low_thr = plugin._thr_vec(plugin.args.low_thresholds)
            high_thr = plugin._thr_vec(plugin.args.high_thresholds)
            fields, p_pad, n_pad = self._prep(view, low_thr, high_thr)
            csp.attributes["nodes"] = str(view["alloc"].shape[0])
            csp.attributes["pods"] = str(view["pod_node"].shape[0])
        step = self._get_step(p_pad, n_pad,
                              plugin.args.max_pods_to_evict_per_node, mesh)
        snap = self._snapshot(mesh)

        def sync_readback():
            # the rebalance pass's designated sync point, run under the
            # dispatch-deadline watchdog — route new syncs through here
            # (koordlint naked-device-sync-without-deadline)
            if self.sync_delay_injector is not None:
                self.sync_delay_injector()
            n = view["alloc"].shape[0]
            sel_count = int(out.sel_count)
            return (sel_count, int(out.cand_count),
                    np.asarray(out.sel_pod)[:sel_count],
                    np.asarray(out.sel_node)[:sel_count],
                    np.asarray(out.sel_score)[:sel_count],
                    np.asarray(out.is_low)[:n],
                    np.asarray(out.is_high)[:n],
                    np.asarray(out.margin)[:n])

        snap.begin_dispatch()
        win.mark_dispatch("mesh" if mesh is not None else "serial")
        abandoned = False
        try:
            with self.tracer.span("score", mesh=str(
                    mesh.devices.size if mesh is not None else 0),
                    decision_id=win.decision_id):
                dev = snap.upload_fields(fields)
                step_args = (dev["rb_usage_pct"], dev["rb_has_metric"],
                             dev["rb_low_thr"], dev["rb_high_thr"],
                             dev["rb_rhs_hi"], dev["rb_rhs_lo"],
                             dev["rb_pod_node"], dev["rb_pod_prio"],
                             dev["rb_pod_cpu"], dev["rb_pod_req"],
                             dev["rb_pod_ok"])
                if self._last_step_compiled:
                    # persistent warm-up index (scheduler/warmup.py):
                    # record the fresh rung so a restarted process can
                    # pre-compile the rebalance pass off the bind path
                    from koordinator_tpu.scheduler.warmup import (
                        record_step_compile,
                    )

                    record_step_compile(
                        "rebalance",
                        # p_pad/n_pad ride the meta so the index keeps
                        # ONE rung per shape bucket (dedupe is on meta;
                        # without them a grown bucket would evict the
                        # old bucket's rung)
                        {"cap": int(
                            plugin.args.max_pods_to_evict_per_node),
                         "p_pad": int(p_pad), "n_pad": int(n_pad),
                         "mesh_tag": [int(d.id)
                                      for d in mesh.devices.flat]
                         if mesh is not None else []},
                        step_args)
                out = step(*step_args)
            with self.tracer.span("readback"):
                try:
                    (sel_count, cand_count, sel_pod, sel_node, sel_score,
                     is_low, is_high, margin) = self.dispatch_watchdog.run(
                        sync_readback, "rebalance")
                except DispatchDeadlineExceeded:
                    # slow-not-dead device: abandon the pass. The
                    # dispatch window stays OPEN on this mirror —
                    # donation can never re-arm under the still-running
                    # program (the scheduler's shared mirror simply runs
                    # non-donating until its own next rebuild) — and a
                    # privately-owned mirror is dropped so the next pass
                    # re-uploads through a fresh one.
                    abandoned = True
                    self._own_snapshots = {
                        k: s for k, s in self._own_snapshots.items()
                        if s is not snap}
                    raise
        finally:
            if not abandoned:
                snap.end_dispatch()
        picked = sel_pod.astype(np.int64)
        stats = {"engine": "device", "candidates": cand_count,
                 "victims": sel_count,
                 "is_low": is_low, "is_high": is_high, "margin": margin,
                 "victim_nodes": sel_node, "victim_scores": sel_score,
                 "decision_id": win.decision_id,
                 "ladder_level": self.ladder.level_name}
        self.stats["device_passes"] += 1
        self.stats["candidates"] += cand_count
        self.stats["victims"] += sel_count
        return picked, stats

    def _record(self, now: float, t0: float, stats: dict) -> None:
        """One pass record into the flight ring (valid ``cycle`` record
        per obs/flight.py's schema, so rebalance dumps replay through
        the same tooling) + the pass metrics."""
        from koordinator_tpu.descheduler import metrics as dm

        duration = time.perf_counter() - t0
        dm.REBALANCE_PASS_SECONDS.observe(duration)
        if stats.get("candidates"):
            dm.REBALANCE_CANDIDATES.inc(stats["candidates"])
        if stats.get("victims"):
            dm.REBALANCE_VICTIMS.inc(stats["victims"])
        self.flight.record_cycle({
            "v": FLIGHT_SCHEMA_VERSION,
            "kind": "cycle",
            "seq": self._seq,
            "ts": float(now),
            "duration_ms": duration * 1000.0,
            "waves": 0,
            "bound": [], "failed": [], "rejected": [], "preempted": [],
            # koordwatch: joins this pass to its timeline window and to
            # the migration jobs it issued
            "decision_id": str(stats.get("decision_id") or ""),
            "metrics": {
                "rebalance_candidates": float(stats.get("candidates", 0)),
                "rebalance_victims": float(stats.get("victims", 0)),
                "rebalance_device": float(stats.get("engine") == "device"),
            },
            "spans": [],
        })
