"""The device rebalance pass: LowNodeLoad as ONE batched tensor program.

``build_rebalance_step`` compiles node classification, per-node overload
margins, and the greedy victim selection into a single jitted pass over
the packed arrays (balance/pack.py), with compacted
(node_idx, pod_idx, score) readback — the device twin of the host oracle
``LowNodeLoad.select_victims_host`` (descheduler/lownodeload.py), which
stays as the diagnose-style reference exactly the way
``host_stage_counts`` is for koordexplain.

Decision-parity discipline (gated by
``pipeline_parity.run_rebalance_parity`` at mesh 1/2/4/8):

  * the victim ORDER is the host's stable lexsort (node, priority asc,
    cpu desc, slot order as the tiebreak), reproduced as three chained
    stable argsorts plus a candidates-first pass — a stable sort of the
    full padded axis restricted to candidate rows IS the stable sort of
    the compressed candidate array;
  * the freed-requests prefix runs as an int32 cumsum: the packed
    request rows are integer-valued by the repo's f32-exactness
    discipline (milli-cores / MiB), a global int32 cumsum may wrap, but
    per-segment DIFFERENCES of prefix sums are exact in modular
    arithmetic while each segment's freed total stays < 2^31 — the
    device-side analog of the host's float64 accumulation;
  * the still-over threshold compare reproduces the host's float64
    comparison bit-for-bit through a two-limb split: the host
    precomputes rhs = (usage_pct - high_thr) * alloc in float64 per node
    (tiny [N, R]) and ships (hi, lo) float32 limbs; the device tests
    ``X < hi  or  (X == hi and lo > 0)``, which for the exactly-
    representable integer X = freed*100 decides ``X < rhs_f64`` exactly.

Everything here is jnp on traced values — no host loops, no store reads
(koordlint rule 16 pins that for this package).
"""

from __future__ import annotations

from typing import NamedTuple


class RebalanceOut(NamedTuple):
    """Device outputs of one rebalance pass (device values until the
    driver's readback sync). ``sel_*`` are compacted: the first
    ``sel_count`` entries are the selected victims in host victim order;
    the tail is -1/0 padding."""

    is_low: object       # [N] bool — below low thresholds on every axis
    is_high: object      # [N] bool — above high thresholds on any axis
    margin: object       # [N] f32  — max checked-axis overload (>= 0)
    cand_count: object   # scalar i32 — movable pods on overloaded nodes
    sel_count: object    # scalar i32 — victims selected
    sel_pod: object      # [P] i32  — pack slot index of victim j (-1 pad)
    sel_node: object     # [P] i32  — node index of victim j (-1 pad)
    sel_score: object    # [P] f32  — victim-order key (cpu request)


def build_rebalance_step(max_evict_per_node: int, jit: bool = True):
    """Compile the rebalance tensor pass for a per-node eviction cap.

    The returned step takes padded arrays (pad pods: ``pod_ok`` False;
    pad nodes: ``has_metric`` False — both make the row inert, the same
    bucket-pad semantics the scheduler kernels use):

      usage_pct [N, R] f32, has_metric [N] bool,
      low_thr [R] f32, high_thr [R] f32,
      rhs_hi [N, R] f32, rhs_lo [N, R] f32   (host float64 limb split),
      pod_node [P] i32, pod_prio [P] i32, pod_cpu [P] f32,
      pod_req_i [P, R] i32, pod_ok [P] bool  (alive & movable)
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    cap = int(max_evict_per_node)

    def step(usage_pct, has_metric, low_thr, high_thr, rhs_hi, rhs_lo,
             pod_node, pod_prio, pod_cpu, pod_req_i, pod_ok):
        N = usage_pct.shape[0]
        P = pod_node.shape[0]
        # ---- classification (classify_nodes, vectorized identically)
        checked_l = low_thr > 0
        low = jnp.all(~checked_l | (usage_pct < low_thr),
                      axis=-1) & has_metric
        checked_h = high_thr > 0
        over = usage_pct - high_thr
        high = jnp.any(checked_h & (over > 0.0), axis=-1) & has_metric
        is_low = low & ~high
        is_high = high
        margin = jnp.where(
            has_metric,
            jnp.max(over, axis=-1, initial=0.0,
                    where=jnp.broadcast_to(checked_h, over.shape)),
            0.0).astype(jnp.float32)
        # host early-outs become a kernel-wide gate: no high or no low
        # nodes -> zero candidates -> empty selection
        active = jnp.any(is_high) & jnp.any(is_low)
        # the host's over_gate spans ALL axes (unchecked thresholds are
        # 0, so any positive usage passes) — replicate verbatim
        over_gate = jnp.any(over > 0.0, axis=-1)
        node_ok = is_high & over_gate
        cand = (pod_ok & (pod_node >= 0)
                & node_ok[jnp.maximum(pod_node, 0)] & active)
        cand_count = jnp.sum(cand.astype(jnp.int32))

        # ---- victim order: stable lexsort (node, prio asc, cpu desc)
        # over the candidate rows. Least-significant key first, then a
        # candidates-first pass pushes pad/non-candidate rows to the
        # tail without perturbing the candidates' relative order.
        idx = jnp.arange(P, dtype=jnp.int32)
        order = jnp.argsort(-pod_cpu, stable=True)
        order = order[jnp.argsort(pod_prio[order], stable=True)]
        order = order[jnp.argsort(pod_node[order], stable=True)]
        order = order[jnp.argsort(
            jnp.where(cand[order], 0, 1).astype(jnp.int32), stable=True)]
        cs = cand[order]
        node_s = pod_node[order]

        # ---- per-node segments over the sorted candidate prefix
        seg_start = cs & ((idx == 0) | (node_s != jnp.roll(node_s, 1)))
        start_pos = lax.cummax(jnp.where(seg_start, idx, -1))
        sp = jnp.maximum(start_pos, 0)
        rank = idx - start_pos

        # ---- exclusive freed-requests prefix per segment: int32
        # modular cumsum (see module doc); non-candidate rows contribute
        # zero so the candidate prefix matches the compressed host array
        reqs_s = jnp.where(cs[:, None], pod_req_i[order], 0)
        gcum = jnp.cumsum(reqs_s, axis=0, dtype=jnp.int32)
        excl = gcum - reqs_s
        freed = excl - excl[sp]
        X = freed.astype(jnp.float32) * 100.0

        # ---- still-over: the host's float64 "freed*100 < rhs" compare,
        # decided exactly via the (hi, lo) limb split
        ns = jnp.clip(node_s, 0, N - 1)
        rh = rhs_hi[ns]
        rl = rhs_lo[ns]
        lt = (X < rh) | ((X == rh) & (rl > 0.0))
        still_over = jnp.any(lt & checked_h, axis=-1)

        # ---- greedy selection: candidate k is taken iff every earlier
        # candidate in its segment (and k itself) kept the node over,
        # and its rank is under the per-node cap — the prefix-AND as a
        # cumsum-of-failures == 0 test, exactly the host formulation
        fail_i = jnp.where(cs, (~still_over).astype(jnp.int32), 0)
        fails_g = jnp.cumsum(fail_i)
        seg_base = fails_g[sp] - fail_i[sp]
        prefix_ok = (fails_g - seg_base) == 0
        selected = cs & prefix_ok & (rank < cap)

        # ---- compacted readback: scatter the selected triples to the
        # front (drop-mode scatter; non-selected rows target index P)
        sel_rank = jnp.cumsum(selected.astype(jnp.int32)) - 1
        sel_count = jnp.sum(selected.astype(jnp.int32))
        tgt = jnp.where(selected, sel_rank, P)
        sel_pod = jnp.full(P, -1, jnp.int32).at[tgt].set(
            order.astype(jnp.int32), mode="drop")
        sel_node = jnp.full(P, -1, jnp.int32).at[tgt].set(
            node_s.astype(jnp.int32), mode="drop")
        sel_score = jnp.zeros(P, jnp.float32).at[tgt].set(
            pod_cpu[order], mode="drop")
        return RebalanceOut(is_low, is_high, margin, cand_count,
                            sel_count, sel_pod, sel_node, sel_score)

    return jax.jit(step) if jit else step


def split_rhs_limbs(usage_pct, alloc, high_thr):
    """Host-side float64 rhs = (usage_pct - high_thr) * max(alloc, 1e-9)
    per node, split into (hi, lo) float32 limbs for the exact device
    compare. Vectorized numpy — tiny [N, R] work, no per-node loop."""
    import numpy as np

    rhs = ((usage_pct.astype(np.float64) - high_thr.astype(np.float64))
           * np.maximum(alloc, np.float32(1e-9)).astype(np.float64))
    hi = rhs.astype(np.float32)
    lo = (rhs - hi.astype(np.float64)).astype(np.float32)
    return hi, lo
