"""koordrace, dynamic half: a deterministic thread-interleaving race
harness over the seeded sim.

The static half (analysis/guards.py + analysis/rules/race.py) learns
which shared fields are guarded by which locks and flags violations
without running anything. This module EXECUTES the smoke scenario with
every concurrency feature armed — pipeline overlap, a (never-firing)
dispatch watchdog, background warm-up — and checks the same discipline
at runtime:

  * every ``threading.Lock``/``RLock`` constructed during the run, plus
    the module-level locks and singleton instance locks that already
    exist at install time, is wrapped in an ownership-tracking proxy
    (:class:`_TracedLock`); the proxy knows which thread holds it, which
    a raw ``_thread.lock`` cannot say;
  * a trace function (``sys.settrace``/``threading.settrace`` — this
    tree runs 3.10, ``sys.monitoring`` does not exist yet) fires at
    every guarded-field touchpoint FROM THE STATIC GUARD MAP, forcing
    seeded thread preemption there and recording a WITNESS whenever the
    guarding lock is not held by the touching thread;
  * acquisitions of canonically-ordered locks (obs/lockorder.py) are
    checked against the declared order as they happen — a runtime
    inversion is recorded even if no deadlock materializes;
  * scraper threads hammer ``/metrics`` and ``/debug/timeline`` through
    ``ObsServer.handle`` for the whole run, validating every response
    parses cleanly (the torn-exposition check).

Determinism contract: the binding log must be BYTE-IDENTICAL across two
different preemption seeds — the harness shakes the schedule, never the
decisions. ``hack/check_races.py`` gates on that plus zero witnesses,
zero order inversions, zero scrape errors, and static/dynamic
agreement (a runtime witness the analyzer did not flag is reported as
its own failure class).

Tests pin SPECIFIC interleavings with :meth:`RaceCheck.add_hook`: a
predicate over the touchpoint spec selects where, the callback runs on
the touching thread at that point — no sleeps, no polling.

Preemption wrinkle (why yields, not a scheduler): CPython's thread
scheduler is not scriptable from pure Python; what IS deterministic
here is WHICH touchpoints yield (a crc32 of seed, site, and a
per-thread counter — no process-randomized ``hash()``), so two runs at
one seed exercise the same yield set, and two seeds exercise different
ones. The assertion is outcome determinism, not schedule determinism.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple

MODULE_OWNER = "<module>"

# armed-but-never-firing: the watchdog spawns its worker and waits, but
# 30s per device window cannot overrun a CPU sim cycle — overruns would
# make the binding log wall-clock-dependent and break the byte-identity
# contract
RACECHECK_DEADLINE_MS = 30_000.0

# ~1/16 of touchpoint hits yield the GIL (one in three of those sleeps
# a real millisecond to widen the window) — enough schedule shaking to
# expose ordering bugs at sim scale without drowning the run in sleeps
_DEFAULT_PREEMPT_PERMILLE = 62

# the raw lock types, captured before any factory patching
_LOCK_TYPE = type(threading.Lock())
_RLOCK_TYPE = type(threading.RLock())
_RAW_LOCK_TYPES = (_LOCK_TYPE, _RLOCK_TYPE)


# ---------------------------------------------------------------------------
# the ownership-tracking lock proxy
# ---------------------------------------------------------------------------

class _TracedLock:
    """Wraps a real ``Lock``/``RLock``; tracks per-thread ownership and
    reports canonical-order acquisitions to the active RaceCheck.

    Defines ``_is_owned``/``_release_save``/``_acquire_restore`` so a
    ``threading.Condition`` built over the proxy (``threading.Event``
    does this internally) keeps exact wait semantics AND keeps the
    ownership books balanced across the wait's release/reacquire."""

    __slots__ = ("_inner", "kind", "label", "_owners")

    def __init__(self, inner, kind: str, label: str = "") -> None:
        self._inner = inner
        self.kind = kind            # "Lock" | "RLock"
        self.label = label          # "Owner.attr" | "path::attr" | ""
        self._owners: Dict[int, int] = {}

    # -- core protocol ------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            me = threading.get_ident()
            self._owners[me] = self._owners.get(me, 0) + 1
            rc = _ACTIVE
            if rc is not None:
                rc._note_acquire(self)
        return ok

    def release(self) -> None:
        me = threading.get_ident()
        n = self._owners.get(me, 0)
        if n <= 1:
            self._owners.pop(me, None)
        else:
            self._owners[me] = n - 1
        rc = _ACTIVE
        if rc is not None:
            rc._note_release(self)
        self._inner.release()

    def __enter__(self) -> "_TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def held_by_me(self) -> bool:
        return threading.get_ident() in self._owners

    # -- Condition integration ----------------------------------------
    def _is_owned(self) -> bool:
        return self.held_by_me()

    def _release_save(self):
        me = threading.get_ident()
        count = self._owners.pop(me, 1)
        inner = self._inner
        if hasattr(inner, "_release_save"):
            return (count, inner._release_save())
        inner.release()
        return (count, None)

    def _acquire_restore(self, state) -> None:
        count, inner_state = state
        inner = self._inner
        if inner_state is not None and hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(inner_state)
        else:
            inner.acquire()
        self._owners[threading.get_ident()] = count

    def __repr__(self) -> str:
        return (f"<_TracedLock {self.kind} {self.label or '?'} "
                f"owners={list(self._owners)}>")


@dataclasses.dataclass(frozen=True)
class TouchSpec:
    """One guarded-field touchpoint from the static map: the trace
    function fires here."""

    path: str       # repo-relative, as the guard map keys it
    line: int
    owner: str      # class name or MODULE_OWNER
    field: str
    guard: str      # lock attribute / module lock name
    write: bool


@dataclasses.dataclass
class RaceReport:
    """What one instrumented run observed."""

    preempt_seed: int
    bindings: int = 0
    binding_log_sha256: str = ""
    touches: int = 0
    preemptions: int = 0
    scrapes: int = 0
    unchecked: int = 0  # touches whose guard was a raw (pre-wrap) lock
    witnesses: List[dict] = dataclasses.field(default_factory=list)
    order_violations: List[dict] = dataclasses.field(default_factory=list)
    scrape_errors: List[str] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not (self.witnesses or self.order_violations
                    or self.scrape_errors)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["ok"] = self.ok
        return d


# the currently-installed harness; _TracedLock reports through this
_ACTIVE: Optional["RaceCheck"] = None


def _repo_root() -> str:
    import koordinator_tpu

    return os.path.dirname(os.path.dirname(
        os.path.abspath(koordinator_tpu.__file__)))


class RaceCheck:
    """Install/uninstall the instrumentation; collect the observations.

    Usage::

        rc = RaceCheck(preempt_seed=7)
        rc.install()
        try:
            ...build + run threads...
        finally:
            rc.uninstall()
        report = rc.report(...)
    """

    def __init__(self, preempt_seed: int = 0,
                 preempt_permille: int = _DEFAULT_PREEMPT_PERMILLE,
                 scan_paths: Tuple[str, ...] = ("koordinator_tpu",)) -> None:
        self.preempt_seed = int(preempt_seed)
        self.preempt_permille = int(preempt_permille)
        self.witnesses: List[dict] = []
        self.order_violations: List[dict] = []
        self.touches = 0
        self.preemptions = 0
        self.unchecked = 0
        self._hooks: List[Tuple[Callable[[TouchSpec], bool],
                                Callable[..., None]]] = []
        self._tls = threading.local()
        # raw (never-wrapped) lock for the counters: list.append is
        # atomic under the GIL but ``+=`` on an int attribute is not
        import _thread

        self._stats_lock = _thread.allocate_lock()
        self._installed = False
        self._restores: List[Tuple[object, str, object]] = []
        self._build_static_index(scan_paths)

    # static index keyed by (root, scan_paths): fact extraction walks +
    # parses the whole tree (~seconds); sources cannot change under a
    # running process, so the gate's second seed and every harness test
    # reuse the first build
    _STATIC_CACHE: Dict[Tuple[str, Tuple[str, ...]], tuple] = {}

    # -- static-map plumbing ------------------------------------------
    def _build_static_index(self, scan_paths: Tuple[str, ...]) -> None:
        from koordinator_tpu.analysis.core import suppressed_lines
        from koordinator_tpu.analysis.guards import (
            build_guard_map,
            collect_facts_for_paths,
        )

        root = _repo_root()
        cached = self._STATIC_CACHE.get((root, scan_paths))
        if cached is not None:
            (self.guard_map, self.canonical_order, self._canon_index,
             self._touch_files, self._lockdef_labels) = cached
            return
        facts_list = collect_facts_for_paths(
            [os.path.join(root, p) for p in scan_paths])
        self.guard_map = build_guard_map(facts_list)
        self.canonical_order: Tuple[str, ...] = tuple(
            self.guard_map.canonical_order)
        self._canon_index = {name: i
                             for i, name in enumerate(self.canonical_order)}

        # suppressed unguarded-shared-field lines are NOT touchpoints:
        # the pragma'd exceptions (documented at the site) hold for the
        # dynamic half exactly as for the static one
        suppress: Dict[str, Dict[int, set]] = {}
        for facts in facts_list:
            try:
                with open(os.path.join(root, facts.path)) as f:
                    suppress[facts.path] = suppressed_lines(f.read())
            except OSError:
                suppress[facts.path] = {}

        self._touch_files: Dict[str, Dict[int, TouchSpec]] = {}
        for facts, t, gf in self.guard_map.guarded_touchpoints():
            rules = suppress.get(facts.path, {}).get(t.line, set())
            if "all" in rules or "unguarded-shared-field" in rules:
                continue
            spec = TouchSpec(path=facts.path, line=t.line, owner=t.owner,
                             field=t.field, guard=gf.guard, write=t.write)
            for key in (os.path.join(root, facts.path), facts.path):
                self._touch_files.setdefault(key, {})[t.line] = spec

        # lock-definition sites -> canonical-style labels, so a lock
        # constructed DURING the run self-identifies from its creation
        # frame (``self._lock = threading.Lock()`` in DeviceSnapshot
        # lands on the LockDef line the static map already knows)
        self._lockdef_labels: Dict[Tuple[str, int], str] = {}
        for facts in facts_list:
            for d in facts.locks:
                label = (f"{facts.path}::{d.attr}"
                         if d.owner == MODULE_OWNER
                         else f"{d.owner}.{d.attr}")
                for key in (os.path.join(root, facts.path), facts.path):
                    self._lockdef_labels[(key, d.line)] = label
        self._STATIC_CACHE[(root, scan_paths)] = (
            self.guard_map, self.canonical_order, self._canon_index,
            self._touch_files, self._lockdef_labels)

    # -- install / uninstall ------------------------------------------
    def install(self) -> None:
        global _ACTIVE
        if self._installed:
            return
        if _ACTIVE is not None:
            raise RuntimeError("another RaceCheck is installed")
        _ACTIVE = self
        self._installed = True
        orig_lock, orig_rlock = threading.Lock, threading.RLock
        self._saved_factories = (orig_lock, orig_rlock)
        threading.Lock = self._make_factory(orig_lock, "Lock")
        threading.RLock = self._make_factory(orig_rlock, "RLock")
        self._sweep_existing()
        self._saved_switch = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)
        threading.settrace(self._global_trace)
        sys.settrace(self._global_trace)

    def uninstall(self) -> None:
        global _ACTIVE
        if not self._installed:
            return
        sys.settrace(None)
        threading.settrace(None)  # type: ignore[arg-type]
        sys.setswitchinterval(self._saved_switch)
        threading.Lock, threading.RLock = self._saved_factories
        # put the raw locks back where the sweep wrapped them in place
        for holder, attr, original in reversed(self._restores):
            try:
                setattr(holder, attr, original)
            except (AttributeError, TypeError):
                pass
        self._restores.clear()
        self._installed = False
        _ACTIVE = None

    def _make_factory(self, orig, kind: str):
        labels = self._lockdef_labels

        def factory():
            fr = sys._getframe(1)
            label = labels.get((fr.f_code.co_filename, fr.f_lineno), "")
            return _TracedLock(orig(), kind, label)

        return factory

    def _sweep_existing(self) -> None:
        """Wrap locks that predate install(): module-level locks and the
        instance locks of import-time singletons (the metrics
        registries and their metric children) across koordinator_tpu.*
        modules. New locks route through the patched factories."""
        root = _repo_root()
        seen: set = set()
        for name, mod in list(sys.modules.items()):
            if not name.startswith("koordinator_tpu") or mod is None:
                continue
            mod_file = getattr(mod, "__file__", None)
            rel = (os.path.relpath(mod_file, root).replace("\\", "/")
                   if mod_file else name)
            for attr, val in list(vars(mod).items()):
                if isinstance(val, _RAW_LOCK_TYPES):
                    self._swap(mod, attr, val, f"{rel}::{attr}")
                elif (type(val).__module__ or "").split(".")[0] == \
                        "koordinator_tpu":
                    self._wrap_instance(val, seen, depth=0)

    def _wrap_instance(self, obj, seen: set, depth: int) -> None:
        if id(obj) in seen:
            return
        seen.add(id(obj))
        d = getattr(obj, "__dict__", None)
        if not isinstance(d, dict):
            return
        qual = type(obj).__qualname__
        for attr, val in list(d.items()):
            if isinstance(val, _RAW_LOCK_TYPES):
                self._swap(obj, attr, val, f"{qual}.{attr}")
            elif depth == 0 and isinstance(val, dict):
                # one container level: Registry._metrics maps names to
                # _Metric instances, each holding its own import-time
                # lock — the /metrics scrape path under test
                for v in list(val.values()):
                    if (type(v).__module__ or "").split(".")[0] == \
                            "koordinator_tpu":
                        self._wrap_instance(v, seen, depth + 1)

    def _swap(self, holder, attr: str, raw, label: str) -> None:
        kind = "RLock" if isinstance(raw, _RLOCK_TYPE) else "Lock"
        try:
            setattr(holder, attr, _TracedLock(raw, kind, label))
        except (AttributeError, TypeError):
            return
        self._restores.append((holder, attr, raw))

    # -- runtime order tracking ---------------------------------------
    def _note_acquire(self, lk: _TracedLock) -> None:
        idx = self._canon_index.get(lk.label)
        if idx is None:
            return
        stack = getattr(self._tls, "canon", None)
        if stack is None:
            stack = self._tls.canon = []
        if stack and self._canon_index[stack[-1]] > idx:
            self.order_violations.append({
                "held": stack[-1], "acquired": lk.label,
                "thread": threading.current_thread().name,
            })
        stack.append(lk.label)

    def _note_release(self, lk: _TracedLock) -> None:
        if lk.label not in self._canon_index:
            return
        stack = getattr(self._tls, "canon", None)
        if stack and lk.label in stack:
            # remove the innermost occurrence (RLock re-entry pops one)
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] == lk.label:
                    del stack[i]
                    break

    # -- the trace function -------------------------------------------
    def _global_trace(self, frame, event, arg):
        if event == "call" and frame.f_code.co_filename in self._touch_files:
            return self._local_trace
        return None

    def _local_trace(self, frame, event, arg):
        if event == "line":
            spec = self._touch_files[frame.f_code.co_filename].get(
                frame.f_lineno)
            if spec is not None:
                self._on_touch(spec, frame)
        return self._local_trace

    def _on_touch(self, spec: TouchSpec, frame) -> None:
        with self._stats_lock:
            self.touches += 1
        # 1) witness check: is the statically-assigned guard actually
        # held by the thread touching the field right now?
        if spec.owner == MODULE_OWNER:
            guard_obj = frame.f_globals.get(spec.guard)
        else:
            slf = frame.f_locals.get("self")
            guard_obj = getattr(slf, spec.guard, None) \
                if slf is not None else None
        if isinstance(guard_obj, _TracedLock):
            if not guard_obj.held_by_me():
                self.witnesses.append({
                    "path": spec.path, "line": spec.line,
                    "owner": spec.owner, "field": spec.field,
                    "guard": spec.guard, "write": spec.write,
                    "thread": threading.current_thread().name,
                })
        elif guard_obj is not None:
            with self._stats_lock:
                self.unchecked += 1
        # 2) pinned-interleaving hooks (tests)
        for pred, fn in self._hooks:
            if pred(spec):
                fn(spec, frame)
        # 3) seeded preemption: crc32 (stable across processes, unlike
        # str hash) of seed + site + per-thread counter picks the yield
        # points — same seed, same yield set, every run
        tls = self._tls
        n = getattr(tls, "n", 0) + 1
        tls.n = n
        h = zlib.crc32(
            f"{self.preempt_seed}:{spec.path}:{spec.line}:{n}".encode())
        if h % 1000 < self.preempt_permille:
            with self._stats_lock:
                self.preemptions += 1
            time.sleep(0.001 if h % 3 == 0 else 0)

    # -- test API ------------------------------------------------------
    def add_hook(self, pred: Callable[[TouchSpec], bool],
                 fn: Callable[..., None]) -> None:
        """Run ``fn(spec, frame)`` on the touching thread at every
        touchpoint where ``pred(spec)`` — the no-sleeps way for a test
        to pin an interleaving."""
        self._hooks.append((pred, fn))

    def report(self, sim_report=None, preempt_seed: Optional[int] = None,
               scrapes: int = 0,
               scrape_errors: Optional[List[str]] = None) -> RaceReport:
        rep = RaceReport(
            preempt_seed=(self.preempt_seed if preempt_seed is None
                          else preempt_seed),
            touches=self.touches,
            preemptions=self.preemptions,
            unchecked=self.unchecked,
            witnesses=list(self.witnesses),
            order_violations=list(self.order_violations),
            scrapes=scrapes,
            scrape_errors=list(scrape_errors or []),
        )
        if sim_report is not None:
            rep.bindings = len(sim_report.binding_log)
            rep.binding_log_sha256 = sim_report.binding_log_sha256
        return rep


# ---------------------------------------------------------------------------
# the scrape validators (torn-exposition check)
# ---------------------------------------------------------------------------

def validate_metrics_body(body: str) -> None:
    """Every sample line of a Prometheus exposition must parse — a torn
    scrape shows up as a half-written line or a non-numeric value."""
    for ln in body.splitlines():
        if not ln or ln.startswith("#"):
            continue
        name, _, value = ln.rpartition(" ")
        if not name:
            raise ValueError(f"torn metrics line: {ln!r}")
        float(value)  # ValueError on a torn value


def validate_timeline_body(body: str) -> None:
    from koordinator_tpu.obs.timeline import load_bundle

    _header, _records, errors = load_bundle(body.splitlines())
    if errors:
        raise ValueError(f"timeline bundle errors: {errors[:3]}")


# ---------------------------------------------------------------------------
# the scenario runner
# ---------------------------------------------------------------------------

def racecheck_scenario(cycles: int = 24):
    """The smoke scenario with the concurrency features armed: pipeline
    overlap on, the dispatch watchdog armed (but un-fireable — see
    RACECHECK_DEADLINE_MS). Background warm-up and the compile cache
    come from the env, set by :func:`run_racecheck`."""
    from koordinator_tpu.sim.scenarios import SCENARIOS

    sc = SCENARIOS["smoke"].resolved(cycles=cycles)
    return dataclasses.replace(
        sc, pipeline=True, dispatch_deadline_ms=RACECHECK_DEADLINE_MS)


def run_racecheck(preempt_seed: int = 0, cycles: int = 24,
                  scrape: bool = True, hooks=(),
                  scenario=None) -> RaceReport:
    """Build + run one instrumented sim; returns the :class:`RaceReport`.

    Env during the run: ``KOORD_TPU_WARMUP=background`` (the warm-up
    ladder races the first cycles for real) and a throwaway
    ``KOORD_TPU_COMPILE_CACHE_DIR`` (so the background ladder has an
    index to record into); both restored after."""
    import shutil
    import tempfile

    rc = RaceCheck(preempt_seed=preempt_seed)
    for pred, fn in hooks:
        rc.add_hook(pred, fn)
    sc = scenario if scenario is not None else racecheck_scenario(cycles)

    saved_env = {k: os.environ.get(k)
                 for k in ("KOORD_TPU_WARMUP", "KOORD_TPU_COMPILE_CACHE_DIR")}
    cache_dir = tempfile.mkdtemp(prefix="koordrace-cache-")
    os.environ["KOORD_TPU_WARMUP"] = "background"
    os.environ["KOORD_TPU_COMPILE_CACHE_DIR"] = cache_dir

    scrape_errors: List[str] = []
    scrape_count = [0]
    stop = threading.Event()
    scrapers: List[threading.Thread] = []
    sim_report = None

    rc.install()
    try:
        from koordinator_tpu.obs.server import ObsServer
        from koordinator_tpu.scheduler import metrics as scheduler_metrics
        from koordinator_tpu.sim.harness import ChurnSimulator

        sim = ChurnSimulator(sc)
        srv = ObsServer(scheduler_metrics.REGISTRY, sim.sched.tracer,
                        health_provider=sim.sched.health_snapshot,
                        flight=sim.sched.flight,
                        timeline=sim.sched.timeline, slo=sim.slo)

        def scraper(path: str, validate) -> None:
            while not stop.is_set():
                try:
                    status, _ctype, body = srv.handle(path)
                    if status != 200:
                        raise ValueError(f"{path} -> {status}")
                    validate(body)
                    scrape_count[0] += 1
                except Exception as exc:  # any tear is a failure
                    scrape_errors.append(f"{path}: {exc!r}")
                    return
                time.sleep(0.0005)

        if scrape:
            for path, validate in (("/metrics", validate_metrics_body),
                                   ("/debug/timeline",
                                    validate_timeline_body)):
                t = threading.Thread(target=scraper, args=(path, validate),
                                     name=f"koordrace-scrape{path}",
                                     daemon=True)
                scrapers.append(t)
                t.start()

        sim_report = sim.run()
    finally:
        stop.set()
        for t in scrapers:
            t.join(timeout=10.0)
        try:
            # the background ladder may still be recording rungs into
            # the throwaway cache dir — join it before the rmtree below
            # yanks the directory out from under its index writes
            from koordinator_tpu.scheduler.warmup import _join_live_ladders

            _join_live_ladders()
        except Exception as e:
            # cleanup is best-effort: a ladder that refuses to join only
            # risks a benign FileNotFoundError from the rmtree below
            print(f"racecheck: warm-up join skipped: {e!r}",
                  file=sys.stderr)
        rc.uninstall()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(cache_dir, ignore_errors=True)

    return rc.report(sim_report=sim_report, scrapes=scrape_count[0],
                     scrape_errors=scrape_errors)
