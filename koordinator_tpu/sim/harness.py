"""The churn simulator: seeded hostile traffic against the real Scheduler.

One :class:`ChurnSimulator` owns a synthetic cluster store, drives the
production :class:`~koordinator_tpu.scheduler.cycle.Scheduler` (and
optionally the descheduler) cycle by cycle on a synthetic clock, and
layers on everything a shared cluster throws at a scheduler:

  * seeded arrival/departure processes — Poisson pod arrivals with a
    prod/BE/quota/feature mix, gang storms, burst queues, Poisson
    departures of running pods;
  * cluster events — node drain (cordon + evict + uncordon-or-delete),
    spot reclamation of bound BE pods (re-queued as fresh arrivals),
    NodeMetric expiry flips, elastic-quota rebalances;
  * fault injection — a :class:`FaultPlan` arming dispatch exceptions
    (exercising the degradation ladder), scheduler store-write failures
    and dead-sidecar cycles at chosen cycles;
  * pending-queue backpressure — a bounded admitted queue with a
    waiting room: arrivals beyond ``queue_cap`` wait (requeue) and
    beyond ``overflow_cap`` are shed;
  * per-cycle invariant checks (:mod:`koordinator_tpu.sim.invariants`)
    and time-to-bind SLO tracking, flight-recorder dumps on any breach
    or overrun.

Everything is deterministic for a (scenario, seed) pair — the binding
log is byte-stable and ``hack/lint.sh`` pins that.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

from koordinator_tpu.api.objects import (
    LABEL_POD_GROUP,
    LABEL_QUOTA_NAME,
    ElasticQuota,
    Node,
    NodeMetric,
    NodeMetricInfo,
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    PodGroup,
    PodSpec,
)
from koordinator_tpu.api.resources import ResourceList
from koordinator_tpu.client.store import (
    KIND_ELASTIC_QUOTA,
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    KIND_POD_GROUP,
    ObjectStore,
)
from koordinator_tpu.sim.faults import (
    DeadSidecarClient,
    FaultPlan,
    FaultyStore,
)
from koordinator_tpu.sim.invariants import check_invariants
from koordinator_tpu.sim.scenarios import Scenario

GIB = 1024 ** 3
ZONE = "topology.kubernetes.io/zone"
PRIORITY_PROD = 9500
PRIORITY_BE = 5500
MAX_EVENT_DUMPS = 3  # flight dumps per trigger kind, so a pathological
#                      run cannot turn the recorder into the bottleneck


@dataclasses.dataclass
class SimReport:
    """Everything a scenario run produced, JSON-ready via to_dict()."""

    scenario: str
    seed: int
    cycles: int
    pods_created: int = 0
    pods_bound: int = 0
    pods_departed: int = 0
    pods_reclaimed: int = 0
    pods_drained: int = 0
    pods_shed: int = 0
    pods_requeued: int = 0
    max_pending: int = 0
    max_overflow: int = 0
    final_pending: int = 0
    ttb_seconds: List[float] = dataclasses.field(default_factory=list)
    slo_target_seconds: float = 0.0
    slo_overruns: int = 0
    invariant_breaches: List[str] = dataclasses.field(default_factory=list)
    cycle_exceptions: List[str] = dataclasses.field(default_factory=list)
    faults_injected: int = 0
    sidecar_fallbacks: int = 0
    # koordguard: monitored-sync overruns (slow-not-dead devices) and
    # the crash-restart recovery SLO — sim-clock seconds from each
    # scheduler teardown to the fresh scheduler's first bind
    deadline_overruns: int = 0
    restarts: int = 0
    restart_to_first_bind_seconds: List[float] = dataclasses.field(
        default_factory=list)
    # wall-clock recovery (report-only): dominated by the fresh
    # scheduler's cold compiles — the number the ROADMAP's AOT-warm-up
    # item will have to beat; sim-clock gates the SLO because wall time
    # is backend-bound
    restart_to_first_bind_wall_seconds: List[float] = dataclasses.field(
        default_factory=list)
    # PR 15: the wall-clock recovery split — how much of each restart's
    # wall was compile (step builds + freshly-compiled kernel windows +
    # the warm-up ladder) vs pack/encode, so the persistent-cache win is
    # attributable (the CHURN_r03 comparability note in BENCH_NOTES)
    restart_wall_compile_seconds: List[float] = dataclasses.field(
        default_factory=list)
    restart_wall_pack_seconds: List[float] = dataclasses.field(
        default_factory=list)
    # steady-state compile guard (koordlint rule 20, runtime half): step
    # cache misses flagged AFTER a warm-up ladder completed — per
    # restart up to its first bind, and the run total
    restart_steady_state_compiles: List[int] = dataclasses.field(
        default_factory=list)
    steady_state_compile_flags: int = 0
    # warm-up ladder stats of the LAST-BUILT scheduler (the restarted
    # one, in crash-restart scenarios) — empty dict when warm-up is off
    warmup: Dict[str, object] = dataclasses.field(default_factory=dict)
    # koordwatch device timeline: final idle fraction (gap-over-wall) —
    # THE number the pack-overlap A/B pair must move
    device_idle_fraction: float = 0.0
    restart_slo_seconds: float = 0.0
    ladder_transitions: List[dict] = dataclasses.field(default_factory=list)
    cycles_at_level: Dict[str, int] = dataclasses.field(default_factory=dict)
    final_level: str = "full"
    flight_dumps: int = 0
    descheduler_runs: int = 0
    # koordbalance: the rebalance closed loop's activity + SLO
    migration_jobs_created: int = 0
    pods_migrated: int = 0
    hotspot_events: int = 0
    hotspots_open: int = 0        # flagged node sets still hot at end
    dissipate_cycles: List[int] = dataclasses.field(default_factory=list)
    dissipate_slo_cycles: int = 0
    # koordcolo: the colocation control loop's activity + SLO
    manager_rounds: int = 0
    colo_device_passes: int = 0
    colo_host_passes: int = 0
    overcommit_shifts: int = 0
    batch_pods_bound: int = 0
    colo_staleness_cycles: List[int] = dataclasses.field(
        default_factory=list)
    colo_staleness_slo_cycles: int = 0
    colo_final_engine: str = ""
    # koordwatch demotion profile: cycles that ran below their
    # configured wave/explain/mesh level (CycleResult.demotions), each
    # attributed to its FIRST structured reason so the per-reason counts
    # sum exactly to cycles_demoted — zero unattributed demotions
    cycles_demoted: int = 0
    demotion_cycles_by_reason: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    # koordwatch pending-queue visibility: per-cycle depth at dispatch
    # and the oldest enqueued entry's age (store-pending + waiting room)
    queue_depth_by_cycle: List[int] = dataclasses.field(
        default_factory=list)
    queue_oldest_wait_by_cycle: List[float] = dataclasses.field(
        default_factory=list)
    binding_log: List[str] = dataclasses.field(default_factory=list)
    wall_seconds: float = 0.0
    # pipeline-occupancy accounting under realistic arrivals: per-cycle
    # wall and device-busy sums, plus bound/wall bucketed by the logical
    # cycles each dispatch consumed (CycleResult.waves) — the churn-side
    # pods_per_sec_at_k / pipeline_occupancy the bench report cites
    cycle_wall_seconds: float = 0.0
    device_busy_seconds: float = 0.0
    wall_by_waves: Dict[int, float] = dataclasses.field(default_factory=dict)
    bound_by_waves: Dict[int, int] = dataclasses.field(default_factory=dict)

    def percentile(self, q: float) -> float:
        if not self.ttb_seconds:
            return 0.0
        return float(np.percentile(np.asarray(self.ttb_seconds), q))

    def slo_registry(self, burn_gauge=None, met_gauge=None):
        """The report's SLO accounting as koordwatch registrations
        (obs/slo.py): the four objectives the scenarios gate — ttb p99,
        restart-to-first-bind (max-gated), hotspot dissipation
        (max-gated) and colo staleness p99 — registered against one
        SloRegistry and bulk-observed from the sample lists. to_dict's
        SLO blocks compute through this registry (shape pinned
        field-for-field by test), and the ChurnSimulator keeps a live
        instance feeding the koord_slo_* gauges and /debug/slo."""
        from koordinator_tpu.obs.slo import SloRegistry

        reg = SloRegistry(burn_gauge=burn_gauge, met_gauge=met_gauge)
        reg.register("ttb_p99", target=self.slo_target_seconds,
                     percentile=99.0, unit="seconds")
        reg.observe_many("ttb_p99", self.ttb_seconds)
        reg.register("restart_to_first_bind",
                     target=self.restart_slo_seconds,
                     percentile=100.0, unit="seconds")
        reg.observe_many("restart_to_first_bind",
                         self.restart_to_first_bind_seconds)
        reg.register("hotspot_dissipate",
                     target=float(self.dissipate_slo_cycles),
                     percentile=100.0, unit="cycles")
        reg.observe_many("hotspot_dissipate",
                         [float(c) for c in self.dissipate_cycles])
        reg.register("colo_staleness",
                     target=float(self.colo_staleness_slo_cycles),
                     percentile=99.0, unit="cycles")
        reg.observe_many("colo_staleness",
                         [float(c) for c in self.colo_staleness_cycles])
        return reg

    @property
    def binding_log_sha256(self) -> str:
        h = hashlib.sha256()
        for line in self.binding_log:
            h.update(line.encode())
            h.update(b"\n")
        return h.hexdigest()

    def to_dict(self, include_log: bool = False) -> dict:
        # the SLO math routes through the koordwatch registry — ONE
        # implementation of percentile/target/burn arithmetic for the
        # sim report, the live gauges and /debug/slo. The JSON shape of
        # every pre-existing block is preserved field-for-field
        # (tests/test_koordwatch.py pins it against the legacy
        # expressions); scenario-specific met rules compose from the
        # registry's stats below.
        reg = self.slo_registry()
        ttb_o = reg.objective("ttb_p99")
        restart_o = reg.objective("restart_to_first_bind")
        dissipate_o = reg.objective("hotspot_dissipate")
        stale_o = reg.objective("colo_staleness")
        ttb = {
            "count": ttb_o.count(),
            "p50": round(ttb_o.quantile(50), 3),
            "p90": round(ttb_o.quantile(90), 3),
            "p99": round(ttb_o.quantile(99), 3),
            "max": round(ttb_o.maximum(), 3) if self.ttb_seconds
            else 0.0,
            "mean": round(ttb_o.mean(), 3)
            if self.ttb_seconds else 0.0,
        }
        out = {
            "scenario": self.scenario,
            "seed": self.seed,
            "cycles": self.cycles,
            "pods": {
                "created": self.pods_created,
                "bound": self.pods_bound,
                "departed": self.pods_departed,
                "reclaimed": self.pods_reclaimed,
                "drained": self.pods_drained,
                "shed": self.pods_shed,
                "requeued": self.pods_requeued,
                "final_pending": self.final_pending,
            },
            "time_to_bind_seconds": ttb,
            "slo": {
                "ttb_p99_target_seconds": self.slo_target_seconds,
                "met": (ttb["p99"] <= self.slo_target_seconds
                        if self.ttb_seconds else True),
                "overruns": self.slo_overruns,
            },
            "queue": {
                "max_pending": self.max_pending,
                "max_overflow": self.max_overflow,
                # koordwatch pending-queue visibility (per-cycle stats)
                "depth": {
                    "mean": (round(float(np.mean(
                        self.queue_depth_by_cycle)), 1)
                        if self.queue_depth_by_cycle else 0.0),
                    "max": (int(max(self.queue_depth_by_cycle))
                            if self.queue_depth_by_cycle else 0),
                },
                "oldest_wait_seconds": {
                    "p50": (round(float(np.percentile(np.asarray(
                        self.queue_oldest_wait_by_cycle), 50)), 3)
                        if self.queue_oldest_wait_by_cycle else 0.0),
                    "p99": (round(float(np.percentile(np.asarray(
                        self.queue_oldest_wait_by_cycle), 99)), 3)
                        if self.queue_oldest_wait_by_cycle else 0.0),
                    "max": (round(max(self.queue_oldest_wait_by_cycle), 3)
                            if self.queue_oldest_wait_by_cycle else 0.0),
                },
            },
            "invariant_breaches": len(self.invariant_breaches),
            "invariant_breach_samples": self.invariant_breaches[:5],
            "cycle_exceptions": len(self.cycle_exceptions),
            "cycle_exception_samples": self.cycle_exceptions[:5],
            "faults_injected": self.faults_injected,
            "sidecar_fallbacks": self.sidecar_fallbacks,
            "deadline_overruns": self.deadline_overruns,
            "restart": {
                "count": self.restarts,
                "to_first_bind_seconds": {
                    "count": restart_o.count(),
                    "p50": restart_o.quantile(50),
                    "p99": restart_o.quantile(99),
                    "max": restart_o.maximum(),
                },
                "to_first_bind_wall_seconds": [
                    round(w, 2)
                    for w in self.restart_to_first_bind_wall_seconds],
                # the wall split (PR 15): compile vs pack attribution of
                # each recovery — the persistent compile cache's win
                # shows up as the compile component collapsing
                "restart_wall_compile_seconds": [
                    round(w, 2)
                    for w in self.restart_wall_compile_seconds],
                "restart_wall_pack_seconds": [
                    round(w, 3)
                    for w in self.restart_wall_pack_seconds],
                "steady_state_compiles": list(
                    self.restart_steady_state_compiles),
                "slo_seconds": self.restart_slo_seconds,
                # every restart must have rebound within the SLO; a
                # restart that never rebinds can never meet it
                "met": (self.restarts == 0 or (
                    self.restart_slo_seconds <= 0) or (
                    restart_o.count() == self.restarts
                    and restart_o.met())),
            },
            "degradation": {
                "transitions": self.ladder_transitions,
                "cycles_at_level": self.cycles_at_level,
                "final_level": self.final_level,
            },
            # koordwatch demotion profile: first-reason attribution, so
            # sum(by_reason.values()) == cycles_demoted exactly — zero
            # unattributed demotions (tests pin this)
            "demotions": {
                "cycles_demoted": self.cycles_demoted,
                "fraction_of_cycles": (
                    round(self.cycles_demoted / self.cycles, 3)
                    if self.cycles else 0.0),
                "by_reason": {
                    k: self.demotion_cycles_by_reason[k]
                    for k in sorted(self.demotion_cycles_by_reason)},
            },
            # koordwatch SLO registry dump: the same objectives the
            # blocks above gate, with burn rates — the /debug/slo view
            # of this run
            "slos": {
                name: {k: v for k, v in rec.items()
                       if k not in ("v", "kind", "slo")}
                for name, rec in reg.snapshot().items()},
            "flight_dumps": self.flight_dumps,
            "descheduler_runs": self.descheduler_runs,
            "rebalance": {
                "migration_jobs": self.migration_jobs_created,
                "pods_migrated": self.pods_migrated,
                "hotspot_events": self.hotspot_events,
                "hotspots_undissipated": self.hotspots_open,
                "time_to_dissipate_cycles": {
                    "count": dissipate_o.count(),
                    "p50": dissipate_o.quantile(50),
                    "p99": dissipate_o.quantile(99),
                    # int in the JSON, as the raw cycle counts are
                    "max": (max(self.dissipate_cycles)
                            if self.dissipate_cycles else 0),
                },
                "dissipate_slo_cycles": self.dissipate_slo_cycles,
                "dissipate_slo_met": (
                    self.dissipate_slo_cycles <= 0
                    or (self.hotspots_open == 0 and dissipate_o.met())),
            },
            "colo": {
                "manager_rounds": self.manager_rounds,
                "device_passes": self.colo_device_passes,
                "host_passes": self.colo_host_passes,
                "overcommit_shifts": self.overcommit_shifts,
                "batch_pods_bound": self.batch_pods_bound,
                "final_engine": self.colo_final_engine,
                "staleness_cycles": {
                    "count": stale_o.count(),
                    "p50": stale_o.quantile(50),
                    "p99": stale_o.quantile(99),
                    # int in the JSON, as the raw cycle counts are
                    "max": (max(self.colo_staleness_cycles)
                            if self.colo_staleness_cycles else 0),
                },
                "staleness_slo_cycles": self.colo_staleness_slo_cycles,
                "staleness_slo_met": stale_o.met(),
            },
            "binding_log_sha256": self.binding_log_sha256,
            "bindings": len(self.binding_log),
            "wall_seconds": round(self.wall_seconds, 2),
            # warm-up ladder + steady-state compile guard (PR 15)
            "warmup": dict(self.warmup),
            "steady_state_compile_flags": self.steady_state_compile_flags,
            "pipeline": {
                "device_idle_fraction": round(
                    self.device_idle_fraction, 3),
                "occupancy": (
                    round(self.device_busy_seconds
                          / self.cycle_wall_seconds, 3)
                    if self.cycle_wall_seconds > 0 else 0.0),
                "pods_per_sec_at_k": {
                    str(k): round(self.bound_by_waves.get(k, 0)
                                  / self.wall_by_waves[k], 1)
                    for k in sorted(self.wall_by_waves)
                    if self.wall_by_waves[k] > 0},
                "cycle_wall_seconds": round(self.cycle_wall_seconds, 2),
                "device_busy_seconds": round(self.device_busy_seconds, 2),
            },
        }
        if include_log:
            out["binding_log"] = list(self.binding_log)
        return out


class ChurnSimulator:
    """Drive one scenario. ``run()`` returns the :class:`SimReport`."""

    def __init__(self, scenario: Scenario,
                 flight_dir: Optional[str] = None) -> None:
        import random

        self.sc = scenario
        self.rng = random.Random(scenario.seed)
        self.store = ObjectStore()  # the simulator's own (never-failing) view
        self.plan = FaultPlan(scenario.faults)
        self.now = 1_000_000.0
        self.report = SimReport(
            scenario=scenario.name,
            seed=scenario.seed,
            cycles=scenario.cycles,
            slo_target_seconds=scenario.ttb_slo_seconds,
            dissipate_slo_cycles=scenario.hotspot_dissipate_slo_cycles,
            restart_slo_seconds=scenario.restart_slo_seconds)
        self._uid = 0
        self._arrival_time: Dict[str, float] = {}   # pod key -> sim arrival
        self._overflow: List[Pod] = []              # waiting room (FIFO)
        self._draining: List[Tuple[str, int]] = []  # (node, cycles left)
        self._gangs: List[Tuple[int, str, List[str]]] = (
            [])  # (finish cycle, PodGroup key, member pod keys)
        self._metric_flip_state = False
        # koordbalance: per-pod usage multipliers (hotspot-marked pods
        # run HOT; migration replacements inherit — the workload is hot
        # wherever it runs, so hotspots dissipate by SPREADING) and the
        # open hotspot events awaiting dissipation
        self._pod_mult: Dict[str, float] = {}
        self._hotspots: List[Tuple[int, set]] = []
        # koordcolo: the active prod-usage surge (end cycle, marked pod
        # keys) and the pending staleness probes — (metric-write cycle,
        # node -> batch-cpu baseline) awaiting the dispatch that first
        # observes the shifted overcommit
        self._surge: Optional[Tuple[int, set]] = None
        self._colo_pending: List[Tuple[int, Dict[str, int]]] = []
        self._dump_budget = {"invariant_breach": MAX_EVENT_DUMPS,
                             "slo_overrun": MAX_EVENT_DUMPS}
        # crash-restart (koordguard): sim time of the last restart still
        # awaiting its first bind, plus the dead schedulers' counters
        # folded into the final report
        self._flight_dir = flight_dir
        self._restart_time: Optional[float] = None
        self._restart_wall = 0.0
        self._prior_transitions: List[dict] = []
        self._prior_flight_dumps = 0
        self._prior_sidecar_fallbacks = 0
        self._prior_deadline_overruns = 0
        self._build_world()
        self._build_scheduler(flight_dir)
        # koordwatch: the LIVE SloRegistry — same objectives the report
        # computes from, observed as samples land, feeding the
        # koord_slo_burn_rate/koord_slo_met gauges and /debug/slo.
        # Built AFTER _build_scheduler: the colo staleness target lands
        # on the report there, and the registry must register the REAL
        # target, not the dataclass default.
        from koordinator_tpu.scheduler import metrics as scheduler_metrics

        self.slo = self.report.slo_registry(
            burn_gauge=scheduler_metrics.SLO_BURN_RATE,
            met_gauge=scheduler_metrics.SLO_MET)

    # ------------------------------------------------------------------
    # world + scheduler construction
    # ------------------------------------------------------------------
    def _build_world(self) -> None:
        import json

        for i in range(self.sc.nodes):
            node = Node(
                meta=ObjectMeta(name=f"n{i}", namespace=""),
                allocatable=ResourceList.of(cpu=16_000, memory=64 * GIB,
                                            pods=50))
            node.meta.labels[ZONE] = f"z{i % 3}"
            if i % 4 == 0:
                node.attachable_volume_limit = 3
            if i % 5 == 0:
                node.meta.annotations[
                    "node.koordinator.sh/reservation"] = json.dumps(
                        {"resources": {"cpu": "2", "memory": "4Gi"}})
            self.store.add(KIND_NODE, node)
            nm = NodeMetric(
                meta=ObjectMeta(name=f"n{i}", namespace=""),
                update_time=self.now,
                node_metric=NodeMetricInfo(
                    node_usage=ResourceList.of(
                        cpu=1_000 + 500 * (i % 3), memory=4 * GIB)))
            self.store.add(KIND_NODE_METRIC, nm)
        # pre-bound initial workload (plain pods, round-robin): load
        # events (hotspots, drain storms) have real mass from cycle 0
        # instead of waiting for arrivals to fill the cluster
        rng = self.rng
        for i in range(self.sc.initial_pods):
            uid = self._next_uid()
            pod = Pod(
                meta=ObjectMeta(name=f"w{uid}", namespace="sim",
                                uid=f"w{uid}",
                                creation_timestamp=self.now,
                                labels={"app": rng.choice("abc")},
                                owner_kind="ReplicaSet",
                                owner_name=f"rs-{uid % 13}"),
                spec=PodSpec(
                    node_name=f"n{i % self.sc.nodes}",
                    priority=(PRIORITY_BE
                              if rng.random() < self.sc.be_fraction
                              else PRIORITY_PROD),
                    requests=ResourceList.of(
                        cpu=rng.choice([250, 500, 1000, 2000]),
                        memory=rng.choice([1, 2, 4]) * GIB)),
                phase="Running")
            self.store.add(KIND_POD, pod)
        # two sibling elastic quotas; the rebalance event shifts max
        # capacity between them
        total_cpu = self.sc.nodes * 16_000
        for qname in ("q-a", "q-b"):
            self.store.add(KIND_ELASTIC_QUOTA, ElasticQuota(
                meta=ObjectMeta(name=qname, namespace="sim"),
                min=ResourceList.of(cpu=2_000, memory=8 * GIB),
                max=ResourceList.of(cpu=total_cpu // 2,
                                    memory=self.sc.nodes * 32 * GIB)))

    def _build_scheduler(self, flight_dir: Optional[str]) -> None:
        from koordinator_tpu.obs.flight import FlightRecorder
        from koordinator_tpu.scheduler import metrics as scheduler_metrics
        from koordinator_tpu.scheduler.cycle import CyclePipeline, Scheduler
        from koordinator_tpu.scheduler.degrade import DegradationLadder

        sc = self.sc
        self.sched_store = FaultyStore(self.store, self.plan)
        self.sched = Scheduler(
            self.sched_store,
            waves=sc.waves,
            explain=sc.explain if sc.explain is not None else "off",
            mesh=sc.mesh if sc.mesh is not None else "off",
            ladder=DegradationLadder(promote_after=sc.promote_after),
            dispatch_deadline_ms=(sc.dispatch_deadline_ms
                                  if sc.dispatch_deadline_ms is not None
                                  else 0),
            pack_overlap=sc.pack_overlap,
        )
        # koordlint rule 20, runtime half: after the warm-up ladder
        # completes, a step-cache miss in the hot path is flagged — the
        # report carries the per-restart (to first bind) and run totals
        # the coldstart gate asserts on
        self._steady_flags_since_restart = 0

        def _on_steady_miss(_key) -> None:
            self._steady_flags_since_restart += 1
            self.report.steady_state_compile_flags += 1

        self.sched.compile_miss_hook = _on_steady_miss
        self.sched.fault_injector = self.plan.dispatch_hook
        self.sched.sync_delay_injector = self.plan.sync_delay_hook
        self.sched.upload_fault_injector = self.plan.upload_hook
        if flight_dir:
            self.sched.flight = FlightRecorder(
                dump_dir=flight_dir,
                dump_counter=scheduler_metrics.FLIGHT_DUMPS)
        self.pipeline = (CyclePipeline(self.sched, enabled=True)
                         if sc.pipeline else None)
        self.manager = None
        if sc.colo_every > 0:
            from koordinator_tpu.manager import Manager

            # the co-located koord-manager (koordcolo): shares the
            # scheduler's SnapshotCache subscriptions (the pack) and
            # DeviceSnapshot (the uploads) — the third consumer. It
            # writes through the simulator's own store view (manager
            # store writes are not the faulted path under test) and its
            # lease never expires inside a run (one replica).
            self.manager = Manager(
                self.store, identity="sim-manager",
                scheduler=self.sched,
                colo=(sc.colo if sc.colo is not None else "on"),
                lease_duration_seconds=1e9)
            self.report.colo_staleness_slo_cycles = (
                sc.colo_staleness_slo_cycles)
        self.desch = None
        if sc.descheduler_every > 0:
            from koordinator_tpu.descheduler.descheduler import Descheduler

            # the descheduler shares the simulator's store view directly
            # (injected store faults target the scheduler's bind path)
            # and the SCHEDULER's snapshot: its LowNodeLoad view rides
            # the SnapshotCache subscription chain and the device
            # rebalance pass uploads through the scheduler's
            # DeviceSnapshot — the one-upload-two-consumers production
            # wiring (koordbalance)
            self.desch = Descheduler(self.store, scheduler=self.sched,
                                     rebalance=sc.rebalance)

    # ------------------------------------------------------------------
    # workload generation
    # ------------------------------------------------------------------
    def _next_uid(self) -> int:
        self._uid += 1
        return self._uid

    def _make_pod(self, prefix: str = "p") -> Pod:
        rng = self.rng
        uid = self._next_uid()
        name = f"{prefix}{uid}"
        labels = {"app": rng.choice("abc")}
        is_be = rng.random() < self.sc.be_fraction
        is_batch = (is_be and self.sc.batch_fraction > 0
                    and rng.random() < self.sc.batch_fraction)
        if is_batch:
            # koordcolo consumer: a batch-class pod whose requests live
            # on the overcommit axes the colo pass publishes — it binds
            # only where batch allocatable (capacity*reclaim% - usage)
            # currently covers it
            spec = PodSpec(
                priority=PRIORITY_BE,
                requests=ResourceList.of(
                    batch_cpu=rng.choice([500, 1000, 2000]),
                    batch_memory=rng.choice([1, 2]) * GIB))
        else:
            spec = PodSpec(
                priority=PRIORITY_BE if is_be else PRIORITY_PROD,
                requests=ResourceList.of(
                    cpu=rng.choice([250, 500, 1000, 2000]),
                    memory=rng.choice([1, 2, 4]) * GIB))
        # controller-owned (ReplicaSet analog): the eviction chain
        # categorically refuses bare pods, so ownerless sim pods would
        # make every migration vacuous. Deterministic owner from uid —
        # no extra rng draws, the arrival stream is unchanged.
        pod = Pod(meta=ObjectMeta(name=name, namespace="sim", uid=name,
                                  creation_timestamp=self.now,
                                  labels=labels,
                                  owner_kind="ReplicaSet",
                                  owner_name=f"rs-{uid % 13}"),
                  spec=spec)
        r = rng.random()
        if r < 0.10:
            pod.spec.host_ports.append(
                ("TCP", rng.choice([80, 443, 9090])))
        elif r < 0.20:
            pod.spec.pvc_names = [f"claim-{uid}"]
        elif r < 0.30:
            pod.spec.pod_anti_affinity.append(PodAffinityTerm(
                selector={"app": labels["app"]}, topology_key=ZONE))
        elif r < 0.40 and not is_be:
            pod.meta.labels[LABEL_QUOTA_NAME] = rng.choice(["q-a", "q-b"])
        return pod

    def _make_gang(self, storm_idx: int, cycle: int) -> List[Pod]:
        gname = f"gang-{storm_idx}-{self._next_uid()}"
        pg = PodGroup(
            meta=ObjectMeta(name=gname, namespace="sim",
                            creation_timestamp=self.now),
            min_member=self.sc.gang_size)
        self.store.add(KIND_POD_GROUP, pg)
        members = []
        for _ in range(self.sc.gang_size):
            uid = self._next_uid()
            members.append(Pod(
                meta=ObjectMeta(name=f"g{uid}", namespace="sim",
                                uid=f"g{uid}",
                                creation_timestamp=self.now,
                                labels={LABEL_POD_GROUP: gname},
                                # training jobs protect their members:
                                # the PDB-like guard keeps the
                                # descheduler's migration pass off gang
                                # pods (evicting one would break the
                                # all-or-nothing invariant mid-life)
                                annotations={
                                    "descheduler.alpha.kubernetes.io/"
                                    "evict": "false"}),
                spec=PodSpec(requests=ResourceList.of(
                    cpu=1000, memory=GIB))))
        if self.sc.gang_lifetime > 0:
            self._gangs.append((cycle + self.sc.gang_lifetime, pg.meta.key,
                                [m.meta.key for m in members]))
        return members

    def _finish_gangs(self, cycle: int) -> None:
        """Whole gangs complete as one unit (a training job finishing):
        every member and the PodGroup leave together — all-or-nothing in
        death as in life, so the invariant checker never sees a partial
        gang from lifecycle churn. Without this, immortal gangs slowly
        clog the cluster and strangle plain-pod throughput."""
        due = [g for g in self._gangs if g[0] <= cycle]
        if not due:
            return
        self._gangs = [g for g in self._gangs if g[0] > cycle]
        for _at, pg_key, member_keys in due:
            for key in member_keys:
                if self.store.get(KIND_POD, key) is not None:
                    self.store.delete(KIND_POD, key)
                self._arrival_time.pop(key, None)
                self.report.pods_departed += 1
            self.store.delete(KIND_POD_GROUP, pg_key)

    def _poisson(self, lam: float) -> int:
        """Knuth's seeded Poisson draw — numpy's generator would need a
        second seed stream; random.Random keeps ONE deterministic
        sequence for the whole scenario."""
        import math

        if lam <= 0:
            return 0
        L = math.exp(-lam)
        k, p = 0, 1.0
        while True:
            p *= self.rng.random()
            if p <= L:
                return k
            k += 1

    # ------------------------------------------------------------------
    # queue admission (backpressure)
    # ------------------------------------------------------------------
    def _pending_count(self) -> int:
        return sum(1 for p in self.store.list(KIND_POD)
                   if not p.is_assigned and not p.is_terminated)

    def _admit(self, fresh: List[Pod]) -> None:
        """Bounded-queue admission: the waiting room drains FIFO first
        (requeue), fresh arrivals line up behind it, and anything beyond
        the waiting room's own bound is shed (dropped, counted). Gang
        members bypass the cap as one unit — admitting half a gang would
        deadlock its barrier forever."""
        for pod in fresh:
            self._arrival_time.setdefault(pod.meta.key, self.now)
        gangs, plain = [], []
        for pod in fresh:
            (gangs if pod.gang_name else plain).append(pod)
        for pod in gangs:
            self.store.add(KIND_POD, pod)
        queue = self._overflow + plain
        budget = max(0, self.sc.queue_cap - self._pending_count())
        admit, wait = queue[:budget], queue[budget:]
        fresh_ids = {id(p) for p in plain}
        self.report.pods_requeued += sum(
            1 for p in admit if id(p) not in fresh_ids)
        for pod in admit:
            self.store.add(KIND_POD, pod)
        if len(wait) > self.sc.overflow_cap:
            shed = wait[self.sc.overflow_cap:]
            wait = wait[:self.sc.overflow_cap]
            self.report.pods_shed += len(shed)
            for pod in shed:
                self._arrival_time.pop(pod.meta.key, None)
        self._overflow = wait
        self.report.max_overflow = max(self.report.max_overflow,
                                       len(self._overflow))

    # ------------------------------------------------------------------
    # cluster events
    # ------------------------------------------------------------------
    def _running_pods(self, include_gang: bool = False) -> List[Pod]:
        return [p for p in self.store.list(KIND_POD)
                if p.is_assigned and not p.is_terminated
                and (include_gang or not p.gang_key)]

    def _departures(self) -> None:
        n = self._poisson(self.sc.departure_rate)
        if n <= 0:
            return
        running = self._running_pods()
        for pod in self.rng.sample(running, min(n, len(running))):
            self.store.delete(KIND_POD, pod.meta.key)
            self._arrival_time.pop(pod.meta.key, None)
            self._pod_mult.pop(pod.meta.key, None)
            self.report.pods_departed += 1

    def _drain_step(self, cycle: int) -> None:
        sc = self.sc
        # advance in-flight drains
        by_name = {n.meta.name: n for n in self.store.list(KIND_NODE)}
        still = []
        for name, left in self._draining:
            node = by_name.get(name)
            if node is None:
                continue
            if left > 1:
                still.append((name, left - 1))
                continue
            # drain complete: delete the node only when nothing is bound
            # to it anymore (gang pods are not drained — see below — and
            # deleting a node under them would orphan bound pods)
            bound = [p for p in self.store.list(KIND_POD)
                     if p.spec.node_name == name and p.is_assigned
                     and not p.is_terminated]
            if sc.drain_delete and not bound:
                self.store.delete(KIND_NODE, node.meta.key)
            else:
                node.unschedulable = False
                self.store.update(KIND_NODE, node)
        self._draining = still
        if sc.drain_every <= 0 or cycle == 0 or cycle % sc.drain_every:
            return
        # drains_per_event > 1 is the drain-storm shape: several nodes
        # cordoned in one event, their load concentrating on the
        # survivors (which the descheduler then has to rebalance)
        for _ in range(max(1, sc.drains_per_event)):
            draining_names = {n for n, _ in self._draining}
            candidates = [n for n in self.store.list(KIND_NODE)
                          if not n.unschedulable
                          and n.meta.name not in draining_names]
            if len(candidates) <= 2:
                return  # never drain the cluster below a working floor
            node = self.rng.choice(candidates)
            node.unschedulable = True
            self.store.update(KIND_NODE, node)
            self._draining.append((node.meta.name,
                                   sc.drain_uncordon_after))
            # evict (and requeue) the node's non-gang pods — the
            # reference drains via eviction + reschedule; gang members
            # stay (evicting one would legitimately break
            # all-or-nothing, which is gang lifecycle churn, not a
            # scheduler violation)
            evicted = []
            for pod in self.store.list(KIND_POD):
                if (pod.spec.node_name == node.meta.name
                        and pod.is_assigned
                        and not pod.is_terminated and not pod.gang_key):
                    self.store.delete(KIND_POD, pod.meta.key)
                    self._arrival_time.pop(pod.meta.key, None)
                    self._pod_mult.pop(pod.meta.key, None)
                    evicted.append(pod)
            self.report.pods_drained += len(evicted)
            self._admit([self._make_pod(prefix="re") for _ in evicted])

    def _spot_reclaim(self, cycle: int) -> None:
        sc = self.sc
        if sc.spot_reclaim_every <= 0 or cycle == 0 or (
                cycle % sc.spot_reclaim_every):
            return
        be = [p for p in self._running_pods()
              if (p.spec.priority or 0) < 9000]
        victims = self.rng.sample(be, min(sc.spot_reclaim_count, len(be)))
        for pod in victims:
            self.store.delete(KIND_POD, pod.meta.key)
            self._arrival_time.pop(pod.meta.key, None)
            self._pod_mult.pop(pod.meta.key, None)
            self.report.pods_reclaimed += 1
        # the reclaimed workload comes straight back as fresh arrivals —
        # spot churn, not capacity loss
        self._admit([self._make_pod(prefix="sp") for _ in victims])

    def _metric_flip(self, cycle: int) -> None:
        sc = self.sc
        if sc.metric_flip_every <= 0 or cycle == 0 or (
                cycle % sc.metric_flip_every):
            return
        self._metric_flip_state = not self._metric_flip_state
        for i, nm in enumerate(self.store.list(KIND_NODE_METRIC)):
            if i % 2 == (0 if self._metric_flip_state else 1):
                nm.update_time = self.now  # fresh
                nm.node_metric.node_usage = ResourceList.of(
                    cpu=1_000 + 250 * (i % 5), memory=4 * GIB)
            else:
                nm.update_time = self.now - 10_000.0  # expired
            self.store.update(KIND_NODE_METRIC, nm)

    # ------------------------------------------------------------------
    # rebalance-under-load events (koordbalance)
    # ------------------------------------------------------------------
    def _hotspot_step(self, cycle: int) -> None:
        """Every hotspot_every cycles: the pods on a few seeded nodes
        turn HOT (usage multiplier) — real overload from mis-estimated
        workloads, which only migration can dissipate. Gang pods are
        skipped (their guard makes them unevictable, so their heat could
        never dissipate)."""
        sc = self.sc
        if sc.hotspot_every <= 0 or cycle == 0 or cycle % sc.hotspot_every:
            return
        nodes = [n for n in self.store.list(KIND_NODE)
                 if not n.unschedulable]
        if not nodes:
            return
        # the MOST-LOADED nodes flip hot (deterministic: count desc,
        # name): a hotspot on an empty node is not a hotspot
        counts: Dict[str, int] = {}
        for pod in self.store.list(KIND_POD):
            if pod.is_assigned and not pod.is_terminated and not pod.gang_key:
                counts[pod.spec.node_name] = counts.get(
                    pod.spec.node_name, 0) + 1
        nodes.sort(key=lambda n: (-counts.get(n.meta.name, 0),
                                  n.meta.name))
        chosen = nodes[: sc.hotspot_nodes]
        names = {n.meta.name for n in chosen}
        marked = 0
        for pod in self.store.list(KIND_POD):
            if (pod.is_assigned and not pod.is_terminated
                    and pod.spec.node_name in names and not pod.gang_key):
                self._pod_mult[pod.meta.key] = sc.hotspot_multiplier
                marked += 1
        if marked:
            self._hotspots.append((cycle, names))
            self.report.hotspot_events += 1

    # ------------------------------------------------------------------
    # overcommit-shift events (koordcolo)
    # ------------------------------------------------------------------
    def _batch_cpu_baseline(self, names) -> Dict[str, int]:
        out = {}
        for name in names:
            node = self.store.get(KIND_NODE, f"/{name}")
            if node is not None:
                out[name] = node.allocatable[
                    "kubernetes.io/batch-cpu"] or 0
        return out

    def _overcommit_surge(self, cycle: int) -> None:
        """Prod-usage surge: the PROD pods on the busiest nodes run hot
        for overcommit_surge_cycles (usage-derived NodeMetrics rise, the
        colo pass shrinks batch allocatable), then recede. Both edges
        record a staleness probe: the metric-write cycle plus the nodes'
        batch-cpu baseline — resolved by the first dispatch that runs
        against a changed value."""
        sc = self.sc
        if sc.overcommit_surge_every <= 0:
            return
        if self._surge is not None:
            end, keys = self._surge
            if cycle >= end:
                names = set()
                for key in keys:
                    self._pod_mult.pop(key, None)
                    pod = self.store.get(KIND_POD, key)
                    if pod is not None and pod.spec.node_name:
                        names.add(pod.spec.node_name)
                self._surge = None
                self.report.overcommit_shifts += 1
                self._colo_pending.append(
                    (cycle, self._batch_cpu_baseline(names)))
            return
        if cycle == 0 or cycle % sc.overcommit_surge_every:
            return
        counts: Dict[str, int] = {}
        for pod in self.store.list(KIND_POD):
            if (pod.is_assigned and not pod.is_terminated
                    and not pod.gang_key
                    and (pod.spec.priority or 0) >= 9000):
                counts[pod.spec.node_name] = counts.get(
                    pod.spec.node_name, 0) + 1
        nodes = sorted(counts, key=lambda n: (-counts[n], n))
        chosen = set(nodes[: sc.overcommit_surge_nodes])
        if not chosen:
            return
        keys = set()
        for pod in self.store.list(KIND_POD):
            if (pod.is_assigned and not pod.is_terminated
                    and pod.spec.node_name in chosen and not pod.gang_key
                    and (pod.spec.priority or 0) >= 9000):
                self._pod_mult[pod.meta.key] = (
                    sc.overcommit_surge_multiplier)
                keys.add(pod.meta.key)
        if keys:
            self._surge = (cycle + sc.overcommit_surge_cycles, keys)
            self.report.overcommit_shifts += 1
            self._colo_pending.append(
                (cycle, self._batch_cpu_baseline(chosen)))

    def _observe_colo_staleness(self, cycle: int) -> None:
        """Resolve pending staleness probes: the first cycle whose
        dispatch ran against a changed batch-cpu on any probed node
        closes the probe at (cycle - write cycle)."""
        still = []
        for write_cycle, baseline in self._colo_pending:
            if not baseline:
                # every probed node departed before the edge landed:
                # nothing left to observe — drop rather than park the
                # probe forever (the SLO must not claim unmeasured edges)
                continue
            changed = False
            for n, base in baseline.items():
                node = self.store.get(KIND_NODE, f"/{n}")
                if node is not None and (
                        node.allocatable["kubernetes.io/batch-cpu"]
                        or 0) != base:
                    changed = True
                    break
            if changed:
                self.report.colo_staleness_cycles.append(
                    cycle - write_cycle)
                self.slo.observe("colo_staleness",
                                 float(cycle - write_cycle))
            else:
                still.append((write_cycle, baseline))
        self._colo_pending = still

    def _refresh_usage_metrics(self) -> None:
        """metrics_follow_usage: NodeMetric usage derives from the pods
        actually bound to each node (x their hot multipliers), so
        migrating load away genuinely lowers the source node's reading.
        Metrics the flip event deliberately expired stay expired."""
        sc = self.sc
        if not sc.metrics_follow_usage:
            return
        cpu_by: Dict[str, float] = {}
        mem_by: Dict[str, float] = {}
        for pod in self.store.list(KIND_POD):
            if not pod.is_assigned or pod.is_terminated:
                continue
            mult = self._pod_mult.get(pod.meta.key, 1.0)
            node = pod.spec.node_name
            cpu_by[node] = cpu_by.get(node, 0.0) + (
                pod.spec.requests.get("cpu", 0) or 0) * mult
            mem_by[node] = mem_by.get(node, 0.0) + (
                pod.spec.requests.get("memory", 0) or 0) * mult
        for nm in self.store.list(KIND_NODE_METRIC):
            expired = nm.update_time <= self.now - 9_000.0
            nm.node_metric = NodeMetricInfo(node_usage=ResourceList.of(
                cpu=sc.usage_idle_cpu + int(
                    cpu_by.get(nm.meta.name, 0.0) * sc.usage_fraction),
                memory=2 * GIB + int(
                    mem_by.get(nm.meta.name, 0.0) * sc.usage_fraction)))
            if not expired:
                nm.update_time = self.now
            self.store.update(KIND_NODE_METRIC, nm)

    def _node_is_hot(self, name: str) -> bool:
        """LowNodeLoad's default high thresholds (70% cpu / 80% mem)
        against the current metric — the dissipation probe."""
        node = self.store.get(KIND_NODE, f"/{name}")
        nm = self.store.get(KIND_NODE_METRIC, f"/{name}")
        if node is None or nm is None:
            return False
        alloc = node.allocatable
        usage = nm.node_metric.node_usage
        cpu_pct = (usage.get("cpu", 0) or 0) * 100.0 / max(
            alloc.get("cpu", 0) or 1, 1)
        mem_pct = (usage.get("memory", 0) or 0) * 100.0 / max(
            alloc.get("memory", 0) or 1, 1)
        return cpu_pct > 70.0 or mem_pct > 80.0

    def _note_hotspot_dissipation(self, cycle: int) -> None:
        still: List[Tuple[int, set]] = []
        for event_cycle, names in self._hotspots:
            if (cycle > event_cycle
                    and not any(self._node_is_hot(n) for n in names)):
                self.report.dissipate_cycles.append(cycle - event_cycle)
                self.slo.observe("hotspot_dissipate",
                                 float(cycle - event_cycle))
            else:
                still.append((event_cycle, names))
        self._hotspots = still

    def _sweep_migrated(self) -> None:
        """The workload-controller analog for migration evictions: a pod
        the migration controller evicted (Failed + the evicted
        annotation) is replaced by a fresh replica with the same labels
        and requests — which the scheduler's nomination pre-pass matches
        to the migration's replacement Reservation. The replacement
        inherits the hot multiplier: the workload is hot wherever it
        runs, so hotspots dissipate by SPREADING, not by vanishing."""
        evicted = [p for p in self.store.list(KIND_POD)
                   if p.phase == "Failed"
                   and "koordinator.sh/evicted" in p.meta.annotations]
        if not evicted:
            return
        fresh: List[Pod] = []
        for pod in evicted:
            self.store.delete(KIND_POD, pod.meta.key)
            mult = self._pod_mult.pop(pod.meta.key, 1.0)
            self._arrival_time.pop(pod.meta.key, None)
            self.report.pods_migrated += 1
            uid = self._next_uid()
            repl = Pod(
                meta=ObjectMeta(name=f"mg{uid}", namespace="sim",
                                uid=f"mg{uid}",
                                creation_timestamp=self.now,
                                labels=dict(pod.meta.labels),
                                owner_kind=pod.meta.owner_kind,
                                owner_name=pod.meta.owner_name),
                spec=PodSpec(priority=pod.spec.priority,
                             requests=pod.spec.requests.copy()))
            if mult != 1.0:
                self._pod_mult[repl.meta.key] = mult
            fresh.append(repl)
        self._admit(fresh)

    def _quota_rebalance(self, cycle: int) -> None:
        sc = self.sc
        if sc.quota_rebalance_every <= 0 or cycle == 0 or (
                cycle % sc.quota_rebalance_every):
            return
        total_cpu = max(1, len(self.store.list(KIND_NODE))) * 16_000
        quotas = sorted(self.store.list(KIND_ELASTIC_QUOTA),
                        key=lambda q: q.meta.name)
        if len(quotas) < 2:
            return
        # shift capacity: one quota tight, the other generous, alternating
        tight, wide = ((quotas[0], quotas[1])
                       if (cycle // sc.quota_rebalance_every) % 2
                       else (quotas[1], quotas[0]))
        tight.max = ResourceList.of(cpu=total_cpu // 8,
                                    memory=len(quotas) * 16 * GIB)
        wide.max = ResourceList.of(cpu=total_cpu,
                                   memory=len(quotas) * 64 * GIB)
        self.store.update(KIND_ELASTIC_QUOTA, tight)
        self.store.update(KIND_ELASTIC_QUOTA, wide)

    # ------------------------------------------------------------------
    # the loop
    # ------------------------------------------------------------------
    def _dump(self, reason: str) -> None:
        if self._dump_budget.get(reason, 0) > 0:
            self._dump_budget[reason] -= 1
            self.sched.flight.dump(reason)

    def _crash_restart(self, cycle: int) -> None:
        """The crash-restart event (koordguard): the scheduler process
        dies mid-soak — every watch its store view registered is severed
        (the apiserver dropping a dead client), and ALL in-process state
        goes with the object graph: device buffers, compiled step
        caches, the pack memo, plugin assumed/quota state. A fresh
        Scheduler is then constructed against the SURVIVING store: its
        plugins and SnapshotCache replay list-then-watch, so the first
        cycle re-derives assumed/quota/gang state from store-visible
        binds. The report tracks sim time from here to the fresh
        scheduler's first bind (the restart-to-first-bind SLO)."""
        old = self.sched
        self._prior_transitions.extend(old.ladder.transitions)
        self._prior_flight_dumps += old.flight.dumps
        self._prior_sidecar_fallbacks += old.sidecar_fallbacks
        self._prior_deadline_overruns += old.dispatch_watchdog.overruns
        if self.desch is not None and self.desch.rebalancer is not None:
            # the descheduler dies with the scheduler process: its
            # rebalance-pass overruns must survive into the report too
            self._prior_deadline_overruns += (
                self.desch.rebalancer.dispatch_watchdog.overruns)
        if self.manager is not None and self.manager.colo is not None:
            # so do the co-located manager's colo-pass overruns
            self._prior_deadline_overruns += (
                self.manager.colo.dispatch_watchdog.overruns)
        self.sched_store.sever()
        self.report.restarts += 1
        # the crash is anchored at the END of the previous cycle: a
        # fresh scheduler that binds within its first cycle reads one
        # dt of sim-clock recovery, not a degenerate 0.0
        self._restart_time = self.now - self.sc.dt_seconds
        self._restart_wall = time.perf_counter()
        self._build_scheduler(self._flight_dir)
        logger.warning("sim cycle %d: scheduler crash-restart (store "
                       "survives, scheduler state dropped)", cycle)

    def _account_bind(self, cycle: int, pod_key: str,
                      node_name: str) -> None:
        """One committed binding into the report: phase bookkeeping is
        the caller's; this records ttb (+ SLO overrun), the bound
        counter, restart recovery, and the binding-log line."""
        if self._restart_time is not None:
            recovery = self.now - self._restart_time
            self.report.restart_to_first_bind_seconds.append(recovery)
            self.slo.observe("restart_to_first_bind", recovery)
            self.report.restart_to_first_bind_wall_seconds.append(
                time.perf_counter() - self._restart_wall)
            # the recovery wall split (PR 15): the fresh scheduler's
            # cumulative compile/pack wall IS the restart's — it was
            # born at the crash, and warm-up ran inside this window
            self.report.restart_wall_compile_seconds.append(
                self.sched.compile_wall_seconds)
            self.report.restart_wall_pack_seconds.append(
                self.sched.pack_wall_seconds)
            self.report.restart_steady_state_compiles.append(
                self._steady_flags_since_restart)
            self._restart_time = None
        arrived = self._arrival_time.pop(pod_key, None)
        if arrived is not None:
            ttb = self.now - arrived
            self.report.ttb_seconds.append(ttb)
            self.slo.observe("ttb_p99", ttb)
            if ttb > self.sc.ttb_slo_seconds:
                self.report.slo_overruns += 1
                self._dump("slo_overrun")
        self.report.pods_bound += 1
        self.report.binding_log.append(
            f"{cycle}\t{pod_key}\t{node_name}")

    def _reconcile_store_binds(self, cycle: int):
        """After a mid-cycle exception: bindings the cycle applied before
        the wreck are already store-visible (a store-write fault raises
        mid-bind-loop), but never reached ``result.bound``. Sweep the
        tracked pending pods and account any the store now shows
        assigned, exactly as the normal path would — arrival order, the
        seeded run's deterministic iteration order. Returns the
        reconciled keys so the invariant check (batch-bind discipline
        included) sees the partial cycle's binds."""
        bound = []
        for key in list(self._arrival_time):
            pod = self.store.get(KIND_POD, key)
            if pod is None or not pod.is_assigned or pod.is_terminated:
                continue
            if pod.phase != "Running":
                pod.phase = "Running"
                self.store.update(KIND_POD, pod)
            self._account_bind(cycle, key, pod.spec.node_name)
            bound.append(key)
        return bound

    def _check_invariants(self, cycle: int, bound_keys=()) -> None:
        breaches = check_invariants(
            self.store, now=self.now,
            batch_shrink_grace=self.sc.colo_every > 0)
        if self.sc.colo_every > 0 and bound_keys:
            from koordinator_tpu.sim.invariants import (
                check_batch_bind_discipline,
            )

            breaches = breaches + check_batch_bind_discipline(
                self.store, bound_keys)
        if breaches:
            self.report.invariant_breaches.extend(
                f"cycle {cycle}: {b}" for b in breaches)
            self._dump("invariant_breach")

    def _run_one_cycle(self, cycle: int) -> None:
        sc = self.sc
        self.now += sc.dt_seconds
        if cycle in sc.restart_at:
            self._crash_restart(cycle)
        self.plan.begin_cycle(cycle)
        # sidecar fault window: swap a dead client in (the sidecar layer
        # must degrade to the local step, never wedge the cycle)
        self.sched._sidecar_client = (DeadSidecarClient()
                                      if self.plan.sidecar_armed() else None)
        # cluster events before arrivals, arrivals before the cycle —
        # a fixed order is what makes the run reproducible
        self._finish_gangs(cycle)
        self._drain_step(cycle)
        self._spot_reclaim(cycle)
        self._metric_flip(cycle)
        self._quota_rebalance(cycle)
        self._departures()
        self._hotspot_step(cycle)
        self._overcommit_surge(cycle)
        self._refresh_usage_metrics()
        self._note_hotspot_dissipation(cycle)
        fresh = [self._make_pod() for _ in range(
            self._poisson(sc.arrival_rate))]
        if sc.burst_every > 0 and cycle > 0 and cycle % sc.burst_every == 0:
            fresh.extend(self._make_pod(prefix="b")
                         for _ in range(sc.burst_size))
        if sc.gang_every > 0 and cycle > 0 and cycle % sc.gang_every == 0:
            for s in range(sc.gangs_per_storm):
                fresh.extend(self._make_gang(cycle * 10 + s, cycle))
        self.report.pods_created += len(fresh)
        self._admit(fresh)
        # koordwatch pending-queue visibility: the depth this cycle's
        # dispatch will drain, plus the oldest enqueued entry's age
        # (store-pending AND waiting-room pods — both are enqueued)
        depth = self._pending_count()
        self.report.max_pending = max(self.report.max_pending, depth)
        self.report.queue_depth_by_cycle.append(depth)
        self.report.queue_oldest_wait_by_cycle.append(
            self.now - min(self._arrival_time.values())
            if self._arrival_time else 0.0)

        # koordcolo: the manager tick BEFORE the dispatch — the very
        # next scheduling dispatch consumes the overcommit this pass
        # publishes (the closed-loop ordering the acceptance pins)
        if (self.manager is not None
                and cycle % self.sc.colo_every == 0):
            self.manager.tick(now=self.now)
            self.report.manager_rounds += 1
            stats = (self.manager.colo.last_pass_stats
                     if self.manager.colo is not None else {})
            if stats.get("engine") == "device":
                self.report.colo_device_passes += 1
            elif stats.get("engine"):
                self.report.colo_host_passes += 1

        driver = self.pipeline if self.pipeline is not None else self.sched
        t_cycle = time.perf_counter()
        try:
            result = driver.run_cycle(now=self.now)
        except Exception as exc:  # the flight recorder already dumped
            # the wrecked cycle's wall still counts (device idle in it)
            self.report.cycle_wall_seconds += (
                time.perf_counter() - t_cycle)
            self.report.cycle_exceptions.append(
                f"cycle {cycle}: {type(exc).__name__}: {exc}")
            logger.warning("sim cycle %d raised: %s", cycle, exc)
            # bindings applied before the wreck are already store-visible
            # (e.g. a store-write fault mid-bind-loop): reconcile them
            # into the report so binding_log/ttb/pods_bound match the
            # store, then still run the invariant check — a partially
            # applied cycle is exactly when it matters
            bound_keys = self._reconcile_store_binds(cycle)
            self._check_invariants(cycle, bound_keys=bound_keys)
            return
        wall = time.perf_counter() - t_cycle
        self.report.cycle_wall_seconds += wall
        self.report.device_busy_seconds += result.device_busy_seconds
        # koordwatch demotion profile: a cycle that ran below its
        # configured level carries its structured reasons; attribute the
        # cycle to the FIRST (the chokepoint appends in hit order), so
        # per-reason counts sum exactly to cycles_demoted
        if result.demotions:
            self.report.cycles_demoted += 1
            reason = result.demotions[0]
            self.report.demotion_cycles_by_reason[reason] = (
                self.report.demotion_cycles_by_reason.get(reason, 0) + 1)
        k = max(1, int(result.waves))
        self.report.wall_by_waves[k] = (
            self.report.wall_by_waves.get(k, 0.0) + wall)
        self.report.bound_by_waves[k] = (
            self.report.bound_by_waves.get(k, 0) + len(result.bound))
        for b in result.bound:
            pod = self.store.get(KIND_POD, b.pod_key)
            if pod is None or pod.is_terminated:
                # bound and then preempted/evicted within the SAME cycle
                # (a later wave's preemption chose it as a victim):
                # flipping it back to Running would resurrect a
                # terminated pod in place and overcommit its node
                continue
            pod.phase = "Running"  # bind -> Running, as the kubelet would
            self.store.update(KIND_POD, pod)
            self._account_bind(cycle, b.pod_key, b.node_name)
            if (pod.spec.requests["kubernetes.io/batch-cpu"]
                    or pod.spec.requests["kubernetes.io/batch-memory"]):
                self.report.batch_pods_bound += 1
        if self.manager is not None:
            self._observe_colo_staleness(cycle)
        if (self.desch is not None and cycle > 0
                and cycle % sc.descheduler_every == 0):
            try:
                out = self.desch.run_once(now=self.now)
                self.report.descheduler_runs += 1
                self.report.migration_jobs_created += out.get(
                    "jobs_created", 0)
            except Exception as exc:
                self.report.cycle_exceptions.append(
                    f"cycle {cycle} descheduler: "
                    f"{type(exc).__name__}: {exc}")
            # the workload-controller analog replaces migration-evicted
            # pods (they re-enter the queue and consume the replacement
            # reservations via the nomination pre-pass)
            self._sweep_migrated()
        # invariants run AFTER the descheduler so the migration-job and
        # reservation double-booking checks see its writes every cycle
        self._check_invariants(
            cycle, bound_keys=[b.pod_key for b in result.bound])

    def run(self) -> SimReport:
        self._t0 = time.perf_counter()
        for cycle in range(self.sc.cycles):
            self._run_one_cycle(cycle)
        return self.run_report()

    def run_report(self) -> SimReport:
        """Finalize the report — run() is loop + run_report(); tests
        that drive cycles manually (inspecting scheduler state between
        them) call this directly."""
        if self.pipeline is not None:
            self.pipeline.flush()
        self.report.wall_seconds = (
            time.perf_counter() - getattr(self, "_t0", time.perf_counter()))
        self.report.final_pending = self._pending_count()
        self.report.hotspots_open = len(self._hotspots)
        self.report.faults_injected = len(self.plan.injected)
        self.report.sidecar_fallbacks = (
            self._prior_sidecar_fallbacks + self.sched.sidecar_fallbacks)
        self.report.ladder_transitions = (
            self._prior_transitions + list(self.sched.ladder.transitions))
        self.report.final_level = self.sched.ladder.level_name
        self.report.flight_dumps = (
            self._prior_flight_dumps + self.sched.flight.dumps)
        overruns = (self._prior_deadline_overruns
                    + self.sched.dispatch_watchdog.overruns)
        if self.desch is not None and self.desch.rebalancer is not None:
            overruns += self.desch.rebalancer.dispatch_watchdog.overruns
        if self.manager is not None and self.manager.colo is not None:
            overruns += self.manager.colo.dispatch_watchdog.overruns
            self.report.colo_final_engine = str(
                self.manager.colo.last_pass_stats.get("engine", ""))
        self.report.deadline_overruns = overruns
        # koordwatch timeline: the final scheduler's idle fraction (the
        # ring is per-scheduler, so a crash-restart resets the window —
        # the A/B pack-overlap pair runs restart-free soaks)
        self.report.device_idle_fraction = self.sched.timeline.idle_fraction()
        if self.sched.warmup is not None:
            self.report.warmup = dict(self.sched.warmup.stats)
        return self.report


def run_scenario(scenario: Scenario,
                 flight_dir: Optional[str] = None) -> SimReport:
    """Build + run in one call; the harness tracks the per-cycle ladder
    residency histogram here so every caller gets it."""
    sim = ChurnSimulator(scenario, flight_dir=flight_dir)
    # per-cycle level residency: wrap the cycle runner
    counts: Dict[str, int] = {}
    orig = sim._run_one_cycle

    def counted(cycle: int) -> None:
        orig(cycle)
        name = sim.sched.ladder.level_name
        counts[name] = counts.get(name, 0) + 1

    sim._run_one_cycle = counted
    report = sim.run()
    report.cycles_at_level = counts
    return report
