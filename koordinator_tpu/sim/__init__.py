"""koordsim: a fault-injecting churn simulator for the real scheduler.

The cluster simulator ROADMAP calls the scenario-diversity engine and
the regression harness: seeded arrival/departure processes (Poisson
arrivals, gang storms, burst queues), cluster events (node drain/delete,
spot reclamation, metric-expiry flips, quota rebalances) and an
injectable :class:`FaultPlan` drive the REAL :class:`Scheduler` (and
optionally the descheduler) for thousands of cycles, checking the
store-level invariants (:mod:`koordinator_tpu.sim.invariants`) after
every cycle and tracking time-to-bind p50/p99 SLOs with pending-queue
backpressure.

Run named scenarios with ``python -m koordinator_tpu.sim <scenario>``;
the catalog lives in :mod:`koordinator_tpu.sim.scenarios`.
"""

from koordinator_tpu.sim.faults import (  # noqa: F401
    DeviceLossFault,
    Fault,
    FaultPlan,
    FaultyStore,
    InjectedFault,
)
from koordinator_tpu.sim.harness import ChurnSimulator, SimReport  # noqa: F401
from koordinator_tpu.sim.invariants import check_invariants  # noqa: F401
from koordinator_tpu.sim.scenarios import SCENARIOS, Scenario  # noqa: F401
