"""Fault injection: the plan, the dispatch hook, and the faulty store.

A :class:`FaultPlan` is a schedule of :class:`Fault` entries, armed by
the simulator at the start of each sim cycle:

  * ``kind="dispatch"`` — the scheduler's ``fault_injector`` hook raises
    :class:`InjectedFault` from inside the device-dispatch window for
    the next ``count`` attempts, exercising the degradation ladder
    (scheduler/degrade.py) exactly like a real XLA/mesh fault. Two
    failing attempts demote one rung (retry-once policy), so ``count``
    is the demotion depth dial: 2 = one rung, 8 = all the way to the
    pure-host fallback.
  * ``kind="store_write"`` — the next ``count`` store writes issued by
    the SCHEDULER (the simulator wraps only the scheduler's store view
    in :class:`FaultyStore`; its own churn mutations never fail) raise.
    This lands mid-bind or in the condition writer — paths the ladder
    deliberately does not absorb — so it pins that an unhandled cycle
    exception flight-dumps, re-raises, and the next cycle carries on.
  * ``kind="sidecar"`` — installs a dead in-process sidecar client stub
    (every RPC raises) for ``count`` cycles, exercising the sidecar's
    own local-step fallback path.

Everything is deterministic: faults fire at fixed cycles with fixed
budgets, no randomness.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple


class InjectedFault(RuntimeError):
    """The exception every injected fault raises — distinguishable from
    real bugs in sim reports."""


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scheduled fault: at sim cycle ``cycle``, arm ``count`` units
    of ``kind`` failure."""

    cycle: int
    kind: str              # "dispatch" | "store_write" | "sidecar"
    count: int = 1
    message: str = "injected fault"

    def __post_init__(self):
        if self.kind not in ("dispatch", "store_write", "sidecar"):
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.cycle < 0 or self.count < 1:
            raise ValueError("fault cycle must be >= 0 and count >= 1")


class FaultPlan:
    """Armed budgets per fault kind, advanced cycle by cycle. The
    simulator owns the lifecycle: ``begin_cycle`` arms the entries
    scheduled for that cycle, the hooks consume budget as they fire."""

    def __init__(self, faults: Sequence[Fault] = ()) -> None:
        self.faults: Tuple[Fault, ...] = tuple(faults)
        self._budget: Dict[str, int] = {
            "dispatch": 0, "store_write": 0, "sidecar": 0}
        self._message: Dict[str, str] = {}
        self.injected: List[dict] = []  # what actually fired, per kind
        self._cycle = -1

    def begin_cycle(self, cycle: int) -> None:
        self._cycle = cycle
        for f in self.faults:
            if f.cycle == cycle:
                self._budget[f.kind] += f.count
                self._message[f.kind] = f.message

    def budget(self, kind: str) -> int:
        return self._budget[kind]

    def _fire(self, kind: str, detail: str) -> None:
        self._budget[kind] -= 1
        self.injected.append(
            {"cycle": self._cycle, "kind": kind, "detail": detail})
        raise InjectedFault(
            f"{self._message.get(kind, 'injected fault')} "
            f"({kind}: {detail})")

    # ---- scheduler.fault_injector hook --------------------------------
    def dispatch_hook(self, stage: str) -> None:
        """Installed as ``Scheduler.fault_injector``; raises while the
        dispatch budget lasts."""
        if self._budget["dispatch"] > 0:
            self._fire("dispatch", stage)

    # ---- store-write hook ---------------------------------------------
    def store_write_hook(self, kind: str, key: str) -> None:
        if self._budget["store_write"] > 0:
            self._fire("store_write", f"{kind} {key}")

    # ---- sidecar ------------------------------------------------------
    def sidecar_armed(self) -> bool:
        """True while a sidecar fault cycle is active; the simulator
        swaps a dead client stub in/out of the scheduler. Consumes one
        budget unit per armed cycle."""
        if self._budget["sidecar"] > 0:
            self._budget["sidecar"] -= 1
            self.injected.append(
                {"cycle": self._cycle, "kind": "sidecar", "detail": "stub"})
            return True
        return False


class DeadSidecarClient:
    """A sidecar client whose every RPC raises a channel-level transport
    failure: what a timed-out / crashed gRPC peer looks like to
    schedule_batch_or_fallback, which must degrade to the local step
    (scheduler/sidecar.py catches ConnectionError/OSError)."""

    def schedule_batch(self, request):
        raise ConnectionError("sidecar timeout (injected)")

    def close(self) -> None:
        pass


class FaultyStore:
    """The scheduler's store view with write faults: forwards everything
    to the real store, but ``update``/``add``/``delete`` consult the
    plan first. Only the scheduler holds this wrapper — the simulator's
    own churn mutations go to the inner store directly."""

    def __init__(self, inner, plan: FaultPlan) -> None:
        # bypass __setattr__-free plain attributes; no locking needed,
        # the sim drives a single cycle thread
        self._inner = inner
        self._plan = plan

    def update(self, kind: str, obj):
        self._plan.store_write_hook(kind, getattr(
            getattr(obj, "meta", None), "key", "?"))
        return self._inner.update(kind, obj)

    def add(self, kind: str, obj):
        self._plan.store_write_hook(kind, getattr(
            getattr(obj, "meta", None), "key", "?"))
        return self._inner.add(kind, obj)

    def delete(self, kind: str, key: str):
        self._plan.store_write_hook(kind, key)
        return self._inner.delete(kind, key)

    def update_many(self, kind: str, objs):
        """Batched writes keep PER-OBJECT fault semantics: a store-write
        fault armed mid-batch leaves the earlier objects applied, exactly
        like N sequential updates — the reconcile-after-wreck path in the
        harness depends on partially-applied batches being visible."""
        return [self.update(kind, obj) for obj in objs]

    def __getattr__(self, name):
        return getattr(self._inner, name)
