"""Fault injection: the plan, the dispatch hooks, and the faulty store.

A :class:`FaultPlan` is a schedule of :class:`Fault` entries, armed by
the simulator at the start of each sim cycle:

  * ``kind="dispatch"`` — the scheduler's ``fault_injector`` hook raises
    :class:`InjectedFault` from inside the device-dispatch window for
    the next ``count`` attempts, exercising the degradation ladder
    (scheduler/degrade.py) exactly like a real XLA/mesh fault. Two
    failing attempts demote one rung (retry-once policy), so ``count``
    is the demotion depth dial: 2 = one rung, 8 = all the way to the
    pure-host fallback.
  * ``kind="device_loss"`` — like ``dispatch``, but the raised
    :class:`DeviceLossFault` NAMES the dead mesh devices
    (``devices=(6, 7)``): the failure is attributable, so the ladder's
    partial-mesh rung (koordguard) sheds only those devices and keeps
    dispatching on the surviving submesh.
  * ``kind="latency"`` — the next ``count`` MONITORED readback syncs
    sleep ``delay_ms`` before completing: a slow-not-dead device. With
    ``KOORD_TPU_DISPATCH_DEADLINE_MS`` armed below the delay, the
    dispatch watchdog (scheduler/deadline.py) abandons the window and
    the ladder demotes instead of the cycle wedging.
  * ``kind="oom_upload"`` — the next ``count`` DeviceSnapshot field
    uploads raise a RESOURCE_EXHAUSTED-shaped allocation failure, which
    snapshot_cache classifies as a ladder-demotable device fault
    (DeviceAllocationError), not a cycle exception.
  * ``kind="store_write"`` — the next ``count`` store writes issued by
    the SCHEDULER (the simulator wraps only the scheduler's store view
    in :class:`FaultyStore`; its own churn mutations never fail) raise.
    This lands mid-bind or in the condition writer — paths the ladder
    deliberately does not absorb — so it pins that an unhandled cycle
    exception flight-dumps, re-raises, and the next cycle carries on.
  * ``kind="sidecar"`` — installs a dead in-process sidecar client stub
    (every RPC raises) for ``count`` cycles, exercising the sidecar's
    own local-step fallback path.

Everything is deterministic: faults fire at fixed cycles with fixed
budgets, no randomness. The latency sleep is real wall time but the sim
clock is synthetic, so binding decisions (and the binding log) stay
byte-stable as long as ``delay_ms`` clears the armed deadline with
margin.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

FAULT_KINDS = ("dispatch", "device_loss", "latency", "oom_upload",
               "store_write", "sidecar")


class InjectedFault(RuntimeError):
    """The exception every injected fault raises — distinguishable from
    real bugs in sim reports."""


class DeviceLossFault(InjectedFault):
    """A dispatch fault attributable to specific mesh devices — carries
    ``failed_device_ids``, the attribute
    scheduler/degrade.attributable_device_ids reads to engage the
    partial-mesh rung."""

    def __init__(self, message: str, device_ids) -> None:
        super().__init__(message)
        self.failed_device_ids = frozenset(int(i) for i in device_ids)


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scheduled fault: at sim cycle ``cycle``, arm ``count`` units
    of ``kind`` failure. ``devices`` names the dead mesh device ids for
    ``device_loss``; ``delay_ms`` is the injected sync latency for
    ``latency``."""

    cycle: int
    kind: str              # see FAULT_KINDS
    count: int = 1
    message: str = "injected fault"
    devices: Tuple[int, ...] = ()
    delay_ms: float = 0.0

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.cycle < 0 or self.count < 1:
            raise ValueError("fault cycle must be >= 0 and count >= 1")
        if self.kind == "device_loss" and not self.devices:
            raise ValueError("device_loss faults must name their devices")
        if self.kind == "latency" and self.delay_ms <= 0:
            raise ValueError("latency faults need delay_ms > 0")


class FaultPlan:
    """Armed budgets per fault kind, advanced cycle by cycle. The
    simulator owns the lifecycle: ``begin_cycle`` arms the entries
    scheduled for that cycle, the hooks consume budget as they fire."""

    def __init__(self, faults: Sequence[Fault] = ()) -> None:
        self.faults: Tuple[Fault, ...] = tuple(faults)
        self._budget: Dict[str, int] = {k: 0 for k in FAULT_KINDS}
        self._message: Dict[str, str] = {}
        self._devices: Tuple[int, ...] = ()
        self._delay_ms: float = 0.0
        self.injected: List[dict] = []  # what actually fired, per kind
        self._cycle = -1

    def begin_cycle(self, cycle: int) -> None:
        self._cycle = cycle
        for f in self.faults:
            if f.cycle == cycle:
                self._budget[f.kind] += f.count
                self._message[f.kind] = f.message
                if f.kind == "device_loss":
                    self._devices = f.devices
                if f.kind == "latency":
                    self._delay_ms = f.delay_ms

    def budget(self, kind: str) -> int:
        return self._budget[kind]

    def _consume(self, kind: str, detail: str) -> str:
        self._budget[kind] -= 1
        self.injected.append(
            {"cycle": self._cycle, "kind": kind, "detail": detail})
        return (f"{self._message.get(kind, 'injected fault')} "
                f"({kind}: {detail})")

    def _fire(self, kind: str, detail: str) -> None:
        raise InjectedFault(self._consume(kind, detail))

    # ---- scheduler.fault_injector hook --------------------------------
    def dispatch_hook(self, stage: str) -> None:
        """Installed as ``Scheduler.fault_injector``; raises while the
        dispatch (or attributable device-loss) budget lasts."""
        if self._budget["device_loss"] > 0:
            raise DeviceLossFault(
                self._consume("device_loss",
                              f"{stage} devices={list(self._devices)}"),
                self._devices)
        if self._budget["dispatch"] > 0:
            self._fire("dispatch", stage)

    # ---- scheduler.sync_delay_injector hook ---------------------------
    def sync_delay_hook(self) -> None:
        """Installed as ``Scheduler.sync_delay_injector`` (and the
        rebalancer's): sleeps inside the monitored readback while the
        latency budget lasts — the slow-not-dead device."""
        if self._budget["latency"] > 0:
            import time

            self._consume("latency", f"sleep {self._delay_ms:.0f}ms")
            time.sleep(self._delay_ms / 1000.0)

    # ---- DeviceSnapshot.fault_injector hook ---------------------------
    def upload_hook(self, field: str) -> None:
        """Installed as ``Scheduler.upload_fault_injector``; raises a
        RESOURCE_EXHAUSTED-shaped allocation failure while the
        oom_upload budget lasts (snapshot_cache classifies it as a
        device fault)."""
        if self._budget["oom_upload"] > 0:
            raise InjectedFault(
                "RESOURCE_EXHAUSTED: out of memory allocating device "
                "buffer (" + self._consume("oom_upload", field) + ")")

    # ---- store-write hook ---------------------------------------------
    def store_write_hook(self, kind: str, key: str) -> None:
        if self._budget["store_write"] > 0:
            self._fire("store_write", f"{kind} {key}")

    # ---- sidecar ------------------------------------------------------
    def sidecar_armed(self) -> bool:
        """True while a sidecar fault cycle is active; the simulator
        swaps a dead client stub in/out of the scheduler. Consumes one
        budget unit per armed cycle."""
        if self._budget["sidecar"] > 0:
            self._budget["sidecar"] -= 1
            self.injected.append(
                {"cycle": self._cycle, "kind": "sidecar", "detail": "stub"})
            return True
        return False


class DeadSidecarClient:
    """A sidecar client whose every RPC raises a channel-level transport
    failure: what a timed-out / crashed gRPC peer looks like to
    schedule_batch_or_fallback, which must degrade to the local step
    (scheduler/sidecar.py catches ConnectionError/OSError)."""

    def schedule_batch(self, request):
        raise ConnectionError("sidecar timeout (injected)")

    def close(self) -> None:
        pass


class FaultyStore:
    """The scheduler's store view with write faults: forwards everything
    to the real store, but ``update``/``add``/``delete`` consult the
    plan first. Only the scheduler holds this wrapper — the simulator's
    own churn mutations go to the inner store directly.

    The view also RECORDS every watch registered through it so the
    crash-restart event can ``sever()`` them: the apiserver dropping a
    dead client's watch connections. A severed view's handlers stop
    receiving events; the fresh scheduler's own subscriptions replay
    list-then-watch from the surviving store."""

    def __init__(self, inner, plan: FaultPlan) -> None:
        # bypass __setattr__-free plain attributes; no locking needed,
        # the sim drives a single cycle thread
        self._inner = inner
        self._plan = plan
        self._subs: List[tuple] = []  # (kind, handler) watches registered

    def subscribe(self, kind: str, handler, replay: bool = True) -> None:
        self._subs.append((kind, handler))
        return self._inner.subscribe(kind, handler, replay=replay)

    def sever(self) -> None:
        """Crash teardown: unsubscribe every watch this view's owner
        registered. The dead scheduler's informers, plugins and
        snapshot cache stop consuming events from the surviving store."""
        for kind, handler in self._subs:
            self._inner.unsubscribe(kind, handler)
        self._subs = []

    def update(self, kind: str, obj):
        self._plan.store_write_hook(kind, getattr(
            getattr(obj, "meta", None), "key", "?"))
        return self._inner.update(kind, obj)

    def add(self, kind: str, obj):
        self._plan.store_write_hook(kind, getattr(
            getattr(obj, "meta", None), "key", "?"))
        return self._inner.add(kind, obj)

    def delete(self, kind: str, key: str):
        self._plan.store_write_hook(kind, key)
        return self._inner.delete(kind, key)

    def update_many(self, kind: str, objs):
        """Batched writes keep PER-OBJECT fault semantics: a store-write
        fault armed mid-batch leaves the earlier objects applied, exactly
        like N sequential updates — the reconcile-after-wreck path in the
        harness depends on partially-applied batches being visible."""
        return [self.update(kind, obj) for obj in objs]

    def __getattr__(self, name):
        return getattr(self._inner, name)
