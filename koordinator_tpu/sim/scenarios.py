"""Named simulation scenarios: the workload catalog.

Every scenario is a fully-seeded :class:`Scenario` — same name + same
seed means the same arrival stream, the same cluster events, the same
fault schedule, and (because the scheduler itself is deterministic on a
fixed backend) a byte-stable binding log. ``hack/lint.sh`` pins exactly
that for ``smoke``; ``bench.py --churn`` runs any scenario as a
back-to-back A/B pair per the BENCH_NOTES noise protocol.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from koordinator_tpu.sim.faults import Fault


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One reproducible churn workload. Times are sim-clock seconds
    (the simulator advances ``dt_seconds`` per cycle); every `_every`
    knob is in cycles, 0 = event disabled."""

    name: str
    description: str = ""
    seed: int = 11
    cycles: int = 200
    nodes: int = 12
    initial_pods: int = 0         # pre-bound plain pods (round-robin)
    #                               so load events bite from cycle 0
    dt_seconds: float = 5.0
    # arrivals / departures
    arrival_rate: float = 6.0     # Poisson mean pods per cycle
    be_fraction: float = 0.35     # arrivals that are best-effort (spot prey)
    departure_rate: float = 2.0   # Poisson mean running-pod deletions/cycle
    burst_every: int = 0          # burst queue: +burst_size pods at once
    burst_size: int = 40
    gang_every: int = 0           # gang storm cadence
    gang_size: int = 3
    gangs_per_storm: int = 1
    gang_lifetime: int = 0        # cycles until a whole gang finishes
    #                               (0 = gangs run forever)
    # cluster events
    drain_every: int = 0          # cordon a node, evict its pods, then
    drain_delete: bool = False    # ... delete it (True) or uncordon later
    drain_uncordon_after: int = 6
    drains_per_event: int = 1     # nodes cordoned per drain event
    #                               (>1 = the drain-storm shape)
    spot_reclaim_every: int = 0   # evict bound BE pods (re-queued as new)
    spot_reclaim_count: int = 3
    metric_flip_every: int = 0    # alternate NodeMetric fresh <-> expired
    quota_rebalance_every: int = 0  # shrink/grow quota max
    # rebalance-under-load (koordbalance): NodeMetric usage derived from
    # the pods actually bound to each node, so migrating load away
    # genuinely lowers the source node's reading
    metrics_follow_usage: bool = False
    usage_fraction: float = 0.6   # measured usage per unit of request
    usage_idle_cpu: int = 500     # per-node idle floor (milli-cores)
    hotspot_every: int = 0        # skew event: pods on chosen nodes run HOT
    hotspot_nodes: int = 2        # nodes skewed per event
    hotspot_multiplier: float = 2.5  # hot pods' usage multiplier
    hotspot_dissipate_slo_cycles: int = 0  # 0 = report-only
    # backpressure
    queue_cap: int = 512          # max pending pods admitted to the store
    overflow_cap: int = 2048      # waiting-room bound; beyond it -> shed
    # koordguard: scheduler crash-restart events (the harness tears the
    # Scheduler down at these cycles and rebuilds it against the
    # surviving store) and the recovery SLO; dispatch deadline in ms
    # (None pins it OFF for determinism — latency faults need it armed)
    restart_at: Tuple[int, ...] = ()
    restart_slo_seconds: float = 0.0   # 0 = report-only
    dispatch_deadline_ms: Optional[float] = None
    # koordcolo: the colocation control loop in the sim — a co-located
    # koord-manager (sharing the scheduler's snapshot) recomputes
    # batch/mid overcommit + runtime quotas every colo_every cycles
    colo_every: int = 0           # manager tick cadence (0 = no manager)
    colo: Optional[str] = None    # KOORD_TPU_COLO pin (None = env default)
    batch_fraction: float = 0.0   # BE arrivals requesting batch-cpu/mem
    #                               (the overcommit consumers)
    overcommit_surge_every: int = 0   # prod-usage surge event cadence
    overcommit_surge_cycles: int = 8  # cycles until the surge recedes
    overcommit_surge_nodes: int = 3   # nodes whose prod pods run hot
    overcommit_surge_multiplier: float = 3.0
    colo_staleness_slo_cycles: int = 0  # metric write -> observing
    #                                     dispatch, p99 target (0 = off)
    # SLOs
    ttb_slo_seconds: float = 120.0  # time-to-bind p99 target
    # scheduler configuration under test
    waves: object = 1             # Scheduler(waves=...): int or "auto"
    pack_overlap: Optional[bool] = None  # KOORD_TPU_PACK_OVERLAP pin
    #                               (None = env default; the bench
    #                               --churn A/B pair pins on/off)
    explain: Optional[str] = None  # None keeps explain off ("off" pin)
    mesh: Optional[int] = None    # KOORD_TPU_MESH-style device count
    pipeline: bool = False        # drive through CyclePipeline
    descheduler_every: int = 0    # run the real descheduler every N cycles
    rebalance: Optional[str] = None  # KOORD_TPU_REBALANCE pin for the
    #                                  descheduler (None = env default)
    promote_after: int = 8        # ladder clean-cycle re-promotion probe
    # fault schedule
    faults: Tuple[Fault, ...] = ()

    def resolved(self, cycles: Optional[int] = None,
                 seed: Optional[int] = None,
                 waves=None) -> "Scenario":
        """CLI overrides without losing the catalog definition."""
        changes = {}
        if cycles is not None:
            changes["cycles"] = cycles
        if seed is not None:
            changes["seed"] = seed
        if waves is not None:
            changes["waves"] = waves
        return dataclasses.replace(self, **changes) if changes else self


SCENARIOS: Dict[str, Scenario] = {}


def _register(sc: Scenario) -> Scenario:
    if sc.name in SCENARIOS:
        raise ValueError(f"duplicate scenario {sc.name!r}")
    SCENARIOS[sc.name] = sc
    return sc


_register(Scenario(
    name="smoke",
    description=(
        "tier-1 / lint gate: ~50 cycles of light churn with one gang "
        "storm cadence, a node drain, metric flips, and a dispatch-fault "
        "burst that demotes the ladder to the host fallback and back — "
        "fixed seed, byte-stable binding log, zero invariant breaches"),
    seed=11, cycles=50, nodes=10,
    arrival_rate=5.0, departure_rate=1.5,
    gang_every=9, gang_size=3,
    drain_every=17, drain_uncordon_after=5,
    metric_flip_every=13,
    queue_cap=128,
    ttb_slo_seconds=180.0,
    promote_after=6,
    faults=(Fault(cycle=20, kind="dispatch", count=3,
                  message="smoke dispatch fault"),),
))

_register(Scenario(
    name="soak",
    description=(
        "the 1000-cycle acceptance soak (slow): sustained Poisson "
        "traffic with gang storms, bursts, drains, spot reclamation, "
        "metric flips, quota rebalances, and dispatch/store-write "
        "faults mid-soak; emits the CHURN SLO report"),
    seed=7, cycles=1000, nodes=16, initial_pods=120,
    # near-capacity but sustainable: ~16x16 cores hold ~270 of these
    # pods; steady arrivals (+ gang storms and bursts on top) roughly
    # match departures + reclamation so the queue breathes instead of
    # diverging — the bursts are the stress, not a monotone backlog
    arrival_rate=3.0, departure_rate=4.0, be_fraction=0.4,
    burst_every=97, burst_size=60,
    gang_every=23, gang_size=4, gangs_per_storm=2, gang_lifetime=40,
    drain_every=61, drain_uncordon_after=8,
    spot_reclaim_every=43, spot_reclaim_count=4,
    metric_flip_every=29,
    quota_rebalance_every=53,
    queue_cap=384, overflow_cap=1536,
    ttb_slo_seconds=300.0,
    waves="auto",
    # the citable occupancy/throughput pair runs through the production
    # CyclePipeline: deferred condition writes drain in the next kernel
    # window and the fused dispatches replay overlapped — decisions (and
    # the binding log) are parity-gated identical either way
    pipeline=True,
    # rebalance-under-load (koordbalance): usage-derived metrics +
    # periodic hotspots give the descheduler REAL work every soak —
    # tests assert nonzero migration activity (binding-log change vs
    # pre-koordbalance soaks declared in BENCH_NOTES_r11)
    metrics_follow_usage=True, usage_fraction=0.8,
    hotspot_every=60, hotspot_nodes=2, hotspot_multiplier=4.0,
    descheduler_every=25,
    promote_after=16,
    faults=(
        Fault(cycle=300, kind="dispatch", count=2,
              message="soak transient dispatch fault"),
        Fault(cycle=450, kind="store_write", count=1,
              message="soak store-write fault"),
        Fault(cycle=600, kind="dispatch", count=8,
              message="soak dispatch fault storm"),
        Fault(cycle=750, kind="sidecar", count=3,
              message="soak sidecar outage"),
    ),
))

_register(Scenario(
    name="gang-storm",
    description=(
        "gang-dominated arrivals: storms of multi-member PodGroups every "
        "few cycles plus burst queues — the all-or-nothing admission "
        "path under sustained pressure"),
    seed=3, cycles=300, nodes=14,
    arrival_rate=3.0, departure_rate=2.0,
    burst_every=31, burst_size=30,
    gang_every=3, gang_size=5, gangs_per_storm=2, gang_lifetime=12,
    queue_cap=256,
    ttb_slo_seconds=240.0,
    waves="auto",
))

_register(Scenario(
    name="spot-churn",
    description=(
        "spot-heavy cluster: most arrivals are best-effort and "
        "reclamation keeps evicting bound BE pods (re-queued as fresh "
        "arrivals) while drains rotate nodes out and back"),
    seed=5, cycles=300, nodes=12,
    arrival_rate=7.0, be_fraction=0.7, departure_rate=1.0,
    spot_reclaim_every=5, spot_reclaim_count=4,
    drain_every=41, drain_uncordon_after=6,
    metric_flip_every=19,
    queue_cap=256,
    ttb_slo_seconds=240.0,
))

_register(Scenario(
    name="drain-storm",
    description=(
        "mass cordon + migration under arrival pressure: every drain "
        "event cordons several nodes at once, their load concentrates "
        "on the survivors (usage-derived metrics), and the descheduler "
        "must keep rebalancing through its reservation closed loop "
        "while arrivals keep coming"),
    seed=17, cycles=200, nodes=16, initial_pods=96,
    arrival_rate=5.0, departure_rate=3.0, be_fraction=0.3,
    drain_every=23, drains_per_event=3, drain_uncordon_after=7,
    # near-1.0 usage-per-request: a survivor node that fills up with
    # drained load genuinely reads above the 70% high threshold, so the
    # storm's concentration is what the descheduler must dissipate
    metrics_follow_usage=True, usage_fraction=0.85,
    descheduler_every=5,
    queue_cap=384,
    ttb_slo_seconds=240.0,
    waves="auto",
))

_register(Scenario(
    name="hotspot",
    description=(
        "skewed usage flips that must dissipate: every event marks the "
        "pods on a few nodes HOT (usage multiplier), LowNodeLoad "
        "classifies them high, and the migration closed loop "
        "(reservation -> next dispatch -> evict -> respread) must bring "
        "every flagged node back under the high thresholds within the "
        "dissipation SLO"),
    seed=23, cycles=160, nodes=16, initial_pods=128,
    arrival_rate=3.5, departure_rate=3.0, be_fraction=0.3,
    metrics_follow_usage=True, usage_fraction=0.5,
    hotspot_every=40, hotspot_nodes=2, hotspot_multiplier=3.5,
    hotspot_dissipate_slo_cycles=30,
    descheduler_every=3,
    queue_cap=256,
    # time-to-dissipate is this scenario's tight deliverable; the ttb
    # target stays loose enough that feature-stuck stragglers (hostPort
    # collisions under load) do not mask a dissipation regression
    ttb_slo_seconds=360.0,
))

_register(Scenario(
    name="overcommit-shift",
    description=(
        "koordcolo closed loop under load: a co-located koord-manager "
        "recomputes batch/mid overcommit on device every cycle while "
        "batch-class BE pods consume it; mid-soak prod-usage surges "
        "(usage-derived NodeMetrics) shrink batch allocatable and then "
        "recede, and the invariants pin that batch binds never exceed "
        "the CURRENT batch allocatable at their dispatch plus a bounded "
        "metric-write-to-observing-dispatch staleness SLO — fixed seed, "
        "byte-stable binding log, the bench --colo A/B pair (device vs "
        "host oracle) must be log-identical"),
    seed=31, cycles=160, nodes=12, initial_pods=72,
    arrival_rate=4.0, departure_rate=3.0, be_fraction=0.55,
    metrics_follow_usage=True, usage_fraction=0.7,
    colo_every=1, batch_fraction=0.6,
    overcommit_surge_every=40, overcommit_surge_cycles=12,
    overcommit_surge_nodes=4, overcommit_surge_multiplier=3.0,
    colo_staleness_slo_cycles=2,
    queue_cap=256,
    ttb_slo_seconds=400.0,
    promote_after=8,
))

_register(Scenario(
    name="fault-ladder",
    description=(
        "robustness proof (koordguard): mesh + fused waves + explain "
        "all on with a dispatch deadline armed; an attributable device "
        "loss lands the ladder on partial-mesh (surviving submesh), a "
        "slow-not-dead device (latency > deadline) demotes via the "
        "watchdog instead of wedging, a dispatch-fault storm walks the "
        "rest of the ladder to the host fallback, a crash-restart "
        "tears the scheduler down against the surviving store, and "
        "clean cycles re-promote after each — the deterministic seeded "
        "scenario the acceptance test pins"),
    seed=13, cycles=72, nodes=8,
    arrival_rate=4.0, departure_rate=1.0,
    queue_cap=128,
    ttb_slo_seconds=300.0,
    waves=4, explain="counts", mesh=2,
    dispatch_deadline_ms=150.0,
    restart_at=(58,),
    restart_slo_seconds=60.0,
    promote_after=5,
    faults=(
        # one mesh device named dead -> partial-mesh (1-device submesh)
        Fault(cycle=8, kind="device_loss", count=2, devices=(1,),
              message="ICI link down"),
        # slow-not-dead device: the monitored sync overruns the 150ms
        # deadline twice (retry, then demote) — 600ms clears it with
        # margin on any CI box
        Fault(cycle=22, kind="latency", count=2, delay_ms=600.0,
              message="slow-not-dead device"),
        # anonymous fault storm: walks the remaining rungs to host
        Fault(cycle=34, kind="dispatch", count=8,
              message="ladder walk fault storm"),
    ),
))

_register(Scenario(
    name="crash-restart",
    description=(
        "koordguard recovery gate: light churn with gangs and quota "
        "pods, then the scheduler crash-restarts mid-soak — device "
        "state, step caches and the pack memo all drop, the fresh "
        "scheduler replays list-then-watch from the surviving store, "
        "re-derives assumed/quota/gang state from store-visible binds, "
        "and must reach its first bind inside the restart SLO with "
        "zero double-booking breaches across the boundary — fixed "
        "seed, byte-stable binding log (hack/lint.sh runs it twice)"),
    seed=19, cycles=36, nodes=10, initial_pods=30,
    arrival_rate=5.0, departure_rate=2.0, be_fraction=0.3,
    gang_every=7, gang_size=3, gang_lifetime=18,
    quota_rebalance_every=11,
    queue_cap=192,
    ttb_slo_seconds=240.0,
    restart_at=(16,),
    restart_slo_seconds=30.0,
    promote_after=6,
))
