"""Store-level cluster invariants: what must hold after EVERY cycle.

The single source both the churn soak test (tests/test_churn_soak.py)
and the simulator's per-cycle net assert. Mirrors what the reference's
admission chain guarantees: no node overcommitted past its (trimmed)
allocatable, no hostPort double-bind, CSI volume limits respected, gang
all-or-nothing. Returns breach DESCRIPTIONS instead of asserting so the
simulator can count, flight-dump, and keep churning — the test layer
asserts the list is empty.
"""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_POD,
    KIND_POD_GROUP,
    KIND_POD_MIGRATION_JOB,
    KIND_RESERVATION,
    ObjectStore,
)
from koordinator_tpu.ops.estimator import estimate_node_allocatable


def check_invariants(store: ObjectStore,
                     now: Optional[float] = None,
                     batch_shrink_grace: bool = False) -> List[str]:
    """Check the invariant set against the store; [] == clean.
    ``now`` governs reservation expiry (sim clock); defaults to wall.

    ``batch_shrink_grace`` (koordcolo scenarios): the batch/mid axes are
    OVERCOMMIT — the colo loop may legitimately shrink a node's batch
    allocatable below what already-bound batch pods consume (the
    reference reclaims via BE eviction, asynchronously). With the grace
    on, the capacity check skips those axes and the bind-time discipline
    is pinned separately by :func:`check_batch_bind_discipline` (new
    binds must respect the CURRENT overcommit; existing binds may ride
    out a shrink)."""
    now = time.time() if now is None else now
    breaches: List[str] = []
    grace_axes: List[int] = []
    if batch_shrink_grace:
        from koordinator_tpu.api.resources import (
            RESOURCE_INDEX,
            ResourceName,
        )

        grace_axes = [RESOURCE_INDEX[rn] for rn in (
            ResourceName.BATCH_CPU, ResourceName.BATCH_MEMORY,
            ResourceName.MID_CPU, ResourceName.MID_MEMORY)]
    nodes = {n.meta.name: n for n in store.list(KIND_NODE)}
    pods = [p for p in store.list(KIND_POD)
            if p.is_assigned and not p.is_terminated]
    by_node = {}
    for p in pods:
        by_node.setdefault(p.spec.node_name, []).append(p)
    for name, plist in by_node.items():
        node = nodes.get(name)
        if node is None:
            breaches.append(f"pod bound to unknown node {name}")
            continue
        # 1. capacity: sum of requests <= trimmed allocatable per axis
        alloc = estimate_node_allocatable(node)
        total = np.zeros_like(alloc)
        for p in plist:
            total = total + p.spec.requests.to_vector()
        over = total > alloc + 1e-3
        if grace_axes:
            over[grace_axes] = False
        if over.any():
            breaches.append(
                f"node {name} overcommitted: {total[over]} > {alloc[over]}")
        # 1b. pod-count axis: requests vectors carry no pods term (the
        # kernel adds the +1-per-pod via with_pod_count), so the axis
        # check above cannot see pod-count overcommit — count directly.
        # Matters across a crash-restart boundary, where the fresh
        # scheduler re-derives every per-node sum from the store.
        from koordinator_tpu.api.resources import RESOURCE_INDEX, ResourceName

        pods_cap = float(alloc[RESOURCE_INDEX[ResourceName.PODS]])
        if pods_cap > 0 and len(plist) > pods_cap:
            breaches.append(
                f"node {name} exceeds its pod capacity: "
                f"{len(plist)} > {pods_cap:g}")
        # 2. hostPorts: no (protocol, port) bound twice
        seen = set()
        for p in plist:
            for slot in p.spec.host_ports:
                if slot in seen:
                    breaches.append(
                        f"hostPort {slot} double-bound on {name}")
                seen.add(slot)
        # 3. volume limit
        if node.attachable_volume_limit > 0:
            claims = set()
            for p in plist:
                claims.update(
                    f"{p.meta.namespace}/{c}" for c in p.spec.pvc_names)
            if len(claims) > node.attachable_volume_limit:
                breaches.append(
                    f"node {name} exceeds volume limit: "
                    f"{len(claims)} > {node.attachable_volume_limit}")
    # 4. gang all-or-nothing: a gang with any bound member has >= min bound
    gangs = {g.meta.key: g for g in store.list(KIND_POD_GROUP)}
    bound_per_gang = {}
    for p in pods:
        g = p.gang_key
        if g:
            bound_per_gang[g] = bound_per_gang.get(g, 0) + 1
    for g, count in bound_per_gang.items():
        pg = gangs.get(g)
        if pg is not None and count < pg.min_member:
            breaches.append(
                f"gang {g} partially bound: {count} < {pg.min_member}")
    # 5. rebalance discipline: an active migration job must target a
    # MOVABLE pod — never a DaemonSet replica or a pod carrying the
    # PDB-like opt-out guard (a missing/terminated pod is a lifecycle
    # race the controller resolves, not a breach)
    from koordinator_tpu.balance.pack import has_pdb_like_guard

    for job in store.list(KIND_POD_MIGRATION_JOB):
        if job.phase not in ("Pending", "Running"):
            continue
        pod = store.get(KIND_POD, f"{job.pod_namespace}/{job.pod_name}")
        if pod is None or pod.is_terminated:
            continue
        if has_pdb_like_guard(pod):
            breaches.append(
                f"migration job {job.meta.key} targets PDB-guarded pod "
                f"{pod.meta.key}")
        if pod.meta.owner_kind == "DaemonSet":
            breaches.append(
                f"migration job {job.meta.key} targets DaemonSet pod "
                f"{pod.meta.key}")
    # 6. reserved capacity is not double-booked: per node, assigned pod
    # requests PLUS the unconsumed remainder of Available unexpired
    # reservations must fit the trimmed allocatable (the scheduler
    # counts held reservation capacity via ReservationRestoreTransformer
    # — this pins that the rebalance closed loop cannot overcommit a
    # node through its replacement reservations)
    reserved = {}
    for res in store.list(KIND_RESERVATION):
        if not res.is_available or res.is_expired(now):
            continue
        free = np.maximum(
            res.allocatable.to_vector() - res.allocated.to_vector(), 0.0)
        reserved[res.node_name] = reserved.get(res.node_name, 0.0) + free
    for name, held in reserved.items():
        node = nodes.get(name)
        if node is None:
            breaches.append(f"reservation held on unknown node {name}")
            continue
        alloc = estimate_node_allocatable(node)
        total = np.asarray(held, np.float64).copy()
        for p in by_node.get(name, []):
            total = total + p.spec.requests.to_vector()
        over = total > alloc + 1e-3
        if over.any():
            breaches.append(
                f"node {name} double-booked by reservations: "
                f"{total[over]} > {alloc[over]}")
    return breaches


def check_batch_bind_discipline(store: ObjectStore,
                                bound_keys) -> List[str]:
    """koordcolo bind-time invariant: a batch-class pod bound THIS cycle
    must fit the node's CURRENT batch/mid allocatable together with
    every batch pod already there — the dispatch that placed it consumed
    the overcommit the colo pass published this very cycle, so a bind
    into an already-over node means the scheduler read stale overcommit
    (the closed loop failed). Existing binds riding out a later shrink
    are legitimate (see check_invariants batch_shrink_grace)."""
    from koordinator_tpu.api.resources import RESOURCE_INDEX, ResourceName

    axes = [RESOURCE_INDEX[rn] for rn in (
        ResourceName.BATCH_CPU, ResourceName.BATCH_MEMORY,
        ResourceName.MID_CPU, ResourceName.MID_MEMORY)]
    breaches: List[str] = []
    touched = set()
    for key in bound_keys:
        pod = store.get(KIND_POD, key)
        if pod is None or not pod.is_assigned or pod.is_terminated:
            continue
        vec = pod.spec.requests.to_vector()
        if not any(vec[a] > 0 for a in axes):
            continue
        touched.add(pod.spec.node_name)
    if not touched:
        return breaches
    # ONE store walk accumulating per-node totals (the check above
    # already walks pods once; k touched nodes must not mean k walks)
    totals: dict = {}
    for p in store.list(KIND_POD):
        if (p.is_assigned and not p.is_terminated
                and p.spec.node_name in touched):
            node_total = totals.get(p.spec.node_name)
            vec = p.spec.requests.to_vector()
            totals[p.spec.node_name] = (
                vec if node_total is None else node_total + vec)
    for name in touched:
        node = store.get(KIND_NODE, f"/{name}")
        if node is None:
            continue
        alloc = estimate_node_allocatable(node)
        total = totals.get(name)
        if total is None:
            continue
        for a in axes:
            if total[a] > alloc[a] + 1e-3:
                breaches.append(
                    f"batch bind onto {name} exceeds current overcommit "
                    f"axis {a}: {total[a]} > {alloc[a]}")
    return breaches
