"""koordsim CLI: run named churn scenarios against the real scheduler.

    python -m koordinator_tpu.sim --list
    python -m koordinator_tpu.sim smoke
    python -m koordinator_tpu.sim smoke --check-determinism
    python -m koordinator_tpu.sim soak --out CHURN_r01.json

Exit codes: 0 clean; 1 invariant breaches above --max-breaches;
2 determinism check failed; 3 SLO missed under --enforce-slo;
4 usage error. The SLO verdict is always REPORTED; it only fails the
run when asked, because wall-clock-free sim time keeps the binding log
deterministic but CPU-vs-TPU backends still bind different amounts per
cycle-budget (BENCH_NOTES noise protocol: cross-run numbers are not
comparable, pinned gates must be structural).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _force_cpu_devices_for_mesh() -> None:
    """Mesh scenarios on the CPU backend need the virtual device split
    forced before the first jax import (same shape tests/conftest.py and
    bench.py --mesh pin); real accelerators keep their topology."""
    if (os.environ.get("JAX_PLATFORMS", "") == "cpu"
            and "jax" not in sys.modules):
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m koordinator_tpu.sim",
        description="fault-injecting churn simulator for the koordinator "
                    "scheduler")
    ap.add_argument("scenario", nargs="?", help="scenario name (see --list)")
    ap.add_argument("--list", action="store_true",
                    help="list the scenario catalog and exit")
    ap.add_argument("--cycles", type=int, default=None,
                    help="override the scenario's cycle count")
    ap.add_argument("--seed", type=int, default=None,
                    help="override the scenario's seed")
    ap.add_argument("--waves", default=None,
                    help="override the scenario's fused-wave depth "
                    "(int or 'auto') — the coldstart gate pins 4 so the "
                    "compile ladder has real chain programs to warm")
    ap.add_argument("--out", default=None,
                    help="write the SLO report JSON here (default: stdout "
                    "only)")
    ap.add_argument("--flight-dir", default=None,
                    help="land flight-recorder dumps as files here")
    ap.add_argument("--check-determinism", action="store_true",
                    help="run the scenario twice and require byte-identical "
                    "binding logs")
    ap.add_argument("--max-breaches", type=int, default=0,
                    help="fail (exit 1) when invariant breaches exceed this "
                    "(default 0)")
    ap.add_argument("--enforce-slo", action="store_true",
                    help="fail (exit 3) when the time-to-bind p99 misses "
                    "the scenario SLO")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the progress line, print only the JSON")
    args = ap.parse_args(argv)

    from koordinator_tpu.sim.scenarios import SCENARIOS

    if args.list or not args.scenario:
        for name, sc in SCENARIOS.items():
            print(f"{name:14s} {sc.cycles:5d} cycles, {sc.nodes} nodes — "
                  f"{sc.description}")
        return 0 if args.list else 4
    sc = SCENARIOS.get(args.scenario)
    if sc is None:
        print(f"unknown scenario {args.scenario!r}; --list shows the "
              "catalog", file=sys.stderr)
        return 4
    waves = args.waves
    if waves is not None and waves != "auto":
        try:
            waves = int(waves)
        except ValueError:
            print(f"--waves must be an int or 'auto', got {waves!r}",
                  file=sys.stderr)
            return 4
    sc = sc.resolved(cycles=args.cycles, seed=args.seed, waves=waves)
    if sc.mesh is not None:
        _force_cpu_devices_for_mesh()

    from koordinator_tpu.sim.harness import run_scenario

    def progress(msg: str) -> None:
        if not args.quiet:
            print(msg, file=sys.stderr)

    progress(f"[koordsim] scenario {sc.name}: {sc.cycles} cycles, "
             f"{sc.nodes} nodes, seed {sc.seed}")
    report = run_scenario(sc, flight_dir=args.flight_dir)
    payload = report.to_dict()
    progress(f"[koordsim] bound {report.pods_bound}/{report.pods_created} "
             f"pods, ttb p50/p99 {report.percentile(50):.1f}/"
             f"{report.percentile(99):.1f}s, "
             f"{len(report.invariant_breaches)} breaches, "
             f"{len(report.cycle_exceptions)} cycle exceptions, "
             f"final ladder level {report.final_level}, "
             f"{report.wall_seconds:.1f}s wall")

    if args.check_determinism:
        progress("[koordsim] determinism check: re-running with the same "
                 "seed")
        twin = run_scenario(sc, flight_dir=None)
        if twin.binding_log != report.binding_log:
            first = next(
                (i for i, (a, b) in enumerate(
                    zip(report.binding_log, twin.binding_log)) if a != b),
                min(len(report.binding_log), len(twin.binding_log)))
            print(f"binding logs DIVERGED at entry {first}: "
                  f"{len(report.binding_log)} vs {len(twin.binding_log)} "
                  "bindings", file=sys.stderr)
            return 2
        payload["determinism"] = {
            "checked": True,
            "binding_log_stable": True,
        }
        progress(f"[koordsim] binding log byte-stable "
                 f"({len(report.binding_log)} bindings, sha256 "
                 f"{report.binding_log_sha256[:16]}…)")

    body = json.dumps(payload, indent=2, sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(body + "\n")
        progress(f"[koordsim] report written to {args.out}")
    print(body)

    if len(report.invariant_breaches) > args.max_breaches:
        print(f"invariant breaches: {len(report.invariant_breaches)} > "
              f"--max-breaches {args.max_breaches}", file=sys.stderr)
        return 1
    if args.enforce_slo and report.ttb_seconds and (
            report.percentile(99) > sc.ttb_slo_seconds):
        print(f"SLO missed: ttb p99 {report.percentile(99):.1f}s > "
              f"{sc.ttb_slo_seconds}s", file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
