"""Admission webhooks (analog of reference `pkg/webhook/`, SURVEY.md 2.4)."""

from koordinator_tpu.webhook.server import AdmissionServer, AdmissionError  # noqa: F401
