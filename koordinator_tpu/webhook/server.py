"""Admission webhook framework + handlers.

Analog of reference `pkg/webhook/` (server.go + per-GVK registration):
  * pod mutating: ClusterColocationProfile application — inject QoS label,
    priority class/value, scheduler name, labels/annotations, and translate
    requests to batch-*/mid-* extended resources
    (pod/mutating/cluster_colocation_profile.go:53-259 + :157-259); the
    original requests are recorded in the extended-resource-spec annotation for
    koordlet/runtime-proxy (mutating/extended_resource_spec.go).
  * pod validating: QoS/priority combination rules + resource consistency
    (pod/validating/).
  * elasticquota mutating/validating: tree guard rails (webhook/elasticquota/):
    parent existence, min <= max, parent-child min consistency, forbidden
    modifications.
  * node validating: resource amplification annotations (webhook/node/).
  * configmap validating: sloconfig schema (webhook/cm/ via utils/sloconfig).

Wired into the store as admission interceptors: `admit(kind, obj)` runs
mutators then validators; store helpers in tests call it before add/update
(the reference's apiserver does the same).
"""

from __future__ import annotations

import json
import math
from typing import Callable, Dict, List, Optional

from koordinator_tpu.api.objects import (
    ANNOTATION_EXTENDED_RESOURCE_SPEC,
    ANNOTATION_RESERVE_POD,
    ClusterColocationProfile,
    ConfigMap,
    ElasticQuota,
    LABEL_POD_PRIORITY,
    LABEL_POD_QOS,
    Node,
    Pod,
)
from koordinator_tpu.api.priority import (
    DEFAULT_PRIORITY_BY_CLASS,
    PriorityClass,
    priority_class_by_name,
)
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.api.resources import (
    ResourceList,
    ResourceName,
    translate_resource_by_priority_class,
)
from koordinator_tpu.client.store import (
    KIND_COLOCATION_PROFILE,
    KIND_ELASTIC_QUOTA,
    KIND_QUOTA_PROFILE,
    ObjectStore,
)
from koordinator_tpu.utils.features import MANAGER_GATES
from koordinator_tpu.utils.sloconfig import (
    COLOCATION_CONFIG_KEY,
    CONFIG_MAP_NAME,
    parse_colocation_config,
)


class AdmissionError(Exception):
    """Admission denied (apiserver 4xx analog)."""


# injectable randomness for profile probability (the reference stubs
# rand.Intn in tests the same way: cluster_colocation_profile.go:47)
_rand_intn: Callable[[int], int] = None


def _default_rand_intn(n: int) -> int:
    import random

    return random.randrange(n)


# annotations only the scheduler may set; user pods are rejected
# (pod/validating/verify_annotations.go:60-76)
FORBIDDEN_POD_ANNOTATIONS = (ANNOTATION_RESERVE_POD,)


class AdmissionServer:
    def __init__(self, store: ObjectStore):
        self.store = store

    # ------------------------------------------------------------------
    def admit_pod_create(self, pod: Pod) -> Pod:
        if MANAGER_GATES.enabled("PodMutatingWebhook"):
            self.mutate_pod(pod)
        if MANAGER_GATES.enabled("PodValidatingWebhook"):
            self.validate_pod(pod)
        return pod

    # -- pod mutating ---------------------------------------------------
    def _namespace_matches(self, namespace: str,
                           selector: Dict[str, str]) -> bool:
        """namespaceSelector matches the Namespace object's labels
        (cluster_colocation_profile.go:113-130); a missing Namespace object
        cannot match a non-empty selector."""
        from koordinator_tpu.client.store import KIND_NAMESPACE

        ns = self.store.get(KIND_NAMESPACE, f"/{namespace}")
        if ns is None:
            return False
        return all(ns.meta.labels.get(k) == v for k, v in selector.items())

    def _probability_skips(self, profile: ClusterColocationProfile) -> bool:
        """Percent-based sampling (cluster_colocation_profile.go:147-154):
        skip when percent == 0, apply when 100, else draw. The strict `>`
        mirrors the reference exactly — including its bias of applying on
        draws 0..percent, i.e. (percent+1)% of pods for 0 < percent < 100."""
        percent = profile.probability
        if percent is None:
            return False
        rand_intn = _rand_intn or _default_rand_intn
        return percent == 0 or (percent != 100 and rand_intn(100) > percent)

    def _matching_profile(self, pod: Pod) -> Optional[ClusterColocationProfile]:
        for profile in sorted(
            self.store.list(KIND_COLOCATION_PROFILE), key=lambda p: p.meta.name
        ):
            if profile.namespace_selector and not self._namespace_matches(
                pod.meta.namespace, profile.namespace_selector
            ):
                continue
            if profile.selector and not all(
                pod.meta.labels.get(k) == v for k, v in profile.selector.items()
            ):
                continue
            if self._probability_skips(profile):
                continue
            return profile
        return None

    def mutate_pod_quota_tree_affinity(self, pod: Pod) -> None:
        """multi_quota_tree_affinity.go:37-110: a pod whose quota belongs to a
        tree gets the tree profile's node selector injected so it can only
        land on that tree's nodes."""
        if not MANAGER_GATES.enabled("MultiQuotaTree"):
            return
        quota_name = pod.quota_name or pod.meta.namespace
        quota = None
        for q in self.store.list(KIND_ELASTIC_QUOTA):
            if q.meta.name == quota_name:
                quota = q
                break
        if quota is None or not quota.tree_id:
            return
        from koordinator_tpu.api.objects import LABEL_QUOTA_TREE_ID

        for profile in sorted(self.store.list(KIND_QUOTA_PROFILE),
                              key=lambda p: p.meta.name):
            if profile.quota_labels.get(LABEL_QUOTA_TREE_ID) != quota.tree_id:
                continue
            if profile.node_selector:  # first profile WITH a selector wins
                for k, v in profile.node_selector.items():
                    pod.spec.node_selector.setdefault(k, v)
                return

    def mutate_pod(self, pod: Pod) -> None:
        """cluster_colocation_profile.go:53-259. (Tree affinity runs AFTER the
        profile so a profile-injected quota-name label is honored.)"""
        profile = self._matching_profile(pod)
        if profile is not None:
            pod.meta.labels.update(profile.labels)
            pod.meta.annotations.update(profile.annotations)
            if profile.qos_class is not None:
                pod.meta.labels[LABEL_POD_QOS] = profile.qos_class.label
            if profile.scheduler_name:
                pod.spec.scheduler_name = profile.scheduler_name
            if profile.priority_class_name:
                pod.spec.priority_class_name = profile.priority_class_name
                cls = priority_class_by_name(profile.priority_class_name)
                if cls is not PriorityClass.NONE and pod.spec.priority is None:
                    pod.spec.priority = DEFAULT_PRIORITY_BY_CLASS[cls]
            if profile.koordinator_priority is not None:
                pod.meta.labels[LABEL_POD_PRIORITY] = str(profile.koordinator_priority)
        self.mutate_pod_quota_tree_affinity(pod)
        self.mutate_extended_resources(pod)

    def mutate_extended_resources(self, pod: Pod) -> None:
        """requests cpu/memory -> batch-*/mid-* for BATCH/MID pods
        (:157-259), recording the original spec in the annotation."""
        if MANAGER_GATES.enabled("ColocationProfileSkipMutatingResources"):
            return
        cls = pod.priority_class
        if cls not in (PriorityClass.BATCH, PriorityClass.MID):
            return
        original: Dict[str, Dict[str, int]] = {}
        for source in (pod.spec.requests, pod.spec.limits):
            moved = {}
            for name in (ResourceName.CPU, ResourceName.MEMORY):
                val = source[name]
                if not val:
                    continue
                target = translate_resource_by_priority_class(cls, name)
                moved[name] = (target, val)
            for name, (target, val) in moved.items():
                del source.quantities[name]
                source.quantities[target] = val
            if moved and source is pod.spec.requests:
                original["requests"] = {t: v for (t, v) in moved.values()}
        if original:
            pod.meta.annotations[ANNOTATION_EXTENDED_RESOURCE_SPEC] = json.dumps(
                {"containers": {"main": original}}
            )

    # -- pod validating -------------------------------------------------
    def validate_pod(self, pod: Pod) -> None:
        """pod/validating: QoS x priority-class consistency rules +
        forbidden scheduler-internal annotations."""
        for ann in FORBIDDEN_POD_ANNOTATIONS:
            if ann in pod.meta.annotations:
                raise AdmissionError(f"annotation {ann!r} cannot be set")
        qos = pod.qos_class
        cls = pod.priority_class
        if qos is QoSClass.BE and cls == PriorityClass.PROD:
            raise AdmissionError("BE pods cannot use koord-prod priority")
        if qos in (QoSClass.LSE, QoSClass.LSR):
            if cls in (PriorityClass.BATCH, PriorityClass.FREE):
                raise AdmissionError(
                    f"{qos.label} pods cannot use {cls.label} priority"
                )
            cpu = pod.spec.requests[ResourceName.CPU]
            if cpu % 1000 != 0:
                raise AdmissionError(
                    f"{qos.label} pods must request whole cpus, got {cpu}m"
                )
        be_resources = pod.spec.requests[ResourceName.BATCH_CPU] or pod.spec.requests[
            ResourceName.BATCH_MEMORY
        ]
        if be_resources and cls not in (PriorityClass.BATCH, PriorityClass.FREE, PriorityClass.NONE):
            raise AdmissionError("batch resources require koord-batch/free priority")
        # resource verify (pod/validating resource checks): limits bound requests
        for name, req in pod.spec.requests.quantities.items():
            limit = pod.spec.limits.get(name, 0)
            if limit and req > limit:
                raise AdmissionError(
                    f"request[{name}]={req} exceeds limit={limit}")

    # -- elasticquota ---------------------------------------------------
    def _quota_by_name(self, name: str) -> Optional[ElasticQuota]:
        for q in self.store.list(KIND_ELASTIC_QUOTA):
            if q.meta.name == name:
                return q
        return None

    def _quota_children(self, name: str) -> List[ElasticQuota]:
        return [q for q in self.store.list(KIND_ELASTIC_QUOTA)
                if q.parent == name and q.meta.name != name]

    def validate_elastic_quota(self, quota: ElasticQuota,
                               old: Optional[ElasticQuota] = None) -> None:
        """webhook/elasticquota guard rails (quota_topology_check.go)."""
        for name, mn in quota.min.quantities.items():
            mx = quota.max.get(name, 0)
            if mx and mn > mx:
                raise AdmissionError(f"min[{name}]={mn} exceeds max={mx}")
        parent_name = quota.parent
        if parent_name:
            parent = self._quota_by_name(parent_name)
            if parent is None:
                raise AdmissionError(f"parent quota {parent_name!r} does not exist")
            if not parent.is_parent:
                raise AdmissionError(f"quota {parent_name!r} is not a parent group")
            # checkSubAndParentGroupMaxQuotaKeySame (:182-213): a child may
            # only cap resources its parent also caps, else the child's max
            # is unenforceable against the parent's tree accounting
            if parent.max.quantities:
                extra = set(quota.max.quantities) - set(parent.max.quantities)
                if extra:
                    raise AdmissionError(
                        f"max keys {sorted(extra)} not present in parent "
                        f"{parent_name!r} max")
            # checkMinQuotaValidate (:214-255): Σ sibling min (incl. this
            # quota) must fit inside the parent's min — over the UNION of
            # the siblings' min keys, a key the parent's min omits counts
            # as 0 (LessThanOrEqualCompletely semantics), so any child min
            # in it is rejected
            siblings = [q for q in self._quota_children(parent_name)
                        if q.meta.name != quota.meta.name]
            sibling_keys = set(quota.min.quantities).union(
                *[set(q.min.quantities) for q in siblings]) if siblings \
                else set(quota.min.quantities)
            for name in sibling_keys:
                pmn = parent.min.get(name, 0)
                sibling_sum = quota.min.get(name, 0) + sum(
                    q.min.get(name, 0) for q in siblings)
                if sibling_sum > pmn:
                    raise AdmissionError(
                        f"sibling min[{name}] sum={sibling_sum} exceeds "
                        f"parent min={pmn}")
        # Σ children min must fit inside this quota's (possibly shrunken) min
        children = self._quota_children(quota.meta.name)
        for name in {n for c in children for n in c.min.quantities}:
            child_sum = sum(c.min.get(name, 0) for c in children)
            if child_sum > quota.min.get(name, 0):
                raise AdmissionError(
                    f"children min[{name}] sum={child_sum} exceeds new "
                    f"min={quota.min.get(name, 0)}")
        if old is not None:
            self._validate_quota_update(quota, old)

    def _validate_quota_update(self, quota: ElasticQuota,
                               old: ElasticQuota) -> None:
        """checkIsParentChange (:142-165) + tree-id immutability."""
        if MANAGER_GATES.enabled("ElasticQuotaImmutableAnnotations"):
            if old.tree_id and quota.tree_id != old.tree_id:
                raise AdmissionError("quota tree-id is immutable")
        if old.is_parent != quota.is_parent:
            if old.is_parent and self._quota_children(old.meta.name):
                raise AdmissionError(
                    "quota has children; isParent cannot become false")
            from koordinator_tpu.client.store import KIND_POD

            # a pod binds to the quota either by explicit label or by the
            # namespace-default rule (see mutate_pod_quota_tree_affinity);
            # terminated pods no longer hold quota and must not block
            if quota.is_parent and any(
                (p.quota_name or p.meta.namespace) == old.meta.name
                and not p.is_terminated
                for p in self.store.list(KIND_POD)
            ):
                raise AdmissionError(
                    "quota has bound pods; isParent cannot become true")

    def validate_elastic_quota_delete(self, quota: ElasticQuota) -> None:
        """Deletion guard (webhook/elasticquota): a parent group with child
        quotas cannot be deleted (the orphans would silently detach from the
        tree and escape their ancestors' limits)."""
        if not quota.is_parent:
            return
        children = [
            q.meta.name
            for q in self.store.list(KIND_ELASTIC_QUOTA)
            if q.parent == quota.meta.name and q.meta.name != quota.meta.name
        ]
        if children:
            raise AdmissionError(
                f"quota {quota.meta.name!r} still has children: "
                f"{sorted(children)}")

    # -- generic dispatch ----------------------------------------------
    def admit(self, kind: str, obj, old=None, delete: bool = False):
        """Run the registered mutators + validators for a kind (server.go's
        per-GVK handler registration, flattened)."""
        from koordinator_tpu.client.store import (
            KIND_CONFIG_MAP,
            KIND_NODE,
            KIND_POD,
        )

        if kind == KIND_POD and not delete:
            return self.admit_pod_create(obj)
        if kind == KIND_ELASTIC_QUOTA:
            if delete:
                if MANAGER_GATES.enabled("ElasticQuotaValidatingWebhook"):
                    self.validate_elastic_quota_delete(obj)
            elif MANAGER_GATES.enabled("ElasticQuotaValidatingWebhook"):
                self.validate_elastic_quota(obj, old)
        elif kind == KIND_NODE and not delete:
            if MANAGER_GATES.enabled("NodeMutatingWebhook"):
                self.mutate_node(obj, old)
            if MANAGER_GATES.enabled("NodeValidatingWebhook"):
                self.validate_node(obj)
        elif kind == KIND_CONFIG_MAP and not delete:
            if MANAGER_GATES.enabled("ConfigMapValidatingWebhook"):
                self.validate_config_map(obj)
        return obj

    # -- node -----------------------------------------------------------
    AMPLIFICATION_RATIO_ANNOTATION = (
        "node.koordinator.sh/resource-amplification-ratio")
    RAW_ALLOCATABLE_ANNOTATION = "node.koordinator.sh/raw-allocatable"
    _AMPLIFIABLE = (ResourceName.CPU, ResourceName.MEMORY)

    def mutate_node(self, node: Node, old: Optional[Node] = None) -> None:
        """Resource amplification (webhook/node/plugins/resourceamplification):
        allocatable = kubelet-reported raw allocatable x per-resource ratio.
        The raw values are remembered in an annotation so repeated admissions
        don't compound the ratio; a kubelet allocatable change refreshes them.
        Clearing the ratio annotation restores raw allocatable."""
        ann = node.meta.annotations
        raw_ratio = ann.get(self.AMPLIFICATION_RATIO_ANNOTATION, "")
        if not raw_ratio:
            saved = ann.pop(self.RAW_ALLOCATABLE_ANNOTATION, None)
            if saved:  # feature switched off: restore kubelet values
                for name, val in json.loads(saved).items():
                    node.allocatable.quantities[name] = int(val)
            return
        try:
            ratios = json.loads(raw_ratio)
        except ValueError:
            raise AdmissionError("resource-amplification-ratio is not JSON")
        if not isinstance(ratios, dict):
            raise AdmissionError(
                "resource-amplification-ratio must be a JSON object of "
                "resource name to ratio")
        # old-vs-new compares whatever the cluster stored (amplified) against
        # the incoming values; a kubelet raw update that happens to equal the
        # old amplified value is missed — the reference has the identical
        # documented limitation (resource_amplification.go "FIXME 1")
        supported_changed = old is not None and any(
            old.allocatable.get(r) != node.allocatable.get(r)
            for r in self._AMPLIFIABLE
        )
        if self.RAW_ALLOCATABLE_ANNOTATION not in ann or supported_changed:
            raw = {r: node.allocatable.get(r)
                   for r in self._AMPLIFIABLE if node.allocatable.get(r)}
            ann[self.RAW_ALLOCATABLE_ANNOTATION] = json.dumps(raw)
        original = json.loads(ann[self.RAW_ALLOCATABLE_ANNOTATION])
        for name in self._AMPLIFIABLE:
            ratio = ratios.get(name)
            if ratio is None:
                continue
            try:
                ratio = float(ratio)
            except (TypeError, ValueError):
                raise AdmissionError(
                    f"resource-amplification-ratio[{name}] is not a number")
            if not math.isfinite(ratio):
                raise AdmissionError(
                    f"resource-amplification-ratio[{name}] is not finite")
            if ratio <= 1 or name not in original:
                continue
            node.allocatable.quantities[name] = int(original[name] * ratio)

    def validate_node(self, node: Node) -> None:
        raw = node.meta.annotations.get("node.koordinator.sh/cpu-normalization-ratio")
        if raw:
            try:
                ratio = float(raw)
            except ValueError:
                raise AdmissionError("cpu-normalization-ratio must be a number")
            if not 0.1 <= ratio <= 10:
                raise AdmissionError("cpu-normalization-ratio out of range [0.1, 10]")

    # -- configmap ------------------------------------------------------
    def validate_config_map(self, cm: ConfigMap) -> None:
        if cm.meta.name != CONFIG_MAP_NAME:
            return
        if COLOCATION_CONFIG_KEY in cm.data:
            _, err = parse_colocation_config(cm.data)
            if err:
                raise AdmissionError(err)
