"""koordcolo: the control plane's resource model on device.

The THIRD consumer of the scheduler's DeviceSnapshot (after the
dispatch kernels and the koordbalance descheduler pass): the
slo-controller's batch/mid overcommit pipeline and the
quota-controller's elastic-quota runtime fairness run as ONE jitted
tensor program over packed state the SnapshotCache's existing store
subscriptions maintain — closing the colocation loop (usage ->
overcommit -> scheduling -> rebalance -> revoke) entirely on device,
host-oracle parity-gated by ``pipeline_parity.run_colo_parity``.
"""

from koordinator_tpu.colo.pack import ColoPack  # noqa: F401
from koordinator_tpu.colo.reconciler import (  # noqa: F401
    COLO_NODE_FIELDS,
    DeviceColoReconciler,
    colo_from_env,
)
from koordinator_tpu.colo.step import ColoOut, build_colo_step  # noqa: F401
