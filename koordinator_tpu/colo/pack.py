"""ColoPack: event-maintained packed arrays for the colo pass.

The colo device program (colo/step.py) consumes two state families:

  * per-node columns of the NodeResource pipeline — capacity, the
    node-reservation annotation split, the per-node strategy scalars,
    NodeMetric usage, and the per-class pod aggregate sums;
  * the elastic-quota tree — parent indices, min/max/weight/guarantee,
    live request/used — plus the cluster allocatable total.

When a :class:`~koordinator_tpu.scheduler.snapshot_cache.SnapshotCache`
lives in the same process it *forwards* its existing store subscriptions
into this pack (``SnapshotCache.colo_pack``) instead of the pack opening
a second subscription chain — the "one upload, three consumers"
invariant koordlint rule 18 (``host-reconcile-in-colo-path``) pins for
new code in this package, the same shape as balance/pack.py. A
standalone koord-manager (no co-located scheduler) constructs the pack
with ``subscribe=True`` and it watches the store itself.

Exactness: node rows are built by the SAME row builders the host oracle
uses (``slocontroller.noderesource.node_static_row`` /
``node_metric_row``) so the device pass reads bit-identical inputs; the
static rows memoize on (node resourceVersion, config epoch) and the
metric rows refresh only for nodes whose NodeMetric or pod membership
changed — the per-pass cost is the delta, not the cluster. The quota
arrays memoize on the quota plugin's (tree_epoch, state_epoch) and the
cluster total on the node epoch — the `_runtime_by_name` memo satellite
made device-shaped.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

import numpy as np

from koordinator_tpu.api.objects import Node, NodeMetric, Pod
from koordinator_tpu.api.resources import NUM_RESOURCES
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    EventType,
    ObjectStore,
)
from koordinator_tpu.slocontroller.noderesource import (
    node_metric_row,
    node_static_row,
)
from koordinator_tpu.utils.sloconfig import ColocationConfigSource


class ColoPack:
    """Packed node + quota state for the colo pass (see module doc).

    ``config_source`` is shared with the host-oracle
    ``NodeResourceController`` so both engines see the SAME effective
    (hot-reloaded) ColocationConfig. Construct via
    ``SnapshotCache.colo_pack`` (shared-process: events forwarded) or
    directly with ``subscribe=True`` (standalone manager)."""

    def __init__(self, store: ObjectStore,
                 config_source: ColocationConfigSource,
                 subscribe: bool = True) -> None:
        self.store = store
        self.config_source = config_source
        self._config_epoch_seen = -1
        # node table (store list order; rebuilt when the layout changes)
        self._nodes: List[Node] = []
        self._node_idx: Dict[str, int] = {}
        self._layout_stale = True
        self._static_dirty: Set[str] = set()
        self._metric_dirty: Set[str] = set()
        self._static_key: Dict[str, tuple] = {}
        R = NUM_RESOURCES
        self.capacity = np.zeros((0, R), np.float32)
        self.node_reserved = np.zeros((0, R), np.float32)
        self.system_reserved = np.zeros((0, R), np.float32)
        self.reclaim_pct = np.zeros((0, R), np.float32)
        self.mid_pct = np.zeros((0, R), np.float32)
        self.degrade_seconds = np.zeros(0, np.float64)
        self.node_used = np.zeros((0, R), np.float32)
        self.prod_reclaimable = np.zeros((0, R), np.float32)
        self.pod_all_used = np.zeros((0, R), np.float32)
        self.hp_used = np.zeros((0, R), np.float32)
        self.hp_request = np.zeros((0, R), np.float32)
        self.hp_max = np.zeros((0, R), np.float32)
        self.nm_time = np.zeros(0, np.float64)
        # assigned-pod membership per node (the metric-row join input)
        self._pods_on_node: Dict[str, Dict[str, Pod]] = {}
        self._pod_node: Dict[str, str] = {}
        # quota-side memos
        self._quota_memo: Optional[tuple] = None   # (epoch key, arrays)
        self._total_memo: Optional[tuple] = None   # (nodes epoch, vec)
        self._nodes_epoch = 0
        if subscribe:
            store.subscribe(KIND_NODE, self.on_node)
            store.subscribe(KIND_NODE_METRIC, self.on_metric)
            store.subscribe(KIND_POD, self.on_pod)

    # ------------------------------------------------------------------
    # events (called by the store OR forwarded by SnapshotCache)
    # ------------------------------------------------------------------
    def on_node(self, ev, node, old) -> None:
        self._nodes_epoch += 1
        name = node.meta.name
        if ev is EventType.DELETED or old is None:
            self._layout_stale = True
        else:
            # the store may swap in a NEW object instance on update
            # (store.update replaces the stored reference): re-anchor
            # the table entry so the static-row refresh reads the fresh
            # labels/annotations and the writeback mutates the LIVE
            # object, never a stale copy
            idx = self._node_idx.get(name)
            if idx is not None and not self._layout_stale:
                self._nodes[idx] = node
        self._static_dirty.add(name)
        self._metric_dirty.add(name)

    def on_metric(self, ev, nm, old) -> None:
        self._metric_dirty.add(nm.meta.name)

    def on_pod(self, ev, pod: Pod, old) -> None:
        key = pod.meta.key
        live = (ev is not EventType.DELETED and pod.is_assigned
                and not pod.is_terminated)
        prev_node = self._pod_node.pop(key, None)
        if prev_node is not None:
            self._pods_on_node.get(prev_node, {}).pop(key, None)
            self._metric_dirty.add(prev_node)
        if live:
            node = pod.spec.node_name
            self._pods_on_node.setdefault(node, {})[key] = pod
            self._pod_node[key] = node
            self._metric_dirty.add(node)
        elif old is not None and old.spec.node_name:
            self._metric_dirty.add(old.spec.node_name)

    # ------------------------------------------------------------------
    # refresh
    # ------------------------------------------------------------------
    def _refresh_layout(self) -> None:
        # layout rebuild runs only on node add/delete events, never
        # per pass — the one sanctioned store walk in this package
        # koordlint: disable=host-reconcile-in-colo-path
        nodes = self.store.list(KIND_NODE)
        self._nodes = nodes
        self._node_idx = {n.meta.name: i for i, n in enumerate(nodes)}
        N = len(nodes)
        R = NUM_RESOURCES
        # fixed column-array re-allocation on layout change (11 names)
        # koordlint: disable=host-reconcile-in-colo-path
        for field in ("capacity", "node_reserved", "system_reserved",
                      "reclaim_pct", "mid_pct", "node_used",
                      "prod_reclaimable", "pod_all_used", "hp_used",
                      "hp_request", "hp_max"):
            setattr(self, field, np.zeros((N, R), np.float32))
        self.degrade_seconds = np.zeros(N, np.float64)
        self.nm_time = np.zeros(N, np.float64)
        self._static_key.clear()
        self._static_dirty = {n.meta.name for n in nodes}
        self._metric_dirty = {n.meta.name for n in nodes}
        self._layout_stale = False

    def _refresh_static(self, config) -> None:
        config_epoch = self.config_source.epoch
        if config_epoch != self._config_epoch_seen:
            # policy scalars changed: every strategy row re-derives
            self._config_epoch_seen = config_epoch
            self._static_key.clear()
            self._static_dirty.update(self._node_idx)
        if not self._static_dirty:
            return
        # event-driven refresh, not per-pass work: only nodes whose
        # store object (or the effective config) changed re-derive their
        # strategy/annotation row — the shared row builder guarantees
        # bit-parity with the host oracle's gather
        # koordlint: disable=host-reconcile-in-colo-path
        for name in self._static_dirty:
            i = self._node_idx.get(name)
            if i is None:
                continue
            node = self._nodes[i]
            key = (node.meta.resource_version, config_epoch)
            if self._static_key.get(name) == key:
                continue
            strategy = config.strategy_for_node(
                node.meta.labels, node.meta.annotations)
            (self.capacity[i], self.node_reserved[i],
             self.system_reserved[i], self.reclaim_pct[i],
             self.mid_pct[i], self.degrade_seconds[i]) = node_static_row(
                node, strategy)
            self._static_key[name] = key
        self._static_dirty.clear()

    def _refresh_metrics(self) -> None:
        if not self._metric_dirty:
            return
        # event-driven refresh: only nodes whose NodeMetric or assigned
        # pod membership changed re-join their aggregate rows
        # koordlint: disable=host-reconcile-in-colo-path
        for name in self._metric_dirty:
            i = self._node_idx.get(name)
            if i is None:
                continue
            nm: Optional[NodeMetric] = self.store.get(
                KIND_NODE_METRIC, f"/{name}")
            pods = list(self._pods_on_node.get(name, {}).values())
            (self.node_used[i], self.prod_reclaimable[i],
             self.pod_all_used[i], self.hp_used[i], self.hp_request[i],
             self.hp_max[i]) = node_metric_row(nm, pods)
            self.nm_time[i] = nm.update_time if nm is not None else 0.0
        self._metric_dirty.clear()

    # ------------------------------------------------------------------
    # views
    # ------------------------------------------------------------------
    def view(self, now: float) -> dict:
        """Packed node arrays for the colo pass — refreshes lazily.
        ``degraded`` is the staleness decision at ``now`` (vectorized
        host compare, like the rebalance pack's has_metric)."""
        config = self.config_source.get()
        if self._layout_stale:
            self._refresh_layout()
        self._refresh_static(config)
        self._refresh_metrics()
        degraded = (self.nm_time <= 0.0) | (
            now - self.nm_time > self.degrade_seconds)
        return {
            "nodes": self._nodes,
            "capacity": self.capacity,
            "node_reserved": self.node_reserved,
            "system_reserved": self.system_reserved,
            "node_used": self.node_used,
            "pod_all_used": self.pod_all_used,
            "hp_used": self.hp_used,
            "hp_request": self.hp_request,
            "hp_max": self.hp_max,
            "prod_reclaimable": self.prod_reclaimable,
            "reclaim_pct": self.reclaim_pct,
            "mid_pct": self.mid_pct,
            "degraded": degraded,
            "cpu_policy": config.cluster_strategy.cpu_calculate_policy,
            "memory_policy": config.cluster_strategy.memory_calculate_policy,
        }

    def quota_view(self, quota_plugin) -> Optional[dict]:
        """Packed quota-tree arrays from the (scheduler-shared) elastic
        quota plugin's live caches, memoized on its (tree_epoch,
        state_epoch) and the cluster total on the node epoch — rebuilt
        only when a quota CR, a member pod, or a node changed. None when
        no quotas exist (the kernel's quota side runs empty-padded)."""
        total = self._cluster_total(quota_plugin)
        key = (quota_plugin.tree_epoch, quota_plugin.state_epoch,
               self._nodes_epoch)
        hit = self._quota_memo
        if hit is not None and hit[0] == key:
            return hit[1]
        tree = quota_plugin.packed_tree()
        arrays = None
        if tree is not None:
            G = len(tree.names)
            enable = (tree.enable_min_scale
                      if tree.enable_min_scale.shape[0] == G
                      else np.ones(G, bool))
            arrays = {
                "names": tree.names,
                "tree": tree,
                "q_parent": tree.parent.astype(np.int32),
                "q_level": tree.level.astype(np.int32),
                "q_min": tree.min.astype(np.float32),
                "q_max": tree.max.astype(np.float32),
                "q_weight": tree.shared_weight.astype(np.float32),
                "q_guarantee": tree.guarantee.astype(np.float32),
                "q_request": tree.request.astype(np.float32),
                # LEAF used (not the tree's parent-aggregated rolls):
                # the revoke mask is a leaf-level decision
                "q_used": quota_plugin.leaf_used_matrix(tree.names),
                "q_allow_lent": tree.allow_lent.astype(bool),
                "q_enable_scale": enable,
                "q_total": total.astype(np.float32),
            }
        self._quota_memo = (key, arrays)
        return arrays

    def _cluster_total(self, quota_plugin) -> np.ndarray:
        hit = self._total_memo
        if hit is not None and hit[0] == self._nodes_epoch:
            return hit[1]
        total = quota_plugin.cluster_total_vec(self.store)
        self._total_memo = (self._nodes_epoch, total)
        return total
