"""DeviceColoReconciler: drive the colo tensor pass against the shared
device mirror, with the PR 7 degradation ladder underneath.

The reconciler is the koord-manager-side consumer of the scheduler's
``DeviceSnapshot`` — the THIRD, after the dispatch kernels and the
koordbalance descheduler pass: its arrays upload through the SAME
reuse/scatter/put machinery (``upload_fields``) under ``colo_*`` names,
so a steady-state cluster ships only row deltas and the three consumers
share one device mirror. Under ``KOORD_TPU_MESH`` the node-axis fields
shard over the mesh via the existing ``put_on_mesh``/NamedSharding
helpers (parallel/colo_mesh.py) and every output replicates.

The colocation loop closes on device: the batch/mid writeback goes
through the host oracle's OWN ``NodeResourceController.apply`` (so the
store-visible effect is engine-independent by construction) and the
VERY NEXT scheduling dispatch packs the new allocatable — usage ->
overcommit -> scheduling -> rebalance -> revoke without a host
reconcile loop. The quota runtime fold runs against the PREDICTED
post-writeback cluster total (the kernel knows its own batch/mid
integers); the prediction is verified against the store after the
writeback and the published device runtime is dropped on any mismatch
(the plugin-chain edge: a Device CR write in the same pass), falling
back to the epoch-memoized host fold — decisions never drift.

Resilience reuses the scheduler's ladder machine
(scheduler/degrade.DegradationLadder) with only the rungs that change
behavior here: ``full`` (sharded device pass) -> ``no-mesh`` (single-
device pass) -> ``host-fallback`` (the retained host oracles:
NodeResourceController + compute_runtime_quotas). Retry-once, clean-
pass re-promotion with exponential backoff, and the dispatch-deadline
watchdog (koordguard) all behave exactly like the dispatch and
rebalance windows.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from koordinator_tpu.api.resources import RESOURCE_INDEX, ResourceName
from koordinator_tpu.obs import Tracer
from koordinator_tpu.obs.flight import FLIGHT_SCHEMA_VERSION, FlightRecorder
from koordinator_tpu.ops.quota import MAX_QUOTA_DEPTH
from koordinator_tpu.scheduler.deadline import (
    DeadlineWatchdog,
    DispatchDeadlineExceeded,
    deadline_seconds_from,
)
from koordinator_tpu.scheduler.degrade import (
    LEVEL_HOST_FALLBACK,
    LEVEL_NO_MESH,
    DegradationLadder,
)

logger = logging.getLogger(__name__)

# names of the node-axis upload fields — shared with
# snapshot_cache._mesh_node_fields so the mesh-backed DeviceSnapshot
# shards them exactly like the scheduler's own node arrays
COLO_NODE_FIELDS = (
    "colo_capacity", "colo_node_reserved", "colo_system_reserved",
    "colo_node_used", "colo_pod_all_used", "colo_hp_used",
    "colo_hp_request", "colo_hp_max", "colo_prod_reclaimable",
    "colo_reclaim_pct", "colo_mid_pct", "colo_degraded",
)

BATCH_CPU_AXIS = RESOURCE_INDEX[ResourceName.BATCH_CPU]
BATCH_MEM_AXIS = RESOURCE_INDEX[ResourceName.BATCH_MEMORY]
MID_CPU_AXIS = RESOURCE_INDEX[ResourceName.MID_CPU]
MID_MEM_AXIS = RESOURCE_INDEX[ResourceName.MID_MEMORY]
_OVERCOMMIT_AXES = (BATCH_CPU_AXIS, BATCH_MEM_AXIS,
                    MID_CPU_AXIS, MID_MEM_AXIS)

# f32 integer-exact envelope for the quota fold (colo/step.py module
# doc): segment sums and the cluster total must stay below 2^24 for the
# device fold's order-free arithmetic to equal the host's
_F32_EXACT_BOUND = float(2 ** 24)


def colo_from_env() -> str:
    """KOORD_TPU_COLO=on|off|host selects the control-plane engine:
    "on" (default) runs the device colo pass (with the host-oracle
    fallback ladder underneath), "host" pins the host reconcilers with
    the colo surfaces (metrics/spans/flight) kept, "off" detaches the
    colo subsystem entirely — the legacy per-controller reconciles run
    exactly as before (the incident kill switch)."""
    import os

    raw = os.environ.get("KOORD_TPU_COLO", "on").strip().lower()
    if raw in ("", "on", "1", "true", "device"):
        return "on"
    if raw in ("off", "0", "false", "no"):
        return "off"
    if raw == "host":
        return "host"
    logger.warning("KOORD_TPU_COLO=%r unknown; using 'on'", raw)
    return "on"


def _bucket(n: int, lo: int) -> int:
    """Power-of-two pad bucket (>= lo): each distinct padded shape is a
    distinct compiled program, so shapes quantize."""
    p = lo
    while p < n:
        p *= 2
    return p


class DeviceColoReconciler:
    """Owns the compiled colo steps, the (possibly shared) device
    mirror, the colo ladder, span tree, metrics and flight ring.

    ``controller`` is the host-oracle NodeResourceController (writeback
    + host fallback), ``quota_plugin`` the (scheduler-shared)
    ElasticQuotaPlugin, ``pack`` the ColoPack. ``snapshot_getter``
    returns the scheduler's live DeviceSnapshot (rebuilt on scheduler
    ladder transitions, so the reference is read per pass); without one
    the reconciler owns a private mirror."""

    def __init__(self, store, controller, quota_plugin, pack,
                 mesh=None,
                 snapshot_getter: Optional[Callable[[], object]] = None,
                 ladder: Optional[DegradationLadder] = None,
                 promote_after: int = 16,
                 tracer: Optional[Tracer] = None,
                 flight: Optional[FlightRecorder] = None,
                 dispatch_deadline_ms=None,
                 engine: str = "on",
                 timeline=None) -> None:
        self.store = store
        self.controller = controller
        self.quota_plugin = quota_plugin
        self.pack = pack
        self.mesh = mesh
        self.engine = engine  # "on" = device (ladder under it) | "host"
        self.snapshot_getter = snapshot_getter
        self.ladder = ladder if ladder is not None else DegradationLadder(
            promote_after=promote_after)
        self.tracer = tracer if tracer is not None else Tracer()
        self.flight = flight if flight is not None else FlightRecorder()
        # koordwatch: the device timeline this pass records its windows
        # into — the SCHEDULER's ring when co-located (three consumers,
        # one device, one ring / decision-id sequence), else private.
        # The per-pass decision id lands on last_pass_stats and the
        # flight record; it is deliberately NOT written to the store —
        # the batch/mid writeback must stay engine-independent byte for
        # byte (run_colo_parity pins that).
        if timeline is None:
            # standalone: record into the MANAGER's registry — the one
            # this binary's /metrics actually serves — and honor the
            # KOORD_TPU_WATCH kill switch like every other ring
            from koordinator_tpu import manager_metrics as mm
            from koordinator_tpu.obs.timeline import (
                DeviceTimeline,
                watch_from_env,
            )

            timeline = DeviceTimeline(
                window_histogram=mm.DEVICE_WINDOW_SECONDS,
                idle_gauge=mm.DEVICE_IDLE_FRACTION,
                enabled=watch_from_env())
        self.timeline = timeline
        self.last_decision_id: Optional[str] = None
        self._step_cache: Dict[Tuple, object] = {}
        self._last_step_compiled = False
        self._own_snapshots: Dict[bool, object] = {}  # mesh_on -> mirror
        self._seq = 0
        self._warned_host_only = False
        # sim/test failure-injection hook: a callable() invoked at the
        # top of every device-pass window; raising from it exercises the
        # colo ladder exactly like a real XLA/mesh fault
        self.fault_injector = None
        # koordguard dispatch deadline: shares the scheduler's
        # KOORD_TPU_DISPATCH_DEADLINE_MS knob and watchdog discipline
        self.dispatch_deadline_seconds = deadline_seconds_from(
            dispatch_deadline_ms)
        self.dispatch_watchdog = DeadlineWatchdog(
            self.dispatch_deadline_seconds,
            on_overrun=self._on_deadline_overrun)
        self.sync_delay_injector = None
        self.stats = {"device_passes": 0, "host_passes": 0,
                      "nodes_changed": 0, "degraded_nodes": 0,
                      "revoke_candidates": 0}
        self.last_pass_stats: Dict[str, object] = {}

    def _on_deadline_overrun(self, path: str) -> None:
        from koordinator_tpu.scheduler import metrics as scheduler_metrics

        scheduler_metrics.DISPATCH_DEADLINE_OVERRUNS.inc(path=path)
        self.flight.dump("dispatch_deadline")

    # ------------------------------------------------------------------
    def _features(self) -> Dict[str, bool]:
        return {"mesh": self.mesh is not None,
                "waves": False, "explain": False}

    def _active_mesh(self):
        return self.mesh if self.ladder.level < LEVEL_NO_MESH else None

    def _snapshot(self, mesh):
        """The device mirror for this pass — the scheduler's shared
        mirror while its mesh placement matches ours, else a private
        one (same contract as balance/rebalancer._snapshot)."""
        if self.snapshot_getter is not None:
            shared = self.snapshot_getter()
            if shared is not None and getattr(shared, "mesh", None) is mesh:
                return shared
        key = mesh is not None
        snap = self._own_snapshots.get(key)
        if snap is None:
            from koordinator_tpu.scheduler.snapshot_cache import (
                DeviceSnapshot,
            )

            snap = DeviceSnapshot(mesh=mesh)
            self._own_snapshots[key] = snap
        return snap

    def _get_step(self, n_pad: int, g_pad: int, policies: Tuple[str, str],
                  mesh):
        # device IDS, not just the count (koordguard partial-mesh
        # discipline: two same-size submeshes never share a step)
        mesh_tag = (tuple(d.id for d in mesh.devices.flat)
                    if mesh is not None else ())
        # policy strings key the cache — a config hot-reload that flips
        # the calculate policy reuses the previously compiled step on
        # the next flip instead of leaking a fresh compile per change
        key = (n_pad, g_pad, policies, mesh_tag)
        step = self._step_cache.get(key)
        self._last_step_compiled = step is None
        if step is None:
            with self.tracer.span("compile", signature=str(key)):
                if mesh is not None:
                    from koordinator_tpu.parallel import (
                        build_sharded_colo_step,
                    )

                    step = build_sharded_colo_step(
                        policies[0], policies[1], mesh)
                else:
                    from koordinator_tpu.colo.step import build_colo_step

                    step = build_colo_step(policies[0], policies[1])
            self._step_cache[key] = step
        return step

    # ------------------------------------------------------------------
    @staticmethod
    def _device_eligible(qv) -> Optional[str]:
        """The device quota fold's exactness preconditions (colo/step.py
        module doc). A view outside them is not a fault — it is a
        per-pass demotion to the host oracle, like the rebalancer's
        integer guard. The batch/mid side has no preconditions (it is
        the host's own f32 kernel)."""
        if qv is None:
            return None
        # static 5-name integrality sweep (vectorized numpy inside)
        # koordlint: disable=host-reconcile-in-colo-path
        for name in ("q_min", "q_guarantee", "q_request", "q_weight",
                     "q_total"):
            a = qv[name]
            if a.size and not np.all(np.floor(a) == a):
                return f"non-integer {name} rows"
        if np.any(qv["q_total"] >= _F32_EXACT_BOUND):
            return "cluster total exceeds the f32-exact bound"
        parent = qv["q_parent"]
        G = parent.shape[0]
        seg = np.where(parent >= 0, parent, G)
        eff_min = np.maximum(qv["q_min"], qv["q_guarantee"])
        # static 3-name segment-sum bound sweep (vectorized inside)
        # koordlint: disable=host-reconcile-in-colo-path
        for name, a in (("min", eff_min), ("request", qv["q_request"]),
                        ("weight", qv["q_weight"])):
            sums = np.zeros((G + 1, a.shape[1]), np.float64)
            np.add.at(sums, seg, a)
            if np.any(sums >= _F32_EXACT_BOUND):
                return (f"per-parent {name} sums exceed the f32-exact "
                        f"bound")
        return None

    def _prep(self, view, qv):
        """Pad-bucketed host arrays for the upload."""
        n = view["capacity"].shape[0]
        R = view["capacity"].shape[1]
        n_pad = _bucket(max(n, 1), 8)
        fields: Dict[str, np.ndarray] = {}
        # fixed 11-field pad staging (whole-array copies, no per-row work)
        # koordlint: disable=host-reconcile-in-colo-path
        for src, dst in (
                ("capacity", "colo_capacity"),
                ("node_reserved", "colo_node_reserved"),
                ("system_reserved", "colo_system_reserved"),
                ("node_used", "colo_node_used"),
                ("pod_all_used", "colo_pod_all_used"),
                ("hp_used", "colo_hp_used"),
                ("hp_request", "colo_hp_request"),
                ("hp_max", "colo_hp_max"),
                ("prod_reclaimable", "colo_prod_reclaimable"),
                ("reclaim_pct", "colo_reclaim_pct"),
                ("mid_pct", "colo_mid_pct")):
            buf = np.zeros((n_pad, R), np.float32)
            buf[:n] = view[src]
            fields[dst] = buf
        degraded = np.zeros(n_pad, bool)
        degraded[:n] = view["degraded"]
        fields["colo_degraded"] = degraded
        # quota side (replicated): pad rows are level=-1 / invalid
        if qv is not None:
            G = qv["q_parent"].shape[0]
            total = qv["q_total"].copy()
        else:
            G = 0
            total = np.zeros(R, np.float32)
        g_pad = _bucket(max(G, 1), 8)
        q_parent = np.full(g_pad, -1, np.int32)
        q_level = np.full(g_pad, -1, np.int32)
        q_valid = np.zeros(g_pad, bool)
        q_allow = np.zeros(g_pad, bool)
        q_enable = np.zeros(g_pad, bool)
        mats = {name: np.zeros((g_pad, R), np.float32)
                for name in ("q_min", "q_max", "q_weight", "q_guarantee",
                             "q_request", "q_used")}
        if qv is not None:
            q_parent[:G] = qv["q_parent"]
            q_level[:G] = qv["q_level"]
            q_valid[:G] = True
            q_allow[:G] = qv["q_allow_lent"]
            q_enable[:G] = qv["q_enable_scale"]
            # fixed 6-matrix pad staging
            # koordlint: disable=host-reconcile-in-colo-path
            for name in mats:
                mats[name][:G] = qv[name]
        # the runtime fold divides the PREDICTED post-writeback total:
        # base axes from the store total, the overcommit axes re-derived
        # in-kernel from this pass's own batch/mid integers
        total_base = total.copy()
        total_base[list(_OVERCOMMIT_AXES)] = 0.0
        fields.update({
            "colo_q_parent": q_parent, "colo_q_level": q_level,
            "colo_q_valid": q_valid, "colo_q_allow_lent": q_allow,
            "colo_q_enable_scale": q_enable,
            "colo_q_total_base": total_base.astype(np.float32),
        })
        # fixed 6-matrix field naming
        # koordlint: disable=host-reconcile-in-colo-path
        for name, mat in mats.items():
            fields[f"colo_{name}"] = mat
        return fields, n_pad, g_pad

    # ------------------------------------------------------------------
    def reconcile(self, now: Optional[float] = None) -> int:
        """One colo pass: batch/mid writeback + the quota runtime
        publish. Returns the node change count (the host controller's
        reconcile contract, so the Manager's last_changes stays
        shaped)."""
        now = time.time() if now is None else now
        t0 = time.perf_counter()
        self._seq += 1
        with self.tracer.span("colo"):
            changes = self._reconcile_inner(now, t0)
        return changes

    def _reconcile_inner(self, now: float, t0: float) -> int:
        self.ladder.begin_pass()
        with self.tracer.span("pack"):
            view = self.pack.view(now)
            qv = self.pack.quota_view(self.quota_plugin)
        if not view["nodes"]:
            self.last_pass_stats = {"engine": "empty"}
            return 0
        # koordwatch: one decision id per pass (device or host); only a
        # completed device pass records a timeline window
        win = self.timeline.open(
            "colo",
            "mesh" if self._active_mesh() is not None else "serial")
        self.last_decision_id = win.decision_id
        if self.engine != "on":
            return self._host_pass(view, now, t0, engine="host-pinned")
        reason = self._device_eligible(qv)
        if reason is not None:
            if not self._warned_host_only:
                logger.warning("colo device pass ineligible (%s); using "
                               "the host oracle", reason)
                self._warned_host_only = True
            return self._host_pass(view, now, t0, engine="host-ineligible")
        attempts = 0
        had_deadline = False
        level0 = self.ladder.level
        while True:
            if self.ladder.level >= LEVEL_HOST_FALLBACK:
                return self._host_pass(view, now, t0)
            mesh = self._active_mesh()
            try:
                changes = self._device_pass(view, qv, now, t0, mesh, win)
                outcome = ("deadline" if had_deadline
                           else "demoted" if self.ladder.level > level0
                           else "retried" if attempts else "clean")
                self.timeline.close(win, outcome)
                self.ladder.note_cycle()
                return changes
            except Exception as exc:
                attempts += 1
                if isinstance(exc, DispatchDeadlineExceeded):
                    had_deadline = True
                action = self.ladder.on_failure(
                    self._features(),
                    error=f"{type(exc).__name__}: {exc}")
                if action == "exhausted":
                    raise
                logger.warning(
                    "colo device pass failed (%s: %s); %s at ladder "
                    "level %s", type(exc).__name__, exc, action,
                    self.ladder.level_name)
        # unreachable

    # ------------------------------------------------------------------
    def _host_pass(self, view, now: float, t0: float,
                   engine: str = "host") -> int:
        """The retained host oracles: NodeResourceController.reconcile
        plus the epoch-memoized host runtime fold (consumed lazily by
        the revoke controller — nothing to publish)."""
        with self.tracer.span("writeback", host="1"):
            changes = self.controller.reconcile(now)
        self.quota_plugin.device_runtime = None
        degraded = int(np.count_nonzero(view["degraded"]))
        self.stats["host_passes"] += 1
        self.stats["nodes_changed"] += changes
        self.stats["degraded_nodes"] = degraded
        self.last_pass_stats = {
            "engine": engine, "changes": changes,
            "degraded": view["degraded"].copy(),
            "decision_id": self.last_decision_id,
            "ladder_level": self.ladder.level_name,
        }
        self._record(now, t0, engine, changes, degraded, 0)
        self.ladder.note_cycle()
        return changes

    def _device_pass(self, view, qv, now: float, t0: float, mesh,
                     win) -> int:
        if self.fault_injector is not None:
            self.fault_injector()
        with self.tracer.span("encode") as esp:
            fields, n_pad, g_pad = self._prep(view, qv)
            esp.attributes["nodes"] = str(len(view["nodes"]))
            esp.attributes["quotas"] = str(
                0 if qv is None else len(qv["names"]))
        policies = (view["cpu_policy"], view["memory_policy"])
        step = self._get_step(n_pad, g_pad, policies, mesh)
        snap = self._snapshot(mesh)

        def sync_readback():
            # the colo pass's designated sync point, run under the
            # dispatch-deadline watchdog — route new syncs through here
            # (koordlint naked-device-sync-without-deadline)
            if self.sync_delay_injector is not None:
                self.sync_delay_injector()
            n = len(view["nodes"])
            g = 0 if qv is None else len(qv["names"])
            return (np.asarray(out.batch_cpu)[:n],
                    np.asarray(out.batch_mem)[:n],
                    np.asarray(out.mid_cpu)[:n],
                    np.asarray(out.mid_mem)[:n],
                    np.asarray(out.runtime)[:g],
                    np.asarray(out.revoke_over)[:g],
                    np.asarray(out.revoke_mask)[:g],
                    np.asarray(out.predicted_total))

        snap.begin_dispatch()
        win.mark_dispatch("mesh" if mesh is not None else "serial")
        abandoned = False
        try:
            with self.tracer.span("kernel", mesh=str(
                    mesh.devices.size if mesh is not None else 0),
                    decision_id=win.decision_id):
                dev = snap.upload_fields(fields)
                step_args = (
                    dev["colo_capacity"], dev["colo_node_reserved"],
                    dev["colo_system_reserved"], dev["colo_node_used"],
                    dev["colo_pod_all_used"], dev["colo_hp_used"],
                    dev["colo_hp_request"], dev["colo_hp_max"],
                    dev["colo_prod_reclaimable"],
                    dev["colo_reclaim_pct"], dev["colo_mid_pct"],
                    dev["colo_degraded"],
                    dev["colo_q_parent"], dev["colo_q_level"],
                    dev["colo_q_min"], dev["colo_q_max"],
                    dev["colo_q_weight"], dev["colo_q_guarantee"],
                    dev["colo_q_request"], dev["colo_q_used"],
                    dev["colo_q_allow_lent"], dev["colo_q_enable_scale"],
                    dev["colo_q_valid"], dev["colo_q_total_base"])
                if self._last_step_compiled:
                    # persistent warm-up index (scheduler/warmup.py):
                    # record the fresh rung so a restarted process can
                    # pre-compile the colo pass off the bind path
                    from koordinator_tpu.scheduler.warmup import (
                        record_step_compile,
                    )

                    record_step_compile(
                        "colo",
                        # n_pad/g_pad ride the meta so the index keeps
                        # ONE rung per shape bucket (dedupe is on meta;
                        # without them a grown bucket would evict the
                        # old bucket's rung)
                        {"policies": [policies[0], policies[1]],
                         "n_pad": int(n_pad), "g_pad": int(g_pad),
                         "mesh_tag": [int(d.id)
                                      for d in mesh.devices.flat]
                         if mesh is not None else []},
                        step_args)
                out = step(*step_args)
            with self.tracer.span("readback"):
                try:
                    (batch_cpu, batch_mem, mid_cpu, mid_mem, runtime,
                     revoke_over, revoke_mask,
                     predicted_total) = self.dispatch_watchdog.run(
                        sync_readback, "colo")
                except DispatchDeadlineExceeded:
                    # slow-not-dead device: abandon the pass, keep the
                    # shared mirror's dispatch window OPEN so donation
                    # cannot re-arm under the still-running program;
                    # drop a privately-owned mirror entirely
                    abandoned = True
                    self._own_snapshots = {
                        k: s for k, s in self._own_snapshots.items()
                        if s is not snap}
                    raise
        finally:
            if not abandoned:
                snap.end_dispatch()

        # ---- writeback: the host oracle's own apply(), so the
        # store-visible effect of a pass is engine-independent
        with self.tracer.span("writeback"):
            changes = self.controller.apply(
                view["nodes"], batch_cpu, batch_mem, mid_cpu, mid_mem)
            self._publish_runtime(qv, runtime, revoke_over, revoke_mask,
                                  predicted_total)
        degraded = int(np.count_nonzero(view["degraded"]))
        candidates = int(np.count_nonzero(revoke_mask))
        self.stats["device_passes"] += 1
        self.stats["nodes_changed"] += changes
        self.stats["degraded_nodes"] = degraded
        self.stats["revoke_candidates"] = candidates
        self.last_pass_stats = {
            "engine": "device", "changes": changes,
            "degraded": view["degraded"].copy(),
            "batch_cpu": batch_cpu, "batch_mem": batch_mem,
            "mid_cpu": mid_cpu, "mid_mem": mid_mem,
            "runtime": runtime, "revoke_mask": revoke_mask,
            "decision_id": win.decision_id,
            "ladder_level": self.ladder.level_name,
        }
        self._record(now, t0, "device", changes, degraded, candidates)
        return changes

    def _publish_runtime(self, qv, runtime, revoke_over, revoke_mask,
                         predicted_total) -> None:
        """Land the device fold's quota decisions on the plugin — but
        only when the kernel's predicted post-writeback cluster total
        matches the store (the plugin-chain edge can move non-overcommit
        axes); a mismatch falls back to the host fold, never drifts."""
        plugin = self.quota_plugin
        if qv is None:
            plugin.device_runtime = None
            return
        # the verification total routes through the pack's nodes-epoch
        # memo: a writeback that changed nothing reuses the cached
        # vector (no store walk); only a pass that actually moved node
        # status pays the O(N) re-sum — event-driven, not per-pass
        actual = self.pack._cluster_total(plugin)
        if not np.array_equal(predicted_total, actual):
            logger.warning(
                "colo: predicted post-writeback cluster total does not "
                "match the store (plugin-chain resource write?); "
                "dropping the device runtime for this pass")
            plugin.device_runtime = None
            return
        plugin.set_device_runtime(
            qv["names"], runtime, revoke_over, revoke_mask,
            key=plugin.epoch_key)

    def _record(self, now: float, t0: float, engine: str, changes: int,
                degraded: int, candidates: int) -> None:
        """One pass record into the flight ring (valid ``cycle`` record
        per obs/flight.py's schema, so colo dumps replay through the
        same tooling) + the pass metrics."""
        from koordinator_tpu import manager_metrics as mm

        duration = time.perf_counter() - t0
        mm.COLO_PASS_SECONDS.observe(duration)
        mm.COLO_PASSES_TOTAL.inc(engine=(
            "device" if engine == "device" else "host"))
        mm.COLO_DEGRADED_NODES.set(degraded)
        mm.COLO_REVOKE_CANDIDATES.set(candidates)
        if changes:
            mm.COLO_NODES_CHANGED_TOTAL.inc(changes)
        self.flight.record_cycle({
            "v": FLIGHT_SCHEMA_VERSION,
            "kind": "cycle",
            "seq": self._seq,
            "ts": float(now),
            "duration_ms": duration * 1000.0,
            "waves": 0,
            "bound": [], "failed": [], "rejected": [], "preempted": [],
            # koordwatch: the colo writeback's join key — spans, the
            # timeline window and this record share it
            "decision_id": str(self.last_decision_id or ""),
            "metrics": {
                "colo_nodes_changed": float(changes),
                "colo_degraded_nodes": float(degraded),
                "colo_revoke_candidates": float(candidates),
                "colo_device": float(engine == "device"),
            },
            "spans": [],
        })
