"""The device colo pass: the control plane's resource model as ONE program.

``build_colo_step`` compiles the two koord-manager reconciler families the
device mirror never touched into a single jitted pass:

  * the slo-controller's NodeResource pipeline — the batch/mid overcommit
    formula of ``slocontroller/noderesource._batch_mid_kernel`` reproduced
    verbatim over the packed per-node columns (colo/pack.py), with the
    staleness degrade folded in as ``degraded -> zero batch/mid rows``
    exactly like the host controller's gather;
  * the quota-controller's elastic-quota runtime fairness — the
    ``ops/quota.compute_runtime_quotas`` level fold (auto-scaled mins +
    water-filling redistribution per (parent, resource) segment) expressed
    as segment ops over the packed tree, plus the over-runtime
    revoke-candidate mask the overuse loop consumes.

Decision-parity discipline (gated by ``pipeline_parity.run_colo_parity``
at single-device and mesh 1/2/4/8):

  * the batch/mid arithmetic is the exact f32 op sequence of the host
    kernel — both sides run IEEE f32 elementwise ops on bit-identical
    packed rows, so the ``int()`` writeback truncation lands on the same
    integers;
  * the water-filling rounds are the host's own f32 arithmetic
    (``go_round_np`` is ``floor(x + 0.5)`` on f32 arrays), transcribed
    op-for-op; segment sums are order-free because every packed quota
    quantity is integer-valued (milli-cores / MiB) and the reconciler's
    eligibility guard bounds per-parent sums under 2^24 — the f32
    integer-exact envelope;
  * the ONE float64 site in the host fold — ``scaled_min_level``'s
    ``floor(avail * min / en_sum)`` — is an exact integer floor-division
    for in-envelope operands, reproduced on device through an f32 quotient
    candidate plus an int32 MODULAR correction (the same wraparound trick
    balance/step.py uses for its freed-prefix cumsum: ``a*m - q*s`` is
    exact in int32 arithmetic while the true remainder stays < 2^31).

Everything here is jnp on traced values — no host loops, no store reads
(koordlint rule 18 ``host-reconcile-in-colo-path`` pins that for this
package).
"""

from __future__ import annotations

from typing import NamedTuple

from koordinator_tpu.api.resources import RESOURCE_INDEX, ResourceName
from koordinator_tpu.ops.quota import MAX_QUOTA_DEPTH
from koordinator_tpu.utils.sloconfig import (
    POLICY_MAX_USAGE_REQUEST,
    POLICY_REQUEST,
)

CPU = RESOURCE_INDEX[ResourceName.CPU]
MEM = RESOURCE_INDEX[ResourceName.MEMORY]
BATCH_CPU_AXIS = RESOURCE_INDEX[ResourceName.BATCH_CPU]
BATCH_MEM_AXIS = RESOURCE_INDEX[ResourceName.BATCH_MEMORY]
MID_CPU_AXIS = RESOURCE_INDEX[ResourceName.MID_CPU]
MID_MEM_AXIS = RESOURCE_INDEX[ResourceName.MID_MEMORY]


class ColoOut(NamedTuple):
    """Device outputs of one colo pass (device values until the driver's
    readback sync). Node columns are the 4 allocatable vectors the
    writeback publishes; quota rows carry the runtime matrix and the
    revoke-candidate mask the overuse loop consumes."""

    batch_cpu: object     # [N] f32 — batch-cpu allocatable (milli)
    batch_mem: object     # [N] f32 — batch-memory allocatable (MiB)
    mid_cpu: object       # [N] f32
    mid_mem: object       # [N] f32
    n_degraded: object    # scalar i32 — staleness-degraded real nodes
    runtime: object       # [G, R] f32 — runtime quota per group
    revoke_over: object   # [G, R] f32 — max(used - runtime, 0)
    revoke_mask: object   # [G] bool  — any axis over runtime
    predicted_total: object  # [R] f32 — post-writeback cluster total
    #                          the runtime fold divided (verified by
    #                          the reconciler against the store)


def _exact_floordiv(a, m, s):
    """``floor(a * m / s)`` computed EXACTLY for integer-valued f32
    operands with ``a, m < 2^24`` and ``m <= s`` wherever the result is
    consumed: an f32 quotient candidate (absolute error <= 3 after
    floor), then an int32 modular correction — ``a*m`` and ``q*s`` wrap
    identically mod 2^32, so their difference is the true remainder
    whenever it stays < 2^31, which the +-3 candidate window guarantees.
    Rows violating the preconditions are masked off by the caller (the
    reconciler's eligibility guard demotes out-of-envelope trees to the
    host oracle before this runs)."""
    import jax.numpy as jnp

    s1 = jnp.maximum(s, 1.0)
    q0 = jnp.floor(a * m / s1)
    ai = a.astype(jnp.int32)
    mi = m.astype(jnp.int32)
    si = s1.astype(jnp.int32)
    am = ai * mi  # wraps mod 2^32 — exactness lives in the difference
    best = jnp.zeros_like(q0)
    # static 7-candidate unroll at trace time, not a host data loop
    # koordlint: disable=host-reconcile-in-colo-path
    for off in range(-3, 4):
        q = jnp.maximum(q0 + off, 0.0)
        k = am - q.astype(jnp.int32) * si
        best = jnp.where(k >= 0, jnp.maximum(best, q), best)
    return best


def _scaled_min_level(total, parent, min_, enable, level, cur_level, gp):
    """Device twin of ops/quota.scaled_min_level: AutoScaleMin for the
    groups at ``cur_level``. The host's float64 segment sums are exact
    f32 under the eligibility envelope; the one genuine f64 computation
    (the proportional floor-division) goes through _exact_floordiv."""
    import jax.numpy as jnp

    R = min_.shape[1]
    active = level == cur_level
    seg = jnp.where(parent >= 0, parent, gp)

    def seg_sum(mask):
        contrib = jnp.where((active & mask)[:, None], min_, 0.0)
        return jnp.zeros((gp + 1, R), jnp.float32).at[seg].add(contrib)

    en_sum = seg_sum(enable)
    dis_sum = seg_sum(~enable)
    seg_total = jnp.full((gp + 1, R), -jnp.inf, jnp.float32).at[seg].max(
        jnp.where(active[:, None], total, -jnp.inf))
    seg_total = jnp.where(jnp.isfinite(seg_total), seg_total, 0.0)

    need_scale = (en_sum + dis_sum) > seg_total
    avail = jnp.maximum(seg_total - dis_sum, 0.0)
    scaled = _exact_floordiv(avail[seg], min_, en_sum[seg])
    use = active[:, None] & enable[:, None] & need_scale[seg]
    return jnp.where(use, scaled, min_).astype(jnp.float32)


def _water_fill_level(total, parent, min_, guarantee, request, weight,
                      allow_lent, level, cur_level, gp):
    """Device twin of ops/quota.water_fill_level: one level of the
    iterated redistribution, the host's f32 op sequence transcribed with
    the data-dependent break as a lax.while_loop predicate (the body is
    idempotent once no group stays adjustable, so the padded bound never
    changes the fixpoint)."""
    import jax.numpy as jnp
    from jax import lax

    active = (level == cur_level)[:, None]
    eff_min = jnp.maximum(min_, guarantee)
    over = request > eff_min
    base = jnp.where(over, eff_min,
                     jnp.where(allow_lent[:, None], request, eff_min))
    base = jnp.where(active, base, 0.0)
    seg = jnp.where(parent >= 0, parent, gp)
    adjustable = over & active & (weight > 0)

    def seg_sum(x):
        return jnp.zeros((gp + 1, x.shape[1]), x.dtype).at[seg].add(x)

    spent = seg_sum(base)
    seg_total = jnp.full((gp + 1, total.shape[1]), -jnp.inf,
                         jnp.float32).at[seg].max(
        jnp.where(active, total, -jnp.inf))
    leftover = jnp.maximum(seg_total - spent, 0.0)
    leftover = jnp.where(jnp.isfinite(leftover), leftover, 0.0)

    def cond(carry):
        i, _runtime, adj, left = carry
        return (i < gp + 2) & jnp.any(adj) & jnp.any(left > 0)

    def body(carry):
        i, runtime, adj, left = carry
        w = jnp.where(adj, weight, 0.0)
        wsum = seg_sum(w)[seg]
        delta = jnp.where(
            (wsum > 0) & adj,
            jnp.floor(weight * left[seg] / jnp.maximum(wsum, 1e-9) + 0.5),
            0.0)
        new_rt = runtime + delta
        overshoot = jnp.maximum(new_rt - request, 0.0)
        # only adjustable rows clamp to request; a non-lent sibling sits
        # at eff_min > request and must keep it (host comment verbatim)
        new_rt = jnp.where(adj, jnp.minimum(new_rt, request), runtime)
        still = adj & (new_rt < request)
        left = seg_sum(jnp.where(adj, overshoot, 0.0))
        return i + 1, new_rt, still, left

    _, runtime, _, _ = lax.while_loop(
        cond, body, (0, base, adjustable, leftover))
    return jnp.where(active, runtime, 0.0).astype(jnp.float32)


def device_runtime_quotas(parent, level, q_min, q_max, weight, guarantee,
                          request, enable_scale, allow_lent, q_valid,
                          cluster_total, scale_min_enabled: bool = True):
    """Device twin of ops/quota.compute_runtime_quotas: the top-down
    level fold. Levels are a static Python loop over the bounded tree
    depth (MAX_QUOTA_DEPTH); levels past the real depth have no active
    rows and are no-ops, so ONE compiled program serves every tree."""
    import jax.numpy as jnp

    gp = parent.shape[0]
    total_row = cluster_total.astype(jnp.float32)
    runtime = jnp.zeros_like(q_min)
    # static bounded-depth unroll at trace time (the host fold's level
    # loop); every op inside is a traced array op
    # koordlint: disable=host-reconcile-in-colo-path
    for lvl in range(MAX_QUOTA_DEPTH + 1):
        total = jnp.where(
            (parent >= 0)[:, None],
            runtime[jnp.clip(parent, 0, gp - 1)],
            total_row[None, :])
        min_eff = (
            _scaled_min_level(total, parent, q_min, enable_scale, level,
                              lvl, gp)
            if scale_min_enabled else q_min)
        rt_lvl = _water_fill_level(total, parent, min_eff, guarantee,
                                   request, weight, allow_lent, level,
                                   lvl, gp)
        runtime = jnp.where((level == lvl)[:, None], rt_lvl, runtime)
    runtime = jnp.minimum(runtime, q_max).astype(jnp.float32)
    return jnp.where(q_valid[:, None], runtime, 0.0)


def build_colo_step(cpu_policy: str, memory_policy: str,
                    scale_min_enabled: bool = True, jit: bool = True):
    """Compile the colo tensor pass for a (cpu, memory) calculate-policy
    pair (the slo-config scalars — static so the policy pick lowers to a
    column select, exactly like the host kernel's static_argnames).

    The returned step takes padded arrays (pad nodes: all-zero rows with
    ``degraded`` False — batch/mid formula yields 0; pad quota rows:
    ``level`` -1 and ``q_valid`` False — never active at any level):

      node axis [N, R] f32: capacity, node_reserved, system_reserved,
        node_used, pod_all_used, hp_used, hp_request, hp_max,
        prod_reclaimable, reclaim_pct, mid_pct; degraded [N] bool
      quota axis: q_parent/q_level [G] i32, q_min/q_max/q_weight/
        q_guarantee/q_request/q_used [G, R] f32, q_allow_lent/
        q_enable_scale/q_valid [G] bool

    ``q_total_base`` is the cluster allocatable total with the four
    overcommit axes ZEROED: the runtime fold divides the PREDICTED
    post-writeback total — base axes from the store, batch/mid axes
    re-derived from this pass's own truncated columns — because in the
    host world the noderesource writeback lands BEFORE the revoke loop
    computes runtime, and the device pass must match that ordering
    inside one program.
    """
    import jax
    import jax.numpy as jnp

    def pick(by_usage, by_request, by_max, policy):
        if policy == POLICY_REQUEST:
            return by_request
        if policy == POLICY_MAX_USAGE_REQUEST:
            return by_max
        return by_usage

    def step(capacity, node_reserved, system_reserved, node_used,
             pod_all_used, hp_used, hp_request, hp_max, prod_reclaimable,
             reclaim_pct, mid_pct, degraded,
             q_parent, q_level, q_min, q_max, q_weight, q_guarantee,
             q_request, q_used, q_allow_lent, q_enable_scale, q_valid,
             q_total_base):
        # ---- batch/mid: slocontroller/noderesource._batch_mid_kernel,
        # the identical f32 op sequence (parity is bit-level)
        reclaimable_capacity = capacity * reclaim_pct / 100.0
        system_used = jnp.maximum(node_used - pod_all_used, 0.0)
        system_used = jnp.maximum(system_used, system_reserved)
        by_usage = jnp.maximum(
            reclaimable_capacity - node_reserved - system_used - hp_used,
            0.0)
        by_request = jnp.maximum(
            reclaimable_capacity - node_reserved - system_reserved
            - hp_request, 0.0)
        by_max = jnp.maximum(
            reclaimable_capacity - node_reserved - system_used - hp_max,
            0.0)
        batch = by_usage
        batch = batch.at[:, CPU].set(
            pick(by_usage, by_request, by_max, cpu_policy)[:, CPU])
        batch = batch.at[:, MEM].set(
            pick(by_usage, by_request, by_max, memory_policy)[:, MEM])
        batch = jnp.where(degraded[:, None], 0.0, batch)
        mid = jnp.minimum(prod_reclaimable, capacity * mid_pct / 100.0)
        mid = jnp.where(degraded[:, None], 0.0, jnp.maximum(mid, 0.0))

        # ---- predicted post-writeback cluster total: the writeback
        # publishes int(column) per node (truncation = floor for these
        # nonnegative values), so the new overcommit-axis totals are the
        # floored column sums — exact f32 under the eligibility envelope
        predicted_total = q_total_base
        # static 4-axis unroll at trace time
        # koordlint: disable=host-reconcile-in-colo-path
        for axis, col in ((BATCH_CPU_AXIS, batch[:, CPU]),
                          (BATCH_MEM_AXIS, batch[:, MEM]),
                          (MID_CPU_AXIS, mid[:, CPU]),
                          (MID_MEM_AXIS, mid[:, MEM])):
            predicted_total = predicted_total.at[axis].set(
                jnp.sum(jnp.floor(col)))

        # ---- quota runtime fold + the revoke-candidate mask
        runtime = device_runtime_quotas(
            q_parent, q_level, q_min, q_max, q_weight, q_guarantee,
            q_request, q_enable_scale, q_allow_lent, q_valid,
            predicted_total, scale_min_enabled=scale_min_enabled)
        revoke_over = jnp.maximum(q_used - runtime, 0.0) * jnp.where(
            q_valid[:, None], 1.0, 0.0)
        revoke_mask = jnp.any(revoke_over > 0, axis=-1) & q_valid

        return ColoOut(
            batch_cpu=batch[:, CPU], batch_mem=batch[:, MEM],
            mid_cpu=mid[:, CPU], mid_mem=mid[:, MEM],
            n_degraded=jnp.sum(degraded.astype(jnp.int32)),
            runtime=runtime, revoke_over=revoke_over,
            revoke_mask=revoke_mask, predicted_total=predicted_total)

    return jax.jit(step) if jit else step
