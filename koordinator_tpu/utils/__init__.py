"""Shared infrastructure: analog of reference `pkg/util/` + `pkg/features/`."""
