"""NUMA-affinity bitmasks: analog of reference `pkg/util/bitmask/bitmask.go`.

Used by the topology manager (frameworkext/topologymanager) to merge per-plugin NUMA
hints: masks are AND-ed across providers and the "narrowest" preferred mask wins.
Backed by a plain int; NUMA node count is small (K <= 8) so this is cheap on host,
and `ops/numa.py` enumerates all 2^K masks statically for the device-side admit.
"""

from __future__ import annotations

from typing import Iterable, List


class BitMask:
    __slots__ = ("_bits",)

    def __init__(self, bits: Iterable[int] = ()):  # noqa: D107
        v = 0
        for b in bits:
            if b < 0 or b >= 64:
                raise ValueError(f"bit {b} out of range")
            v |= 1 << b
        self._bits = v

    @staticmethod
    def from_int(v: int) -> "BitMask":
        m = BitMask()
        m._bits = v
        return m

    @staticmethod
    def fill(count: int) -> "BitMask":
        return BitMask(range(count))

    def and_(self, *others: "BitMask") -> "BitMask":
        v = self._bits
        for o in others:
            v &= o._bits
        return BitMask.from_int(v)

    def or_(self, *others: "BitMask") -> "BitMask":
        v = self._bits
        for o in others:
            v |= o._bits
        return BitMask.from_int(v)

    def count(self) -> int:
        return bin(self._bits).count("1")

    def is_set(self, bit: int) -> bool:
        return bool(self._bits >> bit & 1)

    def is_empty(self) -> bool:
        return self._bits == 0

    def is_narrower_than(self, other: "BitMask") -> bool:
        """Fewer set bits wins; tie broken by lower numeric value (reference
        bitmask.IsNarrowerThan: prefers masks with lower-numbered bits)."""
        if self.count() == other.count():
            return self._bits < other._bits
        return self.count() < other.count()

    def get_bits(self) -> List[int]:
        return [i for i in range(64) if self.is_set(i)]

    def to_int(self) -> int:
        return self._bits

    def __eq__(self, other: object) -> bool:
        return isinstance(other, BitMask) and self._bits == other._bits

    def __hash__(self) -> int:
        return hash(self._bits)

    def __repr__(self) -> str:
        return f"BitMask({self.get_bits()})"
