"""Cluster colocation strategy config: analog of `pkg/util/sloconfig/` +
`apis/configuration/`.

The slo-controller-config ConfigMap carries a cluster-wide ColocationStrategy
plus per-nodepool (node-selector) overrides; the nodeslo controller renders
per-node NodeSLO CRs from it and the noderesource controller reads the
thresholds/policies for the batch/mid calculations."""

from __future__ import annotations

import json
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

CONFIG_MAP_NAME = "slo-controller-config"
COLOCATION_CONFIG_KEY = "colocation-config"

# per-node colocation strategy metadata (apis/extension/node_colocation.go)
ANNOTATION_NODE_COLOCATION_STRATEGY = (
    "node.koordinator.sh/colocation-strategy")
LABEL_CPU_RECLAIM_RATIO = "node.koordinator.sh/cpu-reclaim-ratio"
LABEL_MEMORY_RECLAIM_RATIO = "node.koordinator.sh/memory-reclaim-ratio"

POLICY_USAGE = "usage"
POLICY_REQUEST = "request"
POLICY_MAX_USAGE_REQUEST = "maxUsageRequest"


@dataclass
class ColocationStrategy:
    """Defaults mirror sloconfig defaults (colocation_config.go)."""

    enable: bool = False
    cpu_reclaim_threshold_percent: int = 60
    memory_reclaim_threshold_percent: int = 65
    mid_cpu_threshold_percent: int = 10
    mid_memory_threshold_percent: int = 10
    degrade_time_minutes: int = 15
    update_time_threshold_seconds: int = 300
    resource_disk_reclaim_ratio: float = 0.0
    cpu_calculate_policy: str = POLICY_USAGE
    memory_calculate_policy: str = POLICY_USAGE
    metric_aggregate_duration_seconds: int = 300

    @staticmethod
    def from_dict(data: Dict) -> "ColocationStrategy":
        s = ColocationStrategy()
        mapping = {
            "enable": "enable",
            "cpuReclaimThresholdPercent": "cpu_reclaim_threshold_percent",
            "memoryReclaimThresholdPercent": "memory_reclaim_threshold_percent",
            "midCPUThresholdPercent": "mid_cpu_threshold_percent",
            "midMemoryThresholdPercent": "mid_memory_threshold_percent",
            "degradeTimeMinutes": "degrade_time_minutes",
            "updateTimeThresholdSeconds": "update_time_threshold_seconds",
            "cpuCalculatePolicy": "cpu_calculate_policy",
            "memoryCalculatePolicy": "memory_calculate_policy",
        }
        for k, attr in mapping.items():
            if k in data:
                setattr(s, attr, data[k])
        return s


@dataclass
class NodeStrategy:
    """Per-nodepool override: node label selector + strategy patch."""

    node_selector: Dict[str, str] = field(default_factory=dict)
    strategy: Dict = field(default_factory=dict)


@dataclass
class ColocationConfig:
    cluster_strategy: ColocationStrategy = field(default_factory=ColocationStrategy)
    node_strategies: List[NodeStrategy] = field(default_factory=list)

    _STRATEGY_KEYS = {
        "enable": "enable",
        "cpuReclaimThresholdPercent": "cpu_reclaim_threshold_percent",
        "memoryReclaimThresholdPercent": "memory_reclaim_threshold_percent",
        "midCPUThresholdPercent": "mid_cpu_threshold_percent",
        "midMemoryThresholdPercent": "mid_memory_threshold_percent",
        "degradeTimeMinutes": "degrade_time_minutes",
        "updateTimeThresholdSeconds": "update_time_threshold_seconds",
        "cpuCalculatePolicy": "cpu_calculate_policy",
        "memoryCalculatePolicy": "memory_calculate_policy",
    }

    def _merge_keys(self, merged: "ColocationStrategy",
                    data: Dict) -> "ColocationStrategy":
        patched = ColocationStrategy.from_dict(data)
        for k in data:
            attr = self._STRATEGY_KEYS.get(k)
            if attr:
                setattr(merged, attr, getattr(patched, attr))
        return merged

    def strategy_for_node(
        self, node_labels: Dict[str, str],
        node_annotations: Optional[Dict[str, str]] = None,
    ) -> ColocationStrategy:
        """Cluster strategy patched by the first matching node-pool
        strategy, then by per-node METADATA (sloconfig
        GetNodeColocationStrategy): the node colocation-strategy annotation
        merges the same keys, and the cpu/memory reclaim-ratio labels
        (float ratios) override the reclaim threshold percents last."""
        merged = self.cluster_strategy
        for ns in self.node_strategies:
            if all(node_labels.get(k) == v for k, v in ns.node_selector.items()):
                merged = replace(merged)
                merged = self._merge_keys(merged, ns.strategy)
                break
        # per-node metadata layer (node_colocation.go):
        ann = node_annotations or {}
        raw = ann.get(ANNOTATION_NODE_COLOCATION_STRATEGY)
        if raw:
            try:
                data = json.loads(raw)
                if isinstance(data, dict):
                    merged = self._merge_keys(replace(merged), data)
            except (ValueError, TypeError):
                pass
        for label, attr in (
            (LABEL_CPU_RECLAIM_RATIO, "cpu_reclaim_threshold_percent"),
            (LABEL_MEMORY_RECLAIM_RATIO, "memory_reclaim_threshold_percent"),
        ):
            raw = node_labels.get(label)
            if raw is None:
                continue
            try:
                ratio = float(raw)
            except (TypeError, ValueError):
                continue
            if 0 <= ratio <= 1:  # getNodeReclaimPercent bounds
                merged = replace(merged)
                setattr(merged, attr, ratio * 100.0)
        return merged


def parse_colocation_config(config_map_data: Dict[str, str]) -> Tuple[ColocationConfig, Optional[str]]:
    """Parse + validate the configmap payload; returns (config, error)."""
    raw = config_map_data.get(COLOCATION_CONFIG_KEY)
    if not raw:
        return ColocationConfig(), None
    try:
        data = json.loads(raw)
    except (ValueError, TypeError) as e:
        return ColocationConfig(), f"invalid colocation-config json: {e}"
    cfg = ColocationConfig(cluster_strategy=ColocationStrategy.from_dict(data))
    node_cfgs = data.get("nodeConfigs", [])
    if not isinstance(node_cfgs, list):
        return ColocationConfig(), (
            f"invalid colocation-config json: nodeConfigs must be a list, "
            f"got {type(node_cfgs).__name__}")
    for ns in node_cfgs:
        if not isinstance(ns, dict):
            return ColocationConfig(), (
                f"invalid colocation-config json: nodeConfigs entry must "
                f"be an object, got {type(ns).__name__}")
        cfg.node_strategies.append(
            NodeStrategy(
                node_selector=ns.get("nodeSelector", {}),
                strategy={k: v for k, v in ns.items() if k != "nodeSelector"},
            )
        )
    err = validate_colocation_config(cfg)
    return cfg, err


def validate_colocation_config(cfg: ColocationConfig) -> Optional[str]:
    """ConfigMap webhook validation analog (pkg/webhook/cm/)."""
    s = cfg.cluster_strategy
    for name, v in (
        ("cpuReclaimThresholdPercent", s.cpu_reclaim_threshold_percent),
        ("memoryReclaimThresholdPercent", s.memory_reclaim_threshold_percent),
        ("midCPUThresholdPercent", s.mid_cpu_threshold_percent),
        ("midMemoryThresholdPercent", s.mid_memory_threshold_percent),
    ):
        if not 0 <= v <= 100:
            return f"{name} must be in [0, 100], got {v}"
    if s.degrade_time_minutes <= 0:
        return "degradeTimeMinutes must be positive"
    return None


class ColocationConfigSource:
    """Hot-reloadable ColocationConfig: the slo-controller-config
    ConfigMap's colocation-config section, memoized on the ConfigMap's
    resourceVersion, falling back to the constructor-provided base when
    the map (or the key) is absent or fails validation — the reference
    controllers keep their last good config on a bad update.

    Shared by the NodeResourceController host oracle AND the colo pack
    (colo/pack.py), so a config hot-reload reaches the device pass's
    policy scalars through the SAME parsed object the oracle sees.
    ``epoch`` bumps whenever the effective config object changes — the
    pack keys its per-node strategy rows on it."""

    def __init__(self, store, base: Optional[ColocationConfig] = None):
        self.store = store
        self.base = base or ColocationConfig()
        self.epoch = 0
        self._rv_key: object = object()  # never matches the first get()
        self._effective = self.base

    def get(self) -> ColocationConfig:
        from koordinator_tpu.client.store import KIND_CONFIG_MAP

        cm = self.store.get(
            KIND_CONFIG_MAP, f"koordinator-system/{CONFIG_MAP_NAME}")
        key = (cm.meta.resource_version if cm is not None else None)
        if key == self._rv_key:
            return self._effective
        self._rv_key = key
        raw = cm.data.get(COLOCATION_CONFIG_KEY) if cm is not None else None
        if not raw:
            # the key (or the map) being ABSENT means "no cluster
            # config" — back to the constructor base, not an error
            effective = self.base
        else:
            cfg, err = parse_colocation_config(cm.data)
            # a malformed/invalid update keeps the LAST GOOD config: a
            # typo in the ConfigMap must not rewrite every node's batch
            # allocatable with defaults
            effective = self._effective if err else cfg
        if effective is not self._effective:
            self.epoch += 1
            self._effective = effective
        return self._effective
