"""Bounded parallel map: analog of reference `pkg/util/parallelize/parallelize.go`.

The reference fans Filter/Score out over nodes with a bounded goroutine pool. In the
TPU rebuild the hot fan-out is replaced by batched tensors; this helper remains for
host-side control work (informer callbacks, per-node controller reconciles) where
thread parallelism still applies (I/O bound).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")

DEFAULT_PARALLELISM = 16


def parallelize_until(
    pieces: int, do_work: Callable[[int], None], parallelism: int = DEFAULT_PARALLELISM
) -> None:
    """Run do_work(i) for i in [0, pieces) on a bounded pool (errors propagate)."""
    if pieces <= 0:
        return
    workers = min(parallelism, pieces)
    if workers <= 1:
        for i in range(pieces):
            do_work(i)
        return
    with ThreadPoolExecutor(max_workers=workers) as pool:
        for f in [pool.submit(do_work, i) for i in range(pieces)]:
            f.result()


def parallel_map(
    items: Sequence[T],
    fn: Callable[[T], R],
    parallelism: int = DEFAULT_PARALLELISM,
) -> List[R]:
    out: List[R] = [None] * len(items)  # type: ignore[list-item]

    def work(i: int) -> None:
        out[i] = fn(items[i])

    parallelize_until(len(items), work, parallelism)
    return out
