"""CPU set algebra: analog of reference `pkg/util/cpuset/cpuset.go`.

Parses/serializes the Linux list format ("0-3,7,9-11") and provides set operations
used by the NUMA-resource plugin's cpu accumulator and koordlet's cpuset hooks.
Immutable, backed by frozenset.
"""

from __future__ import annotations

from typing import FrozenSet, Iterable, Iterator, List


class CPUSet:
    __slots__ = ("_cpus",)

    def __init__(self, cpus: Iterable[int] = ()):  # noqa: D107
        self._cpus: FrozenSet[int] = frozenset(int(c) for c in cpus)

    # -- constructors -------------------------------------------------------
    @staticmethod
    def parse(s: str) -> "CPUSet":
        """Parse Linux cpu list format; empty string -> empty set."""
        s = s.strip()
        if not s:
            return CPUSet()
        out: List[int] = []
        for part in s.split(","):
            part = part.strip()
            if not part:
                continue
            if "-" in part:
                lo_s, hi_s = part.split("-", 1)
                lo, hi = int(lo_s), int(hi_s)
                if hi < lo:
                    raise ValueError(f"invalid cpu range {part!r}")
                out.extend(range(lo, hi + 1))
            else:
                out.append(int(part))
        return CPUSet(out)

    # -- set algebra --------------------------------------------------------
    def union(self, other: "CPUSet") -> "CPUSet":
        return CPUSet(self._cpus | other._cpus)

    def intersection(self, other: "CPUSet") -> "CPUSet":
        return CPUSet(self._cpus & other._cpus)

    def difference(self, other: "CPUSet") -> "CPUSet":
        return CPUSet(self._cpus - other._cpus)

    def is_subset_of(self, other: "CPUSet") -> bool:
        return self._cpus <= other._cpus

    def contains(self, cpu: int) -> bool:
        return cpu in self._cpus

    # -- views --------------------------------------------------------------
    def to_list(self) -> List[int]:
        return sorted(self._cpus)

    def __len__(self) -> int:
        return len(self._cpus)

    def __iter__(self) -> Iterator[int]:
        return iter(sorted(self._cpus))

    def __bool__(self) -> bool:
        return bool(self._cpus)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, CPUSet) and self._cpus == other._cpus

    def __hash__(self) -> int:
        return hash(self._cpus)

    def __repr__(self) -> str:
        return f"CPUSet({self.format()!r})"

    def format(self) -> str:
        """Serialize to Linux list format with collapsed ranges."""
        cpus = self.to_list()
        if not cpus:
            return ""
        parts: List[str] = []
        start = prev = cpus[0]
        for c in cpus[1:] + [None]:  # type: ignore[list-item]
            if c is not None and c == prev + 1:
                prev = c
                continue
            parts.append(str(start) if start == prev else f"{start}-{prev}")
            if c is not None:
                start = prev = c
        return ",".join(parts)
