"""Feature gates: analog of reference `pkg/features/`.

Three gate sets, as in the reference: manager/webhook gates (features.go:28-86),
koordlet gates (koordlet_features.go:33-129), and scheduler gates. Each gate has a
default and can be flipped via `set_from_map` (the flag-parsing entry point).
"""

from __future__ import annotations

import threading
from typing import Dict, Mapping


class FeatureGate:
    def __init__(self, defaults: Mapping[str, bool]):
        self._lock = threading.Lock()
        self._defaults = dict(defaults)
        self._overrides: Dict[str, bool] = {}

    def enabled(self, name: str) -> bool:
        with self._lock:
            if name in self._overrides:
                return self._overrides[name]
            return self._defaults.get(name, False)

    def known(self, name: str) -> bool:
        return name in self._defaults

    def set_from_map(self, values: Mapping[str, bool]) -> None:
        with self._lock:
            for k, v in values.items():
                if k not in self._defaults:
                    raise ValueError(f"unknown feature gate {k!r}")
                self._overrides[k] = bool(v)

    def reset(self) -> None:
        with self._lock:
            self._overrides.clear()


# Manager/webhook gates (reference pkg/features/features.go:28-52)
MANAGER_GATES = FeatureGate(
    {
        "PodMutatingWebhook": True,
        "PodValidatingWebhook": True,
        "ElasticQuotaMutatingWebhook": True,
        "ElasticQuotaValidatingWebhook": True,
        "NodeMutatingWebhook": False,
        "NodeValidatingWebhook": False,
        "ConfigMapValidatingWebhook": False,
        "WebhookFramework": True,
        "ColocationProfileSkipMutatingResources": False,
        "MultiQuotaTree": True,
        "ElasticQuotaIgnorePodOverhead": False,
        "ElasticQuotaImmutableAnnotations": False,
    }
)

# koordlet gates (reference pkg/features/koordlet_features.go:33-129)
KOORDLET_GATES = FeatureGate(
    {
        "AuditEvents": False,
        "AuditEventsHTTPHandler": False,
        "BECPUSuppress": True,
        "BECPUEvict": False,
        "BEMemoryEvict": False,
        "CPUBurst": False,
        "SystemConfig": False,
        "RdtResctrl": True,
        "CgroupReconcile": False,
        "NodeMetricControl": True,
        "NodeTopologyReport": True,
        "Libpfm4": False,
        "CPICollector": False,
        "PSICollector": True,
        "CPUSuppress": True,
        "CgroupV2": True,
        "ColdPageCollector": False,
        "PageCacheCollector": True,
        "CoreSched": False,
        "BlkIOReconcile": False,
        "TerwayQoS": False,
        # off by default: the TPU sampler initializes the JAX runtime, which
        # takes exclusive chip ownership the workload pods need
        "TPUDeviceCollector": False,
    }
)

# scheduler-side gates
SCHEDULER_GATES = FeatureGate(
    {
        "BatchedTPUKernel": True,       # offload filter/score to the JAX kernel
        "CompiledSerialParity": True,   # exact serial-parity selection loop on device
        "ResizePod": False,
        "DisableDefaultQuota": False,
        # event-driven incremental snapshot packing + device-resident
        # arrays (scheduler/snapshot_cache.py); off = full rebuild per cycle
        "IncrementalSnapshot": True,
    }
)
