"""Decaying histogram: analog of reference `pkg/util/histogram/` (VPA-style).

Used by koordlet's peak-usage predictor (pkg/koordlet/prediction/peak_predictor.go):
samples are added with exponentially-decaying weight (half-life), percentiles are read
from bucket boundaries. Exponential bucket scheme mirrors the reference's
NewExponentialHistogramOptions(maxValue, firstBucketSize, ratio, epsilon).

TPU note: histograms stay on host — they are tiny (O(100) buckets per UID) and feed
the Mid-tier resource calculation; the batched math consumes only their percentile
outputs.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional


@dataclass
class HistogramOptions:
    num_buckets: int
    bucket_start: List[float]  # lower bound of each bucket, ascending
    epsilon: float = 1e-4

    @staticmethod
    def exponential(
        max_value: float, first_bucket_size: float, ratio: float, epsilon: float = 1e-4
    ) -> "HistogramOptions":
        if max_value <= 0 or first_bucket_size <= 0 or ratio <= 1:
            raise ValueError("invalid exponential histogram options")
        num = 1 + int(
            math.ceil(
                math.log(max_value * (ratio - 1) / first_bucket_size + 1)
                / math.log(ratio)
            )
        )
        starts = [0.0]
        for i in range(1, num):
            starts.append(first_bucket_size * (ratio**i - 1) / (ratio - 1))
        return HistogramOptions(num_buckets=num, bucket_start=starts, epsilon=epsilon)

    @staticmethod
    def linear(max_value: float, bucket_size: float, epsilon: float = 1e-4) -> "HistogramOptions":
        num = 1 + int(math.ceil(max_value / bucket_size))
        return HistogramOptions(
            num_buckets=num,
            bucket_start=[i * bucket_size for i in range(num)],
            epsilon=epsilon,
        )

    def find_bucket(self, value: float) -> int:
        if value < self.bucket_start[0]:
            return 0
        lo, hi = 0, self.num_buckets - 1
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if self.bucket_start[mid] <= value:
                lo = mid
            else:
                hi = mid - 1
        return lo


class DecayingHistogram:
    """Histogram whose sample weights decay with half-life anchored at a reference
    time, matching the reference's decayingHistogram: weight(t) = 2^((t-t0)/halflife).
    """

    def __init__(self, options: HistogramOptions, half_life_seconds: float = 86400.0):
        self.options = options
        self.half_life = half_life_seconds
        self.weights = [0.0] * options.num_buckets
        self.total_weight = 0.0
        self.reference_time = 0.0

    def _decay_factor(self, timestamp: float) -> float:
        return 2.0 ** ((timestamp - self.reference_time) / self.half_life)

    def _shift_reference(self, timestamp: float) -> None:
        # keep exponents small by re-anchoring when drifting > half_life
        if timestamp - self.reference_time < self.half_life:
            return
        shift = 2.0 ** ((self.reference_time - timestamp) / self.half_life)
        self.weights = [w * shift for w in self.weights]
        self.total_weight *= shift
        self.reference_time = timestamp

    def add_sample(self, value: float, weight: float, timestamp: float) -> None:
        self._shift_reference(timestamp)
        w = weight * self._decay_factor(timestamp)
        b = self.options.find_bucket(value)
        self.weights[b] += w
        self.total_weight += w

    def percentile(self, p: float) -> float:
        """Return the upper bound of the bucket at cumulative fraction p (0..1);
        empty histogram -> 0 (matching reference Percentile)."""
        if self.is_empty():
            return 0.0
        threshold = p * self.total_weight
        acc = 0.0
        b = 0
        for i, w in enumerate(self.weights):
            acc += w
            b = i
            if acc >= threshold:
                break
        if b < self.options.num_buckets - 1:
            return self.options.bucket_start[b + 1]
        return self.options.bucket_start[b]

    def is_empty(self) -> bool:
        return self.total_weight < self.options.epsilon

    # -- checkpointing (prediction/checkpoint.go:36-95) ---------------------
    def to_checkpoint(self) -> dict:
        return {
            "weights": list(self.weights),
            "total_weight": self.total_weight,
            "reference_time": self.reference_time,
            "half_life": self.half_life,
        }

    @staticmethod
    def from_checkpoint(options: HistogramOptions, data: dict) -> "DecayingHistogram":
        weights = data.get("weights", [])
        if len(weights) != options.num_buckets:
            raise ValueError(
                f"checkpoint has {len(weights)} buckets, options expect "
                f"{options.num_buckets}; refusing to restore"
            )
        h = DecayingHistogram(options, data.get("half_life", 86400.0))
        h.weights = [float(w) for w in weights]
        h.total_weight = float(data.get("total_weight", 0.0))
        h.reference_time = float(data.get("reference_time", 0.0))
        return h
