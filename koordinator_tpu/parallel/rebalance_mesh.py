"""Mesh-sharded rebalance pass: the descheduler's production promotion.

The ROADMAP's "teach the descheduler's 2-D score-matrix mode the same
production promotion": ``build_rebalance_step`` (balance/step.py) jitted
over the device mesh. Node-axis inputs (usage/metric columns + the rhs
limbs) arrive SHARDED flat over every device — the DeviceSnapshot
places them via ``put_on_mesh`` under the same NamedShardings the
scheduler's node arrays use (snapshot_cache._mesh_node_fields includes
the ``rb_*`` node fields) — pod arrays replicate, and every output pins
REPLICATED so the compacted (node_idx, pod_idx, score) readback holds
the host victim order on every shard. Same program, same math: byte
parity with the single-device pass is gated by
``pipeline_parity.run_rebalance_parity`` at 1/2/4/8 devices.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from koordinator_tpu.balance.step import build_rebalance_step


def build_sharded_rebalance_step(max_evict_per_node: int, mesh: Mesh):
    """The rebalance pass jitted with replicated out_shardings over
    ``mesh``. Inputs keep whatever placement the DeviceSnapshot upload
    committed them to (node fields sharded, pod fields replicated);
    XLA lowers the node-axis classification shard-locally and inserts
    the candidate-sort collectives."""
    raw = build_rebalance_step(max_evict_per_node, jit=False)
    rep = NamedSharding(mesh, P())
    return jax.jit(raw, out_shardings=rep)
