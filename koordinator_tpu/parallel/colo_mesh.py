"""Mesh-sharded colo pass: the control plane's production promotion.

``build_colo_step`` (colo/step.py) jitted over the device mesh — the
THIRD consumer of the mesh-backed DeviceSnapshot. Node-axis inputs (the
NodeResource pipeline columns + the degrade mask) arrive SHARDED flat
over every device — the DeviceSnapshot places them via ``put_on_mesh``
under the same NamedShardings the scheduler's node arrays use
(snapshot_cache._mesh_node_fields includes the ``colo_*`` node fields)
— the quota-tree arrays replicate (control-plane scale), and every
output pins REPLICATED so the batch/mid columns, the runtime matrix and
the revoke mask read back whole on every shard. Same program, same
math: decision parity with the single-device pass AND the host oracles
is gated by ``pipeline_parity.run_colo_parity`` at 1/2/4/8 devices.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from koordinator_tpu.colo.step import build_colo_step


def build_sharded_colo_step(cpu_policy: str, memory_policy: str,
                            mesh: Mesh):
    """The colo pass jitted with replicated out_shardings over ``mesh``.
    Inputs keep whatever placement the DeviceSnapshot upload committed
    them to (node fields sharded, quota fields replicated); XLA lowers
    the node-axis batch/mid math shard-locally and inserts the
    column-sum / segment-op collectives for the predicted total and the
    quota fold."""
    raw = build_colo_step(cpu_policy, memory_policy, jit=False)
    rep = NamedSharding(mesh, P())
    return jax.jit(raw, out_shardings=rep)
