"""Multi-chip scaling via jax.sharding Mesh + XLA collectives.

The reference scales the pod x node evaluation with per-node goroutine fan-out and
leader-elected replicas (SURVEY.md section 5.7-5.8). Here the same scaling rides
the device mesh: node-state tensors shard over the "nodes" mesh axis (the analog of
the per-node fan-out, now across chips over ICI), pod batches shard over "pods" for
the one-shot matrix/rebalance mode, and XLA inserts the argmax/reduce collectives.
Multi-host extends the same mesh over DCN (jax distributed initialization) — no
NCCL/MPI analog needed.
"""

from koordinator_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    merge_readback,
    mesh_from_env,
    mesh_row_layout,
    pad_for_sharding,
    put_on_mesh,
    shard_inputs_nodewise,
    shard_inputs_2d,
    build_sharded_schedule_step,
    build_sharded_score_matrix,
)
from koordinator_tpu.parallel.full_chain_mesh import (  # noqa: F401
    build_sharded_chained_wave_step,
    build_sharded_fused_wave_step,
    build_sharded_full_chain_step,
    shard_full_chain_inputs,
    wave_carry_shardings,
    wave_side_shardings,
)
from koordinator_tpu.parallel.rebalance_mesh import (  # noqa: F401
    build_sharded_rebalance_step,
)
from koordinator_tpu.parallel.colo_mesh import (  # noqa: F401
    build_sharded_colo_step,
)
