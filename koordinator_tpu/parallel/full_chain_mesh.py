"""Sharded FULL plugin-chain step: the flagship kernel over a device mesh.

Distributes the fused chain of models/full_chain.py — gang PreFilter, quota
admission, Fit/LoadAware/cpuset/NUMA filters, LoadAware+NUMA scoring, serial
Reserve, gang Permit barrier — the same way the base serial-parity step is
distributed (parallel/mesh.py): the distributed analog of the reference's
per-node goroutine fan-out at
/root/reference/pkg/scheduler/frameworkext/framework_extender.go:204.

Layout:
  * node-axis state sharded over ALL mesh devices ("pods"+"nodes" axes flat):
    allocatable/requested/usage [N, R], NUMA free/capacity [N, K, R], cpuset
    bind state [N] — each fori_loop iteration's filter+score row is computed
    shard-locally and the argmax reduces across shards (ICI all-reduce).
  * pod arrays replicated ([P, ...] is small: the batch, not the cluster).
  * quota tree replicated ([G, R] is tiny); the order-dependent admission check
    and used-rollup run identically on every shard, so the carried quota state
    never needs a collective.
  * gang arrays replicated; the Permit barrier is a segment reduction over the
    replicated `chosen` vector, computed post-loop on every shard.

Bindings are bit-identical to the single-device step at any mesh size: the
per-shard score rows are the same values the unsharded kernel computes, and
argmax tie-breaking (lowest node index) is preserved by XLA's cross-shard
argmax reduction over the global index space.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from koordinator_tpu.models.full_chain import (
    FullChainInputs,
    build_full_chain_step,
)
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.parallel.mesh import (
    _node_axis_spec,
    put_on_mesh,
    shard_inputs_nodewise,
)

# FullChainInputs fields indexed [N, ...] (sharded); everything else (pods,
# quota tree, gangs) is replicated.
_FC_NODE_FIELDS = frozenset(
    {
        "numa_free",
        "numa_capacity",
        "numa_policy",
        "has_topology",
        "bind_free",
        "cpus_per_core",
        "node_taint_group",
        "aff_dom",
        "aff_count",
        "anti_cover",
        "pref_scores",
        "port_used",
        "vol_free",
        "node_vol_group",
        "img_scores",
    }
)


def shard_full_chain_inputs(fc: FullChainInputs, mesh: Mesh) -> FullChainInputs:
    """Place FullChainInputs on the mesh: node state sharded over all devices,
    pod/quota/gang state replicated."""
    node_spec = _node_axis_spec(mesh, flat=True)
    base = shard_inputs_nodewise(fc.base, mesh)

    def put(name, arr):
        spec = node_spec if name in _FC_NODE_FIELDS else P()
        return put_on_mesh(arr, NamedSharding(mesh, spec))

    rest = {k: put(k, v) for k, v in fc._asdict().items() if k != "base"}
    return FullChainInputs(base=base, **rest)


def build_sharded_full_chain_step(
    args: LoadAwareArgs,
    num_gangs: int,
    num_groups: int,
    mesh: Mesh,
    active_axes=None,
):
    """Full-chain step jitted with node-sharded in/out shardings.

    Same contract as build_full_chain_step:
    FullChainInputs -> (chosen[P], requested[N, R], quota_used[G, R]).
    """
    raw = build_full_chain_step(
        args, num_gangs, num_groups, jit=False, active_axes=active_axes
    )
    node_spec = _node_axis_spec(mesh, flat=True)
    out_shardings = (
        NamedSharding(mesh, P()),          # chosen [P] replicated
        NamedSharding(mesh, node_spec),    # requested [N, R] node-sharded
        NamedSharding(mesh, P()),          # quota_used [G, R] replicated
    )
    return jax.jit(raw, out_shardings=out_shardings)
