"""Sharded FULL plugin-chain step: the flagship kernel over a device mesh.

Distributes the fused chain of models/full_chain.py — gang PreFilter, quota
admission, Fit/LoadAware/cpuset/NUMA filters, LoadAware+NUMA scoring, serial
Reserve, gang Permit barrier — the same way the base serial-parity step is
distributed (parallel/mesh.py): the distributed analog of the reference's
per-node goroutine fan-out at
/root/reference/pkg/scheduler/frameworkext/framework_extender.go:204.

Layout:
  * node-axis state sharded over ALL mesh devices ("pods"+"nodes" axes flat):
    allocatable/requested/usage [N, R], NUMA free/capacity [N, K, R], cpuset
    bind state [N] — each fori_loop iteration's filter+score row is computed
    shard-locally and the argmax reduces across shards (ICI all-reduce).
  * pod arrays replicated ([P, ...] is small: the batch, not the cluster).
  * quota tree replicated ([G, R] is tiny); the order-dependent admission check
    and used-rollup run identically on every shard, so the carried quota state
    never needs a collective.
  * gang arrays replicated; the Permit barrier is a segment reduction over the
    replicated `chosen` vector, computed post-loop on every shard.

Bindings are bit-identical to the single-device step at any mesh size: the
per-shard score rows are the same values the unsharded kernel computes, and
argmax tie-breaking (lowest node index) is preserved by XLA's cross-shard
argmax reduction over the global index space.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from koordinator_tpu.models.full_chain import (
    ExplainOut,
    FullChainInputs,
    build_full_chain_step,
)
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.parallel.mesh import (
    _node_axis_spec,
    put_on_mesh,
    shard_inputs_nodewise,
)

# FullChainInputs fields indexed [N, ...] (sharded); everything else (pods,
# quota tree, gangs) is replicated.
_FC_NODE_FIELDS = frozenset(
    {
        "numa_free",
        "numa_capacity",
        "numa_policy",
        "has_topology",
        "bind_free",
        "cpus_per_core",
        "node_taint_group",
        "aff_dom",
        "aff_count",
        "anti_cover",
        "pref_scores",
        "port_used",
        "vol_free",
        "node_vol_group",
        "img_scores",
    }
)


def shard_full_chain_inputs(fc: FullChainInputs, mesh: Mesh) -> FullChainInputs:
    """Place FullChainInputs on the mesh: node state sharded over all devices,
    pod/quota/gang state replicated."""
    node_spec = _node_axis_spec(mesh, flat=True)
    base = shard_inputs_nodewise(fc.base, mesh)

    def put(name, arr):
        spec = node_spec if name in _FC_NODE_FIELDS else P()
        return put_on_mesh(arr, NamedSharding(mesh, spec))

    rest = {k: put(k, v) for k, v in fc._asdict().items() if k != "base"}
    return FullChainInputs(base=base, **rest)


def build_sharded_full_chain_step(
    args: LoadAwareArgs,
    num_gangs: int,
    num_groups: int,
    mesh: Mesh,
    active_axes=None,
    explain=None,
):
    """Full-chain step jitted with node-sharded in/out shardings.

    Same contract as build_full_chain_step:
    FullChainInputs -> (chosen[P], requested[N, R], quota_used[G, R]),
    plus the ExplainOut 4th output (and the extra ``n_real`` operand) when
    ``explain`` is "counts"/"full" — attribution arrays are pod-axis and
    come back replicated, so the readback merge sees one packed buffer.
    """
    raw = build_full_chain_step(
        args, num_gangs, num_groups, jit=False, active_axes=active_axes,
        explain=explain,
    )
    node_spec = _node_axis_spec(mesh, flat=True)
    rep = NamedSharding(mesh, P())
    out_shardings = (
        rep,                               # chosen [P] replicated
        NamedSharding(mesh, node_spec),    # requested [N, R] node-sharded
        rep,                               # quota_used [G, R] replicated
    )
    if explain is not None:
        # ExplainOut(stage_counts[P, S], terms[P, T] | None): pod-axis,
        # replicated. terms is None below "full" — a pytree NON-leaf, so
        # its sharding slot must be None too or the structures mismatch.
        out_shardings = out_shardings + (
            ExplainOut(rep, rep if explain == "full" else None),)
    return jax.jit(raw, out_shardings=out_shardings)


def build_sharded_fused_wave_step(
    args: LoadAwareArgs,
    num_gangs: int,
    num_groups: int,
    waves: int,
    mesh: Mesh,
    active_axes=None,
    explain=None,
    prod: bool = False,
    claims: bool = False,
    res: bool = False,
    score_passes=(),
):
    """Fused multi-wave step (models/fused_waves.py) jitted over the mesh.

    Same contract as build_fused_wave_step — (FullChainInputs,
    WaveSideInputs) -> FusedWaveOut (+ ExplainOut under koordexplain) —
    with the node-axis carried state sharded exactly like the serial mesh
    step: each wave's filter/score rows compute shard-locally, the argmax
    reduces over ICI, and `commit_pod_state`'s node-row updates stay on
    the owning shard. The compacted (pod, node, zone, res) readback
    buffers are pod-axis and pinned REPLICATED, so the host merge sees
    one packed buffer identical on every shard
    (parallel/mesh.merge_readback). The PR 14 carried extensions follow
    the same split: prod est/adj and hot-claim coverage are node-axis,
    claim membership and reservation rows replicate
    (``wave_side_shardings``).
    """
    from koordinator_tpu.models.fused_waves import (
        FusedWaveOut,
        build_fused_wave_step,
    )

    raw = build_fused_wave_step(
        args, num_gangs, num_groups, waves=waves, jit=False,
        active_axes=active_axes, explain=explain,
        prod=prod, claims=claims, res=res, score_passes=score_passes,
    )
    rep = NamedSharding(mesh, P())
    fw_out = FusedWaveOut(rep, rep, rep, rep, rep, rep)
    if explain is None:
        out_shardings = fw_out
    else:
        out_shardings = (
            fw_out, ExplainOut(rep, rep if explain == "full" else None))
    return jax.jit(raw, out_shardings=out_shardings)


def wave_carry_shardings(mesh: Mesh, explain=None, prod: bool = False,
                         claims: bool = False, res: bool = False):
    """Shardings for the chained wave step's carry tuple: node-axis state
    slots sharded flat over the mesh (the same layout the fused carry has
    inside the sharded while_loop), pod/quota/gang/reservation/term slots
    replicated, feature-absent slots None (matching the carry's leafless
    pytree holes). Used both for the step's out_shardings (so the carried
    state never leaves its shard between wave dispatches) and by the
    driver to place the few host-created wave-0 slots (put_on_mesh)."""
    from koordinator_tpu.models.fused_waves import (
        NUM_WAVE_STATE,
        WAVE_STATE_FIELDS,
        WAVE_STATE_NODE_SLOTS,
    )

    node = NamedSharding(mesh, _node_axis_spec(mesh, flat=True))
    rep = NamedSharding(mesh, P())
    present = {
        "est_sum_prod": prod,
        "claim_new": claims,
        "vol_new": claims,
        "res_avail": res,
        "res_remain": res,
        "res_node": res,
        "res_succ": res,
    }
    carry = tuple(
        (None if not present.get(WAVE_STATE_FIELDS[i], True)
         else node if i in WAVE_STATE_NODE_SLOTS else rep)
        for i in range(NUM_WAVE_STATE))
    if explain == "full":
        carry = carry + (rep,)  # per-pod score-term rows
    return carry


def wave_side_shardings(mesh: Mesh, prod: bool = False,
                        claims: bool = False, res: bool = False):
    """Sharding pytree for WaveSideInputs: [N, ...] operands follow the
    flat node sharding, pod-axis/reservation operands replicate."""
    from koordinator_tpu.models.fused_waves import (
        ClaimSides,
        ProdSides,
        ResSides,
        WaveSideInputs,
    )

    node = NamedSharding(mesh, _node_axis_spec(mesh, flat=True))
    rep = NamedSharding(mesh, P())
    return WaveSideInputs(
        la_est=node,
        la_adj=node,
        prod=ProdSides(est=node, adj=node) if prod else None,
        claims=(ClaimSides(pod_claim=rep, pod_nonhot=rep, covered0=node)
                if claims else None),
        res=(ResSides(owner_match=rep, rank=rep, alloc=rep, once=rep,
                      row_of=rep, pod_slot=rep, nominate_ok=rep)
             if res else None),
    )


def build_sharded_chained_wave_step(
    args: LoadAwareArgs,
    num_gangs: int,
    num_groups: int,
    mesh: Mesh,
    active_axes=None,
    explain=None,
    prod: bool = False,
    claims: bool = False,
    res: bool = False,
    score_passes=(),
):
    """One chained wave (models/fused_waves.build_chained_wave_step)
    jitted over the mesh: the overlapped-replay dispatch unit.

    The carry's node-axis slots are pinned to the flat node sharding on
    OUTPUT, so chaining dispatches keeps every wave's filter/score rows
    shard-local with no resharding between waves; the per-wave compacted
    (pod, node, zone, res) rows come back replicated for the host merge
    (parallel/mesh.merge_readback), exactly like the fused step's
    buffers."""
    from koordinator_tpu.models.fused_waves import (
        WaveChainOut,
        build_chained_wave_step,
    )

    raw = build_chained_wave_step(
        args, num_gangs, num_groups, jit=False,
        active_axes=active_axes, explain=explain,
        prod=prod, claims=claims, res=res, score_passes=score_passes,
    )
    rep = NamedSharding(mesh, P())
    rows = WaveChainOut(rep, rep, rep, rep, rep)
    out_shardings = (
        wave_carry_shardings(mesh, explain=explain, prod=prod,
                             claims=claims, res=res),
        rows,
    )
    if explain is not None:
        out_shardings = out_shardings + (rep,)  # this wave's counts row
    return jax.jit(raw, out_shardings=out_shardings)
