"""Mesh layout and sharded scheduling steps.

Two shardings cover the framework's compute:

  1. serial-parity step: node-state arrays ([N, R], [N]) sharded over ALL devices
     on the "nodes" axis; pod arrays replicated. Each fori_loop iteration's
     filter/score row is computed shard-locally; the argmax reduces across shards
     (XLA all-reduce over ICI). This preserves exact serial semantics at any mesh
     size — the distributed analog of kube-scheduler's per-node fan-out.

  2. score-matrix / rebalance: 2-D mesh ("pods", "nodes"); the [P, N] score matrix
     shards over both axes — full SPMD for the descheduler's 50k-pod global
     rebalance (BASELINE.md config 5) and throughput mode.

Multi-host: the same code runs under `jax.distributed.initialize()`; mesh axes laid
out so "nodes" stays within a slice (ICI) and "pods" may span slices (DCN), since
the pods axis only needs its collectives at the final argmax/top-k. Exercised by
`tests/test_multihost.py`: two OS processes x 4 virtual CPU devices federate into
one 8-device mesh and run the sharded full-chain step with gloo collectives
crossing the process boundary, bit-identical to single-device.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from koordinator_tpu.models.scheduler_model import (
    ScheduleInputs,
    build_schedule_step,
    build_score_matrix,
)
from koordinator_tpu.ops.loadaware import LoadAwareArgs

logger = logging.getLogger(__name__)


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """2-D mesh ("pods", "nodes"); the nodes axis gets the larger factor (node
    count exceeds pending-pod count in the target configs)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    pods_dim = 1
    for f in range(int(np.sqrt(n)), 0, -1):
        if n % f == 0:
            pods_dim = f
            break
    nodes_dim = n // pods_dim
    dev_array = np.array(devices).reshape(pods_dim, nodes_dim)
    return Mesh(dev_array, axis_names=("pods", "nodes"))


def mesh_from_env(env_value: Optional[str] = None) -> Optional[Mesh]:
    """KOORD_TPU_MESH=<ndev>|auto selects the production mesh-backed
    dispatch path (scheduler/cycle.py): "auto" takes every visible device,
    an integer takes a prefix of `jax.devices()`. Unset/0/1-device-visible
    "auto"/"off" return None — the single-device path. A request for more
    devices than exist fails loudly (a silently-smaller mesh would make
    capacity planning lie)."""
    import os

    raw = (os.environ.get("KOORD_TPU_MESH", "") if env_value is None
           else str(env_value)).strip().lower()
    if raw in ("", "0", "off", "false", "no"):
        return None
    devices = jax.devices()
    if raw == "auto":
        if len(devices) < 2:
            return None
        return make_mesh(devices)
    try:
        n = int(raw)
    except ValueError:
        logger.warning("KOORD_TPU_MESH=%r not an int or 'auto'; "
                       "mesh dispatch stays off", raw)
        return None
    if n <= 1:
        # a 1-device mesh is still a valid mesh (the parity gates use it);
        # pin it explicitly with KOORD_TPU_MESH=1
        if raw == "1":
            return make_mesh(devices[:1])
        return None
    if n > len(devices):
        raise ValueError(
            f"KOORD_TPU_MESH={n} but only {len(devices)} devices visible")
    return make_mesh(devices[:n])


def surviving_submesh(mesh: Mesh, lost_device_ids) -> Optional[Mesh]:
    """The partial-mesh rung's submesh (koordguard): the configured mesh
    minus the devices a dispatch fault was attributed to, re-factored
    2-D by ``make_mesh``. Non-divisible node axes re-pad through the
    existing ``pad_for_sharding`` on upload, so any survivor count is a
    valid mesh. The scheduler records losses only while survivors
    remain, so its calls never see the defensive None (returned when
    nothing survives) — a caller that can reach it must drop to its
    single-device rung itself."""
    lost = {int(i) for i in lost_device_ids}
    survivors = [d for d in mesh.devices.flat if d.id not in lost]
    if not survivors:
        return None
    return make_mesh(survivors)


def _node_axis_spec(mesh: Mesh, flat: bool) -> P:
    # serial mode shards nodes over every device (both mesh axes)
    return P(("pods", "nodes")) if flat else P("nodes")


def _shard_counts(sharding: NamedSharding, ndim: int) -> Tuple[int, ...]:
    """Shards per dimension a NamedSharding splits an ndim-array into."""
    spec = sharding.spec
    sizes = dict(sharding.mesh.shape)
    counts = []
    for d in range(ndim):
        entry = spec[d] if d < len(spec) else None
        if entry is None:
            counts.append(1)
        elif isinstance(entry, (tuple, list)):
            f = 1
            for ax in entry:
                f *= sizes[ax]
            counts.append(f)
        else:
            counts.append(sizes[entry])
    return tuple(counts)


def pad_for_sharding(arr: np.ndarray, sharding: NamedSharding) -> np.ndarray:
    """Zero-pad each sharded dimension up to the next multiple of its shard
    count, so callers never pre-quantize axis sizes to the mesh factor.

    Zero rows reproduce the snapshot build's own bucket-pad semantics
    exactly (node_ok/allocatable/pod_valid all zero -> the row is
    infeasible for every kernel), which is why padding here cannot perturb
    bindings — the regression gate is test_parallel's 1023-node fixture.
    Divisible shapes pass through untouched (no copy)."""
    arr = np.asarray(arr)
    counts = _shard_counts(sharding, arr.ndim)
    widths = []
    needs = False
    for size, c in zip(arr.shape, counts):
        pad = (-size) % c
        widths.append((0, pad))
        needs = needs or pad > 0
    if not needs:
        return arr
    return np.pad(arr, widths)


def put_on_mesh(arr, sharding: NamedSharding):
    """Place host data on a (possibly multi-host) sharding, zero-padding
    non-divisible sharded axes (`pad_for_sharding`). Single-process meshes
    take the fast `device_put` path; when the mesh spans processes
    (`jax.distributed.initialize()`), each process materializes only its
    addressable shards from the (identically computed) host array."""
    arr = pad_for_sharding(np.asarray(arr), sharding)
    if sharding.is_fully_addressable:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def merge_readback(*arrays) -> Tuple[List[np.ndarray], Dict[int, int]]:
    """Materialize kernel outputs to host numpy, merging from the per-shard
    device buffers, and account the bytes each mesh device actually holds
    for them.

    The sharded steps pin their compacted readback outputs (chosen /
    bind_pods / bind_nodes / bind_zones / wave_counts) to a REPLICATED
    sharding, so every shard holds the full buffer in the same packed order
    the serial driver replays; the merge reads one addressable copy and the
    per-shard byte map feeds the `koord_scheduler_mesh_readback_bytes`
    gauges (shard-imbalance regressions must be visible, not inferred).
    Blocking is intended: this IS the mesh path's designated sync point."""
    out: List[np.ndarray] = []
    per_shard: Dict[int, int] = {}
    for arr in arrays:
        shards = getattr(arr, "addressable_shards", None)
        if shards:
            for sh in shards:
                per_shard[sh.device.id] = (
                    per_shard.get(sh.device.id, 0) + int(sh.data.nbytes))
        # koordlint: disable=unsharded-transfer-in-mesh-path
        out.append(np.asarray(arr))
    return out, per_shard


def mesh_row_layout(mesh: Mesh, n_real: int, n_padded: int) -> List[int]:
    """REAL (unpadded) node rows owned by each shard of the flat node
    sharding, in device order — the shard-imbalance observability input.
    With the node axis padded to `n_padded` over D devices each shard owns
    `n_padded // D` rows; trailing shards may hold only pad rows."""
    ndev = mesh.devices.size
    per = n_padded // ndev if ndev else 0
    return [max(0, min(per, n_real - i * per)) for i in range(ndev)]


def shard_inputs_nodewise(inputs: ScheduleInputs, mesh: Mesh) -> ScheduleInputs:
    """Sharding for the serial-parity step: node arrays sharded over all devices,
    pod arrays + weights replicated."""
    node_spec = _node_axis_spec(mesh, flat=True)
    pod_fields = {
        "fit_requests",
        "estimated",
        "is_prod",
        "is_daemonset",
        "pod_valid",
        "weights",
    }

    def put(name, arr):
        spec = P() if name in pod_fields else node_spec
        return put_on_mesh(arr, NamedSharding(mesh, spec))

    return ScheduleInputs(**{k: put(k, v) for k, v in inputs._asdict().items()})


def shard_inputs_2d(inputs: ScheduleInputs, mesh: Mesh) -> ScheduleInputs:
    """Sharding for the one-shot matrix: pods over "pods", nodes over "nodes"."""
    pod_fields = {"fit_requests", "estimated", "is_prod", "is_daemonset", "pod_valid"}

    def put(name, arr):
        if name == "weights":
            spec = P()
        elif name in pod_fields:
            spec = P("pods")
        else:
            spec = P("nodes")
        return put_on_mesh(arr, NamedSharding(mesh, spec))

    return ScheduleInputs(**{k: put(k, v) for k, v in inputs._asdict().items()})


def build_sharded_schedule_step(args: LoadAwareArgs, mesh: Mesh):
    """Serial-parity step jitted with node-sharded in/out shardings."""
    raw = build_schedule_step(args, jit=False)
    node_spec = _node_axis_spec(mesh, flat=True)
    out_shardings = (
        NamedSharding(mesh, P()),          # chosen [P] replicated
        NamedSharding(mesh, node_spec),    # requested [N, R]
    )
    return jax.jit(raw, out_shardings=out_shardings)


def build_sharded_score_matrix(args: LoadAwareArgs, mesh: Mesh):
    """One-shot [P, N] matrix jitted over the 2-D mesh."""
    raw = build_score_matrix(args, jit=False)
    out = NamedSharding(mesh, P("pods", "nodes"))
    return jax.jit(raw, out_shardings=(out, out))
