"""Mesh layout and sharded scheduling steps.

Two shardings cover the framework's compute:

  1. serial-parity step: node-state arrays ([N, R], [N]) sharded over ALL devices
     on the "nodes" axis; pod arrays replicated. Each fori_loop iteration's
     filter/score row is computed shard-locally; the argmax reduces across shards
     (XLA all-reduce over ICI). This preserves exact serial semantics at any mesh
     size — the distributed analog of kube-scheduler's per-node fan-out.

  2. score-matrix / rebalance: 2-D mesh ("pods", "nodes"); the [P, N] score matrix
     shards over both axes — full SPMD for the descheduler's 50k-pod global
     rebalance (BASELINE.md config 5) and throughput mode.

Multi-host: the same code runs under `jax.distributed.initialize()`; mesh axes laid
out so "nodes" stays within a slice (ICI) and "pods" may span slices (DCN), since
the pods axis only needs its collectives at the final argmax/top-k. Exercised by
`tests/test_multihost.py`: two OS processes x 4 virtual CPU devices federate into
one 8-device mesh and run the sharded full-chain step with gloo collectives
crossing the process boundary, bit-identical to single-device.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from koordinator_tpu.models.scheduler_model import (
    ScheduleInputs,
    build_schedule_step,
    build_score_matrix,
)
from koordinator_tpu.ops.loadaware import LoadAwareArgs


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """2-D mesh ("pods", "nodes"); the nodes axis gets the larger factor (node
    count exceeds pending-pod count in the target configs)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    pods_dim = 1
    for f in range(int(np.sqrt(n)), 0, -1):
        if n % f == 0:
            pods_dim = f
            break
    nodes_dim = n // pods_dim
    dev_array = np.array(devices).reshape(pods_dim, nodes_dim)
    return Mesh(dev_array, axis_names=("pods", "nodes"))


def _node_axis_spec(mesh: Mesh, flat: bool) -> P:
    # serial mode shards nodes over every device (both mesh axes)
    return P(("pods", "nodes")) if flat else P("nodes")


def put_on_mesh(arr, sharding: NamedSharding):
    """Place host data on a (possibly multi-host) sharding. Single-process
    meshes take the fast `device_put` path; when the mesh spans processes
    (`jax.distributed.initialize()`), each process materializes only its
    addressable shards from the (identically computed) host array."""
    if sharding.is_fully_addressable:
        return jax.device_put(arr, sharding)
    arr = np.asarray(arr)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def shard_inputs_nodewise(inputs: ScheduleInputs, mesh: Mesh) -> ScheduleInputs:
    """Sharding for the serial-parity step: node arrays sharded over all devices,
    pod arrays + weights replicated."""
    node_spec = _node_axis_spec(mesh, flat=True)
    pod_fields = {
        "fit_requests",
        "estimated",
        "is_prod",
        "is_daemonset",
        "pod_valid",
        "weights",
    }

    def put(name, arr):
        spec = P() if name in pod_fields else node_spec
        return put_on_mesh(arr, NamedSharding(mesh, spec))

    return ScheduleInputs(**{k: put(k, v) for k, v in inputs._asdict().items()})


def shard_inputs_2d(inputs: ScheduleInputs, mesh: Mesh) -> ScheduleInputs:
    """Sharding for the one-shot matrix: pods over "pods", nodes over "nodes"."""
    pod_fields = {"fit_requests", "estimated", "is_prod", "is_daemonset", "pod_valid"}

    def put(name, arr):
        if name == "weights":
            spec = P()
        elif name in pod_fields:
            spec = P("pods")
        else:
            spec = P("nodes")
        return put_on_mesh(arr, NamedSharding(mesh, spec))

    return ScheduleInputs(**{k: put(k, v) for k, v in inputs._asdict().items()})


def build_sharded_schedule_step(args: LoadAwareArgs, mesh: Mesh):
    """Serial-parity step jitted with node-sharded in/out shardings."""
    raw = build_schedule_step(args, jit=False)
    node_spec = _node_axis_spec(mesh, flat=True)
    out_shardings = (
        NamedSharding(mesh, P()),          # chosen [P] replicated
        NamedSharding(mesh, node_spec),    # requested [N, R]
    )
    return jax.jit(raw, out_shardings=out_shardings)


def build_sharded_score_matrix(args: LoadAwareArgs, mesh: Mesh):
    """One-shot [P, N] matrix jitted over the 2-D mesh."""
    raw = build_score_matrix(args, jit=False)
    out = NamedSharding(mesh, P("pods", "nodes"))
    return jax.jit(raw, out_shardings=(out, out))
