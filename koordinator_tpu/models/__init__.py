"""Composed scheduling models: full plugin chains as single jittable functions.

The flagship "model" of this framework is the fused batched scheduling step
(`scheduler_model.py`): Filter chain + Score chain + serial-parity selection for a
whole pending-pod batch in one compiled program. `__graft_entry__.entry()` exposes
it for single-chip compile checks; `parallel/` shards it over a device mesh.
"""
