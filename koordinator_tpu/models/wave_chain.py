"""Wave-parallel full-chain scheduling step: serial bindings, parallel waves.

The serial kernel (models/full_chain.py) walks pods one at a time because each
binding mutates node/quota state. But the chain's state updates are MONOTONE:
committing a pod only ever (a) raises a node's requested/estimated usage and
shrinks its NUMA/bindable-CPU headroom — so that node's feasibility and score
for later pods can only get WORSE — and (b) raises quota usage along one
ancestor chain — so quota admission can only flip admit -> reject. Under
monotone decay, a pod's serial decision is EXACTLY its decision against the
wave-start state unless something it depends on was touched earlier in the
wave:

  * its argmax node was also chosen by an earlier wave pod (untouched nodes
    only decayed elsewhere, so the argmax — lowest-index tie-break included —
    cannot move), or
  * in-wave quota usage along its ancestor chain flips its admission (checked
    EXACTLY via an in-wave exclusive prefix-sum of ancestor-chain additions,
    not conservatively by chain overlap — sharing the tree root costs
    nothing while headroom lasts).

So each device step evaluates a WINDOW of W pods in parallel against frozen
state (vmapping the IDENTICAL per-pod evaluator the serial kernel uses —
parity is by construction), finds the first conflict, commits the clean
prefix in one batch of matmul/scatter updates, and advances. Conflict-free
prefixes average ~sqrt(N) pods, so the 10k x 5k trace collapses from 10k
serial iterations into ~100 wave iterations of MXU/VPU-friendly [W, N] work.

Same contract and bindings as build_full_chain_step, validated by
tests/test_wave_chain.py across the parity configs (CPU). State rollups run
at Precision.HIGHEST; node-side rollups are EXACT (committed pods occupy
distinct nodes, so each matmul row has a single non-zero term), and the
quota commit reuses the same cumsum the admission pass saw, so the wave is
internally consistent. The one theoretical divergence from the serial kernel
is f32 summation order for a quota group whose packed usage exceeds 2^24
while sitting within one ULP of its runtime — the full-batch binding diff
against the serial step (run on-chip when the selector adopts this kernel)
is the empirical gate.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from koordinator_tpu.models.full_chain import (
    FullChainInputs,
    make_pod_evaluator,
    resolve_balance_idx,
    resolve_weight_idx,
)
from koordinator_tpu.ops.gang import gang_permit_mask
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.ops.numa import numa_spread_fill
from koordinator_tpu.ops.quota import quota_admit_row

DEFAULT_WAVE = 256


def build_wave_full_chain_step(args: LoadAwareArgs, num_gangs: int,
                               num_groups: int, jit: bool = True,
                               active_axes=None, wave: int = DEFAULT_WAVE):
    """FullChainInputs -> (chosen[P], requested[N, R], quota_used[G, R])."""
    weight_idx = resolve_weight_idx(args, active_axes)
    bal_idx = resolve_balance_idx(active_axes)
    prod_mode = args.score_according_prod_usage

    def step(fc: FullChainInputs):
        inputs = fc.base
        P, R = inputs.fit_requests.shape
        N = inputs.allocatable.shape[0]
        G, D = fc.quota_ancestors.shape
        W = min(wave, P)
        evaluate = make_pod_evaluator(fc, weight_idx, prod_mode, bal_idx)

        # [G, G] ancestor membership: anc_mask[g, a] == a is on g's chain
        anc_valid = fc.quota_ancestors >= 0                      # [G, D]
        anc_onehot_gd = jax.nn.one_hot(
            jnp.maximum(fc.quota_ancestors, 0), G, dtype=jnp.float32
        ) * anc_valid[..., None].astype(jnp.float32)             # [G, D, G]
        anc_mask = anc_onehot_gd.sum(axis=1)                     # [G, G] 0/1

        warange = jnp.arange(W, dtype=jnp.int32)

        def cond(state):
            return state[-1] < P

        T = fc.aff_dom.shape[1]

        def wave_body(state):
            (requested, delta_np, delta_pr, numa_free, bind_free,
             quota_used, aff_count, anti_cover, aff_exists, port_used,
             vol_free, chosen, pos) = state
            idx = pos + warange
            valid_w = idx < P
            idxc = jnp.minimum(idx, P - 1)

            (found_w, best_w, zone_w, admit_w, score_w, bal_w,
             maxv_w) = jax.vmap(
                lambda i: evaluate(i, requested, delta_np, delta_pr,
                                   numa_free, bind_free, quota_used,
                                   aff_count, anti_cover, aff_exists,
                                   port_used, vol_free)
            )(idxc)
            found_w = found_w & valid_w

            req_w = fc.requests[idxc]                 # [W, R]
            req_fit_w = inputs.fit_requests[idxc]     # [W, R]
            est_w = inputs.estimated[idxc]            # [W, R]
            qid_w = fc.quota_id[idxc]                 # [W]
            has_quota_w = qid_w >= 0

            # ---- exact in-wave quota re-admission: usage each pod would see
            # serially = wave-start usage + additions of all found pods before
            # it (exclusive prefix over the window)
            pod_anc_w = anc_mask[jnp.maximum(qid_w, 0)] * (
                (found_w & has_quota_w).astype(jnp.float32)[:, None]
            )                                          # [W, G]
            adds = pod_anc_w[:, :, None] * req_w[:, None, :]       # [W, G, R]
            incl = jnp.cumsum(adds, axis=0)                        # inclusive
            prefix = incl - adds                                   # exclusive
            admit_prefix_w = jax.vmap(
                lambda req, qid, pre: quota_admit_row(
                    req, qid, fc.quota_ancestors, quota_used + pre,
                    fc.quota_runtime,
                )
            )(req_w, qid_w, prefix)
            quota_flip_w = found_w & admit_w & ~admit_prefix_w

            # ---- node collision: an earlier wave pod already took this argmax
            sel_w = jax.nn.one_hot(best_w, N, dtype=jnp.float32) * (
                found_w.astype(jnp.float32)[:, None]
            )                                          # [W, N]
            taken_before = jnp.cumsum(sel_w, axis=0) - sel_w       # exclusive
            node_coll_w = found_w & (
                jnp.take_along_axis(
                    taken_before, best_w[:, None], axis=1
                )[:, 0] > 0.5
            )

            # ---- affinity conflict: an earlier in-wave pod MATCHING a term
            # this pod REQUIRES changes the term's counts, so the frozen
            # evaluation may diverge from serial. Anti terms only decay
            # (found -> infeasible), so only found pods conflict; required
            # affinity can FLIP INFEASIBLE -> FEASIBLE (non-monotone), so
            # any pod carrying the term conflicts once a match committed.
            if T:
                match_w = (fc.pod_aff_match[idxc]
                           & found_w[:, None])                     # [W, T]
                matched_before = (jnp.cumsum(
                    match_w.astype(jnp.float32), axis=0) - match_w) > 0.5
                anti_conf = found_w & jnp.any(
                    fc.pod_anti_req[idxc] & matched_before, axis=1)
                # required affinity, topology spread, AND weighted
                # preferences are all count-sensitive (a committed match
                # changes feasibility or the score), so any referenced
                # term with an earlier in-wave match conflicts
                aff_conf = jnp.any(
                    (fc.pod_aff_req[idxc]
                     | (fc.pod_spread_skew[idxc] > 0)
                     | fc.pod_ppref_mask[idxc]) & matched_before,
                    axis=1) & valid_w
                # symmetric anti-affinity: an earlier committed CARRIER of
                # anti term t raises anti_cover, so a later pod MATCHING t
                # may lose nodes the frozen evaluation still offered
                carried_w = (fc.pod_anti_req[idxc]
                             & found_w[:, None])                   # [W, T]
                carried_before = (jnp.cumsum(
                    carried_w.astype(jnp.float32), axis=0) - carried_w) > 0.5
                sym_conf = found_w & jnp.any(
                    fc.pod_aff_match[idxc] & carried_before, axis=1)
                affinity_conf_w = anti_conf | aff_conf | sym_conf
            else:
                affinity_conf_w = jnp.zeros_like(found_w)

            # ---- balanced-allocation conflict: the one NON-monotone score
            # term — committing pod e can make node n_e MORE balanced and so
            # RAISE its score for a later pod w, moving w's serial argmax to
            # a node the frozen evaluation under-scored. Sound pairwise
            # bound: every other term only decays, so w's post-commit score
            # on n_e is at most frozen_score_w(n_e) - frozen_bal_w(n_e) +
            # exact_post_bal_w(n_e) (node collisions guarantee at most ONE
            # in-wave commit per node, so the post state of n_e is frozen +
            # fit_e). Conflict when that bound could reach w's frozen best.
            if bal_idx[0] >= 0:
                ci, mi = bal_idx
                alloc = inputs.allocatable
                cap_c = alloc[best_w, ci]                          # [W] (e)
                cap_m = alloc[best_w, mi]
                base_c = requested[best_w, ci] + req_fit_w[:, ci]  # n_e + e
                base_m = requested[best_w, mi] + req_fit_w[:, mi]

                def _pair_frac(base_e, cap_e, waxis):
                    # reciprocal-multiply form, identical to the evaluator's
                    # _frac so the post-commit bal value is exact
                    from koordinator_tpu.ops.pallas_common import (
                        safe_reciprocal,
                    )

                    inv = safe_reciprocal(cap_e)                       # [W]
                    f = (base_e[None, :] + waxis[:, None]) * inv[None, :]
                    return jnp.minimum(f, 1.0)

                fpc = _pair_frac(base_c, cap_c, req_fit_w[:, ci])  # [W, W]
                fpm = _pair_frac(base_m, cap_m, req_fit_w[:, mi])
                bal_pair = jnp.floor(
                    (1.0 - jnp.abs(fpc - fpm) * 0.5) * 100.0)      # w x e
                score_at_ne = score_w[:, best_w]                   # [W, W]
                bal_at_ne = bal_w[:, best_w]
                bound = score_at_ne - bal_at_ne + bal_pair
                tri_e_before_w = (warange[None, :] < warange[:, None])
                # found_w gate: the bal term moves scores, never
                # feasibility, so a not-found pod stays not-found
                # post-commit and must not cut the wave
                bal_conf_w = found_w & jnp.any(
                    tri_e_before_w & found_w[None, :]
                    & (bound >= maxv_w[:, None]), axis=1)
            else:
                bal_conf_w = jnp.zeros_like(found_w)

            conflict_w = (quota_flip_w | node_coll_w | affinity_conf_w
                          | bal_conf_w)
            cut = jnp.where(
                conflict_w.any(), jnp.argmax(conflict_w), W
            ).astype(jnp.int32)

            commit_w = (warange < cut) & found_w
            cm = commit_w.astype(jnp.float32)
            sel_c = sel_w * cm[:, None]                            # [W, N]

            # HIGHEST precision keeps these f32 (TPU matmuls default to bf16
            # passes); each output row has at most ONE non-zero term — the
            # node-collision cut guarantees distinct nodes per wave — so the
            # rollup equals the serial kernel's add exactly
            hi = jax.lax.Precision.HIGHEST
            mm = lambda a, b: jnp.matmul(a, b, precision=hi)  # noqa: E731
            requested = requested + mm(sel_c.T, req_fit_w)
            delta_np = delta_np + mm(sel_c.T, est_w)
            if prod_mode:
                delta_pr = delta_pr + mm(
                    sel_c.T,
                    inputs.is_prod[idxc].astype(jnp.float32)[:, None] * est_w,
                )
            bind_free = bind_free - mm(
                sel_c.T,
                jnp.where(fc.needs_bind[idxc], fc.cores_needed[idxc], 0.0),
            )
            # NodePorts/volumes: same-node conflicts are impossible within a
            # wave (the node-collision cut commits distinct nodes), so the
            # frozen evaluation is exact and the rollup scatters cleanly
            if fc.port_used.shape[1]:
                port_used = jnp.maximum(
                    port_used,
                    mm(sel_c.T,
                       fc.pod_port_wants[idxc].astype(jnp.float32)))
            # per-pod NEW attachments at the chosen node (volume-group
            # gather — the already-attached exemption), one nonzero per
            # output row as above so the rollup equals the serial add
            vn_at_best = jnp.take_along_axis(
                fc.vol_needed[idxc],
                fc.node_vol_group[best_w][:, None], axis=1)[:, 0]  # [W]
            vol_free = vol_free - mm(sel_c.T, vn_at_best)
            # committed pods occupy DISTINCT nodes (node_coll cut), so the
            # per-pod NUMA fills scatter without aliasing
            new_rows_w = jax.vmap(numa_spread_fill)(
                numa_free[best_w], req_w, zone_w
            )                                          # [W, K, R]
            numa_idx = jnp.where(
                commit_w & fc.needs_numa[idxc], best_w, N
            )
            numa_free = numa_free.at[numa_idx].set(
                new_rows_w, mode="drop"
            )
            # quota commit from the SAME inclusive cumsum the admission pass
            # consumed: the committed total is incl[cut-1] (zero when the cut
            # lands on the first pod), so admission and commit can never see
            # differently-associated sums
            committed_total = jnp.where(
                cut > 0, incl[jnp.maximum(cut - 1, 0)], jnp.zeros_like(incl[0])
            )
            quota_used = quota_used + committed_total

            # affinity commit: every committed pod raises its matched terms'
            # counts over the chosen node's whole domain (exact: 0/1
            # indicator matmul at HIGHEST precision on small integers)
            for t in range(T):
                dom_col = fc.aff_dom[:, t]                         # [N]
                chosen_dom_w = dom_col[best_w]                     # [W]
                inc_w = (cm * fc.pod_aff_match[idxc, t]
                         * (chosen_dom_w >= 0))                    # [W]
                eq = (dom_col[None, :] == chosen_dom_w[:, None]
                      ).astype(jnp.float32)                        # [W, N]
                aff_count = aff_count.at[:, t].add(mm(inc_w[None, :], eq)[0])
                # committed CARRIERS raise anti_cover over their domain
                inc_cov_w = (cm * fc.pod_anti_req[idxc, t]
                             * (chosen_dom_w >= 0))                # [W]
                anti_cover = anti_cover.at[:, t].add(
                    mm(inc_cov_w[None, :], eq)[0])
                aff_exists = aff_exists.at[t].set(
                    aff_exists[t]
                    | jnp.any(commit_w & fc.pod_aff_match[idxc, t]))

            value_w = jnp.where(found_w, best_w.astype(jnp.int32), -1)
            chosen_idx = jnp.where((warange < cut) & valid_w, idx, P)
            chosen = chosen.at[chosen_idx].set(value_w, mode="drop")
            return (requested, delta_np, delta_pr, numa_free, bind_free,
                    quota_used, aff_count, anti_cover, aff_exists, port_used,
                    vol_free, chosen, pos + cut)

        init = (
            inputs.requested,
            jnp.zeros((N, R), jnp.float32),
            jnp.zeros((N, R), jnp.float32),
            fc.numa_free,
            fc.bind_free,
            fc.quota_used,
            fc.aff_count,
            fc.anti_cover,
            jnp.asarray(fc.aff_exists, bool),
            fc.port_used,
            fc.vol_free,
            jnp.full(P, -1, jnp.int32),
            jnp.int32(0),
        )
        (requested, _, _, _, _, quota_used, _, _, _, _, _, chosen,
         _pos) = jax.lax.while_loop(cond, wave_body, init)

        # ---- Permit barrier (gang group all-or-nothing)
        keep = gang_permit_mask(
            chosen, fc.gang_id, fc.gang_min_member, fc.gang_assumed,
            fc.gang_group_id, num_gangs, num_groups,
        )
        chosen = jnp.where(keep, chosen, -1)
        return chosen, requested, quota_used

    return jax.jit(step) if jit else step
