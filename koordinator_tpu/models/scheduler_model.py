"""The fused batched scheduling step.

Replaces the reference's scheduleOne hot loop (SURVEY.md section 3.1): instead of
per-pod Go plugin dispatch with a per-node goroutine fan-out, one compiled XLA
program processes an entire pending-pod batch against the packed node state.

Two execution modes:

  * serial-parity (default): a `lax.fori_loop` walks pods in queue order; each
    iteration filters+scores that pod against ALL nodes in one fused vector pass,
    picks argmax, and applies the assignment to on-device state (Fit `requested`,
    LoadAware assign-cache deltas) before the next pod — bit-matching the
    reference's sequential contract (pod i+1 sees pod i's Reserve). Tie-break is
    lowest node index (the reference randomizes among max-score nodes,
    selectHost; the parity emulator uses the same deterministic rule).

  * score-matrix: one shot [P, N] feasibility + scores for all pods, no
    assignment feedback — used by the descheduler's global rebalance and by
    diagnostics (top-N score dump, frameworkext/debug.go analog).

State layout (all float32/bool, static shapes):
  requested[N, R]   NodeResourcesFit accumulated requests
  delta_np[N, R]    in-batch LoadAware assign-cache estimates (all pods)
  delta_pr[N, R]    same, prod pods only (scoreAccordingProdUsage branch)
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.ops import loadaware as la_ops
from koordinator_tpu.ops.common import least_requested_score
from koordinator_tpu.ops.fit import fit_ok_matrix, fit_ok_row, with_pod_count
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.ops.packing import NodeBatch, PodBatch


class ScheduleInputs(NamedTuple):
    """Device-ready pytree for one scheduling step (LoadAware chain)."""

    # pods [P, ...]
    fit_requests: jnp.ndarray   # [P, R] requests with pods-axis = 1
    estimated: jnp.ndarray      # [P, R]
    is_prod: jnp.ndarray        # [P]
    is_daemonset: jnp.ndarray   # [P]
    pod_valid: jnp.ndarray      # [P]
    # nodes [N, ...]
    allocatable: jnp.ndarray    # [N, R]
    requested: jnp.ndarray      # [N, R]
    node_ok: jnp.ndarray        # [N] valid & schedulable
    la_filter_usage: jnp.ndarray
    la_has_filter_usage: jnp.ndarray
    la_filter_thresholds: jnp.ndarray
    la_prod_thresholds: jnp.ndarray
    la_prod_pod_usage: jnp.ndarray
    la_term_nonprod: jnp.ndarray
    la_term_prod: jnp.ndarray
    la_score_valid: jnp.ndarray
    la_filter_skip: jnp.ndarray
    weights: jnp.ndarray        # [R]


def make_inputs(pods: PodBatch, nodes: NodeBatch, args: LoadAwareArgs) -> ScheduleInputs:
    # host numpy throughout: the jitted step does the single H2D transfer;
    # eager jnp.asarray here would round-trip via reduce_to_active_axes
    ex = nodes.extras
    node_ok = np.asarray(nodes.valid)
    return ScheduleInputs(
        fit_requests=np.asarray(with_pod_count(pods.requests)),
        estimated=np.asarray(pods.estimated),
        is_prod=np.asarray(pods.is_prod),
        is_daemonset=np.asarray(pods.is_daemonset),
        pod_valid=np.asarray(pods.valid),
        allocatable=np.asarray(nodes.allocatable),
        requested=np.asarray(nodes.requested),
        node_ok=np.asarray(node_ok),
        la_filter_usage=np.asarray(ex["la_filter_usage"]),
        la_has_filter_usage=np.asarray(ex["la_has_filter_usage"]),
        la_filter_thresholds=np.asarray(ex["la_filter_thresholds"]),
        la_prod_thresholds=np.asarray(ex["la_prod_thresholds"]),
        la_prod_pod_usage=np.asarray(ex["la_prod_pod_usage"]),
        la_term_nonprod=np.asarray(ex["la_term_nonprod"]),
        la_term_prod=np.asarray(ex["la_term_prod"]),
        la_score_valid=np.asarray(ex["la_score_valid"]),
        la_filter_skip=np.asarray(ex["la_filter_skip"]),
        weights=np.asarray(args.weight_vector()),
    )


def _score_row(
    est_row: jnp.ndarray,       # [R]
    is_prod_i: jnp.ndarray,     # scalar bool
    inputs: ScheduleInputs,
    delta_np: jnp.ndarray,      # [N, R]
    delta_pr: jnp.ndarray,      # [N, R]
    weight_idx: Tuple[int, ...],
    prod_mode: bool,
) -> jnp.ndarray:
    """LoadAware score of one pod against all nodes, honoring in-batch deltas."""
    acc = jnp.zeros(inputs.allocatable.shape[0], jnp.float32)
    wsum = jnp.sum(inputs.weights)
    for r in weight_idx:
        base = (
            jnp.where(
                is_prod_i,
                inputs.la_term_prod[:, r] + delta_pr[:, r],
                inputs.la_term_nonprod[:, r] + delta_np[:, r],
            )
            if prod_mode
            else inputs.la_term_nonprod[:, r] + delta_np[:, r]
        )
        used = est_row[r] + base
        acc = acc + inputs.weights[r] * least_requested_score(
            used, inputs.allocatable[:, r]
        )
    score = jnp.floor(acc / jnp.maximum(wsum, 1.0))
    return jnp.where(inputs.la_score_valid, score, 0.0)


def build_schedule_step(args: LoadAwareArgs, jit: bool = True):
    """Return a jittable step: ScheduleInputs -> (chosen[P] int32, requested[N, R]).

    chosen[i] is the node index assigned to queue-position-i pod, or -1.
    With jit=False the raw traceable fn is returned (for re-jitting under a Mesh
    with explicit shardings, see parallel/).
    """
    weight_idx = tuple(int(i) for i in np.nonzero(args.weight_vector())[0])
    prod_mode = args.score_according_prod_usage

    def step(inputs: ScheduleInputs):
        P = inputs.fit_requests.shape[0]
        N = inputs.allocatable.shape[0]
        reject_np, reject_prod = la_ops.loadaware_node_reject(
            inputs.allocatable,
            inputs.la_filter_usage,
            inputs.la_has_filter_usage,
            inputs.la_filter_thresholds,
            inputs.la_prod_thresholds,
            inputs.la_prod_pod_usage,
            inputs.la_filter_skip,
        )

        def body(i, state):
            requested, delta_np, delta_pr, chosen = state
            req = inputs.fit_requests[i]
            est = inputs.estimated[i]
            is_prod_i = inputs.is_prod[i]
            fit = fit_ok_row(req, inputs.allocatable, requested)
            la_reject = jnp.where(is_prod_i, reject_prod, reject_np)
            la_ok = inputs.is_daemonset[i] | ~la_reject
            feasible = inputs.node_ok & fit & la_ok
            score = _score_row(
                est, is_prod_i, inputs, delta_np, delta_pr, weight_idx, prod_mode
            )
            score = jnp.where(feasible, score, -1.0)
            best = jnp.argmax(score)  # first occurrence -> lowest index tie-break
            found = (score[best] >= 0.0) & inputs.pod_valid[i]
            sel = (jnp.arange(N, dtype=jnp.int32) == best) & found
            requested = requested + sel[:, None] * req[None, :]
            est_add = sel[:, None] * est[None, :]
            delta_np = delta_np + est_add
            if prod_mode:
                delta_pr = delta_pr + jnp.where(is_prod_i, 1.0, 0.0) * est_add
            chosen = chosen.at[i].set(jnp.where(found, best.astype(jnp.int32), -1))
            return requested, delta_np, delta_pr, chosen

        R = inputs.fit_requests.shape[-1]
        init = (
            inputs.requested,
            jnp.zeros((N, R), jnp.float32),
            jnp.zeros((N, R), jnp.float32),
            jnp.full(P, -1, jnp.int32),
        )
        requested, _, _, chosen = jax.lax.fori_loop(0, P, body, init)
        return chosen, requested

    return jax.jit(step) if jit else step


def build_best_schedule_step(args: LoadAwareArgs, vmem_budget_bytes=None):
    """Backend-aware selector: the VMEM-resident Pallas kernel on TPU
    (ops/pallas_step.py, ~3x the fori_loop at 10k x 5k), the XLA step
    elsewhere. Same contract, bit-identical bindings. Past the kernel's
    VMEM budget the per-call dispatch degrades to the XLA step instead of
    failing to compile (see build_best_full_chain_step)."""
    xla_step = build_schedule_step(args)
    if jax.default_backend() != "tpu":
        return xla_step
    from koordinator_tpu.ops import pallas_common as pc
    from koordinator_tpu.ops.pallas_step import (
        build_pallas_schedule_step,
        estimate_vmem_bytes,
    )

    budget = (pc.vmem_budget_bytes() if vmem_budget_bytes is None
              else vmem_budget_bytes)
    pallas_step = build_pallas_schedule_step(args)

    def step(inputs):
        P, R = inputs.fit_requests.shape
        N = inputs.allocatable.shape[0]
        if estimate_vmem_bytes(N, R, P) <= budget:
            step.last_backend = "pallas"
            return pallas_step(inputs)
        step.last_backend = "xla"
        return xla_step(inputs)

    step.last_backend = None
    return step


def build_score_matrix(args: LoadAwareArgs, jit: bool = True):
    """One-shot [P, N] (feasible, score) with no assignment feedback."""
    prod_mode = args.score_according_prod_usage
    weight_idx = tuple(int(i) for i in np.nonzero(args.weight_vector())[0])

    def fn(inputs: ScheduleInputs):
        reject_np, reject_prod = la_ops.loadaware_node_reject(
            inputs.allocatable,
            inputs.la_filter_usage,
            inputs.la_has_filter_usage,
            inputs.la_filter_thresholds,
            inputs.la_prod_thresholds,
            inputs.la_prod_pod_usage,
            inputs.la_filter_skip,
        )
        la_ok = la_ops.loadaware_filter(
            inputs.is_prod, inputs.is_daemonset, reject_np, reject_prod
        )
        fit = fit_ok_matrix(inputs.fit_requests, inputs.allocatable, inputs.requested)
        feasible = (
            la_ok
            & fit
            & inputs.node_ok[None, :]
            & inputs.pod_valid[:, None]
        )
        score = la_ops.loadaware_score_terms(
            inputs.estimated,
            inputs.is_prod,
            inputs.la_term_nonprod,
            inputs.la_term_prod,
            inputs.allocatable,
            inputs.la_score_valid,
            inputs.weights,
            prod_mode,
            weight_idx,
        )
        return feasible, score

    return jax.jit(fn) if jit else fn
