"""Fused multi-wave scheduling: K dependent rounds in ONE device dispatch.

BENCH_r05 measured the production cycle's shape: a 10k x 5k full-chain
round costs ~17ms of marginal kernel but every dispatch pays ~66ms of
fixed overhead (dispatch + result-readback RTT through the axon tunnel),
so realized throughput sits at ~1/5 of the marginal ceiling. The on-chip
chain bench (BENCH_ONCHIP_CHAINS_*) already proved that chaining rounds
inside one jit cancels the fixed cost; this module gives the production
cycle that capability.

One fused dispatch runs up to K WAVES, where wave w is exactly the
scheduling round serial cycle w would run:

  wave body =
    0. carried-transition pre-passes — the host work serial cycle w runs
       BEFORE its kernel, expressed as carried state: the reservation
       reconcile's consumed-allocate-once transition (requested loses the
       reservation's held capacity, its consumer falls back to direct
       accounting) and the nomination pre-pass (owner pods bind onto
       reservations that became Available in an EARLIER wave of this
       dispatch, consuming carried remainders — so a migration-created
       Reservation is consumable by wave 2 of the same dispatch);
    1. evaluation pass — the serial full-chain round (the same
       ``make_pod_evaluator`` + ``commit_pod_state`` the single-round
       kernel traces, models/full_chain.py) over the still-pending pods,
       producing tentative bindings with in-round state feedback;
    2. gang Permit against the CARRIED assumed counters;
    3. kept-only replay pass — the next wave's state is rebuilt from the
       wave-start state by committing ONLY the pods that survived Permit,
       in bind order. This mirrors what the host does between serial
       cycles: reverted gang members never reach the store, so their
       in-round reservations must not leak into the next round's state
       (and NUMA zone choices are re-picked under the kept-only state,
       the same way the host plugin allocates at Reserve). Reservation
       pseudo-pod rows commit their CARRY form here: the allocatable
       vector the restore transformer would add (no pod-count slot, no
       LoadAware estimate, no NUMA/affinity footprint) — the bound CR
       holds capacity, it is not a pod.

Carried device state (``WAVE_STATE_FIELDS``): node requested/NUMA-free/
bindable-cpu/port/volume state, quota used along the ancestor chains,
gang assumed counters, the pod assigned-mask, the LoadAware assigned-
estimate sums (non-prod ``est_sum`` AND, under scoreAccordingProdUsage,
the prod split ``est_sum_prod``), the hot-claim attachment matrix +
non-hot attachment counter (ops/volumes.py), and the reservation rows'
availability/remainder/node state. The LoadAware terms are recomputed
per wave as ``est_sum + adjusted`` — the SAME two-operand association a
next-cycle host rebuild produces (ops/loadaware.py exports both splits),
so carried state is bit-identical to what serial cycle w's snapshot
would contain. A pod rejected in wave i because a node filled up (or a
gang's quota was transiently held) retries in wave i+1 on-device, with
no host round-trip. Feature-absent slots carry ``None`` (a leafless
pytree), so a batch without claims/reservations/prod scoring traces the
exact historical program.

The ONE wave body (``_make_wave_body``) backs two dispatch shapes:

  * ``build_fused_wave_step`` — all K waves under ``lax.while_loop`` in
    one program, compacted (pod_idx, node_idx, zone, res_idx) readback at
    the end. Early exit: a wave that commits nothing (and has no pending
    carried transition) proves the fixpoint. This is the
    ``KOORD_TPU_REPLAY_OVERLAP=0`` path: the host replay of every wave
    runs serially after the single readback.
  * ``build_chained_wave_step`` — ONE wave per dispatch with the carried
    state staying on device between dispatches. The cycle driver
    (scheduler/cycle.py) dispatches wave w+1 asynchronously BEFORE
    syncing wave w's rows, so the host-side replay of wave w overlaps
    device execution of wave w+1 — the replay queue architecture. The
    step is K-independent, so every wave depth shares one compiled
    program. Tracing the SAME wave body keeps the chain bit-identical
    to the fused while_loop (pipeline_parity.run_replay_overlap_parity
    gates it).

Readback is COMPACTED: a (pod_idx, node_idx, zone, res_idx) binding
buffer plus per-wave bound counts — not K full assignment vectors and
none of the score/state matrices. ``res_idx >= 0`` marks a nomination
(the driver replays it as a via-reservation bind — Reserve hooks +
consume — FIRST in the logical cycle, the pre-pass position). The driver
replays the waves host-side as logical cycles; pipeline_parity gates
that a fused-K cycle is byte-identical to K sequential single-round
cycles.

Registered ``ScoreTransformer``s that implement the device-expressible
protocol (``device_pass``, scheduler/frameworkext.py) run as tensor
passes over the rebuilt per-wave inputs — the same rewrite their host
``before_score`` applies to the packed batch each serial cycle.

Remaining demotions (the driver falls back to K=1, the exact serial
path): the degradation ladder's serial rung, the gRPC sidecar (the
remote protocol is single-round), ScoreTransformers WITHOUT a device
pass, and ``claim-entangled`` batches (unbound WaitForFirstConsumer
claims on several pods, or claim-factorization budget overflows — see
ops/volumes.py). The four data-driven reasons this module used to force
— pending-reservations, claim-pods, prod-usage-score, score-transformer
— are retired (PR 14) and pinned retired by the demotion registry.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from koordinator_tpu.models.full_chain import (
    EXPLAIN_TERMS,
    NUM_EXPLAIN_STAGES,
    ExplainOut,
    FullChainInputs,
    commit_pod_state,
    explain_stage_counts,
    make_pod_evaluator,
    resolve_balance_idx,
    resolve_weight_idx,
)
from koordinator_tpu.ops.gang import gang_permit_mask
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.ops.numa import numa_zone_for_node
from koordinator_tpu.ops.volumes import (
    advance_claim_state,
    effective_vol_needed,
)

MAX_WAVES = 8  # bounds the compile-cache key space; auto-K never exceeds it

# carried wave state (the chain step's explicit carry): index layout of
# the leading slots of the while_loop carry — scheduler/cycle.py builds
# the initial tuple via initial_wave_carry and threads the chain's output
# carry back in unchanged. Slots whose feature is off for the dispatch
# (no prod scoring / no hot claims / no pending reservation CRs) carry
# None — a leafless pytree, so the compiled program is the featureless
# trace exactly.
WAVE_STATE_FIELDS = (
    "assigned", "requested", "est_sum", "numa_free", "bind_free",
    "quota_used", "aff_count", "anti_cover", "aff_exists", "port_used",
    "vol_free", "gang_assumed",
    # PR 14 (demotion burn-down) carried extensions:
    "est_sum_prod",   # [N, R] prod assigned-estimate sum (prod mode only)
    "claim_new",      # [N, NC] hot claims newly attached per node
    "vol_new",        # [N] non-hot new attachments per node
    "res_avail",      # [NRES] reservation row became Available in-dispatch
    "res_remain",     # [NRES, R] packed allocatable remainder
    "res_node",       # [NRES] int32 node the row bound to (-1 pending)
    "res_succ",       # [NRES] int32 consumer pod row whose allocate-once
                      # consumption must apply the Succeeded transition at
                      # the NEXT wave boundary (-1 none)
)
NUM_WAVE_STATE = len(WAVE_STATE_FIELDS)
# wave-state slots indexed [N, ...] (node axis): sharded over the mesh in
# the sharded chain step; everything else (pod/quota/gang/reservation/term
# axes) replicated. est_sum (slot 2) is the node-axis LoadAware estimate
# sum; 12..14 are the PR 14 node-axis extensions.
WAVE_STATE_NODE_SLOTS = frozenset({1, 2, 3, 4, 6, 7, 9, 10, 12, 13, 14})

# out-block offsets relative to the carry start
_OUT_PODS = NUM_WAVE_STATE
_OUT_NODES = NUM_WAVE_STATE + 1
_OUT_ZONES = NUM_WAVE_STATE + 2
_OUT_RES = NUM_WAVE_STATE + 3
_N_OUT = NUM_WAVE_STATE + 4
_WAVE_COUNTS = NUM_WAVE_STATE + 5
_EX_COUNTS = NUM_WAVE_STATE + 6
_EX_TERMS = NUM_WAVE_STATE + 7

# nomination rank sentinel (plain int: no device array at import time)
_RANK_INF = 2**31 - 1


class ProdSides(NamedTuple):
    """scoreAccordingProdUsage term split (ops/loadaware.py exports)."""

    est: Any   # [N, R] la_est_prod — prod assigned-estimate sum at start
    adj: Any   # [N, R] la_adj_prod — non-estimated prod usage, static


class ClaimSides(NamedTuple):
    """Hot-claim factorization (ops/volumes.build_claim_pack)."""

    pod_claim: Any   # [P, NC] f32 0/1 — pod references hot claim c
    pod_nonhot: Any  # [P] f32 — the pod's non-hot distinct-claim count
    covered0: Any    # [N, NC] f32 0/1 — attached on node at dispatch start


class ResSides(NamedTuple):
    """Pending-reservation rows riding the batch (one per Reservation CR
    pseudo-pod; scheduler/cycle.py builds these in packed order)."""

    owner_match: Any  # [P, NRES] bool — res.matches(pod), host precompute
    rank: Any         # [NRES] int32 nomination preference (creation order)
    alloc: Any        # [NRES, R] f32 packed template requests (the
                      # restore-transformer add vector; no pod-count slot)
    once: Any         # [NRES] f32 0/1 allocate_once
    row_of: Any       # [NRES] int32 pseudo-pod row of each reservation
    pod_slot: Any     # [P] int32 reservation slot of a pseudo-pod row (-1)
    nominate_ok: Any  # [P] bool — host pre-pass eligibility class


class WaveSideInputs(NamedTuple):
    """Per-dispatch side operands of the fused/chained wave steps.

    ``prod``/``claims``/``res`` are None when the feature is absent from
    the batch — the pytree then has no leaves there and the compiled
    program is the featureless trace."""

    la_est: Any                     # [N, R] la_est_nonprod
    la_adj: Any                     # [N, R] la_adj_nonprod
    prod: Optional[ProdSides] = None
    claims: Optional[ClaimSides] = None
    res: Optional[ResSides] = None


class FusedWaveOut(NamedTuple):
    """Compacted readback of one fused dispatch."""

    bind_pods: jnp.ndarray    # [P] int32 pod row indices in bind order, -1 pad
    bind_nodes: jnp.ndarray   # [P] int32 node index per binding
    bind_zones: jnp.ndarray   # [P] int32 replay-state NUMA zone (-1 = spread)
    bind_res: jnp.ndarray     # [P] int32 reservation slot consumed via
    #     in-kernel nomination (-1 = plain kernel bind)
    wave_counts: jnp.ndarray  # [K] int32 bindings committed per wave
    waves_run: jnp.ndarray    # scalar int32 wave bodies actually executed


class WaveChainOut(NamedTuple):
    """Compacted readback of ONE chained wave dispatch."""

    bind_pods: jnp.ndarray   # [P] int32 this wave's pod rows in bind order
    bind_nodes: jnp.ndarray  # [P] int32 node index per binding
    bind_zones: jnp.ndarray  # [P] int32 replay-state NUMA zone (-1 = spread)
    bind_res: jnp.ndarray    # [P] int32 nomination reservation slot (-1)
    count: jnp.ndarray       # scalar int32 bindings this wave (0 = fixpoint)


def plain_sides(la_est, la_adj) -> WaveSideInputs:
    """The featureless side tuple (tests, benches): nonprod split only."""
    return WaveSideInputs(la_est=la_est, la_adj=la_adj)


def _check_wave_args(args: LoadAwareArgs, sides_prod: bool) -> None:
    if args.score_according_prod_usage != sides_prod:
        # the carry's est_sum_prod slot presence must equal prod_mode or
        # the while_loop carry structure would flip between iterations
        raise ValueError(
            "WaveSideInputs.prod must be supplied exactly when "
            "score_according_prod_usage is on (the prod term split "
            "la_est_prod/la_adj_prod rides the carry)")


def _carry_fc_variants(fc: FullChainInputs, sides: WaveSideInputs):
    """The per-row-kind input variants of the kept-only replay and the
    nomination pre-pass (static per dispatch, hoisted out of the loop).

    ``fc_carry``: reservation pseudo-pod rows commit their CARRY form —
    the packed allocatable vector (what the restore transformer adds at
    the next serial rebuild: no pod-count slot), no LoadAware estimate,
    no NUMA fill, no affinity footprint — a bound CR holds capacity but
    is not a pod. ``fc_nom``: nominated pods commit everything EXCEPT the
    node's requested row — a consumer's usage lives inside the
    reservation's already-counted footprint (the restore transformer's
    double-count subtraction, expressed as never-adding)."""
    inputs = fc.base
    if sides.res is None:
        fc_carry = fc
    else:
        slot = sides.res.pod_slot
        is_res = slot >= 0
        alloc_rows = sides.res.alloc[jnp.maximum(slot, 0)]
        fc_carry = fc._replace(
            base=inputs._replace(
                fit_requests=jnp.where(is_res[:, None], alloc_rows,
                                       inputs.fit_requests),
                estimated=jnp.where(is_res[:, None], 0.0,
                                    inputs.estimated),
            ),
            needs_numa=fc.needs_numa & ~is_res,
            pod_aff_match=fc.pod_aff_match & ~is_res[:, None],
            pod_anti_req=fc.pod_anti_req & ~is_res[:, None],
        )
    fc_nom = fc._replace(
        base=inputs._replace(fit_requests=jnp.zeros_like(inputs.fit_requests)))
    return fc_carry, fc_nom


def _make_wave_body(fc: FullChainInputs, sides: WaveSideInputs, n_real,
                    weight_idx, bal_idx, num_gangs: int, num_groups: int,
                    explain, prod_mode: bool, score_passes=()):
    """The ONE wave body both dispatch shapes trace.

    ``carry`` layout: WAVE_STATE_FIELDS (NUM_WAVE_STATE slots, None where
    the feature is off), then out_pods / out_nodes / out_zones / out_res /
    n_out / wave_counts, then [ex_counts] [ex_terms] under koordexplain,
    then (w, done). Returns the same layout with w+1 and the fixpoint
    flag. Extracted verbatim from the original while_loop body so the
    fused step and the chained step cannot drift — byte parity between
    them is by construction of the trace, and pipeline_parity gates it
    empirically.
    """
    inputs = fc.base
    P, R = inputs.fit_requests.shape
    N = inputs.allocatable.shape[0]
    explain_full = explain == "full"
    has_claims = sides.claims is not None
    has_res = sides.res is not None
    fc_carry, fc_nom = _carry_fc_variants(fc, sides)

    def wave_body(carry):
        (assigned, requested, est_sum, numa_free, bind_free, quota_used,
         aff_count, anti_cover, aff_exists, port_used, vol_free,
         gang_assumed, est_sum_prod, claim_new, vol_new, res_avail,
         res_remain, res_node, res_succ
         ) = carry[:NUM_WAVE_STATE]
        (out_pods, out_nodes, out_zones, out_res, n_out,
         wave_counts) = carry[_OUT_PODS:_WAVE_COUNTS + 1]
        w, done = carry[-2], carry[-1]
        if explain is not None:
            ex_counts = carry[_EX_COUNTS]
            ex_terms = carry[_EX_TERMS] if explain_full else None

        nom_count = jnp.int32(0)
        if has_res:
            # ---- pass 0a: the reservation reconcile's Succeeded
            # transition, one wave after an allocate-once consumption
            # (serial cycle w runs reconcile BEFORE its pre-pass): the
            # reservation stops being counted, so its held capacity
            # leaves the node and its consumer falls back to direct
            # accounting — (requested - alloc) + consumer_fit, the exact
            # event order the host restore recompute produces. All
            # integer-valued packed units: exact regardless of grouping.
            nres = res_succ.shape[0]

            def succ_body(r, req_state):
                p = res_succ[r]
                apply = (p >= 0).astype(jnp.float32)
                noden = jnp.maximum(res_node[r], 0)
                delta = (inputs.fit_requests[jnp.maximum(p, 0)]
                         - sides.res.alloc[r])
                new_row = req_state[noden] + apply * delta
                return jax.lax.dynamic_update_slice(
                    req_state, new_row[None], (noden, 0))

            requested = jax.lax.fori_loop(0, nres, succ_body, requested)
            res_succ = jnp.full_like(res_succ, -1)

            # ---- pass 0b: the nomination pre-pass over carried
            # reservation state — owner pods bind onto rows that became
            # Available in an EARLIER wave of this dispatch (rows
            # pre-dating the dispatch were already host-nominated before
            # the kernel pass). Walks pods in packed (queue) order, picks
            # the earliest-created fitting candidate (the host
            # nominator's sort), and commits everything EXCEPT the
            # node's requested row (fc_nom): the consumer lives inside
            # the reservation's counted footprint.
            est_pr_state = (est_sum_prod if prod_mode
                            else jnp.zeros_like(est_sum))

            def nom_body(i, st):
                (chain, res_avail_, res_remain_, res_succ_,
                 out_p, out_n, out_z, out_r, cnt, assigned_, ncnt) = st
                req = fc.requests[i]
                elig = (sides.res.nominate_ok[i] & ~assigned_[i]
                        & inputs.pod_valid[i])
                fits = jnp.all(
                    (req[None, :] <= 0) | (req[None, :] <= res_remain_),
                    axis=1)
                cand = (res_avail_ > 0.5) & sides.res.owner_match[i] & fits
                r = jnp.argmin(jnp.where(cand, sides.res.rank, _RANK_INF))
                found = elig & jnp.any(cand)
                noden = jnp.maximum(res_node[r], 0)
                zone = numa_zone_for_node(
                    req, fc.needs_numa[i], chain[3][noden],
                    fc.numa_policy[noden])
                chain = commit_pod_state(fc_nom, prod_mode, chain, i,
                                         found, noden, zone)
                fnd = found.astype(jnp.float32)
                res_remain_ = res_remain_.at[r].add(-fnd * req)
                # allocate-once: consumed rows leave the candidate set
                # (the nominator's allocate_once && current_owners skip)
                # and arm next wave's Succeeded transition
                once_hit = found & (sides.res.once[r] > 0.5)
                res_avail_ = res_avail_.at[r].add(
                    -once_hit.astype(jnp.float32) * res_avail_[r])
                res_succ_ = res_succ_.at[r].set(
                    jnp.where(once_hit, i, res_succ_[r]))
                slot = jnp.where(found, cnt, P)
                out_p = out_p.at[slot].set(i, mode="drop")
                out_n = out_n.at[slot].set(res_node[r], mode="drop")
                out_z = out_z.at[slot].set(zone, mode="drop")
                out_r = out_r.at[slot].set(r, mode="drop")
                assigned_ = assigned_.at[i].set(assigned_[i] | found)
                return (chain, res_avail_, res_remain_, res_succ_,
                        out_p, out_n, out_z, out_r,
                        cnt + found.astype(jnp.int32), assigned_,
                        ncnt + found.astype(jnp.int32))

            nom_init = (
                (requested, est_sum, est_pr_state, numa_free, bind_free,
                 quota_used, aff_count, anti_cover, aff_exists, port_used,
                 vol_free),
                res_avail, res_remain, res_succ,
                out_pods, out_nodes, out_zones, out_res, n_out, assigned,
                nom_count,
            )
            nom_out = jax.lax.fori_loop(0, P, nom_body, nom_init)
            (chain0, res_avail, res_remain, res_succ,
             out_pods, out_nodes, out_zones, out_res, n_out, assigned,
             nom_count) = nom_out
            (requested, est_sum, est_pr_state, numa_free, bind_free,
             quota_used, aff_count, anti_cover, aff_exists, port_used,
             vol_free) = chain0
            if prod_mode:
                est_sum_prod = est_pr_state

        # the round's LoadAware base term, rebuilt-association exact:
        # est_sum folds committed estimates in bind order onto the
        # host's initial sum, then ONE add of the adjusted usage
        term = est_sum + sides.la_adj
        active = inputs.pod_valid & ~assigned
        base_w = inputs._replace(la_term_nonprod=term, pod_valid=active)
        if prod_mode:
            base_w = base_w._replace(
                la_term_prod=est_sum_prod + sides.prod.adj)
        fc_w = fc._replace(base=base_w)
        if has_claims:
            # the per-(pod, node) volume view at wave-start claim state:
            # what the next serial cycle's regrouped [P, VG'] gather
            # would produce (ops/volumes.py)
            fc_w = fc_w._replace(
                vol_needed=effective_vol_needed(
                    fc.vol_needed, fc.node_vol_group,
                    sides.claims.pod_claim, claim_new),
                node_vol_group=jnp.arange(N, dtype=jnp.int32))
        for tf in score_passes:
            # device-expressible ScoreTransformers (frameworkext.py): the
            # same rewrite their host before_score applies to the packed
            # batch, re-applied to each wave's rebuilt inputs
            fc_w = tf(fc_w)
        evaluate = make_pod_evaluator(fc_w, weight_idx, prod_mode,
                                      bal_idx,
                                      explain_terms=explain_full)

        if explain is not None:
            # per-wave attribution at wave-START state (post pre-pass,
            # exactly the state serial cycle w's packed batch holds): the
            # counts the driver's logical cycle w formats for pods it
            # leaves unbound (diagnose.py reads wave-start state, see
            # _WaveStateMirror)
            filter_state = (requested, numa_free, bind_free, quota_used,
                            aff_count, anti_cover, aff_exists,
                            port_used, vol_free)
            counts_w = explain_stage_counts(fc_w, evaluate, filter_state,
                                            n_real)
            ex_counts = jax.lax.dynamic_update_slice(
                ex_counts, counts_w[None], (w, 0, 0))

        # ---- pass 1: the serial round (identical tracing to
        # build_full_chain_step's body — decisions are by construction
        # what serial cycle w's kernel would decide)
        def body(i, state):
            if explain_full:
                chain_state, wterms, chosen = (state[:-2], state[-2],
                                               state[-1])
                (found, best, zone_at_best, _adm, score, _b, best_v,
                 la_row, numa_row, pref_row) = evaluate(i, *chain_state)
                runner = jnp.maximum(jnp.max(jnp.where(
                    jnp.arange(N, dtype=jnp.int32) == best,
                    -jnp.inf, score)), -1.0)
                wterms = wterms.at[i].set(jnp.stack([
                    la_row[best], numa_row[best], pref_row[best],
                    best_v, runner]))
            else:
                chain_state, chosen = state[:-1], state[-1]
                found, best, zone_at_best, _adm, _s, _b, _mv = evaluate(
                    i, *chain_state)
            chain_state = commit_pod_state(
                fc_w, prod_mode, chain_state, i, found, best,
                zone_at_best)
            chosen = chosen.at[i].set(
                jnp.where(found, best.astype(jnp.int32), -1))
            if explain_full:
                return chain_state + (wterms, chosen)
            return chain_state + (chosen,)

        init = (
            requested,
            jnp.zeros((N, R), jnp.float32),
            jnp.zeros((N, R), jnp.float32),
            numa_free,
            bind_free,
            quota_used,
            aff_count,
            anti_cover,
            aff_exists,
            port_used,
            vol_free,
        )
        if explain_full:
            init = init + (
                jnp.zeros((P, len(EXPLAIN_TERMS)), jnp.float32),)
        init = init + (jnp.full(P, -1, jnp.int32),)
        pass1 = jax.lax.fori_loop(0, P, body, init)
        chosen = pass1[-1]
        wave_terms = pass1[-2] if explain_full else None

        # ---- Permit barrier against the CARRIED assumed counters
        keep = gang_permit_mask(
            chosen, fc.gang_id, fc.gang_min_member, gang_assumed,
            fc.gang_group_id, num_gangs, num_groups,
        )
        kept = (chosen >= 0) & keep
        kept_count = jnp.sum(kept.astype(jnp.int32))
        if explain_full:
            # the wave that finally KEEPS a pod owns its attribution
            # row (a Permit-reverted choice never persisted host-side)
            ex_terms = jnp.where(kept[:, None], wave_terms, ex_terms)

        # ---- pass 2: kept-only replay from the WAVE-START state.
        # Reverted gang reservations never persisted host-side, so the
        # next wave's base state commits only survivors, in bind
        # order; est_sum rides the delta_np slot (est_sum_prod the
        # delta_pr slot) so the fold order matches the assign-cache
        # append order, and the NUMA zone is re-picked under replay
        # state (= what the host plugin's Reserve sees). Reservation
        # pseudo-pod rows commit their carry form (fc_carry).
        est_pr_rinit = (est_sum_prod if prod_mode
                        else jnp.zeros((N, R), jnp.float32))

        def rbody(i, st):
            chain_state = st[:11]
            out_p, out_n, out_z, out_r, cnt = st[11:]
            k = kept[i]
            best = jnp.maximum(chosen[i], 0)
            zone = numa_zone_for_node(
                fc.requests[i], fc_carry.needs_numa[i],
                chain_state[3][best], fc.numa_policy[best])
            chain_state = commit_pod_state(
                fc_carry, prod_mode, chain_state, i, k, best, zone)
            slot = jnp.where(k, cnt, P)
            out_p = out_p.at[slot].set(i, mode="drop")
            out_n = out_n.at[slot].set(chosen[i], mode="drop")
            out_z = out_z.at[slot].set(zone, mode="drop")
            out_r = out_r.at[slot].set(-1, mode="drop")
            return chain_state + (out_p, out_n, out_z, out_r,
                                  cnt + k.astype(jnp.int32))

        rinit = (
            requested,
            est_sum,                       # delta_np slot: the carry
            est_pr_rinit,                  # delta_pr slot: prod carry
            numa_free,
            bind_free,
            quota_used,
            aff_count,
            anti_cover,
            aff_exists,
            port_used,
            vol_free,
            out_pods, out_nodes, out_zones, out_res, n_out,
        )
        rout = jax.lax.fori_loop(0, P, rbody, rinit)
        (requested, est_sum, est_pr_out, numa_free, bind_free, quota_used,
         aff_count, anti_cover, aff_exists, port_used, vol_free,
         out_pods, out_nodes, out_zones, out_res, n_out) = rout
        if prod_mode:
            est_sum_prod = est_pr_out

        # the vol_needed consumed by pass 1/2 above is FROZEN wave-start
        # state (serial in-cycle semantics); the boundary rebuilds the
        # claim columns + the attachable count set-wise — what the next
        # serial cycle's attached-set recompute yields (ops/volumes.py)
        if has_claims:
            claim_new, vol_new, vol_free = advance_claim_state(
                chosen, kept, sides.claims.pod_claim,
                sides.claims.pod_nonhot, sides.claims.covered0,
                claim_new, vol_new, fc.vol_free)

        if has_res:
            # a KEPT reservation pseudo-pod row turned its CR Available
            # on its chosen node: consumable by the NEXT wave's
            # nomination pre-pass (pass 0b) — the closed rebalance
            # loop's migration Reservation lands here
            rows = sides.res.row_of
            rowc = jnp.maximum(rows, 0)
            became = ((rows >= 0) & kept[rowc]).astype(jnp.float32)
            res_avail = res_avail + became
            res_node = jnp.where(became > 0.5, chosen[rowc], res_node)

        in_gang = fc.gang_id >= 0
        gang_assumed = gang_assumed + jax.ops.segment_sum(
            (kept & in_gang).astype(jnp.float32),
            jnp.maximum(fc.gang_id, 0), num_segments=num_gangs)
        assigned = assigned | kept
        bound_count = kept_count + nom_count
        wave_counts = wave_counts.at[w].set(bound_count)
        # a zero-commit wave with no pending carried transition is a
        # fixpoint: the next wave would see identical state and commit
        # nothing again
        done = bound_count == 0
        if has_res:
            done = done & ~jnp.any(res_succ >= 0)
        new_carry = (assigned, requested, est_sum, numa_free, bind_free,
                     quota_used, aff_count, anti_cover, aff_exists,
                     port_used, vol_free, gang_assumed,
                     est_sum_prod if prod_mode else None,
                     claim_new if has_claims else None,
                     vol_new if has_claims else None,
                     res_avail if has_res else None,
                     res_remain if has_res else None,
                     res_node if has_res else None,
                     res_succ if has_res else None,
                     out_pods, out_nodes, out_zones, out_res, n_out,
                     wave_counts)
        if explain is not None:
            new_carry = new_carry + (ex_counts,)
            if explain_full:
                new_carry = new_carry + (ex_terms,)
        return new_carry + (w + 1, done)

    return wave_body


def initial_wave_carry(fc: FullChainInputs, sides: WaveSideInputs,
                       explain=None):
    """The chain step's wave-0 carry (WAVE_STATE_FIELDS layout), built
    from the same (possibly device-resident/sharded) arrays the fused
    init consumes. Feature-absent slots are None. Under koordexplain
    "full" the carry also holds the per-pod score-term rows
    (kept-wave-wins across the chain)."""
    P = fc.base.fit_requests.shape[0]
    N = fc.base.allocatable.shape[0]
    has_claims = sides.claims is not None
    has_res = sides.res is not None
    if has_claims:
        nc = sides.claims.pod_claim.shape[1]
        claim_new0 = jnp.zeros((N, nc), jnp.float32)
        vol_new0 = jnp.zeros(N, jnp.float32)
    else:
        claim_new0 = vol_new0 = None
    if has_res:
        nres = sides.res.rank.shape[0]
        res_avail0 = jnp.zeros(nres, jnp.float32)
        # a Pending CR entering the batch has nothing allocated yet: the
        # full packed template is the remainder
        res_remain0 = jnp.asarray(sides.res.alloc, jnp.float32)
        res_node0 = jnp.full(nres, -1, jnp.int32)
        res_succ0 = jnp.full(nres, -1, jnp.int32)
    else:
        res_avail0 = res_remain0 = None
        res_node0 = res_succ0 = None
    carry = (
        jnp.zeros(P, bool),
        fc.base.requested,
        sides.la_est,
        fc.numa_free,
        fc.bind_free,
        fc.quota_used,
        fc.aff_count,
        fc.anti_cover,
        jnp.asarray(fc.aff_exists, bool),
        fc.port_used,
        fc.vol_free,
        fc.gang_assumed,
        sides.prod.est if sides.prod is not None else None,
        claim_new0,
        vol_new0,
        res_avail0,
        res_remain0,
        res_node0,
        res_succ0,
    )
    if explain == "full":
        carry = carry + (
            jnp.zeros((P, len(EXPLAIN_TERMS)), jnp.float32),)
    return carry


def build_fused_wave_step(args: LoadAwareArgs, num_gangs: int,
                          num_groups: int, waves: int, jit: bool = True,
                          active_axes=None, explain=None,
                          prod: bool = False, claims: bool = False,
                          res: bool = False, score_passes=()):
    """(FullChainInputs, WaveSideInputs) -> FusedWaveOut.

    ``sides`` carries the LoadAware nonprod score-term split
    (build_loadaware_node_state's ``la_est_nonprod``/``la_adj_nonprod``),
    sliced to the same active axes as the rest of the batch, plus the
    optional prod split, hot-claim factorization and reservation rows —
    the ``prod``/``claims``/``res`` flags pin which optional blocks the
    trace expects (the driver keys its step cache on them).

    ``explain`` (None | "counts" | "full", koordexplain): the step takes an
    extra ``n_real`` int32 operand and returns (FusedWaveOut, ExplainOut)
    with per-WAVE stage counts [waves, P, NUM_EXPLAIN_STAGES], each wave's
    row computed at wave-START state — exactly the state the driver's
    legacy host mirror (_WaveStateMirror) would hand diagnose.py for that
    logical cycle. "full" additionally carries the winning node's score
    terms for each pod across waves (the wave that finally kept the pod
    wins the row). Decisions are untouched: attribution is extra carried
    outputs only.
    """
    if not 1 <= waves <= MAX_WAVES:
        raise ValueError(f"waves must be in [1, {MAX_WAVES}], got {waves}")
    _check_wave_args(args, prod)
    weight_idx = resolve_weight_idx(args, active_axes)
    bal_idx = resolve_balance_idx(active_axes)
    prod_mode = args.score_according_prod_usage
    explain_full = explain == "full"

    def _step_impl(fc: FullChainInputs, sides: WaveSideInputs, n_real):
        inputs = fc.base
        P, _R = inputs.fit_requests.shape

        wave_body = _make_wave_body(fc, sides, n_real, weight_idx,
                                    bal_idx, num_gangs, num_groups,
                                    explain, prod_mode,
                                    score_passes=score_passes)

        def cond(carry):
            w, done = carry[-2], carry[-1]
            return (w < waves) & ~done

        # the parity-critical wave-state slots come from the SAME
        # builder the chain's wave-0 carry uses — the two dispatch
        # shapes cannot desynchronize their initial state
        init = initial_wave_carry(fc, sides) + (
            jnp.full(P, -1, jnp.int32),
            jnp.full(P, -1, jnp.int32),
            jnp.full(P, -1, jnp.int32),
            jnp.full(P, -1, jnp.int32),
            jnp.int32(0),
            jnp.zeros(waves, jnp.int32),
        )
        if explain is not None:
            init = init + (
                jnp.zeros((waves, P, NUM_EXPLAIN_STAGES), jnp.uint32),)
            if explain_full:
                init = init + (
                    jnp.zeros((P, len(EXPLAIN_TERMS)), jnp.float32),)
        init = init + (jnp.int32(0), jnp.bool_(False))
        out = jax.lax.while_loop(cond, wave_body, init)
        fw = FusedWaveOut(
            bind_pods=out[_OUT_PODS], bind_nodes=out[_OUT_NODES],
            bind_zones=out[_OUT_ZONES], bind_res=out[_OUT_RES],
            wave_counts=out[_WAVE_COUNTS], waves_run=out[-2])
        if explain is None:
            return fw
        return fw, ExplainOut(out[_EX_COUNTS],
                              out[_EX_TERMS] if explain_full else None)

    if explain is None:
        def step(fc: FullChainInputs, sides: WaveSideInputs):
            return _step_impl(fc, sides, None)
    else:
        def step(fc: FullChainInputs, sides: WaveSideInputs, n_real):
            return _step_impl(fc, sides, n_real)

    return jax.jit(step) if jit else step


def build_chained_wave_step(args: LoadAwareArgs, num_gangs: int,
                            num_groups: int, jit: bool = True,
                            active_axes=None, explain=None,
                            prod: bool = False, claims: bool = False,
                            res: bool = False, score_passes=()):
    """ONE wave per dispatch, carried state on device between dispatches.

    (FullChainInputs, carry, WaveSideInputs) -> (carry', WaveChainOut),
    where ``carry`` is the initial_wave_carry tuple (or a previous
    dispatch's output carry — the arrays never leave the device between
    waves). Under koordexplain the step takes the extra ``n_real``
    operand and returns (carry', WaveChainOut, counts_row[P, S]) — this
    wave's attribution at wave-START state, the exact row the fused
    step's [K, P, S] buffer holds at index w.

    K-independent by construction: the cycle driver chains as many
    dispatches as the wave budget needs, so every K shares one compiled
    program, and — the point of the chain — wave w+1 can be dispatched
    BEFORE wave w's rows are read back, overlapping the host replay of
    wave w with device execution of wave w+1. A zero ``count`` readback
    is the fixpoint signal (the fused while_loop's early exit); the
    driver stops consuming there (tracking the pending-transition flag
    host-side — a consumed allocate-once reservation arms one more
    wave, see scheduler/cycle.py).
    """
    _check_wave_args(args, prod)
    weight_idx = resolve_weight_idx(args, active_axes)
    bal_idx = resolve_balance_idx(active_axes)
    prod_mode = args.score_according_prod_usage
    explain_full = explain == "full"

    def _step_impl(fc: FullChainInputs, carry, sides: WaveSideInputs,
                   n_real):
        P = fc.base.fit_requests.shape[0]
        wave_body = _make_wave_body(fc, sides, n_real, weight_idx,
                                    bal_idx, num_gangs, num_groups,
                                    explain, prod_mode,
                                    score_passes=score_passes)
        full = tuple(carry[:NUM_WAVE_STATE]) + (
            jnp.full(P, -1, jnp.int32),
            jnp.full(P, -1, jnp.int32),
            jnp.full(P, -1, jnp.int32),
            jnp.full(P, -1, jnp.int32),
            jnp.int32(0),
            jnp.zeros(1, jnp.int32),
        )
        if explain is not None:
            full = full + (
                jnp.zeros((1, P, NUM_EXPLAIN_STAGES), jnp.uint32),)
            if explain_full:
                full = full + (carry[NUM_WAVE_STATE],)
        full = full + (jnp.int32(0), jnp.bool_(False))
        out = wave_body(full)
        new_carry = tuple(out[:NUM_WAVE_STATE])
        if explain_full:
            new_carry = new_carry + (out[_EX_TERMS],)
        rows = WaveChainOut(bind_pods=out[_OUT_PODS],
                            bind_nodes=out[_OUT_NODES],
                            bind_zones=out[_OUT_ZONES],
                            bind_res=out[_OUT_RES],
                            count=out[_N_OUT])
        if explain is None:
            return new_carry, rows
        return new_carry, rows, out[_EX_COUNTS][0]

    if explain is None:
        def step(fc: FullChainInputs, carry, sides: WaveSideInputs):
            return _step_impl(fc, carry, sides, None)
    else:
        def step(fc: FullChainInputs, carry, sides: WaveSideInputs,
                 n_real):
            return _step_impl(fc, carry, sides, n_real)

    return jax.jit(step) if jit else step
