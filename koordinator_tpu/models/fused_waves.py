"""Fused multi-wave scheduling: K dependent rounds in ONE device dispatch.

BENCH_r05 measured the production cycle's shape: a 10k x 5k full-chain
round costs ~17ms of marginal kernel but every dispatch pays ~66ms of
fixed overhead (dispatch + result-readback RTT through the axon tunnel),
so realized throughput sits at ~1/5 of the marginal ceiling. The on-chip
chain bench (BENCH_ONCHIP_CHAINS_*) already proved that chaining rounds
inside one jit cancels the fixed cost; this module gives the production
cycle that capability.

One fused dispatch runs up to K WAVES, where wave w is exactly the
scheduling round serial cycle w would run:

  wave body =
    1. evaluation pass — the serial full-chain round (the same
       ``make_pod_evaluator`` + ``commit_pod_state`` the single-round
       kernel traces, models/full_chain.py) over the still-pending pods,
       producing tentative bindings with in-round state feedback;
    2. gang Permit against the CARRIED assumed counters;
    3. kept-only replay pass — the next wave's state is rebuilt from the
       wave-start state by committing ONLY the pods that survived Permit,
       in bind order. This mirrors what the host does between serial
       cycles: reverted gang members never reach the store, so their
       in-round reservations must not leak into the next round's state
       (and NUMA zone choices are re-picked under the kept-only state,
       the same way the host plugin allocates at Reserve).

Carried device state: node requested/NUMA-free/bindable-cpu/port/volume
state, quota used along the ancestor chains, gang assumed counters, the
pod assigned-mask, and the LoadAware assigned-estimate sum ``est_sum``.
The LoadAware score term is recomputed per wave as ``est_sum + adjusted``
— the SAME two-operand association a next-cycle host rebuild produces
(ops/loadaware.py exports the split), so carried state is bit-identical
to what serial cycle w's snapshot would contain. A pod rejected in wave i
because a node filled up (or a gang's quota was transiently held) retries
in wave i+1 on-device, with no host round-trip.

The ONE wave body (``_make_wave_body``) backs two dispatch shapes:

  * ``build_fused_wave_step`` — all K waves under ``lax.while_loop`` in
    one program, compacted (pod_idx, node_idx, zone) readback at the
    end. Early exit: a wave that commits nothing proves the fixpoint.
    This is the ``KOORD_TPU_REPLAY_OVERLAP=0`` path: the host replay of
    every wave runs serially after the single readback.
  * ``build_chained_wave_step`` — ONE wave per dispatch with the carried
    state staying on device between dispatches. The cycle driver
    (scheduler/cycle.py) dispatches wave w+1 asynchronously BEFORE
    syncing wave w's rows, so the host-side replay of wave w overlaps
    device execution of wave w+1 — the replay queue architecture. The
    step is K-independent, so every wave depth shares one compiled
    program. Tracing the SAME wave body keeps the chain bit-identical
    to the fused while_loop (pipeline_parity.run_replay_overlap_parity
    gates it).

Readback is COMPACTED: a (pod_idx, node_idx, zone) binding buffer plus
per-wave bound counts — not K full assignment vectors and none of the
score/state matrices. The driver (scheduler/cycle.py) replays the waves
host-side as logical cycles; scheduler/pipeline_parity.py gates that a
fused-K cycle is byte-identical to K sequential single-round cycles.

Known demotions (the driver falls back to K=1, the exact serial path):
pending Reservation CRs (a CR bound in wave 1 changes the next cycle's
nomination pre-pass), pending pods carrying PVCs (volume-group
factorization regroups between cycles), ``score_according_prod_usage``
(the prod score term is not carried in split form), and the gRPC sidecar
path (the remote protocol is single-round).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from koordinator_tpu.models.full_chain import (
    EXPLAIN_TERMS,
    NUM_EXPLAIN_STAGES,
    ExplainOut,
    FullChainInputs,
    commit_pod_state,
    explain_stage_counts,
    make_pod_evaluator,
    resolve_balance_idx,
    resolve_weight_idx,
)
from koordinator_tpu.ops.gang import gang_permit_mask
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.ops.numa import numa_zone_for_node

MAX_WAVES = 8  # bounds the compile-cache key space; auto-K never exceeds it

# carried wave state (the chain step's explicit carry): index layout of
# the first 12 slots of the while_loop carry — scheduler/cycle.py builds
# the initial tuple via initial_wave_carry and threads the chain's output
# carry back in unchanged
WAVE_STATE_FIELDS = (
    "assigned", "requested", "est_sum", "numa_free", "bind_free",
    "quota_used", "aff_count", "anti_cover", "aff_exists", "port_used",
    "vol_free", "gang_assumed",
)
NUM_WAVE_STATE = len(WAVE_STATE_FIELDS)
# wave-state slots indexed [N, ...] (node axis): sharded over the mesh in
# the sharded chain step; everything else (pod/quota/gang/term axes)
# replicated. est_sum (slot 2) is the node-axis LoadAware estimate sum.
WAVE_STATE_NODE_SLOTS = frozenset({1, 2, 3, 4, 6, 7, 9, 10})


class FusedWaveOut(NamedTuple):
    """Compacted readback of one fused dispatch."""

    bind_pods: jnp.ndarray    # [P] int32 pod row indices in bind order, -1 pad
    bind_nodes: jnp.ndarray   # [P] int32 node index per binding
    bind_zones: jnp.ndarray   # [P] int32 replay-state NUMA zone (-1 = spread)
    wave_counts: jnp.ndarray  # [K] int32 bindings committed per wave
    waves_run: jnp.ndarray    # scalar int32 wave bodies actually executed


class WaveChainOut(NamedTuple):
    """Compacted readback of ONE chained wave dispatch."""

    bind_pods: jnp.ndarray   # [P] int32 this wave's pod rows in bind order
    bind_nodes: jnp.ndarray  # [P] int32 node index per binding
    bind_zones: jnp.ndarray  # [P] int32 replay-state NUMA zone (-1 = spread)
    count: jnp.ndarray       # scalar int32 bindings this wave (0 = fixpoint)


def _check_wave_args(args: LoadAwareArgs) -> None:
    if args.score_according_prod_usage:
        # the prod-branch term is not carried in split form; the driver
        # demotes to the serial path before ever building this step
        raise ValueError("fused waves do not support "
                         "score_according_prod_usage — use the serial step")


def _make_wave_body(fc: FullChainInputs, la_adj, n_real, weight_idx,
                    bal_idx, num_gangs: int, num_groups: int, explain):
    """The ONE wave body both dispatch shapes trace.

    ``carry`` layout: WAVE_STATE_FIELDS (12 slots), then out_pods /
    out_nodes / out_zones / n_out / wave_counts, then [ex_counts]
    [ex_terms] under koordexplain, then (w, done). Returns the same
    layout with w+1 and the fixpoint flag. Extracted verbatim from the
    original while_loop body so the fused step and the chained step
    cannot drift — byte parity between them is by construction of the
    trace, and pipeline_parity gates it empirically.
    """
    inputs = fc.base
    P, R = inputs.fit_requests.shape
    N = inputs.allocatable.shape[0]
    prod_mode = False
    explain_full = explain == "full"

    def wave_body(carry):
        (assigned, requested, est_sum, numa_free, bind_free, quota_used,
         aff_count, anti_cover, aff_exists, port_used, vol_free,
         gang_assumed, out_pods, out_nodes, out_zones, n_out,
         wave_counts) = carry[:17]
        w, done = carry[-2], carry[-1]
        if explain is not None:
            ex_counts = carry[17]
            ex_terms = carry[18] if explain_full else None

        # the round's LoadAware base term, rebuilt-association exact:
        # est_sum folds committed estimates in bind order onto the
        # host's initial sum, then ONE add of the adjusted usage
        term = est_sum + la_adj
        active = inputs.pod_valid & ~assigned
        fc_w = fc._replace(base=inputs._replace(
            la_term_nonprod=term, pod_valid=active))
        evaluate = make_pod_evaluator(fc_w, weight_idx, prod_mode,
                                      bal_idx,
                                      explain_terms=explain_full)

        if explain is not None:
            # per-wave attribution at wave-START state: the counts the
            # driver's logical cycle w formats for pods it leaves
            # unbound (diagnose.py reads wave-start state, see
            # _WaveStateMirror)
            filter_state = (requested, numa_free, bind_free, quota_used,
                            aff_count, anti_cover, aff_exists,
                            port_used, vol_free)
            counts_w = explain_stage_counts(fc_w, evaluate, filter_state,
                                            n_real)
            ex_counts = jax.lax.dynamic_update_slice(
                ex_counts, counts_w[None], (w, 0, 0))

        # ---- pass 1: the serial round (identical tracing to
        # build_full_chain_step's body — decisions are by construction
        # what serial cycle w's kernel would decide)
        def body(i, state):
            if explain_full:
                chain_state, wterms, chosen = (state[:-2], state[-2],
                                               state[-1])
                (found, best, zone_at_best, _adm, score, _b, best_v,
                 la_row, numa_row, pref_row) = evaluate(i, *chain_state)
                runner = jnp.maximum(jnp.max(jnp.where(
                    jnp.arange(N, dtype=jnp.int32) == best,
                    -jnp.inf, score)), -1.0)
                wterms = wterms.at[i].set(jnp.stack([
                    la_row[best], numa_row[best], pref_row[best],
                    best_v, runner]))
            else:
                chain_state, chosen = state[:-1], state[-1]
                found, best, zone_at_best, _adm, _s, _b, _mv = evaluate(
                    i, *chain_state)
            chain_state = commit_pod_state(
                fc_w, prod_mode, chain_state, i, found, best,
                zone_at_best)
            chosen = chosen.at[i].set(
                jnp.where(found, best.astype(jnp.int32), -1))
            if explain_full:
                return chain_state + (wterms, chosen)
            return chain_state + (chosen,)

        init = (
            requested,
            jnp.zeros((N, R), jnp.float32),
            jnp.zeros((N, R), jnp.float32),
            numa_free,
            bind_free,
            quota_used,
            aff_count,
            anti_cover,
            aff_exists,
            port_used,
            vol_free,
        )
        if explain_full:
            init = init + (
                jnp.zeros((P, len(EXPLAIN_TERMS)), jnp.float32),)
        init = init + (jnp.full(P, -1, jnp.int32),)
        pass1 = jax.lax.fori_loop(0, P, body, init)
        chosen = pass1[-1]
        wave_terms = pass1[-2] if explain_full else None

        # ---- Permit barrier against the CARRIED assumed counters
        keep = gang_permit_mask(
            chosen, fc.gang_id, fc.gang_min_member, gang_assumed,
            fc.gang_group_id, num_gangs, num_groups,
        )
        kept = (chosen >= 0) & keep
        kept_count = jnp.sum(kept.astype(jnp.int32))
        if explain_full:
            # the wave that finally KEEPS a pod owns its attribution
            # row (a Permit-reverted choice never persisted host-side)
            ex_terms = jnp.where(kept[:, None], wave_terms, ex_terms)

        # ---- pass 2: kept-only replay from the WAVE-START state.
        # Reverted gang reservations never persisted host-side, so the
        # next wave's base state commits only survivors, in bind
        # order; est_sum rides the delta_np slot so the fold order
        # matches the assign-cache append order, and the NUMA zone is
        # re-picked under replay state (= what the host plugin's
        # Reserve sees).
        def rbody(i, st):
            chain_state = st[:11]
            out_p, out_n, out_z, cnt = st[11:]
            k = kept[i]
            best = jnp.maximum(chosen[i], 0)
            zone = numa_zone_for_node(
                fc.requests[i], fc.needs_numa[i],
                chain_state[3][best], fc.numa_policy[best])
            chain_state = commit_pod_state(
                fc_w, prod_mode, chain_state, i, k, best, zone)
            slot = jnp.where(k, cnt, P)
            out_p = out_p.at[slot].set(i, mode="drop")
            out_n = out_n.at[slot].set(chosen[i], mode="drop")
            out_z = out_z.at[slot].set(zone, mode="drop")
            return chain_state + (out_p, out_n, out_z,
                                  cnt + k.astype(jnp.int32))

        rinit = (
            requested,
            est_sum,                       # delta_np slot: the carry
            jnp.zeros((N, R), jnp.float32),  # delta_pr: dead (prod off)
            numa_free,
            bind_free,
            quota_used,
            aff_count,
            anti_cover,
            aff_exists,
            port_used,
            vol_free,
            out_pods, out_nodes, out_zones, n_out,
        )
        rout = jax.lax.fori_loop(0, P, rbody, rinit)
        (requested, est_sum, _dpr, numa_free, bind_free, quota_used,
         aff_count, anti_cover, aff_exists, port_used, vol_free,
         out_pods, out_nodes, out_zones, n_out) = rout

        in_gang = fc.gang_id >= 0
        gang_assumed = gang_assumed + jax.ops.segment_sum(
            (kept & in_gang).astype(jnp.float32),
            jnp.maximum(fc.gang_id, 0), num_segments=num_gangs)
        assigned = assigned | kept
        wave_counts = wave_counts.at[w].set(kept_count)
        # a zero-commit wave is a fixpoint: the next wave would see
        # identical state and commit nothing again
        done = kept_count == 0
        new_carry = (assigned, requested, est_sum, numa_free, bind_free,
                     quota_used, aff_count, anti_cover, aff_exists,
                     port_used, vol_free, gang_assumed, out_pods,
                     out_nodes, out_zones, n_out, wave_counts)
        if explain is not None:
            new_carry = new_carry + (ex_counts,)
            if explain_full:
                new_carry = new_carry + (ex_terms,)
        return new_carry + (w + 1, done)

    return wave_body


def initial_wave_carry(fc: FullChainInputs, la_est, explain=None):
    """The chain step's wave-0 carry (WAVE_STATE_FIELDS layout), built
    from the same (possibly device-resident/sharded) arrays the fused
    init consumes. ``la_est`` is the LoadAware ``la_est_nonprod`` side
    array. Under koordexplain "full" the carry also holds the per-pod
    score-term rows (kept-wave-wins across the chain)."""
    P = fc.base.fit_requests.shape[0]
    carry = (
        jnp.zeros(P, bool),
        fc.base.requested,
        la_est,
        fc.numa_free,
        fc.bind_free,
        fc.quota_used,
        fc.aff_count,
        fc.anti_cover,
        jnp.asarray(fc.aff_exists, bool),
        fc.port_used,
        fc.vol_free,
        fc.gang_assumed,
    )
    if explain == "full":
        carry = carry + (
            jnp.zeros((P, len(EXPLAIN_TERMS)), jnp.float32),)
    return carry


def build_fused_wave_step(args: LoadAwareArgs, num_gangs: int,
                          num_groups: int, waves: int, jit: bool = True,
                          active_axes=None, explain=None):
    """(FullChainInputs, la_est[N, R], la_adj[N, R]) -> FusedWaveOut.

    ``la_est``/``la_adj`` are the LoadAware nonprod score-term split
    (build_loadaware_node_state's ``la_est_nonprod``/``la_adj_nonprod``),
    sliced to the same active axes as the rest of the batch.

    ``explain`` (None | "counts" | "full", koordexplain): the step takes an
    extra ``n_real`` int32 operand and returns (FusedWaveOut, ExplainOut)
    with per-WAVE stage counts [waves, P, NUM_EXPLAIN_STAGES], each wave's
    row computed at wave-START state — exactly the state the driver's
    legacy host mirror (_WaveStateMirror) would hand diagnose.py for that
    logical cycle. "full" additionally carries the winning node's score
    terms for each pod across waves (the wave that finally kept the pod
    wins the row). Decisions are untouched: attribution is extra carried
    outputs only.
    """
    if not 1 <= waves <= MAX_WAVES:
        raise ValueError(f"waves must be in [1, {MAX_WAVES}], got {waves}")
    _check_wave_args(args)
    weight_idx = resolve_weight_idx(args, active_axes)
    bal_idx = resolve_balance_idx(active_axes)
    explain_full = explain == "full"

    def _step_impl(fc: FullChainInputs, la_est, la_adj, n_real):
        inputs = fc.base
        P, _R = inputs.fit_requests.shape

        wave_body = _make_wave_body(fc, la_adj, n_real, weight_idx,
                                    bal_idx, num_gangs, num_groups,
                                    explain)

        def cond(carry):
            w, done = carry[-2], carry[-1]
            return (w < waves) & ~done

        # the 12 parity-critical wave-state slots come from the SAME
        # builder the chain's wave-0 carry uses — the two dispatch
        # shapes cannot desynchronize their initial state
        init = initial_wave_carry(fc, la_est) + (
            jnp.full(P, -1, jnp.int32),
            jnp.full(P, -1, jnp.int32),
            jnp.full(P, -1, jnp.int32),
            jnp.int32(0),
            jnp.zeros(waves, jnp.int32),
        )
        if explain is not None:
            init = init + (
                jnp.zeros((waves, P, NUM_EXPLAIN_STAGES), jnp.uint32),)
            if explain_full:
                init = init + (
                    jnp.zeros((P, len(EXPLAIN_TERMS)), jnp.float32),)
        init = init + (jnp.int32(0), jnp.bool_(False))
        out = jax.lax.while_loop(cond, wave_body, init)
        fw = FusedWaveOut(
            bind_pods=out[12], bind_nodes=out[13], bind_zones=out[14],
            wave_counts=out[16], waves_run=out[-2])
        if explain is None:
            return fw
        return fw, ExplainOut(out[17], out[18] if explain_full else None)

    if explain is None:
        def step(fc: FullChainInputs, la_est, la_adj):
            return _step_impl(fc, la_est, la_adj, None)
    else:
        def step(fc: FullChainInputs, la_est, la_adj, n_real):
            return _step_impl(fc, la_est, la_adj, n_real)

    return jax.jit(step) if jit else step


def build_chained_wave_step(args: LoadAwareArgs, num_gangs: int,
                            num_groups: int, jit: bool = True,
                            active_axes=None, explain=None):
    """ONE wave per dispatch, carried state on device between dispatches.

    (FullChainInputs, carry, la_adj[N, R]) -> (carry', WaveChainOut),
    where ``carry`` is the initial_wave_carry tuple (or a previous
    dispatch's output carry — the arrays never leave the device between
    waves). Under koordexplain the step takes the extra ``n_real``
    operand and returns (carry', WaveChainOut, counts_row[P, S]) — this
    wave's attribution at wave-START state, the exact row the fused
    step's [K, P, S] buffer holds at index w.

    K-independent by construction: the cycle driver chains as many
    dispatches as the wave budget needs, so every K shares one compiled
    program, and — the point of the chain — wave w+1 can be dispatched
    BEFORE wave w's rows are read back, overlapping the host replay of
    wave w with device execution of wave w+1. A zero ``count`` readback
    is the fixpoint signal (the fused while_loop's early exit); the
    driver stops consuming there.
    """
    _check_wave_args(args)
    weight_idx = resolve_weight_idx(args, active_axes)
    bal_idx = resolve_balance_idx(active_axes)
    explain_full = explain == "full"

    def _step_impl(fc: FullChainInputs, carry, la_adj, n_real):
        P = fc.base.fit_requests.shape[0]
        wave_body = _make_wave_body(fc, la_adj, n_real, weight_idx,
                                    bal_idx, num_gangs, num_groups,
                                    explain)
        full = tuple(carry[:NUM_WAVE_STATE]) + (
            jnp.full(P, -1, jnp.int32),
            jnp.full(P, -1, jnp.int32),
            jnp.full(P, -1, jnp.int32),
            jnp.int32(0),
            jnp.zeros(1, jnp.int32),
        )
        if explain is not None:
            full = full + (
                jnp.zeros((1, P, NUM_EXPLAIN_STAGES), jnp.uint32),)
            if explain_full:
                full = full + (carry[NUM_WAVE_STATE],)
        full = full + (jnp.int32(0), jnp.bool_(False))
        out = wave_body(full)
        new_carry = tuple(out[:NUM_WAVE_STATE])
        if explain_full:
            new_carry = new_carry + (out[18],)
        rows = WaveChainOut(bind_pods=out[12], bind_nodes=out[13],
                            bind_zones=out[14], count=out[15])
        if explain is None:
            return new_carry, rows
        return new_carry, rows, out[17][0]

    if explain is None:
        def step(fc: FullChainInputs, carry, la_adj):
            return _step_impl(fc, carry, la_adj, None)
    else:
        def step(fc: FullChainInputs, carry, la_adj, n_real):
            return _step_impl(fc, carry, la_adj, n_real)

    return jax.jit(step) if jit else step
