"""Full plugin-chain scheduling step (BASELINE config 4).

Fuses the reference's whole hot loop (SURVEY.md section 3.1) into one compiled
program per batch:

  PreFilter   gang validity (host precompute) + quota admission (in-loop, order
              dependent) + NUMA/cpuset prechecks
  Filter      NodeResourcesFit + LoadAware thresholds + NodeNUMAResource admit
              (cpuset capacity, SMT alignment, NUMA topology policy)
  Score       LoadAware least-allocated + NodeNUMAResource least-allocated,
              equal plugin weights, summed (frameworkext RunScorePlugins
              normalize+weighted-sum)
  Reserve     on-device state updates: Fit requested, LoadAware assign-cache
              deltas, NUMA zone free, bindable-cpu free, quota used
  Permit      gang barrier as a segment-reduction post-pass (ops/gang.py)

Reservation consumption and concrete device/cpuset assignment remain host-side in
the cycle driver (scheduler/cycle.py): they run once per actual binding, not per
pod x node. The serial parity emulator (scheduler/parity.py serial_schedule_full)
implements the identical chain scalar-wise; bindings must match exactly.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.models.scheduler_model import ScheduleInputs, _score_row
from koordinator_tpu.ops import loadaware as la_ops
from koordinator_tpu.ops.fit import fit_ok_row
from koordinator_tpu.ops.gang import gang_permit_mask
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.ops.numa import (
    cpuset_filter_row,
    numa_admit_row,
    numa_score_row,
    numa_spread_fill,
)
from koordinator_tpu.ops.quota import quota_admit_row, quota_used_add_row

# ---------------------------------------------------------------------------
# koordexplain: the per-filter-stage reject taxonomy shared by the on-device
# attribution pass (explain_stage_counts), the host-numpy oracle
# (scheduler/diagnose.py host_stage_counts) and the /explain surfaces.
# ORDER IS LOAD-BEARING: it is the insertion order of diagnose.py's legacy
# reasons dict, and format_stage_counts relies on a stable sort over it to
# reproduce the legacy message tie-break byte-for-byte.
# ---------------------------------------------------------------------------
EXPLAIN_STAGES = (
    "node not schedulable",
    "taint/selector/volume-topology mismatch",
    "insufficient resources",
    "node load over threshold",
    "hostPort in use",
    "CSI volume limit exceeded",
    "insufficient bindable CPUs",
    "NUMA topology cannot fit",
    "affinity/anti-affinity/spread mismatch",
)
# prometheus-safe stage keys for koord_scheduler_filter_rejections_total
EXPLAIN_STAGE_KEYS = (
    "node_not_schedulable",
    "taint_selector_volume_topology",
    "insufficient_resources",
    "node_load_over_threshold",
    "host_port_in_use",
    "csi_volume_limit",
    "insufficient_bindable_cpus",
    "numa_topology",
    "affinity_spread",
    "gang_not_satisfied",
    "quota_exhausted",
)
# pod-level PreFilter verdict slots appended after the per-node stages
# (0/1 flags, not node counts — they reproduce diagnose.py's early returns)
EXPLAIN_STAGE_GANG = len(EXPLAIN_STAGES)
EXPLAIN_STAGE_QUOTA = len(EXPLAIN_STAGES) + 1
NUM_EXPLAIN_STAGES = len(EXPLAIN_STAGES) + 2

# per-plugin score-term slots of ExplainOut.terms rows (the "full" level)
EXPLAIN_TERMS = ("LoadAware", "NodeNUMAResource", "Preferred",
                 "best_score", "runner_up")


class ExplainOut(NamedTuple):
    """Attribution readback riding the scheduling dispatch.

    ``stage_counts``: [P, NUM_EXPLAIN_STAGES] uint32 per-pod rejected-node
    counts over the REAL (unpadded) nodes, evaluated at cycle-start state —
    the same state scheduler/diagnose.py reads — plus the two pod-level
    PreFilter verdict slots. The fused wave step emits [K, P, ...], one row
    per wave at wave-start state. ``terms``: [P, len(EXPLAIN_TERMS)] f32
    decision-time score attribution (None below the "full" level)."""

    stage_counts: jnp.ndarray
    terms: jnp.ndarray  # or None


class FullChainInputs(NamedTuple):
    base: ScheduleInputs
    # pods
    requests: jnp.ndarray       # [P, R] raw requests (quota/NUMA accounting)
    gang_id: jnp.ndarray        # [P] int32
    quota_id: jnp.ndarray       # [P] int32
    needs_numa: jnp.ndarray     # [P] bool — subject to NUMA admission
    needs_bind: jnp.ndarray     # [P] bool — requires cpuset binding
    cores_needed: jnp.ndarray   # [P] float — whole cpus for cpuset pods
    full_pcpus: jnp.ndarray     # [P] bool — resolved FullPCPUs policy
    pod_taint_mask: jnp.ndarray  # [P] f32 bitmask of admitted node groups
    #     (taints tolerated AND node selector/affinity satisfied —
    #     ops/taints.py)
    pod_aff_req: jnp.ndarray    # [P, T] bool — required pod-affinity terms
    pod_anti_req: jnp.ndarray   # [P, T] bool — required anti-affinity terms
    pod_aff_match: jnp.ndarray  # [P, T] bool — pod's labels match term
    pod_spread_skew: jnp.ndarray  # [P, T] f32 — DoNotSchedule topology
    #     spread maxSkew over term t's domains (0 = no constraint)
    pod_pref_id: jnp.ndarray    # [P] int32 preferred-affinity profile (-1)
    pod_ppref_id: jnp.ndarray   # [P] int32 preferred POD-affinity profile
    pod_ppref_mask: jnp.ndarray  # [P, T] bool — terms the profile weighs
    #     (the wave kernel's conflict rule)
    pod_port_wants: jnp.ndarray  # [P, PT] bool — hostPort slots requested
    #     (ops/ports.py NodePorts factorization)
    vol_needed: jnp.ndarray     # [P, VG] f32 — NEW PVC attachments the pod
    #     adds on a node of volume-group g: distinct claims minus claims
    #     already attached there (upstream NodeVolumeLimits counts only new
    #     attachments). VG==1 ("no pending claim attached anywhere") is the
    #     common case and collapses to the plain per-pod count.
    pod_img_id: jnp.ndarray     # [P] int32 ImageLocality profile (-1)
    # nodes
    node_taint_group: jnp.ndarray  # [N] int32 admission-signature group
    aff_dom: jnp.ndarray        # [N, T] f32 topology domain id (-1 invalid)
    aff_count: jnp.ndarray      # [N, T] f32 matching pods in n's domain
    anti_cover: jnp.ndarray     # [N, T] f32 pods CARRYING term t as required
    #     anti-affinity in n's domain (symmetric anti-affinity — upstream
    #     existingAntiAffinityCounts); blocks incoming pods MATCHING t
    aff_exists: jnp.ndarray     # [T] bool — any matching pod anywhere
    #     (domain-labeled or not; drives the first-replica bootstrap)
    pref_scores: jnp.ndarray    # [N, S] f32 preferred-node-affinity score
    #     rows (0..100 per profile, static — ops/podaffinity.py)
    port_used: jnp.ndarray      # [N, PT] f32 — hostPort slot in use on n
    vol_free: jnp.ndarray       # [N] f32 — attachable CSI volumes left
    #     (+inf when the node reports no limit)
    node_vol_group: jnp.ndarray  # [N] int32 — volume-group id: nodes whose
    #     attached-claim sets intersect the pending batch's claims
    #     identically share a group (group 0 = empty intersection)
    img_scores: jnp.ndarray     # [N, max(SI,1)] f32 ImageLocality rows
    ppref_w: jnp.ndarray        # [max(S2,1), max(T,1)] f32 per-profile term
    #     weights for preferred pod affinity (negative = anti preference)
    numa_free: jnp.ndarray      # [N, K, R]
    numa_capacity: jnp.ndarray  # [N, K, R]
    numa_policy: jnp.ndarray    # [N] int32
    has_topology: jnp.ndarray   # [N] bool
    bind_free: jnp.ndarray      # [N] float
    cpus_per_core: jnp.ndarray  # [N] float
    # quota tree
    quota_ancestors: jnp.ndarray  # [G, D]
    quota_used: jnp.ndarray       # [G, R]
    quota_runtime: jnp.ndarray    # [G, R]
    # gangs
    gang_min_member: jnp.ndarray  # [NG]
    gang_assumed: jnp.ndarray     # [NG]
    gang_valid: jnp.ndarray       # [NG] bool (PreFilter validity)
    gang_group_id: jnp.ndarray    # [NG] int32


def resolve_weight_idx(args: LoadAwareArgs, active_axes):
    """Weight-axis resolution shared by every full-chain kernel, so the serial
    and wave kernels can never trace different weight sets."""
    full_weights = args.weight_vector()
    if active_axes is not None:
        full_weights = full_weights[list(active_axes)]
    return tuple(int(i) for i in np.nonzero(full_weights)[0])


def resolve_balance_idx(active_axes):
    """(cpu_axis, mem_axis) positions after active-axes slicing, for the
    NodeResourcesBalancedAllocation score; (-1, -1) when either axis was
    sliced away (score contributes 0 then — upstream needs both)."""
    from koordinator_tpu.api.resources import RESOURCE_INDEX, ResourceName

    cpu = RESOURCE_INDEX[ResourceName.CPU]
    mem = RESOURCE_INDEX[ResourceName.MEMORY]
    if active_axes is None:
        return cpu, mem
    axes = [int(a) for a in active_axes]
    if cpu in axes and mem in axes:
        return axes.index(cpu), axes.index(mem)
    return -1, -1


def make_pod_evaluator(fc: FullChainInputs, weight_idx, prod_mode,
                       bal_idx=(-1, -1), explain_terms=False):
    """The per-pod PreFilter+Filter+Score+select math, factored so the serial
    kernel and the wave kernel (models/wave_chain.py) trace the IDENTICAL
    computation — binding parity between them is by construction.

    Returns evaluate(i, requested, delta_np, delta_pr, numa_free, bind_free,
    quota_used, ...) -> (found, best, zone_at_best, admit, score_row,
    bal_row, best_score) where admit is the pod-level PreFilter verdict
    (gang validity AND quota admission); vmap-able over i at frozen
    state. score_row is the feasibility-masked [N] score vector and
    bal_row the unmasked balanced-allocation term (both consumed by the
    wave kernel's conflict bound; the serial loop drops them).

    With ``explain_terms`` (the KOORD_TPU_EXPLAIN=full kernels) evaluate
    appends the per-plugin score-term rows (la_score, numa_score, pref) so
    the loop body can record the winning node's attribution.

    The returned callable carries ``evaluate.filter_chain`` — the
    PreFilter+Filter verdicts alone, at any frozen state — which the
    attribution pass (explain_stage_counts) vmaps to produce the per-stage
    reject counts diagnose.py formats. It is the SAME closure evaluate
    itself combines into ``feasible``, so counts can never drift from the
    decisions."""
    inputs = fc.base
    reject_np, reject_prod = la_ops.loadaware_node_reject(
        inputs.allocatable,
        inputs.la_filter_usage,
        inputs.la_has_filter_usage,
        inputs.la_filter_thresholds,
        inputs.la_prod_thresholds,
        inputs.la_prod_pod_usage,
        inputs.la_filter_skip,
    )
    gang_pod_ok = jnp.where(
        fc.gang_id >= 0, fc.gang_valid[jnp.maximum(fc.gang_id, 0)], True
    )

    T = fc.aff_dom.shape[1]
    PT = fc.port_used.shape[1]

    # balanced-allocation reciprocals hoisted out of the pod loop
    # (ops/pallas_common.safe_reciprocal documents the cross-kernel
    # bit-parity contract)
    if bal_idx[0] >= 0:
        from koordinator_tpu.ops.pallas_common import safe_reciprocal

        bal_inv_c, bal_inv_m = (
            safe_reciprocal(inputs.allocatable[:, axis]) for axis in bal_idx)

    def filter_chain(i, requested, numa_free, bind_free, quota_used,
                     aff_count, anti_cover, aff_exists, port_used, vol_free):
        """PreFilter + Filter verdicts for pod ``i`` at the given frozen
        state: (gang_ok, quota_ok, fit, la_ok, cpuset_ok, numa_ok, zone,
        taint_ok, affinity_ok, ports_ok, vol_ok). The single home of every
        filter predicate — evaluate combines these into ``feasible`` and
        the attribution pass counts their complements."""
        req_fit = inputs.fit_requests[i]
        req = fc.requests[i]
        is_prod_i = inputs.is_prod[i]

        # ---- PreFilter: gang validity + quota admission (order-dependent)
        quota_ok = quota_admit_row(
            req, fc.quota_id[i], fc.quota_ancestors, quota_used, fc.quota_runtime
        )

        # ---- Filter chain
        fit = fit_ok_row(req_fit, inputs.allocatable, requested)
        la_reject = jnp.where(is_prod_i, reject_prod, reject_np)
        la_ok = inputs.is_daemonset[i] | ~la_reject
        cpuset_ok = cpuset_filter_row(
            fc.needs_bind[i], fc.cores_needed[i], fc.full_pcpus[i],
            fc.has_topology, bind_free, fc.cpus_per_core,
        )
        numa_ok, zone = numa_admit_row(
            req, fc.needs_numa[i], numa_free, fc.numa_policy
        )
        # TaintToleration (vendored default plugin): pod tolerates the node's
        # taint-set group (ops/taints.py bit test)
        taint_ok = (
            jnp.right_shift(
                fc.pod_taint_mask[i].astype(jnp.int32), fc.node_taint_group
            )
            & 1
        ) == 1
        # InterPodAffinity (vendored default plugin, ops/podaffinity.py):
        # every required anti term has a zero count in the node's domain;
        # every required affinity term has a match in a VALID domain, or
        # bootstraps (self-match with no matching pod anywhere)
        affinity_ok = jnp.ones(aff_count.shape[0], bool)
        for t in range(T):
            count_t = aff_count[:, t]
            dom_valid = fc.aff_dom[:, t] >= 0
            anti_ok = ~fc.pod_anti_req[i, t] | (count_t <= 0)
            # symmetric anti-affinity: a pod MATCHING term t may not land
            # where any CARRIER of anti term t lives (anti_cover > 0 only
            # on domain-labeled nodes, so dom_valid is implied)
            sym_ok = ~fc.pod_aff_match[i, t] | (anti_cover[:, t] <= 0)
            bootstrap = fc.pod_aff_match[i, t] & ~aff_exists[t]
            aff_ok = ~fc.pod_aff_req[i, t] | (
                dom_valid & (count_t > 0)) | bootstrap
            # PodTopologySpread (DoNotSchedule): count + self - min over
            # ELIGIBLE domains must stay within maxSkew. Eligibility is the
            # pod's admission bit test (node selector/affinity + taints) —
            # upstream NodeAffinityPolicy=Honor + NodeTaintsPolicy=Honor —
            # so an empty domain the pod could never use cannot pin the
            # minimum at zero. A node without the topology label cannot
            # host the constrained pod.
            skew = fc.pod_spread_skew[i, t]
            self_match = jnp.where(fc.pod_aff_match[i, t], 1.0, 0.0)
            min_count = jnp.min(
                jnp.where(dom_valid & taint_ok, count_t, jnp.inf))
            spread_ok = (skew <= 0) | (
                dom_valid & (count_t + self_match - min_count <= skew))
            affinity_ok = affinity_ok & anti_ok & sym_ok & aff_ok & spread_ok
        # NodePorts (vendored default plugin, ops/ports.py): no requested
        # hostPort slot may already be bound on the node
        ports_ok = jnp.ones(port_used.shape[0], bool)
        for s in range(PT):
            ports_ok = ports_ok & (
                ~fc.pod_port_wants[i, s] | (port_used[:, s] <= 0))
        # NodeVolumeLimits (CSI attachable count): nodes without a reported
        # limit carry vol_free = +inf and always pass; the per-node volume
        # group resolves "claims already attached here don't count again"
        # (upstream's already-attached exemption)
        vn = fc.vol_needed[i][fc.node_vol_group]
        vol_ok = (vn <= 0) | (vol_free >= vn)
        return (gang_pod_ok[i], quota_ok, fit, la_ok, cpuset_ok, numa_ok,
                zone, taint_ok, affinity_ok, ports_ok, vol_ok)

    def evaluate(i, requested, delta_np, delta_pr, numa_free, bind_free,
                 quota_used, aff_count, anti_cover, aff_exists, port_used,
                 vol_free):
        req_fit = inputs.fit_requests[i]
        req = fc.requests[i]
        est = inputs.estimated[i]
        is_prod_i = inputs.is_prod[i]

        (gang_ok, quota_ok, fit, la_ok, cpuset_ok, numa_ok, zone, taint_ok,
         affinity_ok, ports_ok, vol_ok) = filter_chain(
            i, requested, numa_free, bind_free, quota_used, aff_count,
            anti_cover, aff_exists, port_used, vol_free)
        admit = gang_ok & quota_ok
        feasible = (
            inputs.node_ok & fit & la_ok & cpuset_ok & numa_ok & taint_ok
            & affinity_ok & ports_ok & vol_ok & admit
        )

        # ---- Score chain (equal plugin weights, each already 0..100)
        la_score = _score_row(
            est, is_prod_i, inputs, delta_np, delta_pr, weight_idx, prod_mode
        )
        numa_score = numa_score_row(
            req, requested, inputs.allocatable, inputs.weights, weight_idx,
        )
        # NodeResourcesBalancedAllocation (vendored default scoring): for
        # the two balanced axes the upstream std reduces to |fc - fm| / 2
        # (no sqrt — the bit-parity discipline holds); fractions clamp to 1
        # and a zero-capacity axis contributes fraction 0
        if bal_idx[0] >= 0:
            ci, mi = bal_idx
            def _frac(axis, inv):
                return jnp.minimum(
                    (requested[:, axis] + req_fit[axis]) * inv, 1.0)
            std = jnp.abs(_frac(ci, bal_inv_c) - _frac(mi, bal_inv_m)) * 0.5
            bal_row = jnp.floor((1.0 - std) * 100.0)
            numa_score = numa_score + bal_row
        else:
            bal_row = jnp.zeros(requested.shape[0], jnp.float32)
        # preferred node affinity (soft NodeAffinity score): a static,
        # profile-bucketed 0..100 row — pods without preferences add 0.
        # Zero-column tables mean NO pod carries the feature: skip the
        # gather entirely (snapshot emits true empties)
        if fc.pref_scores.shape[1]:
            pid = fc.pod_pref_id[i]
            pref = jnp.where(
                pid >= 0, fc.pref_scores[:, jnp.maximum(pid, 0)], 0.0)
        else:
            pref = jnp.zeros(aff_count.shape[0], jnp.float32)
        # preferred POD affinity (soft InterPodAffinity score): weighted sum
        # of matching-pod counts over the shared term space, max-min
        # normalized to 0..100 per pod (upstream NormalizeScore semantics)
        sid2 = fc.pod_ppref_id[i]
        if T and fc.ppref_w.shape[0]:  # zero rows == no profiles: no work
            w_row = fc.ppref_w[jnp.maximum(sid2, 0), :T]          # [T]
            # elementwise+reduce, not matmul: TPU matmuls default to bf16
            # passes and the products must stay exact integers
            raw = jnp.sum(aff_count * w_row[None, :], axis=1)     # [N]
            # max-min over node_ok nodes only (upstream NormalizeScore
            # spans the candidate set — padded/cordoned rows must not
            # anchor the scale and shift weights across bucket sizes)
            mx = jnp.max(jnp.where(inputs.node_ok, raw, -jnp.inf))
            mn = jnp.min(jnp.where(inputs.node_ok, raw, jnp.inf))
            norm = jnp.where(
                mx > mn,
                jnp.floor((raw - mn) * 100.0 / (mx - mn)), 0.0)
            pref = pref + jnp.where(sid2 >= 0, norm, 0.0)
        # ImageLocality (vendored default plugin, ops/ports.py): static
        # profile-bucketed 0..100 row, like preferred node affinity
        if fc.img_scores.shape[1]:
            iid = fc.pod_img_id[i]
            pref = pref + jnp.where(
                iid >= 0, fc.img_scores[:, jnp.maximum(iid, 0)], 0.0)
        score = la_score + numa_score + pref
        score = jnp.where(feasible, score, -1.0)

        # ---- select
        best = jnp.argmax(score)
        found = (score[best] >= 0.0) & inputs.pod_valid[i]
        # score/bal rows + best value ride along for the wave kernel's
        # balanced-allocation conflict bound; the serial loop ignores them
        # (XLA dead-code-eliminates the unused outputs)
        if explain_terms:
            return (found, best, zone[best], admit, score, bal_row,
                    score[best], la_score, numa_score, pref)
        return found, best, zone[best], admit, score, bal_row, score[best]

    evaluate.filter_chain = filter_chain
    return evaluate


def explain_stage_counts(fc: FullChainInputs, evaluate, filter_state,
                         n_real):
    """[P, NUM_EXPLAIN_STAGES] uint32: per-pod rejected-node counts at the
    frozen ``filter_state`` — the (requested, numa_free, bind_free,
    quota_used, aff_count, anti_cover, aff_exists, port_used, vol_free)
    9-tuple ``evaluate.filter_chain`` takes — over the first ``n_real``
    (unpadded) nodes, plus the two pod-level PreFilter verdict flags.
    Vmapped reuse of the SAME filter_chain the decisions ran through, so a
    count here is exactly "nodes this stage rejected for this pod", in the
    state scheduler/diagnose.py diagnoses against."""
    inputs = fc.base
    N = inputs.allocatable.shape[0]
    P = inputs.fit_requests.shape[0]
    valid = jnp.arange(N, dtype=jnp.int32) < n_real

    def row(i):
        (gang_ok, quota_ok, fit, la_ok, cpuset_ok, numa_ok, _zone, taint_ok,
         affinity_ok, ports_ok, vol_ok) = evaluate.filter_chain(
            i, *filter_state)
        # EXPLAIN_STAGES order (diagnose.py's legacy insertion order)
        bads = (~inputs.node_ok, ~taint_ok, ~fit, ~la_ok, ~ports_ok,
                ~vol_ok, ~cpuset_ok, ~numa_ok, ~affinity_ok)
        counts = [jnp.sum(b & valid).astype(jnp.uint32) for b in bads]
        counts.append(jnp.where(gang_ok, 0, 1).astype(jnp.uint32))
        counts.append(jnp.where(quota_ok, 0, 1).astype(jnp.uint32))
        return jnp.stack(counts)

    return jax.vmap(row)(jnp.arange(P, dtype=jnp.int32))


def commit_pod_state(fc: FullChainInputs, prod_mode: bool, state, i, found,
                     best, zone_at_best):
    """Apply pod ``i``'s tentative binding to the in-round device state.

    ``state`` is the 11-tuple (requested, delta_np, delta_pr, numa_free,
    bind_free, quota_used, aff_count, anti_cover, aff_exists, port_used,
    vol_free) every full-chain kernel carries. Factored out of the serial
    loop so the fused wave kernel (models/fused_waves.py) traces the
    IDENTICAL update sequence — both its in-wave pass and its kept-only
    replay pass call this function, so carried state can never drift from
    what the serial kernel would have produced."""
    inputs = fc.base
    (requested, delta_np, delta_pr, numa_free, bind_free, quota_used,
     aff_count, anti_cover, aff_exists, port_used, vol_free) = state
    T = fc.aff_dom.shape[1]
    PT = fc.port_used.shape[1]
    req_fit = inputs.fit_requests[i]
    req = fc.requests[i]
    est = inputs.estimated[i]
    is_prod_i = inputs.is_prod[i]
    fnd = found.astype(jnp.float32)

    def upd_row(mat, add_row):
        new_row = mat[best] + fnd * add_row
        return jax.lax.dynamic_update_slice(mat, new_row[None], (best, 0))

    requested = upd_row(requested, req_fit)
    delta_np = upd_row(delta_np, est)
    if prod_mode:
        delta_pr = upd_row(
            delta_pr, jnp.where(is_prod_i, 1.0, 0.0) * est
        )
    new_zone_free = numa_spread_fill(numa_free[best], req, zone_at_best)
    apply_numa = (found & fc.needs_numa[i]).astype(jnp.float32)
    mixed = apply_numa * new_zone_free + (1.0 - apply_numa) * numa_free[best]
    numa_free = jax.lax.dynamic_update_slice(
        numa_free, mixed[None], (best, 0, 0)
    )
    bind_free = bind_free.at[best].add(
        -fnd * jnp.where(fc.needs_bind[i], fc.cores_needed[i], 0.0)
    )
    # NodePorts: the placed pod binds its wanted slots on the node
    if PT:
        port_row = jnp.maximum(
            port_used[best],
            fnd * fc.pod_port_wants[i].astype(jnp.float32))
        port_used = jax.lax.dynamic_update_slice(
            port_used, port_row[None], (best, 0))
    vol_free = vol_free.at[best].add(
        -fnd * fc.vol_needed[i][fc.node_vol_group[best]])
    quota_used = quota_used_add_row(
        quota_used, req, fc.quota_id[i], fc.quota_ancestors, found
    )
    # inter-pod affinity: the placed pod raises the count of every
    # term it matches across the chosen node's whole domain, flips
    # the term's exists flag even on an unlabeled node, and — for
    # terms it CARRIES as anti-affinity — raises the domain's
    # anti_cover (symmetric anti-affinity for later pods)
    for t in range(T):
        chosen_dom = fc.aff_dom[best, t]
        in_dom = (chosen_dom >= 0) & (fc.aff_dom[:, t] == chosen_dom)
        inc = found & fc.pod_aff_match[i, t] & in_dom
        aff_count = aff_count.at[:, t].add(inc.astype(jnp.float32))
        inc_cov = found & fc.pod_anti_req[i, t] & in_dom
        anti_cover = anti_cover.at[:, t].add(
            inc_cov.astype(jnp.float32))
        aff_exists = aff_exists.at[t].set(
            aff_exists[t] | (found & fc.pod_aff_match[i, t]))
    return (requested, delta_np, delta_pr, numa_free, bind_free,
            quota_used, aff_count, anti_cover, aff_exists, port_used,
            vol_free)


def build_full_chain_step(args: LoadAwareArgs, num_gangs: int, num_groups: int,
                          jit: bool = True, active_axes=None, explain=None):
    """FullChainInputs -> (chosen[P], requested[N, R], quota_used[G, R]).

    num_gangs/num_groups are static (gang arrays are padded to them).
    active_axes: when the inputs were sliced to the active resource axes
    (snapshot.reduce_to_active_axes), the original axis ids, so weight indices
    map correctly.

    explain: None (the default, the exact historical step), "counts", or
    "full" (koordexplain attribution). An explain step takes an extra
    ``n_real`` int32 scalar (real node count — padding must not inflate
    counts) and returns a 4th output, ExplainOut. The decision computation
    is untouched: attribution is extra outputs only, so bindings stay
    byte-identical to the explain=None step.
    """
    weight_idx = resolve_weight_idx(args, active_axes)
    bal_idx = resolve_balance_idx(active_axes)
    prod_mode = args.score_according_prod_usage
    explain_full = explain == "full"

    def _step_impl(fc: FullChainInputs, n_real):
        inputs = fc.base
        P = inputs.fit_requests.shape[0]
        N = inputs.allocatable.shape[0]
        evaluate = make_pod_evaluator(fc, weight_idx, prod_mode, bal_idx,
                                      explain_terms=explain_full)

        T = fc.aff_dom.shape[1]
        PT = fc.port_used.shape[1]

        def body(i, state):
            if explain_full:
                chain_state, terms, chosen = state[:-2], state[-2], state[-1]
                (found, best, zone_at_best, _admit, score, _b, best_v,
                 la_row, numa_row, pref_row) = evaluate(i, *chain_state)
                # decision-time attribution: the winning node's per-plugin
                # terms + the runner-up score (margin = best - runner_up);
                # -1 marks "no feasible runner-up", matching the score
                # vector's infeasible sentinel
                runner = jnp.maximum(jnp.max(jnp.where(
                    jnp.arange(N, dtype=jnp.int32) == best,
                    -jnp.inf, score)), -1.0)
                terms = terms.at[i].set(jnp.stack([
                    la_row[best], numa_row[best], pref_row[best],
                    best_v, runner]))
            else:
                chain_state, chosen = state[:-1], state[-1]
                found, best, zone_at_best, _admit, _s, _b, _mv = evaluate(
                    i, *chain_state,
                )
            chain_state = commit_pod_state(
                fc, prod_mode, chain_state, i, found, best, zone_at_best)
            chosen = chosen.at[i].set(jnp.where(found, best.astype(jnp.int32), -1))
            if explain_full:
                return chain_state + (terms, chosen)
            return chain_state + (chosen,)

        R = inputs.fit_requests.shape[-1]
        init = (
            inputs.requested,
            jnp.zeros((N, R), jnp.float32),
            jnp.zeros((N, R), jnp.float32),
            fc.numa_free,
            fc.bind_free,
            fc.quota_used,
            fc.aff_count,
            fc.anti_cover,
            jnp.asarray(fc.aff_exists, bool),
            fc.port_used,
            fc.vol_free,
        )
        if explain_full:
            init = init + (jnp.zeros((P, len(EXPLAIN_TERMS)), jnp.float32),)
        init = init + (jnp.full(P, -1, jnp.int32),)
        out = jax.lax.fori_loop(0, P, body, init)
        requested, quota_used, chosen = out[0], out[5], out[-1]
        terms = out[-2] if explain_full else None

        # ---- Permit barrier (gang group all-or-nothing)
        keep = gang_permit_mask(
            chosen, fc.gang_id, fc.gang_min_member, fc.gang_assumed,
            fc.gang_group_id, num_gangs, num_groups,
        )
        chosen = jnp.where(keep, chosen, -1)
        if explain is None:
            return chosen, requested, quota_used
        # attribution counts at CYCLE-START state — diagnose.py's contract
        # (its legacy messages are computed against the packed batch before
        # in-batch placements)
        filter_state = (init[0], init[3], init[4], init[5], init[6],
                        init[7], init[8], init[9], init[10])
        counts = explain_stage_counts(fc, evaluate, filter_state, n_real)
        return chosen, requested, quota_used, ExplainOut(counts, terms)

    if explain is None:
        def step(fc: FullChainInputs):
            return _step_impl(fc, None)
    else:
        def step(fc: FullChainInputs, n_real):
            return _step_impl(fc, n_real)

    return jax.jit(step) if jit else step


def build_best_full_chain_step(args: LoadAwareArgs, num_gangs: int,
                               num_groups: int, active_axes=None,
                               vmem_budget_bytes=None, kernel: str = "auto",
                               explain=None):
    """Backend-aware selector: the VMEM-resident Pallas kernel on TPU
    (ops/pallas_full_chain.py, ~20x the fori_loop at 10k x 5k), the XLA
    step elsewhere. Same contract, bit-identical bindings.

    The Pallas kernel pins all node/NUMA/quota state in VMEM, so its reach
    is bounded (~20k nodes at R=16, less with NUMA zones and quota groups);
    past the budget the per-call dispatch degrades to the XLA step instead
    of failing to compile. The dispatch reads shapes plus one host-side
    numpy flag (any volumes?), so it never syncs the device; under jit the
    shape checks fold at trace time and the volume variant stays
    conservative.

    ``kernel`` forces an implementation: "serial" (XLA fori_loop), "pallas",
    or "wave" (models/wave_chain.py); "auto" is the default selection above.

    ``explain`` (koordexplain attribution) pins the XLA serial step — the
    Pallas/wave kernels do not emit attribution; the cycle driver documents
    the demotion via ``last_backend``.
    """
    def _forced(step_fn, name):
        # plain wrapper: jitted callables reject attribute assignment
        # (varargs: explain steps take an extra n_real operand)
        def step(*fc_args):
            return step_fn(*fc_args)

        step.last_backend = name
        return step

    if explain is not None:
        return _forced(
            build_full_chain_step(args, num_gangs, num_groups,
                                  active_axes=active_axes, explain=explain),
            "xla",
        )
    if kernel == "serial":
        return _forced(
            build_full_chain_step(args, num_gangs, num_groups,
                                  active_axes=active_axes),
            "serial",
        )
    if kernel == "wave":
        from koordinator_tpu.models.wave_chain import (
            build_wave_full_chain_step,
        )

        return _forced(
            build_wave_full_chain_step(args, num_gangs, num_groups,
                                       active_axes=active_axes),
            "wave",
        )
    xla_step = build_full_chain_step(args, num_gangs, num_groups,
                                     active_axes=active_axes)
    if kernel == "pallas" and jax.default_backend() != "tpu":
        raise ValueError("kernel='pallas' requires the TPU backend")
    if jax.default_backend() != "tpu":
        return xla_step
    from koordinator_tpu.ops import pallas_common as pc
    from koordinator_tpu.ops.pallas_full_chain import (
        SMEM_BUDGET_BYTES,
        build_pallas_full_chain_step,
        estimate_smem_bytes,
        estimate_vmem_bytes,
    )

    budget = (pc.vmem_budget_bytes() if vmem_budget_bytes is None
              else vmem_budget_bytes)
    # two lazily-built pallas variants: volume-less batches (the common
    # case) compile out the CSI volume machinery entirely
    pallas_steps = {}

    def _pallas(enable_volumes: bool):
        if enable_volumes not in pallas_steps:
            pallas_steps[enable_volumes] = build_pallas_full_chain_step(
                args, num_gangs, num_groups, active_axes=active_axes,
                enable_volumes=enable_volumes)
        return pallas_steps[enable_volumes]

    def step(fc: FullChainInputs):
        P, R = fc.base.fit_requests.shape
        N = fc.base.allocatable.shape[0]
        K = fc.numa_free.shape[1]
        G = fc.quota_used.shape[0]
        T = fc.aff_dom.shape[1]
        S = fc.pref_scores.shape[1]
        PT = fc.port_used.shape[1]
        SI = fc.img_scores.shape[1]
        VG = fc.vol_needed.shape[1]
        S2 = fc.ppref_w.shape[0] if T else 0
        # VMEM budget first: a batch bound for the XLA step anyway must
        # not pay the vol-flag resolution (which can cost a D2H readback
        # for fresh device-resident arrays)
        if estimate_vmem_bytes(N, R, K, G, P, T, S, PT, SI) > budget:
            step.last_backend = "xla"
            return xla_step(fc)
        # the snapshot builder hands HOST (numpy) arrays, so this check
        # is sync-free; CONCRETE device arrays (device-resident snapshot
        # state) are checked once per buffer and memoized — only tracers
        # conservatively keep the volume machinery. Resolved BEFORE the
        # SMEM guard: a volume-less batch compiles the machinery out (a
        # 1-float placeholder rides the input slot), so high-VG batches
        # with no new PVCs still fit the Pallas budget.
        vn = fc.vol_needed
        if isinstance(vn, np.ndarray):
            vol = bool((vn > 0).any())
        elif isinstance(vn, jax.Array) and not isinstance(
                vn, jax.core.Tracer):
            import weakref

            # memoized per live array object: the weakref guards
            # against id() reuse after GC handing back a stale flag
            cache = step._vol_flags
            hit = cache.get(id(vn))
            if hit is not None and hit[0]() is vn:
                vol = hit[1]
            else:
                vol = bool((np.asarray(vn) > 0).any())
                if len(cache) > 64:
                    cache.clear()
                cache[id(vn)] = (weakref.ref(vn), vol)
        else:
            vol = True
        if (estimate_smem_bytes(P, VG if vol else 0, T, S2)
                <= SMEM_BUDGET_BYTES):
            step.last_backend = "pallas"
            return _pallas(vol)(fc)
        step.last_backend = "xla"
        return xla_step(fc)

    step.last_backend = None
    step._vol_flags = {}
    return step
