"""koordinator_tpu: a TPU-native rebuild of the koordinator QoS co-location scheduler.

The reference (PeterChg/koordinator, mounted at /root/reference) is a Kubernetes
co-location scheduling system written in Go: a scheduler extending kube-scheduler with
7 plugins, a descheduler, a node QoS agent (koordlet), SLO controllers, admission
webhooks and a CRI runtime proxy.

This package re-expresses the hot path — the per-pod x per-node Filter/Score plugin
loop — as batched pod x node constraint tensors evaluated on TPU via JAX, while keeping
the reference's control-plane semantics (QoS classes, priority bands, quota trees, gang
scheduling, reservations) bit-exact where they define bindings.

Layout (mirrors SURVEY.md section 2 component inventory):
  api/            - data model: QoS, priority, resources, CRD-like objects
                    (analog of /root/reference/apis/)
  client/         - in-process object store + informer/watch layer
                    (analog of pkg/client generated clientsets/informers)
  ops/            - pure JAX kernels: loadaware, numa, quota, gang, deviceshare,
                    reservation restore, rebalance (the tensorized plugin math)
  models/         - composed scheduling "models": the fused full-chain batched
                    scheduling step (flagship jittable function)
  parallel/       - jax.sharding Mesh layout + shard_map'd multi-chip step
  scheduler/      - frameworkext analog: extender, plugin registry, cycle driver,
                    parity harness (analog of pkg/scheduler/)
  descheduler/    - LowNodeLoad rebalance + migration controller (pkg/descheduler/)
  slocontroller/  - nodemetric/noderesource/nodeslo controllers (pkg/slo-controller/)
  quotacontroller/- ElasticQuotaProfile controller (pkg/quota-controller/)
  webhook/        - admission mutators/validators (pkg/webhook/)
  koordlet/       - node agent: statesinformer, metriccache, metricsadvisor,
                    qosmanager, resourceexecutor, runtimehooks, prediction, pleg,
                    audit (pkg/koordlet/)
  runtimeproxy/   - CRI-interceptor analog over UDS (pkg/runtimeproxy/)
  native/         - C++ components (perf_event binding analog of the cgo libpfm4 use)
  utils/          - cpuset, bitmask, histogram, parallelize, sloconfig, feature gates
"""

__version__ = "0.1.0"
