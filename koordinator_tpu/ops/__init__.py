"""Pure JAX kernels: the tensorized plugin math.

Each module mirrors one reference plugin's pure "function of (pod, nodeState)"
(SURVEY.md section 7 design stance): loadaware, numa, quota, gang, deviceshare,
reservation, rebalance. Kernels take packed arrays (see `packing.py`) and are
side-effect free; host code owns caches and deltas.

Conventions:
  * shapes: P = padded pod batch, N = padded nodes, R = NUM_RESOURCES, K = NUMA nodes
  * dtype: float32 scores/resources, int32 ids, bool masks
  * padding rows are masked by `valid` flags; kernels must be padding-stable
  * no data-dependent Python control flow — lax.cond/scan/while only
"""
