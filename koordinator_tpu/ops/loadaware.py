"""LoadAware scheduling: vectorized filter + score.

Reference: `pkg/scheduler/plugins/loadaware/load_aware.go` —
  Filter (:123-171): reject nodes whose measured utilization (NodeMetric CR; instant
    or aggregated percentile) crosses per-resource thresholds; DaemonSet pods,
    metric-less nodes, and (optionally) expired metrics skip the check; prod pods
    check prod-tier pod usage when prod thresholds are configured (:226-255).
  Score (:269-335): least-allocated over estimatedUsed = estimator(pending pod)
    + sum(estimated usage of recently-assigned pods not yet visible in metrics)
    + adjusted measured node usage (estimated pods' actual usage deducted).

TPU-first split (SURVEY.md section 7): everything that depends only on
(node, NodeMetric, assign-cache) is precomputed per node on host into [N, R] arrays
(`build_loadaware_node_state`); the kernels below are pure jnp over those arrays and
are shared by the scheduler, the descheduler's LowNodeLoad, and the parity harness.
The per-(pod,node) work on device is two [P, N] fused elementwise/reduce passes —
no scalar plugin dispatch, no per-node goroutine fan-out.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.objects import Node, NodeMetric, Pod
from koordinator_tpu.api.priority import PriorityClass
from koordinator_tpu.api.resources import (
    NUM_RESOURCES,
    RESOURCE_INDEX,
    ResourceName,
)
from koordinator_tpu.ops.common import go_round, least_requested_score
from koordinator_tpu.ops.estimator import estimate_pod_used

ANNOTATION_CUSTOM_USAGE_THRESHOLDS = "scheduling.koordinator.sh/usage-thresholds"
DEFAULT_NODE_METRIC_REPORT_INTERVAL = 60.0


@dataclass
class LoadAwareArgs:
    """LoadAwareSchedulingArgs with the v1beta2 defaults
    (pkg/scheduler/apis/config/v1beta2/defaults.go:32-99)."""

    filter_expired_node_metrics: bool = True
    node_metric_expiration_seconds: float = 180.0
    resource_weights: Dict[str, int] = field(
        default_factory=lambda: {ResourceName.CPU: 1, ResourceName.MEMORY: 1}
    )
    usage_thresholds: Dict[str, int] = field(
        default_factory=lambda: {ResourceName.CPU: 65, ResourceName.MEMORY: 95}
    )
    prod_usage_thresholds: Dict[str, int] = field(default_factory=dict)
    score_according_prod_usage: bool = False
    estimated_scaling_factors: Dict[str, int] = field(
        default_factory=lambda: {ResourceName.CPU: 85, ResourceName.MEMORY: 70}
    )
    # Aggregated (percentile) profile, load_aware.go Aggregated args
    agg_usage_thresholds: Dict[str, int] = field(default_factory=dict)
    agg_usage_aggregation_type: str = ""       # "avg"|"p50"|"p90"|"p95"|"p99"
    agg_usage_duration_seconds: int = 0        # 0 = longest recorded window
    agg_score_aggregation_type: str = ""
    agg_score_duration_seconds: int = 0

    @property
    def filter_with_aggregation(self) -> bool:
        return bool(self.agg_usage_thresholds) and bool(self.agg_usage_aggregation_type)

    @property
    def score_with_aggregation(self) -> bool:
        return bool(self.agg_score_aggregation_type)

    def weight_vector(self) -> np.ndarray:
        w = np.zeros(NUM_RESOURCES, np.float32)
        for name, weight in self.resource_weights.items():
            w[RESOURCE_INDEX[name]] = weight
        return w


def _thresholds_vector(thresholds: Dict[str, int]) -> np.ndarray:
    v = np.zeros(NUM_RESOURCES, np.float32)
    for name, t in thresholds.items():
        v[RESOURCE_INDEX[name]] = t
    return v


def _get_aggregated_usage(
    nm: NodeMetric, duration_seconds: int, agg_type: str
) -> Optional[np.ndarray]:
    """getTargetAggregatedUsage (helper.go:58-90): exact duration match, or the
    longest recorded window when no duration is configured; missing type -> None."""
    if not nm.node_metric.aggregated_node_usages:
        return None
    if duration_seconds:
        windows = [duration_seconds] if duration_seconds in nm.node_metric.aggregated_node_usages else []
    else:
        windows = [max(nm.node_metric.aggregated_node_usages.keys())]
    for d in windows:
        usage = nm.node_metric.aggregated_node_usages[d].get(agg_type)
        if usage is not None and usage:
            return usage.to_vector()
    return None


def _custom_profile(
    node: Node, args: LoadAwareArgs
) -> Tuple[Dict[str, int], Dict[str, int], Optional[Tuple[Dict[str, int], str, int]]]:
    """generateUsageThresholdsFilterProfile (helper.go:102-139): node annotation
    overrides cluster args per section; aggregated profile falls back to args."""
    usage_thr, prod_thr = args.usage_thresholds, args.prod_usage_thresholds
    agg: Optional[Tuple[Dict[str, int], str, int]] = None
    if args.filter_with_aggregation:
        agg = (
            args.agg_usage_thresholds,
            args.agg_usage_aggregation_type,
            args.agg_usage_duration_seconds,
        )
    raw = node.meta.annotations.get(ANNOTATION_CUSTOM_USAGE_THRESHOLDS)
    if raw:
        try:
            data = json.loads(raw)
        except (ValueError, TypeError):
            return usage_thr, prod_thr, agg
        if data.get("usageThresholds"):
            usage_thr = {k: int(v) for k, v in data["usageThresholds"].items()}
        if data.get("prodUsageThresholds"):
            prod_thr = {k: int(v) for k, v in data["prodUsageThresholds"].items()}
        custom_agg = data.get("aggregatedUsage")
        if custom_agg and custom_agg.get("usageThresholds") and custom_agg.get(
            "usageAggregationType"
        ):
            agg = (
                {k: int(v) for k, v in custom_agg["usageThresholds"].items()},
                custom_agg["usageAggregationType"],
                int(custom_agg.get("usageAggregatedDurationSeconds", 0) or 0),
            )
    return usage_thr, prod_thr, agg


def _is_prod_with_default(pod: Pod) -> bool:
    """GetPodPriorityClassWithDefault: pods outside koordinator bands behave as
    PROD for the prod-usage checks."""
    return pod.priority_class in (PriorityClass.PROD, PriorityClass.NONE)


def build_loadaware_node_state(
    nodes: Sequence[Node],
    node_metrics: Dict[str, NodeMetric],
    pods_by_key: Dict[str, Pod],
    assigned: Dict[str, List[Tuple[Pod, float]]],
    args: LoadAwareArgs,
    now: float,
    pad_to: int,
) -> Dict[str, np.ndarray]:
    """Precompute per-node LoadAware terms as [N, R] / [N] arrays.

    `assigned` is the podAssignCache view: node -> [(pod, assign_timestamp)] of
    pods Reserved on the node (pod_assign_cache.go). Returns the extras dict to
    attach to NodeBatch.
    """
    n_pad = pad_to
    R = NUM_RESOURCES
    filter_usage = np.zeros((n_pad, R), np.float32)
    has_filter_usage = np.zeros(n_pad, bool)
    filter_thr = np.zeros((n_pad, R), np.float32)
    prod_thr_arr = np.zeros((n_pad, R), np.float32)
    prod_pod_usage = np.zeros((n_pad, R), np.float32)
    term_np = np.zeros((n_pad, R), np.float32)
    term_pr = np.zeros((n_pad, R), np.float32)
    score_valid = np.zeros(n_pad, bool)
    filter_skip = np.zeros(n_pad, bool)
    # the non-prod score term split into its two components, so the fused
    # wave kernel (models/fused_waves.py) can carry the assigned-estimate
    # sum on device and recompute term = est_sum + adjusted per wave with
    # the SAME association a next-cycle host rebuild would produce
    # (term_np == est_np_arr + adj_np_arr holds bit-exactly: the host adds
    # the identical two operands below)
    est_np_arr = np.zeros((n_pad, R), np.float32)
    adj_np_arr = np.zeros((n_pad, R), np.float32)
    # the PROD score term split the same way (PR 14): term_pr ==
    # est_pr_arr + adj_pr_arr holds bit-exactly because the host below
    # adds exactly those two operands — the fused wave kernel carries the
    # prod assigned-estimate sum on device and recomputes the prod term
    # per wave with the identical two-operand association
    est_pr_arr = np.zeros((n_pad, R), np.float32)
    adj_pr_arr = np.zeros((n_pad, R), np.float32)

    for i, node in enumerate(nodes):
        nm = node_metrics.get(node.meta.name)
        # isNodeMetricExpired (helper.go:36-41)
        expired = (
            nm is None
            or nm.update_time <= 0
            or (
                args.node_metric_expiration_seconds > 0
                and now - nm.update_time >= args.node_metric_expiration_seconds
            )
        )
        if nm is None or (args.filter_expired_node_metrics and expired):
            filter_skip[i] = True  # load_aware.go:135-150: allow without check
        score_valid[i] = nm is not None and not expired
        if nm is None:
            continue

        usage_thr, prod_thr, agg = _custom_profile(node, args)
        if agg is not None:
            agg_thr, agg_type, agg_dur = agg
            filter_thr[i] = _thresholds_vector(agg_thr)
            src = _get_aggregated_usage(nm, agg_dur, agg_type)
        else:
            filter_thr[i] = _thresholds_vector(usage_thr)
            src = nm.node_metric.node_usage.to_vector() if nm.node_metric else None
        if src is not None:
            filter_usage[i] = src
            has_filter_usage[i] = True

        # prod filter (load_aware.go:226-255): requires PodsMetric present
        pod_metrics_prod: Dict[str, np.ndarray] = {}
        pod_metrics_all: Dict[str, np.ndarray] = {}
        for pm in nm.pods_metric:
            key = f"{pm.namespace}/{pm.name}"
            pod = pods_by_key.get(key)
            if pod is None:  # buildPodMetricMap: lister miss -> skip
                continue
            vec = pm.pod_usage.to_vector()
            pod_metrics_all[key] = vec
            if _is_prod_with_default(pod):
                pod_metrics_prod[key] = vec
        if prod_thr and nm.pods_metric:
            prod_thr_arr[i] = _thresholds_vector(prod_thr)
            for vec in pod_metrics_prod.values():
                prod_pod_usage[i] += vec

        # ---- score terms ----
        report_interval = nm.report_interval_seconds or DEFAULT_NODE_METRIC_REPORT_INTERVAL
        if args.score_with_aggregation:
            score_src = _get_aggregated_usage(
                nm, args.agg_score_duration_seconds, args.agg_score_aggregation_type
            )
        else:
            score_src = (
                nm.node_metric.node_usage.to_vector() if nm.node_metric else None
            )

        def assigned_term(
            metrics: Dict[str, np.ndarray], prod_only: bool
        ) -> Tuple[np.ndarray, set]:
            """estimatedAssignedPodUsed (load_aware.go:337-383)."""
            est_sum = np.zeros(R, np.float32)
            est_pods: set = set()
            for pod, ts in assigned.get(node.meta.name, []):
                if prod_only and not _is_prod_with_default(pod):
                    continue
                key = pod.meta.key
                pod_usage = metrics.get(key)
                needs_estimate = (
                    pod_usage is None
                    or ts > nm.update_time  # missedLatestUpdateTime
                    or (ts < nm.update_time and nm.update_time - ts < report_interval)
                    or (args.score_with_aggregation and score_src is None)
                )
                if not needs_estimate:
                    continue
                est = estimate_pod_used(
                    pod, args.resource_weights, args.estimated_scaling_factors
                )
                for native in args.resource_weights:
                    r = RESOURCE_INDEX[native]
                    value = est[r]
                    if pod_usage is not None and pod_usage[r] > value:
                        value = pod_usage[r]
                    est_sum[r] += value
                est_pods.add(key)
            return est_sum, est_pods

        # non-prod branch: node usage minus estimated pods' actual, plus estimates
        est_np, est_pods_np = assigned_term(pod_metrics_all, prod_only=False)
        term = est_np.copy()
        if score_src is not None:
            est_actual = np.zeros(R, np.float32)
            for key in est_pods_np:
                vec = pod_metrics_all.get(key)
                if vec is not None:
                    est_actual += vec
            # quantity.Sub(q) only when quantity >= q (load_aware.go:316-323),
            # decided per-resource on the whole vector
            adjusted = np.where(score_src >= est_actual, score_src - est_actual, score_src)
            term += adjusted
            adj_np_arr[i] = adjusted
        est_np_arr[i] = est_np
        term_np[i] = term

        # prod branch (scoreAccordingProdUsage): prod pod metrics only.
        # The non-estimated prod usages fold into ONE adjusted vector
        # first (their set is static while a dispatch is in flight: a pod
        # bound mid-dispatch has no metrics yet, so it joins the estimate
        # side), then term = est + adjusted — the same two-operand
        # association the nonprod branch established, so the fused wave
        # carry (est fold + one add) reproduces this rebuild bit-for-bit
        if args.score_according_prod_usage:
            est_pr, est_pods_pr = assigned_term(pod_metrics_prod, prod_only=True)
            adjusted_pr = np.zeros(R, np.float32)
            for key, vec in pod_metrics_prod.items():
                if key not in est_pods_pr:  # sumPodUsages excludes estimated pods
                    adjusted_pr += vec
            term_pr[i] = est_pr + adjusted_pr
            est_pr_arr[i] = est_pr
            adj_pr_arr[i] = adjusted_pr

    return {
        "la_filter_usage": filter_usage,
        "la_has_filter_usage": has_filter_usage,
        "la_filter_thresholds": filter_thr,
        "la_prod_thresholds": prod_thr_arr,
        "la_prod_pod_usage": prod_pod_usage,
        "la_term_nonprod": term_np,
        "la_term_prod": term_pr,
        "la_score_valid": score_valid,
        "la_filter_skip": filter_skip,
        # consumed only by the fused wave path (not part of ScheduleInputs)
        "la_est_nonprod": est_np_arr,
        "la_adj_nonprod": adj_np_arr,
        "la_est_prod": est_pr_arr,
        "la_adj_prod": adj_pr_arr,
    }


# ---------------------------------------------------------------------------
# Device kernels (pure jnp; also consumed by the serial parity emulator row-wise)
# ---------------------------------------------------------------------------


def loadaware_node_reject(
    allocatable: jnp.ndarray,        # [N, R]
    filter_usage: jnp.ndarray,       # [N, R]
    has_filter_usage: jnp.ndarray,   # [N]
    filter_thresholds: jnp.ndarray,  # [N, R]
    prod_thresholds: jnp.ndarray,    # [N, R]
    prod_pod_usage: jnp.ndarray,     # [N, R]
    filter_skip: jnp.ndarray,        # [N]
):
    """Per-node reject masks; pod-independent (the pod enters only via
    is_prod/is_daemonset, combined in `loadaware_filter`). Returns
    (reject_nonprod[N], reject_prod[N])."""
    checkable = (filter_thresholds > 0) & (allocatable > 0) & has_filter_usage[:, None]
    ratio = go_round(filter_usage * 100.0 / jnp.maximum(allocatable, 1e-9))
    reject_np = jnp.any(checkable & (ratio >= filter_thresholds), axis=-1)
    reject_np = jnp.where(filter_skip, False, reject_np)

    prod_checkable = (prod_thresholds > 0) & (allocatable > 0)
    prod_ratio = go_round(prod_pod_usage * 100.0 / jnp.maximum(allocatable, 1e-9))
    reject_prod_only = jnp.any(prod_checkable & (prod_ratio >= prod_thresholds), axis=-1)
    has_prod_thr = jnp.any(prod_thresholds > 0, axis=-1)
    # prod pods use the prod check IFF prod thresholds exist, else the normal one
    # (load_aware.go:152-170); expired/missing metrics skip everything (:135-150)
    reject_prod = jnp.where(has_prod_thr, reject_prod_only, reject_np)
    reject_prod = jnp.where(filter_skip, False, reject_prod)
    return reject_np, reject_prod


def loadaware_filter(
    is_prod: jnp.ndarray,       # [P]
    is_daemonset: jnp.ndarray,  # [P]
    reject_nonprod: jnp.ndarray,
    reject_prod: jnp.ndarray,
) -> jnp.ndarray:
    """Combine per-node rejects with pod flags -> feasible[P, N]."""
    reject = jnp.where(is_prod[:, None], reject_prod[None, :], reject_nonprod[None, :])
    return jnp.where(is_daemonset[:, None], True, ~reject)


def loadaware_score_terms(
    estimated: jnp.ndarray,   # [P, R] estimator output for pending pods
    is_prod: jnp.ndarray,     # [P]
    term_nonprod: jnp.ndarray,  # [N, R]
    term_prod: jnp.ndarray,     # [N, R]
    allocatable: jnp.ndarray,   # [N, R]
    score_valid: jnp.ndarray,   # [N]
    weights: jnp.ndarray,       # [R]
    score_according_prod_usage: bool,
    weight_idx: Tuple[int, ...],
) -> jnp.ndarray:
    """score[P, N]: weighted least-allocated over estimatedUsed
    (load_aware.go:283-335 + :385-397). Computed per weighted resource axis
    (static weight_idx) to avoid a [P, N, R] intermediate."""
    wsum = jnp.sum(weights)
    acc = jnp.zeros((estimated.shape[0], term_nonprod.shape[0]), jnp.float32)
    for r in weight_idx:
        if score_according_prod_usage:
            node_term = jnp.where(
                is_prod[:, None], term_prod[None, :, r], term_nonprod[None, :, r]
            )
        else:
            node_term = term_nonprod[None, :, r]
        used = estimated[:, r][:, None] + node_term
        acc = acc + weights[r] * least_requested_score(used, allocatable[None, :, r])
    score = jnp.floor(acc / jnp.maximum(wsum, 1.0))
    return jnp.where(score_valid[None, :], score, 0.0)
