"""NodeResourcesFit: the kube-scheduler default fit check.

The reference scheduler runs koordinator plugins ALONGSIDE kube-scheduler's default
plugins; bindings depend on the native Fit filter (requested + request <= allocatable
per resource, pod-count included), so the batched chain reproduces it here.
Vectorized: axes the pod doesn't request are skipped (k8s semantics).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import RESOURCE_INDEX, ResourceName

PODS_AXIS = RESOURCE_INDEX[ResourceName.PODS]


def with_pod_count(requests: np.ndarray) -> np.ndarray:
    """Return a copy of [P, R] requests with the pods axis set to 1 (every pod
    consumes one pod slot in the Fit check)."""
    out = np.array(requests, copy=True)
    out[:, PODS_AXIS] = 1.0
    return out


def fit_ok_row(
    fit_request: jnp.ndarray,   # [R] single pod (pods axis already set to 1)
    allocatable: jnp.ndarray,   # [N, R]
    requested: jnp.ndarray,     # [N, R] currently assigned
) -> jnp.ndarray:
    """[N] bool: node can fit this pod."""
    need = fit_request[None, :]
    ok = (need <= 0) | (requested + need <= allocatable)
    return jnp.all(ok, axis=-1)


def fit_ok_matrix(
    fit_requests: jnp.ndarray,  # [P, R]
    allocatable: jnp.ndarray,   # [N, R]
    requested: jnp.ndarray,     # [N, R]
) -> jnp.ndarray:
    """[P, N] bool; computed axis-by-axis to avoid a [P, N, R] intermediate."""
    P, R = fit_requests.shape
    N = allocatable.shape[0]
    ok = jnp.ones((P, N), bool)
    for r in range(R):
        need = fit_requests[:, r][:, None]
        ok_r = (need <= 0) | (requested[None, :, r] + need <= allocatable[None, :, r])
        ok = ok & ok_r
    return ok
