"""Coscheduling (gang) feasibility as segment reductions.

Reference: `pkg/scheduler/plugins/coscheduling/` —
  * PreFilter (coscheduling.go:168 + core/core.go): reject a gang member when the
    gang is invalid (total member count below minMember) or its schedule cycle is
    exhausted.
  * Permit (core/core.go:311-338): assigned members wait until every gang in the
    gang-group reaches minMember; on timeout the whole group is rejected and
    unreserved.

Batched formulation: gang validity is a host-precomputed [NG] bool (it depends
only on cache state, gang_cache.go:34). The Permit barrier becomes a POST-pass
after the serial-parity selection loop: count tentative assignments per gang
(segment-sum over the pod axis), check count + already-assumed >= minMember,
AND across each gang-group, then strike the members of failed groups from the
binding vector. Within the batch, members of a still-waiting gang legitimately
hold their reserved resources (exactly like WaitingPods in the reference), so
capacity effects of struck pods are intentionally NOT rolled back on device —
the host applies only surviving bindings and rebuilds state next cycle.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def gang_permit_mask(
    chosen: jnp.ndarray,        # [P] int32 node index or -1
    gang_id: jnp.ndarray,       # [P] int32, -1 = not in a gang
    gang_min_member: jnp.ndarray,   # [NG]
    gang_assumed: jnp.ndarray,  # [NG] members already assumed/bound before batch
    gang_group_id: jnp.ndarray,  # [NG] int32 gang-group (== gang idx if alone)
    num_gangs: int,
    num_groups: int,
) -> jnp.ndarray:
    """[P] bool: keep binding after the Permit barrier."""
    import jax

    in_gang = gang_id >= 0
    gid = jnp.maximum(gang_id, 0)
    assigned = (chosen >= 0) & in_gang
    per_gang = jax.ops.segment_sum(
        assigned.astype(jnp.float32), gid, num_segments=num_gangs
    )
    gang_ok = per_gang + gang_assumed >= gang_min_member
    # all gangs in a gang-group must pass (core.go:311-338)
    group_fail = jax.ops.segment_sum(
        (~gang_ok).astype(jnp.float32), gang_group_id, num_segments=num_groups
    )
    group_ok = group_fail[gang_group_id] == 0  # [NG]
    keep_gang = gang_ok & group_ok
    return jnp.where(in_gang, keep_gang[gid], True)


def gang_prefilter_valid(
    gang_total_members: np.ndarray,  # [NG] pods known to the gang (cache)
    gang_min_member: np.ndarray,     # [NG]
) -> np.ndarray:
    """[NG] bool host precompute: gang invalid when fewer known members than
    minMember (core/gang.go state machine)."""
    return gang_total_members >= gang_min_member
