"""Inter-pod (anti-)affinity, batched (requiredDuringScheduling only).

The vendored kube-scheduler's InterPodAffinity plugin evaluates, per
candidate node, whether pods matching a term's label selector exist within
the node's topology domain (core/v1 PodAffinityTerm; the reference binary
ships the plugin as a vendored default). Per-(pod, node, term) set checks
don't batch, so the snapshot factorizes:

  * the pending batch's DISTINCT terms (selector matchLabels, topologyKey)
    become term ids t < T (T is static per batch; real batches carry a
    handful — replica spreads and co-location pairs);
  * every node gets a domain id per term ([N, T], -1 when the node lacks
    the topology label — such nodes are outside every domain, exactly the
    upstream semantics);
  * aff_count [N, T] carries how many matching pods (existing assigned
    pods at snapshot time, plus in-batch placements as the kernel walks)
    live in node n's domain for term t;
  * each pod carries three [T] bool rows: which terms it REQUIRES as
    affinity, which it FORBIDS as anti-affinity, and which its own labels
    MATCH (driving the in-batch count updates and the first-replica
    bootstrap: a required affinity term that matches the pod's own labels
    admits everywhere while no matching pod exists anywhere — the upstream
    special case that lets the first replica of a self-affine set land).

Feasibility per (pod, node): every anti term has count == 0, every
affinity term has (domain valid AND count > 0) or its bootstrap; the
update after a placement increments the chosen node's whole domain row
for every term the placed pod matches.

Anti-affinity is SYMMETRIC upstream (the vendored InterPodAffinity filter
keeps existingAntiAffinityCounts): an EXISTING pod's required anti term
blocks any incoming pod matching that term from the existing pod's whole
topology domain, even when the incoming pod carries no anti term itself.
That rides a second [N, T] state array, anti_cover: how many pods
CARRYING term t as required anti-affinity live in node n's domain.
Existing assigned pods' anti terms are interned into the shared term
space to seed it; a placed pending pod carrying an anti term raises its
domain row as the kernel walks. Feasibility adds: no term the incoming
pod MATCHES may have anti_cover > 0 on the node.

MAX_TERMS = 24 keeps the Pallas encoding exact (the three bool rows ride
one float bitmask each, < 2^24): batches with more distinct terms mark the
EXCESS pods unschedulable for the round (conservative, loudly logged)
rather than silently dropping a constraint. Existing-pod anti terms
beyond the budget likewise mark the pending pods MATCHING them
unschedulable (never admit a co-location upstream would reject).
"""

from __future__ import annotations

import logging
from typing import List, Tuple

import numpy as np

logger = logging.getLogger(__name__)

MAX_TERMS = 24
# maxSkew cap: the Pallas kernel carries per-(pod, term) skews as 3 bit-plane
# bitmasks, so values clamp to 7 — far beyond practical constraints (the
# upstream default is 1). Clamping happens HERE so every backend (XLA,
# Pallas, wave, oracle, C++ floor) sees the same value and bindings match.
MAX_SKEW = 7

# (namespace set, selector item set, topology key) — terms are namespace
# scoped: an empty PodAffinityTerm.namespaces defaults to the owning pod's
# own namespace, so the same selector in two namespaces is two terms
Term = Tuple[frozenset, frozenset, str]


def _term_key(term, pod) -> Term:
    ns = frozenset(term.namespaces) if term.namespaces else frozenset(
        {pod.meta.namespace})
    return (ns, frozenset(term.selector.items()), term.topology_key)


def _pod_matches(term: Term, pod) -> bool:
    ns, selector, _key = term
    if pod.meta.namespace not in ns:
        return False
    labels = pod.meta.labels
    return all(labels.get(k) == v for k, v in selector)


def _spread_key(con, pod) -> Term:
    """Topology-spread constraints share the affinity term space (identical
    domain/count state); maxSkew rides per (pod, term), so it is NOT part
    of the identity. Spread selectors apply to the pod's own namespace."""
    return (frozenset({pod.meta.namespace}),
            frozenset(con.selector.items()), con.topology_key)


def _terms_of(pod) -> List[Term]:
    """HARD terms only — budget overflow on these marks the pod
    unschedulable. ScheduleAnyway spread is soft and interns with the
    preferences (overflow only drops the score)."""
    out = []
    for term in list(pod.spec.pod_affinity) + list(pod.spec.pod_anti_affinity):
        out.append(_term_key(term, pod))
    for con in pod.spec.topology_spread:
        if con.when_unsatisfiable != "ScheduleAnyway":
            out.append(_spread_key(con, pod))
    return out


def build_affinity_state(pending_pods, nodes, existing_pods, rows=None):
    """-> (terms, ids, aff_dom [N, T] f32, aff_count [N, T] f32,
           anti_cover [N, T] f32, aff_exists [T] bool,
           aff_req [P_valid, T] bool, anti_req [P_valid, T] bool,
           match [P_valid, T] bool, spread_skew [P_valid, T] f32,
           overflow_pod_idx: list[int])

    spread_skew[i, t] > 0 means pod i carries a DoNotSchedule topology
    spread constraint with that maxSkew over term t's domains.

    existing_pods: assigned, non-terminated pods (their labels + node names
    seed the counts; their required ANTI terms are interned too and seed
    anti_cover — the upstream symmetric existingAntiAffinityCounts check).
    aff_exists[t] is True when ANY existing pod matches
    term t — regardless of whether its node carries the topology label —
    driving the first-replica bootstrap exactly as upstream ("no matching
    pod in the cluster"), where counts alone would miss matches on
    unlabeled nodes. Row i of the pod arrays corresponds to
    pending_pods[i]; the caller pads. overflow_pod_idx lists pending pods
    whose terms did not fit MAX_TERMS — they must be marked unschedulable.

    rows: optional indices of pending pods that carry ANY (anti-)affinity /
    spread / preferred-pod-affinity spec — term extraction loops restrict
    to them (a spec-less pod can contribute no term, so the restriction is
    exact); matching against interned terms still scans every pod.
    """
    if rows is None:
        rows = range(len(pending_pods))
    terms: List[Term] = []
    ids = {}
    overflow_pods: List[int] = []
    for i in rows:
        pod = pending_pods[i]
        fits = True
        for term in _terms_of(pod):
            if term in ids:
                continue
            if len(terms) >= MAX_TERMS:
                fits = False
                continue
            ids[term] = len(terms)
            terms.append(term)
        if not fits:
            overflow_pods.append(i)
            logger.warning(
                "pod %s exceeds the %d distinct (anti-)affinity terms the "
                "batch encoding holds; it is unschedulable this round",
                pod.meta.key, MAX_TERMS,
            )
    # existing assigned pods' required anti-affinity terms join the shared
    # space: their domains must gate incoming pods that MATCH them
    # (symmetric anti-affinity). On budget overflow the matching pending
    # pods go unschedulable for the round — conservative, never admitting
    # a co-location the reference's symmetric check would reject.
    existing_anti: List[Tuple[Term, object]] = []  # (term, carrier pod)
    overflow_existing_terms: List[Term] = []
    for epod in existing_pods:
        for raw in epod.spec.pod_anti_affinity:
            key = _term_key(raw, epod)
            existing_anti.append((key, epod))
            if key in ids:
                continue
            if len(terms) >= MAX_TERMS:
                if key not in overflow_existing_terms:
                    overflow_existing_terms.append(key)
                continue
            ids[key] = len(terms)
            terms.append(key)
    if overflow_existing_terms:
        hit = set()
        for i, pod in enumerate(pending_pods):
            if i in hit or i in overflow_pods:
                continue
            if any(_pod_matches(t, pod) for t in overflow_existing_terms):
                hit.add(i)
                overflow_pods.append(i)
        logger.warning(
            "%d existing-pod anti-affinity terms exceed the %d-term batch "
            "budget; %d matching pending pods are unschedulable this round",
            len(overflow_existing_terms), MAX_TERMS, len(hit),
        )
    # preferred pod-affinity terms join the SHARED space (their weighted
    # scores read the same domain counts); budget overflow here only drops
    # the preference — soft scoring degrades, never blocks
    pref_dropped = 0
    for i in rows:
        pod = pending_pods[i]
        soft_keys = [_term_key(raw, pod)
                     for raw in pod.spec.pod_affinity_preferred]
        soft_keys += [_spread_key(con, pod)
                      for con in pod.spec.topology_spread
                      if con.when_unsatisfiable == "ScheduleAnyway"]
        for key in soft_keys:
            if key in ids:
                continue
            if len(terms) >= MAX_TERMS:
                pref_dropped += 1
                continue
            ids[key] = len(terms)
            terms.append(key)
    if pref_dropped:
        logger.warning(
            "preferred pod-affinity terms beyond the %d-term budget: %d "
            "dropped to zero weight this round", MAX_TERMS, pref_dropped)
    T = len(terms)
    N = len(nodes)
    P = len(pending_pods)
    aff_dom = np.full((N, T), -1.0, np.float32)
    aff_count = np.zeros((N, T), np.float32)
    anti_cover = np.zeros((N, T), np.float32)
    aff_exists = np.zeros(T, bool)
    aff_req = np.zeros((P, T), bool)
    anti_req = np.zeros((P, T), bool)
    match = np.zeros((P, T), bool)
    spread_skew = np.zeros((P, T), np.float32)
    if T == 0:
        return (terms, ids, aff_dom, aff_count, anti_cover, aff_exists,
                aff_req, anti_req, match, spread_skew, overflow_pods)

    # domain ids per term: nodes sharing the topology label value
    node_values: List[dict] = []
    for t, (_ns, _sel, key) in enumerate(terms):
        values = {}
        for n, node in enumerate(nodes):
            val = node.meta.labels.get(key)
            if val is not None:
                aff_dom[n, t] = values.setdefault(val, len(values))
        node_values.append(values)
    node_index = {node.meta.name: n for n, node in enumerate(nodes)}

    # seed counts from existing pods: O(E*T) dict accumulation per domain
    # VALUE, then one O(N*T) write — not a [N] mask per matching pod
    dom_counts: List[dict] = [dict() for _ in range(T)]
    for pod in existing_pods:
        for t, term in enumerate(terms):
            if not _pod_matches(term, pod):
                continue
            aff_exists[t] = True
            n = node_index.get(pod.spec.node_name)
            if n is None or aff_dom[n, t] < 0:
                continue
            d = aff_dom[n, t]
            dom_counts[t][d] = dom_counts[t].get(d, 0.0) + 1.0
    for t in range(T):
        if dom_counts[t]:
            col = aff_dom[:, t]
            aff_count[:, t] = np.where(
                col >= 0,
                np.vectorize(lambda d: dom_counts[t].get(d, 0.0))(col),
                0.0,
            )

    # seed anti_cover from existing CARRIERS of interned anti terms: the
    # carrier's node's domain row rises by one per carrier (same per-value
    # accumulation as aff_count, keyed on carrying rather than matching)
    cover_counts: List[dict] = [dict() for _ in range(T)]
    for key, epod in existing_anti:
        t = ids.get(key)
        if t is None:
            continue
        n = node_index.get(epod.spec.node_name)
        if n is None or aff_dom[n, t] < 0:
            continue
        d = aff_dom[n, t]
        cover_counts[t][d] = cover_counts[t].get(d, 0.0) + 1.0
    for t in range(T):
        if cover_counts[t]:
            col = aff_dom[:, t]
            anti_cover[:, t] = np.where(
                col >= 0,
                np.vectorize(lambda d: cover_counts[t].get(d, 0.0))(col),
                0.0,
            )

    for i, pod in enumerate(pending_pods):
        for t, term in enumerate(terms):
            if _pod_matches(term, pod):
                match[i, t] = True
        for term in pod.spec.pod_affinity:
            t = ids.get(_term_key(term, pod))
            if t is not None:
                aff_req[i, t] = True
        for term in pod.spec.pod_anti_affinity:
            t = ids.get(_term_key(term, pod))
            if t is not None:
                anti_req[i, t] = True
        for con in pod.spec.topology_spread:
            t = ids.get(_spread_key(con, pod))
            if t is not None and con.when_unsatisfiable != "ScheduleAnyway":
                spread_skew[i, t] = float(min(max(con.max_skew, 1), MAX_SKEW))
    return (terms, ids, aff_dom, aff_count, anti_cover, aff_exists, aff_req,
            anti_req, match, spread_skew, overflow_pods)


MAX_PREF_PROFILES = 32


def build_preferred_scores(pending_pods, nodes, rows=None):
    """preferredDuringScheduling node affinity, profile-bucketed:

    -> (pref_rows [max(S, 1), N] f32, pod_pref_id [P_valid] int32)

    Pods sharing an identical preferred-term list share a profile; each
    profile's row is the upstream NodeAffinity score — sum of matching term
    weights, normalized to 0..100 over nodes by the framework's
    defaultNormalizeScore (floor semantics) — a STATIC function of node
    labels, so it adds to the kernel score without any in-batch state.
    Batches with more than MAX_PREF_PROFILES distinct profiles drop the
    excess profiles (their pods score 0 preference — soft scoring degrades
    gracefully, loudly logged)."""
    profiles: List[tuple] = []
    ids: dict = {}
    P = len(pending_pods)
    pod_pref_id = np.full(P, -1, np.int32)
    dropped = 0
    for i in (rows if rows is not None else range(P)):
        pod = pending_pods[i]
        terms = tuple(
            (int(t.weight), frozenset(t.labels.items()))
            for t in pod.spec.affinity_preferred if t.labels
        )
        if not terms:
            continue
        sid = ids.get(terms)
        if sid is None:
            if len(profiles) >= MAX_PREF_PROFILES:
                dropped += 1
                continue
            sid = ids[terms] = len(profiles)
            profiles.append(terms)
        pod_pref_id[i] = sid
    if dropped:
        logger.warning(
            "preferred-affinity profile budget exceeded: %d pods keep a "
            "zero preference score this round (max %d distinct profiles)",
            dropped, MAX_PREF_PROFILES,
        )
    S = len(profiles)
    N = len(nodes)
    pref_rows = np.zeros((max(S, 1), N), np.float32)
    if S:
        # one Python pass over nodes per DISTINCT label pair; profile rows
        # compose vectorized (term mask = AND of its pair masks, row = Σ w)
        pair_ids: dict = {}
        for terms in profiles:
            for _w, pairs in terms:
                for kv in pairs:
                    pair_ids.setdefault(kv, len(pair_ids))
        pair_masks = np.zeros((len(pair_ids), N), bool)
        for (k, v), pid in pair_ids.items():
            for n, node in enumerate(nodes):
                if node.meta.labels.get(k) == v:
                    pair_masks[pid, n] = True
        for s, terms in enumerate(profiles):
            row = np.zeros(N, np.float32)
            for w, pairs in terms:
                idx = [pair_ids[kv] for kv in pairs]
                row += np.float32(w) * pair_masks[idx].all(axis=0)
            mx = row.max()
            pref_rows[s] = np.floor(
                row * np.float32(100.0) / np.float32(mx)) if mx > 0 else 0.0
    return pref_rows, pod_pref_id


MAX_PPREF_PROFILES = 16


def build_preferred_pod_profiles(pending_pods, term_ids: dict, T: int,
                                 rows=None):
    """preferredDuringScheduling POD affinity, profile-bucketed over the
    SHARED term space (the counts the required terms maintain are exactly
    the weighted sum's inputs; build_affinity_state interned the terms):

    -> (ppref_w [S2, max(T, 1)] f32 (ZERO rows when no profiles — the
        kernels gate on the shape), pod_ppref_id [P] int32,
        pod_ppref_mask [P, T] bool)

    ppref_w[s] holds the per-term weights of profile s (negative = anti
    preference); pod_ppref_mask marks the terms a pod's profile references
    (the wave kernel's conflict rule). Profiles beyond MAX_PPREF_PROFILES
    are dropped with a warning: soft scoring degrades, never blocks."""
    P = len(pending_pods)
    pod_ppref_id = np.full(P, -1, np.int32)
    profiles: List[tuple] = []
    ids: dict = {}
    dropped = 0
    # spec-less pods contribute no entries; with `rows` (indices of pods
    # carrying any affinity/spread spec) only those rows pay the extraction
    per_pod_terms: List[List[tuple]] = [[] for _ in range(P)]
    for i in (rows if rows is not None else range(P)):
        pod = pending_pods[i]
        entries = []
        for raw in pod.spec.pod_affinity_preferred:
            t = term_ids.get(_term_key(raw, pod))
            if t is None:
                continue  # dropped at intern time (budget), already logged
            # upstream validates weight into 1..100; clamping (with sign
            # preserved for anti preference) also keeps every weighted
            # count sum an exact f32 integer — the bit-parity contract
            w = int(raw.weight)
            w = max(-100, min(w, 100)) or 1
            entries.append((w, t))
        # ScheduleAnyway topology spread scores instead of filtering:
        # emptier domains of the constraint's own term rank higher
        for con in pod.spec.topology_spread:
            if con.when_unsatisfiable != "ScheduleAnyway":
                continue
            t = term_ids.get(_spread_key(con, pod))
            if t is not None:
                entries.append((-1, t))
        per_pod_terms[i] = entries
    for i, entries in enumerate(per_pod_terms):
        if not entries:
            continue
        key = tuple(sorted(entries))
        sid = ids.get(key)
        if sid is None:
            if len(profiles) >= MAX_PPREF_PROFILES:
                dropped += 1
                continue
            sid = ids[key] = len(profiles)
            profiles.append(key)
        pod_ppref_id[i] = sid
    if dropped:
        logger.warning(
            "preferred pod-affinity profile budget exceeded: %d profiles "
            "dropped to zero weight this round", dropped)
    S2 = len(profiles)
    ppref_w = np.zeros((S2, max(T, 1)), np.float32)
    pod_ppref_mask = np.zeros((P, max(T, 1)), bool)
    for s, entries in enumerate(profiles):
        for w, t in entries:
            ppref_w[s, t] += float(w)
    for i, entries in enumerate(per_pod_terms):
        if pod_ppref_id[i] < 0:
            continue
        for _w, t in entries:
            pod_ppref_mask[i, t] = True
    return ppref_w, pod_ppref_id, pod_ppref_mask
