"""Host->device packing: cluster snapshots as static-shaped arrays.

The analog of the scheduler's cache/snapshot layer (nodeInfo snapshots + the
LoadAware podAssignCache, reference `plugins/loadaware/pod_assign_cache.go`), lowered
to bucketed, padded tensors:

  PodBatch  : pending pods   [P, ...]   (P padded to a bucket size)
  NodeBatch : cluster nodes  [N, ...]   (N padded)

Bucketing keeps jit recompilation amortized while pods/nodes churn (SURVEY.md
section 7 "hard parts: dynamic shapes"). Padding rows carry valid=False and are
masked inside every kernel.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from koordinator_tpu.api.objects import Node, NodeMetric, Pod
from koordinator_tpu.api.priority import PriorityClass
from koordinator_tpu.api.resources import NUM_RESOURCES, PACK_SCALE
from koordinator_tpu.ops.estimator import (
    estimate_node_allocatable,
    estimate_pods_used_batch,
)

MIN_BUCKET = 16


def bucket_size(n: int, minimum: int = MIN_BUCKET) -> int:
    """Bucketed padding size >= n (>= minimum). Up to 1024 buckets are powers
    of two; above that the granularity is pow2/8 (e.g. 10k pods -> 10240, 5k
    nodes -> 5120, not 16384/8192). Padded rows are dead work for every kernel
    — at the 10k x 5k north-star config pow2 padding would cost 2.56x compute
    for zero extra recompiles in steady state. Coarse-grained buckets (<= 8
    per doubling, all multiples of 256, so lane/sublane tiling is preserved)
    keep churn-driven recompiles amortized while capping dead rows at one
    granule (< 25% of the padded size, vs up to ~100% for pow2)."""
    b = minimum
    while b < n:
        b *= 2
    if b <= 1024:
        return b
    g = b // 8
    return max(-(-n // g) * g, minimum)


@dataclass
class PodBatch:
    """Packed pending pods. Row order IS the scheduling order (priority queue
    order: priority desc, then creation/sub-priority), so kernels that honor the
    serial contract iterate rows in order."""

    keys: List[str]                      # len = num_valid
    requests: np.ndarray                 # [P, R] float32 packed units
    estimated: np.ndarray                # [P, R] estimator output (native axes)
    priority: np.ndarray                 # [P] int32 numeric pod priority
    qos: np.ndarray                      # [P] int32 QoSClass
    prio_class: np.ndarray               # [P] int32 PriorityClass
    is_prod: np.ndarray                  # [P] bool (priority class == PROD)
    is_daemonset: np.ndarray             # [P] bool (owner kind DaemonSet)
    gang_id: np.ndarray                  # [P] int32, -1 = no gang
    quota_id: np.ndarray                 # [P] int32, -1 = no quota group
    valid: np.ndarray                    # [P] bool
    # row -> reason for pods the ENCODING marked unschedulable this round
    # (term/slot budget overflow) — the cycle driver surfaces these as
    # first-class failure events instead of a generic "no feasible node"
    unschedulable_reasons: Dict[int, str] = field(default_factory=dict)
    # incremental-pack bookkeeping (cache builds only): row i was gathered
    # from row reused_src[i] of the previous build's memo (-1 = repacked
    # from the object). Downstream per-pod loops (snapshot.py flags/masks)
    # use the same mapping to gather THEIR cached columns.
    reused_src: Optional[np.ndarray] = None          # [num_valid] int64
    gang_keys: Optional[np.ndarray] = None           # [num_valid] object, "" = none
    quota_names: Optional[np.ndarray] = None         # [num_valid] object, "" = none
    # the pod objects in packed (queue) order — lets the snapshot builder
    # index pods without re-walking key properties; NOT retained across
    # cycles (the batch itself is cycle-local)
    objs: Optional[List[Pod]] = None

    @property
    def num_valid(self) -> int:
        return len(self.keys)

    @property
    def padded_size(self) -> int:
        return self.requests.shape[0]


@dataclass
class NodeBatch:
    """Packed node-side state. Per-node vectors precomputed on host from Node +
    NodeMetric + plugin caches; kernels combine them with PodBatch rows."""

    names: List[str]
    allocatable: np.ndarray              # [N, R] estimator EstimateNode
    requested: np.ndarray                # [N, R] sum of assigned pod requests (Fit state)
    valid: np.ndarray                    # [N] bool
    # LoadAware terms (built by ops.loadaware.build_loadaware_node_state)
    extras: Dict[str, np.ndarray] = field(default_factory=dict)

    @property
    def num_valid(self) -> int:
        return len(self.names)

    @property
    def padded_size(self) -> int:
        return self.allocatable.shape[0]


def queue_key_for(pod: Pod, gang_sort: Dict[str, Tuple[float, str]]) -> tuple:
    """The scheduling-queue sort key (PrioritySort + coscheduling Less)
    for one pod under a gang grouping map — ONE implementation shared by
    pack_pods and the in-window pre-pack (prepack_memo_rows), so a
    pre-packed queue-key tuple can never drift from the cold fill."""
    group_time, group_key = gang_sort.get(
        pod.gang_key,
        (pod.meta.creation_timestamp, pod.meta.key),
    )
    return (
        -(pod.spec.priority or 0),
        -pod.sub_priority,
        group_time,
        group_key,
        pod.meta.creation_timestamp,
        pod.meta.key,
    )


def prepack_memo_rows(
    cache,
    pods: Sequence[Pod],
    resource_weights: Dict[str, int],
    scaling_factors: Dict[str, int],
) -> List[Tuple[int, Pod]]:
    """Pack/device overlap (PR 15): refresh the pack memo's packed-row
    columns for every pod whose (key, resourceVersion) is stale or
    absent, IN PLACE — changed keys update their existing row, new keys
    append — so the next ``pack_pods`` gathers them as hits instead of
    paying the per-object Python in the inter-window gap. Queue-key
    tuples are computed under the memo's OWN gang grouping (exactly the
    tuples ``same_gs`` reuse requires); the estimator runs the same
    batched call the cold fill uses on the same packed rows, so every
    written bit equals what the next build's miss path would write.

    Returns the (memo row, pod) pairs refreshed — the snapshot layer
    fills its flag/sel columns for the same rows."""
    memo = cache.pack_memo if cache is not None else None
    if memo is None or "req_wire" not in memo:
        return []
    row_of = memo["row_of"]
    rv = memo["rv"]
    qk = memo["qk"]
    gang_sort = memo["gang_sort"]
    todo: List[Tuple[Optional[int], Pod]] = []
    for pod in pods:
        j = row_of.get(pod.meta.key)
        if j is not None and rv[j] == pod.meta.resource_version:
            continue
        todo.append((j, pod))
    if not todo:
        return []
    n_new = sum(1 for j, _p in todo if j is None)
    if n_new:
        for col, fill in (("req_wire", 0.0), ("lim_wire", 0.0),
                          ("prio", 0), ("qos", 5), ("pcls", 0),
                          ("prod", False), ("ds", False), ("est", 0.0),
                          ("gang_key", ""), ("quota_name", "")):
            arr = memo[col]
            pad = np.full((n_new,) + arr.shape[1:], fill, arr.dtype)
            memo[col] = np.concatenate([arr, pad])
    nxt = len(rv)
    placed: List[Tuple[int, Pod]] = []
    for j, pod in todo:
        if j is None:
            j = nxt
            nxt += 1
            row_of[pod.meta.key] = j
            rv.append(pod.meta.resource_version)
            qk.append(None)
        else:
            rv[j] = pod.meta.resource_version
        qk[j] = queue_key_for(pod, gang_sort)
        memo["req_wire"][j] = 0.0
        memo["lim_wire"][j] = 0.0
        pod.spec.requests.fill_wire_row(memo["req_wire"][j])
        pod.spec.limits.fill_wire_row(memo["lim_wire"][j])
        memo["prio"][j] = pod.spec.priority or 0
        memo["qos"][j] = int(pod.qos_class)
        cls = pod.priority_class
        memo["pcls"][j] = int(cls)
        memo["prod"][j] = cls in (PriorityClass.PROD, PriorityClass.NONE)
        memo["ds"][j] = pod.meta.owner_kind == "DaemonSet"
        memo["gang_key"][j] = pod.gang_key
        memo["quota_name"][j] = pod.quota_name
        placed.append((j, pod))
    idx = np.asarray([j for j, _p in placed])
    req = (memo["req_wire"][idx] / PACK_SCALE).astype(np.float32)
    lim = (memo["lim_wire"][idx] / PACK_SCALE).astype(np.float32)
    memo["est"][idx] = estimate_pods_used_batch(
        req, lim, memo["pcls"][idx], resource_weights, scaling_factors)
    cache.stats["pod_rows_prepacked"] = (
        cache.stats.get("pod_rows_prepacked", 0) + len(placed))
    return placed


def pack_pods(
    pods: Sequence[Pod],
    resource_weights: Dict[str, int],
    scaling_factors: Dict[str, int],
    gang_ids: Optional[Dict[str, int]] = None,
    quota_ids: Optional[Dict[str, int]] = None,
    pad_to: Optional[int] = None,
    gang_sort: Optional[Dict[str, Tuple[float, str]]] = None,
    cache=None,
) -> PodBatch:
    """Pack pods in scheduling-queue order (kube-scheduler PrioritySort +
    coscheduling Less, coscheduling.go:118): priority desc, sub-priority
    desc, then the GANG GROUP's identity — members of one gang sort by their
    gang's creation time and name, so a gang schedules contiguously instead
    of interleaving with unrelated pods — then pod creation time asc, key
    asc. ``gang_sort`` maps gang name -> (gang creation time, gang key);
    gangless pods (and unknown gangs) group as themselves.

    With a SnapshotCache attached, packing is INCREMENTAL: the previous
    build's packed rows (and queue-key tuples) live in ``cache.pack_memo``
    keyed by (pod key, resourceVersion); rows whose source object did not
    change are gathered with batched fancy indexing — one numpy op per
    field — and only dirty rows pay the per-object Python fill. The cached
    path produces bit-identical arrays to the cold path (the memo stores
    exactly the rows the cold fill writes)."""
    gang_sort = gang_sort or {}
    n_in = len(pods)
    prev = cache.pack_memo if cache is not None else None
    # cached queue-key tuples are only valid if the gang grouping map they
    # were built with is unchanged (gang creation/identity feeds the order)
    same_gs = prev is not None and prev["gang_sort"] == gang_sort

    def queue_key_of(pod):
        return queue_key_for(pod, gang_sort)

    # one pass: key/rv lookup against the memo + queue-key tuples (cached
    # tuples reused; this loop is the only O(P) Python the warm path pays).
    # rv/qk live as plain Python lists — per-element numpy scalar reads
    # would triple the loop's cost.
    keys_in: List[str] = [None] * n_in
    rvs_in: List[int] = [0] * n_in
    src_in = np.full(n_in, -1, np.int64)
    qk_in: List[tuple] = [None] * n_in
    if prev is not None:
        row_of_get = prev["row_of"].get
        prev_rv = prev["rv"]
        prev_qk = prev["qk"]
        for i, pod in enumerate(pods):
            meta = pod.meta
            k = meta.key
            rv = meta.resource_version
            keys_in[i] = k
            rvs_in[i] = rv
            j = row_of_get(k)
            if j is not None and prev_rv[j] == rv:
                src_in[i] = j
                if same_gs:
                    qk_in[i] = prev_qk[j]
                    continue
            qk_in[i] = queue_key_of(pod)
    else:
        for i, pod in enumerate(pods):
            meta = pod.meta
            keys_in[i] = meta.key
            rvs_in[i] = meta.resource_version
            qk_in[i] = queue_key_of(pod)
    order = sorted(range(n_in), key=qk_in.__getitem__)
    pods = [pods[i] for i in order]
    n = n_in
    p = pad_to or bucket_size(n)
    order_np = np.asarray(order, np.int64) if n else np.zeros(0, np.int64)
    src = src_in[order_np]
    keys_arr = [keys_in[i] for i in order]
    # wire-unit matrices filled in one pass (no per-pod vector allocations),
    # packed with a single vectorized scale
    req_wire = np.zeros((p, NUM_RESOURCES), np.float64)
    lim_wire = np.zeros((p, NUM_RESOURCES), np.float64)
    prio = np.zeros(p, np.int32)
    qos = np.full(p, 5, np.int32)  # QoSClass.NONE
    pcls = np.full(p, int(PriorityClass.NONE), np.int32)
    prod = np.zeros(p, bool)
    ds = np.zeros(p, bool)
    gang = np.full(p, -1, np.int32)
    quota = np.full(p, -1, np.int32)
    valid = np.zeros(p, bool)
    est = np.zeros((p, NUM_RESOURCES), np.float32)
    gang_col = np.full(n, "", object)
    quota_col = np.full(n, "", object)
    hit = np.nonzero(src >= 0)[0]
    if hit.size:
        hsrc = src[hit]
        req_wire[hit] = prev["req_wire"][hsrc]
        lim_wire[hit] = prev["lim_wire"][hsrc]
        prio[hit] = prev["prio"][hsrc]
        qos[hit] = prev["qos"][hsrc]
        pcls[hit] = prev["pcls"][hsrc]
        prod[hit] = prev["prod"][hsrc]
        ds[hit] = prev["ds"][hsrc]
        est[hit] = prev["est"][hsrc]
        gang_col[hit] = prev["gang_key"][hsrc]
        quota_col[hit] = prev["quota_name"][hsrc]
    misses = np.nonzero(src < 0)[0]
    for i in misses:
        pod = pods[i]
        pod.spec.requests.fill_wire_row(req_wire[i])
        pod.spec.limits.fill_wire_row(lim_wire[i])
        prio[i] = pod.spec.priority or 0
        qos[i] = int(pod.qos_class)
        cls = pod.priority_class
        pcls[i] = int(cls)
        # GetPodPriorityClassWithDefault: pods outside koordinator bands
        # default to PROD semantics in LoadAware's prod checks
        prod[i] = cls in (PriorityClass.PROD, PriorityClass.NONE)
        ds[i] = pod.meta.owner_kind == "DaemonSet"
        gang_col[i] = pod.gang_key
        quota_col[i] = pod.quota_name
    valid[:n] = True
    # gang/quota id resolution: unique-name factorization instead of a
    # per-pod dict lookup (the id maps are small; the columns are cached)
    if gang_ids is not None:
        fill_ids_from_names(gang, gang_col, gang_ids)
    if quota_ids is not None:
        fill_ids_from_names(quota, quota_col, quota_ids)
    req = (req_wire / PACK_SCALE).astype(np.float32)
    lim = (lim_wire / PACK_SCALE).astype(np.float32)
    # estimate only rows not served from the cache: padding must carry
    # zeros, never the 250-milli/200-MiB defaults the estimator assigns
    # empty requests
    if cache is None:
        if n:
            est[:n] = estimate_pods_used_batch(
                req[:n], lim[:n], pcls[:n], resource_weights, scaling_factors
            )
    elif misses.size:
        est[misses] = estimate_pods_used_batch(
            req[misses], lim[misses], pcls[misses],
            resource_weights, scaling_factors
        )
    if cache is not None:
        cache.stats["pod_row_hits"] += int(hit.size)
        cache.stats["pod_row_misses"] += int(misses.size)
        # rotate the memo: the OLD one stays visible (pack_memo_prev) so
        # build_full_chain_inputs can gather its flag/mask columns with the
        # same reused_src mapping before storing the new columns
        cache.pack_memo_prev = prev
        cache.pack_memo = {
            "gang_sort": dict(gang_sort),
            "row_of": {k: i for i, k in enumerate(keys_arr)},
            "rv": [rvs_in[i] for i in order],
            "qk": [qk_in[i] for i in order],
            "req_wire": req_wire[:n].copy(),
            "lim_wire": lim_wire[:n].copy(),
            "prio": prio[:n].copy(), "qos": qos[:n].copy(),
            "pcls": pcls[:n].copy(), "prod": prod[:n].copy(),
            "ds": ds[:n].copy(), "est": est[:n].copy(),
            "gang_key": gang_col.copy(), "quota_name": quota_col.copy(),
        }
    return PodBatch(
        keys=keys_arr,
        requests=req,
        estimated=est,
        priority=prio,
        qos=qos,
        prio_class=pcls,
        is_prod=prod,
        is_daemonset=ds,
        gang_id=gang,
        quota_id=quota,
        valid=valid,
        reused_src=src if cache is not None else None,
        gang_keys=gang_col,
        quota_names=quota_col,
        objs=pods,
    )


def fill_ids_from_names(out: np.ndarray, names: np.ndarray,
                         id_map: Dict[str, int]) -> None:
    """out[i] = id_map.get(names[i], -1) for named rows, vectorized through
    a unique-name factorization ("" rows keep -1)."""
    if not names.size or not id_map:
        return
    named = np.nonzero(names != "")[0]
    if not named.size:
        return
    uniq, inv = np.unique(names[named].astype(str), return_inverse=True)
    ids = np.asarray([id_map.get(u, -1) for u in uniq], np.int32)
    out[named] = ids[inv]


def pack_nodes(
    nodes: Sequence[Node],
    assigned_requests: Optional[Dict[str, np.ndarray]] = None,
    pad_to: Optional[int] = None,
) -> NodeBatch:
    """Pack node allocatable + current requested (the NodeResourcesFit state)."""
    n = len(nodes)
    size = pad_to or bucket_size(n)
    alloc = np.zeros((size, NUM_RESOURCES), np.float32)
    requested = np.zeros((size, NUM_RESOURCES), np.float32)
    valid = np.zeros(size, bool)
    for i, node in enumerate(nodes):
        alloc[i] = estimate_node_allocatable(node)
        if assigned_requests is not None:
            vec = assigned_requests.get(node.meta.name)
            if vec is not None:
                requested[i] = vec
        valid[i] = True
    return NodeBatch(
        names=[nd.meta.name for nd in nodes],
        allocatable=alloc,
        requested=requested,
        valid=valid,
    )


def metric_age(node_metric: Optional[NodeMetric], now: Optional[float] = None) -> float:
    if node_metric is None or node_metric.update_time <= 0:
        return float("inf")
    return (time.time() if now is None else now) - node_metric.update_time
