"""Host->device packing: cluster snapshots as static-shaped arrays.

The analog of the scheduler's cache/snapshot layer (nodeInfo snapshots + the
LoadAware podAssignCache, reference `plugins/loadaware/pod_assign_cache.go`), lowered
to bucketed, padded tensors:

  PodBatch  : pending pods   [P, ...]   (P padded to a bucket size)
  NodeBatch : cluster nodes  [N, ...]   (N padded)

Bucketing keeps jit recompilation amortized while pods/nodes churn (SURVEY.md
section 7 "hard parts: dynamic shapes"). Padding rows carry valid=False and are
masked inside every kernel.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from koordinator_tpu.api.objects import Node, NodeMetric, Pod
from koordinator_tpu.api.priority import PriorityClass
from koordinator_tpu.api.resources import NUM_RESOURCES, PACK_SCALE
from koordinator_tpu.ops.estimator import (
    estimate_node_allocatable,
    estimate_pods_used_batch,
)

MIN_BUCKET = 16


def bucket_size(n: int, minimum: int = MIN_BUCKET) -> int:
    """Bucketed padding size >= n (>= minimum). Up to 1024 buckets are powers
    of two; above that the granularity is pow2/8 (e.g. 10k pods -> 10240, 5k
    nodes -> 5120, not 16384/8192). Padded rows are dead work for every kernel
    — at the 10k x 5k north-star config pow2 padding would cost 2.56x compute
    for zero extra recompiles in steady state. Coarse-grained buckets (<= 8
    per doubling, all multiples of 256, so lane/sublane tiling is preserved)
    keep churn-driven recompiles amortized while capping dead rows at one
    granule (< 25% of the padded size, vs up to ~100% for pow2)."""
    b = minimum
    while b < n:
        b *= 2
    if b <= 1024:
        return b
    g = b // 8
    return max(-(-n // g) * g, minimum)


@dataclass
class PodBatch:
    """Packed pending pods. Row order IS the scheduling order (priority queue
    order: priority desc, then creation/sub-priority), so kernels that honor the
    serial contract iterate rows in order."""

    keys: List[str]                      # len = num_valid
    requests: np.ndarray                 # [P, R] float32 packed units
    estimated: np.ndarray                # [P, R] estimator output (native axes)
    priority: np.ndarray                 # [P] int32 numeric pod priority
    qos: np.ndarray                      # [P] int32 QoSClass
    prio_class: np.ndarray               # [P] int32 PriorityClass
    is_prod: np.ndarray                  # [P] bool (priority class == PROD)
    is_daemonset: np.ndarray             # [P] bool (owner kind DaemonSet)
    gang_id: np.ndarray                  # [P] int32, -1 = no gang
    quota_id: np.ndarray                 # [P] int32, -1 = no quota group
    valid: np.ndarray                    # [P] bool
    # row -> reason for pods the ENCODING marked unschedulable this round
    # (term/slot budget overflow) — the cycle driver surfaces these as
    # first-class failure events instead of a generic "no feasible node"
    unschedulable_reasons: Dict[int, str] = field(default_factory=dict)

    @property
    def num_valid(self) -> int:
        return len(self.keys)

    @property
    def padded_size(self) -> int:
        return self.requests.shape[0]


@dataclass
class NodeBatch:
    """Packed node-side state. Per-node vectors precomputed on host from Node +
    NodeMetric + plugin caches; kernels combine them with PodBatch rows."""

    names: List[str]
    allocatable: np.ndarray              # [N, R] estimator EstimateNode
    requested: np.ndarray                # [N, R] sum of assigned pod requests (Fit state)
    valid: np.ndarray                    # [N] bool
    # LoadAware terms (built by ops.loadaware.build_loadaware_node_state)
    extras: Dict[str, np.ndarray] = field(default_factory=dict)

    @property
    def num_valid(self) -> int:
        return len(self.names)

    @property
    def padded_size(self) -> int:
        return self.allocatable.shape[0]


def pack_pods(
    pods: Sequence[Pod],
    resource_weights: Dict[str, int],
    scaling_factors: Dict[str, int],
    gang_ids: Optional[Dict[str, int]] = None,
    quota_ids: Optional[Dict[str, int]] = None,
    pad_to: Optional[int] = None,
    gang_sort: Optional[Dict[str, Tuple[float, str]]] = None,
    cache=None,
) -> PodBatch:
    """Pack pods in scheduling-queue order (kube-scheduler PrioritySort +
    coscheduling Less, coscheduling.go:118): priority desc, sub-priority
    desc, then the GANG GROUP's identity — members of one gang sort by their
    gang's creation time and name, so a gang schedules contiguously instead
    of interleaving with unrelated pods — then pod creation time asc, key
    asc. ``gang_sort`` maps gang name -> (gang creation time, gang key);
    gangless pods (and unknown gangs) group as themselves."""
    gang_sort = gang_sort or {}

    def queue_key(i):
        pod = pods[i]
        group_time, group_key = gang_sort.get(
            pod.gang_key,
            (pod.meta.creation_timestamp, pod.meta.key),
        )
        return (
            -(pod.spec.priority or 0),
            -pod.sub_priority,
            group_time,
            group_key,
            pod.meta.creation_timestamp,
            pod.meta.key,
        )

    order = sorted(range(len(pods)), key=queue_key)
    pods = [pods[i] for i in order]
    n = len(pods)
    p = pad_to or bucket_size(n)
    # wire-unit matrices filled in one pass (no per-pod vector allocations),
    # packed with a single vectorized scale
    req_wire = np.zeros((p, NUM_RESOURCES), np.float64)
    lim_wire = np.zeros((p, NUM_RESOURCES), np.float64)
    prio = np.zeros(p, np.int32)
    qos = np.full(p, 5, np.int32)  # QoSClass.NONE
    pcls = np.full(p, int(PriorityClass.NONE), np.int32)
    prod = np.zeros(p, bool)
    ds = np.zeros(p, bool)
    gang = np.full(p, -1, np.int32)
    quota = np.full(p, -1, np.int32)
    valid = np.zeros(p, bool)
    est = np.zeros((p, NUM_RESOURCES), np.float32)
    # per-pod packed rows memoized by (key, resourceVersion) when a
    # SnapshotCache rides along (scheduler/snapshot_cache.py): pods carried
    # over between cycles skip the wire fill, the QoS/priority resolution
    # AND the estimator (row-wise, so per-row caching is exact)
    misses = []
    for i, pod in enumerate(pods):
        hit = cache.pod_row(pod) if cache is not None else None
        if hit is not None:
            req_wire[i] = hit["req_wire"]
            lim_wire[i] = hit["lim_wire"]
            prio[i] = hit["prio"]
            qos[i] = hit["qos"]
            pcls[i] = hit["pcls"]
            prod[i] = hit["prod"]
            ds[i] = hit["ds"]
            est[i] = hit["est"]
        else:
            misses.append(i)
            pod.spec.requests.fill_wire_row(req_wire[i])
            pod.spec.limits.fill_wire_row(lim_wire[i])
            prio[i] = pod.spec.priority or 0
            qos[i] = int(pod.qos_class)
            cls = pod.priority_class
            pcls[i] = int(cls)
            # GetPodPriorityClassWithDefault: pods outside koordinator bands
            # default to PROD semantics in LoadAware's prod checks
            prod[i] = cls in (PriorityClass.PROD, PriorityClass.NONE)
            ds[i] = pod.meta.owner_kind == "DaemonSet"
        if gang_ids and pod.gang_name:
            gang[i] = gang_ids.get(pod.gang_key, -1)
        if quota_ids and pod.quota_name:
            quota[i] = quota_ids.get(pod.quota_name, -1)
        valid[i] = True
    req = (req_wire / PACK_SCALE).astype(np.float32)
    lim = (lim_wire / PACK_SCALE).astype(np.float32)
    # estimate only rows not served from the cache: padding must carry
    # zeros, never the 250-milli/200-MiB defaults the estimator assigns
    # empty requests
    if cache is None:
        est[:n] = estimate_pods_used_batch(
            req[:n], lim[:n], pcls[:n], resource_weights, scaling_factors
        )
    elif misses:
        mi = np.asarray(misses)
        est[mi] = estimate_pods_used_batch(
            req[mi], lim[mi], pcls[mi], resource_weights, scaling_factors
        )
    if cache is not None:
        for i in misses:
            cache.put_pod_row(pods[i], {
                "req_wire": req_wire[i].copy(), "lim_wire": lim_wire[i].copy(),
                "prio": int(prio[i]), "qos": int(qos[i]),
                "pcls": int(pcls[i]), "prod": bool(prod[i]),
                "ds": bool(ds[i]), "est": est[i].copy(),
            })
    return PodBatch(
        keys=[pd.meta.key for pd in pods],
        requests=req,
        estimated=est,
        priority=prio,
        qos=qos,
        prio_class=pcls,
        is_prod=prod,
        is_daemonset=ds,
        gang_id=gang,
        quota_id=quota,
        valid=valid,
    )


def pack_nodes(
    nodes: Sequence[Node],
    assigned_requests: Optional[Dict[str, np.ndarray]] = None,
    pad_to: Optional[int] = None,
) -> NodeBatch:
    """Pack node allocatable + current requested (the NodeResourcesFit state)."""
    n = len(nodes)
    size = pad_to or bucket_size(n)
    alloc = np.zeros((size, NUM_RESOURCES), np.float32)
    requested = np.zeros((size, NUM_RESOURCES), np.float32)
    valid = np.zeros(size, bool)
    for i, node in enumerate(nodes):
        alloc[i] = estimate_node_allocatable(node)
        if assigned_requests is not None:
            vec = assigned_requests.get(node.meta.name)
            if vec is not None:
                requested[i] = vec
        valid[i] = True
    return NodeBatch(
        names=[nd.meta.name for nd in nodes],
        allocatable=alloc,
        requested=requested,
        valid=valid,
    )


def metric_age(node_metric: Optional[NodeMetric], now: Optional[float] = None) -> float:
    if node_metric is None or node_metric.update_time <= 0:
        return float("inf")
    return (time.time() if now is None else now) - node_metric.update_time
