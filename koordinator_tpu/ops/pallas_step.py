"""Pallas TPU kernel for the serial-parity scheduling step.

The XLA path (models/scheduler_model.build_schedule_step) expresses the
sequential pod loop as `lax.fori_loop`; every iteration re-reads the [N, R]
node state from wherever XLA materialized it. This kernel instead runs the
WHOLE pod loop inside one `pallas_call` with the node state pinned in VMEM:

  * grid = (P_pad / UNROLL,) — TPU grids are sequential, so scratch buffers
    carry the running state (headroom, LoadAware assign-cache deltas) from
    step to step with zero HBM round-trips; each step walks UNROLL pods in
    order with the state held in registers;
  * node arrays are laid out transposed [R, N] so the N axis rides the
    128-wide lanes (R <= 16 sublanes, f32 min tile is (8, 128));
  * pod columns stream in as [R, POD_BLOCK] blocks; per-pod scalars sit in
    SMEM.

Semantics are bit-identical to the XLA step (same go_round / floor-division
helpers, same first-max tie-break); tests/test_pallas_step.py diffs the two
paths on randomized clusters. VMEM budget: ~8 [R, N] f32 arrays — N up to
~20k fits the 16 MB/core budget at R = 16.

Reference anchor: the loop this replaces is the scheduleOne Filter+Score
fan-out (SURVEY.md section 3.1); state carried corresponds to the Fit
`requested` cache and LoadAware's podAssignCache estimates
(plugins/loadaware/pod_assign_cache.go).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from koordinator_tpu.ops import loadaware as la_ops
from koordinator_tpu.ops import pallas_common as pc
from koordinator_tpu.ops.loadaware import LoadAwareArgs


from koordinator_tpu.ops.pallas_common import POD_BLOCK, UNROLL


def _make_kernel(weights: np.ndarray, prod_mode: bool, N: int, R: int):
    wsum = float(max(weights.sum(), 1.0))
    consts = pc.weight_consts(weights)

    def kernel(
        prod_ref, valid_ref, ds_ref,                     # [P] SMEM scalars
        req_ref, est_ref,                                # [R, POD_BLOCK] blocks
        alloc_ref, req0_ref, term_np_ref, term_pr_ref,   # [R, N] VMEM
        lafeas_np_ref, lafeas_pr_ref, node_ok_ref, score_valid_ref,  # [1, N]
        chosen_ref,                                      # [UNROLL, 1] out block
        requested_ref,                                   # [R, N] f32 out
        dnp_ref, dpr_ref,                                # [R, N] scratch
        headroom_ref,                                    # [R, N] scratch
    ):
        i = pl.program_id(0)
        alloc = alloc_ref[:]                             # [R, N]

        # state carried in headroom form (alloc - requested, alloc - base):
        # Fit and least-requested become single compares/subtracts; exact
        # f32 integer arithmetic keeps bindings bit-identical (see
        # ops/pallas_full_chain.py)
        @pl.when(i == 0)
        def _init():
            headroom_ref[:] = alloc - req0_ref[:]
            dnp_ref[:] = alloc - term_np_ref[:]
            if prod_mode:
                dpr_ref[:] = alloc - term_pr_ref[:]

        lafeas_np = lafeas_np_ref[0, :]
        lafeas_pr = lafeas_pr_ref[0, :]
        node_ok_row = node_ok_ref[0, :] > 0
        score_valid_row = score_valid_ref[0, :] > 0
        safe_cap = jnp.where(alloc > 0, alloc, 1.0)
        cap_pos = alloc > 0
        iota = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)[0]
        w_col = pc.weight_col(consts, R)
        req_blk = req_ref[:]
        est_blk = est_ref[:]

        headroom = headroom_ref[:]
        headla_np = dnp_ref[:]
        headla_pr = dpr_ref[:] if prod_mode else headla_np

        for j in range(UNROLL):
            p = i * UNROLL + j
            prod = prod_ref[p] > 0
            lane = (i * UNROLL) % POD_BLOCK + j
            pod_mask = pc.make_pod_mask(lane, POD_BLOCK)
            need = pc.pod_column(req_blk, pod_mask)      # [R, 1]
            est = pc.pod_column(est_blk, pod_mask)       # [R, 1]
            need_eff = jnp.where(need > 0, need, pc.NEG_F32)
            fit = jnp.all(headroom >= need_eff, axis=0)  # [N]

            # LoadAware least-allocated score with in-batch deltas
            headla = jnp.where(prod, headla_pr, headla_np) if prod_mode \
                else headla_np
            per_r = pc.least_requested_rem(headla - est, safe_cap, cap_pos)
            score = pc.weighted_floor_score_col(per_r, w_col, wsum)
            score = jnp.where(score_valid_row, score, 0.0)

            la_feas = jnp.where(prod, lafeas_pr, lafeas_np) > 0
            la_ok = la_feas | (ds_ref[p] > 0)
            feasible = node_ok_row & fit & la_ok
            score = jnp.where(feasible, score, -1.0)

            best, maxv, _ = pc.lowest_index_max(score, N, iota)
            found = (maxv >= 0.0) & (valid_ref[p] > 0)
            sel = ((iota == best) & found).astype(jnp.float32)   # [N]

            headroom = headroom - sel[None, :] * need
            est_add = sel[None, :] * est
            headla_np = headla_np - est_add
            if prod_mode:
                headla_pr = headla_pr - jnp.where(prod, 1.0, 0.0) * est_add
            picked = jnp.where(found, best, jnp.int32(-1))
            chosen_ref[j:j + 1, :] = picked.reshape(1, 1)

        headroom_ref[:] = headroom
        dnp_ref[:] = headla_np
        if prod_mode:
            dpr_ref[:] = headla_pr

        @pl.when(i == pl.num_programs(0) - 1)
        def _emit():
            requested_ref[:] = alloc - headroom

    return kernel


def estimate_vmem_bytes(N: int, R: int, P: int) -> int:
    """Upper-bound VMEM footprint of one pallas_call of the schedule kernel:
    2 double-buffered [R, POD_BLOCK] pod-column blocks, 8 [R, N] node
    buffers (4 in + 1 out + 3 scratch), 4 [1, N] rows, and the [P_pad, 1]
    chosen output, all f32. Used by
    models.scheduler_model.build_best_schedule_step to fall back to the XLA
    step when the state would not fit on-chip."""
    P_pad = -(-P // POD_BLOCK) * POD_BLOCK
    floats = 2 * R * POD_BLOCK * 2 + 8 * R * N + 4 * N + P_pad
    return 4 * floats


def build_pallas_schedule_step(args: LoadAwareArgs, interpret: bool = False,
                               jit: bool = True):
    """ScheduleInputs -> (chosen [P] int32, requested [N, R] f32), same
    contract as models.scheduler_model.build_schedule_step, computed by the
    VMEM-resident Pallas kernel. `interpret=True` runs the kernel in the
    Pallas interpreter (CPU parity tests)."""
    prod_mode = args.score_according_prod_usage
    weights = np.asarray(args.weight_vector(), np.float32)

    def step(inputs) -> Tuple[jnp.ndarray, jnp.ndarray]:
        P, R = inputs.fit_requests.shape
        N = inputs.allocatable.shape[0]
        reject_np, reject_prod = la_ops.loadaware_node_reject(
            inputs.allocatable,
            inputs.la_filter_usage,
            inputs.la_has_filter_usage,
            inputs.la_filter_thresholds,
            inputs.la_prod_thresholds,
            inputs.la_prod_pod_usage,
            inputs.la_filter_skip,
        )
        f32, row = pc.f32, pc.row
        P_pad, pad_p = pc.pad_pods(P, POD_BLOCK)

        def pods_t(x):  # [P, R] -> [R, P_pad]
            return jnp.pad(f32(x), pad_p + [(0, 0)]).T

        kernel = _make_kernel(weights, prod_mode, N, R)
        grid_inputs = (
            jnp.pad(f32(inputs.is_prod), pad_p),
            jnp.pad(f32(inputs.pod_valid), pad_p),  # padding invalid => -1
            jnp.pad(f32(inputs.is_daemonset), pad_p),
            pods_t(inputs.fit_requests), pods_t(inputs.estimated),
            f32(inputs.allocatable).T, f32(inputs.requested).T,
            f32(inputs.la_term_nonprod).T, f32(inputs.la_term_prod).T,
            row(~reject_np), row(~reject_prod),
            row(inputs.node_ok), row(inputs.la_score_valid),
        )
        smem, full = pc.smem_spec, pc.full_spec
        pod_spec = pc.pod_block_spec(R)
        chosen, requested_t = pl.pallas_call(
            kernel,
            grid=(P_pad // UNROLL,),
            in_specs=[
                smem(), smem(), smem(),
                pod_spec, pod_spec,
                full((R, N)), full((R, N)), full((R, N)), full((R, N)),
                full((1, N)), full((1, N)), full((1, N)), full((1, N)),
            ],
            out_specs=[
                pc.chosen_block_spec(),
                full((R, N)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((P_pad, 1), jnp.int32),
                jax.ShapeDtypeStruct((R, N), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((R, N), jnp.float32),
                pltpu.VMEM((R, N), jnp.float32),
                pltpu.VMEM((R, N), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",),
            ),
            interpret=interpret,
        )(*grid_inputs)
        return chosen[:P, 0], requested_t.T

    return jax.jit(step) if jit else step
