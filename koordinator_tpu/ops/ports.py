"""NodePorts (hostPort conflict) factorization, batched.

The vendored kube-scheduler NodePorts plugin rejects a node when any
existing pod on it already binds a requested hostPort. Per-(pod, node) set
checks don't batch, so the snapshot factorizes: the DISTINCT (protocol,
port) pairs the pending batch requests become slot ids s < PT (real
batches carry a handful — hostPorts are rare and fixed per workload);
every node carries port_used [N, PT] (does an existing/placed pod on node
n bind slot s), every pod carries wants [P, PT]. Feasibility is one
compare per slot: no wanted slot may be in use on the node; the update
after a placement marks the chosen node's wanted slots used.

hostIP scoping is collapsed to the 0.0.0.0 wildcard (a conflict on any IP
blocks the node): conservative — the scheduler refuses placements it
cannot prove safe, never the reverse. Reference semantics:
kube NodePorts Filter via cmd/koord-scheduler/main.go:53-62 (the upstream
scheduler app the reference wraps).

MAX_PORT_SLOTS = 16 keeps the Pallas encoding exact (per-pod wants ride
one float bitmask, < 2^24): batches with more distinct hostPorts mark the
EXCESS pods unschedulable for the round (conservative, loudly logged).
"""

from __future__ import annotations

import logging
from typing import List, Tuple

import numpy as np

logger = logging.getLogger(__name__)

MAX_PORT_SLOTS = 16

Slot = Tuple[str, int]  # (protocol, hostPort)


def _slots_of(pod) -> List[Slot]:
    return [(proto or "TCP", int(port)) for proto, port in pod.spec.host_ports]


def build_port_state(pending_pods, nodes, existing_pods, rows=None):
    """-> (slots, port_used [N, PT] f32, wants [P, PT] bool,
           overflow_pod_idx list[int])

    existing_pods: assigned non-terminated pods; their hostPorts seed
    port_used on their nodes (only for slots the pending batch requests —
    other ports can never conflict with this batch).

    rows: optional indices of pending pods that declare hostPorts — the
    extraction loops restrict to them (portless pods contribute no slot
    and want nothing, so the restriction is exact)."""
    if rows is None:
        rows = range(len(pending_pods))
    slots: List[Slot] = []
    ids = {}
    overflow: List[int] = []
    for i in rows:
        pod = pending_pods[i]
        fits = True
        for slot in _slots_of(pod):
            if slot in ids:
                continue
            if len(slots) >= MAX_PORT_SLOTS:
                fits = False
                continue
            ids[slot] = len(slots)
            slots.append(slot)
        if not fits:
            overflow.append(i)
            logger.warning(
                "pod %s exceeds the %d distinct hostPort slots the batch "
                "encoding holds; it is unschedulable this round",
                pod.meta.key, MAX_PORT_SLOTS)
    PT = len(slots)
    N = len(nodes)
    P = len(pending_pods)
    port_used = np.zeros((N, PT), np.float32)
    wants = np.zeros((P, PT), bool)
    if PT == 0:
        return slots, port_used, wants, overflow
    node_index = {node.meta.name: n for n, node in enumerate(nodes)}
    for pod in existing_pods:
        n = node_index.get(pod.spec.node_name)
        if n is None:
            continue
        for slot in _slots_of(pod):
            s = ids.get(slot)
            if s is not None:
                port_used[n, s] = 1.0
    for i in rows:
        pod = pending_pods[i]
        for slot in _slots_of(pod):
            s = ids.get(slot)
            if s is not None:
                wants[i, s] = True
    return slots, port_used, wants, overflow


MAX_IMAGE_PROFILES = 32
MAX_IMAGE_SCORE = 100.0
# upstream ImageLocality clamps the contribution window per image
_MIN_IMG = 23 * 1024 * 1024      # minThreshold: 23 MiB
_MAX_IMG = 1000 * 1024 * 1024    # maxContainerThreshold: 1000 MiB


def build_image_scores(pending_pods, nodes, rows=None):
    """ImageLocality score rows, profile-bucketed like preferred affinity:

    -> (img_rows [max(SI, 1), N] f32, pod_img_id [P] int32)

    Pods sharing an identical image list share a profile; a profile's row
    is the upstream ImageLocality score — sum over the pod's images of
    sizeBytes on the node scaled by how widely the image is spread
    (size * nodes_having / N), then normalized into 0..100 over the
    [minThreshold, maxThreshold * num_containers] window — a STATIC
    function of node.images. Batches with more than MAX_IMAGE_PROFILES
    distinct image sets drop the excess (score 0, loudly logged): soft
    scoring degrades, never blocks."""
    profiles: List[tuple] = []
    ids: dict = {}
    P = len(pending_pods)
    N = len(nodes)
    pod_img_id = np.full(P, -1, np.int32)
    dropped = 0
    for i in (rows if rows is not None else range(P)):
        pod = pending_pods[i]
        imgs = tuple(sorted(set(pod.spec.images)))
        if not imgs:
            continue
        sid = ids.get(imgs)
        if sid is None:
            if len(profiles) >= MAX_IMAGE_PROFILES:
                dropped += 1
                continue
            sid = ids[imgs] = len(profiles)
            profiles.append(imgs)
        pod_img_id[i] = sid
    if dropped:
        logger.warning(
            "ImageLocality profile budget exceeded: %d pods keep a zero "
            "image-locality score this round (max %d distinct image sets)",
            dropped, MAX_IMAGE_PROFILES)
    SI = len(profiles)
    img_rows = np.zeros((max(SI, 1), N), np.float32)
    if SI and N:
        # vectorized: ONE [N, I] spread-weighted size matrix over the
        # distinct referenced images, then each profile row is a column-sum
        # (no per-(profile, node, image) Python loops — the snapshot's
        # pack_wire_matrix discipline)
        img_ids: dict = {}
        for imgs in profiles:
            for name in imgs:
                img_ids.setdefault(name, len(img_ids))
        size_mat = np.zeros((N, len(img_ids)), np.float64)
        for n, node in enumerate(nodes):
            for name, size in node.images.items():
                j = img_ids.get(name)
                if j is not None:
                    size_mat[n, j] = size
        have_frac = (size_mat > 0).sum(axis=0) / N          # spread factor
        weighted = size_mat * have_frac[None, :]            # [N, I]
        for s, imgs in enumerate(profiles):
            cols = [img_ids[name] for name in imgs]
            row = weighted[:, cols].sum(axis=1).astype(np.float32)
            lo, hi = _MIN_IMG, _MAX_IMG * max(len(imgs), 1)
            clipped = np.clip(row, lo, hi)
            img_rows[s] = np.floor(
                (clipped - lo) * np.float32(MAX_IMAGE_SCORE) / (hi - lo))
    return img_rows, pod_img_id
