"""ElasticQuota: hierarchical runtime-quota redistribution + batched admission.

Reference: `pkg/scheduler/plugins/elasticquota/core/` —
  * runtime_quota_calculator.go:111-168 `redistribution`: per (parent, resource),
    children whose request exceeds effective-min (max(min, guarantee)) start at
    min and share the leftover by sharedWeight in iterated rounds
    (delta = floor(w * leftover / totalW + 0.5), capped at request, excess
    recycled) — a fixed-point water-filling.
  * plugin.go:210-256 + plugin_helper.go:281 `checkQuotaRecursive`: admission
    walks the ancestor chain; every ancestor must satisfy
    used + podRequest <= runtimeQuota on every resource.

Batched formulation: all sibling groups across ALL parents are processed in one
[G] vector per round with segment-sums by parent id (one water-filling round is a
segment-reduce + elementwise update; the loop runs until no group changes, bounded
by G rounds). Levels are computed top-down so a child's total is its parent's
runtime. Admission uses a fixed-depth ancestor table ancestors[G, D] so the
per-pod check in the serial loop is a gather + compare, and in-batch `used` deltas
are scatter-adds along the chain.

Order-dependent admission (SURVEY.md section 7 hard parts) is preserved by the
serial-parity loop: pods are admitted in queue order against mutating `used`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCES
from koordinator_tpu.ops.common import go_round_np

MAX_QUOTA_DEPTH = 4  # root -> ... -> leaf (reference trees are shallow)


@dataclass
class QuotaTreeArrays:
    """Packed quota tree (host-built, device-consumed)."""

    names: List[str]
    parent: np.ndarray        # [G] int32, -1 for roots
    ancestors: np.ndarray     # [G, D] int32 self-then-ancestors, -1 padded
    min: np.ndarray           # [G, R]
    max: np.ndarray           # [G, R]
    shared_weight: np.ndarray  # [G, R]
    request: np.ndarray       # [G, R] sum of member pod requests
    used: np.ndarray          # [G, R] sum of scheduled member pod requests
    guarantee: np.ndarray     # [G, R]
    allow_lent: np.ndarray    # [G] bool
    level: np.ndarray         # [G] int32 depth (root=0)
    index: Dict[str, int] = field(default_factory=dict)
    # per-group enable flag for min-quota scaling; the reference's manager flag
    # is global-on (group_quota_manager.go:86) but the ScaleMinQuotaManager
    # tracks both categories, so the mask is kept per group
    enable_min_scale: np.ndarray = field(default_factory=lambda: np.zeros(0, bool))


def water_fill_level(
    total: np.ndarray,         # [G, R] available to each group's children
    parent: np.ndarray,        # [G] int32 (-1 roots)
    min_: np.ndarray,          # [G, R]
    guarantee: np.ndarray,     # [G, R]
    request: np.ndarray,       # [G, R]
    shared_weight: np.ndarray,  # [G, R]
    allow_lent: np.ndarray,    # [G]
    level: np.ndarray,         # [G]
    cur_level: int,
    num_groups: int,
) -> np.ndarray:
    """One level of redistribution: returns runtime[G, R] for groups at cur_level
    (other rows zero). `total[g]` must hold the parent's runtime (or cluster total
    for roots).

    Host numpy, NOT a device kernel: the quota tree is control-plane scale
    (G ~ 10^2) and this runs at snapshot-build time on every reconcile — jitting
    it costs 10^4x its runtime in per-shape XLA compiles. The per-pod admission
    side (quota_admit_row / quota_used_add_row) stays in-kernel where the
    pod-axis batching lives."""
    G = parent.shape[0]
    active = (level == cur_level)[:, None]  # [G, 1]
    eff_min = np.maximum(min_, guarantee)
    over = request > eff_min
    base = np.where(over, eff_min, np.where(allow_lent[:, None], request, eff_min))
    base = np.where(active, base, 0.0)

    # roots share the cluster total: they get a common virtual segment id G
    seg = np.where(parent >= 0, parent, G)
    adjustable = over & active & (shared_weight > 0)

    def seg_sum(x):
        out = np.zeros((G + 1, x.shape[1]), x.dtype)
        np.add.at(out, seg, x)
        return out

    spent = seg_sum(base)                       # [G+1, R]
    # per-parent leftover; total is constant within a segment (parent's runtime)
    seg_total = np.full((G + 1, total.shape[1]), -np.inf, total.dtype)
    np.maximum.at(seg_total, seg, np.where(active, total, -np.inf))
    leftover_seg = np.maximum(seg_total - spent, 0.0)
    leftover_seg[~np.isfinite(leftover_seg)] = 0.0

    runtime = base
    for _ in range(num_groups + 2):
        if not adjustable.any() or not (leftover_seg > 0).any():
            break
        w = np.where(adjustable, shared_weight, 0.0)
        wsum = seg_sum(w)[seg]                  # [G, R]
        delta = np.where(
            (wsum > 0) & adjustable,
            go_round_np(shared_weight * leftover_seg[seg] / np.maximum(wsum, 1e-9)),
            0.0,
        )
        new_rt = runtime + delta
        overshoot = np.maximum(new_rt - request, 0.0)
        # only adjustable (over-requesting) rows clamp to request; a non-lent
        # sibling sits at eff_min > request and must keep it
        # (runtime_quota_calculator.go:128-134 keeps runtimeQuota = min there)
        new_rt = np.where(adjustable, np.minimum(new_rt, request), runtime)
        # a child stays adjustable while below its request EVEN if this round's
        # rounded delta was 0 — recycled overshoot must still reach it next
        # round (reference iterationForRedistribution keeps it in `nodes`)
        still = adjustable & (new_rt < request)
        # next round distributes ONLY the overshoot recycled this round
        # (undistributed rounding remainder is dropped, as in the reference)
        leftover_seg = seg_sum(np.where(adjustable, overshoot, 0.0))
        runtime = new_rt
        adjustable = still
    return np.where(active, runtime, 0.0).astype(np.float32)


def scaled_min_level(
    total: np.ndarray,    # [G, R] each group's parent-available total
    parent: np.ndarray,   # [G]
    min_: np.ndarray,     # [G, R] original min
    enable: np.ndarray,   # [G] bool — group participates in scaling
    level: np.ndarray,    # [G]
    cur_level: int,
) -> np.ndarray:
    """AutoScaleMin for groups at cur_level
    (core/scale_minquota_when_over_root_res.go:103-160): per (parent, resource)
    where the children's min sum exceeds the parent's total, enable-scale
    children split max(0, total - disabledSum) proportionally to their original
    min (truncated, as the reference's int64 conversion does); disable-scale
    children always keep their original min."""
    G, R = min_.shape
    active = level == cur_level
    seg = np.where(parent >= 0, parent, G)

    def seg_sum(mask):
        out = np.zeros((G + 1, R), np.float64)
        rows = active & mask
        np.add.at(out, seg[rows], min_[rows])
        return out

    en_sum = seg_sum(enable)
    dis_sum = seg_sum(~enable)
    # per-segment total (constant within a segment: the parent's runtime)
    seg_total = np.full((G + 1, R), -np.inf)
    np.maximum.at(seg_total, seg[active], total[active])
    seg_total[~np.isfinite(seg_total)] = 0.0

    need_scale = (en_sum + dis_sum) > seg_total          # [G+1, R]
    avail = np.maximum(seg_total - dis_sum, 0.0)
    scaled = np.floor(
        avail[seg] * min_ / np.maximum(en_sum[seg], 1e-9)
    )
    use = active[:, None] & enable[:, None] & need_scale[seg]
    return np.where(use, scaled, min_).astype(np.float32)


def compute_runtime_quotas(
    tree: QuotaTreeArrays,
    cluster_total: np.ndarray,
    scale_min_enabled: bool = True,
) -> np.ndarray:
    """Top-down runtime quota for the whole tree: [G, R] float32.

    Level 0 children share cluster_total; level d children share their parent's
    runtime. When scale_min_enabled (the manager default,
    group_quota_manager.go:86), each level's mins are first auto-scaled where
    the siblings' min sum exceeds the parent total. Host numpy (see
    water_fill_level for why)."""
    G = len(tree.names)
    if G == 0:
        return np.zeros((0, NUM_RESOURCES), np.float32)
    parent = tree.parent
    runtime = np.zeros((G, NUM_RESOURCES), np.float32)
    max_level = int(tree.level.max()) if G else 0
    total_row = np.asarray(cluster_total, np.float32)
    enable = (
        tree.enable_min_scale
        if tree.enable_min_scale.shape[0] == G
        else np.ones(G, bool)
    )
    for lvl in range(max_level + 1):
        total = np.where(
            (parent >= 0)[:, None],
            runtime[np.clip(parent, 0, G - 1)],
            total_row[None, :],
        )
        min_eff = (
            scaled_min_level(total, parent, tree.min, enable, tree.level, lvl)
            if scale_min_enabled
            else tree.min
        )
        rt_lvl = water_fill_level(
            total,
            parent,
            min_eff,
            tree.guarantee,
            tree.request,
            tree.shared_weight,
            tree.allow_lent,
            tree.level,
            lvl,
            G,
        )
        runtime = np.where((tree.level == lvl)[:, None], rt_lvl, runtime)
    # cap by max (runtime never exceeds max; reference setClusterTotalResource /
    # quotaInfo semantics)
    return np.minimum(runtime, tree.max).astype(np.float32)


def quota_admit_row(
    request: jnp.ndarray,     # [R]
    quota_id: jnp.ndarray,    # scalar int32 (-1 = no quota -> admitted)
    ancestors: jnp.ndarray,   # [G, D]
    used: jnp.ndarray,        # [G, R]
    runtime: jnp.ndarray,     # [G, R]
) -> jnp.ndarray:
    """scalar bool: checkQuotaRecursive along the ancestor chain."""
    D = ancestors.shape[1]
    gid = jnp.maximum(quota_id, 0)
    chain = ancestors[gid]  # [D]
    ok = jnp.bool_(True)
    for d in range(D):
        g = chain[d]
        valid = g >= 0
        gg = jnp.maximum(g, 0)
        fit = jnp.all((request <= 0) | (used[gg] + request <= runtime[gg]))
        ok = ok & (~valid | fit)
    return jnp.where(quota_id >= 0, ok, True)


def quota_used_add_row(
    used: jnp.ndarray,        # [G, R]
    request: jnp.ndarray,     # [R]
    quota_id: jnp.ndarray,    # scalar int32
    ancestors: jnp.ndarray,   # [G, D]
    apply: jnp.ndarray,       # scalar bool
) -> jnp.ndarray:
    """Scatter-add the request along the ancestor chain when apply is set."""
    G, D = ancestors.shape
    gid = jnp.maximum(quota_id, 0)
    chain = ancestors[gid]
    onehot = jnp.zeros(G, jnp.float32)
    for d in range(D):
        g = chain[d]
        onehot = onehot + jnp.where(
            (g >= 0) & (quota_id >= 0) & apply,
            (jnp.arange(G, dtype=jnp.int32)
             == jnp.maximum(g, 0)).astype(jnp.float32),
            0.0,
        )
    return used + onehot[:, None] * request[None, :]


# ---------------------------------------------------------------------------
# Host-side tree construction (GroupQuotaManager analog, group_quota_manager.go)
# ---------------------------------------------------------------------------


def merge_group_request(
    pending_by_quota: Dict[str, np.ndarray],
    used_by_quota: Dict[str, np.ndarray],
) -> Dict[str, np.ndarray]:
    """Group request = pending + used: EVERY member pod counts toward the
    group's demand (GroupQuotaManager.updatePodRequestNoLock,
    group_quota_manager.go:184-256), not just the unscheduled ones. Single
    home for the rule — the snapshot builder, preemptor, and revoke
    controller all derive runtime quotas from it."""
    out: Dict[str, np.ndarray] = {k: v.copy() for k, v in pending_by_quota.items()}
    for k, v in used_by_quota.items():
        if k in out:
            out[k] = out[k] + v
        else:
            out[k] = v.copy()
    return out


def build_quota_tree(
    quotas,  # Sequence[ElasticQuota]
    pod_requests_by_quota: Optional[Dict[str, np.ndarray]] = None,
    used_by_quota: Optional[Dict[str, np.ndarray]] = None,
) -> QuotaTreeArrays:
    """Pack ElasticQuota CRs into QuotaTreeArrays (topology rebuild,
    group_quota_manager.go:425-533). Parents referenced by label; missing parents
    become roots. Request/used aggregate child -> parent recursively
    (:184-256)."""
    names = [q.meta.name for q in quotas]
    index = {n: i for i, n in enumerate(names)}
    G = len(names)
    parent = np.full(G, -1, np.int32)
    for i, q in enumerate(quotas):
        p = q.parent
        if p and p in index:
            parent[i] = index[p]
    # levels
    level = np.zeros(G, np.int32)
    for i in range(G):
        g, d = i, 0
        while parent[g] >= 0 and d < MAX_QUOTA_DEPTH:
            g = parent[g]
            d += 1
        level[i] = d
    ancestors = np.full((G, MAX_QUOTA_DEPTH), -1, np.int32)
    for i in range(G):
        g, d = i, 0
        while g >= 0 and d < MAX_QUOTA_DEPTH:
            ancestors[i, d] = g
            g = parent[g]
            d += 1
    min_ = np.zeros((G, NUM_RESOURCES), np.float32)
    max_ = np.zeros((G, NUM_RESOURCES), np.float32)
    weight = np.zeros((G, NUM_RESOURCES), np.float32)
    request = np.zeros((G, NUM_RESOURCES), np.float32)
    used = np.zeros((G, NUM_RESOURCES), np.float32)
    guarantee = np.zeros((G, NUM_RESOURCES), np.float32)
    allow_lent = np.ones(G, bool)
    for i, q in enumerate(quotas):
        min_[i] = q.min.to_vector()
        max_[i] = q.max.to_vector()
        weight[i] = q.shared_weight.to_vector()
        guarantee[i] = q.guaranteed.to_vector()
        allow_lent[i] = q.allow_lent_resource
        if pod_requests_by_quota:
            vec = pod_requests_by_quota.get(q.meta.name)
            if vec is not None:
                request[i] = vec
        if used_by_quota:
            vec = used_by_quota.get(q.meta.name)
            if vec is not None:
                used[i] = vec
    # aggregate request/used up the chain (deltas :184-256). A group's request
    # contribution to its parent is capped at its own max — limitRequest
    # semantics (quota_info.go:196-201, group_quota_manager.go:187) — otherwise
    # an over-max group would soak up leftover its siblings should receive.
    order = np.argsort(-level)
    for i in order:
        request[i] = np.minimum(request[i], max_[i])
        if parent[i] >= 0:
            request[parent[i]] += request[i]
            used[parent[i]] += used[i]
    return QuotaTreeArrays(
        names=names,
        parent=parent,
        ancestors=ancestors,
        min=min_,
        max=max_,
        shared_weight=weight,
        request=request,
        used=used,
        guarantee=guarantee,
        allow_lent=allow_lent,
        level=level,
        index=index,
        enable_min_scale=np.ones(G, bool),
    )
