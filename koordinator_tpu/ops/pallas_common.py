"""Shared fragments for the VMEM-resident Pallas scheduling kernels.

Both kernels (ops/pallas_step.py LoadAware-only, ops/pallas_full_chain.py
full chain) carry the bit-identical-bindings contract against the XLA steps;
the logic they share lives here as plain-Python helpers called from inside
the kernel bodies, so a fix lands in both at once.
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

MAX_NODE_SCORE = 100.0

# Shared unroll/streaming scheme for the sequential kernels: UNROLL pods are
# walked per grid step (grid bookkeeping and state load/store amortize), pod
# columns stream in as [R, POD_BLOCK] blocks, and the chosen output block is
# (UNROLL, 1) — written by exactly one step. UNROLL must divide POD_BLOCK.
UNROLL = 8
POD_BLOCK = 128

# Effective-request sentinel: rows with no demand compare true against any
# headroom, making (req <= 0) | (req <= free) a single compare.
NEG_F32 = -3.0e38

# Per-core VMEM the sequential kernels may pin (TPU v4/v5e expose ~16 MiB
# of VMEM per TensorCore; leave headroom for Mosaic's own spills and the
# grid machinery). The backend selectors fall back to the XLA step past
# this. Override with KOORD_TPU_VMEM_BUDGET_BYTES for chips with more VMEM.
DEFAULT_VMEM_BUDGET_BYTES = 14 * 1024 * 1024


def vmem_budget_bytes() -> int:
    import os

    raw = os.environ.get("KOORD_TPU_VMEM_BUDGET_BYTES", "")
    try:
        return int(raw) if raw else DEFAULT_VMEM_BUDGET_BYTES
    except ValueError:
        return DEFAULT_VMEM_BUDGET_BYTES


def weight_consts(weights: np.ndarray) -> List[Tuple[int, float]]:
    """Static (axis, weight) pairs baked into the kernel as Python floats —
    SMEM only serves scalars, so weights can't ride a vector input."""
    return [(r, float(v)) for r, v in enumerate(weights) if v]


def pod_column(ref, pod_mask) -> jnp.ndarray:
    """Extract pod i's [R, 1] column from an [R, P] array via the lane
    one-hot `pod_mask` ([1, P]). TPU block shapes can't carve a [1, R] row
    and dynamic lane slicing relayouts; the masked reduce is a few hundred
    VPU flops."""
    return jnp.sum(ref[:] * pod_mask, axis=1, keepdims=True)


def make_pod_mask(i, P_pad: int) -> jnp.ndarray:
    return (jax.lax.broadcasted_iota(jnp.int32, (1, P_pad), 1) == i
            ).astype(jnp.float32)


def weight_col(consts, R: int) -> jnp.ndarray:
    """[R, 1] weight column built from a sublane iota — Pallas kernels
    cannot capture array constants, so the static weights are encoded as a
    chain of iota selects."""
    r_iota = jax.lax.broadcasted_iota(jnp.int32, (R, 1), 0)
    col = jnp.zeros((R, 1), jnp.float32)
    for r, wv in consts:
        col = jnp.where(r_iota == r, jnp.float32(wv), col)
    return col


def safe_reciprocal(cap) -> jnp.ndarray:
    """f32 1/cap with 0 for cap <= 0. The balanced-allocation score in every
    implementation (XLA evaluator, Pallas kernel, wave kernel, numpy oracle,
    C++ floor) computes f = min(used * safe_reciprocal(cap), 1) — the SAME
    f32 reciprocal-multiply — so bit-parity across kernels holds while the
    per-pod division rows disappear. The JAX sites all call this helper; the
    numpy/C++ forms transcribe it (1.0f/cap guarded by cap > 0)."""
    return jnp.where(cap > 0, 1.0 / jnp.where(cap > 0, cap, 1.0), 0.0)


def least_requested_rem(rem, safe_cap, cap_pos) -> jnp.ndarray:
    """least_requested with the remainder (alloc - used) precomputed and
    safe_cap/cap_pos hoisted out of the per-pod loop: rem >= 0 is exactly
    used <= alloc for the packed-integer values the kernels carry."""
    per_r = jnp.floor(rem * MAX_NODE_SCORE / safe_cap)
    return jnp.where(cap_pos & (rem >= 0), per_r, 0.0)


def weighted_floor_score_col(per_r, w_col, wsum: float) -> jnp.ndarray:
    """weighted_floor_score as one [R, 1]-broadcast multiply + sublane
    reduce — per-row slicing of an [R, N] array relayouts on Mosaic, so the
    loop form costs ~3x. Same f32 product/sum values, so the floor parity
    holds (per-axis products are exact for packed integers * small weights,
    and the sum order over R is ascending in both forms)."""
    return jnp.floor(jnp.sum(per_r * w_col, axis=0) / wsum)


def lowest_index_max(score, N: int, iota=None):
    """(best, maxv, iota): lowest-index max, computed explicitly — Mosaic's
    argmax does not guarantee first-occurrence on ties, and the binding
    contract (reference selectHost determinism) hangs on this tie-break.
    Pass a precomputed [N] iota to hoist it out of a per-pod loop."""
    maxv = jnp.max(score)
    if iota is None:
        iota = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)[0]
    best = jnp.min(jnp.where(score == maxv, iota, jnp.int32(N))
                   ).astype(jnp.int32)
    return best, maxv, iota


# ---- wrapper-side packing helpers ----------------------------------------

smem_spec = functools.partial(pl.BlockSpec, memory_space=pltpu.SMEM)


def full_spec(shape):
    return pl.BlockSpec(shape, lambda i: (0, 0))


def pod_block_spec(R: int):
    """[R, POD_BLOCK] streaming spec for pod-column arrays: a block serves
    POD_BLOCK // UNROLL consecutive grid steps."""
    return pl.BlockSpec((R, POD_BLOCK), lambda i: (0, (i * UNROLL) // POD_BLOCK))


def chosen_block_spec():
    """(UNROLL, 1) chosen-output block, one per grid step."""
    return pl.BlockSpec((UNROLL, 1), lambda i: (i, 0))


def f32(x) -> jnp.ndarray:
    return jnp.asarray(x, jnp.float32)


def row(x) -> jnp.ndarray:
    return f32(x)[None, :]


def pad_pods(P: int, multiple: int = 8):
    """(P_pad, pad_spec): pods padded to a multiple (8 so the (8, 1) chosen
    blocks divide the grid; the unrolled full-chain kernel asks for its
    POD_BLOCK). Padded entries have pod_valid == 0."""
    P_pad = -(-P // multiple) * multiple
    return P_pad, [(0, P_pad - P)]
