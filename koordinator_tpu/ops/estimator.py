"""Pod/node usage estimator.

Faithful reimplementation of the LoadAware default estimator
(`pkg/scheduler/plugins/loadaware/estimator/default_estimator.go:56-108`):

  for each weighted resource (native name, e.g. cpu/memory):
    real = translate by priority class (cpu -> batch-cpu for koord-batch pods, ...)
    if limit > request: quantity = limit, scalingFactor = 100
    else:               quantity = request, scalingFactor = args factor
    if quantity == 0:   cpu-like -> 250 milli, memory-like -> 200 MiB, else 0
    estimated = round(quantity * scalingFactor / 100), capped at limit when set

Estimates are keyed by the NATIVE resource axis (the scorer compares against native
node allocatable even for batch/mid pods). Units are packed units (milli-cpu / MiB),
applied identically in the serial parity emulator.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from koordinator_tpu.api.objects import Node, Pod
from koordinator_tpu.api.resources import (
    NUM_RESOURCES,
    RESOURCE_INDEX,
    ResourceName,
    translate_resource_by_priority_class,
)

# default_estimator.go:35-38 (packed units)
DEFAULT_MILLI_CPU_REQUEST = 250.0
DEFAULT_MEMORY_REQUEST_MIB = 200.0

_CPU_LIKE = {ResourceName.CPU, ResourceName.BATCH_CPU, ResourceName.MID_CPU}
_MEMORY_LIKE = {ResourceName.MEMORY, ResourceName.BATCH_MEMORY, ResourceName.MID_MEMORY}


def estimate_pod_used(
    pod: Pod,
    resource_weights: Dict[str, int],
    scaling_factors: Dict[str, int],
) -> np.ndarray:
    """Return the [R] float32 estimated-usage vector (native axes only)."""
    req = pod.spec.requests.to_vector().astype(np.float64)
    lim = pod.spec.limits.to_vector().astype(np.float64)
    prio_class = pod.priority_class
    out = np.zeros(NUM_RESOURCES, dtype=np.float64)
    for native in resource_weights:
        real = translate_resource_by_priority_class(prio_class, native)
        if real is None:
            continue
        i_real = RESOURCE_INDEX[real]
        limit_q, request_q = lim[i_real], req[i_real]
        if limit_q > request_q:
            quantity, factor = limit_q, 100.0
        else:
            quantity, factor = request_q, float(scaling_factors.get(native, 100))
        if quantity == 0:
            if real in _CPU_LIKE:
                est = DEFAULT_MILLI_CPU_REQUEST
            elif real in _MEMORY_LIKE:
                est = DEFAULT_MEMORY_REQUEST_MIB
            else:
                est = 0.0
        else:
            est = np.floor(quantity * factor / 100.0 + 0.5)  # go_round
            if limit_q > 0:
                est = min(est, limit_q)
        out[RESOURCE_INDEX[native]] = est
    return out.astype(np.float32)


def estimate_pods_used_batch(
    req_packed: np.ndarray,      # [n, R] packed requests (to_vector units)
    lim_packed: np.ndarray,      # [n, R] packed limits
    prio_class: np.ndarray,      # [n] int PriorityClass values
    resource_weights: Dict[str, int],
    scaling_factors: Dict[str, int],
) -> np.ndarray:
    """Vectorized estimate_pod_used over a whole batch: identical math, one
    set of numpy ops per (priority class, weighted axis) pair instead of a
    python loop per pod — the host-side packing hot path at 10k pods."""
    from koordinator_tpu.api.priority import PriorityClass

    n = req_packed.shape[0]
    req = req_packed.astype(np.float64)
    lim = lim_packed.astype(np.float64)
    out = np.zeros((n, NUM_RESOURCES), np.float64)
    classes = np.unique(prio_class)
    for native in resource_weights:
        i_native = RESOURCE_INDEX[native]
        if native in _CPU_LIKE:
            default = DEFAULT_MILLI_CPU_REQUEST
        elif native in _MEMORY_LIKE:
            default = DEFAULT_MEMORY_REQUEST_MIB
        else:
            default = 0.0
        factor_cfg = float(scaling_factors.get(native, 100))
        for cls_value in classes:
            real = translate_resource_by_priority_class(
                PriorityClass(int(cls_value)), native
            )
            if real is None:
                continue
            rows = prio_class == cls_value
            i_real = RESOURCE_INDEX[real]
            limit_q = lim[rows, i_real]
            request_q = req[rows, i_real]
            over = limit_q > request_q
            quantity = np.where(over, limit_q, request_q)
            factor = np.where(over, 100.0, factor_cfg)
            est = np.floor(quantity * factor / 100.0 + 0.5)  # go_round
            est = np.where(limit_q > 0, np.minimum(est, limit_q), est)
            est = np.where(quantity == 0, default, est)
            out[rows, i_native] = est
    return out.astype(np.float32)


def estimate_node_allocatable(node: Node) -> np.ndarray:
    """EstimateNode (default_estimator.go:110+): raw-allocatable annotation wins
    over status.allocatable when present (resource amplification); we model the
    amplified value directly on Node.allocatable. The node-reservation
    annotation (applyPolicy Default) trims schedulable allocatable — except
    the batch-* axes, which koord-manager already reserved-adjusted
    (pkg/util/node.go TrimNodeAllocatableByNodeReservation)."""
    vec = node.allocatable.to_vector()
    reserved, _cpus, trims = node.node_reservation()
    if trims and reserved.quantities:
        from koordinator_tpu.api.resources import BATCH_AXES

        rvec = reserved.to_vector()
        rvec[list(BATCH_AXES)] = 0.0
        vec = np.maximum(vec - rvec, 0.0)
    return vec
