"""Pod/node usage estimator.

Faithful reimplementation of the LoadAware default estimator
(`pkg/scheduler/plugins/loadaware/estimator/default_estimator.go:56-108`):

  for each weighted resource (native name, e.g. cpu/memory):
    real = translate by priority class (cpu -> batch-cpu for koord-batch pods, ...)
    if limit > request: quantity = limit, scalingFactor = 100
    else:               quantity = request, scalingFactor = args factor
    if quantity == 0:   cpu-like -> 250 milli, memory-like -> 200 MiB, else 0
    estimated = round(quantity * scalingFactor / 100), capped at limit when set

Estimates are keyed by the NATIVE resource axis (the scorer compares against native
node allocatable even for batch/mid pods). Units are packed units (milli-cpu / MiB),
applied identically in the serial parity emulator.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from koordinator_tpu.api.objects import Node, Pod
from koordinator_tpu.api.resources import (
    NUM_RESOURCES,
    RESOURCE_INDEX,
    ResourceName,
    translate_resource_by_priority_class,
)

# default_estimator.go:35-38 (packed units)
DEFAULT_MILLI_CPU_REQUEST = 250.0
DEFAULT_MEMORY_REQUEST_MIB = 200.0

_CPU_LIKE = {ResourceName.CPU, ResourceName.BATCH_CPU, ResourceName.MID_CPU}
_MEMORY_LIKE = {ResourceName.MEMORY, ResourceName.BATCH_MEMORY, ResourceName.MID_MEMORY}


def estimate_pod_used(
    pod: Pod,
    resource_weights: Dict[str, int],
    scaling_factors: Dict[str, int],
) -> np.ndarray:
    """Return the [R] float32 estimated-usage vector (native axes only)."""
    req = pod.spec.requests.to_vector().astype(np.float64)
    lim = pod.spec.limits.to_vector().astype(np.float64)
    prio_class = pod.priority_class
    out = np.zeros(NUM_RESOURCES, dtype=np.float64)
    for native in resource_weights:
        real = translate_resource_by_priority_class(prio_class, native)
        if real is None:
            continue
        i_real = RESOURCE_INDEX[real]
        limit_q, request_q = lim[i_real], req[i_real]
        if limit_q > request_q:
            quantity, factor = limit_q, 100.0
        else:
            quantity, factor = request_q, float(scaling_factors.get(native, 100))
        if quantity == 0:
            if real in _CPU_LIKE:
                est = DEFAULT_MILLI_CPU_REQUEST
            elif real in _MEMORY_LIKE:
                est = DEFAULT_MEMORY_REQUEST_MIB
            else:
                est = 0.0
        else:
            est = np.floor(quantity * factor / 100.0 + 0.5)  # go_round
            if limit_q > 0:
                est = min(est, limit_q)
        out[RESOURCE_INDEX[native]] = est
    return out.astype(np.float32)


def estimate_node_allocatable(node: Node) -> np.ndarray:
    """EstimateNode (default_estimator.go:110+): raw-allocatable annotation wins
    over status.allocatable when present (resource amplification); we model the
    amplified value directly on Node.allocatable."""
    return node.allocatable.to_vector()
