"""Shared kernel helpers reproducing Go arithmetic semantics.

The reference computes scores with int64 arithmetic (floor division) and percent
ratios with math.Round (half away from zero). Binding parity requires reproducing
those exactly; these helpers are used by BOTH the batched kernels and the serial
parity emulator so the two paths cannot drift.
"""

from __future__ import annotations

import jax.numpy as jnp

# kube-scheduler framework.MaxNodeScore
MAX_NODE_SCORE = 100.0


def go_round(x):
    """math.Round for non-negative values: half away from zero.

    (jnp.round is banker's rounding — round-half-to-even — which differs on .5
    boundaries and would flip filter decisions at exact threshold crossings.)
    """
    return jnp.floor(x + 0.5)


def go_round_np(x):
    """Host-numpy twin of go_round (same half-away-from-zero semantics)."""
    import numpy as np

    return np.floor(x + 0.5)


def least_requested_score(requested, capacity):
    """kube-scheduler leastRequestedScore (load_aware.go:389-397): 0 when capacity
    is 0 or requested > capacity, else floor((capacity-requested)*100/capacity)."""
    safe_cap = jnp.where(capacity > 0, capacity, 1.0)
    raw = jnp.floor((capacity - requested) * MAX_NODE_SCORE / safe_cap)
    return jnp.where((capacity > 0) & (requested <= capacity), raw, 0.0)


def most_requested_score(requested, capacity):
    """mostAllocated scorer (nodenumaresource/most_allocated.go): floor(req*100/cap),
    0 when capacity is 0 or requested > capacity."""
    safe_cap = jnp.where(capacity > 0, capacity, 1.0)
    raw = jnp.floor(requested * MAX_NODE_SCORE / safe_cap)
    return jnp.where((capacity > 0) & (requested <= capacity), raw, 0.0)


def weighted_mean_floor(scores, weights, axis=-1):
    """floor(sum(score*w)/sum(w)) — Go integer division of int64 sums."""
    wsum = jnp.sum(weights)
    safe = jnp.where(wsum > 0, wsum, 1.0)
    out = jnp.floor(jnp.sum(scores * weights, axis=axis) / safe)
    return jnp.where(wsum > 0, out, 0.0)
