"""Pallas TPU kernel for the FULL plugin-chain scheduling step.

Extends ops/pallas_step.py's VMEM-resident sequential loop to the whole chain
(models/full_chain.py): Fit + LoadAware + NodeNUMAResource (cpuset capacity,
SMT alignment, topology-policy admit, zone accounting) + ElasticQuota
admission — all state carried in VMEM across the (P,) grid. The gang Permit
barrier remains an XLA post-pass (one segment reduction per batch).

Layout choices (TPU lanes are 128 wide; f32 tile (8, 128)):
  * node arrays transposed [R, N] — nodes on lanes;
  * NUMA free state as one [K*R, N] buffer; zone k is the static row slice
    [k*R:(k+1)*R] (no 3D reductions needed — K is a static python loop);
  * quota tree in [R, G] lane layout — groups on lanes — so the per-pod
    request column [R, 1] broadcasts against (used, runtime) directly, and
    the ancestor-chain walk becomes one dynamic-sublane row slice of a
    host-precomputed [G, G] ancestor-closure matrix;
  * per-pod scalars (quota id, flags) in SMEM; per-pod vectors extracted from
    [R, P] arrays by a lane one-hot reduce.

Bindings are bit-identical to the XLA step — tests/test_pallas_full_chain.py
diffs them across NUMA/quota/gang configs, including the explicit
lowest-index-max tie-break Mosaic's argmax does not guarantee.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from koordinator_tpu.models.full_chain import (
    FullChainInputs,
    resolve_balance_idx,
)
from koordinator_tpu.ops import loadaware as la_ops
from koordinator_tpu.ops import pallas_common as pc
from koordinator_tpu.ops.gang import gang_permit_mask
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.ops.numa import POLICY_NONE, POLICY_SINGLE_NUMA_NODE

from koordinator_tpu.ops.pallas_common import POD_BLOCK, UNROLL


def estimate_vmem_bytes(N: int, R: int, K: int, G: int, P: int,
                        T: int = 0, S: int = 0, PT: int = 0,
                        SI: int = 0) -> int:
    """Upper-bound VMEM footprint of one pallas_call of the full-chain
    kernel, mirroring the in/out/scratch specs below: 3 double-buffered
    [R, POD_BLOCK] pod column blocks, 8 [R, N] node buffers, 2 [K*R, N]
    NUMA buffers, 11 [1, N] rows, quota state (4 [R, G_lane] + the
    double-buffered [UNROLL, G_lane] ancestor blocks) and the chosen
    output, all f32. Used by models.full_chain.build_best_full_chain_step
    to fall back to the XLA step when the state would not fit on-chip."""
    P_pad = -(-P // POD_BLOCK) * POD_BLOCK
    G_eff = max(G, 1)
    G_lane = max(128, -(-G_eff // 128) * 128)
    floats = (3 * POD_BLOCK * R * 2 + 8 * R * N + 2 * K * R * N + 14 * N
              + 5 * max(T, 0) * N + max(S, 1) * N
              + 2 * max(PT, 1) * N + max(SI, 1) * N
              + 4 * R * G_lane + 2 * UNROLL * G_lane + P_pad)
    return 4 * floats


# measured Mosaic SMEM allocation limit on v5e (the compile error reports
# "would exceed memory (size=1048576)")
SMEM_BUDGET_BYTES = 1 << 20


def estimate_smem_bytes(P: int, VG: int = 1, T: int = 0,
                        S2: int = 0) -> int:
    """Upper-bound SMEM footprint: 20 per-pod [P_pad] f32 scalar arrays,
    the flattened [P_pad * VG] volume-group rows (VG == 0 means the
    volume machinery is compiled out — a 1-float placeholder rides the
    input slot), the [max(T,1)] exists seed + scratch, and the
    [max(S2,1), max(T,1)] pod-pref weights. Used alongside
    estimate_vmem_bytes to degrade to the XLA step before Mosaic rejects
    the allocation (a high-VG batch is the only way past the budget at
    the shapes the VMEM check admits)."""
    P_pad = -(-P // POD_BLOCK) * POD_BLOCK
    vol_floats = VG * P_pad if VG else 1
    floats = (20 * P_pad + vol_floats + 2 * max(T, 1)
              + max(S2, 1) * max(T, 1))
    return 4 * floats


def _make_kernel(weights: np.ndarray, prod_mode: bool, N: int, R: int,
                 K: int, G: int, T: int = 0, S: int = 0, S2: int = 0,
                 PT: int = 0, SI: int = 0, VOL: bool = True,
                 VG: int = 1, BAL=(-1, -1)):
    wsum = float(max(weights.sum(), 1.0))
    consts = pc.weight_consts(weights)

    def kernel(
        # --- SMEM per-pod scalars
        prod_ref, valid_ref, ds_ref, gangok_ref,
        needsnuma_ref, needsbind_ref, fullpcpus_ref, cores_ref,  # f32 [P]
        taintmask_ref,                                            # f32 [P]
        affreq_ref, antireq_ref, affmatch_ref,   # f32 [P] term bitmasks
        skew0_ref, skew1_ref, skew2_ref,         # f32 [P] skew bit-planes
        affexists0_ref,                          # f32 [max(T,1)] host seed
        prefid_ref,                              # int32 [P] pref profile
        pprefid_ref,                             # int32 [P] pod-pref profile
        pprefw_ref,                              # f32 [max(S2,1), max(T,1)]
        portwants_ref,                           # f32 [P] port-slot bitmask
        volneeded_ref,                           # f32 [P * VG] new-PVC
        #     counts per node volume-group, FLATTENED row-major (pod p,
        #     group g at [p * VG + g]): a 2-D SMEM window lane-pads each
        #     row to 128 floats — 5 MB at 10k pods, over the 1 MB SMEM
        #     budget — so the per-pod rows stay 1-D
        imgid_ref,                               # int32 [P] image profile
        qid_ref,                                                  # int32 [P]
        # --- VMEM pod column blocks [R, POD_BLOCK]
        fitreq_ref, rawreq_ref, est_ref,
        # --- VMEM node state [R, N]
        alloc_ref, req0_ref, term_np_ref, term_pr_ref,
        # --- VMEM node rows [1, N]
        lafeas_np_ref, lafeas_pr_ref, node_ok_ref, score_valid_ref,
        has_topo_ref, bindfree0_ref, cpc_ref, policy_ref,
        taintpow_ref,                                  # [1, N] f32 2^group
        # --- VMEM numa [K*R, N] / per-pod ancestor rows [UNROLL, G_lane]
        #     (pre-gathered host-side: no in-kernel dynamic slice) / quota
        numafree0_ref, ancpod_ref, qused0_ref, qruntime_ref,
        # --- VMEM inter-pod affinity [max(T,1), N] + preferred-affinity
        #     profile score rows [max(S,1), N] + NodePorts slots
        #     [max(PT,1), N] + volume headroom [1, N] + ImageLocality rows
        affdom_ref, affcount0_ref, anticover0_ref, prefrows_ref,
        portused0_ref, volfree0_ref, volgrp_ref, imgrows_ref,
        # --- outputs
        chosen_ref,                 # (UNROLL, 1) int32 block, one per step
        requested_ref,              # [R, N] (carried)
        qused_ref,                  # [R, G] (carried)
        # --- scratch
        dnp_ref, dpr_ref,           # [R, N] (alloc - LoadAware base)
        numa_ref,                   # [K*R, N]
        bindfree_ref,               # [1, N]
        headroom_ref,               # [R, N] (alloc - requested)
        qacc_ref,                   # [R, G] quota-used accumulator
        affcount_ref,               # [max(T,1), N] carried term counts
        anticover_ref,              # [max(T,1), N] carried anti carriers
        portused_ref,               # [max(PT,1), N] carried port slots
        volfree_ref,                # [1, N] carried volume headroom
        affexists_ref,              # SMEM [max(T,1)] carried exists flags
    ):
        i = pl.program_id(0)
        alloc = alloc_ref[:]

        # Mutable chain state lives in VMEM scratch and is carried in
        # HEADROOM form — headroom_ref holds alloc - requested, dnp/dpr hold
        # alloc - (term + delta) — so the per-pod Fit check and
        # least-requested remainders are single compares/subtracts instead
        # of add-then-compare. The requested/quota-used OUTPUT buffers are
        # written only on the last grid step: output blocks round-trip to
        # HBM, so storing them per step would serialize the pipeline. All
        # quantities are packed integers < 2^24, so f32 arithmetic is exact
        # and the re-association preserves bit-parity with the XLA step.
        @pl.when(i == 0)
        def _init():
            headroom_ref[:] = alloc - req0_ref[:]
            dnp_ref[:] = alloc - term_np_ref[:]
            if prod_mode:
                dpr_ref[:] = alloc - term_pr_ref[:]
            numa_ref[:] = numafree0_ref[:]
            bindfree_ref[:] = bindfree0_ref[:]
            qacc_ref[:] = qused0_ref[:]
            if T:
                affcount_ref[:] = affcount0_ref[:]
                anticover_ref[:] = anticover0_ref[:]
                for t in range(T):
                    affexists_ref[t] = affexists0_ref[t]
            if PT:
                portused_ref[:] = portused0_ref[:]
            if VOL:
                volfree_ref[:] = volfree0_ref[:]

        # read-only node state: load once per grid step
        lafeas_np = lafeas_np_ref[0, :]
        lafeas_pr = lafeas_pr_ref[0, :]
        node_ok_row = node_ok_ref[0, :] > 0
        score_valid_row = score_valid_ref[0, :] > 0
        has_topo_row = has_topo_ref[0, :] > 0
        cpc = jnp.maximum(cpc_ref[0, :], 1.0)
        policy = policy_ref[0, :]
        taintpow = taintpow_ref[0, :]
        qruntime = qruntime_ref[:]
        w_col = pc.weight_col(consts, R)
        iota = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)[0]
        safe_cap = jnp.where(alloc > 0, alloc, 1.0)
        cap_pos = alloc > 0
        if BAL[0] >= 0:
            # balanced-allocation reciprocals once per grid step (8 pods)
            # instead of two division rows per pod (pc.safe_reciprocal
            # documents the cross-kernel bit-parity contract)
            bal_inv_c, bal_inv_m = (
                pc.safe_reciprocal(alloc[axis:axis + 1, :]) for axis in BAL)
        single_node = policy == POLICY_SINGLE_NUMA_NODE              # [N]
        fitreq_blk = fitreq_ref[:]
        rawreq_blk = rawreq_ref[:]
        est_blk = est_ref[:]

        # mutable chain state: carried in registers across the UNROLL pods,
        # stored back to the scratch refs once per grid step
        headroom = headroom_ref[:]                      # alloc - requested
        headla_np = dnp_ref[:]                          # alloc - np base
        headla_pr = dpr_ref[:] if prod_mode else headla_np
        numa = [numa_ref[k * R:(k + 1) * R, :] for k in range(K)]
        bindfree = bindfree_ref[0, :]
        qused = qacc_ref[:]                                          # [R, G]
        aff_dom = [affdom_ref[t:t + 1, :] for t in range(T)]         # [1, N]
        aff_count = [affcount_ref[t:t + 1, :] for t in range(T)]
        anti_cover = [anticover_ref[t:t + 1, :] for t in range(T)]
        port_used = [portused_ref[s:s + 1, :] for s in range(PT)]
        vol_free = volfree_ref[0, :] if VOL else None
        volgrp = volgrp_ref[0, :] if VOL else None  # [N] f32 group ids

        for j in range(UNROLL):
            p = i * UNROLL + j
            prod = prod_ref[p] > 0
            needs_numa = needsnuma_ref[p] > 0
            needs_bind = needsbind_ref[p] > 0
            full_pcpus = fullpcpus_ref[p] > 0
            cores = cores_ref[p]
            gid = qid_ref[p]
            has_quota = gid >= 0

            lane = (i * UNROLL) % POD_BLOCK + j
            pod_mask = pc.make_pod_mask(lane, POD_BLOCK)
            fit_need = pc.pod_column(fitreq_blk, pod_mask)
            raw_req = pc.pod_column(rawreq_blk, pod_mask)
            est = pc.pod_column(est_blk, pod_mask)                   # [R, 1]
            # effective requests: rows with no demand compare true against
            # anything, so (req <= 0) | (req <= free) is one compare
            fit_eff = jnp.where(fit_need > 0, fit_need, pc.NEG_F32)
            raw_eff = jnp.where(raw_req > 0, raw_req, pc.NEG_F32)

            # ---- PreFilter: quota admission along the ancestor closure row
            anc_row = ancpod_ref[j:j + 1, :]                         # [1, G]
            # f32 throughout: Mosaic can't truncate narrow bool vectors
            viol = jnp.max(
                jnp.where((raw_req > 0) & (qused + raw_req > qruntime),
                          1.0, 0.0),
                axis=0, keepdims=True)                               # [1, G]
            quota_ok = jnp.sum(anc_row * viol) <= 0.0
            admit = (gangok_ref[p] > 0) & (quota_ok | ~has_quota)

            # ---- Filter: Fit
            fit = jnp.all(headroom >= fit_eff, axis=0)               # [N]
            # ---- Filter: LoadAware thresholds
            la_feas = jnp.where(prod, lafeas_pr, lafeas_np) > 0
            la_ok = la_feas | (ds_ref[p] > 0)
            # ---- Filter: cpuset capacity + SMT alignment
            smt_ok = (~full_pcpus) | (
                jnp.abs(jnp.remainder(cores, cpc)) < 0.5)
            # f32-valued selects throughout the filter chain: Mosaic cannot
            # truncate/select narrow bool vectors
            cpuset_ok_f = jnp.where(
                has_topo_row & smt_ok & (cores <= bindfree), 1.0, 0.0)
            cpuset_ok = jnp.where(needs_bind, cpuset_ok_f, 1.0) > 0
            # ---- Filter: NUMA topology admit (ops/numa.numa_admit_row):
            # per-zone fits (ascending cumulative free kept for the
            # waterfall), lowest fitting zone wins
            fits = []
            cumfree = []
            run = jnp.zeros((R, N), jnp.float32) if K == 0 else None
            for k in range(K):
                fits.append(jnp.all(numa[k] >= raw_eff, axis=0))
                run = numa[k] if run is None else run + numa[k]
                cumfree.append(run)
            zone = jnp.full((N,), K, jnp.int32)
            for k in range(K - 1, -1, -1):
                zone = jnp.where(fits[k], jnp.int32(k), zone)        # lowest k
            fits_total = jnp.all(run >= raw_eff, axis=0)
            any_zone_f = jnp.where(zone < K, 1.0, 0.0)
            fits_total_f = jnp.where(fits_total, 1.0, 0.0)
            numa_ok_f = jnp.where(single_node, any_zone_f, fits_total_f)
            numa_ok_f = jnp.where(policy == POLICY_NONE, 1.0, numa_ok_f)
            numa_ok = jnp.where(needs_numa, numa_ok_f, 1.0) > 0

            # ---- Filter: TaintToleration — bit test in exact f32 arithmetic
            # (floor/mod; Mosaic has no shift-by-vector): bit g of mask is
            # floor(mask / 2^g) mod 2
            taint_ok = jnp.remainder(
                jnp.floor(taintmask_ref[p] / taintpow), 2.0) >= 1.0
            # ---- Filter: NodePorts (wanted slot free) + CSI volume limit
            # (VOL statically gates the volume machinery: volume-less
            # batches — the common case — pay nothing per pod)
            feasible = (node_ok_row & fit & la_ok & cpuset_ok
                        & numa_ok & taint_ok & admit)
            if VOL:
                # per-node NEW attachments: the pod's [VG] row gathered by
                # the node's volume group (select over static VG; group ids
                # are exact small-integer f32; flattened SMEM indexing)
                vol_needed = jnp.where(
                    volgrp == 0.0, volneeded_ref[p * VG], 0.0)
                for g in range(1, VG):
                    vol_needed = jnp.where(
                        volgrp == float(g), volneeded_ref[p * VG + g],
                        vol_needed)
                feasible = feasible & (
                    (vol_needed <= 0.0) | (vol_free >= vol_needed))
            for s in range(PT):
                want_s = jnp.remainder(
                    jnp.floor(portwants_ref[p] / float(1 << s)), 2.0) >= 1.0
                feasible = feasible & (
                    (~want_s) | (port_used[s][0, :] <= 0))
            # ---- Filter: InterPodAffinity (ops/podaffinity.py). Term
            # membership rides per-pod SMEM bitmasks; 2^t is a static
            # Python constant, so the bit tests are scalar ops.
            for t in range(T):
                aff_t = jnp.remainder(
                    jnp.floor(affreq_ref[p] / float(1 << t)), 2.0) >= 1.0
                anti_t = jnp.remainder(
                    jnp.floor(antireq_ref[p] / float(1 << t)), 2.0) >= 1.0
                match_t = jnp.remainder(
                    jnp.floor(affmatch_ref[p] / float(1 << t)), 2.0) >= 1.0
                count_t = aff_count[t][0, :]
                empty_t = count_t <= 0                              # [N]
                anti_ok = (~anti_t) | empty_t
                # symmetric anti-affinity: carriers of anti term t in this
                # node's domain block any pod matching t
                sym_ok = (~match_t) | (anti_cover[t][0, :] <= 0)
                boot = match_t & (affexists_ref[t] <= 0.0)
                dom_valid_t = aff_dom[t][0, :] >= 0
                aff_ok = (~aff_t) | boot | (dom_valid_t & ~empty_t)
                feasible = feasible & anti_ok & sym_ok & aff_ok
                # PodTopologySpread: skew reconstructed from 3 bit-planes
                bit = lambda ref: jnp.remainder(  # noqa: E731
                    jnp.floor(ref[p] / float(1 << t)), 2.0)
                skew = (bit(skew0_ref) + 2.0 * bit(skew1_ref)
                        + 4.0 * bit(skew2_ref))
                self_m = jnp.where(match_t, 1.0, 0.0)
                # min over domains the pod is ELIGIBLE for (admission test)
                min_count = jnp.min(
                    jnp.where(dom_valid_t & taint_ok, count_t, jnp.inf))
                spread_ok = (skew <= 0.0) | (
                    dom_valid_t & (count_t + self_m - min_count <= skew))
                feasible = feasible & spread_ok

            # ---- Score: LoadAware + NodeNUMAResource least-allocated
            headla = jnp.where(prod, headla_pr, headla_np) if prod_mode \
                else headla_np
            la_per_r = pc.least_requested_rem(headla - est, safe_cap, cap_pos)
            nu_per_r = pc.least_requested_rem(headroom - raw_req, safe_cap,
                                              cap_pos)
            la_score = pc.weighted_floor_score_col(la_per_r, w_col, wsum)
            la_score = jnp.where(score_valid_row, la_score, 0.0)
            score = la_score + pc.weighted_floor_score_col(nu_per_r, w_col,
                                                           wsum)
            # NodeResourcesBalancedAllocation: 2-axis std == |fc - fm| / 2.
            # requested = alloc - headroom (exact integers < 2^24, so the
            # re-association matches the XLA evaluator bit-for-bit)
            if BAL[0] >= 0:
                ci, mi = BAL

                def _frac(axis, inv):
                    cap = alloc[axis:axis + 1, :]
                    used = (cap - headroom[axis:axis + 1, :]
                            + fit_need[axis, 0])
                    return jnp.minimum(used * inv, 1.0)

                bal_std = jnp.abs(
                    _frac(ci, bal_inv_c) - _frac(mi, bal_inv_m)) * 0.5
                score = score + jnp.floor(
                    (1.0 - bal_std) * 100.0)[0, :]
            # preferred node affinity: static profile row one-hot select
            if S:
                sid = prefid_ref[p]
                for s in range(S):
                    score = score + jnp.where(
                        sid == s, prefrows_ref[s:s + 1, :][0, :], 0.0)
            # ImageLocality: static profile rows, same select pattern
            if SI:
                iid = imgid_ref[p]
                for s in range(SI):
                    score = score + jnp.where(
                        iid == s, imgrows_ref[s:s + 1, :][0, :], 0.0)
            # preferred POD affinity: weighted count sum, max-min normalized
            # per pod (weights read as SMEM scalars by traced profile id)
            if T and S2:
                sid2 = pprefid_ref[p]
                s2c = jnp.maximum(sid2, 0)
                raw = jnp.zeros((N,), jnp.float32)
                for t in range(T):
                    raw = raw + pprefw_ref[s2c, t] * aff_count[t][0, :]
                # max-min over node_ok only (upstream NormalizeScore spans
                # the candidate set; padded rows must not anchor the scale)
                mx = jnp.max(jnp.where(node_ok_row, raw, -jnp.inf))
                mn = jnp.min(jnp.where(node_ok_row, raw, jnp.inf))
                norm = jnp.where(
                    mx > mn,
                    jnp.floor((raw - mn) * 100.0 / (mx - mn)), 0.0)
                score = score + jnp.where(sid2 >= 0, norm, 0.0)
            score = jnp.where(feasible, score, -1.0)

            best, maxv, _ = pc.lowest_index_max(score, N, iota)
            found = (maxv >= 0.0) & (valid_ref[p] > 0)
            sel = ((iota == best) & found).astype(jnp.float32)       # [N]

            # ---- Reserve: state updates
            headroom = headroom - sel[None, :] * fit_need
            est_add = sel[None, :] * est
            headla_np = headla_np - est_add
            if prod_mode:
                headla_pr = headla_pr - jnp.where(prod, 1.0, 0.0) * est_add
            bindfree = bindfree - sel * jnp.where(needs_bind, cores, 0.0)
            # ports/volumes: bind wanted slots, debit volume headroom
            for s in range(PT):
                want_s = jnp.remainder(
                    jnp.floor(portwants_ref[p] / float(1 << s)), 2.0) >= 1.0
                port_used[s] = jnp.maximum(
                    port_used[s],
                    (sel * jnp.where(want_s, 1.0, 0.0))[None, :])
            if VOL:
                vol_free = vol_free - sel * vol_needed
            # numa: single-zone subtract + lowest-zones-first waterfall
            # (disjoint). Only the SingleNUMANode policy pins a zone
            # (numa_admit_row returns zone = -1 otherwise); every other
            # policy spread-fills. The waterfall take is the closed form
            # take_k = clip(D - cumfree_{<k}, 0, free_k): exact for packed
            # integers, identical to the sequential remaining-carry.
            apply_numa = sel * jnp.where(needs_numa, 1.0, 0.0)       # [N]
            single_m = apply_numa * jnp.where(
                single_node & (zone < K), 1.0, 0.0)
            spread_m = apply_numa - single_m
            demand = raw_req * spread_m[None, :]                     # [R, N]
            for k in range(K):
                zone_m = (single_m * jnp.where(zone == k, 1.0, 0.0))[None, :]
                free_k = numa[k] - raw_req * zone_m
                # cumfree >= 0, so off-demand columns clamp to 0 unmasked
                rem = demand if k == 0 else \
                    jnp.maximum(demand - cumfree[k - 1], 0.0)
                numa[k] = free_k - jnp.minimum(free_k, rem)
            # quota: add along the ancestor closure
            q_apply = jnp.where(found & has_quota, 1.0, 0.0)
            qused = qused + raw_req * anc_row * q_apply
            # affinity: raise matched terms' counts over the chosen domain
            # and latch the exists flag (even on an unlabeled node)
            for t in range(T):
                match_t = jnp.remainder(
                    jnp.floor(affmatch_ref[p] / float(1 << t)), 2.0) >= 1.0
                anti_t = jnp.remainder(
                    jnp.floor(antireq_ref[p] / float(1 << t)), 2.0) >= 1.0
                dom_row = aff_dom[t][0, :]
                chosen_dom = jnp.sum(sel * dom_row)
                in_dom = (chosen_dom >= 0) & (dom_row == chosen_dom)
                inc = jnp.where((found & match_t) & in_dom, 1.0, 0.0)
                aff_count[t] = aff_count[t] + inc[None, :]
                inc_cov = jnp.where((found & anti_t) & in_dom, 1.0, 0.0)
                anti_cover[t] = anti_cover[t] + inc_cov[None, :]
                affexists_ref[t] = jnp.where(
                    found & match_t, 1.0, affexists_ref[t])

            picked = jnp.where(found, best, jnp.int32(-1))
            chosen_ref[j:j + 1, :] = picked.reshape(1, 1)

        headroom_ref[:] = headroom
        dnp_ref[:] = headla_np
        if prod_mode:
            dpr_ref[:] = headla_pr
        for k in range(K):
            numa_ref[k * R:(k + 1) * R, :] = numa[k]
        bindfree_ref[:] = bindfree[None, :]
        qacc_ref[:] = qused
        for t in range(T):
            affcount_ref[t:t + 1, :] = aff_count[t]
            anticover_ref[t:t + 1, :] = anti_cover[t]
        for s in range(PT):
            portused_ref[s:s + 1, :] = port_used[s]
        if VOL:
            volfree_ref[:] = vol_free[None, :]

        @pl.when(i == pl.num_programs(0) - 1)
        def _emit():
            requested_ref[:] = alloc - headroom
            qused_ref[:] = qused

    return kernel


def build_pallas_full_chain_step(args: LoadAwareArgs, num_gangs: int,
                                 num_groups: int, interpret: bool = False,
                                 jit: bool = True, active_axes=None,
                                 enable_volumes: bool = True):
    """FullChainInputs -> (chosen[P], requested[N, R], quota_used[G, R]);
    same contract as models.full_chain.build_full_chain_step.

    enable_volumes=False compiles OUT the CSI volume-limit machinery (the
    per-pod [N] compare/select/update) — valid only for batches where no
    pod mounts volumes; the backend selector checks the concrete inputs
    and picks the variant."""
    full_weights = args.weight_vector()
    if active_axes is not None:
        full_weights = full_weights[list(active_axes)]
    weights = np.asarray(full_weights, np.float32)
    prod_mode = args.score_according_prod_usage

    def step(fc: FullChainInputs) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        inputs = fc.base
        P, R = inputs.fit_requests.shape
        N = inputs.allocatable.shape[0]
        K = fc.numa_free.shape[1]
        G = fc.quota_used.shape[0]
        G_eff = max(G, 1)
        reject_np, reject_prod = la_ops.loadaware_node_reject(
            inputs.allocatable,
            inputs.la_filter_usage,
            inputs.la_has_filter_usage,
            inputs.la_filter_thresholds,
            inputs.la_prod_thresholds,
            inputs.la_prod_pod_usage,
            inputs.la_filter_skip,
        )
        gang_pod_ok = jnp.where(
            fc.gang_id >= 0, fc.gang_valid[jnp.maximum(fc.gang_id, 0)], True
        )
        # ancestor closure computed traceably (inputs may be tracers under jit):
        # closure[g, a] = 1 iff a appears in g's chain (-1 padding never matches)
        if G:
            anc = jnp.any(
                fc.quota_ancestors[:, :, None]
                == jnp.arange(G, dtype=fc.quota_ancestors.dtype)[None, None, :],
                axis=1,
            ).astype(jnp.float32)
        else:
            anc = jnp.zeros((1, 1), jnp.float32)

        f32, row = pc.f32, pc.row
        P_pad, pad_p = pc.pad_pods(P, POD_BLOCK)
        spad = lambda x: jnp.pad(f32(x), pad_p)  # noqa: E731

        def pods_t(x):  # [P, R] -> [R, P_pad]
            return jnp.pad(f32(x), pad_p + [(0, 0)]).T

        # numa [N, K, R] -> [K*R, N]
        numa0 = jnp.transpose(f32(fc.numa_free), (1, 2, 0)).reshape(K * R, N)
        # quota lane axis padded to >= 128: Mosaic cannot truncate the narrow
        # bool vectors that comparisons on a (R, G<128) block would produce.
        # Padding runtime with +inf keeps phantom groups from ever violating.
        G_lane = max(128, -(-G_eff // 128) * 128)
        if G:
            qused0 = jnp.pad(f32(fc.quota_used).T, [(0, 0), (0, G_lane - G)])
            qruntime = jnp.pad(f32(fc.quota_runtime).T,
                               [(0, 0), (0, G_lane - G)],
                               constant_values=jnp.inf)
            qid = jnp.asarray(fc.quota_id, jnp.int32)
        else:
            qused0 = jnp.zeros((R, G_lane), jnp.float32)
            qruntime = jnp.full((R, G_lane), jnp.inf, jnp.float32)
            qid = jnp.full(P, -1, jnp.int32)
        # pre-gather each pod's ancestor-closure row: [P_pad, G_lane] in HBM,
        # streamed as [UNROLL, G_lane] blocks (quota-less pods hit row 0 of
        # an all-zeros closure or carry has_quota == False, so the row is
        # never applied)
        qid_pad = jnp.pad(qid, pad_p, constant_values=-1)
        anc = jnp.pad(anc, [(0, 0), (0, G_lane - anc.shape[1])])
        anc_pod = jnp.take(anc, jnp.maximum(qid_pad, 0), axis=0)

        # inter-pod affinity: per-pod term rows become [P] f32 bitmasks
        # (exact: T <= 24 < 2^24), node state transposes to [T, N]
        T = fc.aff_dom.shape[1]
        T_eff = max(T, 1)
        pow_t = jnp.asarray(
            [float(1 << t) for t in range(T)], jnp.float32)
        if T:
            def bitmask(rows):  # [P, T] bool -> [P_pad] f32
                return jnp.pad(
                    jnp.sum(f32(rows) * pow_t[None, :], axis=1), pad_p)

            affreq_m = bitmask(fc.pod_aff_req)
            antireq_m = bitmask(fc.pod_anti_req)
            affmatch_m = bitmask(fc.pod_aff_match)
            skew_i = jnp.asarray(fc.pod_spread_skew, jnp.int32)
            skew0_m = bitmask((skew_i & 1) > 0)
            skew1_m = bitmask((skew_i & 2) > 0)
            skew2_m = bitmask((skew_i & 4) > 0)
            affexists0 = f32(fc.aff_exists)
            affdom0 = f32(fc.aff_dom).T
            affcount0 = f32(fc.aff_count).T
            anticover0 = f32(fc.anti_cover).T
        else:
            affreq_m = antireq_m = affmatch_m = jnp.zeros(P_pad, jnp.float32)
            skew0_m = skew1_m = skew2_m = affreq_m
            affexists0 = jnp.zeros(1, jnp.float32)
            affdom0 = jnp.full((1, N), -1.0, jnp.float32)
            affcount0 = jnp.zeros((1, N), jnp.float32)
            anticover0 = jnp.zeros((1, N), jnp.float32)

        # preference-less batches carry ZERO profile columns (snapshot emits
        # true empties); the kernel skips the profile loops and the input
        # slot gets one placeholder row
        S = fc.pref_scores.shape[1]
        S_eff = max(S, 1)
        prefrows0 = (f32(fc.pref_scores).T if S
                     else jnp.zeros((1, N), jnp.float32))
        prefid_pad = jnp.pad(jnp.asarray(fc.pod_pref_id, jnp.int32), pad_p,
                             constant_values=-1)
        S2 = fc.ppref_w.shape[0] if T else 0  # zero rows == no profiles
        pprefid_pad = jnp.pad(jnp.asarray(fc.pod_ppref_id, jnp.int32), pad_p,
                              constant_values=-1)
        pprefw0 = (f32(fc.ppref_w) if S2
                   else jnp.zeros((1, max(T, 1)), jnp.float32))

        # NodePorts slots as per-pod f32 bitmasks (PT <= 16 < 2^24, exact),
        # node state transposed [PT, N]; volume headroom as one [1, N] row;
        # ImageLocality rows like the preference profiles
        PT = fc.port_used.shape[1]
        PT_eff = max(PT, 1)
        if PT:
            pow_s = jnp.asarray(
                [float(1 << s) for s in range(PT)], jnp.float32)
            portwants_m = jnp.pad(jnp.sum(
                f32(fc.pod_port_wants) * pow_s[None, :], axis=1), pad_p)
            portused0 = f32(fc.port_used).T
        else:
            portwants_m = jnp.zeros(P_pad, jnp.float32)
            portused0 = jnp.zeros((1, N), jnp.float32)
        VG = fc.vol_needed.shape[1]
        if enable_volumes:
            volneeded_pad = jnp.pad(
                f32(fc.vol_needed), pad_p + [(0, 0)]).reshape(-1)
        else:
            # volume machinery compiled out: the kernel never reads the
            # ref, so a 1-float placeholder keeps high-VG volume-less
            # batches inside the SMEM budget
            volneeded_pad = jnp.zeros(1, jnp.float32)
        volfree0 = f32(fc.vol_free)[None, :]
        volgrp0 = f32(fc.node_vol_group)[None, :]
        SI = fc.img_scores.shape[1]
        SI_eff = max(SI, 1)
        imgrows0 = (f32(fc.img_scores).T if SI
                    else jnp.zeros((1, N), jnp.float32))
        imgid_pad = jnp.pad(jnp.asarray(fc.pod_img_id, jnp.int32), pad_p,
                            constant_values=-1)

        kernel = _make_kernel(weights, prod_mode, N, R, K, G_eff, T, S, S2,
                              PT, SI, VOL=enable_volumes, VG=VG,
                              BAL=resolve_balance_idx(active_axes))
        grid_inputs = (
            spad(inputs.is_prod), spad(inputs.pod_valid),
            spad(inputs.is_daemonset), spad(gang_pod_ok),
            spad(fc.needs_numa), spad(fc.needs_bind),
            spad(fc.full_pcpus), spad(fc.cores_needed),
            jnp.pad(f32(fc.pod_taint_mask), pad_p, constant_values=1.0),
            affreq_m, antireq_m, affmatch_m,
            skew0_m, skew1_m, skew2_m, affexists0,
            prefid_pad, pprefid_pad, pprefw0,
            portwants_m, volneeded_pad, imgid_pad,
            qid_pad,
            pods_t(inputs.fit_requests), pods_t(fc.requests),
            pods_t(inputs.estimated),
            f32(inputs.allocatable).T, f32(inputs.requested).T,
            f32(inputs.la_term_nonprod).T, f32(inputs.la_term_prod).T,
            row(~reject_np), row(~reject_prod),
            row(inputs.node_ok), row(inputs.la_score_valid),
            row(fc.has_topology), row(fc.bind_free), row(fc.cpus_per_core),
            jnp.asarray(fc.numa_policy, jnp.int32)[None, :],
            jnp.exp2(f32(fc.node_taint_group))[None, :],
            numa0, anc_pod, qused0, qruntime,
            affdom0, affcount0, anticover0, prefrows0,
            portused0, volfree0, volgrp0, imgrows0,
        )
        smem, full = pc.smem_spec, pc.full_spec
        pod_spec = pc.pod_block_spec(R)
        chosen, requested_t, qused_t = pl.pallas_call(
            kernel,
            grid=(P_pad // UNROLL,),
            in_specs=(
                [smem()] * 23
                + [pod_spec] * 3
                + [full((R, N))] * 4
                + [full((1, N))] * 9
                + [full((K * R, N)),
                   pl.BlockSpec((UNROLL, G_lane), lambda i: (i, 0)),
                   full((R, G_lane)), full((R, G_lane))]
                + [full((T_eff, N))] * 3
                + [full((S_eff, N))]
                + [full((PT_eff, N)), full((1, N)), full((1, N)),
                   full((SI_eff, N))]
            ),
            out_specs=[
                pc.chosen_block_spec(),
                full((R, N)),
                full((R, G_lane)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((P_pad, 1), jnp.int32),
                jax.ShapeDtypeStruct((R, N), jnp.float32),
                jax.ShapeDtypeStruct((R, G_lane), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((R, N), jnp.float32),
                pltpu.VMEM((R, N), jnp.float32),
                pltpu.VMEM((K * R, N), jnp.float32),
                pltpu.VMEM((1, N), jnp.float32),
                pltpu.VMEM((R, N), jnp.float32),
                pltpu.VMEM((R, G_lane), jnp.float32),
                pltpu.VMEM((T_eff, N), jnp.float32),
                pltpu.VMEM((T_eff, N), jnp.float32),
                pltpu.VMEM((PT_eff, N), jnp.float32),
                pltpu.VMEM((1, N), jnp.float32),
                pltpu.SMEM((T_eff,), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",),
            ),
            interpret=interpret,
        )(*grid_inputs)
        chosen = chosen[:P, 0]

        # ---- Permit barrier (XLA post-pass, once per batch)
        keep = gang_permit_mask(
            chosen, fc.gang_id, fc.gang_min_member, fc.gang_assumed,
            fc.gang_group_id, num_gangs, num_groups,
        )
        chosen = jnp.where(keep, chosen, -1)
        quota_used = qused_t[:, :G].T if G else fc.quota_used
        return chosen, requested_t.T, quota_used

    return jax.jit(step) if jit else step
