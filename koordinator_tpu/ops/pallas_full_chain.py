"""Pallas TPU kernel for the FULL plugin-chain scheduling step.

Extends ops/pallas_step.py's VMEM-resident sequential loop to the whole chain
(models/full_chain.py): Fit + LoadAware + NodeNUMAResource (cpuset capacity,
SMT alignment, topology-policy admit, zone accounting) + ElasticQuota
admission — all state carried in VMEM across the (P,) grid. The gang Permit
barrier remains an XLA post-pass (one segment reduction per batch).

Layout choices (TPU lanes are 128 wide; f32 tile (8, 128)):
  * node arrays transposed [R, N] — nodes on lanes;
  * NUMA free state as one [K*R, N] buffer; zone k is the static row slice
    [k*R:(k+1)*R] (no 3D reductions needed — K is a static python loop);
  * quota tree in [R, G] lane layout — groups on lanes — so the per-pod
    request column [R, 1] broadcasts against (used, runtime) directly, and
    the ancestor-chain walk becomes one dynamic-sublane row slice of a
    host-precomputed [G, G] ancestor-closure matrix;
  * per-pod scalars (quota id, flags) in SMEM; per-pod vectors extracted from
    [R, P] arrays by a lane one-hot reduce.

Bindings are bit-identical to the XLA step — tests/test_pallas_full_chain.py
diffs them across NUMA/quota/gang configs, including the explicit
lowest-index-max tie-break Mosaic's argmax does not guarantee.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from koordinator_tpu.models.full_chain import FullChainInputs
from koordinator_tpu.ops import loadaware as la_ops
from koordinator_tpu.ops import pallas_common as pc
from koordinator_tpu.ops.gang import gang_permit_mask
from koordinator_tpu.ops.loadaware import LoadAwareArgs
from koordinator_tpu.ops.numa import POLICY_NONE, POLICY_SINGLE_NUMA_NODE

def estimate_vmem_bytes(N: int, R: int, K: int, G: int, P: int) -> int:
    """Upper-bound VMEM footprint of one pallas_call of the full-chain
    kernel, mirroring the in/out/scratch specs below: 3 [R, P_pad] pod
    columns, 7 [R, N] node buffers, 2 [K*R, N] NUMA buffers, 10 [1, N]
    rows, quota state (3 [R, G_lane] + [max(G,8), G_lane]) and the chosen
    output, all f32. Used by models.full_chain.build_best_full_chain_step
    to fall back to the XLA step when the state would not fit on-chip."""
    P_pad = -(-P // 8) * 8
    G_eff = max(G, 1)
    G_lane = max(128, -(-G_eff // 128) * 128)
    floats = (3 * R * P_pad + 7 * R * N + 2 * K * R * N + 11 * N
              + 3 * R * G_lane + max(G_eff, 8) * G_lane + P_pad)
    return 4 * floats


def _make_kernel(weights: np.ndarray, prod_mode: bool, N: int, R: int,
                 K: int, G: int):
    wsum = float(max(weights.sum(), 1.0))
    consts = pc.weight_consts(weights)

    def kernel(
        # --- SMEM per-pod scalars
        prod_ref, valid_ref, ds_ref, gangok_ref,
        needsnuma_ref, needsbind_ref, fullpcpus_ref, cores_ref,  # f32 [P]
        taintmask_ref,                                            # f32 [P]
        qid_ref,                                                  # int32 [P]
        # --- VMEM pod columns [R, P]
        fitreq_ref, rawreq_ref, est_ref,
        # --- VMEM node state [R, N]
        alloc_ref, req0_ref, term_np_ref, term_pr_ref,
        # --- VMEM node rows [1, N]
        lafeas_np_ref, lafeas_pr_ref, node_ok_ref, score_valid_ref,
        has_topo_ref, bindfree0_ref, cpc_ref, policy_ref,
        taintpow_ref,                                  # [1, N] f32 2^group
        # --- VMEM numa [K*R, N] / quota [G, G] + [R, G]
        numafree0_ref, anc_ref, qused0_ref, qruntime_ref,
        # --- outputs
        chosen_ref,                 # (8, 1) int32 blocks over [P_pad, 1]
        requested_ref,              # [R, N] (carried)
        qused_ref,                  # [R, G] (carried)
        # --- scratch
        dnp_ref, dpr_ref,           # [R, N]
        numa_ref,                   # [K*R, N]
        bindfree_ref,               # [1, N]
    ):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            requested_ref[:] = req0_ref[:]
            dnp_ref[:] = jnp.zeros_like(dnp_ref)
            dpr_ref[:] = jnp.zeros_like(dpr_ref)
            numa_ref[:] = numafree0_ref[:]
            bindfree_ref[:] = bindfree0_ref[:]
            qused_ref[:] = qused0_ref[:]

        prod = prod_ref[i] > 0
        needs_numa = needsnuma_ref[i] > 0
        needs_bind = needsbind_ref[i] > 0
        full_pcpus = fullpcpus_ref[i] > 0
        cores = cores_ref[i]
        gid = qid_ref[i]
        has_quota = gid >= 0

        pod_mask = pc.make_pod_mask(i, fitreq_ref.shape[1])
        fit_need = pc.pod_column(fitreq_ref, pod_mask)
        raw_req = pc.pod_column(rawreq_ref, pod_mask)
        est = pc.pod_column(est_ref, pod_mask)                        # [R, 1]

        alloc = alloc_ref[:]
        requested = requested_ref[:]

        # ---- PreFilter: quota admission along the ancestor closure row
        anc_row = anc_ref[pl.dslice(jnp.maximum(gid, 0), 1), :]      # [1, G]
        qused = qused_ref[:]                                         # [R, G]
        # f32 throughout: Mosaic can't truncate narrow bool vectors (G lanes)
        viol = jnp.max(
            jnp.where((raw_req > 0) & (qused + raw_req > qruntime_ref[:]),
                      1.0, 0.0),
            axis=0, keepdims=True)                                   # [1, G]
        quota_ok = jnp.sum(anc_row * viol) <= 0.0
        admit = (gangok_ref[i] > 0) & (quota_ok | ~has_quota)

        # ---- Filter: Fit
        fit = pc.fit_ok(fit_need, requested, alloc)                  # [N]
        # ---- Filter: LoadAware thresholds
        la_feas = jnp.where(prod, lafeas_pr_ref[0, :], lafeas_np_ref[0, :]) > 0
        la_ok = la_feas | (ds_ref[i] > 0)
        # ---- Filter: cpuset capacity + SMT alignment
        cpc = jnp.maximum(cpc_ref[0, :], 1.0)
        smt_ok = (~full_pcpus) | (
            jnp.abs(jnp.remainder(cores, cpc)) < 0.5)
        # f32-valued selects throughout the filter chain: Mosaic cannot
        # truncate/select narrow bool vectors
        cpuset_ok_f = jnp.where(
            (has_topo_ref[0, :] > 0) & smt_ok & (cores <= bindfree_ref[0, :]),
            1.0, 0.0)
        cpuset_ok = jnp.where(needs_bind, cpuset_ok_f, 1.0) > 0
        # ---- Filter: NUMA topology admit (ops/numa.numa_admit_row semantics)
        total_free = jnp.zeros((R, alloc.shape[1]), jnp.float32)
        zone = jnp.full((alloc.shape[1],), K, jnp.int32)
        for k in range(K - 1, -1, -1):
            free_k = numa_ref[k * R:(k + 1) * R, :]                  # [R, N]
            total_free = total_free + free_k
            fits_k = jnp.all((raw_req <= 0) | (raw_req <= free_k), axis=0)
            zone = jnp.where(fits_k, jnp.int32(k), zone)             # lowest k
        fits_total = jnp.all((raw_req <= 0) | (raw_req <= total_free), axis=0)
        policy = policy_ref[0, :]
        any_zone_f = jnp.where(zone < K, 1.0, 0.0)
        fits_total_f = jnp.where(fits_total, 1.0, 0.0)
        numa_ok_f = jnp.where(policy == POLICY_SINGLE_NUMA_NODE,
                              any_zone_f, fits_total_f)
        numa_ok_f = jnp.where(policy == POLICY_NONE, 1.0, numa_ok_f)
        numa_ok = jnp.where(needs_numa, numa_ok_f, 1.0) > 0

        # ---- Filter: TaintToleration — bit test in exact f32 arithmetic
        # (floor/mod; Mosaic has no shift-by-vector): bit g of mask is
        # floor(mask / 2^g) mod 2
        taint_ok = jnp.remainder(
            jnp.floor(taintmask_ref[i] / taintpow_ref[0, :]), 2.0) >= 1.0
        feasible = ((node_ok_ref[0, :] > 0) & fit & la_ok & cpuset_ok
                    & numa_ok & taint_ok & admit)

        # ---- Score: LoadAware + NodeNUMAResource least-allocated
        if prod_mode:
            base = jnp.where(prod, term_pr_ref[:] + dpr_ref[:],
                             term_np_ref[:] + dnp_ref[:])
        else:
            base = term_np_ref[:] + dnp_ref[:]
        la_per_r = pc.least_requested(alloc, est + base)
        nu_per_r = pc.least_requested(alloc, requested + raw_req)
        la_score = pc.weighted_floor_score(la_per_r, consts, wsum)
        la_score = jnp.where(score_valid_ref[0, :] > 0, la_score, 0.0)
        score = la_score + pc.weighted_floor_score(nu_per_r, consts, wsum)
        score = jnp.where(feasible, score, -1.0)

        best, maxv, iota = pc.lowest_index_max(score, alloc.shape[1])
        found = (maxv >= 0.0) & (valid_ref[i] > 0)
        sel = ((iota == best) & found).astype(jnp.float32)           # [N]

        # ---- Reserve: state updates
        requested_ref[:] = requested + sel[None, :] * fit_need
        est_add = sel[None, :] * est
        dnp_ref[:] = dnp_ref[:] + est_add
        if prod_mode:
            dpr_ref[:] = dpr_ref[:] + jnp.where(prod, 1.0, 0.0) * est_add
        bindfree_ref[:] = bindfree_ref[:] - (
            sel * jnp.where(needs_bind, cores, 0.0))[None, :]
        # numa: single-zone subtract + lowest-zones-first waterfall (disjoint).
        # Only the SingleNUMANode policy pins a zone (numa_admit_row returns
        # zone = -1 otherwise); every other policy spread-fills.
        apply_numa = sel * jnp.where(needs_numa, 1.0, 0.0)           # [N]
        single_m = apply_numa * jnp.where(
            (policy == POLICY_SINGLE_NUMA_NODE) & (zone < K), 1.0, 0.0)
        spread_m = apply_numa - single_m
        remaining = raw_req * spread_m[None, :]                      # [R, N]
        for k in range(K):
            free_k = numa_ref[k * R:(k + 1) * R, :]
            zone_m = (single_m * jnp.where(zone == k, 1.0, 0.0))[None, :]
            free_k = free_k - raw_req * zone_m
            take = jnp.minimum(free_k, remaining)
            numa_ref[k * R:(k + 1) * R, :] = free_k - take
            remaining = remaining - take
        # quota: add along the ancestor closure
        q_apply = jnp.where(found & has_quota, 1.0, 0.0)
        qused_ref[:] = qused + raw_req * anc_row * q_apply

        pc.store_chosen(chosen_ref, i, best, found)

    return kernel


def build_pallas_full_chain_step(args: LoadAwareArgs, num_gangs: int,
                                 num_groups: int, interpret: bool = False,
                                 jit: bool = True, active_axes=None):
    """FullChainInputs -> (chosen[P], requested[N, R], quota_used[G, R]);
    same contract as models.full_chain.build_full_chain_step."""
    full_weights = args.weight_vector()
    if active_axes is not None:
        full_weights = full_weights[list(active_axes)]
    weights = np.asarray(full_weights, np.float32)
    prod_mode = args.score_according_prod_usage

    def step(fc: FullChainInputs) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        inputs = fc.base
        P, R = inputs.fit_requests.shape
        N = inputs.allocatable.shape[0]
        K = fc.numa_free.shape[1]
        G = fc.quota_used.shape[0]
        G_eff = max(G, 1)
        reject_np, reject_prod = la_ops.loadaware_node_reject(
            inputs.allocatable,
            inputs.la_filter_usage,
            inputs.la_has_filter_usage,
            inputs.la_filter_thresholds,
            inputs.la_prod_thresholds,
            inputs.la_prod_pod_usage,
            inputs.la_filter_skip,
        )
        gang_pod_ok = jnp.where(
            fc.gang_id >= 0, fc.gang_valid[jnp.maximum(fc.gang_id, 0)], True
        )
        # ancestor closure computed traceably (inputs may be tracers under jit):
        # closure[g, a] = 1 iff a appears in g's chain (-1 padding never matches)
        if G:
            anc = jnp.any(
                fc.quota_ancestors[:, :, None]
                == jnp.arange(G, dtype=fc.quota_ancestors.dtype)[None, None, :],
                axis=1,
            ).astype(jnp.float32)
        else:
            anc = jnp.zeros((1, 1), jnp.float32)

        f32, row = pc.f32, pc.row
        P_pad, pad_p = pc.pad_pods(P)
        spad = lambda x: jnp.pad(f32(x), pad_p)  # noqa: E731

        def pods_t(x):  # [P, R] -> [R, P_pad]
            return jnp.pad(f32(x), pad_p + [(0, 0)]).T

        # numa [N, K, R] -> [K*R, N]
        numa0 = jnp.transpose(f32(fc.numa_free), (1, 2, 0)).reshape(K * R, N)
        # quota lane axis padded to >= 128: Mosaic cannot truncate the narrow
        # bool vectors that comparisons on a (R, G<128) block would produce.
        # Padding runtime with +inf keeps phantom groups from ever violating.
        G_lane = max(128, -(-G_eff // 128) * 128)
        if G:
            qused0 = jnp.pad(f32(fc.quota_used).T, [(0, 0), (0, G_lane - G)])
            qruntime = jnp.pad(f32(fc.quota_runtime).T,
                               [(0, 0), (0, G_lane - G)],
                               constant_values=jnp.inf)
            qid = jnp.asarray(fc.quota_id, jnp.int32)
        else:
            qused0 = jnp.zeros((R, G_lane), jnp.float32)
            qruntime = jnp.full((R, G_lane), jnp.inf, jnp.float32)
            qid = jnp.full(P, -1, jnp.int32)
        anc = jnp.pad(anc, [(0, max(8 - G_eff, 0)), (0, G_lane - anc.shape[1])])

        kernel = _make_kernel(weights, prod_mode, N, R, K, G_eff)
        grid_inputs = (
            spad(inputs.is_prod), spad(inputs.pod_valid),
            spad(inputs.is_daemonset), spad(gang_pod_ok),
            spad(fc.needs_numa), spad(fc.needs_bind),
            spad(fc.full_pcpus), spad(fc.cores_needed),
            jnp.pad(f32(fc.pod_taint_mask), pad_p, constant_values=1.0),
            jnp.pad(qid, pad_p, constant_values=-1),
            pods_t(inputs.fit_requests), pods_t(fc.requests),
            pods_t(inputs.estimated),
            f32(inputs.allocatable).T, f32(inputs.requested).T,
            f32(inputs.la_term_nonprod).T, f32(inputs.la_term_prod).T,
            row(~reject_np), row(~reject_prod),
            row(inputs.node_ok), row(inputs.la_score_valid),
            row(fc.has_topology), row(fc.bind_free), row(fc.cpus_per_core),
            jnp.asarray(fc.numa_policy, jnp.int32)[None, :],
            jnp.exp2(f32(fc.node_taint_group))[None, :],
            numa0, jnp.asarray(anc, jnp.float32), qused0, qruntime,
        )
        smem, full = pc.smem_spec, pc.full_spec
        chosen, requested_t, qused_t = pl.pallas_call(
            kernel,
            grid=(P_pad,),
            in_specs=(
                [smem()] * 10
                + [full((R, P_pad))] * 3
                + [full((R, N))] * 4
                + [full((1, N))] * 9
                + [full((K * R, N)), full((max(G_eff, 8), G_lane)),
                   full((R, G_lane)), full((R, G_lane))]
            ),
            out_specs=[
                pc.chosen_spec(),
                full((R, N)),
                full((R, G_lane)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((P_pad, 1), jnp.int32),
                jax.ShapeDtypeStruct((R, N), jnp.float32),
                jax.ShapeDtypeStruct((R, G_lane), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((R, N), jnp.float32),
                pltpu.VMEM((R, N), jnp.float32),
                pltpu.VMEM((K * R, N), jnp.float32),
                pltpu.VMEM((1, N), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",),
            ),
            interpret=interpret,
        )(*grid_inputs)
        chosen = chosen[:P, 0]

        # ---- Permit barrier (XLA post-pass, once per batch)
        keep = gang_permit_mask(
            chosen, fc.gang_id, fc.gang_min_member, fc.gang_assumed,
            fc.gang_group_id, num_gangs, num_groups,
        )
        chosen = jnp.where(keep, chosen, -1)
        quota_used = qused_t[:, :G].T if G else fc.quota_used
        return chosen, requested_t.T, quota_used

    return jax.jit(step) if jit else step
