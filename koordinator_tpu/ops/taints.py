"""Node-admission (taint/toleration + nodeSelector) factorization, batched.

The kube-scheduler's TaintToleration and NodeAffinity plugins (vendored
defaults in the reference's scheduler binary) reject nodes whose NoSchedule
taints the pod does not tolerate or whose labels don't satisfy the pod's
nodeSelector. Per-(pod, node) set checks don't batch, so the snapshot
factorizes them: nodes with the same ADMISSION SIGNATURE — their taint set
plus their labels projected onto the selector keys the pending batch uses —
share a small group id (real clusters have a handful of signatures), each
node carries its group id [N], and each pod carries a bitmask of admitted
groups [P] (groups whose taints it tolerates AND whose labels satisfy its
nodeSelector). The kernel check collapses to one elementwise bit test:
``(pod_mask >> node_group) & 1``.

Masks are stored as float32 (exact for < 2^24) so the Pallas kernel can do
the bit test with floor/mod arithmetic — Mosaic lowers those everywhere,
unlike shift-by-vector. Group ``MAX_TAINT_GROUPS - 1`` is the overflow
bucket for clusters with more distinct signatures than bits — no pod ever
admits it (conservative: the scheduler refuses placements it cannot prove,
never the reverse)."""

from __future__ import annotations

import logging
from typing import List, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

MAX_TAINT_GROUPS = 24  # bits must stay exact in float32 (< 2^24)


def tolerates_taints(tolerations: Sequence[Tuple[str, str]],
                     taints: Sequence[Tuple[str, str]]) -> bool:
    """Exact (key, value) toleration, or (key, "") as a key-wildcard —
    the same rule the descheduler's NodeTaints plugin applies."""
    held = set(tolerations)
    return all(
        (key, value) in held or (key, "") in held for key, value in taints
    )


def selector_pairs_of(pods, extra_pairs_by_key=None) -> frozenset:
    """The distinct (key, value) nodeSelector PAIRS the pending batch uses.
    Signatures are built from pair-match booleans, not raw label values, so
    a high-cardinality key (kubernetes.io/hostname) contributes one bit per
    PIN, not one signature per node: 5k hostnames with one pinned pod split
    the cluster into 2 groups (the pinned node, everyone else), where a
    value-projection signature would fragment all 5k nodes.

    extra_pairs_by_key: per-pod-key additional required pairs (e.g. the
    VolumeZone filter's PV topology labels, scheduler/snapshot.py)."""
    pairs = set()
    for pod in pods:
        pairs.update(pod.spec.node_selector.items())
        pairs.update(pod.spec.affinity_required_node_labels.items())
        if extra_pairs_by_key:
            pairs.update(extra_pairs_by_key.get(pod.meta.key, ()))
    return frozenset(pairs)


def required_node_pairs(pod) -> frozenset:
    """All (key, value) node-label requirements of a pod: nodeSelector AND
    requiredDuringScheduling node affinity matchLabels — kube-scheduler ANDs
    the two (NodeAffinity plugin)."""
    return frozenset(pod.spec.node_selector.items()) | frozenset(
        pod.spec.affinity_required_node_labels.items())


_UNKNOWN = object()  # bucket marker: label matches not encoded for this group


def group_node_admission(
    nodes, selector_pairs: frozenset = frozenset()
) -> Tuple[np.ndarray, List[Tuple[frozenset, object]]]:
    """(group_id [len(nodes)] int32, group signatures). A signature is
    (taint set, frozenset of batch selector pairs the node's labels match).
    When the bit budget runs out, a node degrades to its per-taint-set
    LABEL-UNKNOWN bucket — still exact for selector-less pods (their
    admission never depends on labels) and conservative (never admitted)
    for selector pods. Only if even those buckets exhaust the budget does a
    node land in the final overflow group, which admits nobody — the same
    stance the taint-only grouping always had."""
    overflow = MAX_TAINT_GROUPS - 1
    out = np.zeros(len(nodes), np.int32)
    pairs = sorted(selector_pairs)

    # pass 1: per-node exact signature + frequency
    node_sigs: List[Tuple[frozenset, frozenset]] = []
    counts: dict = {}
    first_seen: dict = {}
    taint_sets: List[frozenset] = []
    for i, node in enumerate(nodes):
        labels = node.meta.labels
        taints = frozenset(node.taints)
        matched = frozenset((k, v) for k, v in pairs if labels.get(k) == v)
        sig = (taints, matched)
        node_sigs.append(sig)
        counts[sig] = counts.get(sig, 0) + 1
        if sig not in first_seen:
            first_seen[sig] = i
        if taints not in taint_sets:
            taint_sets.append(taints)

    # pass 2: exact signatures get the budget minus a reserved slot per
    # taint set (so a label-unknown bucket can ALWAYS be interned when an
    # exact signature overflows — without the reservation the unknown
    # buckets themselves would overflow); most-common signatures first
    sigs: List[Tuple[frozenset, object]] = []
    ids: dict = {}
    exact_budget = max(overflow - min(len(taint_sets), overflow), 0)
    for sig in sorted(counts, key=lambda s: (-counts[s], first_seen[s])):
        if len(ids) >= exact_budget:
            break
        ids[sig] = len(sigs)
        sigs.append(sig)

    degraded: List[str] = []
    for i, node in enumerate(nodes):
        sig = node_sigs[i]
        gid = ids.get(sig)
        if gid is None:  # degrade: label-unknown bucket for this taint set
            key = (sig[0], _UNKNOWN)
            gid = ids.get(key)
            if gid is not None or len(sigs) < overflow:
                if gid is None:
                    gid = ids[key] = len(sigs)
                    sigs.append(key)
                degraded.append(node.meta.name)
            if gid is None:
                gid = overflow
                logger.warning(
                    "admission-signature bit budget exceeded: node %s "
                    "(taints %s) falls into the overflow group and NO pod "
                    "will schedule there (max %d distinct signatures)",
                    node.meta.name, sorted(sig[0]), overflow,
                )
        out[i] = gid
    if degraded:
        # loud by design: selector-carrying pods can NEVER schedule onto a
        # label-unknown bucket, and host-side dry-runs (preemption) must
        # consult this grouping or they will evict victims in vain
        logger.warning(
            "admission-signature budget exceeded: %d nodes degraded to "
            "their label-unknown bucket (selector-carrying pods will not "
            "schedule there this round): %s%s",
            len(degraded), ", ".join(degraded[:5]),
            "..." if len(degraded) > 5 else "",
        )
    return out, sigs


def degraded_node_count(group_ids, groups) -> int:
    """Nodes whose admission signature was NOT exactly encoded: in a
    label-unknown bucket (selector pods can't schedule there) or the
    admit-nobody overflow group. Feeds the scheduler's degradation gauge."""
    return sum(
        1 for g in group_ids
        if g >= len(groups) or groups[g][1] is _UNKNOWN
    )


def admission_mask(pod, groups: List[Tuple[frozenset, object]],
                   extra_pairs: frozenset = frozenset(),
                   any_of_sets: Sequence = ()) -> float:
    """Bitmask (as an exact float32 integer) of the node groups this pod may
    land on: taints tolerated AND every nodeSelector pair in the group's
    matched set. Label-unknown buckets admit only unconstrained pods; the
    overflow group's bit is never set. extra_pairs joins the pod's own
    required set (VolumeZone).

    any_of_sets carries OR-of-AND requirements (the VolumeBinding analog,
    scheduler/volumebinding.py): each element is a collection of
    ALTERNATIVES for one unbound claim — the group must fully match at
    least one alternative's pair set per element (some candidate PV's
    topology, or some provisioner-allowed topology term). An element with
    no satisfiable alternative zeroes the mask: the claim fits nowhere."""
    mask = 0
    tolerations = pod.spec.tolerations
    selector = required_node_pairs(pod) | extra_pairs
    for gid, (taints, matched) in enumerate(groups):
        if taints and not tolerates_taints(tolerations, taints):
            continue
        if matched is _UNKNOWN:
            if selector or any_of_sets:
                continue
        else:
            if not selector <= matched:
                continue
            if any(not any(alt <= matched for alt in alts)
                   for alts in any_of_sets):
                continue
        mask |= 1 << gid
    return float(mask)
