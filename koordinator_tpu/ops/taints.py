"""Taint/toleration admission, batched.

The kube-scheduler's TaintToleration plugin (a vendored default in the
reference's scheduler binary) rejects nodes whose NoSchedule taints the pod
does not tolerate. Per-(pod, node) set checks don't batch, so the snapshot
factorizes them: distinct node taint-SETS get small group ids (real clusters
have a handful), each node carries its group id [N], and each pod carries a
bitmask of tolerated groups [P]. The kernel check collapses to one
elementwise bit test: ``(pod_mask >> node_group) & 1``.

Masks are stored as float32 (exact for < 2^24) so the Pallas kernel can do
the bit test with floor/mod arithmetic — Mosaic lowers those everywhere,
unlike shift-by-vector. Group 0 is the empty taint set (always tolerated);
group ``MAX_TAINT_GROUPS - 1`` is the overflow bucket for clusters with more
distinct taint sets than bits — no pod ever tolerates it (conservative: the
scheduler refuses placements it cannot prove, never the reverse).
"""

from __future__ import annotations

import logging
from typing import List, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

MAX_TAINT_GROUPS = 24  # bits must stay exact in float32 (< 2^24)


def tolerates_taints(tolerations: Sequence[Tuple[str, str]],
                     taints: Sequence[Tuple[str, str]]) -> bool:
    """Exact (key, value) toleration, or (key, "") as a key-wildcard —
    the same rule the descheduler's NodeTaints plugin applies."""
    held = set(tolerations)
    return all(
        (key, value) in held or (key, "") in held for key, value in taints
    )


def group_node_taints(nodes) -> Tuple[np.ndarray, List[frozenset]]:
    """(group_id [len(nodes)] int32, group taint-sets). Group 0 is the empty
    set; sets beyond the bit budget collapse into the overflow group."""
    sets: List[frozenset] = [frozenset()]
    ids = {frozenset(): 0}
    overflow = MAX_TAINT_GROUPS - 1
    out = np.zeros(len(nodes), np.int32)
    for i, node in enumerate(nodes):
        key = frozenset(node.taints)
        gid = ids.get(key)
        if gid is None:
            if len(sets) < overflow:
                gid = len(sets)
                ids[key] = gid
                sets.append(key)
            else:
                gid = overflow
                logger.warning(
                    "taint-set bit budget exceeded: node %s's taints %s "
                    "fall into the overflow group and NO pod will schedule "
                    "there (max %d distinct sets)",
                    node.meta.name, sorted(key), overflow,
                )
        out[i] = gid
    return out, sets


def toleration_mask(pod, group_sets: List[frozenset]) -> float:
    """Bitmask (as an exact float32 integer) of the groups this pod's
    tolerations cover. The overflow group's bit is never set."""
    mask = 0
    tolerations = pod.spec.tolerations
    for gid, taints in enumerate(group_sets):
        if not taints or tolerates_taints(tolerations, taints):
            mask |= 1 << gid
    return float(mask)
