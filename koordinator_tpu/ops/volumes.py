"""PVC/volume claim state carried across fused waves.

The volume-group factorization (scheduler/snapshot.py) freezes, per
dispatch, which already-attached claims a node exempts from a pod's new-
attachment count (upstream NodeVolumeLimits' already-attached exemption).
Between SERIAL cycles the factorization is rebuilt from the updated
attached sets, so a claim-carrying pod binding in cycle w changes cycle
w+1's ``vol_needed``/``vol_free`` view — the reason the fused-wave path
historically demoted to K=1 whenever any pending pod carried a PVC
(the dominant demotion of the soak profile: claim-pods 478/1000 cycles,
CHURN_r04/r05).

This module removes that demotion by carrying the claim state on device:

  * ``analyze_pending_claims`` classifies the batch. The common case —
    every pending claim unique to its pod and attached nowhere (the sim's
    ``claim-<uid>`` tokens) — needs NO carried state at all: the kernel's
    existing per-commit ``vol_free`` decrement already reproduces the
    next-cycle host rebuild exactly (unique claims make the attached-SET
    rebuild equal the running count, and the group factorization stays
    VG==1 because bound claims leave the pending universe).
  * claims that CAN interact — shared by several pending pods, or already
    attached on some node (so the exemption can grow mid-dispatch) — are
    the HOT claims. ``build_claim_pack`` factorizes them into per-pod
    membership columns and per-node coverage rows; the wave kernel
    carries ``claim_new`` ([N, NC]: hot claims newly attached per node
    this dispatch) + ``vol_new`` ([N]: non-hot new attachments) in
    WAVE_STATE_FIELDS and, per wave, expands ``vol_needed`` to the
    per-(pod, node) effective count — exactly what the next serial
    cycle's regrouped ``[P, VG']`` gather would produce. ``vol_free`` is
    rebuilt at every wave boundary from the dispatch-start value minus
    the attached-SET growth (all integer-valued f32, so the rebuild is
    exact regardless of association — the packed-units discipline).
  * genuinely non-expressible interference — unbound WaitForFirstConsumer
    claims whose CLASSIFICATION (admission bitmask) another pending pod's
    bind can rewrite through the PV/PVC objects, and factorization-budget
    overflows whose degraded nodes regroup between cycles — demotes
    narrowly (reason ``claim-entangled``), the only claim residue left.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

# hot-claim column budget: one [N] f32 column per hot claim rides the
# wave carry; past the budget the driver demotes (claim-entangled)
MAX_WAVE_CLAIMS = 128


def store_volume_aware(store) -> bool:
    """THE volume-aware predicate: any PVC/PV/StorageClass object in the
    store turns real volume binding/classification on; a store with none
    of the three is the opaque-token mode where ``pvc_names`` are CSI
    attachment-count tokens. One shared home (the snapshot classification
    gate, VolumeBinding's Reserve, and the fused claim analysis must
    agree — a desynchronized copy re-creates the pre-PR-14 veto that
    made opaque claim pods immortal queue residents)."""
    from koordinator_tpu.client.store import (
        KIND_PV,
        KIND_PVC,
        KIND_STORAGECLASS,
    )

    return bool(store.list(KIND_PVC) or store.list(KIND_PV)
                or store.list(KIND_STORAGECLASS))


def claim_keys_of(pod) -> frozenset:
    """The pod's distinct claim keys, namespaced the way the snapshot's
    attached sets store them."""
    return frozenset(
        f"{pod.meta.namespace}/{c}" for c in pod.spec.pvc_names)


def attached_claim_sets(store) -> Dict[str, Set[str]]:
    """node name -> attached claim keys, from assigned pods (the no-cache
    fallback mirroring scheduler/snapshot.py's scan)."""
    from koordinator_tpu.client.store import KIND_POD

    attached: Dict[str, Set[str]] = {}
    for pod in store.list(KIND_POD):
        if pod.is_assigned and not pod.is_terminated and pod.spec.pvc_names:
            attached.setdefault(pod.spec.node_name, set()).update(
                claim_keys_of(pod))
    return attached


@dataclass
class ClaimAnalysis:
    """What the pending batch's claims require of the fused path."""

    has_claims: bool = False
    # None = fully carriable; else the classification-drift channel that
    # forces the serial path (surfaced in the demotion log)
    entangled: Optional[str] = None
    # hot claims (shared between pending pods, or attached somewhere):
    # these need carried columns; everything else is exemption-free
    hot: frozenset = frozenset()
    # per-pod claim sets, keyed by pod key (reused by build_claim_pack)
    claims_by_key: Optional[Dict[str, frozenset]] = None
    # the attached-claims view the analysis ran against, stashed so the
    # dispatch's side-input encode never re-materializes it
    attached: Optional[Dict[str, Set[str]]] = None


def analyze_pending_claims(pending, attached: Dict[str, Set[str]],
                           volume_aware: bool = False,
                           unbound_claim_pods: int = 0,
                           max_vol_groups: Optional[int] = None,
                           ) -> ClaimAnalysis:
    """Classify the pending batch's claim structure.

    ``attached`` is the node -> attached-claim-keys view the snapshot's
    volume-group factorization consumes. ``volume_aware`` + the count of
    pending pods carrying UNBOUND (or missing) claims gate the
    classification-drift demotion: an unbound WaitForFirstConsumer
    claim's admission alternatives shrink when another pod's bind
    consumes a candidate PV or binds a shared claim — state the kernel
    cannot see — but a SINGLE such pod is safe (its own bind removes it
    from the batch, and nothing else rewrites PV/PVC objects
    mid-dispatch)."""
    from koordinator_tpu.scheduler.snapshot import MAX_VOL_GROUPS

    budget = MAX_VOL_GROUPS if max_vol_groups is None else max_vol_groups
    claims_by_key: Dict[str, frozenset] = {}
    counts: Dict[str, int] = {}
    for pod in pending:
        if not pod.spec.pvc_names:
            continue
        cs = claim_keys_of(pod)
        claims_by_key[pod.meta.key] = cs
        for c in cs:
            counts[c] = counts.get(c, 0) + 1
    if not claims_by_key:
        return ClaimAnalysis()
    if volume_aware and unbound_claim_pods >= 2:
        return ClaimAnalysis(
            has_claims=True,
            entangled="unbound claims on >= 2 pending pods",
            claims_by_key=claims_by_key, attached=attached)
    universe = frozenset(counts)
    shared = {c for c, n in counts.items() if n >= 2}
    attached_hot: Set[str] = set()
    intersections: Set[frozenset] = set()
    for node_set in attached.values():
        s = universe & node_set
        if s:
            attached_hot |= s
            intersections.add(frozenset(s))
    if len(intersections) + 1 > budget:
        # the snapshot's group factorization would overflow its budget:
        # degraded nodes lose the exemption THIS cycle but may regain it
        # next cycle as the universe shrinks — a regrouping the frozen
        # base cannot express
        return ClaimAnalysis(
            has_claims=True,
            entangled="volume-group budget overflow",
            claims_by_key=claims_by_key, attached=attached)
    hot = frozenset(shared | attached_hot)
    if len(hot) > MAX_WAVE_CLAIMS:
        return ClaimAnalysis(
            has_claims=True,
            entangled="hot-claim column budget overflow",
            claims_by_key=claims_by_key, attached=attached)
    return ClaimAnalysis(has_claims=True, hot=hot,
                         claims_by_key=claims_by_key, attached=attached)


@dataclass
class ClaimPack:
    """Packed hot-claim factorization for one dispatch (host numpy; the
    driver uploads these as fused-wave side inputs)."""

    n_claims: int
    pod_claim: np.ndarray   # [P, NC] f32 0/1 — pod references hot claim c
    pod_nonhot: np.ndarray  # [P] f32 — the pod's NON-hot distinct-claim count
    covered0: np.ndarray    # [N, NC] f32 0/1 — claim attached on node at start


def build_claim_pack(analysis: ClaimAnalysis, pod_keys: Sequence[str],
                     node_names: Sequence[str],
                     attached: Dict[str, Set[str]],
                     p_pad: int, n_pad: int) -> Optional[ClaimPack]:
    """Build the hot-claim side arrays in PACKED row order, or None when
    the batch carries no hot claims (no machinery needed — see module
    doc)."""
    if analysis.entangled is not None or not analysis.hot:
        return None
    hot: List[str] = sorted(analysis.hot)
    cid = {c: j for j, c in enumerate(hot)}
    nc = len(hot)
    pod_claim = np.zeros((p_pad, nc), np.float32)
    pod_nonhot = np.zeros(p_pad, np.float32)
    claims_by_key = analysis.claims_by_key or {}
    for i, key in enumerate(pod_keys):
        cs = claims_by_key.get(key)
        if not cs:
            continue
        nh = 0
        for c in cs:
            j = cid.get(c)
            if j is None:
                nh += 1
            else:
                pod_claim[i, j] = 1.0
        pod_nonhot[i] = float(nh)
    covered0 = np.zeros((n_pad, nc), np.float32)
    for i, name in enumerate(node_names):
        node_set = attached.get(name)
        if not node_set:
            continue
        for c in node_set:
            j = cid.get(c)
            if j is not None:
                covered0[i, j] = 1.0
    return ClaimPack(n_claims=nc, pod_claim=pod_claim,
                     pod_nonhot=pod_nonhot, covered0=covered0)


# ---------------------------------------------------------------------------
# device kernels (pure jnp; traced inside the fused wave body)
# ---------------------------------------------------------------------------


def effective_vol_needed(vol_needed, node_vol_group, pod_claim, claim_new):
    """[P, N] per-(pod, node) NEW-attachment counts at wave-start state:
    the frozen [P, VG] group gather minus the pod's hot claims the node
    newly attached this dispatch (``claim_new`` excludes dispatch-start
    coverage by construction, so nothing is subtracted twice). All
    operands are small integer-valued f32 — the HIGHEST-precision matmul
    keeps the products exact, so the result equals the next serial
    cycle's regrouped gather bit-for-bit."""
    import jax
    import jax.numpy as jnp

    base = jnp.take(vol_needed, node_vol_group, axis=1)         # [P, N]
    overlap = jnp.matmul(pod_claim, claim_new.T,
                         precision=jax.lax.Precision.HIGHEST)   # [P, N]
    return base - overlap


def advance_claim_state(chosen, committed, pod_claim, pod_nonhot, covered0,
                        claim_new, vol_new, vol_free0):
    """Wave-boundary claim-state update from this wave's committed
    bindings (``committed`` [P] bool, ``chosen`` [P] int32 node per pod).

    Returns (claim_new', vol_new', vol_free') where vol_free' is REBUILT
    set-wise — dispatch-start free minus the union growth — exactly what
    the next serial cycle's ``limit - len(attached)`` recompute yields
    (two committed pods sharing a hot claim on one node decremented it
    twice in-wave, the serial in-cycle behavior; the boundary rebuild
    collapses the double-count the way the host's set rebuild does)."""
    import jax
    import jax.numpy as jnp

    n = covered0.shape[0]
    hi = jax.lax.Precision.HIGHEST
    sel = (jax.nn.one_hot(jnp.maximum(chosen, 0), n, dtype=jnp.float32)
           * committed.astype(jnp.float32)[:, None])            # [P, N]
    gain = jnp.matmul(sel.T, pod_claim, precision=hi)           # [N, NC]
    fresh = ((gain > 0.5) & (covered0 <= 0.5)
             & (claim_new <= 0.5)).astype(jnp.float32)
    claim_new2 = claim_new + fresh
    vol_new2 = vol_new + jnp.matmul(
        sel.T, pod_nonhot[:, None], precision=hi)[:, 0]         # [N]
    vol_free2 = vol_free0 - vol_new2 - jnp.sum(claim_new2, axis=1)
    return claim_new2, vol_new2, vol_free2


def host_effective_vol_needed(vol_needed, node_vol_group, pod_claim,
                              claim_new) -> np.ndarray:
    """Numpy twin of ``effective_vol_needed`` for the host wave-state
    mirror (scheduler/cycle._WaveStateMirror): integer-exact, so the
    diagnose oracle sees the same per-(pod, node) counts the kernel
    filtered with."""
    base = np.take(np.asarray(vol_needed, np.float32),
                   np.asarray(node_vol_group), axis=1)
    overlap = np.asarray(pod_claim, np.float32) @ np.asarray(
        claim_new, np.float32).T
    return base - overlap
