"""NodeNUMAResource: NUMA-aware fit + topology-policy admit + scoring, batched.

Reference: `pkg/scheduler/plugins/nodenumaresource/` —
  * Filter (plugin.go:275-338): cpuset-capable pods need a valid CPUTopology,
    SMT-aligned requests (FullPCPUs), enough bindable cpus; NUMA topology policy
    admit via the topology manager (frameworkext/topologymanager/manager.go:58).
  * Hint generation (resource_manager.go:418-532): which NUMA-node sets fit the
    request; the merged affinity prefers the narrowest fitting mask.
  * Scoring (scoring.go, least_allocated.go): least/most-allocated over the
    node-level (and NUMA-level) requested vs allocatable.

Batched formulation: with K NUMA zones per node (padded to MAX_NUMA), the fit
check per policy reduces to
  single-numa-node : exists k with req <= free[k] (choose lowest such k)
  restricted       : total fit (a minimal fitting mask always exists then; the
                     concrete mask is chosen host-side at Reserve)
  best-effort/none : total fit
so no 2^K mask enumeration is needed on device — masks only materialize host-side
when the accumulator allocates concrete cpus (scheduler/cpu_topology.py).

In-batch state for the serial-parity loop: numa_free[N, K, R] (zone free),
bind_free[N] (bindable cpu count). Assignment updates subtract from the chosen
zone (single-numa) or lowest-zones-first (spread fill; the reference splits per
its allocator's choice — same deterministic rule in kernel and parity emulator).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

MAX_NUMA = 8

POLICY_NONE = 0
POLICY_SINGLE_NUMA_NODE = 1
POLICY_RESTRICTED = 2
POLICY_BEST_EFFORT = 3

POLICY_BY_NAME = {
    "": POLICY_NONE,
    "None": POLICY_NONE,
    "none": POLICY_NONE,
    "SingleNUMANode": POLICY_SINGLE_NUMA_NODE,
    "single-numa-node": POLICY_SINGLE_NUMA_NODE,
    "Restricted": POLICY_RESTRICTED,
    "restricted": POLICY_RESTRICTED,
    "BestEffort": POLICY_BEST_EFFORT,
    "best-effort": POLICY_BEST_EFFORT,
}


def numa_admit_row(
    request: jnp.ndarray,      # [R] pod request (packed units)
    needs_numa: jnp.ndarray,   # scalar bool: pod subject to NUMA admission
    numa_free: jnp.ndarray,    # [N, K, R]
    policy: jnp.ndarray,       # [N] int32
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(ok[N], zone[N]): admit + chosen zone (-1 when not single-numa).

    Zero-request axes never constrain (k8s semantics).
    """
    req = request[None, None, :]
    fits_zone = jnp.all((req <= 0) | (req <= numa_free), axis=-1)      # [N, K]
    total_free = jnp.sum(numa_free, axis=1)                            # [N, R]
    fits_total = jnp.all((request[None, :] <= 0) | (request[None, :] <= total_free), axis=-1)
    any_zone = jnp.any(fits_zone, axis=-1)
    first_zone = jnp.argmax(fits_zone, axis=-1).astype(jnp.int32)      # lowest k
    single = policy == POLICY_SINGLE_NUMA_NODE
    ok = jnp.where(single, any_zone, fits_total)
    ok = jnp.where(policy == POLICY_NONE, True, ok)
    ok = jnp.where(needs_numa, ok, True)
    zone = jnp.where(single & any_zone & needs_numa, first_zone, -1)
    return ok, zone


def numa_zone_for_node(
    request: jnp.ndarray,      # [R] pod request (packed units)
    needs_numa: jnp.ndarray,   # scalar bool
    numa_free_n: jnp.ndarray,  # [K, R] free of ONE node
    policy_n: jnp.ndarray,     # scalar int32
) -> jnp.ndarray:
    """Scalar zone choice for a single node: the single-node restriction of
    ``numa_admit_row``'s zone output (-1 when not single-numa). Used by the
    fused wave kernel's kept-only replay pass, where the zone must be
    re-picked under the replay state — the same first-fitting-zone rule the
    host plugin's width-1 hint uses at Reserve."""
    fits_zone = jnp.all(
        (request[None, :] <= 0) | (request[None, :] <= numa_free_n), axis=-1)
    any_zone = jnp.any(fits_zone)
    first_zone = jnp.argmax(fits_zone).astype(jnp.int32)
    single = policy_n == POLICY_SINGLE_NUMA_NODE
    return jnp.where(single & any_zone & needs_numa, first_zone,
                     jnp.int32(-1))


def cpuset_filter_row(
    needs_bind: jnp.ndarray,    # scalar bool: pod requires cpuset binding
    cores_needed: jnp.ndarray,  # scalar float: whole cpus requested
    full_pcpus: jnp.ndarray,    # scalar bool: FullPCPUs policy resolved for pod
    has_topology: jnp.ndarray,  # [N]
    bind_free: jnp.ndarray,     # [N] bindable cpus available
    cpus_per_core: jnp.ndarray,  # [N]
) -> jnp.ndarray:
    """[N]: cpuset feasibility (plugin.go:303-338 — ErrInvalidCPUTopology,
    ErrSMTAlignmentError, capacity)."""
    smt_ok = ~full_pcpus | (
        jnp.abs(jnp.remainder(cores_needed, jnp.maximum(cpus_per_core, 1.0))) < 0.5
    )
    ok = has_topology & smt_ok & (cores_needed <= bind_free)
    return jnp.where(needs_bind, ok, True)


def numa_spread_fill(
    numa_free_n: jnp.ndarray,  # [K, R] free of the chosen node
    request: jnp.ndarray,      # [R]
    zone: jnp.ndarray,         # scalar int32 (-1 = spread fill)
) -> jnp.ndarray:
    """New [K, R] after subtracting the request: all from `zone` when single-numa,
    else lowest-zones-first waterfall."""
    K = numa_free_n.shape[0]

    def single_case():
        onehot = (jnp.arange(K, dtype=jnp.int32) == zone).astype(
            numa_free_n.dtype)
        return numa_free_n - onehot[:, None] * request[None, :]

    def spread_case():
        # waterfall: zone k absorbs min(free_k, remaining)
        def body(carry, free_k):
            remaining = carry
            take = jnp.minimum(free_k, remaining)
            return remaining - take, free_k - take

        import jax

        _, new_free = jax.lax.scan(body, request, numa_free_n)
        return new_free

    import jax

    return jax.lax.cond(zone >= 0, single_case, spread_case)


def numa_score_row(
    request: jnp.ndarray,       # [R]
    node_requested: jnp.ndarray,  # [N, R]
    allocatable: jnp.ndarray,   # [N, R]
    weights: jnp.ndarray,       # [R]
    weight_idx: Tuple[int, ...],
    most_allocated: bool = False,
) -> jnp.ndarray:
    """[N] NodeNUMAResource score: least-allocated (default) or most-allocated
    over requested+request vs allocatable (scoring.go with the v1beta2 default
    strategy cpu=1, memory=1)."""
    from koordinator_tpu.ops.common import least_requested_score, most_requested_score

    scorer = most_requested_score if most_allocated else least_requested_score
    acc = jnp.zeros(allocatable.shape[0], jnp.float32)
    wsum = jnp.sum(weights)
    for r in weight_idx:
        used = node_requested[:, r] + request[r]
        acc = acc + weights[r] * scorer(used, allocatable[:, r])
    return jnp.floor(acc / jnp.maximum(wsum, 1.0))
