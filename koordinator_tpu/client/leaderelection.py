"""Leader election: active/standby control-plane replicas.

Analog of client-go `leaderelection` as used by every koordinator binary
(`cmd/koord-scheduler/app/server.go:227-256`, koord-manager, descheduler):
replicas race to hold a Lease object in the store; only the holder runs its
control loops. The lease is renewed every tick; when the holder stops
renewing (crash, partition), a standby acquires it after lease_duration and
takes over. Optimistic concurrency (the store's resourceVersion CAS) decides
races exactly the way the apiserver does.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from koordinator_tpu.api.objects import ObjectMeta
from koordinator_tpu.client.store import (
    KIND_LEASE,
    ConflictError,
    ObjectStore,
)


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease subset."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0

    def expired(self, now: float) -> bool:
        return now >= self.renew_time + self.lease_duration_seconds


class LeaderElector:
    """tryAcquireOrRenew loop (client-go leaderelection.go semantics):
    call tick(now) on retry_period; it returns whether this replica leads.

    on_started_leading / on_stopped_leading fire on transitions, mirroring
    LeaderCallbacks (server.go:228-247). The reference process exits when it
    loses the lease; here the callback owner decides (tests keep the object
    alive to observe failover)."""

    def __init__(
        self,
        store: ObjectStore,
        lease_name: str,
        identity: str,
        lease_duration_seconds: float = 15.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ) -> None:
        self.store = store
        self.lease_name = lease_name
        self.identity = identity
        self.lease_duration = lease_duration_seconds
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = False

    @property
    def is_leader(self) -> bool:
        return self._leading

    def _set_leading(self, leading: bool) -> bool:
        if leading and not self._leading:
            self._leading = True
            if self.on_started_leading:
                self.on_started_leading()
        elif not leading and self._leading:
            self._leading = False
            if self.on_stopped_leading:
                self.on_stopped_leading()
        return self._leading

    def tick(self, now: Optional[float] = None) -> bool:
        """One tryAcquireOrRenew round; returns leadership after the round."""
        now = time.time() if now is None else now
        lease: Optional[Lease] = self.store.get(KIND_LEASE, f"/{self.lease_name}")
        if lease is None:
            fresh = Lease(
                meta=ObjectMeta(name=self.lease_name, namespace=""),
                holder_identity=self.identity,
                lease_duration_seconds=self.lease_duration,
                acquire_time=now,
                renew_time=now,
            )
            try:
                self.store.add(KIND_LEASE, fresh)
            except ValueError:
                return self._set_leading(False)  # lost the creation race
            return self._set_leading(True)

        if lease.holder_identity == self.identity:
            # renew via CAS: a conflict means another replica took over
            import copy

            renewed = copy.deepcopy(lease)
            renewed.renew_time = now
            try:
                self.store.update(
                    KIND_LEASE, renewed,
                    expect_rv=lease.meta.resource_version,
                )
            except ConflictError:
                return self._set_leading(False)
            return self._set_leading(True)

        if not lease.expired(now):
            return self._set_leading(False)

        # expired foreign lease: try to take it over
        import copy

        taken = copy.deepcopy(lease)
        taken.holder_identity = self.identity
        taken.acquire_time = now
        taken.renew_time = now
        taken.lease_transitions += 1
        try:
            self.store.update(
                KIND_LEASE, taken, expect_rv=lease.meta.resource_version
            )
        except ConflictError:
            return self._set_leading(False)  # another standby won the race
        return self._set_leading(True)

    def release(self, now: Optional[float] = None) -> None:
        """Voluntary hand-off (ReleaseOnCancel): zero the renew time so a
        standby acquires immediately."""
        now = time.time() if now is None else now
        lease: Optional[Lease] = self.store.get(KIND_LEASE, f"/{self.lease_name}")
        if lease is None or lease.holder_identity != self.identity:
            return
        import copy

        released = copy.deepcopy(lease)
        released.renew_time = now - self.lease_duration
        try:
            self.store.update(
                KIND_LEASE, released, expect_rv=lease.meta.resource_version
            )
        except ConflictError:
            pass
        self._set_leading(False)


class ElectedRunner:
    """Run a control loop only while holding the lease — the active/standby
    wrapper every control-plane binary uses (server.go:227-256). run_fn fires
    each tick only on the current leader."""

    def __init__(self, elector: LeaderElector, run_fn: Callable[[float], None]):
        self.elector = elector
        self.run_fn = run_fn
        self.runs = 0

    def tick(self, now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        if self.elector.tick(now):
            self.run_fn(now)
            self.runs += 1
            return True
        return False
