"""In-process object store + informer layer.

Analog of the reference's generated clientsets/informers/listers (`pkg/client/`,
SURVEY.md section 2.7) plus the API-server watch bus (section 5.8a). The reference's
only cluster-wide communication channel is the Kubernetes API server; here the same
role is played by `ObjectStore`: typed collections with resourceVersion bumping and
subscriber callbacks, so controllers/schedulers/agents interoperate exactly as they
do against a real API server, and tests run hermetically (the fake-clientset tier of
the reference's test strategy, SURVEY.md section 4).
"""

from koordinator_tpu.client.store import ObjectStore, EventType, Informer  # noqa: F401
from koordinator_tpu.client.leaderelection import (  # noqa: F401
    ElectedRunner,
    LeaderElector,
    Lease,
)
