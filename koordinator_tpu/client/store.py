"""Typed object store with watch semantics.

Collections keyed by object kind; each add/update/delete bumps a global
resourceVersion and fans out to informer subscribers (synchronously, in registration
order — matching client-go's single event-handler goroutine per informer). Optimistic
concurrency: `update` can require the caller's resourceVersion to match (the analog
of an apiserver 409), which the scheduler's assume/bind path relies on.
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional


class EventType(enum.Enum):
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"


Handler = Callable[[EventType, Any, Optional[Any]], None]  # (event, obj, old_obj)


class ConflictError(Exception):
    """Optimistic-concurrency conflict (apiserver 409 analog)."""


@dataclass
class _Collection:
    objects: Dict[str, Any] = field(default_factory=dict)
    handlers: List[Handler] = field(default_factory=list)


# Canonical kind names used across the framework.
KIND_POD = "Pod"
KIND_NODE = "Node"
KIND_NODE_METRIC = "NodeMetric"
KIND_NODE_SLO = "NodeSLO"
KIND_RESERVATION = "Reservation"
KIND_POD_GROUP = "PodGroup"
KIND_ELASTIC_QUOTA = "ElasticQuota"
KIND_DEVICE = "Device"
KIND_NODE_TOPOLOGY = "NodeResourceTopology"
KIND_POD_MIGRATION_JOB = "PodMigrationJob"
KIND_COLOCATION_PROFILE = "ClusterColocationProfile"
KIND_QUOTA_PROFILE = "ElasticQuotaProfile"
KIND_CONFIG_MAP = "ConfigMap"
KIND_PDB = "PodDisruptionBudget"
KIND_LEASE = "Lease"  # coordination.k8s.io leader-election lease
KIND_PVC = "PersistentVolumeClaim"
KIND_PV = "PersistentVolume"
KIND_STORAGECLASS = "StorageClass"
KIND_NAMESPACE = "Namespace"

ALL_KINDS = (
    KIND_POD,
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_NODE_SLO,
    KIND_RESERVATION,
    KIND_POD_GROUP,
    KIND_ELASTIC_QUOTA,
    KIND_DEVICE,
    KIND_NODE_TOPOLOGY,
    KIND_POD_MIGRATION_JOB,
    KIND_COLOCATION_PROFILE,
    KIND_QUOTA_PROFILE,
    KIND_CONFIG_MAP,
    KIND_PDB,
    KIND_LEASE,
    KIND_PVC,
    KIND_PV,
    KIND_STORAGECLASS,
    KIND_NAMESPACE,
)


def _key_of(obj: Any) -> str:
    meta = getattr(obj, "meta", None)
    if meta is None:
        raise TypeError(f"object {obj!r} has no .meta")
    return meta.key


class ObjectStore:
    """The cluster-wide bus: all durable state lives here (SURVEY.md section 5.4)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._rv = 0  # global mutation counter (see resource_version)
        self._collections: Dict[str, _Collection] = {k: _Collection() for k in ALL_KINDS}
        # admission interceptors (apiserver -> webhook call path): named so a
        # standby replica installing the same server is idempotent
        self._admission: Dict[str, Any] = {}

    def set_admission(self, name: str, fn) -> None:
        """Install an admission interceptor `fn(kind, obj, old=None,
        delete=False)` run before every add/update/delete; raising rejects
        the operation (the store's analog of registering a webhook with the
        apiserver). Passing None removes it."""
        with self._lock:
            if fn is None:
                self._admission.pop(name, None)
            else:
                self._admission[name] = fn

    def _admit(self, kind: str, obj: Any, old: Any = None,
               delete: bool = False) -> None:
        with self._lock:
            interceptors = list(self._admission.values())
        for fn in interceptors:
            fn(kind, obj, old=old, delete=delete)

    # -- accessors -----------------------------------------------------------
    def get(self, kind: str, key: str) -> Optional[Any]:
        with self._lock:
            return self._collections[kind].objects.get(key)

    def list(self, kind: str) -> List[Any]:
        with self._lock:
            return list(self._collections[kind].objects.values())

    def __len__(self) -> int:
        with self._lock:
            return sum(len(c.objects) for c in self._collections.values())

    # -- mutators ------------------------------------------------------------
    @property
    def resource_version(self) -> int:
        """Global mutation counter: bumps on every add/update/delete.
        Cheap cache-invalidation key for derived indexes."""
        with self._lock:
            return self._rv

    def add(self, kind: str, obj: Any) -> Any:
        self._admit(kind, obj)
        with self._lock:
            key = _key_of(obj)
            col = self._collections[kind]
            if key in col.objects:
                raise ValueError(f"{kind} {key} already exists")
            self._rv += 1
            obj.meta.resource_version = self._rv
            col.objects[key] = obj
            handlers = list(col.handlers)
        self._notify(handlers, EventType.ADDED, obj, None)
        return obj

    def update(self, kind: str, obj: Any, expect_rv: Optional[int] = None) -> Any:
        self._admit(kind, obj, old=self.get(kind, _key_of(obj)))
        with self._lock:
            key = _key_of(obj)
            col = self._collections[kind]
            old = col.objects.get(key)
            if old is None:
                raise KeyError(f"{kind} {key} not found")
            if expect_rv is not None and old.meta.resource_version != expect_rv:
                raise ConflictError(
                    f"{kind} {key}: rv {old.meta.resource_version} != expected {expect_rv}"
                )
            self._rv += 1
            obj.meta.resource_version = self._rv
            col.objects[key] = obj
            handlers = list(col.handlers)
        self._notify(handlers, EventType.MODIFIED, obj, old)
        return obj

    def update_many(self, kind: str, objs: List[Any]) -> List[Any]:
        """Vectorized update transaction: N updates of one kind under
        TWO lock acquisitions (admission pre-read + apply) instead of
        2N+. Each object still gets its own resourceVersion bump and its
        own MODIFIED event (handlers receive the identical (obj, old)
        pairs, in order, that N sequential ``update`` calls would
        deliver — only the lock round-trips are amortized; a mid-batch
        failure (missing key, admission rejection) applies and notifies
        the prefix, then raises, exactly like the sequential loop. One
        batching departure: admission interceptors see the pre-batch
        ``old`` side, not the just-applied prefix). The
        scheduler's wave-replay batches (bind patches per wave, the
        deferred condition flush) route through this so a K-wave dispatch
        pays one store transaction per batch instead of one per pod."""
        if not objs:
            return objs
        with self._lock:  # one locked pre-read for the admission olds
            col = self._collections[kind]
            olds = [col.objects.get(_key_of(obj)) for obj in objs]
        admitted: List[Any] = []
        failure: Optional[Exception] = None
        for obj, old in zip(objs, olds):
            try:
                self._admit(kind, obj, old=old)
            except Exception as exc:  # admission rejection: stop where
                failure = exc         # the sequential loop would
                break
            admitted.append(obj)
        events: List[tuple] = []
        with self._lock:
            col = self._collections[kind]
            for obj in admitted:
                key = _key_of(obj)
                old = col.objects.get(key)
                if old is None:
                    # stop exactly where N sequential updates would: the
                    # applied prefix keeps its rv bumps AND (below) its
                    # MODIFIED events before the KeyError surfaces
                    failure = KeyError(f"{kind} {key} not found")
                    break
                self._rv += 1
                obj.meta.resource_version = self._rv
                col.objects[key] = obj
                events.append((obj, old))
            handlers = list(col.handlers)
        for obj, old in events:
            self._notify(handlers, EventType.MODIFIED, obj, old)
        if failure is not None:
            raise failure
        return objs

    def upsert(self, kind: str, obj: Any) -> Any:
        with self._lock:
            exists = _key_of(obj) in self._collections[kind].objects
        return self.update(kind, obj) if exists else self.add(kind, obj)

    def delete(self, kind: str, key: str) -> Optional[Any]:
        existing = self.get(kind, key)
        if existing is not None:
            self._admit(kind, existing, delete=True)
        with self._lock:
            col = self._collections[kind]
            old = col.objects.pop(key, None)
            if old is None:
                return None
            self._rv += 1
            handlers = list(col.handlers)
        self._notify(handlers, EventType.DELETED, old, old)
        return old

    # -- watch ---------------------------------------------------------------
    def subscribe(self, kind: str, handler: Handler, replay: bool = True) -> None:
        """Register a handler; with replay=True, existing objects are delivered as
        ADDED first (informer list-then-watch semantics)."""
        with self._lock:
            existing = list(self._collections[kind].objects.values())
            self._collections[kind].handlers.append(handler)
        if replay:
            for obj in existing:
                handler(EventType.ADDED, obj, None)

    def unsubscribe(self, kind: str, handler: Handler) -> None:
        """Drop a watch handler registered by subscribe (no-op when it
        was never registered). The apiserver analog of a client's watch
        connection closing — crash-restart teardown severs a dead
        consumer's handlers so they stop receiving events."""
        with self._lock:
            handlers = self._collections[kind].handlers
            if handler in handlers:
                handlers.remove(handler)

    @staticmethod
    def _notify(handlers: Iterable[Handler], ev: EventType, obj: Any, old: Any) -> None:
        for h in handlers:
            h(ev, obj, old)


class Informer:
    """Thin lister façade over one collection (client-go lister analog)."""

    def __init__(self, store: ObjectStore, kind: str):
        self._store = store
        self._kind = kind

    def get(self, key: str) -> Optional[Any]:
        return self._store.get(self._kind, key)

    def list(self) -> List[Any]:
        return self._store.list(self._kind)

    def on_event(self, handler: Handler, replay: bool = True) -> None:
        self._store.subscribe(self._kind, handler, replay=replay)
