"""koord-manager metrics registry (analog of reference
pkg/slo-controller + pkg/quota-controller metrics).

Same shared Registry class as the koordlet/scheduler/descheduler
registries, so all four binaries expose the identical Prometheus text
format through `obs.server.ObsServer` and one scrape config covers the
deployment."""

from __future__ import annotations

from koordinator_tpu.koordlet.metrics import Registry

REGISTRY = Registry()

RECONCILE_SECONDS = REGISTRY.histogram(
    "koord_manager_reconcile_seconds",
    "Per-controller reconcile latency, labeled by controller",
)
RECONCILES_TOTAL = REGISTRY.counter(
    "koord_manager_reconciles_total",
    "Reconcile rounds executed per controller (leader only)",
)
# koordcolo (colo/): the device-resident control-plane resource model
COLO_PASS_SECONDS = REGISTRY.histogram(
    "koord_manager_colo_pass_seconds",
    "Colo pass latency (device or host engine), end to end",
)
COLO_PASSES_TOTAL = REGISTRY.counter(
    "koord_manager_colo_passes_total",
    "Colo passes executed, labeled by engine (device/host)",
)
COLO_DEGRADED_NODES = REGISTRY.gauge(
    "koord_manager_colo_degraded_nodes",
    "Nodes whose batch/mid resources were zeroed by the staleness "
    "degrade in the last colo pass",
)
COLO_NODES_CHANGED_TOTAL = REGISTRY.counter(
    "koord_manager_colo_nodes_changed_total",
    "Node status writes the colo writeback committed",
)
COLO_REVOKE_CANDIDATES = REGISTRY.gauge(
    "koord_manager_colo_revoke_candidates",
    "Quota groups over their runtime in the last colo pass "
    "(the revoke-candidate mask population)",
)
QUOTA_REVOKES_TOTAL = REGISTRY.counter(
    "koord_manager_quota_revokes_total",
    "Pods evicted by the elastic-quota overuse revoke loop",
)

# koordwatch (obs/timeline.py): a STANDALONE manager's private colo
# device timeline records into this registry so its own /metrics shows
# the windows; a co-located manager shares the scheduler's timeline
DEVICE_WINDOW_SECONDS = REGISTRY.histogram(
    "koord_device_window_seconds",
    "Device-window dispatch-to-last-sync interval, labeled by consumer "
    "and path",
    buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0),
)
DEVICE_IDLE_FRACTION = REGISTRY.gauge(
    "koord_device_idle_fraction",
    "Gap time between consecutive device windows over wall time",
)
