"""ctypes binding for the compiled serial scheduling floor (libkoordfloor.so).

`serial_schedule_full_native(fc, args)` runs the same full-chain serial loop
as `scheduler/parity.py::serial_schedule_full` — the scalar transcription of
the reference's per-pod Go chain — but compiled (g++ -O2, no FMA/fast-math so
float32 results stay bit-identical to numpy). bench.py times it on the same
packed trace as the TPU step and reports `vs_compiled_floor`: an honest
order-of-magnitude proxy for the reference's serial Go scheduler, which is
not runnable in this environment.

Build with `make -C koordinator_tpu/native` (or `build()` here); if the
library is missing, `available()` is False and callers fall back to the
numpy oracle.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_LIB_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_LIB_DIR, "libkoordfloor.so")

_lib: Optional[ctypes.CDLL] = None

_F32P = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_I32P = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")


def build(timeout: int = 120) -> bool:
    try:
        subprocess.run(
            ["make", "-C", _LIB_DIR, "-s", "libkoordfloor.so"],
            check=True, capture_output=True, timeout=timeout)
        return True
    except (subprocess.SubprocessError, OSError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    # reject a stale .so built against an older argument list — calling it
    # would read every pointer after the insertion shifted
    try:
        lib.koord_floor_abi_version.restype = ctypes.c_int
        if lib.koord_floor_abi_version() != 11:
            return None
    except AttributeError:
        return None
    lib.koord_serial_full_chain.restype = None
    lib.koord_serial_full_chain.argtypes = (
        [ctypes.c_int] * 16          # P R N K G A NG T S S2 PT SI VG CI MI prod
        + [_F32P] * 3                # fit_requests requests estimated
        + [_I32P] * 7                # is_prod..needs_bind
        + [_F32P] + [_I32P]          # cores_needed full_pcpus
        + [_I32P]                    # pod_taint_mask
        + [_I32P] * 3                # pod_aff_req pod_anti_req pod_aff_match
        + [_I32P]                    # pod_spread_skew [P, T]
        + [_I32P]                    # pod_pref_id [P]
        + [_I32P]                    # pod_ppref_id [P]
        + [_F32P]                    # ppref_w [max(S2,1), max(T,1)]
        + [_I32P] + [_F32P] + [_I32P]  # pod_port_wants vol_needed pod_img_id
        + [_F32P, _F32P] + [_I32P]   # allocatable requested node_ok
        + [_F32P] + [_I32P]          # filter_usage has_filter_usage
        + [_F32P] * 5                # filter_thr prod_thr prod_usage term_np term_pr
        + [_I32P] * 2                # score_valid filter_skip
        + [_F32P]                    # weights
        + [_F32P] + [_I32P] * 2      # numa_free numa_policy has_topology
        + [_F32P] * 2                # bind_free cpus_per_core
        + [_I32P]                    # node_taint_group
        + [_F32P] * 3                # aff_dom aff_count anti_cover
        + [_I32P]                    # aff_exists
        + [_F32P]                    # pref_scores [N, S]
        + [_F32P] * 2 + [_I32P]      # port_used vol_free node_vol_group
        + [_F32P]                    # img_scores
        + [_I32P] + [_F32P] * 2      # ancestors quota_used quota_runtime
        + [_I32P] + [_F32P] * 2      # gang_valid gang_min gang_assumed
        + [_I32P, ctypes.c_int]      # gang_group num_groups
        + [_I32P]                    # chosen (out)
    )
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def lownodeload_floor_native(alloc, usage_pct, has_metric, low_thr, high_thr,
                             pod_node, pod_prio, pod_req, movable,
                             pod_sort_cpu, max_evict_per_node: int):
    """Compiled serial floor for the LowNodeLoad rebalance pass: returns
    victim[P] int32 (1 = selected). Same classify/sort/select semantics as
    descheduler/lownodeload.py, executed per-node/per-pod serially — the
    honest stand-in for the reference's Go loops (BASELINE config 5)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(
            "libkoordfloor.so not built (make -C koordinator_tpu/native)")
    fn = lib.koord_lownodeload_floor
    if not getattr(fn, "_typed", False):
        fn.restype = None
        fn.argtypes = (
            [ctypes.c_int] * 3
            + [_F32P] * 2 + [_I32P]      # alloc usage_pct has_metric
            + [_F32P] * 2                # low_thr high_thr
            + [_I32P] * 2 + [_F32P]      # pod_node pod_prio pod_req
            + [_I32P] + [_F32P]          # movable pod_sort_cpu
            + [ctypes.c_int] + [_I32P]   # max_evict victim(out)
        )
        fn._typed = True
    alloc = _f32(alloc)
    N, R = alloc.shape
    pod_node = _i32(pod_node)
    P = pod_node.shape[0]
    victim = np.zeros(P, np.int32)
    fn(N, P, R, alloc, _f32(usage_pct), _i32(has_metric), _f32(low_thr),
       _f32(high_thr), pod_node, _i32(pod_prio), _f32(pod_req),
       _i32(movable), _f32(pod_sort_cpu), int(max_evict_per_node), victim)
    return victim


def _f32(x) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(x), np.float32)


def _i32(x) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(x), np.int32)


def serial_schedule_full_native(fc, args, num_groups: int = 0,
                                active_axes=None) -> np.ndarray:
    """Native analog of parity.serial_schedule_full: returns chosen[P] int32.
    Raises RuntimeError if the library is not built. active_axes: original
    axis ids when fc was sliced (resolves the balanced-allocation axes)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(
            "libkoordfloor.so not built (make -C koordinator_tpu/native)")
    inputs = fc.base
    fit_requests = _f32(inputs.fit_requests)
    P, R = fit_requests.shape
    allocatable = _f32(inputs.allocatable)
    N = allocatable.shape[0]
    numa_free = _f32(fc.numa_free).copy()
    K = numa_free.shape[1]
    ancestors = _i32(fc.quota_ancestors)
    if ancestors.ndim != 2:
        ancestors = ancestors.reshape(0, 1)
    G, A = ancestors.shape if ancestors.size else (0, 1)
    gang_min = _f32(fc.gang_min_member)
    NG = gang_min.shape[0]
    gang_group = _i32(fc.gang_group_id)
    n_groups = int(num_groups or (int(gang_group.max()) + 1 if NG else 0))
    T = int(np.asarray(fc.aff_dom).shape[1])
    S = int(np.asarray(fc.pref_scores).shape[1])
    S2 = int(np.asarray(fc.ppref_w).shape[0]) if T else 0
    PT = int(np.asarray(fc.port_used).shape[1])
    SI = int(np.asarray(fc.img_scores).shape[1])
    pow_t = (1 << np.arange(max(T, 1), dtype=np.int64))[:T]

    def term_mask(rows) -> np.ndarray:  # [P, T] bool -> [P] int32 bitmask
        if not T:
            return np.zeros(P, np.int32)
        return _i32((np.asarray(rows, bool) * pow_t[None, :]).sum(axis=1))

    if PT:
        pow_s = (1 << np.arange(PT, dtype=np.int64))
        port_mask = _i32(
            (np.asarray(fc.pod_port_wants, bool) * pow_s[None, :]).sum(axis=1))
    else:
        port_mask = np.zeros(P, np.int32)
    from koordinator_tpu.models.full_chain import resolve_balance_idx

    bal_ci, bal_mi = resolve_balance_idx(active_axes)
    chosen = np.full(P, -1, np.int32)
    VG = int(np.asarray(fc.vol_needed).shape[1])
    lib.koord_serial_full_chain(
        P, R, N, K, max(G, 0), A, NG, T, S, S2, PT, SI, VG, bal_ci, bal_mi,
        1 if args.score_according_prod_usage else 0,
        fit_requests, _f32(fc.requests), _f32(inputs.estimated),
        _i32(inputs.is_prod), _i32(inputs.is_daemonset),
        _i32(inputs.pod_valid), _i32(fc.gang_id), _i32(fc.quota_id),
        _i32(fc.needs_numa), _i32(fc.needs_bind),
        _f32(fc.cores_needed), _i32(fc.full_pcpus),
        _i32(fc.pod_taint_mask),
        term_mask(fc.pod_aff_req), term_mask(fc.pod_anti_req),
        term_mask(fc.pod_aff_match),
        (_i32(fc.pod_spread_skew) if T
         else np.zeros((P, 1), np.int32)),
        _i32(fc.pod_pref_id),
        _i32(fc.pod_ppref_id),
        (_f32(fc.ppref_w) if S2
         else np.zeros((1, max(T, 1)), np.float32)),
        port_mask, _f32(fc.vol_needed), _i32(fc.pod_img_id),
        allocatable, _f32(inputs.requested).copy(), _i32(inputs.node_ok),
        _f32(inputs.la_filter_usage), _i32(inputs.la_has_filter_usage),
        _f32(inputs.la_filter_thresholds), _f32(inputs.la_prod_thresholds),
        _f32(inputs.la_prod_pod_usage),
        _f32(inputs.la_term_nonprod).copy(), _f32(inputs.la_term_prod).copy(),
        _i32(inputs.la_score_valid), _i32(inputs.la_filter_skip),
        _f32(inputs.weights),
        numa_free, _i32(fc.numa_policy), _i32(fc.has_topology),
        _f32(fc.bind_free).copy(), _f32(fc.cpus_per_core),
        _i32(fc.node_taint_group),
        (_f32(fc.aff_dom) if T
         else np.full((N, 1), -1.0, np.float32)),
        (_f32(fc.aff_count).copy() if T
         else np.zeros((N, 1), np.float32)),
        (_f32(fc.anti_cover).copy() if T
         else np.zeros((N, 1), np.float32)),
        _i32(fc.aff_exists) if T else np.zeros(1, np.int32),
        (_f32(fc.pref_scores) if S
         else np.zeros((N, 1), np.float32)),
        (_f32(fc.port_used).copy() if PT
         else np.zeros((N, 1), np.float32)),
        _f32(fc.vol_free).copy(), _i32(fc.node_vol_group),
        (_f32(fc.img_scores) if SI
         else np.zeros((N, 1), np.float32)),
        ancestors if ancestors.size else np.zeros((1, 1), np.int32),
        _f32(fc.quota_used).copy() if G else np.zeros((1, R), np.float32),
        _f32(fc.quota_runtime) if G else np.zeros((1, R), np.float32),
        _i32(fc.gang_valid) if NG else np.zeros(1, np.int32),
        gang_min if NG else np.zeros(1, np.float32),
        _f32(fc.gang_assumed) if NG else np.zeros(1, np.float32),
        gang_group if NG else np.zeros(1, np.int32),
        n_groups,
        chosen)
    return chosen
