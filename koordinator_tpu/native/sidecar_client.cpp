// Compiled-language sidecar client: a C++ consumer of the ScheduleBatch
// wire format, proving a non-Python host (the reference's Go event loop —
// SURVEY.md 5.8, modeled on /root/reference/apis/runtime/v1alpha1/
// api.proto:148-171's proto-service pattern) can pack a batch, call the
// JAX sidecar over the real socket, and read bindings back.
//
// grpc++ is not available in this image, so this speaks the gRPC wire
// protocol directly: HTTP/2 cleartext (h2c) over a unix socket with
// hand-rolled framing — client preface, SETTINGS exchange, one HEADERS
// frame (HPACK literal-without-indexing, no huffman — always valid HPACK),
// DATA frames carrying the 5-byte gRPC length-prefixed protobuf message,
// flow-control bookkeeping, PING/SETTINGS acks, and trailer detection.
// Messages (de)serialize through protoc-generated C++ classes
// (sidecar.pb.cc), the same schema the Python server registered.
//
// Usage: koord_sidecar_client <uds-path> <request-file> <response-file>
//                             [timeout-seconds]
//   request-file: serialized ScheduleBatchRequest
//   response-file: receives the serialized ScheduleBatchResponse
// Exit 0 on success; nonzero with a stderr line on any failure.

#include <arpa/inet.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <fstream>
#include <iostream>
#include <string>
#include <vector>

#include "sidecar.pb.h"

namespace {

constexpr uint8_t kFrameData = 0x0;
constexpr uint8_t kFrameHeaders = 0x1;
constexpr uint8_t kFrameRstStream = 0x3;
constexpr uint8_t kFrameSettings = 0x4;
constexpr uint8_t kFramePing = 0x6;
constexpr uint8_t kFrameGoaway = 0x7;
constexpr uint8_t kFrameWindowUpdate = 0x8;
constexpr uint8_t kFlagAck = 0x1;
constexpr uint8_t kFlagEndStream = 0x1;
constexpr uint8_t kFlagEndHeaders = 0x4;

int die(const std::string& msg) {
  std::cerr << "koord_sidecar_client: " << msg << "\n";
  return 1;
}

bool send_all(int fd, const uint8_t* buf, size_t len) {
  while (len > 0) {
    ssize_t n = ::send(fd, buf, len, 0);
    if (n <= 0) return false;
    buf += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool recv_all(int fd, uint8_t* buf, size_t len) {
  while (len > 0) {
    ssize_t n = ::recv(fd, buf, len, 0);
    if (n <= 0) return false;
    buf += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

void put_frame_header(std::vector<uint8_t>& out, uint32_t len, uint8_t type,
                      uint8_t flags, uint32_t stream) {
  out.push_back((len >> 16) & 0xff);
  out.push_back((len >> 8) & 0xff);
  out.push_back(len & 0xff);
  out.push_back(type);
  out.push_back(flags);
  out.push_back((stream >> 24) & 0x7f);
  out.push_back((stream >> 16) & 0xff);
  out.push_back((stream >> 8) & 0xff);
  out.push_back(stream & 0xff);
}

// HPACK: literal header field without indexing, new name, no huffman.
// Integer fits in the 7-bit prefix for every length used here (< 127).
void put_literal_header(std::vector<uint8_t>& out, const std::string& name,
                        const std::string& value) {
  out.push_back(0x00);
  out.push_back(static_cast<uint8_t>(name.size()));
  out.insert(out.end(), name.begin(), name.end());
  out.push_back(static_cast<uint8_t>(value.size()));
  out.insert(out.end(), value.begin(), value.end());
}

struct FrameHeader {
  uint32_t length;
  uint8_t type;
  uint8_t flags;
  uint32_t stream;
};

bool read_frame_header(int fd, FrameHeader* fh) {
  uint8_t b[9];
  if (!recv_all(fd, b, 9)) return false;
  fh->length = (uint32_t(b[0]) << 16) | (uint32_t(b[1]) << 8) | b[2];
  fh->type = b[3];
  fh->flags = b[4];
  fh->stream = (uint32_t(b[5] & 0x7f) << 24) | (uint32_t(b[6]) << 16) |
               (uint32_t(b[7]) << 8) | b[8];
  return true;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 4)
    return die("usage: <uds-path> <request-file> <response-file> [timeout-s]");
  const char* sock_path = argv[1];
  long timeout_s = argc > 4 ? atol(argv[4]) : 120;

  std::ifstream req_in(argv[2], std::ios::binary);
  if (!req_in) return die(std::string("cannot read ") + argv[2]);
  std::string req_bytes((std::istreambuf_iterator<char>(req_in)),
                        std::istreambuf_iterator<char>());
  {  // validate the request parses as the schema we claim to speak
    koordinator::scheduler::v1::ScheduleBatchRequest req;
    if (!req.ParseFromString(req_bytes))
      return die("request file is not a valid ScheduleBatchRequest");
  }

  int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return die("socket() failed");
  struct timeval tv = {timeout_s, 0};
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  struct sockaddr_un addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sun_family = AF_UNIX;
  std::strncpy(addr.sun_path, sock_path, sizeof(addr.sun_path) - 1);
  if (::connect(fd, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr)))
    return die(std::string("connect failed: ") + sock_path);

  // ---- connection preface + empty SETTINGS
  std::vector<uint8_t> out;
  const char* preface = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
  out.insert(out.end(), preface, preface + 24);
  put_frame_header(out, 0, kFrameSettings, 0, 0);

  // ---- HEADERS (stream 1): the gRPC unary-call pseudo + grpc headers
  std::vector<uint8_t> hpack;
  put_literal_header(hpack, ":method", "POST");
  put_literal_header(hpack, ":scheme", "http");
  put_literal_header(
      hpack, ":path",
      "/koordinator.scheduler.v1.BatchedScheduler/ScheduleBatch");
  put_literal_header(hpack, ":authority", "localhost");
  put_literal_header(hpack, "content-type", "application/grpc");
  put_literal_header(hpack, "te", "trailers");
  put_frame_header(out, hpack.size(), kFrameHeaders, kFlagEndHeaders, 1);
  out.insert(out.end(), hpack.begin(), hpack.end());
  if (!send_all(fd, out.data(), out.size()))
    return die("send of preface/headers failed");

  // ---- DATA: 5-byte gRPC prefix (uncompressed flag + BE32 length) + body
  std::string payload;
  payload.push_back('\0');
  uint32_t blen = htonl(static_cast<uint32_t>(req_bytes.size()));
  payload.append(reinterpret_cast<char*>(&blen), 4);
  payload += req_bytes;

  // flow-control state (RFC 7540 defaults; server SETTINGS may raise them)
  int64_t conn_window = 65535, stream_window = 65535;
  int64_t initial_window = 65535;  // last advertised INITIAL_WINDOW_SIZE
  uint32_t max_frame = 16384;
  std::string resp_data;
  bool stream_done = false, settings_acked_by_us = false;
  size_t sent = 0;

  auto pump_one_frame = [&]() -> int {  // 0 ok, <0 error, 1 stream done
    FrameHeader fh;
    if (!read_frame_header(fd, &fh)) return -1;
    std::vector<uint8_t> body(fh.length);
    if (fh.length && !recv_all(fd, body.data(), fh.length)) return -1;
    switch (fh.type) {
      case kFrameSettings:
        if (!(fh.flags & kFlagAck)) {
          for (size_t i = 0; i + 6 <= body.size(); i += 6) {
            uint16_t id = (uint16_t(body[i]) << 8) | body[i + 1];
            uint32_t v = (uint32_t(body[i + 2]) << 24) |
                         (uint32_t(body[i + 3]) << 16) |
                         (uint32_t(body[i + 4]) << 8) | body[i + 5];
            if (id == 4) {  // INITIAL_WINDOW_SIZE: delta vs the PREVIOUS
                            // advertised value (re-sent SETTINGS are legal)
              stream_window += int64_t(v) - initial_window;
              initial_window = int64_t(v);
            } else if (id == 5) {
              max_frame = v;
            }
          }
          std::vector<uint8_t> ack;
          put_frame_header(ack, 0, kFrameSettings, kFlagAck, 0);
          if (!send_all(fd, ack.data(), ack.size())) return -1;
          settings_acked_by_us = true;
        }
        return 0;
      case kFramePing:
        if (!(fh.flags & kFlagAck)) {
          std::vector<uint8_t> ack;
          put_frame_header(ack, 8, kFramePing, kFlagAck, 0);
          ack.insert(ack.end(), body.begin(), body.end());
          if (!send_all(fd, ack.data(), ack.size())) return -1;
        }
        return 0;
      case kFrameWindowUpdate: {
        if (body.size() != 4) return -1;
        uint32_t inc = (uint32_t(body[0] & 0x7f) << 24) |
                       (uint32_t(body[1]) << 16) | (uint32_t(body[2]) << 8) |
                       body[3];
        if (fh.stream == 0)
          conn_window += inc;
        else if (fh.stream == 1)
          stream_window += inc;
        return 0;
      }
      case kFrameData: {
        if (fh.stream == 1) {
          resp_data.append(reinterpret_cast<char*>(body.data()), body.size());
          // replenish receive windows so large responses never stall
          if (fh.length) {
            std::vector<uint8_t> wu;
            for (uint32_t sid : {0u, 1u}) {
              put_frame_header(wu, 4, kFrameWindowUpdate, 0, sid);
              wu.push_back((fh.length >> 24) & 0x7f);
              wu.push_back((fh.length >> 16) & 0xff);
              wu.push_back((fh.length >> 8) & 0xff);
              wu.push_back(fh.length & 0xff);
            }
            if (!send_all(fd, wu.data(), wu.size())) return -1;
          }
          if (fh.flags & kFlagEndStream) return 1;
        }
        return 0;
      }
      case kFrameHeaders:  // response headers or trailers (HPACK skipped:
                           // success is judged by the protobuf payload)
        if (fh.stream == 1 && (fh.flags & kFlagEndStream)) return 1;
        return 0;
      case kFrameRstStream:
        return die("server reset the stream"), -1;
      case kFrameGoaway:
        return die("server sent GOAWAY"), -1;
      default:
        return 0;  // ignore PRIORITY, PUSH_PROMISE etc.
    }
  };

  while (sent < payload.size()) {
    int64_t can = std::min(conn_window, stream_window);
    if (can <= 0) {  // exhausted: service frames until a WINDOW_UPDATE
      int r = pump_one_frame();
      if (r < 0) return 1;
      if (r == 1) { stream_done = true; break; }
      continue;
    }
    size_t chunk = std::min(payload.size() - sent,
                            std::min(size_t(can), size_t(max_frame)));
    bool last = sent + chunk == payload.size();
    std::vector<uint8_t> data;
    put_frame_header(data, chunk, kFrameData, last ? kFlagEndStream : 0, 1);
    data.insert(data.end(), payload.begin() + sent,
                payload.begin() + sent + chunk);
    if (!send_all(fd, data.data(), data.size())) return die("DATA send failed");
    sent += chunk;
    conn_window -= chunk;
    stream_window -= chunk;
  }

  while (!stream_done) {
    int r = pump_one_frame();
    if (r < 0) return die("connection failed mid-response");
    if (r == 1) stream_done = true;
  }
  (void)settings_acked_by_us;
  ::close(fd);

  if (resp_data.size() < 5) return die("no gRPC message in response");
  if (resp_data[0] != 0) return die("compressed response unsupported");
  uint32_t mlen;
  std::memcpy(&mlen, resp_data.data() + 1, 4);
  mlen = ntohl(mlen);
  if (resp_data.size() < 5 + mlen) return die("truncated gRPC message");
  std::string msg = resp_data.substr(5, mlen);

  koordinator::scheduler::v1::ScheduleBatchResponse resp;
  if (!resp.ParseFromString(msg))
    return die("response is not a valid ScheduleBatchResponse");
  std::ofstream out_f(argv[3], std::ios::binary);
  out_f.write(msg.data(), msg.size());
  if (!out_f) return die(std::string("cannot write ") + argv[3]);
  std::cerr << "koord_sidecar_client: ok, chosen tensor "
            << resp.chosen().data().size() << " bytes, kernel "
            << resp.kernel_seconds() << "s\n";
  return 0;
}
