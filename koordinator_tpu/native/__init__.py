"""Native C++ components (reference SURVEY.md 2.1: the cgo/libpfm4 binding is
the reference's one native component; rebuilt here as a direct
perf_event_open(2) syscall binding in C++ with a ctypes Python wrapper)."""
