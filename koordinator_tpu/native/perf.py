"""ctypes binding for the native perf counter reader (libkoordperf.so).

Python side of the reference's libpfm4 cgo component (perf_group_linux.go):
opens a cycles+instructions group per cgroup (or the current process), reads
cumulative counters, computes CPI. Degrades gracefully — if the library isn't
built or perf_event_open is denied (containers commonly set
perf_event_paranoid), `available()` is False and the CPI collector stays off,
matching the Libpfm4/CPICollector feature-gate behavior."""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

_LIB_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_LIB_DIR, "libkoordperf.so")

_lib: Optional[ctypes.CDLL] = None


def build(timeout: int = 120) -> bool:
    """Compile libkoordperf.so via the Makefile. Deliberately NOT called from
    the load path: the daemon must never block on a compiler at startup — run
    this from packaging/tests (`make -C koordinator_tpu/native`)."""
    try:
        subprocess.run(
            ["make", "-C", _LIB_DIR, "-s"],
            check=True,
            capture_output=True,
            timeout=timeout,
        )
        return True
    except (subprocess.SubprocessError, OSError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.koordperf_open_group.restype = ctypes.c_long
    lib.koordperf_open_group.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.koordperf_read.restype = ctypes.c_int
    lib.koordperf_read.argtypes = [
        ctypes.c_long,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.koordperf_close.restype = None
    lib.koordperf_close.argtypes = [ctypes.c_long]
    _lib = lib
    return lib


class PerfGroup:
    """One cycles+instructions counter group."""

    def __init__(self, handle: int):
        self._handle = handle

    @staticmethod
    def open_self(cpu: int = -1) -> Optional["PerfGroup"]:
        """Counters for the current process (any cpu)."""
        lib = _load()
        if lib is None:
            return None
        handle = lib.koordperf_open_group(0, cpu, 0)
        return PerfGroup(handle) if handle > 0 else None

    @staticmethod
    def open_cgroup(cgroup_dir: str, cpu: int = 0) -> Optional["PerfGroup"]:
        """Counters for a cgroup (per-cpu, as perf requires for cgroup mode)."""
        lib = _load()
        if lib is None:
            return None
        try:
            fd = os.open(cgroup_dir, os.O_RDONLY)
        except OSError:
            return None
        handle = lib.koordperf_open_group(fd, cpu, 1)
        os.close(fd)
        return PerfGroup(handle) if handle > 0 else None

    def read(self) -> Optional[Tuple[int, int]]:
        """(cycles, instructions), cumulative since open."""
        lib = _load()
        if lib is None or self._handle <= 0:
            return None
        cycles = ctypes.c_uint64()
        instructions = ctypes.c_uint64()
        rc = lib.koordperf_read(
            self._handle, ctypes.byref(cycles), ctypes.byref(instructions)
        )
        if rc != 0:
            return None
        return cycles.value, instructions.value

    def cpi(self) -> Optional[float]:
        sample = self.read()
        if not sample or sample[1] == 0:
            return None
        return sample[0] / sample[1]

    def close(self) -> None:
        lib = _load()
        if lib is not None and self._handle > 0:
            lib.koordperf_close(self._handle)
            self._handle = 0


def available() -> bool:
    """True when the native lib loads AND the kernel permits perf events."""
    g = PerfGroup.open_self()
    if g is None:
        return False
    ok = g.read() is not None
    g.close()
    return ok


class CgroupPerfReader:
    """Per-pod CPI sampler used by the performance collector
    (metricsadvisor.collect_performance): one perf group per pod cgroup,
    per-tick (cycles, instructions) deltas. `gc(live_keys)` closes groups for
    departed pods — without it, pod churn leaks perf-event fds until EMFILE."""

    def __init__(self, config):
        self.config = config
        self.groups = {}
        self.last = {}

    def __call__(self, pod):
        from koordinator_tpu.koordlet.metricsadvisor import pod_qos_dir

        rel = self.config.pod_relative_path(
            pod_qos_dir(pod), pod.meta.uid or pod.meta.name
        )
        path = self.config.cgroup_file_path(rel, "cpu.max")
        cgroup_dir = os.path.dirname(path)
        key = pod.meta.key
        if key not in self.groups:
            g = PerfGroup.open_cgroup(cgroup_dir)
            if g is None:
                return None
            self.groups[key] = g
        sample = self.groups[key].read()
        if sample is None:
            return None
        prev = self.last.get(key, (0, 0))
        self.last[key] = sample
        return (sample[0] - prev[0], sample[1] - prev[1])

    def gc(self, live_keys) -> None:
        live = set(live_keys)
        for key in list(self.groups):
            if key not in live:
                self.groups.pop(key).close()
                self.last.pop(key, None)

    def close(self) -> None:
        self.gc(())


def build_cgroup_perf_reader(config):
    """CgroupPerfReader, or None when perf is unusable on this host."""
    if not available():
        return None
    return CgroupPerfReader(config)
