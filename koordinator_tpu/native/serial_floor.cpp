// Compiled serial floor for the full plugin-chain scheduling step.
//
// A C++ transcription of scheduler/parity.py::serial_schedule_full (itself a
// scalar transcription of the reference's per-pod Go chain: kube
// NodeResourcesFit + load_aware.go:123-335 + NUMA admit + quota admission +
// gang permit). bench.py times this on the SAME packed trace as the TPU step
// and reports vs_compiled_floor — an order-of-magnitude-honest stand-in for
// the reference's serial Go scheduler, which cannot run here (no Go
// toolchain, no cluster).
//
// Float discipline mirrors the numpy oracle exactly so bindings are
// bit-identical: float32 arithmetic everywhere, except the usage-ratio
// computation which numpy promotes through float64 before the float32 cast.
// Build with -ffp-contract=off (see Makefile) so no FMA contraction changes
// results vs numpy.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <vector>

namespace {

inline float go_round(float x) { return std::floor(x + 0.5f); }

inline float least_requested(float requested, float capacity) {
  if (capacity <= 0.0f || requested > capacity) return 0.0f;
  return std::floor((capacity - requested) * 100.0f / capacity);
}

}  // namespace

// ABI version: bump when koord_serial_full_chain's signature changes, so a
// stale .so is rejected instead of mis-reading shifted pointers.
extern "C" int koord_floor_abi_version() { return 11; }

extern "C" {

// All 2-D arrays are row-major contiguous. Mutable state arrays (requested,
// term_np, term_pr, numa_free, bind_free, quota_used) are scratch copies the
// caller owns; they are mutated in place, as in the numpy oracle.
void koord_serial_full_chain(
    // dims
    int P, int R, int N, int K, int G, int A, int NG, int T, int S,
    int S2, int PT, int SI, int VG,
    int bal_ci, int bal_mi,  // balanced-allocation cpu/mem axes (-1 = off)
    int prod_mode,
    // pods
    const float* fit_requests,   // [P, R]
    const float* requests,       // [P, R]
    const float* estimated,      // [P, R]
    const int32_t* is_prod,      // [P]
    const int32_t* is_daemonset, // [P]
    const int32_t* pod_valid,    // [P]
    const int32_t* gang_id,      // [P]
    const int32_t* quota_id,     // [P]
    const int32_t* needs_numa,   // [P]
    const int32_t* needs_bind,   // [P]
    const float* cores_needed,   // [P]
    const int32_t* full_pcpus,   // [P]
    const int32_t* pod_taint_mask, // [P] bitmask of tolerated taint groups
    const int32_t* pod_aff_req,    // [P] bitmask of required affinity terms
    const int32_t* pod_anti_req,   // [P] bitmask of anti-affinity terms
    const int32_t* pod_aff_match,  // [P] bitmask of terms the pod matches
    const int32_t* pod_spread_skew, // [P, T] maxSkew per term (0 = none)
    const int32_t* pod_pref_id,    // [P] preferred-affinity profile (-1)
    const int32_t* pod_ppref_id,   // [P] preferred POD-affinity profile
    const float* ppref_w,          // [max(S2,1), max(T,1)] profile weights
    const int32_t* pod_port_wants, // [P] bitmask of hostPort slots
    const float* vol_needed,       // [P, VG] new PVC volume count per node
                                   //         volume group
    const int32_t* pod_img_id,     // [P] ImageLocality profile (-1)
    // nodes
    const float* allocatable,    // [N, R]
    float* requested_state,      // [N, R] (mutated)
    const int32_t* node_ok,      // [N]
    const float* filter_usage,   // [N, R]
    const int32_t* has_filter_usage, // [N]
    const float* filter_thr,     // [N, R]
    const float* prod_thr,       // [N, R]
    const float* prod_usage,     // [N, R]
    float* term_np,              // [N, R] (mutated)
    float* term_pr,              // [N, R] (mutated)
    const int32_t* score_valid,  // [N]
    const int32_t* filter_skip,  // [N]
    const float* weights,        // [R]
    // topology
    float* numa_free,            // [N, K, R] (mutated)
    const int32_t* numa_policy,  // [N]  0=none, 1=single-numa-node
    const int32_t* has_topology, // [N]
    float* bind_free,            // [N] (mutated)
    const float* cpus_per_core,  // [N]
    const int32_t* node_taint_group, // [N]
    const float* aff_dom,        // [N, T] topology domain ids (-1 invalid)
    float* aff_count,            // [N, T] matching pods per domain (mutated)
    float* anti_cover,           // [N, T] anti-term CARRIERS per domain
                                 //        (mutated; symmetric anti-affinity)
    const int32_t* aff_exists0,  // [T] any matching pod anywhere (host seed)
    const float* pref_scores,    // [N, S] preferred-affinity score rows
    float* port_used,            // [N, PT] hostPort slot bound (mutated)
    float* vol_free,             // [N] CSI attachable headroom (mutated;
                                 //     +inf when the node reports no limit)
    const int32_t* node_vol_group, // [N] volume group selecting the pod's
                                   //     NEW-attachment count
    const float* img_scores,     // [N, SI] ImageLocality score rows
    // quota
    const int32_t* ancestors,    // [G, A] (-1 padded)
    float* quota_used,           // [G, R] (mutated)
    const float* quota_runtime,  // [G, R]
    // gangs
    const int32_t* gang_valid,   // [NG]
    const float* gang_min,       // [NG]
    const float* gang_assumed,   // [NG]
    const int32_t* gang_group,   // [NG]
    int num_groups,
    // out
    int32_t* chosen)             // [P]
{
  float wsum = 0.0f;
  for (int r = 0; r < R; ++r) wsum += weights[r];
  const float wdiv = wsum > 1.0f ? wsum : 1.0f;

  // per-term "any matching pod anywhere" (host-seeded, incl. pods on nodes
  // without the topology label; flipped on every in-batch match placement)
  bool* term_has_match = T > 0 ? new bool[T]() : nullptr;
  for (int t = 0; t < T; ++t) term_has_match[t] = aff_exists0[t] != 0;

  for (int p = 0; p < P; ++p) {
    chosen[p] = -1;
    if (!pod_valid[p]) continue;
    // PreFilter: gang validity + quota admission along the ancestor chain
    if (gang_id[p] >= 0 && !gang_valid[gang_id[p]]) continue;
    bool admit = true;
    if (quota_id[p] >= 0) {
      const int32_t* chain = ancestors + (int64_t)quota_id[p] * A;
      for (int a = 0; a < A && admit; ++a) {
        int g = chain[a];
        if (g < 0) continue;
        for (int r = 0; r < R; ++r) {
          float need = requests[(int64_t)p * R + r];
          if (need > 0.0f &&
              quota_used[(int64_t)g * R + r] + need >
                  quota_runtime[(int64_t)g * R + r]) {
            admit = false;
            break;
          }
        }
      }
    }
    if (!admit) continue;

    int best_n = -1, best_zone = -1;
    float best_score = -1.0f;
    const float* fitp = fit_requests + (int64_t)p * R;
    const float* reqp = requests + (int64_t)p * R;
    const float* estp = estimated + (int64_t)p * R;
    const bool use_prod_score = prod_mode && is_prod[p];

    // preferred POD affinity: weighted count row + max-min norm, hoisted
    // per pod (counts are frozen during one pod's node scan)
    float* ppref_norm = nullptr;
    if (T > 0 && S2 > 0 && pod_ppref_id[p] >= 0) {
      const float* w = ppref_w + (int64_t)pod_ppref_id[p] * (T > 0 ? T : 1);
      ppref_norm = new float[N];
      // max-min over node_ok only (upstream NormalizeScore spans the
      // candidate set; padded rows must not anchor the scale)
      float mx = -3.4e38f, mn = 3.4e38f;
      for (int n = 0; n < N; ++n) {
        float raw = 0.0f;
        for (int t = 0; t < T; ++t)
          raw += w[t] * aff_count[(int64_t)n * T + t];
        ppref_norm[n] = raw;
        if (node_ok[n]) {
          if (raw > mx) mx = raw;
          if (raw < mn) mn = raw;
        }
      }
      for (int n = 0; n < N; ++n)
        ppref_norm[n] = mx > mn
            ? std::floor((ppref_norm[n] - mn) * 100.0f / (mx - mn))
            : 0.0f;
    }
    // spread minimums hoisted per (pod, term): invariant across the node
    // scan, restricted to domains of nodes the pod is ELIGIBLE for
    // (admission bit test), matching the batched evaluators
    float spread_min[32];
    if (T > 0) {
      bool any_spread = false;
      for (int t = 0; t < T; ++t)
        if (pod_spread_skew[(int64_t)p * T + t] > 0) { any_spread = true; break; }
      if (any_spread) {
        for (int t = 0; t < T; ++t) spread_min[t] = 3.4e38f;
        for (int n = 0; n < N; ++n) {
          if (!((pod_taint_mask[p] >> node_taint_group[n]) & 1)) continue;
          for (int t = 0; t < T; ++t) {
            float d = aff_dom[(int64_t)n * T + t];
            float c = aff_count[(int64_t)n * T + t];
            if (d >= 0.0f && c < spread_min[t]) spread_min[t] = c;
          }
        }
      }
    }

    for (int n = 0; n < N; ++n) {
      if (!node_ok[n]) continue;
      // TaintToleration: group bit test (ops/taints.py)
      if (!((pod_taint_mask[p] >> node_taint_group[n]) & 1)) continue;
      // InterPodAffinity (ops/podaffinity.py)
      if (T > 0) {
        bool affinity_ok = true;
        const float* cnt = aff_count + (int64_t)n * T;
        const float* dom = aff_dom + (int64_t)n * T;
        const float* cov = anti_cover + (int64_t)n * T;
        for (int t = 0; t < T && affinity_ok; ++t) {
          if (((pod_anti_req[p] >> t) & 1) && cnt[t] > 0.0f)
            affinity_ok = false;
          // symmetric anti-affinity: a carrier of anti term t in this
          // node's domain blocks any pod matching t
          if (((pod_aff_match[p] >> t) & 1) && cov[t] > 0.0f)
            affinity_ok = false;
          if ((pod_aff_req[p] >> t) & 1) {
            bool boot = ((pod_aff_match[p] >> t) & 1) && !term_has_match[t];
            if (!(boot || (dom[t] >= 0.0f && cnt[t] > 0.0f)))
              affinity_ok = false;
          }
          // PodTopologySpread (DoNotSchedule)
          int skew = pod_spread_skew[(int64_t)p * T + t];
          if (affinity_ok && skew > 0) {
            if (dom[t] < 0.0f) { affinity_ok = false; continue; }
            float self_m = ((pod_aff_match[p] >> t) & 1) ? 1.0f : 0.0f;
            if (cnt[t] + self_m - spread_min[t] > (float)skew)
              affinity_ok = false;
          }
        }
        if (!affinity_ok) continue;
      }
      // NodePorts: no wanted hostPort slot already bound on the node
      if (PT > 0) {
        bool port_ok = true;
        for (int s = 0; s < PT && port_ok; ++s)
          if (((pod_port_wants[p] >> s) & 1) &&
              port_used[(int64_t)n * PT + s] > 0.0f)
            port_ok = false;
        if (!port_ok) continue;
      }
      // CSI volume limit (+inf when the node reports none); the node's
      // volume group selects NEW attachments only
      {
        float vn = vol_needed[(int64_t)p * VG + node_vol_group[n]];
        if (vn > 0.0f && vol_free[n] < vn) continue;
      }
      const float* alloc = allocatable + (int64_t)n * R;
      const float* reqn = requested_state + (int64_t)n * R;
      // Filter: Fit
      bool fit = true;
      for (int r = 0; r < R; ++r) {
        if (fitp[r] > 0.0f && reqn[r] + fitp[r] > alloc[r]) { fit = false; break; }
      }
      if (!fit) continue;
      // Filter: LoadAware thresholds (load_aware.go:123-171)
      if (!is_daemonset[p] && !filter_skip[n]) {
        bool prod_configured = false;
        const float* pthr = prod_thr + (int64_t)n * R;
        for (int r = 0; r < R; ++r)
          if (pthr[r] > 0.0f) { prod_configured = true; break; }
        const bool use_prod = is_prod[p] && prod_configured;
        const float* usage =
            (use_prod ? prod_usage : filter_usage) + (int64_t)n * R;
        const float* thr = (use_prod ? prod_thr : filter_thr) + (int64_t)n * R;
        bool skip = !use_prod && !has_filter_usage[n];
        if (!skip) {
          bool ok = true;
          for (int r = 0; r < R; ++r) {
            if (thr[r] == 0.0f || alloc[r] == 0.0f) continue;
            // numpy computes this ratio in float64 then casts to float32
            float ratio = go_round(
                (float)((double)usage[r] * 100.0 / (double)alloc[r]));
            if (ratio >= thr[r]) { ok = false; break; }
          }
          if (!ok) continue;
        }
      }
      // Filter: cpuset capacity + SMT alignment
      if (needs_bind[p]) {
        if (!has_topology[n]) continue;
        float cpc = cpus_per_core[n] > 1.0f ? cpus_per_core[n] : 1.0f;
        if (full_pcpus[p] && std::fmod(cores_needed[p], cpc) != 0.0f) continue;
        if (cores_needed[p] > bind_free[n]) continue;
      }
      // NUMA admit
      int zone = -1;
      if (needs_numa[p] && numa_policy[n] != 0) {
        const float* nf = numa_free + ((int64_t)n * K) * R;
        if (numa_policy[n] == 1) {  // single-numa-node
          for (int k = 0; k < K && zone < 0; ++k) {
            bool fits = true;
            for (int r = 0; r < R; ++r) {
              if (reqp[r] > 0.0f && reqp[r] > nf[(int64_t)k * R + r]) {
                fits = false;
                break;
              }
            }
            if (fits) zone = k;
          }
          if (zone < 0) continue;
        } else {
          bool fits = true;
          for (int r = 0; r < R && fits; ++r) {
            if (reqp[r] <= 0.0f) continue;
            float total = 0.0f;
            for (int k = 0; k < K; ++k) total += nf[(int64_t)k * R + r];
            if (reqp[r] > total) fits = false;
          }
          if (!fits) continue;
        }
      }
      // Score: LoadAware least-requested + NUMA fit score
      const float* term = (use_prod_score ? term_pr : term_np) + (int64_t)n * R;
      float acc = 0.0f, acc2 = 0.0f;
      for (int r = 0; r < R; ++r) {
        if (weights[r] == 0.0f) continue;
        acc += weights[r] * least_requested(estp[r] + term[r], alloc[r]);
        acc2 += weights[r] * least_requested(reqn[r] + reqp[r], alloc[r]);
      }
      float la_score = score_valid[n] ? std::floor(acc / wdiv) : 0.0f;
      float numa_score = std::floor(acc2 / wdiv);
      // NodeResourcesBalancedAllocation: 2-axis std == |fc - fm| / 2
      if (bal_ci >= 0) {
        // reciprocal-multiply, NOT division: matches the f32 value the
        // XLA/Pallas/numpy implementations compute (used * f32(1/cap))
        float fc_ = 0.0f, fm_ = 0.0f;
        float capc = alloc[bal_ci];
        if (capc > 0.0f) {
          float invc = 1.0f / capc;
          fc_ = (reqn[bal_ci] + fitp[bal_ci]) * invc;
          if (fc_ > 1.0f) fc_ = 1.0f;
        }
        float capm = alloc[bal_mi];
        if (capm > 0.0f) {
          float invm = 1.0f / capm;
          fm_ = (reqn[bal_mi] + fitp[bal_mi]) * invm;
          if (fm_ > 1.0f) fm_ = 1.0f;
        }
        float std_ = std::fabs(fc_ - fm_) * 0.5f;
        numa_score += std::floor((1.0f - std_) * 100.0f);
      }
      float s = la_score + numa_score;
      // preferred node affinity: static profile score row
      if (S > 0 && pod_pref_id[p] >= 0)
        s += pref_scores[(int64_t)n * S + pod_pref_id[p]];
      if (ppref_norm) s += ppref_norm[n];
      if (SI > 0 && pod_img_id[p] >= 0)
        s += img_scores[(int64_t)n * SI + pod_img_id[p]];
      if (s > best_score) {  // strict: lowest index wins ties
        best_n = n;
        best_score = s;
        best_zone = zone;
      }
    }
    delete[] ppref_norm;
    if (best_n < 0) continue;
    chosen[p] = best_n;
    // Reserve: Fit state + assign cache + NUMA/cpuset/quota accounting
    float* reqn = requested_state + (int64_t)best_n * R;
    float* tnp = term_np + (int64_t)best_n * R;
    float* tpr = term_pr + (int64_t)best_n * R;
    for (int r = 0; r < R; ++r) {
      reqn[r] += fitp[r];
      tnp[r] += estp[r];
      if (prod_mode && is_prod[p]) tpr[r] += estp[r];
    }
    if (needs_numa[p]) {
      float* nf = numa_free + ((int64_t)best_n * K) * R;
      if (best_zone >= 0) {
        for (int r = 0; r < R; ++r) nf[(int64_t)best_zone * R + r] -= reqp[r];
      } else {
        for (int r = 0; r < R; ++r) {
          float remaining = reqp[r];
          for (int k = 0; k < K; ++k) {
            float avail = nf[(int64_t)k * R + r];
            float take = avail < remaining ? avail : remaining;
            nf[(int64_t)k * R + r] -= take;
            remaining -= take;
          }
        }
      }
    }
    if (needs_bind[p]) bind_free[best_n] -= cores_needed[p];
    for (int s = 0; s < PT; ++s)
      if ((pod_port_wants[p] >> s) & 1)
        port_used[(int64_t)best_n * PT + s] = 1.0f;
    {
      float vnb = vol_needed[(int64_t)p * VG + node_vol_group[best_n]];
      if (vnb > 0.0f) vol_free[best_n] -= vnb;
    }
    if (quota_id[p] >= 0) {
      const int32_t* chain = ancestors + (int64_t)quota_id[p] * A;
      for (int a = 0; a < A; ++a) {
        int g = chain[a];
        if (g < 0) continue;
        float* qu = quota_used + (int64_t)g * R;
        for (int r = 0; r < R; ++r) qu[r] += reqp[r];
      }
    }
    for (int t = 0; t < T; ++t) {
      float d = aff_dom[(int64_t)best_n * T + t];
      if ((pod_aff_match[p] >> t) & 1) {
        term_has_match[t] = true;  // even when the node lacks the label
        if (d >= 0.0f)
          for (int n = 0; n < N; ++n)
            if (aff_dom[(int64_t)n * T + t] == d)
              aff_count[(int64_t)n * T + t] += 1.0f;
      }
      // a placed CARRIER of anti term t raises its domain's anti_cover
      if (((pod_anti_req[p] >> t) & 1) && d >= 0.0f)
        for (int n = 0; n < N; ++n)
          if (aff_dom[(int64_t)n * T + t] == d)
            anti_cover[(int64_t)n * T + t] += 1.0f;
    }
  }
  delete[] term_has_match;

  // ---- gang permit barrier (all-or-nothing per gang group)
  if (NG > 0) {
    // heap-free small passes: counts fit on the stack only for tiny NG, so
    // allocate; this is outside the timed per-pod loop's hot path anyway
    float* per_gang = new float[NG]();
    for (int p = 0; p < P; ++p)
      if (gang_id[p] >= 0 && chosen[p] >= 0) per_gang[gang_id[p]] += 1.0f;
    bool* gang_ok = new bool[NG];
    int ngrp = num_groups > 0 ? num_groups : 1;
    int* group_fail = new int[ngrp]();
    for (int g = 0; g < NG; ++g) {
      gang_ok[g] = per_gang[g] + gang_assumed[g] >= gang_min[g];
      if (!gang_ok[g]) group_fail[gang_group[g]] += 1;
    }
    for (int p = 0; p < P; ++p) {
      int g = gang_id[p];
      if (g >= 0 && (!gang_ok[g] || group_fail[gang_group[g]] > 0))
        chosen[p] = -1;
    }
    delete[] per_gang;
    delete[] gang_ok;
    delete[] group_fail;
  }
}

// Serial floor for the koord-descheduler LowNodeLoad global rebalance
// (BASELINE config 5): a per-node/per-pod transcription of the classify /
// sort / select pass (reference pkg/descheduler/framework/plugins/loadaware/
// utilization_util.go semantics as implemented by descheduler/lownodeload.py:
// classify nodes by measured utilization, walk each high node's movable
// pods sorted by (priority asc, cpu desc), select until the node would drop
// back under the high thresholds or the per-node cap hits). Same float32
// arithmetic as the python pass so the selected victim set is identical.
void koord_lownodeload_floor(
    int N, int P, int R,
    const float* alloc,          // [N, R]
    const float* usage_pct,      // [N, R] measured utilization percent
    const int32_t* has_metric,   // [N]
    const float* low_thr,        // [R] (0 = unchecked)
    const float* high_thr,       // [R]
    const int32_t* pod_node,     // [P] node index (-1 = unassigned)
    const int32_t* pod_prio,     // [P]
    const float* pod_req,        // [P, R]
    const int32_t* movable,      // [P]
    const float* pod_sort_cpu,   // [P] cpu request (sort key)
    int max_evict_per_node,
    int32_t* victim)             // [P] out: 1 = selected for migration
{
  for (int p = 0; p < P; ++p) victim[p] = 0;
  // classification
  std::vector<bool> is_low(N, false), is_high(N, false);
  for (int n = 0; n < N; ++n) {
    if (!has_metric[n]) continue;
    bool low = true, high = false;
    for (int r = 0; r < R; ++r) {
      float u = usage_pct[(int64_t)n * R + r];
      if (low_thr[r] > 0.0f && !(u < low_thr[r])) low = false;
      if (high_thr[r] > 0.0f && u > high_thr[r]) high = true;
    }
    is_high[n] = high;
    is_low[n] = low && !high;
  }
  bool any_low = false, any_high = false;
  for (int n = 0; n < N; ++n) {
    any_low = any_low || is_low[n];
    any_high = any_high || is_high[n];
  }
  if (!any_low || !any_high) return;

  // per-node movable pod lists (single pass, stable order = input order)
  std::vector<std::vector<int>> node_pods(N);
  for (int p = 0; p < P; ++p) {
    int n = pod_node[p];
    if (n >= 0 && n < N && movable[p]) node_pods[n].push_back(p);
  }
  for (int n = 0; n < N; ++n) {
    if (!is_high[n]) continue;
    // over-gate mirrors lownodeload.py exactly: NO thr>0 mask here (the
    // python pass max(usage - thr, 0).any() counts unchecked axes too)
    bool over = false;
    for (int r = 0; r < R; ++r)
      if (usage_pct[(int64_t)n * R + r] - high_thr[r] > 0.0f) over = true;
    if (!over) continue;
    std::vector<int>& cand = node_pods[n];
    std::stable_sort(cand.begin(), cand.end(), [&](int a, int b) {
      if (pod_prio[a] != pod_prio[b]) return pod_prio[a] < pod_prio[b];
      return pod_sort_cpu[a] > pod_sort_cpu[b];
    });
    // freed accumulates in DOUBLE (like the reference's int64 quantity
    // math): the python pass computes the same prefix as one global f64
    // cumsum, which is exactly this sequential accumulation for the
    // integer-valued packed requests. The still-over test uses the
    // MULTIPLY form freed*100 < (usage - thr) * alloc (alloc > 0), the
    // identical double expression the python pass evaluates, so the
    // comparison is bit-deterministic on both sides.
    std::vector<double> freed(R, 0.0), rhs(R, 0.0);
    for (int r = 0; r < R; ++r) {
      float a = alloc[(int64_t)n * R + r];
      float denom = a > 1e-9f ? a : 1e-9f;
      rhs[r] = ((double)usage_pct[(int64_t)n * R + r] -
                (double)high_thr[r]) * (double)denom;
    }
    int count = 0;
    for (int pi : cand) {
      if (count >= max_evict_per_node) break;
      bool still_over = false;
      for (int r = 0; r < R; ++r) {
        if (high_thr[r] <= 0.0f) continue;
        if (freed[r] * 100.0 < rhs[r]) still_over = true;
      }
      if (!still_over) break;
      victim[pi] = 1;
      for (int r = 0; r < R; ++r)
        freed[r] += (double)pod_req[(int64_t)pi * R + r];
      ++count;
    }
  }
}

}  // extern "C"
