// Hardware performance-counter reader for CPI collection.
//
// Native analog of the reference's one cgo component: the libpfm4 binding
// wrapping perf_event_open(2) to read per-cgroup cycles/instructions for the
// CPI metric (pkg/koordlet/util/perf_group/perf_group_linux.go:39-40,
// metricsadvisor performance collector :46-101). Instead of depending on
// libpfm4, this binds the two fixed architectural events directly via the raw
// syscall — no external library, same counters.
//
// Exposed as a C ABI consumed from Python via ctypes
// (koordinator_tpu/native/perf.py). Build: `make -C koordinator_tpu/native`.
//
// Usage pattern (mirrors the reference's perf group lifecycle):
//   handle = koordperf_open_group(target_fd, cpu, is_cgroup)
//     target_fd: an open fd of the cgroup directory (PERF_FLAG_PID_CGROUP) or
//                -1/0 for "this process" (pid = 0)
//   koordperf_read(handle, &cycles, &instructions)  // cumulative
//   koordperf_close(handle)

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <vector>

#if defined(__linux__)
#include <linux/perf_event.h>
#include <sys/ioctl.h>
#include <sys/syscall.h>
#include <unistd.h>
#endif

extern "C" {

struct KoordPerfGroup {
  int leader_fd;   // cycles (group leader)
  int member_fd;   // instructions
};

#if defined(__linux__)

static long perf_event_open_sys(struct perf_event_attr *attr, pid_t pid,
                                int cpu, int group_fd, unsigned long flags) {
  return syscall(SYS_perf_event_open, attr, pid, cpu, group_fd, flags);
}

static int open_counter(uint64_t config, pid_t pid, int cpu, int group_fd,
                        unsigned long flags) {
  struct perf_event_attr attr;
  memset(&attr, 0, sizeof(attr));
  attr.type = PERF_TYPE_HARDWARE;
  attr.size = sizeof(attr);
  attr.config = config;
  attr.disabled = (group_fd == -1) ? 1 : 0;  // leader starts disabled
  attr.inherit = 1;
  attr.read_format = PERF_FORMAT_GROUP;
  attr.exclude_kernel = 0;
  attr.exclude_hv = 1;
  return (int)perf_event_open_sys(&attr, pid, cpu, group_fd, flags);
}

// Returns an opaque handle (>0) or -errno on failure.
long koordperf_open_group(int target_fd, int cpu, int is_cgroup) {
  pid_t pid = 0;
  unsigned long flags = 0;
  if (is_cgroup) {
    pid = target_fd;  // cgroup fd goes in the pid slot
    flags = PERF_FLAG_PID_CGROUP;
  }
  int leader =
      open_counter(PERF_COUNT_HW_CPU_CYCLES, pid, cpu, -1, flags);
  if (leader < 0) return -(long)errno;
  int member =
      open_counter(PERF_COUNT_HW_INSTRUCTIONS, pid, cpu, leader, flags);
  if (member < 0) {
    long err = -(long)errno;
    close(leader);
    return err;
  }
  ioctl(leader, PERF_EVENT_IOC_RESET, PERF_IOC_FLAG_GROUP);
  ioctl(leader, PERF_EVENT_IOC_ENABLE, PERF_IOC_FLAG_GROUP);
  KoordPerfGroup *g = new KoordPerfGroup{leader, member};
  return (long)(intptr_t)g;
}

// PERF_FORMAT_GROUP layout: u64 nr; { u64 value; } cntr[nr];
int koordperf_read(long handle, uint64_t *cycles, uint64_t *instructions) {
  if (handle <= 0) return -EINVAL;
  KoordPerfGroup *g = (KoordPerfGroup *)(intptr_t)handle;
  uint64_t buf[1 + 2];
  ssize_t n = read(g->leader_fd, buf, sizeof(buf));
  if (n < (ssize_t)sizeof(uint64_t)) return -errno;
  uint64_t nr = buf[0];
  *cycles = nr >= 1 ? buf[1] : 0;
  *instructions = nr >= 2 ? buf[2] : 0;
  return 0;
}

void koordperf_close(long handle) {
  if (handle <= 0) return;
  KoordPerfGroup *g = (KoordPerfGroup *)(intptr_t)handle;
  ioctl(g->leader_fd, PERF_EVENT_IOC_DISABLE, PERF_IOC_FLAG_GROUP);
  close(g->member_fd);
  close(g->leader_fd);
  delete g;
}

#else  // non-linux stub

long koordperf_open_group(int, int, int) { return -38 /* ENOSYS */; }
int koordperf_read(long, uint64_t *, uint64_t *) { return -38; }
void koordperf_close(long) {}

#endif

}  // extern "C"
