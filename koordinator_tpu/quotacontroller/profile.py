"""ElasticQuotaProfile controller.

Analog of `pkg/quota-controller/profile/profile_controller.go`: a profile
selects a node group (e.g. an AZ) by labels and generates/refreshes an
ElasticQuota whose min/max track the selected nodes' total allocatable (ratio
annotation supported)."""

from __future__ import annotations

from typing import Optional

from koordinator_tpu.api.objects import (
    ElasticQuota,
    ElasticQuotaProfile,
    LABEL_QUOTA_IS_PARENT,
    ObjectMeta,
)
from koordinator_tpu.api.resources import ResourceList, ResourceName
from koordinator_tpu.client.store import (
    KIND_ELASTIC_QUOTA,
    KIND_NODE,
    KIND_QUOTA_PROFILE,
    ObjectStore,
)

ANNOTATION_QUOTA_RATIO = "quota.scheduling.koordinator.sh/total-resource-ratio"


class QuotaProfileController:
    def __init__(self, store: ObjectStore):
        self.store = store

    def reconcile(self) -> int:
        changes = 0
        for profile in self.store.list(KIND_QUOTA_PROFILE):
            total = ResourceList()
            for node in self.store.list(KIND_NODE):
                if all(
                    node.meta.labels.get(k) == v
                    for k, v in profile.node_selector.items()
                ):
                    total = total.add(node.allocatable)
            ratio = 1.0
            raw = profile.meta.annotations.get(ANNOTATION_QUOTA_RATIO)
            if raw:
                try:
                    ratio = max(0.0, min(1.0, float(raw)))
                except ValueError:
                    ratio = 1.0
            scaled = ResourceList(
                {
                    k: int(v * ratio)
                    for k, v in total.quantities.items()
                    if k in (ResourceName.CPU, ResourceName.MEMORY)
                }
            )
            name = profile.quota_name or profile.meta.name
            key = f"{profile.meta.namespace}/{name}"
            existing: Optional[ElasticQuota] = self.store.get(KIND_ELASTIC_QUOTA, key)
            if existing is None:
                meta = ObjectMeta(
                    name=name,
                    namespace=profile.meta.namespace,
                    labels={LABEL_QUOTA_IS_PARENT: "true", **profile.quota_labels},
                )
                self.store.add(
                    KIND_ELASTIC_QUOTA,
                    ElasticQuota(meta=meta, min=scaled.copy(), max=scaled.copy()),
                )
                changes += 1
            elif existing.min.quantities != scaled.quantities:
                existing.min = scaled.copy()
                existing.max = scaled.copy()
                self.store.update(KIND_ELASTIC_QUOTA, existing)
                changes += 1
        return changes
