"""Quota controller (reference `pkg/quota-controller/`)."""

from koordinator_tpu.quotacontroller.profile import QuotaProfileController  # noqa: F401
