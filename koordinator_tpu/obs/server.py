"""Shared /metrics + /traces + /healthz surface for every binary.

The koordlet API server (`koordlet/server.py`) established the pattern:
a socket-free routing core `handle(path, query) -> (status, content_type,
body)` that tests drive directly, wrapped by `serve()` in a
ThreadingHTTPServer for live use. This module extracts that pattern so the
scheduler and descheduler expose the exact same Prometheus exposition
format (and JSONL trace dumps) as the node agent — one scrape config for
the whole deployment.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse


def serve_handler(handle, port: int = 0):
    """Wrap a `(path, query) -> (status, content_type, body)` routing core
    in a ThreadingHTTPServer on 127.0.0.1; returns (server, thread). The
    one HTTP wrapper every handler-pattern server shares (ObsServer,
    KoordletServer) — fix transport behavior here, not per server."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib API)
            url = urlparse(self.path)
            q = {k: v[0] for k, v in parse_qs(url.query).items()}
            status, ctype, body = handle(url.path, q)
            payload = body.encode()
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, fmt, *args):  # silence
            pass

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, thread


class ObsServer:
    """Routing core for the observability endpoints.

    * ``/healthz`` — liveness
    * ``/metrics`` — Prometheus text exposition from the given Registry
    * ``/traces``  — the tracer ring as JSONL (``?limit=N`` newest roots),
      replayable with ``python -m koordinator_tpu.obs``
    """

    def __init__(self, metrics_registry=None, tracer=None):
        self.metrics_registry = metrics_registry
        self.tracer = tracer

    def handle(self, path: str, query: Optional[Dict[str, str]] = None
               ) -> Tuple[int, str, str]:
        """(status, content_type, body)."""
        query = query or {}
        parts = [p for p in path.split("/") if p]
        if parts == ["healthz"]:
            return 200, "text/plain", "ok"
        if parts == ["metrics"] and self.metrics_registry is not None:
            return (200, "text/plain; version=0.0.4",
                    self.metrics_registry.expose())
        if parts == ["traces"] and self.tracer is not None:
            raw = query.get("limit")
            if raw is None or raw == "":
                limit = None  # absent: the whole ring
            else:
                try:
                    limit = int(raw)
                except ValueError:
                    return 400, "text/plain", "limit must be an integer"
                if limit < 0:
                    return 400, "text/plain", "limit must be non-negative"
            body = self.tracer.export_jsonl(limit=limit)
            return 200, "application/x-ndjson", body
        return 404, "text/plain", f"unknown path {path!r}"

    def serve(self, port: int = 0):
        """Start the HTTP server; returns (server, thread)."""
        return serve_handler(self.handle, port)
