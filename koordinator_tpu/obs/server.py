"""Shared /metrics + /traces + /healthz (+ koordexplain) surface.

The koordlet API server (`koordlet/server.py`) established the pattern:
a socket-free routing core `handle(path, query) -> (status, content_type,
body)` that tests drive directly, wrapped by `serve()` in a
ThreadingHTTPServer for live use. This module extracts that pattern so the
scheduler and descheduler expose the exact same Prometheus exposition
format (and JSONL trace dumps) as the node agent — one scrape config for
the whole deployment.

koordexplain (PR 5) adds the decision surfaces: ``/explain?pod=<key>``
answers "why this node / why not at all" from the scheduler's latest
attribution, and ``/debug/flightrecorder`` serves the cycle flight
recorder (GET = status, POST = dump the ring as a JSONL bundle).
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse


def serve_handler(handle, port: int = 0):
    """Wrap a `(path, query[, method]) -> (status, content_type, body)`
    routing core in a ThreadingHTTPServer on 127.0.0.1; returns
    (server, thread). The one HTTP wrapper every handler-pattern server
    shares (ObsServer, KoordletServer) — fix transport behavior here, not
    per server. Handlers that accept a ``method`` parameter also receive
    POSTs; two-argument handlers stay GET-only (POST returns 405)."""
    import inspect
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    try:
        accepts_method = "method" in inspect.signature(handle).parameters
    except (TypeError, ValueError):
        accepts_method = False

    class Handler(BaseHTTPRequestHandler):
        def _route(self, method: str):
            url = urlparse(self.path)
            q = {k: v[0] for k, v in parse_qs(url.query).items()}
            if accepts_method:
                status, ctype, body = handle(url.path, q, method)
            elif method == "GET":
                status, ctype, body = handle(url.path, q)
            else:
                status, ctype, body = 405, "text/plain", "method not allowed"
            payload = body.encode()
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):  # noqa: N802 (stdlib API)
            self._route("GET")

        def do_POST(self):  # noqa: N802 (stdlib API)
            self._route("POST")

        def log_message(self, fmt, *args):  # silence
            pass

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, thread


class ObsServer:
    """Routing core for the observability endpoints.

    * ``/healthz`` — liveness; with a ``health_provider`` the body is its
      JSON payload (the scheduler reports last-completed-cycle age + wave
      count — a stale-cycle signal instead of a bare 200), else "ok"
    * ``/metrics`` — Prometheus text exposition from the given Registry
    * ``/traces``  — the tracer ring as JSONL (``?limit=N`` newest roots),
      replayable with ``python -m koordinator_tpu.obs``
    * ``/explain?pod=<key>`` — the pod's latest decision attribution
      (``explain_provider``: pod key -> record dict or None)
    * ``/debug/flightrecorder`` — GET: ring status; POST: dump the ring as
      a JSONL bundle (``flight``: an obs.flight.FlightRecorder)
    * ``/debug/timeline`` — the koordwatch device-window ring as a JSONL
      bundle (``timeline``: an obs.timeline.DeviceTimeline), replayable
      with ``python -m koordinator_tpu.obs timeline``
    * ``/debug/slo`` — the koordwatch SLO registry as a JSONL bundle
      (``slo``: an obs.slo.SloRegistry)
    """

    def __init__(self, metrics_registry=None, tracer=None,
                 health_provider=None, explain_provider=None, flight=None,
                 timeline=None, slo=None):
        self.metrics_registry = metrics_registry
        self.tracer = tracer
        self.health_provider = health_provider
        self.explain_provider = explain_provider
        self.flight = flight
        self.timeline = timeline
        self.slo = slo

    def handle(self, path: str, query: Optional[Dict[str, str]] = None,
               method: str = "GET") -> Tuple[int, str, str]:
        """(status, content_type, body)."""
        query = query or {}
        parts = [p for p in path.split("/") if p]
        if parts == ["healthz"]:
            if self.health_provider is not None:
                return (200, "application/json",
                        json.dumps(self.health_provider(), sort_keys=True))
            return 200, "text/plain", "ok"
        if parts == ["metrics"] and self.metrics_registry is not None:
            return (200, "text/plain; version=0.0.4",
                    self.metrics_registry.expose())
        if parts == ["traces"] and self.tracer is not None:
            raw = query.get("limit")
            if raw is None or raw == "":
                limit = None  # absent: the whole ring
            else:
                try:
                    limit = int(raw)
                except ValueError:
                    return 400, "text/plain", "limit must be an integer"
                if limit < 0:
                    return 400, "text/plain", "limit must be non-negative"
            body = self.tracer.export_jsonl(limit=limit)
            return 200, "application/x-ndjson", body
        if parts == ["explain"] and self.explain_provider is not None:
            pod = query.get("pod")
            if not pod:
                return (400, "text/plain",
                        "missing ?pod=<namespace/name> parameter")
            record = self.explain_provider(pod)
            if record is None:
                return (404, "application/json", json.dumps({
                    "pod": pod,
                    "error": "no decision recorded for this pod (not "
                             "scheduled since explain was enabled, or "
                             "KOORD_TPU_EXPLAIN is off)",
                }, sort_keys=True))
            return (200, "application/json",
                    json.dumps({"pod": pod, **record}, sort_keys=True))
        if parts == ["debug", "timeline"] and self.timeline is not None:
            return (200, "application/x-ndjson",
                    self.timeline.export_jsonl())
        if parts == ["debug", "slo"] and self.slo is not None:
            return (200, "application/x-ndjson", self.slo.export_jsonl())
        if parts == ["debug", "flightrecorder"] and self.flight is not None:
            if method == "POST":
                return (200, "application/x-ndjson",
                        self.flight.dump("http"))
            return (200, "application/json",
                    json.dumps(self.flight.status(), sort_keys=True))
        return 404, "text/plain", f"unknown path {path!r}"

    def serve(self, port: int = 0):
        """Start the HTTP server; returns (server, thread)."""
        return serve_handler(self.handle, port)
