"""koordwatch device timeline: the cross-consumer device-window record.

Three consumers serialize around one device — the scheduler's dispatch
kernels, the koordbalance rebalance pass, and the koordcolo control-plane
pass all upload through the same DeviceSnapshot — but until now nothing
recorded HOW they shared it: the idle gaps between consecutive windows
are exactly what the ROADMAP's host-tail and koordbalance-overlap items
promise to close, and without a timeline those items cannot be measured
before or after.

The :class:`DeviceTimeline` keeps a bounded, lock-guarded ring of
device-window records — consumer (scheduler/rebalance/colo), path
(serial/fused/chained/mesh), dispatch->last-sync wall interval, outcome
(clean/retried/demoted/deadline) — written from ``scheduler/cycle.py``'s
dispatch windows, ``balance/rebalancer.py`` and ``colo/reconciler.py``.
Each window mints a ``decision_id`` (``<consumer>-<seq>``, deterministic:
no wall clock or randomness in the id, so seeded runs stay byte-stable)
that the owners stamp through their closed loops — kernel spans, flight
records, migration-job -> Reservation annotations — so records can be
joined across the scheduler, descheduler and manager.

Exported surfaces:

  * ``koord_device_window_seconds{consumer,path}`` histogram +
    ``koord_device_idle_fraction`` gauge (gap time between consecutive
    windows over wall) — injected by the owner, the flight-recorder
    ``dump_counter`` pattern: this module never imports a registry;
  * ``/debug/timeline`` on every ObsServer serves the ring as a JSONL
    bundle (header line + one line per window, oldest first);
  * ``python -m koordinator_tpu.obs timeline <bundle>`` renders the
    waterfall; the schema is pinned by ``hack/lint.sh`` against
    ``tests/fixtures/timeline_golden.jsonl`` exactly like the trace and
    flight schemas.

Thread discipline (koordlint's concurrency rules gate this package): the
ring and the idle accumulators are lock-guarded — consumers record from
their own threads while the ObsServer thread exports.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

TIMELINE_SCHEMA_VERSION = 1
TIMELINE_SCHEMA_NAME = "koordwatch-timeline"

WINDOW_OUTCOMES = ("clean", "retried", "demoted", "deadline")
WINDOW_PATHS = ("serial", "fused", "chained", "mesh")


def watch_from_env() -> bool:
    """KOORD_TPU_WATCH=0 turns koordwatch off: the device-timeline ring
    stops recording and the demotion chokepoint stops accounting (ids
    keep minting so decision correlation stays wired). Default on — the
    bench A/B pair (koordwatch_overhead_pct) pins the cost ≤ ~2%. THE
    canonical read: the scheduler, the standalone rebalancer and the
    standalone colo reconciler all consult this one helper, so the kill
    switch covers every consumer's ring."""
    import os

    return os.environ.get("KOORD_TPU_WATCH", "1") != "0"


class DeviceWindow:
    """One in-flight device window: minted at ``open()``, stamped at the
    actual dispatch (``mark_dispatch``, re-stamped by ladder retries so
    the recorded interval is the SUCCESSFUL attempt's dispatch->sync
    wall), appended to the ring at ``close()``. A window that never
    completes (ladder exhausted, cycle exception) is simply dropped —
    the flight recorder owns failure records."""

    __slots__ = ("decision_id", "consumer", "path", "ts", "start_mono")

    def __init__(self, decision_id: str, consumer: str, path: str) -> None:
        self.decision_id = decision_id
        self.consumer = consumer
        self.path = path
        self.ts = time.time()
        self.start_mono = time.perf_counter()

    def mark_dispatch(self, path: Optional[str] = None) -> None:
        """Stamp the dispatch instant (and the effective path — a ladder
        demotion mid-pass can move mesh -> serial between attempts)."""
        if path is not None:
            self.path = path
        self.ts = time.time()
        self.start_mono = time.perf_counter()


class DeviceTimeline:
    """Bounded ring of device-window records + the idle accounting.

    ``window_histogram`` (labels consumer, path) and ``idle_gauge`` are
    optional injected metrics. ``enabled=False`` (the koordwatch kill
    switch / bench A/B off-world) turns ``close()`` into a no-op while
    ``mint()``/``open()`` keep handing out deterministic ids, so the
    decision-correlation plumbing never goes None-shaped."""

    def __init__(self, capacity: int = 512, window_histogram=None,
                 idle_gauge=None, enabled: bool = True) -> None:
        self._lock = threading.Lock()
        self._ring = deque(maxlen=capacity)
        self._seq = 0            # decision ids minted
        self._windows_total = 0  # windows ever closed (wraparound-visible)
        self.enabled = enabled
        self.window_histogram = window_histogram
        self.idle_gauge = idle_gauge
        # idle accounting: gap time between consecutive windows over the
        # wall interval first-start .. last-end (all monotonic)
        self._first_start: Optional[float] = None
        self._last_end: Optional[float] = None
        self._gap_total = 0.0

    # -- write side ------------------------------------------------------
    def mint(self, consumer: str) -> str:
        """A fresh decision id (``<consumer>-<seq>``). Deterministic per
        process history — seeded sim runs mint identical id sequences."""
        with self._lock:
            self._seq += 1
            return f"{consumer}-{self._seq}"

    def open(self, consumer: str, path: str) -> DeviceWindow:
        return DeviceWindow(self.mint(consumer), consumer, path)

    def close(self, window: DeviceWindow, outcome: str,
              end_mono: Optional[float] = None) -> Optional[dict]:
        """Complete a window: append the record, feed the histogram and
        the idle-fraction gauge. Returns the record (None when
        disabled)."""
        if not self.enabled:
            return None
        end = time.perf_counter() if end_mono is None else end_mono
        duration = max(0.0, end - window.start_mono)
        with self._lock:
            if self._first_start is None:
                self._first_start = window.start_mono
                gap = 0.0
            else:
                gap = max(0.0, window.start_mono - self._last_end)
                self._gap_total += gap
            self._last_end = (end if self._last_end is None
                              else max(self._last_end, end))
            wall = self._last_end - self._first_start
            idle = self._gap_total / wall if wall > 0 else 0.0
            self._windows_total += 1
            record = {
                "v": TIMELINE_SCHEMA_VERSION,
                "kind": "window",
                "seq": self._windows_total,
                "decision_id": window.decision_id,
                "consumer": window.consumer,
                "path": window.path,
                "outcome": outcome,
                "ts": float(window.ts),
                "duration_ms": duration * 1000.0,
                "gap_ms": gap * 1000.0,
            }
            self._ring.append(record)
        if self.window_histogram is not None:
            self.window_histogram.observe(
                duration, consumer=window.consumer, path=window.path)
        if self.idle_gauge is not None:
            self.idle_gauge.set(idle)
        return record

    # -- read side -------------------------------------------------------
    def snapshot(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._ring]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def idle_fraction(self) -> float:
        with self._lock:
            if self._first_start is None or self._last_end is None:
                return 0.0
            wall = self._last_end - self._first_start
            return self._gap_total / wall if wall > 0 else 0.0

    def export_jsonl(self) -> str:
        """The ``/debug/timeline`` body: header line + one line per
        window, oldest first — the bundle shape ``load_bundle`` below
        (and the ``obs timeline`` CLI) validates."""
        records = self.snapshot()
        header = {
            "v": TIMELINE_SCHEMA_VERSION,
            "kind": "header",
            "schema": TIMELINE_SCHEMA_NAME,
            "dumped_at": time.time(),
            "windows": len(records),
            "idle_fraction": self.idle_fraction(),
        }
        lines = [json.dumps(header, sort_keys=True)]
        lines.extend(json.dumps(r, sort_keys=True) for r in records)
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# bundle schema (the hack/lint.sh golden-fixture contract)
# ---------------------------------------------------------------------------


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_header(obj) -> List[str]:
    """Schema check for the bundle's first line."""
    if not isinstance(obj, dict):
        return ["header is not a JSON object"]
    errs: List[str] = []
    if obj.get("v") != TIMELINE_SCHEMA_VERSION:
        errs.append(f"v must be {TIMELINE_SCHEMA_VERSION}, "
                    f"got {obj.get('v')!r}")
    if obj.get("kind") != "header":
        errs.append(f"kind must be 'header', got {obj.get('kind')!r}")
    if obj.get("schema") != TIMELINE_SCHEMA_NAME:
        errs.append(f"schema must be {TIMELINE_SCHEMA_NAME!r}, "
                    f"got {obj.get('schema')!r}")
    if not _is_num(obj.get("dumped_at")) or obj.get("dumped_at") < 0:
        errs.append(f"dumped_at must be a non-negative number, "
                    f"got {obj.get('dumped_at')!r}")
    if not isinstance(obj.get("windows"), int) or isinstance(
            obj.get("windows"), bool) or obj.get("windows") < 0:
        errs.append(f"windows must be a non-negative int, "
                    f"got {obj.get('windows')!r}")
    idle = obj.get("idle_fraction")
    if not _is_num(idle) or idle < 0:
        errs.append(f"idle_fraction must be a non-negative number, "
                    f"got {idle!r}")
    return errs


def validate_window_record(obj) -> List[str]:
    """Schema check for one window line."""
    if not isinstance(obj, dict):
        return ["record is not a JSON object"]
    errs: List[str] = []
    if obj.get("v") != TIMELINE_SCHEMA_VERSION:
        errs.append(f"v must be {TIMELINE_SCHEMA_VERSION}, "
                    f"got {obj.get('v')!r}")
    if obj.get("kind") != "window":
        errs.append(f"kind must be 'window', got {obj.get('kind')!r}")
    if not isinstance(obj.get("seq"), int) or isinstance(
            obj.get("seq"), bool) or obj.get("seq") < 0:
        errs.append(f"seq must be a non-negative int, got {obj.get('seq')!r}")
    for key in ("decision_id", "consumer"):
        if not isinstance(obj.get(key), str) or not obj.get(key):
            errs.append(f"{key} must be a non-empty string, "
                        f"got {obj.get(key)!r}")
    if obj.get("path") not in WINDOW_PATHS:
        errs.append(f"path must be one of {WINDOW_PATHS}, "
                    f"got {obj.get('path')!r}")
    if obj.get("outcome") not in WINDOW_OUTCOMES:
        errs.append(f"outcome must be one of {WINDOW_OUTCOMES}, "
                    f"got {obj.get('outcome')!r}")
    for key in ("ts", "duration_ms", "gap_ms"):
        if not _is_num(obj.get(key)) or obj.get(key) < 0:
            errs.append(f"{key} must be a non-negative number, "
                        f"got {obj.get(key)!r}")
    return errs


def load_bundle(lines) -> Tuple[Optional[dict], List[dict], List[str]]:
    """Parse + validate a timeline bundle; returns (header, windows,
    errors). The contract ``hack/lint.sh`` pins against the golden
    fixture: any error-list growth is schema drift and must be a
    conscious version bump."""
    from koordinator_tpu.obs import load_jsonl_bundle

    return load_jsonl_bundle(lines, validate_header=validate_header,
                             validate_record=validate_window_record,
                             count_key="windows")
