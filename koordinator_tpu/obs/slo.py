"""koordwatch SLO engine: named objectives with burn-rate computation.

SLO accounting used to be ad-hoc fields scattered through
``sim/harness.py`` — ttb percentiles here, restart recovery there, colo
staleness and hotspot dissipation in their own blocks — each with its own
copy of the percentile/target/met arithmetic and nothing exported live.
The :class:`SloRegistry` makes an objective first-class: a name, a unit,
a target, the gating percentile (99 for tail objectives, 100 for
max-gated ones), the observed samples, and the derived numbers every
consumer needs — observed value at the percentile, overrun count, the
burn rate (observed/target: 1.0 is exactly on budget, 2.0 is burning the
error budget twice as fast as allowed) and the default met verdict
(vacuously true with no samples; ``observed <= target`` otherwise —
objectives with scenario-specific met rules compose them from these
stats, see ``sim/harness.SimReport.to_dict``).

Exported surfaces:

  * ``koord_slo_burn_rate{slo}`` / ``koord_slo_met{slo}`` gauges —
    injected by the owner (the flight-recorder ``dump_counter`` pattern:
    this module never imports a registry), refreshed on every observe;
  * ``/debug/slo`` on the ObsServer serves the registry as a JSONL
    bundle (header line + one line per objective);
  * ``python -m koordinator_tpu.obs slo <bundle>`` validates + renders;
    the schema is pinned by ``hack/lint.sh`` against
    ``tests/fixtures/slo_golden.jsonl`` exactly like the trace, flight
    and timeline schemas.

Thread discipline (koordlint's concurrency rules gate this package):
sample lists are lock-guarded — owners observe from their work threads
while the ObsServer thread exports.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

SLO_SCHEMA_VERSION = 1
SLO_SCHEMA_NAME = "koordwatch-slo"


class SloObjective:
    """One named objective. ``target <= 0`` means report-only (burn rate
    0, always met) — the sim scenarios' convention."""

    def __init__(self, name: str, target: float, percentile: float = 99.0,
                 unit: str = "seconds") -> None:
        self.name = name
        self.target = float(target)
        self.percentile = float(percentile)
        self.unit = unit
        self.samples: List[float] = []
        self.overruns = 0
        self._max: Optional[float] = None  # running max: O(1) observed()
        #                                    for max-gated objectives

    def add(self, value: float) -> None:
        """One observation (running max + overrun accounting in one
        place; the registry calls this under its lock)."""
        value = float(value)
        self.samples.append(value)
        if self._max is None or value > self._max:
            self._max = value
        if self.target > 0 and value > self.target:
            self.overruns += 1

    # -- stats (all pure reads over the sample list) --------------------
    def count(self) -> int:
        return len(self.samples)

    def quantile(self, q: float) -> float:
        if not self.samples:
            return 0.0
        return float(np.percentile(np.asarray(self.samples), q))

    def observed(self) -> float:
        """The value at the gating percentile (100 = max)."""
        if not self.samples:
            return 0.0
        if self.percentile >= 100.0:
            return self.maximum()
        return self.quantile(self.percentile)

    def maximum(self) -> float:
        return self._max if self._max is not None else 0.0

    def mean(self) -> float:
        return float(np.mean(self.samples)) if self.samples else 0.0

    def burn_rate(self) -> float:
        if self.target <= 0 or not self.samples:
            return 0.0
        return self.observed() / self.target

    def met(self) -> bool:
        """Default verdict: vacuously true with no samples, else
        ``observed <= target``. Report-only objectives (target <= 0)
        are always met."""
        if self.target <= 0 or not self.samples:
            return True
        return self.observed() <= self.target

    def to_record(self) -> dict:
        """One export record. The observed value is computed ONCE and
        burn/met derived from it, so a record can never contradict
        itself (e.g. met=true with burn>1) even if read while samples
        land — the registry additionally builds records under its lock
        for a consistent multi-objective export."""
        observed = self.observed()
        has_samples = bool(self.samples)
        return {
            "v": SLO_SCHEMA_VERSION,
            "kind": "slo",
            "slo": self.name,
            "unit": self.unit,
            "target": self.target,
            "percentile": self.percentile,
            "count": len(self.samples),
            "observed": observed,
            "burn_rate": (observed / self.target
                          if self.target > 0 and has_samples else 0.0),
            "met": (self.target <= 0 or not has_samples
                    or observed <= self.target),
            "overruns": self.overruns,
        }


class SloRegistry:
    """Named objectives + live gauge export + the ``/debug/slo`` dump."""

    def __init__(self, burn_gauge=None, met_gauge=None) -> None:
        self._lock = threading.Lock()
        self._objectives: Dict[str, SloObjective] = {}
        self.burn_gauge = burn_gauge
        self.met_gauge = met_gauge

    def register(self, name: str, target: float, percentile: float = 99.0,
                 unit: str = "seconds") -> SloObjective:
        with self._lock:
            if name in self._objectives:
                raise ValueError(f"SLO {name!r} already registered")
            obj = SloObjective(name, target, percentile=percentile,
                               unit=unit)
            self._objectives[name] = obj
        self._refresh(obj)
        return obj

    # percentile-gated gauges refresh at most every Nth sample (plus on
    # every overrun, when the met verdict can actually flip, and on
    # export): a full np.percentile per observation would make the
    # owner's hot path — once per bound pod in the sim — quadratic
    _REFRESH_EVERY = 16

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            obj = self._objectives[name]
            overruns0 = obj.overruns
            obj.add(value)
            force = obj.overruns != overruns0
        self._refresh(obj, force=force)

    def observe_many(self, name: str, values: Iterable[float]) -> None:
        values = list(values)
        with self._lock:
            obj = self._objectives[name]
            for v in values:
                obj.add(v)
        self._refresh(obj, force=True)

    def _refresh(self, obj: SloObjective, force: bool = False) -> None:
        """Move the injected gauges for one objective (outside the
        registry lock: gauges carry their own). The observed value is
        computed ONCE and reused for both gauges; max-gated objectives
        are O(1) via the running max, and percentile-gated ones
        throttle to every ``_REFRESH_EVERY``th sample unless forced
        (an overrun / a bulk observe / a registration)."""
        if self.burn_gauge is None and self.met_gauge is None:
            return
        if (not force and obj.percentile < 100.0
                and len(obj.samples) % self._REFRESH_EVERY):
            return
        observed = obj.observed()
        has_samples = bool(obj.samples)
        if self.burn_gauge is not None:
            burn = (observed / obj.target
                    if obj.target > 0 and has_samples else 0.0)
            self.burn_gauge.set(burn, slo=obj.name)
        if self.met_gauge is not None:
            met = (obj.target <= 0 or not has_samples
                   or observed <= obj.target)
            self.met_gauge.set(1.0 if met else 0.0, slo=obj.name)

    # -- read side -------------------------------------------------------
    def objective(self, name: str) -> Optional[SloObjective]:
        with self._lock:
            return self._objectives.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._objectives)

    def snapshot(self) -> Dict[str, dict]:
        # records built UNDER the lock: an owner observing mid-export
        # must not tear count/observed/met across objectives
        with self._lock:
            return {o.name: o.to_record()
                    for o in self._objectives.values()}

    def export_jsonl(self) -> str:
        """The ``/debug/slo`` body: header line + one line per
        objective, registration order (records built under the lock —
        see snapshot)."""
        with self._lock:
            records = [o.to_record() for o in self._objectives.values()]
        header = {
            "v": SLO_SCHEMA_VERSION,
            "kind": "header",
            "schema": SLO_SCHEMA_NAME,
            "dumped_at": time.time(),
            "slos": len(records),
        }
        lines = [json.dumps(header, sort_keys=True)]
        lines.extend(json.dumps(r, sort_keys=True) for r in records)
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# bundle schema (the hack/lint.sh golden-fixture contract)
# ---------------------------------------------------------------------------


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_header(obj) -> List[str]:
    if not isinstance(obj, dict):
        return ["header is not a JSON object"]
    errs: List[str] = []
    if obj.get("v") != SLO_SCHEMA_VERSION:
        errs.append(f"v must be {SLO_SCHEMA_VERSION}, got {obj.get('v')!r}")
    if obj.get("kind") != "header":
        errs.append(f"kind must be 'header', got {obj.get('kind')!r}")
    if obj.get("schema") != SLO_SCHEMA_NAME:
        errs.append(f"schema must be {SLO_SCHEMA_NAME!r}, "
                    f"got {obj.get('schema')!r}")
    if not _is_num(obj.get("dumped_at")) or obj.get("dumped_at") < 0:
        errs.append(f"dumped_at must be a non-negative number, "
                    f"got {obj.get('dumped_at')!r}")
    if not isinstance(obj.get("slos"), int) or isinstance(
            obj.get("slos"), bool) or obj.get("slos") < 0:
        errs.append(f"slos must be a non-negative int, "
                    f"got {obj.get('slos')!r}")
    return errs


def validate_slo_record(obj) -> List[str]:
    if not isinstance(obj, dict):
        return ["record is not a JSON object"]
    errs: List[str] = []
    if obj.get("v") != SLO_SCHEMA_VERSION:
        errs.append(f"v must be {SLO_SCHEMA_VERSION}, got {obj.get('v')!r}")
    if obj.get("kind") != "slo":
        errs.append(f"kind must be 'slo', got {obj.get('kind')!r}")
    for key in ("slo", "unit"):
        if not isinstance(obj.get(key), str) or not obj.get(key):
            errs.append(f"{key} must be a non-empty string, "
                        f"got {obj.get(key)!r}")
    # target may legitimately be <= 0 (report-only objectives)
    if not _is_num(obj.get("target")):
        errs.append(f"target must be a number, got {obj.get('target')!r}")
    pct = obj.get("percentile")
    if not _is_num(pct) or not (0 < pct <= 100):
        errs.append(f"percentile must be in (0, 100], got {pct!r}")
    for key in ("observed", "burn_rate"):
        if not _is_num(obj.get(key)) or obj.get(key) < 0:
            errs.append(f"{key} must be a non-negative number, "
                        f"got {obj.get(key)!r}")
    for key in ("count", "overruns"):
        if not isinstance(obj.get(key), int) or isinstance(
                obj.get(key), bool) or obj.get(key) < 0:
            errs.append(f"{key} must be a non-negative int, "
                        f"got {obj.get(key)!r}")
    if not isinstance(obj.get("met"), bool):
        errs.append(f"met must be a bool, got {obj.get('met')!r}")
    return errs


def load_bundle(lines) -> Tuple[Optional[dict], List[dict], List[str]]:
    """Parse + validate an SLO bundle; returns (header, records,
    errors)."""
    from koordinator_tpu.obs import load_jsonl_bundle

    return load_jsonl_bundle(lines, validate_header=validate_header,
                             validate_record=validate_slo_record,
                             count_key="slos")
