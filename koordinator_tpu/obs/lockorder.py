"""The canonical lock order for the co-located device consumers.

Three consumers share one device view: the scheduler's dispatch path,
the balance rebalancer, and the colo reconciler all touch the
DeviceSnapshot mirror, record their kernel windows on the DeviceTimeline
ring, and feed the metrics registry. Any code path that needs more than
one of those locks MUST acquire them in the order declared below —
outer first — and release before re-acquiring an earlier one. koordlint
(`lock-order-inversion` in analysis/rules/race.py) enforces the order AS
DECLARED HERE: it parses this tuple from source and errors on any
acquisition edge that contradicts it, so the order cannot silently
drift to whatever the newest caller happened to nest. The racecheck
harness (sim/racecheck.py) imports it at runtime and records a witness
when live threads nest against it.

Entries are ``ClassName.attr`` lock names:

1. ``DeviceSnapshot._lock`` — the device mirror's dispatch-window
   ledger (scheduler/snapshot_cache.py). Outermost because the mirror
   brackets whole kernel windows: while it is held the holder may still
   mint/close timeline windows and bump metrics.
2. ``DeviceTimeline._lock`` — the koordwatch window ring
   (obs/timeline.py). Feeds gauges/histograms, so it precedes the
   registry locks; timeline.close() deliberately observes its
   histograms AFTER releasing the ring lock, which trivially satisfies
   the order and keeps the ring lock narrow.
3. ``Registry._lock`` — the metrics registry's family table
   (koordlet/metrics.py).
4. ``_Metric._lock`` — a single metric family's series map. Innermost:
   never call out of a metric while holding it.

Locks NOT listed here (tracer ring, SLO registry, flight ring, store,
warm-up ladder…) are intentionally unordered against each other; the
analyzer still rejects cycles among them.
"""

from __future__ import annotations

CANONICAL_LOCK_ORDER = (
    "DeviceSnapshot._lock",
    "DeviceTimeline._lock",
    "Registry._lock",
    "_Metric._lock",
)
