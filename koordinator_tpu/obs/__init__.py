"""koordtrace: span-based cycle tracing for every koordinator binary.

Analog of the reference's `k8s.io/utils/trace` plumbing in
`pkg/scheduler/frameworkext/debug.go` plus the client_golang histogram
vectors the Go components hang off every hot loop: a `Tracer` produces
nested `Span`s (wall-clock start + monotonic duration), finished root
spans land in a bounded in-memory ring (the `koordlet/audit.py` ring
discipline), and the whole ring exports as JSONL — one line per span,
parent-linked — so an operator can dump `/traces` from a live binary and
replay the latency waterfall with `python -m koordinator_tpu.obs`.

Why spans and not just timers: the batched-tensor design introduces one
pathology the reference cannot have — an XLA recompile on a shape-signature
cache miss — and a flat cycle timer cannot distinguish "kernel was slow"
from "we recompiled" from "the store patch loop dragged". The span tree
makes the per-stage split (snapshot build, tensor encode, compile vs
execute, host-side bind work) first-class.

Thread discipline: the span stack is thread-local (each thread traces its
own tree); the finished-root ring is shared and lock-guarded. koordlint's
concurrency rules gate this package — no unlocked shared mutation.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

TRACE_SCHEMA_VERSION = 1


@dataclass
class Span:
    """One timed operation. `start_unix` is wall clock (for cross-host
    correlation), `start_mono`/`duration_seconds` are monotonic (immune to
    clock steps — offsets inside a trace always use these)."""

    name: str
    trace_id: int
    span_id: int
    parent_id: Optional[int]
    start_unix: float
    start_mono: float
    duration_seconds: float = 0.0
    attributes: Dict[str, str] = field(default_factory=dict)
    children: List["Span"] = field(default_factory=list)

    def walk(self) -> Iterator["Span"]:
        """This span and every descendant, depth-first in start order."""
        yield self
        for child in self.children:
            yield from child.walk()

    def find(self, name: str) -> Optional["Span"]:
        """First descendant (or self) with the given name."""
        for span in self.walk():
            if span.name == name:
                return span
        return None

    def find_all(self, name: str) -> List["Span"]:
        return [s for s in self.walk() if s.name == name]

    def to_record(self) -> Dict[str, object]:
        """The JSONL wire record for this single span (children are their
        own lines, linked by `parent`)."""
        return {
            "v": TRACE_SCHEMA_VERSION,
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "start_unix": self.start_unix,
            "start_mono": self.start_mono,
            "duration_ms": self.duration_seconds * 1000.0,
            "attrs": dict(self.attributes),
        }


def validate_record(obj: object) -> List[str]:
    """Schema check for one decoded JSONL line; returns human-readable
    errors (empty = valid). This is the contract `hack/lint.sh` pins with
    the golden fixture: drift here must be a conscious version bump."""
    if not isinstance(obj, dict):
        return ["record is not a JSON object"]
    errs: List[str] = []
    if obj.get("v") != TRACE_SCHEMA_VERSION:
        errs.append(f"v must be {TRACE_SCHEMA_VERSION}, got {obj.get('v')!r}")
    for key in ("trace", "span"):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool):
            errs.append(f"{key} must be an int, got {v!r}")
    parent = obj.get("parent", "MISSING")
    if parent is not None and (not isinstance(parent, int)
                               or isinstance(parent, bool)):
        errs.append(f"parent must be an int or null, got {parent!r}")
    if not (isinstance(obj.get("name"), str) and obj["name"]):
        errs.append(f"name must be a non-empty string, got {obj.get('name')!r}")
    for key in ("start_unix", "start_mono", "duration_ms"):
        v = obj.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
            errs.append(f"{key} must be a non-negative number, got {v!r}")
    attrs = obj.get("attrs")
    if not isinstance(attrs, dict):
        errs.append(f"attrs must be an object, got {attrs!r}")
    else:
        for k, v in attrs.items():
            if not isinstance(k, str) or not isinstance(v, str):
                errs.append(f"attrs entries must be string->string, "
                            f"got {k!r}: {v!r}")
    return errs


def load_jsonl_bundle(lines, *, validate_header, validate_record,
                      count_key: str):
    """Shared bundle parser for every JSONL dump format in this package
    (flight, timeline, slo): line 1 validates as the header, every
    further line as a record, and the header's ``count_key`` field must
    match the record count. Returns (header, records, errors) — the
    golden-fixture contract each format's ``load_bundle`` pins."""
    header = None
    records: List[dict] = []
    errors: List[str] = []
    seen_any = False
    for lineno, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as exc:
            errors.append(f"line {lineno}: invalid JSON ({exc})")
            continue
        if not seen_any:
            seen_any = True
            errs = validate_header(obj)
            if errs:
                errors.extend(f"line {lineno}: {e}" for e in errs)
            else:
                header = obj
            continue
        errs = validate_record(obj)
        if errs:
            errors.extend(f"line {lineno}: {e}" for e in errs)
        else:
            records.append(obj)
    if not seen_any:
        errors.append("empty bundle: missing header line")
    elif header is not None and header[count_key] != len(records) and (
            not errors):
        errors.append(
            f"header says {header[count_key]} {count_key}, "
            f"found {len(records)}")
    return header, records, errors


class Tracer:
    """Nested-span tracer with a bounded finished-root ring.

    `span(...)` is a context manager; nesting follows the thread-local
    stack, so `with tracer.span("cycle"): with tracer.span("kernel"): ...`
    yields kernel as a child of cycle with zero plumbing at call sites.
    A root span (no parent on this thread) is committed to the ring when
    it closes; children travel inside their root.

    Memory is bounded on BOTH axes (audit.py discipline): the ring keeps
    at most `capacity` roots, and each trace retains at most
    `max_spans_per_trace` spans. Only spans at depth >= 2 (per-item work:
    `bind_pod` and below on a 10k-pod cycle) count against the budget —
    the root and its direct children are the per-stage skeleton, bounded
    by instrumentation sites rather than cluster size, and must survive
    even when a huge pre-pass burns the budget first. Spans beyond the
    budget are timed but not retained; the root reports how many via a
    `dropped_spans` attribute.
    """

    def __init__(self, capacity: int = 256, max_spans_per_trace: int = 512):
        from collections import deque

        self._lock = threading.Lock()
        self._ring = deque(maxlen=capacity)  # of root Spans
        self._max_spans = max_spans_per_trace
        self._seq = 0  # total roots ever committed (wraparound-visible)
        self._ids = itertools.count(1)  # atomic under the GIL
        self._local = threading.local()

    @contextmanager
    def span(self, name: str, **attributes: str):
        stack: List[Span] = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        parent = stack[-1] if stack else None
        if parent is None:
            self._local.retained = 0
            self._local.dropped = 0
        # depth 0/1 = the per-stage skeleton, always retained; the budget
        # gates only per-item depth (>= 2), so a huge pre-pass can never
        # evict the snapshot/encode/kernel/bind split
        over_budget = (len(stack) >= 2
                       and self._local.retained >= self._max_spans)
        if over_budget:
            self._local.dropped += 1
        elif len(stack) >= 2:
            self._local.retained += 1  # skeleton spans don't consume budget
        span_id = next(self._ids)
        sp = Span(
            name=name,
            trace_id=parent.trace_id if parent is not None else span_id,
            span_id=span_id,
            parent_id=parent.span_id if parent is not None else None,
            start_unix=time.time(),
            start_mono=time.perf_counter(),
            attributes={k: str(v) for k, v in attributes.items()},
        )
        stack.append(sp)
        try:
            yield sp
        except BaseException as exc:
            sp.attributes.setdefault("error", type(exc).__name__)
            raise
        finally:
            sp.duration_seconds = time.perf_counter() - sp.start_mono
            stack.pop()
            if parent is not None:
                if not over_budget:
                    parent.children.append(sp)
            else:
                if self._local.dropped:
                    sp.attributes["dropped_spans"] = str(self._local.dropped)
                self._commit_root(sp)

    def _commit_root(self, root: Span) -> None:
        with self._lock:
            self._seq += 1
            self._ring.append(root)  # deque maxlen evicts the oldest

    # -- read side -------------------------------------------------------
    @property
    def seq(self) -> int:
        """Total root spans ever committed (> len(ring) after wraparound)."""
        with self._lock:
            return self._seq

    def roots(self, limit: Optional[int] = None) -> List[Span]:
        """Finished root spans, oldest first. `limit` keeps the newest N;
        an explicit 0 means zero roots, None means everything."""
        with self._lock:
            ring = list(self._ring)
        if limit is None:
            return ring
        return ring[-limit:] if limit > 0 else []

    def export_jsonl(self, limit: Optional[int] = None) -> str:
        """The ring flattened to JSONL: one line per span, depth-first per
        trace — the `/traces` body and the CLI's input format."""
        lines = []
        for root in self.roots(limit=limit):
            for span in root.walk():
                lines.append(json.dumps(span.to_record(), sort_keys=True))
        return "\n".join(lines) + ("\n" if lines else "")

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)
