"""Trace replay + koordexplain + koordwatch CLI.

    python -m koordinator_tpu.obs trace.jsonl            # span waterfall
    curl -s localhost:9090/traces | python -m koordinator_tpu.obs -
    python -m koordinator_tpu.obs flight bundle.jsonl    # validate bundle
    python -m koordinator_tpu.obs explain bundle.jsonl ns/pod
    python -m koordinator_tpu.obs timeline timeline.jsonl  # device waterfall
    python -m koordinator_tpu.obs slo slo.jsonl            # SLO table

Each trace renders as an indented latency waterfall — bar offset is the
span's monotonic start relative to its root, bar length its share of the
root's duration — so "where did the cycle spend its time" is answerable
from a terminal with no tooling.

``flight`` validates a flight-recorder bundle (obs/flight.py) against its
schema and prints a per-cycle summary; ``explain`` renders the stage-by-
stage verdict table for one pod from the newest cycle record that carries
it — the offline twin of the live ``/explain?pod=`` endpoint.

``timeline`` validates a koordwatch device-timeline bundle
(obs/timeline.py, the ``/debug/timeline`` body) and renders the
cross-consumer device waterfall — one bar per window, offset by its
idle gap, so "who had the device and when" is answerable from a
terminal. ``slo`` validates an SLO bundle (obs/slo.py, the
``/debug/slo`` body) and renders the objective table with burn rates.

Exit codes (the `hack/lint.sh` golden-fixture contract, all subcommands):
  0  every record parsed and validated (explain: pod found)
  1  schema drift: bad JSON, missing/mistyped fields, dangling parent ids
     (explain: pod absent from the bundle)
  2  usage error (unreadable input)
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

from koordinator_tpu.obs import validate_record


def load_records(lines) -> Tuple[List[dict], List[str]]:
    records: List[dict] = []
    errors: List[str] = []
    for lineno, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as exc:
            errors.append(f"line {lineno}: invalid JSON ({exc})")
            continue
        errs = validate_record(obj)
        if errs:
            errors.extend(f"line {lineno}: {e}" for e in errs)
            continue
        records.append(obj)
    return records, errors


def build_traces(records: List[dict]
                 ) -> Tuple[List[Tuple[dict, Dict[int, List[dict]]]], List[str]]:
    """Group records into (root, children_by_parent) per trace id."""
    errors: List[str] = []
    by_trace: Dict[int, List[dict]] = {}
    for rec in records:
        by_trace.setdefault(rec["trace"], []).append(rec)
    traces = []
    for trace_id, spans in sorted(by_trace.items()):
        ids = {s["span"] for s in spans}
        roots = [s for s in spans if s["parent"] is None]
        for s in spans:
            if s["parent"] is not None and s["parent"] not in ids:
                errors.append(
                    f"trace {trace_id}: span {s['span']} ({s['name']!r}) "
                    f"has dangling parent {s['parent']}")
        if len(roots) != 1:
            errors.append(
                f"trace {trace_id}: expected exactly 1 root span, "
                f"got {len(roots)}")
            continue
        children: Dict[int, List[dict]] = {}
        for s in spans:
            if s["parent"] is not None:
                children.setdefault(s["parent"], []).append(s)
        for sibs in children.values():
            sibs.sort(key=lambda s: s["start_mono"])
        traces.append((roots[0], children))
    return traces, errors


def _bar(offset_ms: float, dur_ms: float, total_ms: float, width: int) -> str:
    if total_ms <= 0:
        return " " * width
    scale = width / total_ms
    lead = min(width - 1, int(round(offset_ms * scale)))
    length = max(1, int(round(dur_ms * scale)))
    length = min(length, width - lead)
    return " " * lead + "█" * length + " " * (width - lead - length)


def render_trace(root: dict, children: Dict[int, List[dict]],
                 width: int = 40, out=sys.stdout) -> None:
    total_ms = root["duration_ms"]
    n_spans = 1 + sum(len(v) for v in children.values())
    out.write(f"trace {root['trace']} · {root['name']} · "
              f"{total_ms:.2f}ms · {n_spans} spans\n")
    name_width = max(
        (len(s["name"]) + 2 * depth
         for s, depth in _walk(root, children)), default=0)

    for span, depth in _walk(root, children):
        offset_ms = (span["start_mono"] - root["start_mono"]) * 1000.0
        label = "  " * depth + span["name"]
        attrs = "".join(
            f" {k}={v}" for k, v in sorted(span["attrs"].items()))
        out.write(
            f"  {label:<{name_width}} "
            f"|{_bar(offset_ms, span['duration_ms'], total_ms, width)}| "
            f"{span['duration_ms']:8.2f}ms{attrs}\n")


def _walk(root: dict, children: Dict[int, List[dict]], depth: int = 0):
    yield root, depth
    for child in children.get(root["span"], []):
        yield from _walk(child, children, depth + 1)


def _read_lines(path: str) -> Optional[List[str]]:
    if path == "-":
        return sys.stdin.readlines()
    try:
        with open(path) as f:
            return f.readlines()
    except OSError as exc:
        print(f"cannot read {path!r}: {exc}", file=sys.stderr)
        return None


def flight_main(argv: List[str]) -> int:
    """`flight <bundle>`: schema-validate + summarize a flight bundle."""
    from koordinator_tpu.obs.flight import load_bundle

    ap = argparse.ArgumentParser(
        prog="python -m koordinator_tpu.obs flight",
        description="validate and summarize a flight-recorder JSONL bundle")
    ap.add_argument("bundle", help="flight bundle file, or '-' for stdin")
    args = ap.parse_args(argv)
    lines = _read_lines(args.bundle)
    if lines is None:
        return 2
    header, records, errors = load_bundle(lines)
    if errors:
        for err in errors:
            print(f"schema error: {err}", file=sys.stderr)
        return 1
    print(f"flight bundle · reason={header['reason']} · "
          f"{header['cycles']} cycles")
    for rec in records:
        err = f" error={rec['error']!r}" if rec.get("error") else ""
        print(f"  cycle {rec['seq']}: {rec['duration_ms']:.2f}ms "
              f"waves={rec['waves']} bound={len(rec['bound'])} "
              f"failed={len(rec['failed'])} "
              f"rejected={len(rec['rejected'])}{err}")
    return 0


def explain_main(argv: List[str]) -> int:
    """`explain <bundle> <pod>`: the pod's stage-by-stage verdict table
    from the newest flight-bundle cycle that carries it."""
    from koordinator_tpu.obs.flight import load_bundle

    ap = argparse.ArgumentParser(
        prog="python -m koordinator_tpu.obs explain",
        description="render one pod's decision attribution from a "
                    "flight-recorder bundle")
    ap.add_argument("bundle", help="flight bundle file, or '-' for stdin")
    ap.add_argument("pod", help="pod key (namespace/name)")
    args = ap.parse_args(argv)
    lines = _read_lines(args.bundle)
    if lines is None:
        return 2
    _header, records, errors = load_bundle(lines)
    if errors:
        for err in errors:
            print(f"schema error: {err}", file=sys.stderr)
        return 1
    hit = None
    for rec in records:  # newest record (bundle is oldest-first) wins
        for field in ("bound", "failed", "rejected"):
            for entry in rec[field]:
                if entry["pod"] == args.pod:
                    hit = (rec, field, entry)
    if hit is None:
        print(f"pod {args.pod!r} not found in any bundle cycle",
              file=sys.stderr)
        return 1
    rec, field, entry = hit
    verdict = "bound" if field == "bound" else f"unbound ({field})"
    print(f"pod {args.pod} · cycle {rec['seq']} · verdict: {verdict}")
    if field == "bound":
        print(f"  node: {entry['node']}")
        terms = entry.get("terms")
        if terms:
            width = max(len(k) for k in terms)
            for name, value in terms.items():
                print(f"  {name:<{width}}  {value:g}")
            if "best_score" in terms and "runner_up" in terms:
                print(f"  {'margin':<{width}}  "
                      f"{terms['best_score'] - terms['runner_up']:g}")
    else:
        if entry.get("reason"):
            print(f"  reason: {entry['reason']}")
        stages = entry.get("stages")
        if stages:
            width = max(len(k) for k in stages)
            print("  stage" + " " * (max(width - 5, 0) + 2)
                  + "rejected nodes")
            for name, count in sorted(stages.items(),
                                      key=lambda kv: -kv[1]):
                print(f"  {name:<{width}}  {count}")
        if entry.get("message"):
            print(f"  message: {entry['message']}")
    return 0


def timeline_main(argv: List[str]) -> int:
    """`timeline <bundle>`: schema-validate + render the device-window
    waterfall of a koordwatch timeline bundle."""
    from koordinator_tpu.obs.timeline import load_bundle

    ap = argparse.ArgumentParser(
        prog="python -m koordinator_tpu.obs timeline",
        description="validate and render a koordwatch device-timeline "
                    "JSONL bundle as a cross-consumer waterfall")
    ap.add_argument("bundle", help="timeline bundle file, or '-' for stdin")
    ap.add_argument("--width", type=int, default=40,
                    help="waterfall bar width in characters")
    args = ap.parse_args(argv)
    lines = _read_lines(args.bundle)
    if lines is None:
        return 2
    header, records, errors = load_bundle(lines)
    if errors:
        for err in errors:
            print(f"schema error: {err}", file=sys.stderr)
        return 1
    print(f"device timeline · {header['windows']} windows · "
          f"idle fraction {header['idle_fraction']:.3f}")
    if not records:
        return 0
    width = max(10, args.width)
    # the waterfall axis: gap-prefixed windows laid end to end
    offsets, cursor = [], 0.0
    for rec in records:
        cursor += rec["gap_ms"]
        offsets.append(cursor)
        cursor += rec["duration_ms"]
    total = cursor or 1.0
    label_w = max(len(f"{r['consumer']}/{r['path']}") for r in records)
    id_w = max(len(r["decision_id"]) for r in records)
    for rec, off in zip(records, offsets):
        label = f"{rec['consumer']}/{rec['path']}"
        print(f"  {rec['decision_id']:<{id_w}} {label:<{label_w}} "
              f"|{_bar(off, rec['duration_ms'], total, width)}| "
              f"{rec['duration_ms']:8.2f}ms gap {rec['gap_ms']:8.2f}ms "
              f"{rec['outcome']}")
    return 0


def slo_main(argv: List[str]) -> int:
    """`slo <bundle>`: schema-validate + render a koordwatch SLO bundle
    as the objective table."""
    from koordinator_tpu.obs.slo import load_bundle

    ap = argparse.ArgumentParser(
        prog="python -m koordinator_tpu.obs slo",
        description="validate and render a koordwatch SLO JSONL bundle")
    ap.add_argument("bundle", help="SLO bundle file, or '-' for stdin")
    args = ap.parse_args(argv)
    lines = _read_lines(args.bundle)
    if lines is None:
        return 2
    header, records, errors = load_bundle(lines)
    if errors:
        for err in errors:
            print(f"schema error: {err}", file=sys.stderr)
        return 1
    print(f"slo registry · {header['slos']} objectives")
    if not records:
        return 0
    name_w = max(len(r["slo"]) for r in records)
    for rec in records:
        pct = ("max" if rec["percentile"] >= 100
               else f"p{rec['percentile']:g}")
        verdict = "MET" if rec["met"] else "BLOWN"
        print(f"  {rec['slo']:<{name_w}}  {pct:>4} "
              f"{rec['observed']:10.3f} / {rec['target']:g} {rec['unit']} "
              f"· burn {rec['burn_rate']:.2f} · {rec['count']} samples "
              f"({rec['overruns']} overruns) · {verdict}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    # subcommand dispatch keeps the historical `obs <trace.jsonl>` call
    # shape working (hack/lint.sh pins it against the golden fixture)
    if argv and argv[0] == "flight":
        return flight_main(argv[1:])
    if argv and argv[0] == "explain":
        return explain_main(argv[1:])
    if argv and argv[0] == "timeline":
        return timeline_main(argv[1:])
    if argv and argv[0] == "slo":
        return slo_main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="python -m koordinator_tpu.obs",
        description="replay a koordtrace JSONL dump as a latency waterfall")
    ap.add_argument("trace", help="JSONL trace file, or '-' for stdin")
    ap.add_argument("--width", type=int, default=40,
                    help="waterfall bar width in characters")
    args = ap.parse_args(argv)

    lines = _read_lines(args.trace)
    if lines is None:
        return 2

    records, errors = load_records(lines)
    traces, tree_errors = build_traces(records)
    errors.extend(tree_errors)
    if errors:
        for err in errors:
            print(f"schema error: {err}", file=sys.stderr)
        return 1
    if not records:
        print("no spans in input", file=sys.stderr)
        return 0
    try:
        for i, (root, children) in enumerate(traces):
            if i:
                print()
            render_trace(root, children, width=max(10, args.width))
    except BrokenPipeError:
        # downstream pager/head closed the pipe mid-waterfall: normal CLI
        # usage, not an error; hand stdout a sink so interpreter shutdown
        # doesn't print a second traceback flushing the dead pipe
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
