"""Trace replay CLI: pretty-print a koordtrace JSONL dump as a waterfall.

    python -m koordinator_tpu.obs trace.jsonl
    curl -s localhost:9090/traces | python -m koordinator_tpu.obs -

Each trace renders as an indented latency waterfall — bar offset is the
span's monotonic start relative to its root, bar length its share of the
root's duration — so "where did the cycle spend its time" is answerable
from a terminal with no tooling.

Exit codes (the `hack/lint.sh` golden-fixture contract):
  0  every record parsed and validated
  1  schema drift: bad JSON, missing/mistyped fields, dangling parent ids
  2  usage error (unreadable input)
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

from koordinator_tpu.obs import validate_record


def load_records(lines) -> Tuple[List[dict], List[str]]:
    records: List[dict] = []
    errors: List[str] = []
    for lineno, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as exc:
            errors.append(f"line {lineno}: invalid JSON ({exc})")
            continue
        errs = validate_record(obj)
        if errs:
            errors.extend(f"line {lineno}: {e}" for e in errs)
            continue
        records.append(obj)
    return records, errors


def build_traces(records: List[dict]
                 ) -> Tuple[List[Tuple[dict, Dict[int, List[dict]]]], List[str]]:
    """Group records into (root, children_by_parent) per trace id."""
    errors: List[str] = []
    by_trace: Dict[int, List[dict]] = {}
    for rec in records:
        by_trace.setdefault(rec["trace"], []).append(rec)
    traces = []
    for trace_id, spans in sorted(by_trace.items()):
        ids = {s["span"] for s in spans}
        roots = [s for s in spans if s["parent"] is None]
        for s in spans:
            if s["parent"] is not None and s["parent"] not in ids:
                errors.append(
                    f"trace {trace_id}: span {s['span']} ({s['name']!r}) "
                    f"has dangling parent {s['parent']}")
        if len(roots) != 1:
            errors.append(
                f"trace {trace_id}: expected exactly 1 root span, "
                f"got {len(roots)}")
            continue
        children: Dict[int, List[dict]] = {}
        for s in spans:
            if s["parent"] is not None:
                children.setdefault(s["parent"], []).append(s)
        for sibs in children.values():
            sibs.sort(key=lambda s: s["start_mono"])
        traces.append((roots[0], children))
    return traces, errors


def _bar(offset_ms: float, dur_ms: float, total_ms: float, width: int) -> str:
    if total_ms <= 0:
        return " " * width
    scale = width / total_ms
    lead = min(width - 1, int(round(offset_ms * scale)))
    length = max(1, int(round(dur_ms * scale)))
    length = min(length, width - lead)
    return " " * lead + "█" * length + " " * (width - lead - length)


def render_trace(root: dict, children: Dict[int, List[dict]],
                 width: int = 40, out=sys.stdout) -> None:
    total_ms = root["duration_ms"]
    n_spans = 1 + sum(len(v) for v in children.values())
    out.write(f"trace {root['trace']} · {root['name']} · "
              f"{total_ms:.2f}ms · {n_spans} spans\n")
    name_width = max(
        (len(s["name"]) + 2 * depth
         for s, depth in _walk(root, children)), default=0)

    for span, depth in _walk(root, children):
        offset_ms = (span["start_mono"] - root["start_mono"]) * 1000.0
        label = "  " * depth + span["name"]
        attrs = "".join(
            f" {k}={v}" for k, v in sorted(span["attrs"].items()))
        out.write(
            f"  {label:<{name_width}} "
            f"|{_bar(offset_ms, span['duration_ms'], total_ms, width)}| "
            f"{span['duration_ms']:8.2f}ms{attrs}\n")


def _walk(root: dict, children: Dict[int, List[dict]], depth: int = 0):
    yield root, depth
    for child in children.get(root["span"], []):
        yield from _walk(child, children, depth + 1)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m koordinator_tpu.obs",
        description="replay a koordtrace JSONL dump as a latency waterfall")
    ap.add_argument("trace", help="JSONL trace file, or '-' for stdin")
    ap.add_argument("--width", type=int, default=40,
                    help="waterfall bar width in characters")
    args = ap.parse_args(argv)

    if args.trace == "-":
        lines = sys.stdin.readlines()
    else:
        try:
            with open(args.trace) as f:
                lines = f.readlines()
        except OSError as exc:
            print(f"cannot read {args.trace!r}: {exc}", file=sys.stderr)
            return 2

    records, errors = load_records(lines)
    traces, tree_errors = build_traces(records)
    errors.extend(tree_errors)
    if errors:
        for err in errors:
            print(f"schema error: {err}", file=sys.stderr)
        return 1
    if not records:
        print("no spans in input", file=sys.stderr)
        return 0
    try:
        for i, (root, children) in enumerate(traces):
            if i:
                print()
            render_trace(root, children, width=max(10, args.width))
    except BrokenPipeError:
        # downstream pager/head closed the pipe mid-waterfall: normal CLI
        # usage, not an error; hand stdout a sink so interpreter shutdown
        # doesn't print a second traceback flushing the dead pipe
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
