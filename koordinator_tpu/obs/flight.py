"""koordexplain cycle flight recorder: the last N scheduling cycles, dumpable.

A bad cycle — a parity mismatch, a deadline overrun, an unhandled
exception — used to leave nothing behind to debug from: the tracer ring
has timings but no decisions, the store has outcomes but no attribution.
The flight recorder keeps a bounded, lock-guarded ring of per-cycle
DECISION records (bind/fail/reject lists with koordexplain attribution,
the cycle's span tree, metric deltas, wave count) and serializes it as a
schema-validated JSONL bundle on trigger:

  * cycle deadline overrun (``KOORD_TPU_CYCLE_DEADLINE_MS``)
  * unhandled cycle exception (the driver records the wreck, dumps, re-raises)
  * pipeline/fused-wave parity mismatch (scheduler/pipeline_parity.py)
  * on demand: ``POST /debug/flightrecorder`` on the ObsServer, or
    ``FlightRecorder.dump()`` directly

Bundle format: line 1 is a header record, every further line one cycle
record, newest last. ``hack/lint.sh`` pins the schema against
``tests/fixtures/flight_golden.jsonl`` (the trace-JSONL golden-fixture
pattern); render/inspect with ``python -m koordinator_tpu.obs explain
<bundle> <pod>`` or validate with ``python -m koordinator_tpu.obs flight
<bundle>``.

Thread discipline (koordlint's concurrency rules gate this package): the
ring and dump counters are lock-guarded — the scheduler thread records
while the ObsServer thread dumps.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from koordinator_tpu.obs import validate_record as validate_span_record

FLIGHT_SCHEMA_VERSION = 1
FLIGHT_SCHEMA_NAME = "koordexplain-flight"

# cycle-record list fields whose entries must be {"pod": str, ...} objects
_POD_LIST_FIELDS = ("bound", "failed", "rejected")


class FlightRecorder:
    """Bounded ring of cycle decision records + triggered bundle dumps.

    ``dump_dir`` (default: the ``KOORD_TPU_FLIGHT_DIR`` env var) makes
    every dump also land as a file; without it the bundle is returned to
    the caller only (the HTTP surface ships it as the response body).
    ``dump_counter`` is an optional metrics Counter with a ``reason``
    label — the recorder never imports a metrics registry itself, the
    owner injects one (scheduler/metrics.FLIGHT_DUMPS).
    """

    def __init__(self, capacity: int = 16, dump_dir: Optional[str] = None,
                 dump_counter=None):
        self._lock = threading.Lock()
        self._ring = deque(maxlen=capacity)
        self._dumps = 0
        self.dump_dir = (dump_dir if dump_dir is not None
                         else os.environ.get("KOORD_TPU_FLIGHT_DIR") or None)
        self.dump_counter = dump_counter
        self._last_dump_path: Optional[str] = None  # _lock-guarded

    def record_cycle(self, record: Dict) -> None:
        with self._lock:
            self._ring.append(record)

    def snapshot(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def dumps(self) -> int:
        with self._lock:
            return self._dumps

    @property
    def last_dump_path(self) -> Optional[str]:
        with self._lock:
            return self._last_dump_path

    def status(self) -> Dict[str, object]:
        """One consistent snapshot for the HTTP status surface."""
        with self._lock:
            return {
                "cycles": len(self._ring),
                "dumps": self._dumps,
                "last_dump_path": self._last_dump_path,
            }

    def dump(self, reason: str, path: Optional[str] = None) -> str:
        """Serialize the ring as a JSONL bundle (header line + one line per
        cycle, oldest first); returns the bundle body. Writes a file when
        ``path`` or ``dump_dir`` is set. Never raises on ring content —
        a dump fired from a failing cycle must not add its own failure."""
        records = self.snapshot()
        header = {
            "v": FLIGHT_SCHEMA_VERSION,
            "kind": "header",
            "schema": FLIGHT_SCHEMA_NAME,
            "reason": str(reason),
            "dumped_at": time.time(),
            "cycles": len(records),
        }
        lines = [json.dumps(header, sort_keys=True)]
        lines.extend(json.dumps(r, sort_keys=True, default=str)
                     for r in records)
        body = "\n".join(lines) + "\n"
        target = path
        if target is None and self.dump_dir:
            target = os.path.join(
                self.dump_dir,
                f"flight_{reason}_{int(header['dumped_at'])}.jsonl")
        written = None
        if target:
            try:
                with open(target, "w") as f:
                    f.write(body)
                written = target
            except OSError:
                # an unwritable dump dir must not wedge the trigger path;
                # the caller still gets the bundle body
                written = None
        with self._lock:
            self._dumps += 1
            if target:
                self._last_dump_path = written
        if self.dump_counter is not None:
            self.dump_counter.inc(reason=str(reason))
        return body


# ---------------------------------------------------------------------------
# bundle schema (the hack/lint.sh golden-fixture contract)
# ---------------------------------------------------------------------------


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_header(obj) -> List[str]:
    """Schema check for the bundle's first line."""
    if not isinstance(obj, dict):
        return ["header is not a JSON object"]
    errs: List[str] = []
    if obj.get("v") != FLIGHT_SCHEMA_VERSION:
        errs.append(f"v must be {FLIGHT_SCHEMA_VERSION}, got {obj.get('v')!r}")
    if obj.get("kind") != "header":
        errs.append(f"kind must be 'header', got {obj.get('kind')!r}")
    if obj.get("schema") != FLIGHT_SCHEMA_NAME:
        errs.append(f"schema must be {FLIGHT_SCHEMA_NAME!r}, "
                    f"got {obj.get('schema')!r}")
    if not isinstance(obj.get("reason"), str) or not obj.get("reason"):
        errs.append(f"reason must be a non-empty string, "
                    f"got {obj.get('reason')!r}")
    if not _is_num(obj.get("dumped_at")) or obj.get("dumped_at") < 0:
        errs.append(f"dumped_at must be a non-negative number, "
                    f"got {obj.get('dumped_at')!r}")
    if not isinstance(obj.get("cycles"), int) or isinstance(
            obj.get("cycles"), bool) or obj.get("cycles") < 0:
        errs.append(f"cycles must be a non-negative int, "
                    f"got {obj.get('cycles')!r}")
    return errs


def validate_cycle_record(obj) -> List[str]:
    """Schema check for one cycle record line."""
    if not isinstance(obj, dict):
        return ["record is not a JSON object"]
    errs: List[str] = []
    if obj.get("v") != FLIGHT_SCHEMA_VERSION:
        errs.append(f"v must be {FLIGHT_SCHEMA_VERSION}, got {obj.get('v')!r}")
    if obj.get("kind") != "cycle":
        errs.append(f"kind must be 'cycle', got {obj.get('kind')!r}")
    if not isinstance(obj.get("seq"), int) or isinstance(obj.get("seq"), bool):
        errs.append(f"seq must be an int, got {obj.get('seq')!r}")
    for key in ("ts", "duration_ms"):
        if not _is_num(obj.get(key)) or obj.get(key) < 0:
            errs.append(f"{key} must be a non-negative number, "
                        f"got {obj.get(key)!r}")
    waves = obj.get("waves")
    if not isinstance(waves, int) or isinstance(waves, bool) or waves < 0:
        errs.append(f"waves must be a non-negative int, got {waves!r}")
    for field in _POD_LIST_FIELDS:
        entries = obj.get(field)
        if not isinstance(entries, list):
            errs.append(f"{field} must be a list, got {entries!r}")
            continue
        for e in entries:
            if not isinstance(e, dict) or not isinstance(e.get("pod"), str):
                errs.append(f"{field} entries must be objects with a "
                            f"string 'pod', got {e!r}")
                continue
            if field == "bound" and not isinstance(e.get("node"), str):
                errs.append(f"bound entry for {e['pod']} needs a string "
                            f"'node', got {e.get('node')!r}")
            stages = e.get("stages")
            if stages is not None:
                if not isinstance(stages, dict) or not all(
                        isinstance(k, str) and isinstance(v, int)
                        and not isinstance(v, bool)
                        for k, v in stages.items()):
                    errs.append(f"stages of {e['pod']} must map stage "
                                f"name -> int count, got {stages!r}")
            terms = e.get("terms")
            if terms is not None:
                if not isinstance(terms, dict) or not all(
                        isinstance(k, str) and _is_num(v)
                        for k, v in terms.items()):
                    errs.append(f"terms of {e['pod']} must map term "
                                f"name -> number, got {terms!r}")
    preempted = obj.get("preempted")
    if not isinstance(preempted, list) or any(
            not isinstance(k, str) for k in preempted):
        errs.append(f"preempted must be a list of strings, got {preempted!r}")
    # koordwatch (optional, so pre-PR-13 bundles keep validating): the
    # cycle's structured demotion reasons and device-window decision ids
    for key in ("demotions", "decision_ids"):
        val = obj.get(key)
        if val is not None and (not isinstance(val, list) or any(
                not isinstance(k, str) for k in val)):
            errs.append(f"{key} must be a list of strings when present, "
                        f"got {val!r}")
    decision_id = obj.get("decision_id")
    if decision_id is not None and not isinstance(decision_id, str):
        errs.append(f"decision_id must be a string when present, "
                    f"got {decision_id!r}")
    metrics = obj.get("metrics")
    if not isinstance(metrics, dict) or not all(
            isinstance(k, str) and _is_num(v)
            for k, v in (metrics or {}).items()):
        errs.append(f"metrics must map name -> number, got {metrics!r}")
    spans = obj.get("spans")
    if not isinstance(spans, list):
        errs.append(f"spans must be a list, got {spans!r}")
    else:
        for s in spans:
            errs.extend(f"span: {e}" for e in validate_span_record(s))
    error = obj.get("error")
    if error is not None and not isinstance(error, str):
        errs.append(f"error must be a string when present, got {error!r}")
    return errs


def load_bundle(lines) -> Tuple[Optional[dict], List[dict], List[str]]:
    """Parse + validate a bundle; returns (header, cycle_records, errors).
    The contract ``hack/lint.sh`` pins: any error list growth against the
    golden fixture is schema drift and must be a conscious version bump."""
    from koordinator_tpu.obs import load_jsonl_bundle

    return load_jsonl_bundle(lines, validate_header=validate_header,
                             validate_record=validate_cycle_record,
                             count_key="cycles")
