"""NodeMetric controller: one NodeMetric CR per node + collect policy.

Analog of `pkg/slo-controller/nodemetric/nodemetric_controller.go:59-180`: on
node events, ensure the NodeMetric CR exists and its spec (report interval,
aggregate windows) reflects the cluster sloconfig; delete orphans."""

from __future__ import annotations

from typing import Optional

from koordinator_tpu.api.objects import NodeMetric, ObjectMeta
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    ObjectStore,
)
from koordinator_tpu.utils.sloconfig import ColocationConfig


class NodeMetricController:
    def __init__(self, store: ObjectStore, config: Optional[ColocationConfig] = None):
        self.store = store
        self.config = config or ColocationConfig()

    def reconcile(self) -> int:
        """Ensure CR per node; returns number of changes."""
        changes = 0
        nodes = {n.meta.name for n in self.store.list(KIND_NODE)}
        existing = {m.meta.name for m in self.store.list(KIND_NODE_METRIC)}
        interval = max(
            60,
            self.config.cluster_strategy.metric_aggregate_duration_seconds // 5,
        )
        for name in nodes - existing:
            self.store.add(
                KIND_NODE_METRIC,
                NodeMetric(
                    meta=ObjectMeta(name=name, namespace=""),
                    report_interval_seconds=interval,
                ),
            )
            changes += 1
        for name in existing - nodes:
            self.store.delete(KIND_NODE_METRIC, f"/{name}")
            changes += 1
        return changes
