"""SLO controllers (analog of reference `pkg/slo-controller/`, SURVEY.md 2.4):
nodemetric (CR lifecycle + collect policy), noderesource (THE colocation
resource pipeline — batch/mid allocatable, vectorized over all nodes in one JAX
pass), nodeslo (per-node strategy rendering from the cluster config)."""

from koordinator_tpu.slocontroller.nodemetric import NodeMetricController  # noqa: F401
from koordinator_tpu.slocontroller.noderesource import NodeResourceController  # noqa: F401
from koordinator_tpu.slocontroller.nodeslo import NodeSLOController  # noqa: F401
