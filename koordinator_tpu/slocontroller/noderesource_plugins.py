"""NodeResource controller plugins beyond batch/mid: cpu normalization,
GPU device resources, resource amplification.

Analog of `pkg/slo-controller/noderesource/plugins/{cpunormalization,
gpudeviceresource, resourceamplification}` (plugin.go in each): each plugin
Calculates resource items / metadata for a node and Prepares them onto the
node object; the controller applies the chain per node after the vectorized
batch/mid pass. Plugin order matters: ResourceAmplification derives its
ratio from the annotation CPUNormalization prepared in the same round
(resourceamplification/plugin.go:82-111).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from koordinator_tpu.api.objects import Device, Node, NodeResourceTopology
from koordinator_tpu.api.resources import ResourceList, ResourceName
from koordinator_tpu.client.store import (
    KIND_DEVICE,
    KIND_NODE_TOPOLOGY,
    ObjectStore,
)

ANNOTATION_CPU_NORMALIZATION_RATIO = "node.koordinator.sh/cpu-normalization-ratio"
ANNOTATION_CPU_BASIC_INFO = "node.koordinator.sh/cpu-basic-info"
ANNOTATION_AMPLIFICATION_RATIO = "node.koordinator.sh/resource-amplification-ratio"
LABEL_CPU_NORMALIZATION_ENABLED = "node.koordinator.sh/cpu-normalization-enabled"
LABEL_GPU_MODEL = "node.koordinator.sh/gpu-model"
LABEL_GPU_DRIVER_VERSION = "node.koordinator.sh/gpu-driver-version"

CPU_NORMALIZATION_CONFIG_KEY = "cpu-normalization-config"
DEFAULT_RATIO_STR = "1.00"
MIN_RATIO, MAX_RATIO = 1.0, 5.0

GPU_RESOURCE_NAMES = (
    ResourceName.GPU,
    ResourceName.GPU_CORE,
    ResourceName.GPU_MEMORY,
    ResourceName.GPU_MEMORY_RATIO,
)


@dataclass
class NodeResource:
    """Accumulator the plugin chain fills for one node (framework's
    NodeResource: Resources/Resets/Labels/Annotations)."""

    resources: Dict[str, int] = field(default_factory=dict)
    resets: Dict[str, bool] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    # annotations to remove when a plugin calculates "unset"
    annotation_removals: List[str] = field(default_factory=list)


class NodeResourcePlugin:
    """Calculate fills the NodeResource; Prepare writes it onto the node and
    reports whether the node changed."""

    name = "plugin"

    def calculate(self, node: Node, nr: NodeResource, store: ObjectStore,
                  config) -> None:
        raise NotImplementedError

    def prepare(self, node: Node, nr: NodeResource) -> bool:
        raise NotImplementedError


class CPUNormalizationPlugin(NodeResourcePlugin):
    """Ratio from the CPU model info (cpunormalization/plugin.go:130-215):
    the sloconfig's ratio model keyed by CPU model picks base / HT / turbo /
    HT+turbo ratios from the NodeResourceTopology's cpu-basic-info
    annotation; the node label can force-enable/disable. Result lands in the
    cpu-normalization-ratio annotation, validated to [1.0, 5.0]."""

    name = "CPUNormalization"

    def calculate(self, node: Node, nr: NodeResource, store: ObjectStore,
                  config) -> None:
        strategy = (config or {})
        enabled = strategy.get("enable", False)
        node_label = node.meta.labels.get(LABEL_CPU_NORMALIZATION_ENABLED)
        if node_label is not None:
            enabled = node_label == "true"
        if not enabled:
            nr.annotations[ANNOTATION_CPU_NORMALIZATION_RATIO] = DEFAULT_RATIO_STR
            return
        nrt: Optional[NodeResourceTopology] = store.get(
            KIND_NODE_TOPOLOGY, f"/{node.meta.name}")
        if nrt is None:
            return  # abort: missing NRT skips the annotation update
        raw = nrt.meta.annotations.get(ANNOTATION_CPU_BASIC_INFO, "")
        try:
            info = json.loads(raw) if raw else None
        except ValueError:
            info = None
        if not isinstance(info, dict):
            return
        model = info.get("cpuModel", "")
        ratio_model = strategy.get("ratioModel", {})
        cfg = ratio_model.get(model)
        if cfg is None:
            return
        ht = bool(info.get("hyperThreadEnabled"))
        turbo = bool(info.get("turboEnabled"))
        if ht and turbo:
            ratio = cfg.get("hyperThreadTurboEnabledRatio")
        elif ht:
            ratio = cfg.get("hyperThreadEnabledRatio")
        elif turbo:
            ratio = cfg.get("turboEnabledRatio")
        else:
            ratio = cfg.get("baseRatio")
        if ratio is None or not (MIN_RATIO <= float(ratio) <= MAX_RATIO):
            return
        nr.annotations[ANNOTATION_CPU_NORMALIZATION_RATIO] = f"{float(ratio):.2f}"

    def prepare(self, node: Node, nr: NodeResource) -> bool:
        ratio = nr.annotations.get(ANNOTATION_CPU_NORMALIZATION_RATIO)
        if ratio is None:
            return False
        if node.meta.annotations.get(ANNOTATION_CPU_NORMALIZATION_RATIO) == ratio:
            return False
        node.meta.annotations[ANNOTATION_CPU_NORMALIZATION_RATIO] = ratio
        return True


class GPUDeviceResourcePlugin(NodeResourcePlugin):
    """Device-CR -> node-status sync (gpudeviceresource/plugin.go:133-213):
    sum healthy GPU devices' resources into node allocatable/capacity (the
    koordinator.sh/gpu total is the summed gpu-core quantity), copy the
    device's model/driver labels, and reset all GPU resources when the
    Device CR is gone."""

    name = "GPUDeviceResource"

    def calculate(self, node: Node, nr: NodeResource, store: ObjectStore,
                  config) -> None:
        device: Optional[Device] = store.get(KIND_DEVICE, f"/{node.meta.name}")
        if device is None:
            for rn in GPU_RESOURCE_NAMES:
                nr.resets[rn] = True
            return
        totals: Dict[str, int] = {}
        total_gpu = 0
        for d in device.devices:
            if d.type != "gpu" or not d.health:
                continue
            for name, qty in d.resources.quantities.items():
                totals[name] = totals.get(name, 0) + qty
            total_gpu += d.resources.get(ResourceName.GPU_CORE)
        totals[ResourceName.GPU] = total_gpu
        nr.resources.update(totals)
        for label in (LABEL_GPU_MODEL, LABEL_GPU_DRIVER_VERSION):
            if label in device.meta.labels:
                nr.labels[label] = device.meta.labels[label]

    def prepare(self, node: Node, nr: NodeResource) -> bool:
        changed = False
        alloc = dict(node.allocatable.quantities)
        cap = dict(node.capacity.quantities)
        for rn in GPU_RESOURCE_NAMES:
            if nr.resets.get(rn):
                if rn in alloc or rn in cap:
                    alloc.pop(rn, None)
                    cap.pop(rn, None)
                    changed = True
        for rn, qty in nr.resources.items():
            if alloc.get(rn) != qty:
                alloc[rn] = qty
                cap[rn] = qty
                changed = True
        if changed:
            node.allocatable = ResourceList(alloc)
            node.capacity = ResourceList(cap)
        for label, val in nr.labels.items():
            if node.meta.labels.get(label) != val:
                node.meta.labels[label] = val
                changed = True
        return changed


class ResourceAmplificationPlugin(NodeResourcePlugin):
    """Amplification ratio from the normalization ratio
    (resourceamplification/plugin.go:82-111): ratio > 1 produces the
    resource-amplification-ratio annotation ({"cpu": ratio}) that the node
    mutating webhook consumes to amplify allocatable; ratio <= 1 removes it."""

    name = "ResourceAmplification"

    def calculate(self, node: Node, nr: NodeResource, store: ObjectStore,
                  config) -> None:
        # read the ratio CPUNormalization prepared this round (falling back
        # to what is already on the node)
        raw = nr.annotations.get(
            ANNOTATION_CPU_NORMALIZATION_RATIO,
            node.meta.annotations.get(ANNOTATION_CPU_NORMALIZATION_RATIO, ""))
        try:
            ratio = float(raw) if raw else -1.0
        except ValueError:
            return
        if ratio <= 1.0:
            nr.annotation_removals.append(ANNOTATION_AMPLIFICATION_RATIO)
            return
        nr.annotations[ANNOTATION_AMPLIFICATION_RATIO] = json.dumps(
            {"cpu": ratio})

    def prepare(self, node: Node, nr: NodeResource) -> bool:
        changed = False
        if ANNOTATION_AMPLIFICATION_RATIO in nr.annotation_removals:
            if node.meta.annotations.pop(ANNOTATION_AMPLIFICATION_RATIO, None) is not None:
                changed = True
            return changed
        val = nr.annotations.get(ANNOTATION_AMPLIFICATION_RATIO)
        if val is not None and node.meta.annotations.get(
                ANNOTATION_AMPLIFICATION_RATIO) != val:
            node.meta.annotations[ANNOTATION_AMPLIFICATION_RATIO] = val
            changed = True
        return changed


DEFAULT_PLUGINS = (
    CPUNormalizationPlugin(),
    GPUDeviceResourcePlugin(),
    ResourceAmplificationPlugin(),
)


def run_plugin_chain(node: Node, store: ObjectStore,
                     cpu_normalization_config: Optional[dict] = None,
                     plugins=DEFAULT_PLUGINS) -> bool:
    """Calculate + Prepare the chain for one node; True if the node changed."""
    nr = NodeResource()
    for plugin in plugins:
        cfg = (cpu_normalization_config
               if plugin.name == "CPUNormalization" else None)
        plugin.calculate(node, nr, store, cfg)
    changed = False
    for plugin in plugins:
        changed |= plugin.prepare(node, nr)
    return changed
