"""NodeSLO controller: render per-node QoS strategies from the cluster config.

Analog of `pkg/slo-controller/nodeslo/` (controller + resource_strategy.go
merge): the slo-controller-config ConfigMap (thresholds, resource-qos, cpu
burst, system tuning) merged with per-nodepool overrides becomes one NodeSLO CR
per node, consumed by koordlet's qosmanager via the statesinformer."""

from __future__ import annotations

import json
from typing import Dict, Optional

from koordinator_tpu.api.objects import (
    CPUBurstStrategy,
    NodeSLO,
    ObjectMeta,
    ResourceQOSStrategy,
    ResourceThresholdStrategy,
    SystemStrategy,
)
from koordinator_tpu.client.store import (
    KIND_CONFIG_MAP,
    KIND_NODE,
    KIND_NODE_SLO,
    ObjectStore,
)
from koordinator_tpu.utils.sloconfig import CONFIG_MAP_NAME

THRESHOLD_CONFIG_KEY = "resource-threshold-config"
QOS_CONFIG_KEY = "resource-qos-config"
CPU_BURST_CONFIG_KEY = "cpu-burst-config"
SYSTEM_CONFIG_KEY = "system-config"
HOST_APP_CONFIG_KEY = "host-application-config"


def _merge_threshold(data: Dict) -> ResourceThresholdStrategy:
    s = ResourceThresholdStrategy()
    s.enable = data.get("enable", s.enable)
    s.cpu_suppress_threshold_percent = data.get(
        "cpuSuppressThresholdPercent", s.cpu_suppress_threshold_percent
    )
    s.cpu_suppress_policy = data.get("cpuSuppressPolicy", s.cpu_suppress_policy)
    s.memory_evict_threshold_percent = data.get(
        "memoryEvictThresholdPercent", s.memory_evict_threshold_percent
    )
    s.memory_evict_lower_percent = data.get(
        "memoryEvictLowerPercent", s.memory_evict_lower_percent
    )
    s.cpu_evict_be_usage_threshold_percent = data.get(
        "cpuEvictBEUsageThresholdPercent", s.cpu_evict_be_usage_threshold_percent
    )
    return s


class NodeSLOController:
    def __init__(self, store: ObjectStore):
        self.store = store

    def _config_section(self, key: str) -> Dict:
        cm = self.store.get(KIND_CONFIG_MAP, f"koordinator-system/{CONFIG_MAP_NAME}")
        if cm is None:
            return {}
        raw = getattr(cm, "data", {}).get(key)
        if not raw:
            return {}
        try:
            return json.loads(raw)
        except (ValueError, TypeError):
            return {}

    def _node_override(self, section: Dict, node_labels: Dict[str, str]) -> Dict:
        """clusterStrategy + first matching nodeStrategies entry."""
        merged = dict(section.get("clusterStrategy", {}))
        for ns in section.get("nodeStrategies", []):
            selector = ns.get("nodeSelector", {})
            if all(node_labels.get(k) == v for k, v in selector.items()):
                merged.update(
                    {k: v for k, v in ns.items() if k != "nodeSelector"}
                )
                break
        return merged

    def reconcile(self) -> int:
        changes = 0
        threshold_cfg = self._config_section(THRESHOLD_CONFIG_KEY)
        qos_cfg = self._config_section(QOS_CONFIG_KEY)
        burst_cfg = self._config_section(CPU_BURST_CONFIG_KEY)
        system_cfg = self._config_section(SYSTEM_CONFIG_KEY)
        host_app_cfg = self._config_section(HOST_APP_CONFIG_KEY)
        for node in self.store.list(KIND_NODE):
            labels = node.meta.labels
            slo = NodeSLO(
                meta=ObjectMeta(name=node.meta.name, namespace=""),
                resource_used_threshold_with_be=_merge_threshold(
                    self._node_override(threshold_cfg, labels)
                ),
            )
            qos = self._node_override(qos_cfg, labels)
            slo.resource_qos_strategy = ResourceQOSStrategy(
                ls_enable=qos.get("lsEnable", False),
                be_enable=qos.get("beEnable", False),
                ls_group_identity=qos.get("lsGroupIdentity", 2),
                be_group_identity=qos.get("beGroupIdentity", -1),
                llc_be_percent=qos.get("llcBEPercent", 100),
                mba_be_percent=qos.get("mbaBEPercent", 100),
                blkio_enable=qos.get("blkioEnable", False),
                ls_blkio_weight=qos.get("lsBlkioWeight", 500),
                be_blkio_weight=qos.get("beBlkioWeight", 100),
                core_sched_enable=qos.get("coreSchedEnable", False),
                net_qos_policy=qos.get("netQOSPolicy", ""),
                net_hw_tx_bps=qos.get("netHwTxBps", 0),
                net_hw_rx_bps=qos.get("netHwRxBps", 0),
            )
            burst = self._node_override(burst_cfg, labels)
            slo.cpu_burst_strategy = CPUBurstStrategy(
                policy=burst.get("policy", "none"),
                cpu_burst_percent=burst.get("cpuBurstPercent", 1000),
                cfs_quota_burst_percent=burst.get("cfsQuotaBurstPercent", 300),
            )
            system = self._node_override(system_cfg, labels)
            slo.system_strategy = SystemStrategy(
                min_free_kbytes_factor=system.get("minFreeKbytesFactor", 100),
                watermark_scale_factor=system.get("watermarkScaleFactor", 150),
            )
            # host applications (HostApplicationConfigKey /
            # apis/configuration HostApplicationCfg): cluster list, with the
            # first matching nodeConfigs entry replacing it, rendered into
            # the NodeSLO extension the koordlet consumes
            # (nodeslo_controller.go:110 getHostApplicationConfig)
            host_apps = host_app_cfg.get("applications")
            for ncfg in host_app_cfg.get("nodeConfigs", []):
                selector = ncfg.get("nodeSelector", {})
                if isinstance(selector, dict) and all(
                        labels.get(k) == v for k, v in selector.items()):
                    host_apps = ncfg.get("applications", host_apps)
                    break
            if host_apps:
                slo.extensions = dict(slo.extensions or {})
                slo.extensions["hostApplications"] = host_apps
            existing = self.store.get(KIND_NODE_SLO, f"/{node.meta.name}")
            if existing is None:
                self.store.add(KIND_NODE_SLO, slo)
                changes += 1
            elif (
                existing.resource_used_threshold_with_be
                != slo.resource_used_threshold_with_be
                or existing.resource_qos_strategy != slo.resource_qos_strategy
                or existing.cpu_burst_strategy != slo.cpu_burst_strategy
                or existing.system_strategy != slo.system_strategy
            ):
                slo.meta = existing.meta
                self.store.update(KIND_NODE_SLO, slo)
                changes += 1
        return changes
