"""NodeResource controller: the colocation resource pipeline, vectorized.

Analog of `pkg/slo-controller/noderesource/` (controller :72-165, batchresource
plugin + util.go:38-66, midresource, degrade :467-485). The per-node formula

  System.Used        = max(Node.Used - Pod(All).Used, Node.Anno.Reserved)
  Batch.Alloc[usage] = max(Node.Total*(reclaim%/100) - Node.Reserved
                           - System.Used - Pod(HP).Used, 0)
  Batch.Alloc[request]        likewise with requests and System.Reserved
  Batch.Alloc[maxUsageRequest] likewise with max(request, used)
  Mid.Alloc          = min(ProdReclaimable, Node.Total * mid%/100)

is identical for every node — SURVEY.md 7's "already pure tensor math over
ResourceLists" — so the whole cluster reconciles in ONE jitted [N, R] pass
instead of the reference's per-node reconcile loop. Stale NodeMetrics degrade
the node (batch resources reset to zero) per the degrade window.
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.objects import Node, NodeMetric, Pod
from koordinator_tpu.api.priority import PriorityClass
from koordinator_tpu.api.resources import (
    NUM_RESOURCES,
    RESOURCE_INDEX,
    ResourceList,
    ResourceName,
)
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    ObjectStore,
)
from koordinator_tpu.slocontroller.noderesource_plugins import (
    CPU_NORMALIZATION_CONFIG_KEY,
    run_plugin_chain,
)
from koordinator_tpu.utils.sloconfig import (
    POLICY_MAX_USAGE_REQUEST,
    POLICY_REQUEST,
    ColocationConfig,
)

CPU = RESOURCE_INDEX[ResourceName.CPU]
MEM = RESOURCE_INDEX[ResourceName.MEMORY]
ANNOTATION_NODE_RESERVATION = "node.koordinator.sh/reservation"


@functools.partial(jax.jit, static_argnames=("cpu_policy", "memory_policy"))
def _batch_mid_kernel(
    capacity,            # [N, R]
    node_reserved,       # [N, R]
    system_reserved,     # [N, R]
    node_used,           # [N, R]
    pod_all_used,        # [N, R]
    pod_hp_used,         # [N, R]
    pod_hp_request,      # [N, R]
    pod_hp_max_used_req,  # [N, R]
    prod_reclaimable,    # [N, R]
    reclaim_percent,     # [N, R] (cpu/mem thresholds broadcast per axis)
    mid_percent,         # [N, R]
    degraded,            # [N] bool
    cpu_policy: str,
    memory_policy: str,
):
    reclaimable_capacity = capacity * reclaim_percent / 100.0
    system_used = jnp.maximum(node_used - pod_all_used, 0.0)
    system_used = jnp.maximum(system_used, system_reserved)
    by_usage = jnp.maximum(
        reclaimable_capacity - node_reserved - system_used - pod_hp_used, 0.0
    )
    by_request = jnp.maximum(
        reclaimable_capacity - node_reserved - system_reserved - pod_hp_request, 0.0
    )
    by_max = jnp.maximum(
        reclaimable_capacity - node_reserved - system_used - pod_hp_max_used_req, 0.0
    )

    def pick(policy):
        if policy == POLICY_REQUEST:
            return by_request
        if policy == POLICY_MAX_USAGE_REQUEST:
            return by_max
        return by_usage

    batch = by_usage
    batch = batch.at[:, CPU].set(pick(cpu_policy)[:, CPU])
    batch = batch.at[:, MEM].set(pick(memory_policy)[:, MEM])
    batch = jnp.where(degraded[:, None], 0.0, batch)
    mid = jnp.minimum(prod_reclaimable, capacity * mid_percent / 100.0)
    mid = jnp.where(degraded[:, None], 0.0, jnp.maximum(mid, 0.0))
    return batch, mid


class NodeResourceController:
    def __init__(self, store: ObjectStore, config: Optional[ColocationConfig] = None):
        self.store = store
        self.config = config or ColocationConfig()

    # -- host gather ---------------------------------------------------------
    def _gather(self, nodes: List[Node], now: float):
        N = len(nodes)
        R = NUM_RESOURCES
        capacity = np.zeros((N, R), np.float32)
        node_reserved = np.zeros((N, R), np.float32)
        system_reserved = np.zeros((N, R), np.float32)
        node_used = np.zeros((N, R), np.float32)
        pod_all_used = np.zeros((N, R), np.float32)
        pod_hp_used = np.zeros((N, R), np.float32)
        pod_hp_request = np.zeros((N, R), np.float32)
        pod_hp_max = np.zeros((N, R), np.float32)
        prod_reclaimable = np.zeros((N, R), np.float32)
        reclaim = np.zeros((N, R), np.float32)
        mid_pct = np.zeros((N, R), np.float32)
        degraded = np.zeros(N, bool)

        pods_by_node: Dict[str, List[Pod]] = {}
        for pod in self.store.list(KIND_POD):
            if pod.is_assigned and not pod.is_terminated:
                pods_by_node.setdefault(pod.spec.node_name, []).append(pod)

        for i, node in enumerate(nodes):
            strategy = self.config.strategy_for_node(
                node.meta.labels, node.meta.annotations)
            capacity[i] = node.capacity.to_vector() if node.capacity else node.allocatable.to_vector()
            reclaim[i, CPU] = strategy.cpu_reclaim_threshold_percent
            reclaim[i, MEM] = strategy.memory_reclaim_threshold_percent
            mid_pct[i, CPU] = strategy.mid_cpu_threshold_percent
            mid_pct[i, MEM] = strategy.mid_memory_threshold_percent
            raw = node.meta.annotations.get(ANNOTATION_NODE_RESERVATION)
            if raw:
                import json

                try:
                    data = json.loads(raw)
                    from koordinator_tpu.api.resources import parse_quantity

                    def to_vec(section):
                        return ResourceList(
                            {
                                k: parse_quantity(v, cpu=(k == ResourceName.CPU))
                                for k, v in section.items()
                            }
                        ).to_vector()

                    node_reserved[i] = to_vec(data.get("resources", {}))
                    # the system daemons' reserve feeds both the system-used
                    # floor and the by-request policy subtrahend
                    system_reserved[i] = to_vec(data.get("systemResources", {}))
                except (ValueError, TypeError):
                    pass
            nm: Optional[NodeMetric] = self.store.get(
                KIND_NODE_METRIC, f"/{node.meta.name}"
            )
            if nm is None or nm.update_time <= 0:
                degraded[i] = True
                continue
            if now - nm.update_time > strategy.degrade_time_minutes * 60:
                degraded[i] = True  # degrade on stale metrics (plugin.go:467-485)
                continue
            node_used[i] = nm.node_metric.node_usage.to_vector()
            prod_reclaimable[i] = nm.prod_reclaimable.to_vector()
            pod_usage = {
                f"{pm.namespace}/{pm.name}": pm.pod_usage.to_vector()
                for pm in nm.pods_metric
            }
            for pod in pods_by_node.get(node.meta.name, []):
                used = pod_usage.get(pod.meta.key)
                if used is not None:
                    pod_all_used[i] += used
                cls = pod.priority_class
                if cls in (PriorityClass.PROD, PriorityClass.MID, PriorityClass.NONE):
                    req = pod.spec.requests.to_vector()
                    u = used if used is not None else np.zeros(R, np.float32)
                    pod_hp_used[i] += u
                    pod_hp_request[i] += req
                    pod_hp_max[i] += np.maximum(req, u)
        return (capacity, node_reserved, system_reserved, node_used, pod_all_used,
                pod_hp_used, pod_hp_request, pod_hp_max, prod_reclaimable,
                reclaim, mid_pct, degraded)

    # -- reconcile -----------------------------------------------------------
    def reconcile(self, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        nodes = self.store.list(KIND_NODE)
        if not nodes:
            return 0
        arrays = self._gather(nodes, now)
        strategy = self.config.cluster_strategy
        batch, mid = _batch_mid_kernel(
            *[jnp.asarray(a) for a in arrays],
            cpu_policy=strategy.cpu_calculate_policy,
            memory_policy=strategy.memory_calculate_policy,
        )
        batch, mid = np.asarray(batch), np.asarray(mid)
        changes = 0
        for i, node in enumerate(nodes):
            update = ResourceList.of(
                batch_cpu=int(batch[i, CPU]),
                batch_memory=int(batch[i, MEM]) * 1024 * 1024,
                mid_cpu=int(mid[i, CPU]),
                mid_memory=int(mid[i, MEM]) * 1024 * 1024,
            )
            merged = dict(node.allocatable.quantities)
            changed = False
            for name in (
                ResourceName.BATCH_CPU,
                ResourceName.BATCH_MEMORY,
                ResourceName.MID_CPU,
                ResourceName.MID_MEMORY,
            ):
                val = update[name]
                if merged.get(name, 0) != val:
                    merged[name] = val
                    changed = True
            if changed:
                node.allocatable = ResourceList(merged)
            # post-pass plugin chain: cpunormalization + gpudeviceresource +
            # resourceamplification (reference plugins_profile.go order);
            # runs after the batch/mid merge so it sees the final allocatable
            plugin_changed = run_plugin_chain(
                node, self.store,
                cpu_normalization_config=self._cpu_normalization_config())
            if changed or plugin_changed:
                self.store.update(KIND_NODE, node)
                changes += 1
        return changes

    def _cpu_normalization_config(self) -> Optional[dict]:
        """cpu-normalization-config section of the slo-controller-config
        ConfigMap (configuration/slo_controller_config.go:34)."""
        from koordinator_tpu.client.store import KIND_CONFIG_MAP
        from koordinator_tpu.utils.sloconfig import CONFIG_MAP_NAME

        cm = self.store.get(
            KIND_CONFIG_MAP, f"koordinator-system/{CONFIG_MAP_NAME}")
        raw = getattr(cm, "data", {}).get(CPU_NORMALIZATION_CONFIG_KEY) if cm else None
        if not raw:
            return None
        import json

        try:
            cfg = json.loads(raw)
        except ValueError:
            return None
        return cfg if isinstance(cfg, dict) else None
