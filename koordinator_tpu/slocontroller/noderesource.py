"""NodeResource controller: the colocation resource pipeline, vectorized.

Analog of `pkg/slo-controller/noderesource/` (controller :72-165, batchresource
plugin + util.go:38-66, midresource, degrade :467-485). The per-node formula

  System.Used        = max(Node.Used - Pod(All).Used, Node.Anno.Reserved)
  Batch.Alloc[usage] = max(Node.Total*(reclaim%/100) - Node.Reserved
                           - System.Used - Pod(HP).Used, 0)
  Batch.Alloc[request]        likewise with requests and System.Reserved
  Batch.Alloc[maxUsageRequest] likewise with max(request, used)
  Mid.Alloc          = min(ProdReclaimable, Node.Total * mid%/100)

is identical for every node — SURVEY.md 7's "already pure tensor math over
ResourceLists" — so the whole cluster reconciles in ONE jitted [N, R] pass
instead of the reference's per-node reconcile loop. Stale NodeMetrics degrade
the node (batch resources reset to zero) per the degrade window.
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.objects import Node, NodeMetric, Pod
from koordinator_tpu.api.priority import PriorityClass
from koordinator_tpu.api.resources import (
    NUM_RESOURCES,
    RESOURCE_INDEX,
    ResourceList,
    ResourceName,
)
from koordinator_tpu.client.store import (
    KIND_NODE,
    KIND_NODE_METRIC,
    KIND_POD,
    ObjectStore,
)
from koordinator_tpu.slocontroller.noderesource_plugins import (
    CPU_NORMALIZATION_CONFIG_KEY,
    run_plugin_chain,
)
from koordinator_tpu.utils.sloconfig import (
    POLICY_MAX_USAGE_REQUEST,
    POLICY_REQUEST,
    ColocationConfig,
    ColocationConfigSource,
)

CPU = RESOURCE_INDEX[ResourceName.CPU]
MEM = RESOURCE_INDEX[ResourceName.MEMORY]
ANNOTATION_NODE_RESERVATION = "node.koordinator.sh/reservation"


def node_static_row(node: Node, strategy):
    """The metric-independent packed columns for one node: capacity, the
    node-reservation annotation split, and the per-node strategy scalars.
    Shared by the host gather AND the colo pack (colo/pack.py) so the
    device pass reads bit-identical rows."""
    R = NUM_RESOURCES
    capacity = (node.capacity.to_vector() if node.capacity
                else node.allocatable.to_vector())
    node_reserved = np.zeros(R, np.float32)
    system_reserved = np.zeros(R, np.float32)
    reclaim = np.zeros(R, np.float32)
    mid_pct = np.zeros(R, np.float32)
    reclaim[CPU] = strategy.cpu_reclaim_threshold_percent
    reclaim[MEM] = strategy.memory_reclaim_threshold_percent
    mid_pct[CPU] = strategy.mid_cpu_threshold_percent
    mid_pct[MEM] = strategy.mid_memory_threshold_percent
    raw = node.meta.annotations.get(ANNOTATION_NODE_RESERVATION)
    if raw:
        import json

        try:
            data = json.loads(raw)
            from koordinator_tpu.api.resources import parse_quantity

            def to_vec(section):
                return ResourceList(
                    {
                        k: parse_quantity(v, cpu=(k == ResourceName.CPU))
                        for k, v in section.items()
                    }
                ).to_vector()

            node_reserved = to_vec(data.get("resources", {}))
            # the system daemons' reserve feeds both the system-used
            # floor and the by-request policy subtrahend
            system_reserved = to_vec(data.get("systemResources", {}))
        except (ValueError, TypeError):
            pass
    degrade_seconds = strategy.degrade_time_minutes * 60.0
    return capacity, node_reserved, system_reserved, reclaim, mid_pct, \
        degrade_seconds


def node_metric_row(nm: Optional[NodeMetric], pods: List[Pod]):
    """The metric-dependent packed columns for one node: usage, prod
    reclaimable, and the per-class pod aggregate sums — accumulated in
    float64 over the exact f32 per-pod rows (order-free, the
    SnapshotCache discipline), cast to f32 at the end. ``pods`` is the
    node's assigned non-terminated set; a missing/zeroed NodeMetric
    yields all-zero rows (the kernel's degrade gate zeroes the outputs
    for such nodes anyway)."""
    R = NUM_RESOURCES
    node_used = np.zeros(R, np.float32)
    prod_reclaimable = np.zeros(R, np.float32)
    pod_all_used = np.zeros(R, np.float64)
    hp_used = np.zeros(R, np.float64)
    hp_request = np.zeros(R, np.float64)
    hp_max = np.zeros(R, np.float64)
    if nm is None or nm.update_time <= 0:
        return (node_used, prod_reclaimable,
                pod_all_used.astype(np.float32),
                hp_used.astype(np.float32),
                hp_request.astype(np.float32),
                hp_max.astype(np.float32))
    node_used = nm.node_metric.node_usage.to_vector()
    prod_reclaimable = nm.prod_reclaimable.to_vector()
    pod_usage = {
        f"{pm.namespace}/{pm.name}": pm.pod_usage.to_vector()
        for pm in nm.pods_metric
    }
    for pod in pods:
        used = pod_usage.get(pod.meta.key)
        if used is not None:
            pod_all_used += used
        cls = pod.priority_class
        if cls in (PriorityClass.PROD, PriorityClass.MID,
                   PriorityClass.NONE):
            req = pod.spec.requests.to_vector()
            u = used if used is not None else np.zeros(R, np.float32)
            hp_used += u
            hp_request += req
            hp_max += np.maximum(req, u)
    return (node_used, prod_reclaimable,
            pod_all_used.astype(np.float32), hp_used.astype(np.float32),
            hp_request.astype(np.float32), hp_max.astype(np.float32))


@functools.partial(jax.jit, static_argnames=("cpu_policy", "memory_policy"))
def _batch_mid_kernel(
    capacity,            # [N, R]
    node_reserved,       # [N, R]
    system_reserved,     # [N, R]
    node_used,           # [N, R]
    pod_all_used,        # [N, R]
    pod_hp_used,         # [N, R]
    pod_hp_request,      # [N, R]
    pod_hp_max_used_req,  # [N, R]
    prod_reclaimable,    # [N, R]
    reclaim_percent,     # [N, R] (cpu/mem thresholds broadcast per axis)
    mid_percent,         # [N, R]
    degraded,            # [N] bool
    cpu_policy: str,
    memory_policy: str,
):
    reclaimable_capacity = capacity * reclaim_percent / 100.0
    system_used = jnp.maximum(node_used - pod_all_used, 0.0)
    system_used = jnp.maximum(system_used, system_reserved)
    by_usage = jnp.maximum(
        reclaimable_capacity - node_reserved - system_used - pod_hp_used, 0.0
    )
    by_request = jnp.maximum(
        reclaimable_capacity - node_reserved - system_reserved - pod_hp_request, 0.0
    )
    by_max = jnp.maximum(
        reclaimable_capacity - node_reserved - system_used - pod_hp_max_used_req, 0.0
    )

    def pick(policy):
        if policy == POLICY_REQUEST:
            return by_request
        if policy == POLICY_MAX_USAGE_REQUEST:
            return by_max
        return by_usage

    batch = by_usage
    batch = batch.at[:, CPU].set(pick(cpu_policy)[:, CPU])
    batch = batch.at[:, MEM].set(pick(memory_policy)[:, MEM])
    batch = jnp.where(degraded[:, None], 0.0, batch)
    mid = jnp.minimum(prod_reclaimable, capacity * mid_percent / 100.0)
    mid = jnp.where(degraded[:, None], 0.0, jnp.maximum(mid, 0.0))
    return batch, mid


class NodeResourceController:
    """The host oracle of the colocation resource pipeline. With
    koordcolo (colo/) attached, the SAME formula runs as part of the
    device colo pass and this controller is retained as the
    decision-parity reference (``run_colo_parity``); ``apply`` is the
    shared writeback both engines route through. The effective config
    hot-reloads from the slo-controller-config ConfigMap (memoized on
    its resourceVersion) with the constructor config as the base."""

    def __init__(self, store: ObjectStore, config: Optional[ColocationConfig] = None):
        self.store = store
        self.config_source = ColocationConfigSource(store, config)

    @property
    def config(self) -> ColocationConfig:
        return self.config_source.get()

    # -- host gather ---------------------------------------------------------
    def _gather(self, nodes: List[Node], now: float):
        config = self.config
        N = len(nodes)
        R = NUM_RESOURCES
        capacity = np.zeros((N, R), np.float32)
        node_reserved = np.zeros((N, R), np.float32)
        system_reserved = np.zeros((N, R), np.float32)
        node_used = np.zeros((N, R), np.float32)
        pod_all_used = np.zeros((N, R), np.float32)
        pod_hp_used = np.zeros((N, R), np.float32)
        pod_hp_request = np.zeros((N, R), np.float32)
        pod_hp_max = np.zeros((N, R), np.float32)
        prod_reclaimable = np.zeros((N, R), np.float32)
        reclaim = np.zeros((N, R), np.float32)
        mid_pct = np.zeros((N, R), np.float32)
        degraded = np.zeros(N, bool)

        pods_by_node: Dict[str, List[Pod]] = {}
        for pod in self.store.list(KIND_POD):
            if pod.is_assigned and not pod.is_terminated:
                pods_by_node.setdefault(pod.spec.node_name, []).append(pod)

        for i, node in enumerate(nodes):
            strategy = config.strategy_for_node(
                node.meta.labels, node.meta.annotations)
            (capacity[i], node_reserved[i], system_reserved[i],
             reclaim[i], mid_pct[i], degrade_seconds) = node_static_row(
                node, strategy)
            nm: Optional[NodeMetric] = self.store.get(
                KIND_NODE_METRIC, f"/{node.meta.name}"
            )
            if nm is None or nm.update_time <= 0:
                degraded[i] = True
                continue
            if now - nm.update_time > degrade_seconds:
                degraded[i] = True  # degrade on stale metrics (plugin.go:467-485)
                continue
            (node_used[i], prod_reclaimable[i], pod_all_used[i],
             pod_hp_used[i], pod_hp_request[i], pod_hp_max[i]) = (
                node_metric_row(nm, pods_by_node.get(node.meta.name, [])))
        return (capacity, node_reserved, system_reserved, node_used, pod_all_used,
                pod_hp_used, pod_hp_request, pod_hp_max, prod_reclaimable,
                reclaim, mid_pct, degraded)

    # -- reconcile -----------------------------------------------------------
    def reconcile(self, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        nodes = self.store.list(KIND_NODE)
        if not nodes:
            return 0
        arrays = self._gather(nodes, now)
        strategy = self.config.cluster_strategy
        batch, mid = _batch_mid_kernel(
            *[jnp.asarray(a) for a in arrays],
            cpu_policy=strategy.cpu_calculate_policy,
            memory_policy=strategy.memory_calculate_policy,
        )
        batch, mid = np.asarray(batch), np.asarray(mid)
        return self.apply(nodes, batch[:, CPU], batch[:, MEM],
                          mid[:, CPU], mid[:, MEM])

    # -- writeback (shared with the device colo pass) -------------------------
    def apply(self, nodes: List[Node], batch_cpu, batch_mem,
              mid_cpu, mid_mem) -> int:
        """Publish the computed batch/mid columns onto node status and
        run the post-pass plugin chain — the single writeback both the
        host reconcile and the colo device pass route through, so the
        store-visible effect of a pass is engine-independent by
        construction."""
        changes = 0
        for i, node in enumerate(nodes):
            update = ResourceList.of(
                batch_cpu=int(batch_cpu[i]),
                batch_memory=int(batch_mem[i]) * 1024 * 1024,
                mid_cpu=int(mid_cpu[i]),
                mid_memory=int(mid_mem[i]) * 1024 * 1024,
            )
            merged = dict(node.allocatable.quantities)
            changed = False
            for name in (
                ResourceName.BATCH_CPU,
                ResourceName.BATCH_MEMORY,
                ResourceName.MID_CPU,
                ResourceName.MID_MEMORY,
            ):
                val = update[name]
                if merged.get(name, 0) != val:
                    merged[name] = val
                    changed = True
            if changed:
                node.allocatable = ResourceList(merged)
            # post-pass plugin chain: cpunormalization + gpudeviceresource +
            # resourceamplification (reference plugins_profile.go order);
            # runs after the batch/mid merge so it sees the final allocatable
            plugin_changed = run_plugin_chain(
                node, self.store,
                cpu_normalization_config=self._cpu_normalization_config())
            if changed or plugin_changed:
                self.store.update(KIND_NODE, node)
                changes += 1
        return changes

    def _cpu_normalization_config(self) -> Optional[dict]:
        """cpu-normalization-config section of the slo-controller-config
        ConfigMap (configuration/slo_controller_config.go:34)."""
        from koordinator_tpu.client.store import KIND_CONFIG_MAP
        from koordinator_tpu.utils.sloconfig import CONFIG_MAP_NAME

        cm = self.store.get(
            KIND_CONFIG_MAP, f"koordinator-system/{CONFIG_MAP_NAME}")
        raw = getattr(cm, "data", {}).get(CPU_NORMALIZATION_CONFIG_KEY) if cm else None
        if not raw:
            return None
        import json

        try:
            cfg = json.loads(raw)
        except ValueError:
            return None
        return cfg if isinstance(cfg, dict) else None
